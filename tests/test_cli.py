"""CLI verb tests (reference CLI parity — SURVEY.md section 2.7).

Each verb is driven through ``main(argv)`` exactly as ``python -m
hadoop_bam_tpu`` would, on synthesized fixtures, asserting on stdout and on
the written artifacts re-read through the library.
"""
from __future__ import annotations

import os
import random

import pytest

from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.formats.vcf import VcfRecord
from hadoop_bam_tpu.tools.cli import main
from tests.fixtures import make_header, make_records


@pytest.fixture(scope="module")
def bam_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    header = make_header()
    recs = make_records(header, 200, seed=21)
    path = str(d / "in.bam")
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path, header, recs


def test_view_count(bam_file, capsys):
    path, _, recs = bam_file
    assert main(["view", "-c", path]) == 0
    assert capsys.readouterr().out.strip() == str(len(recs))


def test_view_header_only(bam_file, capsys):
    path, header, _ = bam_file
    assert main(["view", "-H", path]) == 0
    assert capsys.readouterr().out == header.to_sam_text()


def test_view_records(bam_file, capsys):
    path, header, recs = bam_file
    assert main(["view", "--no-header", path]) == 0
    lines = capsys.readouterr().out.splitlines()
    assert len(lines) == len(recs)
    got = SamRecord.from_line(lines[0])
    assert got.qname == recs[0].qname
    assert got.seq == recs[0].seq


def test_view_region(bam_file, capsys):
    path, header, recs = bam_file
    assert main(["view", "-c", path, "chr1"]) == 0
    n_chr1 = int(capsys.readouterr().out.strip())
    want = sum(1 for r in recs if r.rname == "chr1")
    assert n_chr1 == want
    assert main(["view", "-c", path, "nonexistent"]) == 1


def test_index_verb(bam_file, capsys, tmp_path):
    path, _, recs = bam_file
    assert main(["index", "-g", "32", path]) == 0
    sidecar = path + ".splitting-bai"
    assert os.path.exists(sidecar)
    from hadoop_bam_tpu.split.splitting_index import SplittingIndex
    idx = SplittingIndex.load_for(path)
    # every 32nd record + end sentinel
    assert len(idx.voffsets) == (len(recs) + 31) // 32 + 1
    os.remove(sidecar)


def test_cat(bam_file, tmp_path, capsys):
    path, header, recs = bam_file
    out = str(tmp_path / "cat.bam")
    assert main(["cat", out, path, path]) == 0
    _, batch = read_bam(out)
    assert len(batch) == 2 * len(recs)
    assert batch.read_name(0) == recs[0].qname
    assert batch.read_name(len(recs)) == recs[0].qname


def test_sort_coordinate(bam_file, tmp_path, capsys):
    path, header, recs = bam_file
    out = str(tmp_path / "sorted.bam")
    assert main(["sort", path, out]) == 0
    hdr, batch = read_bam(out)
    assert "SO:coordinate" in hdr.text
    import numpy as np
    refid = batch.refid.astype(np.int64)
    refkey = np.where(refid < 0, np.int64(1 << 40), refid)
    keys = list(zip(refkey.tolist(), batch.pos.tolist()))
    assert keys == sorted(keys)
    assert len(batch) == len(recs)


def test_sort_by_name(bam_file, tmp_path):
    # Write a shuffled copy first so a no-op "sort" cannot pass.
    path, header, recs = bam_file
    shuffled = recs[:]
    random.Random(11).shuffle(shuffled)
    src = str(tmp_path / "shuffled.bam")
    with BamWriter(src, header) as w:
        for r in shuffled:
            w.write_sam_record(r)
    out = str(tmp_path / "nsorted.bam")
    assert main(["sort", "-n", src, out]) == 0
    _, batch = read_bam(out)
    names = [batch.read_name(i) for i in range(len(batch))]
    assert names == sorted(names)
    assert sorted(names) == sorted(r.qname for r in recs)
    assert names != [r.qname for r in shuffled]  # the sort actually moved records


def test_fixmate(tmp_path, capsys):
    header = make_header()
    a = SamRecord(qname="p1", flag=0x1 | 0x40, rname="chr1", pos=100,
                  mapq=60, cigar="50M", rnext="*", pnext=0, tlen=0,
                  seq="A" * 50, qual="I" * 50)
    b = SamRecord(qname="p1", flag=0x1 | 0x80 | 0x10, rname="chr1", pos=300,
                  mapq=60, cigar="50M", rnext="*", pnext=0, tlen=0,
                  seq="C" * 50, qual="I" * 50)
    src = str(tmp_path / "pairs.bam")
    with BamWriter(src, header) as w:
        w.write_sam_record(a)
        w.write_sam_record(b)
    out = str(tmp_path / "fixed.bam")
    assert main(["fixmate", src, out]) == 0
    _, batch = read_bam(out)
    l0 = SamRecord.from_line(batch.to_sam_line(0))
    l1 = SamRecord.from_line(batch.to_sam_line(1))
    assert l0.rnext == "=" and l0.pnext == 300
    assert l1.rnext == "=" and l1.pnext == 100
    assert l0.tlen == 250 and l1.tlen == -250
    assert l0.flag & 0x20          # mate-reverse set from b's 0x10
    assert not (l1.flag & 0x20)


def test_vcf_sort(tmp_path, capsys):
    from tests.test_vcf import make_vcf_header, make_variants
    from hadoop_bam_tpu.api.writers import VcfShardWriter
    header = make_vcf_header()
    recs = make_variants(60, seed=2)
    rng = random.Random(0)
    shuffled = recs[:]
    rng.shuffle(shuffled)
    src = str(tmp_path / "in.vcf")
    with VcfShardWriter(src, header) as w:
        for r in shuffled:
            w.write_record(r)
    out = str(tmp_path / "out.vcf")
    assert main(["vcf-sort", src, out]) == 0
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    got = [(r.chrom, r.pos) for r in open_vcf(out).records()]
    assert got == sorted(got, key=lambda t: (header.contigs.index(t[0]), t[1]))


def test_summarize(bam_file, capsys):
    path, _, recs = bam_file
    assert main(["summarize", path]) == 0
    out = capsys.readouterr().out
    assert f"{len(recs)} + 0 in total" in out


def test_error_path(capsys):
    assert main(["view", "/does/not/exist.bam"]) == 1
    assert "error:" in capsys.readouterr().err


def test_external_sort_multiple_runs(tmp_path):
    """Spill-merge sort with tiny runs produces globally sorted output
    with every record preserved, identical to a single in-memory sort."""
    import random

    from fixtures import make_header, make_records
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.utils.sort import coordinate_key, name_key, sort_bam

    header = make_header()
    records = make_records(header, 2000, seed=41)
    random.Random(5).shuffle(records)
    path = str(tmp_path / "unsorted.bam")
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)

    out_ext = str(tmp_path / "sorted_ext.bam")
    n = sort_bam(path, out_ext, run_records=300)  # forces ~7 runs
    assert n == len(records)
    out_mem = str(tmp_path / "sorted_mem.bam")
    assert sort_bam(path, out_mem, run_records=10_000_000) == len(records)
    assert open(out_ext, "rb").read() != b""

    def record_bytes(p):
        ds = open_bam(p)
        return [b.record_bytes(i) for bt in ds.batches()
                for b, i in ((bt, j) for j in range(len(bt)))]

    ext = record_bytes(out_ext)
    mem = record_bytes(out_mem)
    keys = [coordinate_key(r) for r in ext]
    assert keys == sorted(keys)
    assert sorted(ext) == sorted(mem)         # same multiset
    assert [coordinate_key(r) for r in mem] == keys  # same global order
    hdr = open_bam(out_ext).header
    assert "SO:coordinate" in hdr.text

    # queryname mode — assert on decoded read names, not name_key itself
    # (keying the check on name_key would be circular: a broken key that
    # returns b'' for every record would trivially "sort").
    out_qn = str(tmp_path / "sorted_qn.bam")
    sort_bam(path, out_qn, by_name=True, run_records=256)
    ds_qn = open_bam(out_qn)
    qn = [bt.read_name(i) for bt in ds_qn.batches() for i in range(len(bt))]
    assert qn == sorted(qn)
    assert sorted(qn) == sorted(r.qname for r in records)
    # and name_key agrees with the decoded names on real records
    qn_keys = [name_key(r).decode() for r in record_bytes(out_qn)]
    assert qn_keys == qn


def test_external_vcf_sort_multiple_runs(tmp_path):
    import random

    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    from hadoop_bam_tpu.utils.sort import sort_vcf

    header_text = ("##fileformat=VCFv4.2\n"
                   "##contig=<ID=c1,length=100000>\n"
                   "##contig=<ID=c2,length=100000>\n"
                   "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
    rng = random.Random(13)
    recs = [f"c{rng.choice([1, 2])}\t{rng.randint(1, 99999)}\t.\tA\tG\t"
            f"30\tPASS\t." for _ in range(1500)]
    path = str(tmp_path / "u.vcf")
    with open(path, "w") as f:
        f.write(header_text)
        f.write("\n".join(recs) + "\n")
    out = str(tmp_path / "s.vcf")
    n = sort_vcf(path, out, run_records=200)  # forces ~8 BCF runs
    assert n == 1500
    ds = open_vcf(out)
    got = [(r.chrom, r.pos) for r in ds.records()]
    assert got == sorted(got)
    assert len(got) == 1500


def test_vcf_sort_undeclared_contigs(tmp_path):
    """Text VCF with no ##contig lines (legal) must still external-sort —
    runs spill as text, so no BCF contig dictionary is required."""
    import random

    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.utils.sort import sort_vcf

    header_text = ("##fileformat=VCFv4.2\n"
                   "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
    rng = random.Random(2)
    path = str(tmp_path / "nc.vcf")
    with open(path, "w") as f:
        f.write(header_text)
        for _ in range(700):
            f.write(f"chrX\t{rng.randint(1, 9999)}\t.\tA\tT\t9\tPASS\t.\n")
    out = str(tmp_path / "nc_sorted.vcf")
    assert sort_vcf(path, out, run_records=100) == 700  # forces 7 runs
    got = [r.pos for r in open_vcf(out).records()]
    assert got == sorted(got) and len(got) == 700


def test_coverage_verb(bam_file, tmp_path, capsys):
    path, header, recs = bam_file
    rname = header.ref_names[0]
    bg = str(tmp_path / "d.bedgraph")
    assert main(["coverage", path, f"{rname}:1-50,000",
                 "--bedgraph", bg]) == 0
    out = capsys.readouterr().out
    assert "mean_depth\t" in out and f"region\t{rname}:1-50000" in out
    # bedgraph runs agree with the printed covered-base count
    covered = int(next(l.split("\t")[1] for l in out.splitlines()
                       if l.startswith("covered")))
    runs = [l.split("\t") for l in open(bg).read().splitlines()]
    assert sum(int(e) - int(s) for _, s, e, _ in runs) == covered
    # bad region is a loud error (main maps ValueError to exit 1)
    assert main(["coverage", path, "chrNOPE:1-100"]) == 1


def test_coverage_whole_contig_and_tiling(bam_file, tmp_path, capsys,
                                          monkeypatch):
    """A bare contig name covers the whole reference by tiling windows;
    runs merge seamlessly across tile boundaries."""
    import hadoop_bam_tpu.tools.cli as cli
    path, header, recs = bam_file
    rname = header.ref_names[0]
    # per-region ground truth from the untiled driver path
    bg1 = str(tmp_path / "one.bedgraph")
    assert main(["coverage", path, f"{rname}:1-60,000",
                 "--bedgraph", bg1]) == 0
    out1 = capsys.readouterr().out
    # force tiny tiles so the merge logic really runs
    monkeypatch.setattr(cli, "_COVERAGE_TILE", 7_000)
    bg2 = str(tmp_path / "tiled.bedgraph")
    assert main(["coverage", path, f"{rname}:1-60,000",
                 "--bedgraph", bg2]) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2.replace("wrote " + bg2, "wrote " + bg1)
    assert open(bg1).read() == open(bg2).read()


def test_coverage_whole_contig_bare_name(bam_file, capsys):
    path, header, recs = bam_file
    assert main(["coverage", path, header.ref_names[0]]) == 0
    out = capsys.readouterr().out
    assert f"region\t{header.ref_names[0]}:1-{header.ref_lengths[0]}" in out


def test_coverage_colon_contig_resolves_verbatim(tmp_path, capsys):
    """A contig whose NAME contains ':' (GRCh38 HLA alts) must resolve as
    a whole-contig region, not misparse at the colon."""
    from hadoop_bam_tpu.formats.bam import SAMHeader
    hla = "HLA-A*01:01"
    header = SAMHeader(
        text=f"@HD\tVN:1.6\n@SQ\tSN:{hla}\tLN:4000\n",
        ref_names=[hla], ref_lengths=[4000])
    path = str(tmp_path / "hla.bam")
    with BamWriter(path, header) as w:
        w.write_sam_record(SamRecord(
            qname="r", flag=0, rname=hla, pos=100, mapq=30, cigar="10M",
            rnext="*", pnext=0, tlen=0, seq="ACGTACGTAC",
            qual="IIIIIIIIII"))
    assert main(["coverage", path, hla]) == 0
    out = capsys.readouterr().out
    assert f"region\t{hla}:1-4000" in out and "covered\t10" in out


def test_coverage_failure_leaves_no_bedgraph(bam_file, tmp_path):
    """A mid-run error must not leave a plausible-looking partial
    bedGraph behind."""
    path, header, recs = bam_file
    bg = str(tmp_path / "part.bedgraph")
    rc = main(["coverage", path, f"{header.ref_names[0]}:1-10,000",
               "--max-cigar", "0", "--bedgraph", bg])
    assert rc == 1                      # max_cigar=0 always overflows
    assert not os.path.exists(bg)
    assert not os.path.exists(bg + ".tmp")


def test_view_count_cram_header_scan(tmp_path, capsys):
    """view -c on CRAM counts from container headers without decoding."""
    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.cramio import write_cram
    from hadoop_bam_tpu.formats.sam import SamRecord as SR
    from hadoop_bam_tpu.tools.cli import main

    hdr = SAMHeader.from_sam_text("@HD\tVN:1.6\n@SQ\tSN:c1\tLN:9999\n")
    recs = [SR(qname=f"r{i}", flag=0, rname="c1", pos=1 + i, mapq=60,
               cigar="5M", rnext="*", pnext=0, tlen=0,
               seq="ACGTA", qual="IIIII") for i in range(321)]
    path = str(tmp_path / "c.cram")
    with open(path, "wb") as f:
        write_cram(f, hdr, recs)
    assert main(["view", "-c", path]) == 0
    assert capsys.readouterr().out.strip() == "321"


def test_cli_sort_mesh_spill(tmp_path, capsys):
    """hbam sort --mesh --run-records engages the spill exchange and the
    output matches the plain spill-merge sort byte for byte."""
    import random

    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.tools.cli import main
    from hadoop_bam_tpu.utils.sort import sort_bam

    from fixtures import make_header, make_records

    header = make_header()
    recs = make_records(header, 900, seed=31)
    random.Random(2).shuffle(recs)
    src = str(tmp_path / "in.bam")
    with BamWriter(src, header) as w:
        for r in recs:
            w.write_sam_record(r)
    out = str(tmp_path / "out.bam")
    assert main(["sort", src, out, "--mesh", "--run-records", "120"]) == 0
    assert "mesh spill" in capsys.readouterr().out
    ref = str(tmp_path / "ref.bam")
    sort_bam(src, ref)
    assert open(out, "rb").read() == open(ref, "rb").read()
