"""Crash-safe jobs tests (hadoop_bam_tpu/jobs/): durable journal
semantics, SIGKILL-and-resume byte identity for the spill sort / cohort
join / sharded write, refuse-to-resume contracts, straggler speculation
and the pool hard-timeout hang fix.

The kill tests are REAL: a subprocess doing the real pipeline work
SIGKILLs itself at a seeded journal offset (after the Nth committed
unit — deterministic, no timing races), and the parent resumes from
the journal and compares bytes against an uninterrupted oracle run.
"""
import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.jobs import (
    JobJournal, UnitLatency, config_fingerprint, file_digest,
    journal_path_for, sweep_unrecorded, verify_artifact,
)
from hadoop_bam_tpu.utils.errors import (
    CorruptDataError, PlanError, TransientIOError,
)
from hadoop_bam_tpu.utils.metrics import MetricsContext

from fixtures import make_header, make_records

pytestmark = pytest.mark.resilience

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))

# every journal-touching pipeline in these tests runs with fsync off:
# the durability property it buys needs a power failure to test, and
# the tmpfs-backed CI runs only care about the record/replay semantics
NOSYNC = dataclasses.replace(DEFAULT_CONFIG, journal_fsync=False)


def _child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


def _run_child(script_body: str, *args, timeout=180):
    """Run a self-killing child script; return its CompletedProcess."""
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(textwrap.dedent(script_body))
        script = f.name
    try:
        return subprocess.run(
            [sys.executable, script, *map(str, args)],
            env=_child_env(), timeout=timeout, capture_output=True,
            text=True)
    finally:
        os.unlink(script)


# ---------------------------------------------------------------------------
# journal core semantics
# ---------------------------------------------------------------------------

def _mini_job(tmp_path, fingerprint="fp", params=None, kind="k"):
    inp = tmp_path / "in.dat"
    inp.write_bytes(b"x" * 1000)
    from hadoop_bam_tpu.jobs import file_identity_digest
    jp = str(tmp_path / "j.hbam-journal")
    return jp, [(str(inp), file_identity_digest(str(inp)))], {
        "kind": kind, "output": str(tmp_path / "out.dat"),
        "fingerprint": fingerprint, "params": params or {"a": 1}}


def test_journal_roundtrip_and_replay(tmp_path):
    jp, inputs, hdr = _mini_job(tmp_path)
    j, st = JobJournal.resume(jp, inputs=inputs, **hdr)
    assert st is None
    j.event("bounds", bhi=[7], blo=[9])
    j.unit_done("round", 0, runs=[["a", "b", 1, "0abc"]], round_total=5)
    j.unit_done("round", 1, runs=[], round_total=3)
    j.job_done(records=8, size=1, crc="00000000")
    j.close()
    st = JobJournal.replay(jp)
    assert st.kind == "k" and st.done["records"] == 8
    assert st.unit("round", 1)["round_total"] == 3
    assert st.last_event("bounds")["bhi"] == [7]
    assert not st.torn_tail
    # second resume sees the prior state and appends a resume event
    j2, st2 = JobJournal.resume(jp, inputs=inputs, **hdr)
    assert st2 is not None and len(st2.units) == 2
    j2.close()
    assert JobJournal.replay(jp).last_event("resume") is not None


def test_journal_torn_tail_tolerated_mid_corruption_refused(tmp_path):
    jp, inputs, hdr = _mini_job(tmp_path)
    j, _ = JobJournal.resume(jp, inputs=inputs, **hdr)
    j.unit_done("round", 0, round_total=1)
    j.unit_done("round", 1, round_total=2)
    j.close()
    raw = open(jp, "rb").read()
    # torn tail: half a final line — expected after SIGKILL, dropped
    open(jp, "wb").write(raw[:-9])
    st = JobJournal.replay(jp)
    assert st.torn_tail and st.unit("round", 0) is not None \
        and st.unit("round", 1) is None
    # mid-file corruption: NOT an honest crash shape — refused
    lines = raw.split(b"\n")
    lines[1] = lines[1].replace(b"round_total", b"round_tXtal")
    open(jp, "wb").write(b"\n".join(lines))
    with pytest.raises(CorruptDataError):
        JobJournal.replay(jp)


def test_resume_after_torn_tail_keeps_journal_replayable(tmp_path):
    """Appending onto a torn final line would weld the new record into
    one unparseable MID-file line — the resume must truncate the torn
    fragment first so resuming a resume stays the same code path."""
    jp, inputs, hdr = _mini_job(tmp_path)
    j, _ = JobJournal.resume(jp, inputs=inputs, **hdr)
    j.unit_done("round", 0, round_total=1)
    j.unit_done("round", 1, round_total=2)
    j.close()
    raw = open(jp, "rb").read()
    open(jp, "wb").write(raw[:-9])             # tear the final unit
    j2, st2 = JobJournal.resume(jp, inputs=inputs, **hdr)
    assert st2.torn_tail and st2.unit("round", 1) is None
    j2.unit_done("round", 1, round_total=2)
    j2.job_done(records=3, size=1, crc="00000000")
    j2.close()
    st3 = JobJournal.replay(jp)                # resume-of-a-resume
    assert not st3.torn_tail
    assert st3.done is not None
    assert st3.unit("round", 1)["round_total"] == 2
    assert any(e.get("name") == "resume" for e in st3.events)


@pytest.mark.parametrize("mutate,what", [
    (lambda h: {**h, "fingerprint": "other"}, "fingerprint"),
    (lambda h: {**h, "kind": "zzz"}, "kind"),
    (lambda h: {**h, "params": {"a": 2}}, "parameters"),
    (lambda h: {**h, "output": "elsewhere"}, "output"),
])
def test_resume_refuses_mismatch(tmp_path, mutate, what):
    jp, inputs, hdr = _mini_job(tmp_path)
    JobJournal.resume(jp, inputs=inputs, **hdr)[0].close()
    with pytest.raises(PlanError, match="refusing to resume"):
        JobJournal.resume(jp, inputs=inputs, **mutate(hdr))


def test_resume_refuses_changed_input_identity(tmp_path):
    jp, inputs, hdr = _mini_job(tmp_path)
    JobJournal.resume(jp, inputs=inputs, **hdr)[0].close()
    p = inputs[0][0]
    time.sleep(0.01)
    with open(p, "ab") as f:       # size + mtime change
        f.write(b"more")
    from hadoop_bam_tpu.jobs import file_identity_digest
    with pytest.raises(PlanError, match="input file identity"):
        JobJournal.resume(jp, inputs=[(p, file_identity_digest(p))],
                          **hdr)


def test_plan_digest_refuses_device_host_route_swap(tmp_path):
    """The decode ROUTE is plan identity: a journaled job compiled for
    the BCF device variant route (round 21: ``variant_unpack_device`` in
    the op DAG) refuses to resume against a host-plane journal, and vice
    versa — the two routes partition work differently (device-plane span
    grain vs the host span plan), so silently mixing them would
    mis-stitch units."""
    from hadoop_bam_tpu.jobs.runner import plan_journal_params
    from hadoop_bam_tpu.plan import builders

    bcf = str(tmp_path / "x.bcf")       # builders never open the file
    host_plan = builders.variant_stats_plan(
        bcf, dataclasses.replace(DEFAULT_CONFIG,
                                 inflate_backend="native"))
    dev_plan = builders.variant_stats_plan(
        bcf, dataclasses.replace(DEFAULT_CONFIG,
                                 inflate_backend="device"))
    assert [o["op"] for o in dev_plan.to_doc()["ops"]] == [
        "variant_pack", "variant_unpack_device", "variant_stats_reduce"]
    assert "variant_unpack_device" not in [
        o["op"] for o in host_plan.to_doc()["ops"]]
    assert host_plan.digest() != dev_plan.digest()

    jp, inputs, hdr = _mini_job(tmp_path)
    host_hdr = {**hdr, "params": plan_journal_params(host_plan)}
    JobJournal.resume(jp, inputs=inputs, **host_hdr)[0].close()
    with pytest.raises(PlanError, match="refusing to resume"):
        JobJournal.resume(
            jp, inputs=inputs,
            **{**hdr, "params": plan_journal_params(dev_plan)})
    # and the mirror image: device journal, host resume
    jp2 = jp + ".dev"
    dev_hdr = {**hdr, "params": plan_journal_params(dev_plan)}
    JobJournal.resume(jp2, inputs=inputs, **dev_hdr)[0].close()
    with pytest.raises(PlanError, match="refusing to resume"):
        JobJournal.resume(
            jp2, inputs=inputs,
            **{**hdr, "params": plan_journal_params(host_plan)})
    # a text VCF compiles the SAME plan under either backend (no device
    # row exists for it) — no spurious refusal on a config-only change
    vcf = str(tmp_path / "x.vcf")
    assert builders.variant_stats_plan(
        vcf, dataclasses.replace(DEFAULT_CONFIG,
                                 inflate_backend="native")).digest() == \
        builders.variant_stats_plan(
            vcf, dataclasses.replace(DEFAULT_CONFIG,
                                     inflate_backend="device")).digest()


def test_artifact_verification_and_sweep(tmp_path):
    a = tmp_path / "art1"
    a.write_bytes(b"payload")
    size, crc = file_digest(str(a))
    assert verify_artifact(str(a), size, crc)
    assert not verify_artifact(str(a), size + 1, crc)
    a.write_bytes(b"pAyload")
    assert not verify_artifact(str(a), size, crc)
    d = tmp_path / "arts"
    d.mkdir()
    keep = d / "keep"
    keep.write_bytes(b"k")
    (d / "stale1").write_bytes(b"s")
    (d / "stale2").write_bytes(b"s")
    assert sweep_unrecorded(str(d), [str(keep)]) == 2
    assert sorted(os.listdir(d)) == ["keep"]


def test_config_fingerprint_tracks_only_named_fields():
    base = config_fingerprint(DEFAULT_CONFIG, ("write_compress_level",))
    changed = config_fingerprint(
        dataclasses.replace(DEFAULT_CONFIG, write_compress_level=1),
        ("write_compress_level",))
    unrelated = config_fingerprint(
        dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False),
        ("write_compress_level",))
    assert base != changed and base == unrelated


# ---------------------------------------------------------------------------
# straggler defense: decaying latency -> soft deadlines, speculation
# ---------------------------------------------------------------------------

def test_unit_latency_deadline_and_decay():
    ul = UnitLatency(multiplier=2.0, min_s=0.0, min_samples=8,
                     decay_every=16)
    assert ul.soft_deadline_s() is None     # warmup: never speculate
    for _ in range(8):
        ul.observe(1.0)
    d0 = ul.soft_deadline_s()
    assert d0 == pytest.approx(2.0, rel=0.25)
    # regime shift: decay lets the deadline follow RECENT latencies
    for _ in range(200):
        ul.observe(0.01)
    assert ul.soft_deadline_s() < d0 / 10


def test_speculation_first_result_wins(shared_pool):
    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    lock = threading.Lock()
    seen = set()

    def fn(i):
        with lock:
            first = i not in seen
            seen.add(i)
        if i == 30 and first:
            time.sleep(2.0)        # the straggler's FIRST copy only
            return i
        time.sleep(0.005)
        return i

    cfg = dataclasses.replace(DEFAULT_CONFIG, straggler_min_s=0.05,
                              straggler_multiplier=2.0)
    with MetricsContext() as m:
        out = list(_iter_windowed(shared_pool, range(32), fn, 4,
                                  config=cfg))
    snap = m.snapshot()
    assert out == list(range(32))          # order preserved, no dupes
    assert snap["counters"].get("jobs.speculative_launched", 0) >= 1
    assert snap["counters"].get("jobs.speculative_won", 0) >= 1


def test_small_runs_never_speculate(shared_pool):
    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    cfg = dataclasses.replace(DEFAULT_CONFIG, straggler_min_s=0.0,
                              straggler_multiplier=0.0)
    with MetricsContext() as m:
        out = list(_iter_windowed(shared_pool, range(8),
                                  lambda i: i, 4, config=cfg))
    assert out == list(range(8))
    assert m.snapshot()["counters"].get("jobs.speculative_launched",
                                        0) == 0


@pytest.fixture()
def shared_pool():
    import concurrent.futures as cf

    pool = cf.ThreadPoolExecutor(max_workers=8)
    yield pool
    pool.shutdown(wait=False, cancel_futures=True)


# ---------------------------------------------------------------------------
# pool hard timeout: the wedged-worker hang fix
# ---------------------------------------------------------------------------

def test_pool_timeout_resubmits_past_wedged_worker(shared_pool):
    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    release = threading.Event()
    lock = threading.Lock()
    attempts = {}

    def fn(i):
        with lock:
            attempts[i] = attempts.get(i, 0) + 1
            first = attempts[i] == 1
        if i == 5 and first:
            release.wait()                 # wedged worker
            return -1
        return i * 10

    cfg = dataclasses.replace(DEFAULT_CONFIG, pool_task_timeout_s=0.25,
                              speculative_decode=False)
    try:
        with MetricsContext() as m:
            out = list(_iter_windowed(shared_pool, range(8), fn, 4,
                                      config=cfg))
        snap = m.snapshot()
        assert out == [i * 10 for i in range(8)]
        assert snap["counters"].get("pool.task_timeouts", 0) >= 1
        assert snap["counters"].get("jobs.timeout_resubmits", 0) >= 1
    finally:
        release.set()


def test_pool_timeout_exhaustion_is_classified_transient(shared_pool):
    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    release = threading.Event()

    def fn(i):
        if i == 2:
            release.wait()
            return -1
        return i

    cfg = dataclasses.replace(DEFAULT_CONFIG, pool_task_timeout_s=0.15,
                              span_retries=1, speculative_decode=False)
    try:
        with pytest.raises(TransientIOError, match="pool_task_timeout"):
            list(_iter_windowed(shared_pool, range(4), fn, 2,
                                config=cfg))
    finally:
        release.set()


def test_pool_timeout_does_not_resubmit_deterministic_failures(
        shared_pool):
    """A span whose decode genuinely FAILED (vs timed out) must raise
    immediately — burning the timeout re-submission budget on a
    known-failing span duplicates the failure and mislabels it as a
    wedged worker."""
    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    calls = {"n": 0}

    def fn(i):
        if i == 1:
            calls["n"] += 1
            raise CorruptDataError("bad bytes")
        return i

    cfg = dataclasses.replace(DEFAULT_CONFIG, pool_task_timeout_s=30.0,
                              speculative_decode=False)
    with MetricsContext() as m:
        with pytest.raises(CorruptDataError):
            list(_iter_windowed(shared_pool, range(4), fn, 2,
                                config=cfg))
    assert calls["n"] == 1                  # ran once, never re-raced
    assert m.snapshot()["counters"].get("jobs.timeout_resubmits",
                                        0) == 0


def test_pool_timeout_is_active_wait_not_submit_age():
    """Queue wait on a backlogged-but-healthy single-worker pool must
    not burn the wedged-worker deadline: the tail items' submit age
    (~1.3s) far exceeds the 1.0s timeout, but each one's ACTIVE wait is
    well under it — a submit-anchored deadline would abandon healthy
    decodes and exhaust the budget on re-submissions that queue behind
    the same backlog."""
    import concurrent.futures as cf

    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    pool = cf.ThreadPoolExecutor(max_workers=1)

    def fn(i):
        time.sleep(0.7 if i == 0 else 0.3)
        return i

    cfg = dataclasses.replace(DEFAULT_CONFIG, pool_task_timeout_s=1.0,
                              span_retries=0, speculative_decode=False)
    try:
        with MetricsContext() as m:
            out = list(_iter_windowed(pool, range(4), fn, 4,
                                      config=cfg))
        assert out == list(range(4))
        assert m.snapshot()["counters"].get("pool.task_timeouts",
                                            0) == 0
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def test_chaos_pool_task_delay_wedges_worker_and_timeout_heals():
    """The standing hang: a chaos 'delay' fault at the new pool.task
    point wedges a WORKER mid-task; without pool_task_timeout_s the
    consumer would block for the full delay — with it, the item is
    re-submitted and the run completes promptly."""
    import concurrent.futures as cf

    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed
    from hadoop_bam_tpu.resilience.chaos import (
        PointFault, fault_points_on,
    )
    from hadoop_bam_tpu.utils import pools

    pool = cf.ThreadPoolExecutor(max_workers=4)
    cfg = dataclasses.replace(DEFAULT_CONFIG, pool_task_timeout_s=0.2,
                              speculative_decode=False)
    t0 = time.perf_counter()
    try:
        with fault_points_on("pool.task",
                             [PointFault(kind="delay", at_call=1,
                                         delay_s=5.0)]):
            with MetricsContext() as m:
                out = list(_iter_windowed(pool, range(6), lambda i: i,
                                          2, config=cfg))
        assert out == list(range(6))
        assert m.snapshot()["counters"].get("pool.task_timeouts",
                                            0) >= 1
        assert time.perf_counter() - t0 < 10.0    # not the 30s wedge
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def test_fully_wedged_pool_still_surfaces_within_grace():
    """When EVERY worker is wedged, re-submissions never dequeue — the
    bounded queued-anchor grace must let the budget exhaust and raise
    instead of holding the anchor (and the consumer) forever."""
    import concurrent.futures as cf

    from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

    release = threading.Event()
    pool = cf.ThreadPoolExecutor(max_workers=2)
    cfg = dataclasses.replace(DEFAULT_CONFIG, pool_task_timeout_s=0.1,
                              span_retries=1, speculative_decode=False)
    t0 = time.perf_counter()
    try:
        with pytest.raises(TransientIOError, match="pool_task_timeout"):
            list(_iter_windowed(pool, range(4),
                                lambda i: (release.wait(), i)[1], 4,
                                config=cfg))
        # ~timeout + (retries * grace-bounded queued wait) — bounded,
        # never the forever-hang
        assert time.perf_counter() - t0 < 10.0
    finally:
        release.set()
        pool.shutdown(wait=False, cancel_futures=True)


def test_result_with_timeout_classifies(shared_pool):
    ev = threading.Event()
    from hadoop_bam_tpu.utils.pools import result_with_timeout

    fut = shared_pool.submit(ev.wait)
    try:
        with pytest.raises(TransientIOError):
            result_with_timeout(fut, 0.1, what="probe")
    finally:
        ev.set()


# ---------------------------------------------------------------------------
# ShardedFileWriter: stale temp sweep + journaled shard commits
# ---------------------------------------------------------------------------

def test_sharded_writer_sweeps_stale_temps(tmp_path):
    from hadoop_bam_tpu.write import ShardedFileWriter

    sw = ShardedFileWriter(str(tmp_path / "out.bin"), 3)
    os.makedirs(sw.shard_dir)
    for name in ("part-00000.tmp", "part-00002.tmp"):
        (tmp_path / "out.bin.hbam-shards" / name).write_bytes(b"junk")
    (tmp_path / "out.bin.hbam-shards" / "part-00001").write_bytes(b"ok")
    with MetricsContext() as m:
        assert sw.sweep_stale_temps() == 2
    assert m.snapshot()["counters"]["write.stale_temps_swept"] == 2
    assert os.listdir(sw.shard_dir) == ["part-00001"]
    # prepare() also counts before clearing the directory
    (tmp_path / "out.bin.hbam-shards" / "part-00000.tmp").write_bytes(
        b"junk")
    with MetricsContext() as m:
        sw.prepare()
    assert m.snapshot()["counters"]["write.stale_temps_swept"] == 1
    assert not os.path.isdir(sw.shard_dir)


def test_sharded_writer_journal_skip_and_reverify(tmp_path):
    from hadoop_bam_tpu.write import (
        ShardedFileWriter, write_shards_journaled,
    )

    final = str(tmp_path / "out.bin")
    jp = str(tmp_path / "w.hbam-journal")
    payloads = [bytes([i]) * 64 for i in range(5)]
    jr, st = JobJournal.resume(jp, kind="shard_write", inputs=[],
                               output=final, fingerprint="f", params={})
    sw = ShardedFileWriter(final, 5, journal=jr)
    assert write_shards_journaled(sw, payloads) == 5
    jr.close()
    mtimes = {k: os.stat(sw.shard_path(k)).st_mtime_ns for k in range(5)}
    jr2, st2 = JobJournal.resume(jp, kind="shard_write", inputs=[],
                                 output=final, fingerprint="f",
                                 params={})
    sw2 = ShardedFileWriter(final, 5, journal=jr2, resume_state=st2)
    with MetricsContext() as m:
        assert write_shards_journaled(sw2, payloads) == 0
    assert m.snapshot()["counters"].get("jobs.shards_skipped") == 5
    assert all(os.stat(sw2.shard_path(k)).st_mtime_ns == mtimes[k]
               for k in range(5))          # verified-skip, not rewrite
    # a part the crash corrupted fails verification and rewrites
    open(sw2.shard_path(3), "wb").write(b"garbage")
    assert write_shards_journaled(sw2, payloads) == 1
    assert open(sw2.shard_path(3), "rb").read() == payloads[3]
    jr2.close()


def test_sigkill_mid_sharded_write_resumes_byte_identical(tmp_path):
    """Child SIGKILLs itself after 2 committed shards; the resumed
    parent writes only the remainder and the concatenation matches an
    uninterrupted oracle byte for byte."""
    out = str(tmp_path / "out.bin")
    jp = str(tmp_path / "w.hbam-journal")
    r = _run_child("""
        import os, signal, sys
        from hadoop_bam_tpu.jobs import JobJournal
        from hadoop_bam_tpu.write import (
            ShardedFileWriter, write_shards_journaled,
        )
        out, jp = sys.argv[1:3]
        orig = JobJournal.unit_done
        n = [0]
        def patched(self, kind, key, **kw):
            orig(self, kind, key, **kw)
            n[0] += 1
            if n[0] >= 2:
                os.kill(os.getpid(), signal.SIGKILL)
        JobJournal.unit_done = patched
        payloads = [bytes([i]) * 4096 for i in range(6)]
        jr, st = JobJournal.resume(jp, kind="shard_write", inputs=[],
                                   output=out, fingerprint="f",
                                   params={}, fsync=False)
        sw = ShardedFileWriter(out, 6, journal=jr, resume_state=st)
        # a stale temp from "an even earlier crash"
        os.makedirs(sw.shard_dir, exist_ok=True)
        open(os.path.join(sw.shard_dir, "part-00005.tmp"), "wb").write(
            b"debris")
        write_shards_journaled(sw, payloads)
        raise SystemExit("unreachable: child must have been killed")
    """, out, jp, timeout=60)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)

    payloads = [bytes([i]) * 4096 for i in range(6)]
    jr, st = JobJournal.resume(jp, kind="shard_write", inputs=[],
                               output=out, fingerprint="f", params={},
                               fsync=False)
    from hadoop_bam_tpu.write import (
        ShardedFileWriter, write_shards_journaled,
    )
    sw = ShardedFileWriter(out, 6, journal=jr, resume_state=st)
    with MetricsContext() as m:
        swept = sw.sweep_stale_temps()
        wrote = write_shards_journaled(sw, payloads)
    snap = m.snapshot()
    assert swept >= 1                      # the crashed run's debris
    assert 0 < wrote <= 4                  # committed shards skipped
    assert snap["counters"].get("jobs.shards_skipped", 0) >= 2
    assert sw.missing_parts() == []
    got = b"".join(open(sw.shard_path(k), "rb").read()
                   for k in range(6))
    assert got == b"".join(payloads)
    jr.close()


# ---------------------------------------------------------------------------
# SIGKILL mid-sort -> hbam resume, byte-identical, fewer spans decoded
# ---------------------------------------------------------------------------

_SORT_CHILD = """
    import os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import signal
    from hadoop_bam_tpu.jobs import JobJournal
    kill_after, src, out, jp, rr = (int(sys.argv[1]), sys.argv[2],
                                    sys.argv[3], sys.argv[4],
                                    int(sys.argv[5]))
    orig = JobJournal.unit_done
    n = [0]
    def patched(self, kind, key, **kw):
        orig(self, kind, key, **kw)
        if kind == "round":
            n[0] += 1
            if n[0] >= kill_after:
                os.kill(os.getpid(), signal.SIGKILL)
    JobJournal.unit_done = patched
    import dataclasses
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
    cfg = dataclasses.replace(DEFAULT_CONFIG, journal_fsync=False)
    sort_bam_mesh(src, out, round_records=rr, journal_path=jp,
                  config=cfg)
    raise SystemExit("unreachable: child must have been killed")
"""


@pytest.fixture(scope="module")
def sort_fixture(tmp_path_factory):
    """A shuffled BAM + its uninterrupted spill-sort oracle bytes."""
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

    d = tmp_path_factory.mktemp("jobs_sort")
    header = make_header()
    recs = list(make_records(header, 700, seed=11))
    random.Random(5).shuffle(recs)
    src = str(d / "in.bam")
    with BamWriter(src, header) as w:
        for rec in recs:
            w.write_sam_record(rec)
    oracle = str(d / "oracle.bam")
    n = sort_bam_mesh(src, oracle, round_records=30)
    return {"src": src, "oracle_bytes": open(oracle, "rb").read(),
            "records": n, "round_records": 30}


@pytest.mark.parametrize("kill_after", [1, 2])
def test_sigkill_mid_mesh_sort_resumes_byte_identical(tmp_path,
                                                      sort_fixture,
                                                      kill_after):
    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    r = _run_child(_SORT_CHILD, kill_after, sort_fixture["src"], out,
                   jp, sort_fixture["round_records"])
    assert r.returncode == -signal.SIGKILL, (r.returncode,
                                             r.stderr[-2000:])
    st = JobJournal.replay(jp)
    assert len([u for (k, _), u in st.units.items()
                if k == "round"]) == kill_after
    assert os.path.isdir(out + ".mesh-spill")   # survived the kill

    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

    with MetricsContext() as m:
        n = sort_bam_mesh(sort_fixture["src"], out,
                          round_records=sort_fixture["round_records"],
                          journal_path=jp, config=NOSYNC)
    snap = m.snapshot()
    assert n == sort_fixture["records"]
    assert open(out, "rb").read() == sort_fixture["oracle_bytes"]
    # journal-verified skips: strictly fewer spans re-decoded
    assert snap["counters"].get("jobs.rounds_skipped") == kill_after
    assert snap["counters"].get("jobs.spans_skipped", 0) > 0
    ev = JobJournal.replay(jp).last_event("resume_plan")
    assert ev["rounds_skipped"] == kill_after
    assert ev["spans_skipped"] > 0
    assert not os.path.isdir(out + ".mesh-spill")  # cleaned on success


def test_sort_journal_torn_tail_resumes(tmp_path, sort_fixture):
    """Truncate the journal mid-final-line (what an unflushed page
    loses): the torn unit's round re-runs, output stays identical."""
    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    r = _run_child(_SORT_CHILD, 2, sort_fixture["src"], out, jp,
                   sort_fixture["round_records"])
    assert r.returncode == -signal.SIGKILL
    raw = open(jp, "rb").read()
    open(jp, "wb").write(raw[:-11])        # tear the final unit record
    st = JobJournal.replay(jp)
    assert st.torn_tail

    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

    with MetricsContext() as m:
        n = sort_bam_mesh(sort_fixture["src"], out,
                          round_records=sort_fixture["round_records"],
                          journal_path=jp, config=NOSYNC)
    assert n == sort_fixture["records"]
    assert open(out, "rb").read() == sort_fixture["oracle_bytes"]
    assert m.snapshot()["counters"].get("jobs.rounds_skipped") == 1


def test_sort_resume_refuses_config_fingerprint_mismatch(tmp_path,
                                                         sort_fixture):
    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    r = _run_child(_SORT_CHILD, 1, sort_fixture["src"], out, jp,
                   sort_fixture["round_records"])
    assert r.returncode == -signal.SIGKILL

    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

    cfg = dataclasses.replace(NOSYNC, write_compress_level=1)
    with pytest.raises(PlanError, match="fingerprint"):
        sort_bam_mesh(sort_fixture["src"], out,
                      round_records=sort_fixture["round_records"],
                      journal_path=jp, config=cfg)
    # and a changed round_records is a params mismatch
    with pytest.raises(PlanError, match="parameters"):
        sort_bam_mesh(sort_fixture["src"], out, round_records=29,
                      journal_path=jp, config=NOSYNC)


def test_completed_sort_job_is_verified_noop(tmp_path, sort_fixture):
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    n1 = sort_bam_mesh(sort_fixture["src"], out,
                       round_records=sort_fixture["round_records"],
                       journal_path=jp, config=NOSYNC)
    mtime = os.stat(out).st_mtime_ns
    with MetricsContext() as m:
        n2 = sort_bam_mesh(sort_fixture["src"], out,
                           round_records=sort_fixture["round_records"],
                           journal_path=jp, config=NOSYNC)
    assert (n1, n2) == (sort_fixture["records"],) * 2
    assert m.snapshot()["counters"].get("jobs.jobs_skipped") == 1
    assert os.stat(out).st_mtime_ns == mtime    # genuinely untouched
    # ...but a vanished output rebuilds from the journal's done record
    os.unlink(out)
    n3 = sort_bam_mesh(sort_fixture["src"], out,
                       round_records=sort_fixture["round_records"],
                       journal_path=jp, config=NOSYNC)
    assert n3 == n1
    assert open(out, "rb").read() == sort_fixture["oracle_bytes"]


def test_hbam_resume_reconstructs_nondefault_config(tmp_path,
                                                    sort_fixture,
                                                    capsys):
    """A job journaled with non-default output-affecting knobs must be
    resumable from the bare CLI: the header's recorded field values
    rebuild the config, instead of DEFAULT_CONFIG's fingerprint
    refusing a journal nothing actually invalidated."""
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
    from hadoop_bam_tpu.tools import cli

    cfg = dataclasses.replace(NOSYNC, write_compress_level=1)
    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    n1 = sort_bam_mesh(sort_fixture["src"], out,
                       round_records=sort_fixture["round_records"],
                       journal_path=jp, config=cfg)
    want = open(out, "rb").read()
    assert want != sort_fixture["oracle_bytes"]    # level 1 != level 6
    os.unlink(out)                                 # force a rebuild
    assert cli.main(["resume", jp]) == 0
    capsys.readouterr()
    assert open(out, "rb").read() == want
    assert n1 == sort_fixture["records"]


def test_hbam_resume_and_jobs_cli(tmp_path, sort_fixture, capsys):
    """The CLI verbs over a real killed job: `hbam jobs` reports it
    resumable, `hbam resume` finishes it byte-identically."""
    from hadoop_bam_tpu.tools import cli

    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    r = _run_child(_SORT_CHILD, 1, sort_fixture["src"], out, jp,
                   sort_fixture["round_records"])
    assert r.returncode == -signal.SIGKILL

    assert cli.main(["jobs", str(tmp_path)]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert any("mesh_sort_spill" in ln and "resumable" in ln
               for ln in lines)

    assert cli.main(["resume", jp]) == 0
    cap = capsys.readouterr().out
    assert open(out, "rb").read() == sort_fixture["oracle_bytes"]
    # the verb reports the skip counters (value is the process-global
    # accumulation, so pin presence, not magnitude)
    assert "jobs.rounds_skipped" in cap

    assert cli.main(["jobs", str(tmp_path)]) == 0
    assert "done" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# SIGKILL mid-cohort-join -> resumed chunks byte-identical
# ---------------------------------------------------------------------------

def _cohort_fixture(tmp_path):
    from test_cohort import _random_sample_lines, _write_sample

    rng = random.Random(17)
    files = []
    for i in range(4):
        p = str(tmp_path / f"s{i}.vcf")
        _write_sample(p, f"s{i}", _random_sample_lines(rng, n_sites=25))
        files.append(p)
    mp = str(tmp_path / "cohort.json")
    with open(mp, "w") as f:
        json.dump({"samples": [{"id": f"s{i}", "path": p}
                               for i, p in enumerate(files)]}, f)
    return mp


def _chunks_of(ds):
    return [{k: v.copy() for k, v in c.items()}
            for c in ds.site_chunks()]


def _assert_chunks_equal(a, b):
    assert len(a) == len(b)
    for ca, cb in zip(a, b):
        for k in ca:
            np.testing.assert_array_equal(ca[k], cb[k])


def test_sigkill_mid_cohort_join_resumes_identical(tmp_path):
    from hadoop_bam_tpu.cohort.dataset import open_cohort

    mp = _cohort_fixture(tmp_path)
    cfg = dataclasses.replace(NOSYNC, cohort_chunk_sites=11)
    oracle = _chunks_of(open_cohort(mp, cfg))
    assert len(oracle) > 4

    jp = str(tmp_path / "cohort.hbam-journal")
    r = _run_child("""
        import os, signal, sys, dataclasses
        os.environ.pop("JAX_PLATFORMS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from hadoop_bam_tpu.jobs import JobJournal
        mp, jp = sys.argv[1:3]
        orig = JobJournal.unit_done
        n = [0]
        def patched(self, kind, key, **kw):
            orig(self, kind, key, **kw)
            n[0] += 1
            if n[0] >= 3:
                os.kill(os.getpid(), signal.SIGKILL)
        JobJournal.unit_done = patched
        from hadoop_bam_tpu.cohort.dataset import open_cohort
        from hadoop_bam_tpu.config import DEFAULT_CONFIG
        cfg = dataclasses.replace(DEFAULT_CONFIG, cohort_chunk_sites=11,
                                  journal_fsync=False)
        for _ in open_cohort(mp, cfg, journal_path=jp).site_chunks():
            pass
        raise SystemExit("unreachable: child must have been killed")
    """, mp, jp, timeout=120)
    assert r.returncode == -signal.SIGKILL, (r.returncode,
                                             r.stderr[-2000:])
    assert len(JobJournal.replay(jp).units) == 3

    with MetricsContext() as m:
        got = _chunks_of(open_cohort(mp, cfg, journal_path=jp))
    snap = m.snapshot()
    _assert_chunks_equal(oracle, got)
    assert snap["counters"].get("jobs.chunks_replayed") == 3
    # finished job: a THIRD pass is pure replay — no join work at all
    with MetricsContext() as m:
        again = _chunks_of(open_cohort(mp, cfg, journal_path=jp))
    snap = m.snapshot()
    _assert_chunks_equal(oracle, again)
    assert snap["counters"].get("jobs.jobs_skipped") == 1
    assert "cohort.join_wall" not in snap.get("wall_timers", {})


def test_concurrent_journaled_joins_refused(tmp_path):
    """Two live journaled iterations of one dataset would be two
    writers on one journal — the second must refuse up front instead of
    corrupting it; a finished iteration releases the guard."""
    from hadoop_bam_tpu.cohort.dataset import open_cohort

    mp = _cohort_fixture(tmp_path)
    cfg = dataclasses.replace(NOSYNC, cohort_chunk_sites=11)
    jp = str(tmp_path / "cohort.hbam-journal")
    ds = open_cohort(mp, cfg, journal_path=jp)
    it = ds.site_chunks()
    next(it)                                   # live mid-iteration
    with pytest.raises(PlanError, match="already in progress"):
        ds.site_chunks()
    for _ in it:                               # exhaust -> releases
        pass
    assert len(_chunks_of(ds)) > 0             # sequential reuse is fine
    # a generator that is created but NEVER STARTED must not take the
    # lock (or open the journal) — the setup is lazy, at first next()
    never_started = ds.site_chunks()
    del never_started
    assert len(_chunks_of(ds)) > 0


def test_cohort_resume_refuses_changed_inputs(tmp_path):
    from hadoop_bam_tpu.cohort.dataset import open_cohort

    mp = _cohort_fixture(tmp_path)
    cfg = dataclasses.replace(NOSYNC, cohort_chunk_sites=11)
    jp = str(tmp_path / "cohort.hbam-journal")
    _chunks_of(open_cohort(mp, cfg, journal_path=jp))
    time.sleep(0.01)
    with open(str(tmp_path / "s1.vcf"), "a") as f:
        f.write("chr21\t99999999\t.\tA\tC\t50\tPASS\t.\tGT:DP\t0/1:9\n")
    with pytest.raises(PlanError, match="input file identity"):
        _chunks_of(open_cohort(mp, cfg, journal_path=jp))
    # and a changed chunk size is an output-affecting fingerprint change
    sub = tmp_path / "x2"
    sub.mkdir()
    jp2 = str(tmp_path / "cohort2.hbam-journal")
    mp2 = _cohort_fixture(sub)
    _chunks_of(open_cohort(mp2, cfg, journal_path=jp2))
    cfg2 = dataclasses.replace(cfg, cohort_chunk_sites=7)
    with pytest.raises(PlanError, match="fingerprint"):
        _chunks_of(open_cohort(mp2, cfg2, journal_path=jp2))


# ---------------------------------------------------------------------------
# multi-host loss detection plumbing (single-process observables)
# ---------------------------------------------------------------------------

def test_collective_heartbeats_and_timeout():
    from hadoop_bam_tpu.parallel.distributed import _run_collective

    with MetricsContext() as m:
        out = _run_collective(lambda: (time.sleep(0.1) or 7),
                              "probe", timeout_s=5.0)
    snap = m.snapshot()
    assert out == 7
    assert snap["counters"].get("distributed.heartbeats", 0) >= 1
    assert "distributed.collective_wait_s" in snap.get("histograms", {})
    ev = threading.Event()
    try:
        with pytest.raises(TransientIOError, match="timed out"):
            _run_collective(ev.wait, "hung", timeout_s=0.2)
    finally:
        ev.set()


def test_collective_timeout_config_knob():
    from hadoop_bam_tpu.parallel.distributed import collective_timeout

    assert collective_timeout(DEFAULT_CONFIG) is None
    cfg = dataclasses.replace(DEFAULT_CONFIG, collective_timeout_s=12.5)
    assert collective_timeout(cfg) == 12.5
    assert collective_timeout(None) is None
    from hadoop_bam_tpu.config import HBamConfig
    assert HBamConfig.from_dict(
        {"hbam.collective-timeout-s": "3.5",
         "hbam.pool-task-timeout-s": "9",
         "hbam.speculative-decode": "false",
         "hbam.journal-fsync": "0",
         "hbam.straggler-multiplier": "6",
         "hbam.straggler-min-s": "0.25"}) == HBamConfig(
        collective_timeout_s=3.5, pool_task_timeout_s=9.0,
        speculative_decode=False, journal_fsync=False,
        straggler_multiplier=6.0, straggler_min_s=0.25)
