"""VCF/BCF family tests: codecs, guesser, spans, writers, mergers.

Mirrors the reference's test strategy for test/TestVCFInputFormat.java,
test/TestVCFOutputFormat.java, test/TestVCFRoundTrip.java (SURVEY.md
section 4): round-trips through our own codecs plus every-byte-offset split
robustness — the union of all spans must yield each record exactly once no
matter where boundaries land.
"""
from __future__ import annotations

import io
import os
import random

import pytest

from hadoop_bam_tpu.config import HBamConfig
from hadoop_bam_tpu.api.dispatch import (
    VCFContainer, clear_sniff_caches, sniff_vcf_container,
)
from hadoop_bam_tpu.api.vcf_dataset import open_vcf
from hadoop_bam_tpu.api.writers import (
    BcfShardWriter, VcfShardWriter, open_vcf_writer,
)
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bcf import BCFRecordCodec, encode_header
from hadoop_bam_tpu.formats.bcfio import BcfWriter, read_bcf, read_bcf_header, write_bcf
from hadoop_bam_tpu.formats.vcf import VCFHeader, VariantBatch, VcfRecord
from hadoop_bam_tpu.split.bcf_guesser import BCFSplitGuesser
from hadoop_bam_tpu.split.spans import FileByteSpan, FileVirtualSpan
from hadoop_bam_tpu.split.vcf_planners import (
    plan_bcf_spans, plan_bgzf_text_spans, read_bcf_span, read_bgzf_text_span,
)
from hadoop_bam_tpu.utils.mergers import merge_bcf_shards, merge_vcf_shards

HEADER_TEXT = """##fileformat=VCFv4.2
##contig=<ID=chr20,length=64444167>
##contig=<ID=chr21,length=46709983>
##FILTER=<ID=q10,Description="Quality below 10">
##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">
##INFO=<ID=AF,Number=A,Type=Float,Description="Allele freq">
##INFO=<ID=DB,Number=0,Type=Flag,Description="dbSNP membership">
##INFO=<ID=END,Number=1,Type=Integer,Description="End position">
##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">
##FORMAT=<ID=DP,Number=1,Type=Integer,Description="Read depth">
##FORMAT=<ID=PL,Number=G,Type=Integer,Description="Phred likelihoods">
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\tS2
"""


def make_vcf_header() -> VCFHeader:
    return VCFHeader.from_text(HEADER_TEXT)


def make_variants(n: int, seed: int = 0):
    rng = random.Random(seed)
    recs = []
    pos = 0
    for i in range(n):
        pos += rng.randint(1, 500)
        chrom = "chr20" if pos % 3 else "chr21"
        ref = rng.choice(["A", "C", "G", "T", "AT", "GCC"])
        alts = tuple(rng.sample(["A", "C", "G", "T", "TT"],
                                rng.randint(1, 2)))
        alts = tuple(a for a in alts if a != ref) or ("T" if ref != "T" else "A",)
        gts = []
        for _ in range(2):
            a = rng.randint(0, len(alts))
            b = rng.randint(0, len(alts))
            dp = rng.randint(0, 90)
            gts.append(f"{a}/{b}:{dp}")
        recs.append(VcfRecord(
            chrom=chrom, pos=pos,
            id=f"rs{i}" if rng.random() < 0.3 else None,
            ref=ref, alts=alts,
            qual=round(rng.uniform(1, 100), 1) if rng.random() < 0.8 else None,
            filters=("PASS",) if rng.random() < 0.7 else ("q10",),
            info={"DP": str(rng.randint(1, 99)),
                  **({"DB": True} if rng.random() < 0.2 else {})},
            fmt=("GT", "DP"), genotypes=gts))
    return recs


@pytest.fixture(scope="module")
def vcf_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("vcf")
    header = make_vcf_header()
    recs = make_variants(400, seed=7)
    text_path = str(d / "t.vcf")
    with VcfShardWriter(text_path, header, write_header=True) as w:
        for r in recs:
            w.write_record(r)
    gz_path = str(d / "t.vcf.gz")
    # small blocks so splits land mid-stream often
    with open(gz_path, "wb") as f:
        bw = bgzf.BGZFWriter(f, level=5)
        bw.write(header.to_text().encode())
        for r in recs:
            bw.write((r.to_line() + "\n").encode())
            if bw.tell_voffset() & 0xFFFF > 1200:
                bw.flush()
        bw.close()
    bcf_path = str(d / "t.bcf")
    with BcfWriter(bcf_path, header, level=5) as w:
        for r in recs:
            w.write_record(r)
            if w._w.tell_voffset() & 0xFFFF > 1200:
                w._w.flush()  # small blocks so splits land mid-stream
    raw_bcf_path = str(d / "t_raw.bcf")
    with BcfWriter(raw_bcf_path, header, compress=False) as w:
        for r in recs:
            w.write_record(r)
    return {"dir": d, "header": header, "recs": recs,
            "vcf": text_path, "vcf_gz": gz_path, "bcf": bcf_path,
            "raw_bcf": raw_bcf_path}


# ---------------------------------------------------------------------------
# codec round-trips
# ---------------------------------------------------------------------------

def test_vcf_line_roundtrip(vcf_files):
    for r in vcf_files["recs"]:
        assert VcfRecord.from_line(r.to_line()).to_line() == r.to_line()


def test_bcf_record_roundtrip(vcf_files):
    codec = BCFRecordCodec(vcf_files["header"])
    for r in vcf_files["recs"][:100]:
        buf = codec.encode(r)
        r2, end = codec.decode(buf)
        assert end == len(buf)
        assert r2.to_line() == r.to_line()


def test_bcf_file_roundtrip(vcf_files):
    header, recs = read_bcf(vcf_files["bcf"])
    assert header.to_text() == vcf_files["header"].to_text()
    assert [r.to_line() for r in recs] == \
        [r.to_line() for r in vcf_files["recs"]]


def test_raw_bcf_file_roundtrip(vcf_files):
    _, recs = read_bcf(vcf_files["raw_bcf"])
    assert [r.to_line() for r in recs] == \
        [r.to_line() for r in vcf_files["recs"]]


def test_header_dictionaries():
    h = make_vcf_header()
    d = h.string_dictionary()
    assert d[0] == "PASS"
    assert set(["q10", "DP", "AF", "GT", "PL"]) <= set(d)
    assert h.contigs == ["chr20", "chr21"]
    assert h.samples == ["S1", "S2"]


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def test_sniff_containers(vcf_files):
    clear_sniff_caches()
    cfg = HBamConfig(vcf_trust_exts=False)  # force content sniffing
    assert sniff_vcf_container(vcf_files["vcf"], cfg) is VCFContainer.VCF
    assert sniff_vcf_container(vcf_files["vcf_gz"], cfg) is VCFContainer.VCF_BGZF
    assert sniff_vcf_container(vcf_files["bcf"], cfg) is VCFContainer.BCF
    assert sniff_vcf_container(vcf_files["raw_bcf"], cfg) is VCFContainer.BCF
    clear_sniff_caches()


# ---------------------------------------------------------------------------
# datasets: union-of-spans == whole file
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("key", ["vcf", "vcf_gz", "bcf", "raw_bcf"])
@pytest.mark.parametrize("num_spans", [1, 3, 8])
def test_dataset_span_union(vcf_files, key, num_spans):
    clear_sniff_caches()
    ds = open_vcf(vcf_files[key], HBamConfig(vcf_trust_exts=False))
    got = [r.to_line() for r in ds.records(num_spans=num_spans)]
    want = [r.to_line() for r in vcf_files["recs"]]
    assert got == want


def test_dataset_checkpoint_resume(vcf_files):
    clear_sniff_caches()
    ds = open_vcf(vcf_files["bcf"])
    it = ds.records(num_spans=4)
    first = [next(it).to_line() for _ in range(3)]
    state = ds.state_dict()
    ds2 = open_vcf(vcf_files["bcf"])
    ds2.load_state_dict(state)
    got = first[:0]  # records already consumed inside span 0 are re-read:
    # resume is span-granular, like re-running a map task from its split start
    rest = [r.to_line() for r in ds2.records()]
    all_lines = [r.to_line() for r in vcf_files["recs"]]
    assert rest[-1] == all_lines[-1]
    assert set(rest) <= set(all_lines)


# ---------------------------------------------------------------------------
# split robustness: every-byte-offset guessing (THE critical property)
# ---------------------------------------------------------------------------

def test_bcf_guesser_every_offset(vcf_files):
    """From every byte offset, the guesser must find a true record boundary
    (or EOF) — and never a false positive that decodes garbage."""
    path = vcf_files["bcf"]
    header = vcf_files["header"]
    size = os.path.getsize(path)
    g = BCFSplitGuesser(path, header, is_bgzf=True)
    want = [r.to_line() for r in vcf_files["recs"]]
    # a sample of offsets incl. adversarial ones near block boundaries
    rng = random.Random(3)
    offsets = sorted({0, 1, size - 1, size // 2} |
                     {rng.randrange(size) for _ in range(40)})
    for off in offsets:
        v = g.guess_next_record_start(off)
        if v is None:
            continue
        span = FileVirtualSpan(path, v, size << 16)
        recs = read_bcf_span(path, span, header=header, is_bgzf=True)
        got = [r.to_line() for r in recs]
        # suffix property: records from the guessed point = tail of the file
        assert got == want[len(want) - len(got):]


def test_bcf_spans_every_boundary(vcf_files):
    """Union of spans yields every record exactly once for many span counts."""
    path = vcf_files["bcf"]
    want = [r.to_line() for r in vcf_files["recs"]]
    for num_spans in (2, 5, 13):
        spans = plan_bcf_spans(path, num_spans=num_spans)
        got = []
        for s in spans:
            got += [r.to_line() for r in
                    read_bcf_span(path, s, header=vcf_files["header"],
                                  is_bgzf=True)]
        assert got == want, f"num_spans={num_spans}"


def test_bgzf_text_spans_every_boundary(vcf_files):
    path = vcf_files["vcf_gz"]
    raw = open(path, "rb").read()
    want = [r.to_line() for r in vcf_files["recs"]]
    # adversarial: span boundaries at every block start +/- 1
    blocks = [b.coffset for b in bgzf.scan_blocks(raw)]
    size = len(raw)
    for num_spans in (2, 7):
        spans = plan_bgzf_text_spans(path, num_spans=num_spans)
        assert spans[0].start == 0 and spans[-1].end == size
        got = []
        for s in spans:
            text = read_bgzf_text_span(path, s).decode()
            got += [l for l in text.splitlines() if l and not l.startswith("#")]
        assert got == want, f"num_spans={num_spans}"
    # hand-crafted spans exactly on block boundaries
    mid = blocks[len(blocks) // 2]
    for cut in (mid, mid - 1, mid + 1):
        s1 = FileByteSpan(path, 0, cut)
        s2 = FileByteSpan(path, cut, size)
        # snap: s2 must begin at a block start; emulate planner snapping
        g_start = cut if cut in blocks else next(b for b in blocks if b > cut)
        s1 = FileByteSpan(path, 0, g_start)
        s2 = FileByteSpan(path, g_start, size)
        text = (read_bgzf_text_span(path, s1) +
                read_bgzf_text_span(path, s2)).decode()
        got = [l for l in text.splitlines() if l and not l.startswith("#")]
        assert got == want, f"cut={cut}"


# ---------------------------------------------------------------------------
# writers + mergers
# ---------------------------------------------------------------------------

def test_vcf_output_format_dispatch(vcf_files, tmp_path):
    header = vcf_files["header"]
    w = open_vcf_writer(str(tmp_path / "o.bcf"), header)
    assert isinstance(w, BcfShardWriter)
    w.close()
    w = open_vcf_writer(str(tmp_path / "o.vcf"), header)
    assert isinstance(w, VcfShardWriter)
    w.close()
    cfg = HBamConfig(vcf_output_format="BCF")
    w = open_vcf_writer(str(tmp_path / "part-00000"), header, cfg)
    assert isinstance(w, BcfShardWriter)
    w.close()


def test_merge_vcf_shards(vcf_files, tmp_path):
    header = vcf_files["header"]
    recs = vcf_files["recs"]
    cfg = HBamConfig(write_header=False, write_terminator=False)
    paths = []
    for i, lo in enumerate(range(0, len(recs), 150)):
        p = str(tmp_path / f"part-{i:05d}")
        with VcfShardWriter(p, header, cfg) as w:
            for r in recs[lo:lo + 150]:
                w.write_record(r)
        paths.append(p)
    out = str(tmp_path / "merged.vcf")
    merge_vcf_shards(paths, out, header)
    ds = open_vcf(out, HBamConfig(vcf_trust_exts=True))
    assert [r.to_line() for r in ds.records(num_spans=2)] == \
        [r.to_line() for r in recs]


def test_merge_bcf_shards(vcf_files, tmp_path):
    header = vcf_files["header"]
    recs = vcf_files["recs"]
    cfg = HBamConfig(write_header=False, write_terminator=False)
    paths = []
    for i, lo in enumerate(range(0, len(recs), 170)):
        p = str(tmp_path / f"part-{i:05d}.bcfshard")
        with BcfShardWriter(p, header, cfg) as w:
            for r in recs[lo:lo + 170]:
                w.write_record(r)
        paths.append(p)
    out = str(tmp_path / "merged.bcf")
    merge_bcf_shards(paths, out, header)
    hdr, got = read_bcf(out)
    assert [r.to_line() for r in got] == [r.to_line() for r in recs]
    # merged file ends with the EOF terminator [SPEC]
    assert open(out, "rb").read().endswith(bgzf.EOF_BLOCK)


# ---------------------------------------------------------------------------
# SoA batch
# ---------------------------------------------------------------------------

def test_variant_batch_columns(vcf_files):
    header = vcf_files["header"]
    recs = vcf_files["recs"][:50]
    b = VariantBatch(recs, header)
    assert len(b) == 50
    for i, r in enumerate(recs):
        assert b.pos[i] == r.pos
        assert b.chrom[i] == header.contig_index(r.chrom)
        assert b.n_allele[i] == r.n_allele


def test_plain_gzip_vcf_fallback(tmp_path):
    """A .vcf.gz that is plain gzip (NOT BGZF) reads as one whole-file
    span — the BGZFEnhancedGzipCodec fallback behavior — and stats work."""
    import gzip

    from hadoop_bam_tpu.api.dispatch import (
        VCFContainer, sniff_vcf_container, _vcf_cache,
    )
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf

    header_text = ("##fileformat=VCFv4.2\n"
                   "##contig=<ID=c1,length=1000>\n"
                   "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
    body = "".join(f"c1\t{10+i}\t.\tA\tG\t40\tPASS\t.\n" for i in range(300))
    path = str(tmp_path / "p.vcf.gz")
    with open(path, "wb") as f:
        f.write(gzip.compress((header_text + body).encode()))
    _vcf_cache.clear()
    assert sniff_vcf_container(path) is VCFContainer.VCF_GZIP
    ds = open_vcf(path)
    recs = list(ds.records())
    assert len(recs) == 300 and recs[0].pos == 10 and recs[-1].pos == 309
    stats = ds.variant_stats()
    assert stats["n_variants"] == 300 and stats["n_snp"] == 300


def test_tabix_query(tmp_path):
    """Build .tbi over a sorted BGZF VCF; region queries return exactly the
    overlapping records, reading only indexed chunk ranges."""
    import random

    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.split.tabix import TabixIndex, write_tabix

    header_text = ("##fileformat=VCFv4.2\n"
                   "##contig=<ID=c1,length=2000000>\n"
                   "##contig=<ID=c2,length=2000000>\n"
                   "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
    rng = random.Random(29)
    recs = []
    for chrom in ("c1", "c2"):
        poss = sorted(rng.sample(range(1, 1999000), 4000))
        for p in poss:
            recs.append((chrom, p))
    lines = [f"{c}\t{p}\t.\tA\tG\t30\tPASS\t." for c, p in recs]
    path = str(tmp_path / "t.vcf.gz")
    open(path, "wb").write(
        bgzf.compress_bytes((header_text + "\n".join(lines) + "\n")
                            .encode()))
    out = write_tabix(path)
    idx = TabixIndex.from_bytes(open(out, "rb").read())
    assert idx.names == ["c1", "c2"] and idx.fmt == 2

    ds = open_vcf(path)
    for region, want in (
        ("c1:500000-700000",
         [(c, p) for c, p in recs if c == "c1" and 500000 <= p <= 700000]),
        ("c2:1-1000",
         [(c, p) for c, p in recs if c == "c2" and p <= 1000]),
        ("c1", [(c, p) for c, p in recs if c == "c1"]),
    ):
        got = [(r.chrom, r.pos) for r in ds.query(region)]
        assert got == want, (region, len(got), len(want))
    assert list(ds.query("c9:1-100")) == []
