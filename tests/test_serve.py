"""Serving-tier tests (``pytest -m serve``): the device-resident tile
cache bypassing host decode, slot-pinning aliasing proofs, predictive
prefetch usefulness, per-tenant quota/priority isolation, per-client
MetricsContext isolation across the shared pool, the thread-safety
hammer over ``ChunkCache``, enqueue-anchored deadlines, background
pool priority, and the JSONL transports.
"""
import concurrent.futures as cf
import dataclasses
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.query import (
    ChunkCache, QueryEngine, QueryRequest, QueryScheduler,
)
from hadoop_bam_tpu.serve import ServeLoop, handle_stream
from hadoop_bam_tpu.utils.errors import PlanError, TransientIOError
from hadoop_bam_tpu.utils.metrics import METRICS, MetricsContext

from fixtures import make_header, make_records

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _coord_sorted(header, recs):
    def key(r):
        rid = (header.ref_names.index(r.rname) if r.rname != "*"
               else 1 << 30)
        return (rid, r.pos)
    return sorted(recs, key=key)


def _write_bam(path, header, n, seed):
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    recs = _coord_sorted(header, make_records(header, n, seed=seed))
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    write_bai(path)


@pytest.fixture(scope="module")
def served_bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("serve") / "s.bam")
    header = make_header(2)
    _write_bam(path, header, 2500, seed=77)
    return path, header


_REGIONS = ["chr1:1000-200000", "chr1:500,000-650,000", "chr2:1-5000",
            "chr2:100000-400000"]


def _oracle_counts(path, regions):
    engine = QueryEngine()
    res = engine.query_records([QueryRequest(path, r) for r in regions])
    return [len(r.records) for r in res], res


# ---------------------------------------------------------------------------
# tile cache: hits bypass the decode path entirely
# ---------------------------------------------------------------------------

def test_serve_counts_match_engine_oracle(served_bam):
    path, _header = served_bam
    want, _ = _oracle_counts(path, _REGIONS)
    with ServeLoop() as loop:
        res = loop.query(path, _REGIONS)
        assert [r.count for r in res] == want
        assert sum(want) > 0
        assert all(r.n_candidates >= r.count for r in res)


def test_warm_tile_hits_skip_decode_and_host_work(served_bam):
    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False)
    with ServeLoop(config=cfg) as loop:
        cold = loop.query(path, _REGIONS)
        assert all(r.tile_misses > 0 for r in cold)
        with MetricsContext() as warm_metrics:
            warm = loop.query(path, _REGIONS)
        # identical results off the warm path...
        assert [r.count for r in warm] == [r.count for r in cold]
        # ...with every chunk served from resident device tiles:
        assert all(r.tile_misses == 0 and r.tile_hits > 0 for r in warm)
        # the whole decode path was bypassed — the warm run's isolated
        # context saw no fresh chunk decodes and ZERO host-decode work
        snap = warm_metrics.snapshot()
        assert snap["counters"].get("query.chunks_decoded", 0) == 0
        assert snap["timers"].get("pipeline.host_decode", 0.0) == 0.0
        assert snap["timers"].get("pipeline.inflate", 0.0) == 0.0
        assert loop.tiles.stats()["hits"] > 0


def test_records_mode_matches_oracle_byte_identical(served_bam):
    path, _header = served_bam
    _want_counts, oracle = _oracle_counts(path, _REGIONS[:2])
    with ServeLoop() as loop:
        loop.query(path, _REGIONS[:2])          # warm the tiles
        res = loop.query(path, _REGIONS[:2], want_records=True)
    for out, want in zip(res, oracle):
        assert [r.to_line() for r in out.records] == \
            [r.to_line() for r in want.records]
    assert sum(len(o.records) for o in res) > 0


def test_tile_invalidation_on_file_change(tmp_path):
    """Rewriting the file invalidates resident tiles: the next query is
    byte-identical to a fresh cold engine on the NEW bytes."""
    path = str(tmp_path / "inval.bam")
    header = make_header(1)
    region = "chr1:1-1000000"
    _write_bam(path, header, 400, seed=1)
    with ServeLoop() as loop:
        first = loop.query(path, [region], want_records=True)[0]
        assert first.records

        _write_bam(path, header, 150, seed=2)   # replace in place
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))

        second = loop.query(path, [region], want_records=True)[0]
        _counts, oracle = _oracle_counts(path, [region])
        assert [r.to_line() for r in second.records] == \
            [r.to_line() for r in oracle[0].records]
        assert [r.to_line() for r in second.records] != \
            [r.to_line() for r in first.records]
        # the old identity's tiles were proactively purged, not merely
        # orphaned under a dead key
        assert loop.tiles.stats()["invalidated"] > 0


def test_tile_cache_evicts_but_stays_correct(served_bam):
    path, _header = served_bam
    # cap 512 -> one group is 3 * 8dev * 512 * 4B ~= 49 KiB; a 120 KB
    # budget holds ~2 of the 4 regions' tiles, forcing LRU churn
    cfg = dataclasses.replace(DEFAULT_CONFIG,
                              serve_tile_records=512,
                              serve_tile_cache_bytes=120_000,
                              serve_prefetch=False)
    want, _ = _oracle_counts(path, _REGIONS)
    with ServeLoop(config=cfg) as loop:
        for _ in range(3):
            res = loop.query(path, _REGIONS)
            assert [r.count for r in res] == want
        stats = loop.tiles.stats()
        assert stats["evictions"] > 0
        assert stats["bytes"] <= stats["byte_budget"]


def test_device_tile_cache_unit_semantics():
    from hadoop_bam_tpu.serve import DeviceTileCache
    from hadoop_bam_tpu.serve.tiles import TileSet

    def ts(ident, nbytes):
        return TileSet(groups=[], n=0, nbytes=nbytes, ident=ident)

    ident_a = ("/f/a.bam", 10, 111)
    cache = DeviceTileCache(byte_budget=100)
    cache.put((ident_a, "bam", 0, 1, "iv", 8, 64), ts(ident_a, 60))
    cache.put((ident_a, "bam", 2, 3, "iv", 8, 64), ts(ident_a, 30))
    assert len(cache) == 2
    # same path, NEW identity: the old identity's entries purge
    ident_a2 = ("/f/a.bam", 11, 222)
    cache.put((ident_a2, "bam", 0, 1, "iv", 8, 64), ts(ident_a2, 10))
    assert cache.get((ident_a, "bam", 0, 1, "iv", 8, 64)) is None
    assert cache.stats()["invalidated"] == 2
    # byte budget enforces LRU eviction
    ident_b = ("/f/b.bam", 1, 1)
    cache.put((ident_b, "bam", 0, 1, "iv", 8, 64), ts(ident_b, 95))
    assert cache.bytes_used <= 100
    # oversize entries are never admitted
    cache.put((ident_b, "bam", 9, 9, "iv", 8, 64), ts(ident_b, 1000))
    assert cache.get((ident_b, "bam", 9, 9, "iv", 8, 64)) is None
    with pytest.raises(PlanError):
        DeviceTileCache(byte_budget=0)


# ---------------------------------------------------------------------------
# slot pinning: cached device tiles are never aliased by ring reuse
# ---------------------------------------------------------------------------

def test_pinned_slot_leaves_ring_and_is_replenished():
    from hadoop_bam_tpu.parallel.staging import StagingRing, TileSpec

    ring = StagingRing(2, 4, [TileSpec((), np.int32)], slots=2)
    cancel = threading.Event()
    a = ring.lease(cancel)
    a.arrays[0][:] = 7
    a.pin()
    a.release()                       # ownership leaves the ring
    assert a.parked
    # capacity unchanged: two OTHER buffer sets circulate
    b = ring.lease(cancel)
    c = ring.lease(cancel)
    assert b is not a and c is not a
    for s in (b, c):
        assert s.arrays[0] is not a.arrays[0]
        s.arrays[0][:] = 123          # scribble: must never touch a
        s.release()
    # churn hard: the pinned buffers never re-enter circulation
    for _ in range(6):
        s = ring.lease(cancel)
        assert s is not a and s.arrays[0] is not a.arrays[0]
        s.arrays[0][:] = 9
        s.release()
    assert np.all(a.arrays[0] == 7)
    a.unpin()                         # relinquish bookkeeping only...
    s = ring.lease(cancel)
    assert s is not a                 # ...still never re-leased
    # an unpin BEFORE release cancels the pin: normal recirculation
    s.pin()
    s.unpin()
    s.release()
    assert ring.lease(cancel) in (s, b, c)


def test_cached_tiles_survive_ring_churn(served_bam):
    """The serve-level aliasing proof: snapshot a cached tile's device
    values, push many other queries through the same builder ring, and
    require the snapshot to still match — a recycled (aliased) slot
    would have scribbled over it."""
    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False,
                              serve_tile_records=256)
    with ServeLoop(config=cfg) as loop:
        loop.query(path, [_REGIONS[0]])
        key, tiles = next(iter(loop.tiles._entries.items()))
        snap = [tuple(np.asarray(c).copy() for c in g.cols)
                for g in tiles.groups]
        # churn: every other region, twice, through the same ring
        for _ in range(2):
            loop.query(path, _REGIONS[1:])
        tiles2 = loop.tiles._entries.get(key)
        assert tiles2 is tiles
        for g, cols in zip(tiles.groups, snap):
            for dev_col, saved in zip(g.cols, cols):
                assert np.array_equal(np.asarray(dev_col), saved)


def test_quarantined_chunk_not_cached_as_empty_tile(served_bam):
    """skip_bad_spans quarantine serves a faulted chunk as empty but
    must NOT freeze that emptiness into the device tile tier — once the
    fault heals, the same region re-decodes and serves its records."""
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on

    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=0, serve_prefetch=False)
    region = "chr2:100000-400000"
    with ServeLoop(config=cfg) as loop:
        loop.query(path, ["chr1:1-2000"])     # warm metadata cleanly
        with chaos_on(path, [FaultSpec("bitflip", at_read=0, count=64,
                                       xor_mask=0xFF)]):
            faulted = loop.query(path, [region])[0]
        assert faulted.count == 0             # quarantined, not crashed
        healed = loop.query(path, [region])[0]
        _counts, oracle = _oracle_counts(path, [region])
        assert healed.count == len(oracle[0].records) > 0


# ---------------------------------------------------------------------------
# predictive prefetch
# ---------------------------------------------------------------------------

def test_prefetch_decodes_adjacent_windows(served_bam):
    path, _header = served_bam
    with ServeLoop() as loop:
        loop.query(path, ["chr1:1000-60000"])
        loop.prefetcher.drain()
        assert loop.prefetcher.stats()["issued"] > 0
        assert METRICS.get("serve.prefetch_issued") > 0
        # the EXACT adjacent window (width 59001 -> [60001, 119001])
        # arrives already host-decoded: the foreground serves from the
        # cache (hits, prefetch booked useful); the only decodes in
        # this query's context are the NEXT windows' background
        # prefetch, which rides the submitter's context by design
        adjacent = "chr1:60001-119001"
        with MetricsContext() as m:
            res = loop.query(path, [adjacent])[0]
            loop.prefetcher.drain()
        assert m.counters.get("serve.prefetch_useful", 0) >= 1
        assert m.counters.get("query.cache_hits", 0) >= 1
        assert m.counters.get("query.chunks_decoded", 0) <= \
            m.counters.get("serve.prefetch_issued", 0)
        assert loop.prefetcher.stats()["useful"] > 0
        _counts, oracle = _oracle_counts(path, [adjacent])
        assert res.count == len(oracle[0].records)


def test_prefetch_disabled_issues_nothing(served_bam):
    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False)
    with ServeLoop(config=cfg) as loop:
        loop.query(path, ["chr1:1000-60000"])
        loop.prefetcher.drain()
        assert loop.prefetcher.stats()["issued"] == 0


# ---------------------------------------------------------------------------
# background pool priority
# ---------------------------------------------------------------------------

def test_background_submit_never_starves_foreground():
    from hadoop_bam_tpu.utils import pools

    pool = cf.ThreadPoolExecutor(max_workers=4)
    release = threading.Event()
    peak = [0]
    running = [0]
    lock = threading.Lock()

    def bg_task():
        with lock:
            running[0] += 1
            peak[0] = max(peak[0], running[0])
        release.wait(5.0)
        with lock:
            running[0] -= 1
        return "bg"

    try:
        bg_futs = [pools.submit(pool, bg_task, priority="bg")
                   for _ in range(6)]
        time.sleep(0.05)
        # background concurrency is capped at a quarter of the pool
        assert pools.background_limit(pool) == 1
        assert peak[0] <= 1
        # foreground tasks run immediately despite queued bg work
        t0 = time.perf_counter()
        assert pools.submit(pool, lambda: "fg").result(timeout=2.0) == "fg"
        assert time.perf_counter() - t0 < 1.0
        release.set()
        assert [f.result(timeout=10.0) for f in bg_futs] == ["bg"] * 6
        assert peak[0] <= 1
    finally:
        release.set()
        pool.shutdown(wait=True)


def test_cancel_background_drops_queued_tasks():
    from hadoop_bam_tpu.utils import pools

    pool = cf.ThreadPoolExecutor(max_workers=4)
    release = threading.Event()
    try:
        first = pools.submit(pool, release.wait, 5.0, priority="bg")
        time.sleep(0.02)          # let the first occupy the bg permit
        queued = [pools.submit(pool, lambda: None, priority="bg")
                  for _ in range(3)]
        cancelled = pools.cancel_background()
        assert cancelled == 3
        assert all(f.cancelled() for f in queued)
        release.set()
        first.result(timeout=5.0)
    finally:
        release.set()
        pool.shutdown(wait=True)


def test_bad_priority_is_plan_error():
    from hadoop_bam_tpu.utils import pools

    pool = cf.ThreadPoolExecutor(max_workers=1)
    try:
        with pytest.raises(PlanError):
            pools.submit(pool, lambda: None, priority="urgent")
    finally:
        pool.shutdown(wait=True)


# ---------------------------------------------------------------------------
# tenancy: quotas + priority classes
# ---------------------------------------------------------------------------

def test_tenant_quota_sheds_only_the_flooder(served_bam):
    """Tenant A saturating its quota sheds A's overflow with
    TransientIOError while tenant B keeps admitting and serving within
    its deadline — the isolation contract, deterministically: A's one
    slot is occupied directly through its admission gate."""
    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False,
                              serve_tenant_max_in_flight=1,
                              serve_tenant_queue_depth=0)
    with ServeLoop(config=cfg) as loop:
        loop.query(path, _REGIONS[:2], tenant="B")   # warm: serving fast
        before_rejects = METRICS.get("query.rejected")
        with loop.tenants.scheduler("A").admit():    # occupy A's slot
            # A's queue_depth is 0: the next A submit sheds immediately
            with pytest.raises(TransientIOError):
                loop.submit(path, [_REGIONS[0]], tenant="A")
            assert METRICS.get("query.rejected") == before_rejects + 1
            # B is untouched by A's saturation: admits AND completes
            # well inside a generous deadline
            res = loop.query(path, [_REGIONS[1]], tenant="B",
                             deadline_s=30.0)
            assert res[0].tile_hits > 0
        # A's slot freed: A admits again
        assert loop.query(path, [_REGIONS[0]], tenant="A")


def test_priority_classes_let_interactive_jump_batch(served_bam):
    """An interactive request submitted AFTER a pile of batch work
    completes before the batch tail — priority isolation keeps the
    interactive tenant's latency bounded by its own work, not the
    flooder's backlog."""
    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False,
                              serve_tenant_max_in_flight=8,
                              serve_tenant_queue_depth=32)
    done_order = []
    lock = threading.Lock()

    def mark(tag):
        def _cb(_fut):
            with lock:
                done_order.append(tag)
        return _cb

    n_batch = 24
    with ServeLoop(config=cfg) as loop:
        loop.query(path, _REGIONS)            # warm: per-query cost tiny
        batch_futs = []
        for i in range(n_batch):
            f = loop.submit(path, [_REGIONS[i % len(_REGIONS)]],
                            tenant="bulk", priority="batch")
            f.add_done_callback(mark(("batch", i)))
            batch_futs.append(f)
        # submitted AFTER the whole batch backlog
        inter = loop.submit(path, [_REGIONS[0]], tenant="web",
                            priority="interactive")
        inter.add_done_callback(mark(("inter", 0)))
        inter.result(timeout=30.0)
        cf.wait(batch_futs, timeout=60.0)
    # the interactive request was submitted after the ENTIRE backlog yet
    # finishes ahead of the batch tail — it jumped the queue instead of
    # draining behind the flood
    assert ("inter", 0) in done_order
    assert done_order.index(("inter", 0)) < done_order.index(
        ("batch", n_batch - 1))


def test_unknown_priority_and_empty_regions_are_plan_errors(served_bam):
    path, _header = served_bam
    with ServeLoop() as loop:
        with pytest.raises(PlanError):
            loop.submit(path, [_REGIONS[0]], priority="vip")
        with pytest.raises(PlanError):
            loop.submit(path, [])
        with pytest.raises(PlanError):
            loop.submit(path, [_REGIONS[0]], tenant="")


def test_idle_tenant_gates_are_lru_bounded():
    from hadoop_bam_tpu.serve import TenantQuotas

    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_max_tenants=4)
    quotas = TenantQuotas(cfg)
    for i in range(16):
        quotas.scheduler(f"tenant-{i}")
    assert len(quotas.stats()) <= 4


# ---------------------------------------------------------------------------
# MetricsContext isolation across the shared dispatcher + pool
# ---------------------------------------------------------------------------

def test_metrics_context_isolated_per_client(served_bam):
    path, _header = served_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False)
    n_a, n_b = 6, 3
    out = {}

    with ServeLoop(config=cfg) as loop:
        loop.query(path, _REGIONS)            # warm

        def client(tag, n):
            with MetricsContext() as m:
                for i in range(n):
                    loop.query(path, [_REGIONS[i % len(_REGIONS)]],
                               tenant=tag)
            out[tag] = m

        ta = threading.Thread(target=client, args=("a", n_a))
        tb = threading.Thread(target=client, args=("b", n_b))
        ta.start(); tb.start()
        ta.join(30.0); tb.join(30.0)

    # each client's context saw exactly its own requests — none of the
    # other client's, even though dispatcher + decode pool are shared
    assert out["a"].hist_summary("serve.latency_s")["count"] == n_a
    assert out["b"].hist_summary("serve.latency_s")["count"] == n_b
    assert out["a"].counters.get("serve.requests", 0) == n_a
    assert out["b"].counters.get("serve.requests", 0) == n_b


# ---------------------------------------------------------------------------
# ChunkCache: the hammer + single-flight
# ---------------------------------------------------------------------------

def test_chunk_cache_concurrent_hammer():
    """Many threads get/put/evict one small cache at once: no exception,
    byte accounting stays within budget, and per-instance stats add up
    exactly (the serve-path thread-safety contract)."""
    cache = ChunkCache(byte_budget=4096)
    n_threads, ops = 8, 400
    errs = []

    def worker(seed):
        rng = np.random.RandomState(seed)
        try:
            for i in range(ops):
                k = ("k", int(rng.randint(0, 64)))
                if rng.rand() < 0.5:
                    cache.get(k)
                else:
                    cache.put(k, bytes(8), nbytes=int(rng.randint(1, 256)))
        except BaseException as e:  # noqa: BLE001 — crosses the thread
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert errs == []
    stats = cache.stats()
    assert stats["hits"] + stats["misses"] + stats["evictions"] > 0
    assert cache.bytes_used <= 4096
    # recount the books under no concurrency: accounting is consistent
    with cache._lock:
        assert cache._bytes == sum(nb for _v, nb in
                                   cache._entries.values())


def test_chunk_cache_single_flight_coalesces_computes():
    cache = ChunkCache(byte_budget=1 << 20)
    n_threads = 6
    computes = [0]
    barrier = threading.Barrier(n_threads)
    started = threading.Event()
    results = []

    def compute():
        computes[0] += 1
        started.set()
        time.sleep(0.05)          # hold the flight open
        return ({"v": 42}, 64)

    def caller():
        barrier.wait(5.0)
        results.append(cache.get_or_compute(("hot",), compute))

    threads = [threading.Thread(target=caller) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert computes[0] == 1                      # ONE leader decoded
    assert all(r is results[0] for r in results)
    assert cache.stats()["coalesced"] == n_threads - 1
    # uncacheable results (nbytes=None) serve but do not stick
    out = cache.get_or_compute(("skip",), lambda: ({"empty": True}, None))
    assert out == {"empty": True}
    assert cache.contains(("hot",)) and not cache.contains(("skip",))


def test_single_flight_leader_exception_reaches_waiters():
    cache = ChunkCache(byte_budget=1 << 20)
    gate = threading.Event()

    def compute():
        gate.wait(5.0)
        raise TransientIOError("decode blew up")

    def waiter():
        with pytest.raises(TransientIOError):
            cache.get_or_compute(("bad",), compute)

    t1 = threading.Thread(target=waiter)
    t1.start()
    time.sleep(0.05)
    t2 = threading.Thread(target=waiter)
    t2.start()
    time.sleep(0.05)
    gate.set()
    t1.join(5.0); t2.join(5.0)
    # the failed flight is fully cleaned up: a retry computes fresh
    assert cache.get_or_compute(("bad",), lambda: ("ok", 8)) == "ok"


# ---------------------------------------------------------------------------
# deadlines: enqueue anchoring + the miss counter
# ---------------------------------------------------------------------------

def test_per_request_deadline_anchored_at_enqueue(served_bam):
    """Admission wait counts against per-request deadline overrides: a
    request that waited past its own budget in the queue fails with
    TransientIOError even though the actual serving would be instant."""
    path, _header = served_bam
    sched = QueryScheduler(max_in_flight=1, queue_depth=4)
    engine = QueryEngine(scheduler=sched)
    engine.query_records([QueryRequest(path, _REGIONS[0])])  # warm meta

    release = threading.Event()
    holding = threading.Event()

    def hold_slot():
        with sched.admit():
            holding.set()
            release.wait(5.0)

    t = threading.Thread(target=hold_slot)
    t.start()
    holding.wait(2.0)
    before = METRICS.get("query.deadline_misses")

    def free_later():
        time.sleep(0.3)           # admission wait >> the 0.1s budget
        release.set()

    threading.Thread(target=free_later).start()
    with pytest.raises(TransientIOError):
        engine.query_records(
            [QueryRequest(path, _REGIONS[0], deadline_s=0.1)])
    t.join(5.0)
    assert METRICS.get("query.deadline_misses") > before


def test_deadline_rebudget_keeps_anchor():
    from hadoop_bam_tpu.query.scheduler import Deadline

    t = [100.0]
    clock = lambda: t[0]
    batch = Deadline(10.0, clock=clock)
    t[0] = 100.4
    req = batch.rebudget(0.5)
    assert req.t_start == batch.t_start       # anchored at enqueue
    assert abs(req.remaining() - 0.1) < 1e-9  # 0.4s already spent
    t[0] = 100.6
    assert req.expired and not batch.expired
    with pytest.raises(TransientIOError):
        req.check("serve")


def test_serve_job_finishing_late_counts_a_miss(served_bam):
    path, _header = served_bam
    with ServeLoop() as loop:
        loop.query(path, [_REGIONS[0]])
        before = METRICS.get("query.deadline_misses")
        # generous enough to finish, tiny enough to be missed... use 0:
        # the deadline is already expired at enqueue; the job still
        # raises transient AND books the miss
        with pytest.raises(TransientIOError):
            loop.query(path, [_REGIONS[0]], deadline_s=0.0)
        assert METRICS.get("query.deadline_misses") > before


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

def test_jsonl_stream_serves_counts_and_errors(served_bam):
    path, _header = served_bam
    want, _ = _oracle_counts(path, _REGIONS[:2])
    lines = [
        json.dumps({"id": "q1", "path": path, "regions": _REGIONS[:2]}),
        "this is not json",
        json.dumps({"id": "q2", "path": "/nope.bam",
                    "region": "chr1:1-10"}),
        json.dumps({"id": "q3", "path": path}),       # missing regions
        json.dumps({"id": "q4", "path": path, "region": _REGIONS[2],
                    "tenant": "t", "priority": "batch",
                    "records": True}),
    ]
    out = io.StringIO()
    with ServeLoop() as loop:
        n = handle_stream(loop, io.StringIO("\n".join(lines) + "\n"), out)
    assert n == 5
    docs = {d.get("id"): d
            for d in map(json.loads, out.getvalue().splitlines())}
    assert [r["count"] for r in docs["q1"]["results"]] == want
    assert docs["q1"]["latency_ms"] >= 0
    assert docs["q2"]["kind"] == "plan"           # missing file
    assert docs["q3"]["kind"] == "plan"           # malformed request
    assert docs[2]["kind"] == "plan"              # unparseable line
    assert "records" in docs["q4"]["results"][0]
    _w, oracle = _oracle_counts(path, [_REGIONS[2]])
    assert docs["q4"]["results"][0]["records"] == \
        [r.to_line() for r in oracle[0].records]


def test_tcp_transport_round_trip(served_bam):
    import socket

    from hadoop_bam_tpu.serve import make_tcp_server

    path, _header = served_bam
    want, _ = _oracle_counts(path, [_REGIONS[0]])
    with ServeLoop() as loop:
        server = make_tcp_server(loop, port=0)
        host, port = server.server_address[:2]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            with socket.create_connection((host, port), timeout=10) as s:
                req = json.dumps({"id": 1, "path": path,
                                  "region": _REGIONS[0]}) + "\n"
                s.sendall(req.encode())
                s.shutdown(socket.SHUT_WR)
                buf = b""
                s.settimeout(10)
                while b"\n" not in buf:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            doc = json.loads(buf.decode().splitlines()[0])
            assert [r["count"] for r in doc["results"]] == want
        finally:
            server.shutdown()
            server.server_close()
            t.join(5.0)


def test_cli_serve_verb_stdin(served_bam, capsys, monkeypatch):
    from hadoop_bam_tpu.tools.cli import main

    path, _header = served_bam
    want, _ = _oracle_counts(path, [_REGIONS[0]])
    req = json.dumps({"id": 7, "path": path, "region": _REGIONS[0]})
    monkeypatch.setattr("sys.stdin", io.StringIO(req + "\n"))
    assert main(["serve", "--no-prefetch", "--metrics",
                 "--warm", path]) == 0
    out = capsys.readouterr()
    doc = json.loads(out.out.strip().splitlines()[-1])
    assert doc["id"] == 7
    assert [r["count"] for r in doc["results"]] == want
    assert "serve stats" in out.err


def test_stopped_loop_sheds_submissions(served_bam):
    path, _header = served_bam
    loop = ServeLoop()
    loop.start()
    loop.query(path, [_REGIONS[0]])
    loop.stop()
    with pytest.raises(TransientIOError):
        loop.submit(path, [_REGIONS[0]])
