"""split/kmerge.py — the extracted k-way streaming merge core.

Pins the contracts the two consumers rely on: global heap order,
stream-order tie-breaking (= ``heapq.merge`` stability, which is what
keeps the mesh-sort spill merge byte-identical after the extraction),
exhausted-stream handling, empty inputs, and the grouped flavor the
cohort join builds sites from.
"""
import heapq
import random

import numpy as np
import pytest

from hadoop_bam_tpu.split.kmerge import kmerge, kmerge_grouped, kmerge_indexed

pytestmark = pytest.mark.cohort


def test_heap_order_randomized_matches_sorted_concat():
    rng = random.Random(7)
    for _ in range(25):
        k = rng.randint(1, 8)
        streams = [sorted(rng.randint(0, 40) for _ in range(rng.randint(0, 30)))
                   for _ in range(k)]
        out = list(kmerge([iter(s) for s in streams]))
        assert out == sorted(x for s in streams for x in s)


def test_key_function_and_heap_order():
    a = [(1, "a0"), (3, "a1"), (3, "a2"), (9, "a3")]
    b = [(2, "b0"), (3, "b1"), (8, "b2")]
    out = list(kmerge([a, b], key=lambda t: t[0]))
    assert [t[0] for t in out] == [1, 2, 3, 3, 3, 8, 9]


def test_tie_breaking_is_stream_order():
    # equal keys must yield stream 0's items first — heapq.merge
    # stability, load-bearing for mesh-sort byte identity
    a = [(5, "a0"), (5, "a1")]
    b = [(5, "b0")]
    c = [(5, "c0")]
    out = list(kmerge([a, b, c], key=lambda t: t[0]))
    assert out == [(5, "a0"), (5, "a1"), (5, "b0"), (5, "c0")]
    # matches the stdlib's answer exactly
    assert out == list(heapq.merge(a, b, c, key=lambda t: t[0]))


def test_exhausted_streams_drop_out():
    # wildly different lengths: short streams end without disturbing
    # the rest, the long tail still arrives in order
    a = [1]
    b = [0, 2, 4, 6, 8, 10, 12]
    c: list = []
    d = [3, 5]
    assert list(kmerge([a, b, c, d])) == [0, 1, 2, 3, 4, 5, 6, 8, 10, 12]


def test_empty_inputs():
    assert list(kmerge([])) == []
    assert list(kmerge([[], [], []])) == []
    assert list(kmerge_grouped([[], []], key=lambda x: x)) == []


def test_indexed_carries_stream_identity():
    out = list(kmerge_indexed([[1, 4], [2, 3]]))
    assert out == [(0, 1), (1, 2), (1, 3), (0, 4)]


def test_streaming_one_item_lookahead():
    """Inputs are streamed, not materialized: after the first yield only
    one item per stream has been pulled past it."""
    pulled = []

    def trace(si, items):
        for x in items:
            pulled.append((si, x))
            yield x

    g = kmerge([trace(0, [1, 3]), trace(1, [2, 4])])
    assert next(g) == 1
    # priming pulled exactly one item per stream and nothing more
    assert pulled == [(0, 1), (1, 2)]
    assert next(g) == 2
    # advancing past 1 pulled only stream 0's successor
    assert pulled == [(0, 1), (1, 2), (0, 3)]
    g.close()


def test_grouped_runs_of_equal_keys():
    a = [(0, 10), (2, 11), (2, 12)]
    b = [(0, 20), (3, 21)]
    groups = list(kmerge_grouped([a, b], key=lambda t: t[0]))
    assert [k for k, _ in groups] == [0, 2, 3]
    assert groups[0][1] == [(0, (0, 10)), (1, (0, 20))]
    # duplicates within one stream land in the SAME group, stream order
    assert groups[1][1] == [(0, (2, 11)), (0, (2, 12))]
    assert groups[2][1] == [(1, (3, 21))]


def test_mesh_sort_spill_merge_repinned_on_kmerge():
    """_merge_bucket_runs (now on kmerge) is byte-identical to the
    heapq.merge oracle over synthetic framed runs."""
    from hadoop_bam_tpu.parallel import mesh_sort as ms

    rng = random.Random(13)

    def frame(recs):
        out = bytearray()
        for hi, lo, gidx, payload in recs:
            out += int(hi).to_bytes(4, "little")
            out += int(lo).to_bytes(4, "little")
            out += int(gidx).to_bytes(4, "little", signed=True)
            out += len(payload).to_bytes(4, "little", signed=True)
            out += payload
        return bytes(out)

    def rand_runs(tmpdir, n_runs):
        paths = []
        for r in range(n_runs):
            recs = sorted(
                ((rng.randint(0, 3), rng.randint(0, 50), rng.randint(0, 99),
                  bytes(rng.randrange(256)
                        for _ in range(rng.randint(0, 12))))
                 for _ in range(rng.randint(0, 20))),
                key=lambda t: t[:3])
            p = str(tmpdir / f"run{r}.bin")
            with open(p, "wb") as f:
                f.write(frame(recs))
            paths.append(p)
        return paths

    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as td:
        paths = rand_runs(Path(td), 5)
        payload, lens = ms._merge_bucket_runs(paths)
        # oracle: stdlib heapq.merge over the same frame iterators
        chunks = [p for _k, p in heapq.merge(
            *(ms._iter_run_frames(p) for p in paths),
            key=lambda kv: kv[0])]
        assert payload == b"".join(chunks)
        assert lens.tolist() == [len(c) for c in chunks]
        assert lens.dtype == np.int64
