"""Variant device feed: dosage tensors + mesh stats on the CPU mesh."""
import random

import numpy as np
import pytest

from hadoop_bam_tpu.formats.vcf import VariantBatch, VCFHeader, VcfRecord
from hadoop_bam_tpu.parallel.variant_pipeline import (
    VariantGeometry, variant_stats_file,
)

N_SAMPLES = 5
HEADER_TEXT = (
    "##fileformat=VCFv4.2\n"
    "##contig=<ID=c1,length=1000000>\n"
    "##contig=<ID=c2,length=500000>\n"
    '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
    '##FILTER=<ID=q10,Description="Quality below 10">\n'
    '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
    "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
    + "\t".join(f"s{i}" for i in range(N_SAMPLES)) + "\n")


def _make_records(n, seed=5):
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        chrom = "c1" if i % 3 else "c2"
        ref = rng.choice("ACGT")
        alt = rng.choice([c for c in "ACGT" if c != ref])
        gts = []
        for _ in range(N_SAMPLES):
            r = rng.random()
            gts.append("./." if r < 0.1 else
                       rng.choice(["0/0", "0/1", "1/1", "0|1"]))
        filt = "PASS" if rng.random() < 0.8 else "q10"
        recs.append(VcfRecord.from_line(
            f"{chrom}\t{100 + i * 7}\t.\t{ref}\t{alt}\t{30 + i % 40}\t"
            f"{filt}\tDP={i}\tGT\t" + "\t".join(gts)))
    return recs


@pytest.fixture(scope="module")
def vcf(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("varpipe") / "v.vcf")
    header = VCFHeader.from_text(HEADER_TEXT)
    recs = _make_records(2000)
    with open(path, "w") as f:
        f.write(HEADER_TEXT)
        for r in recs:
            f.write(r.to_line() + "\n")
    return path, header, recs


def test_dosage_matrix(vcf):
    path, header, recs = vcf
    batch = VariantBatch(recs[:50], header)
    d = batch.dosage_matrix()
    assert d.shape == (50, N_SAMPLES)
    for i in (0, 17, 49):
        for s in range(N_SAMPLES):
            gt = recs[i].genotypes[s].split(":")[0]
            if gt.startswith("."):
                assert d[i, s] == -1
            else:
                expect = sum(1 for a in gt.replace("|", "/").split("/")
                             if int(a) > 0)
                assert d[i, s] == expect


def test_variant_stats_file_matches_oracle(vcf):
    path, header, recs = vcf
    stats = variant_stats_file(path, header=header)
    assert stats["n_variants"] == len(recs)
    n_pass = sum(1 for r in recs if r.filters == ("PASS",))
    assert stats["n_pass"] == n_pass
    assert stats["n_snp"] == len(recs)  # all synthesized records are SNPs
    # oracle AF + callrates
    batch = VariantBatch(recs, header)
    d = batch.dosage_matrix().astype(np.int64)
    called = d >= 0
    af = np.where(called.sum(1) > 0,
                  np.where(called, d, 0).sum(1)
                  / (2.0 * np.maximum(called.sum(1), 1)), 0.0)
    has = called.sum(1) > 0
    assert abs(stats["mean_af"] - af[has].mean()) < 1e-6
    np.testing.assert_allclose(stats["sample_callrate"],
                               called.mean(axis=0), atol=1e-9)


def test_variant_tensor_batches(vcf):
    path, header, recs = vcf
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    ds = open_vcf(path)
    g = VariantGeometry(tile_records=512, n_samples=header.n_samples)
    total = 0
    for batch in ds.tensor_batches(geometry=g, num_spans=3):
        counts = np.asarray(batch["n_records"])
        total += int(counts.sum())
        assert batch["dosage"].shape[1:] == (512, g.samples_pad)
        assert batch["chrom"].shape[1:] == (512,)
    assert total == len(recs)


def test_variant_stats_on_bcf(vcf, tmp_path):
    """Same stats through the BCF container (binary codec round-trip)."""
    path, header, recs = vcf
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    out = str(tmp_path / "v.bcf")
    with open_vcf_writer(out, header) as w:
        for r in recs:
            w.write_record(r)
    stats = variant_stats_file(out)
    assert stats["n_variants"] == len(recs)
    assert stats["n_snp"] == len(recs)


def test_fast_tokenizer_matches_generic(vcf):
    """pack_variant_tiles_from_text == VariantBatch-based packing."""
    path, header, recs = vcf
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.parallel.variant_pipeline import (
        pack_variant_tiles, pack_variant_tiles_from_text,
    )
    g = VariantGeometry(n_samples=header.n_samples)
    ds = open_vcf(path)
    for span in ds.spans(3):
        text = ds.read_span_text(span)
        fast = pack_variant_tiles_from_text(text, header, g)
        slow = pack_variant_tiles(
            __import__("hadoop_bam_tpu.formats.vcf",
                       fromlist=["VariantBatch"]).VariantBatch(
                ds.read_span(span), header), g)
        for k in ("chrom", "pos", "flags", "dosage"):
            np.testing.assert_array_equal(fast[k], slow[k], err_msg=k)


def test_bcf_fast_scan_wide_gt_and_half_missing(tmp_path):
    """scan_variant_columns must (a) decode GT vectors the encoder widened
    to int16 (allele index >= 63 -> value 128 > int8 max) and (b) agree
    with VariantBatch.dosage_matrix on half-missing genotypes ('0/.' ->
    -1), across text and binary containers."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.bcf import scan_variant_columns
    from hadoop_bam_tpu.parallel.variant_pipeline import pack_variant_tiles
    from hadoop_bam_tpu.split.vcf_planners import read_bcf_span_bytes

    n_alts = 70  # forces (70+1)<<1 = 142 -> int16 GT encoding
    alts = ",".join("ACGT"[i % 4] * (i // 4 + 2) for i in range(n_alts))
    hdr_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=c1,length=1000000>\n"
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
        "s0\ts1\ts2\ts3\n")
    header = VCFHeader.from_text(hdr_text)
    lines = [
        f"c1\t100\t.\tA\t{alts}\t30\tPASS\t.\tGT\t0/70\t70/70\t0/0\t./.",
        f"c1\t200\t.\tA\t{alts}\t30\tPASS\t.\tGT\t0/.\t./0\t1/.\t0|70",
        "c1\t300\t.\tA\tC\t30\tPASS\t.\tGT\t0/1\t0|.\t./.\t1/1",
    ]
    recs = [VcfRecord.from_line(ln) for ln in lines]
    out = str(tmp_path / "wide.bcf")
    with open_vcf_writer(out, header) as w:
        for r in recs:
            w.write_record(r)
    ds = open_vcf(out)
    g = VariantGeometry(n_samples=header.n_samples)
    (span,) = ds.spans(1)
    raw = read_bcf_span_bytes(out, span, ds._is_bgzf_bcf)
    fast = scan_variant_columns(raw, header, g.samples_pad)
    # oracle: the generic per-record path
    slow = pack_variant_tiles(VariantBatch(ds.read_span(span), header), g)
    for k in ("chrom", "pos", "flags", "dosage"):
        np.testing.assert_array_equal(fast[k], slow[k], err_msg=k)
    # explicit semantics: int16 GT decoded, half-missing -> -1
    np.testing.assert_array_equal(
        fast["dosage"][:, :4],
        [[1, 2, 0, -1], [-1, -1, -1, 1], [1, -1, -1, 2]])


def test_bcf_fast_scan_matches_generic(vcf, tmp_path):
    """scan_variant_columns == VariantBatch packing for BCF spans."""
    path, header, recs = vcf
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.bcf import scan_variant_columns
    from hadoop_bam_tpu.parallel.variant_pipeline import pack_variant_tiles
    from hadoop_bam_tpu.split.vcf_planners import read_bcf_span_bytes

    out = str(tmp_path / "scan.bcf")
    with open_vcf_writer(out, header) as w:
        for r in recs:
            w.write_record(r)
    ds = open_vcf(out)
    g = VariantGeometry(n_samples=header.n_samples)
    total = 0
    for span in ds.spans(3):
        raw = read_bcf_span_bytes(out, span, ds._is_bgzf_bcf)
        fast = scan_variant_columns(raw, header, g.samples_pad)
        slow = pack_variant_tiles(VariantBatch(ds.read_span(span), header),
                                  g)
        for k in ("chrom", "pos", "flags", "dosage"):
            np.testing.assert_array_equal(fast[k], slow[k], err_msg=k)
        total += fast["chrom"].shape[0]
    assert total == len(recs)


def test_text_tokenizer_vectorized_matches_scalar():
    """Differential fuzz: the NumPy grid tokenizer (+ its irregular-row
    fallback) must match the per-line scalar parse byte-for-byte across
    adversarial shapes: multi-allelic ALTs, wide ALTs, polyploid and
    multi-digit genotypes, missing trailing fields, '.' everywhere."""
    import random as _random

    from hadoop_bam_tpu.formats.vcf import VCFHeader
    from hadoop_bam_tpu.parallel.variant_pipeline import (
        VariantGeometry, _pack_variant_tiles_from_text_scalar,
        pack_variant_tiles_from_text,
    )
    header = VCFHeader.from_text(
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr1,length=1000000>\n"
        "##contig=<ID=chrX_alt,length=50000>\n"
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="G">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
        "s0\ts1\ts2\n")
    rng = _random.Random(17)
    alts = ["A", "T", "A,C", "A,C,G,T,A,C,G,T,A",     # > _ALT_W wide
            "AT", "A,TT", ".", "<DEL>", "A,<INS>", "*"]
    gts = ["0/0", "0/1", "1|1", "./.", ".", "0", "2", "10/1", "0/1/1",
           "1", "0|0|1", "./0", "0/.", "", "1/2:99", "0/1:.:3"]
    formats = ["GT", "GT:GQ", "GQ", "GTX"]
    lines = []
    for i in range(400):
        chrom = rng.choice(["chr1", "chrX_alt", "chrUnknown"])
        pos = rng.randint(1, 999999)
        nf = rng.choice([8, 9, 10, 11, 12])
        parts = [chrom, str(pos), ".", rng.choice(["A", "AT"]),
                 rng.choice(alts), "30",
                 rng.choice(["PASS", "q10", "."]), "DP=5"]
        if nf > 8:
            parts.append(rng.choice(formats))
            for _ in range(nf - 9):
                parts.append(rng.choice(gts))
        lines.append("\t".join(parts))
    text = ("\n".join(lines) + "\n").encode()
    geom = VariantGeometry(n_samples=3)
    want = _pack_variant_tiles_from_text_scalar(text, header, geom)
    got = pack_variant_tiles_from_text(text, header, geom)
    for k in want:
        assert (want[k] == got[k]).all(), k
    # and without a trailing newline
    got2 = pack_variant_tiles_from_text(text[:-1], header, geom)
    for k in want:
        assert (want[k] == got2[k]).all(), k


def test_variant_geometry_byte_budget_large_cohorts():
    """The auto tile sizing is byte-clamped, not record-floored: a
    100k-sample cohort must stay near the ~8 MB dosage budget instead
    of blowing up to a 4096-record (1.6 GB int32) tile (ADVICE r4)."""
    from hadoop_bam_tpu.parallel.variant_pipeline import VariantGeometry

    g = VariantGeometry(n_samples=100_000)
    assert g.tile_records * g.samples_pad <= (16 << 20)   # ~2x budget max
    assert g.tile_records >= 64
    # small cohorts still get big tiles (dispatch amortization)
    g_small = VariantGeometry(n_samples=3)
    assert g_small.tile_records == 1 << 16
