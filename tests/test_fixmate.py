"""Streaming fixmate (utils/fixmate.py) vs an object-level oracle.

The oracle below is the pre-rework implementation (SamRecord objects,
adjacent-pair fixing) — the streaming byte-patching path must reproduce
its field-level output on name-grouped inputs, while never materializing
the file (the rework's point: the old path OOM'd on WGS-scale BAMs).
"""
import random
import re

from hadoop_bam_tpu.api.dataset import open_bam
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.utils.fixmate import fixmate_bam

HDR = SAMHeader.from_sam_text(
    "@HD\tVN:1.6\tSO:queryname\n"
    "@SQ\tSN:chr1\tLN:100000\n@SQ\tSN:chr2\tLN:100000\n")


def _alen(r) -> int:
    if r.cigar in ("*", ""):
        return len(r.seq) if r.seq != "*" else 0
    return sum(int(n) for n, op in re.findall(r"(\d+)([MIDNSHP=X])", r.cigar)
               if op in "MDN=X")


def oracle_fixmate(recs):
    """The old cmd_fixmate algorithm, object-level, mutating copies —
    extended with the same two semantic fixes the streaming path carries
    (secondary/supplementary never pair; uncomputable tlen zeroes)."""
    import copy
    recs = [copy.deepcopy(r) for r in recs]
    primaries = [r for r in recs if not (r.flag & 0x900)]
    i = 0
    while i < len(primaries):
        a = primaries[i]
        if i + 1 < len(primaries) and primaries[i + 1].qname == a.qname \
                and (a.flag & 0x1):
            b = primaries[i + 1]
            a.rnext = "=" if b.rname == a.rname else b.rname
            b.rnext = "=" if a.rname == b.rname else a.rname
            a.pnext, b.pnext = b.pos, a.pos
            if a.rname == b.rname and a.pos and b.pos:
                span = max(a.pos + _alen(a), b.pos + _alen(b)) \
                    - min(a.pos, b.pos)
                sign = 1 if a.pos <= b.pos else -1
                a.tlen, b.tlen = sign * span, -sign * span
            else:
                a.tlen, b.tlen = 0, 0
            for x, y in ((a, b), (b, a)):
                x.flag = (x.flag & ~0x28) | (0x8 if y.flag & 0x4 else 0) \
                    | (0x20 if y.flag & 0x10 else 0)
            i += 2
        else:
            i += 1
    return recs


def make_pair(name, pos_a, pos_b, rname="chr1", flags=(0x1 | 0x40,
                                                       0x1 | 0x80 | 0x10)):
    l = 20
    mk = lambda pos, fl: SamRecord(
        qname=name, flag=fl, rname=rname, pos=pos, mapq=60,
        cigar=f"{l}M", rnext="*", pnext=0, tlen=0,
        seq="A" * l, qual="I" * l)
    return [mk(pos_a, flags[0]), mk(pos_b, flags[1])]


def write_bam(path, recs):
    with BamWriter(path, HDR) as w:
        for r in recs:
            w.write_sam_record(r)


def read_fields(path):
    ds = open_bam(path)
    out = []
    for b in ds.batches():
        for i in range(len(b)):
            out.append(SamRecord.from_line(b.to_sam_line(i)))
    return out


def assert_matches_oracle(recs, tmp_path):
    src = str(tmp_path / "in.bam")
    dst = str(tmp_path / "out.bam")
    write_bam(src, recs)
    n = fixmate_bam(src, dst)
    assert n == len(recs)
    got = read_fields(dst)
    want = oracle_fixmate(recs)
    # secondary/supplementary records may legally be emitted ahead of a
    # held primary (samtools does the same); compare as multisets keyed
    # by identity fields, and positions of primaries in order
    key = lambda r: (r.qname, r.flag, r.rname, r.pos, r.rnext, r.pnext,
                     r.tlen, r.cigar, r.seq)
    assert sorted(map(key, got)) == sorted(map(key, want))
    prim = lambda rs: [key(r) for r in rs if not (r.flag & 0x900)]
    assert prim(got) == prim(want)
    return got


def test_simple_pair(tmp_path):
    recs = make_pair("p1", 100, 300)
    got = assert_matches_oracle(recs, tmp_path)
    a, b = got
    assert a.rnext == "=" and a.pnext == 300 and a.tlen == 220
    assert b.rnext == "=" and b.pnext == 100 and b.tlen == -220
    assert a.flag & 0x20 and not (b.flag & 0x20)


def test_cross_reference_pair_zeroes_tlen(tmp_path):
    a, b = make_pair("x1", 100, 500)
    b.rname = "chr2"
    a.tlen, b.tlen = 777, -777          # stale values must be cleared
    got = assert_matches_oracle([a, b], tmp_path)
    ga = next(r for r in got if r.flag & 0x40)
    gb = next(r for r in got if r.flag & 0x80)
    assert ga.tlen == 0 and gb.tlen == 0
    assert ga.rnext == "chr2" and gb.rnext == "chr1"


def test_unmapped_mate(tmp_path):
    a, b = make_pair("u1", 100, 0)
    b.flag |= 0x4                        # unmapped
    b.rname, b.cigar = "*", "*"
    b.pos = 0
    got = assert_matches_oracle([a, b], tmp_path)
    ga = next(r for r in got if r.flag & 0x40)
    assert ga.flag & 0x8                 # mate-unmapped propagated
    assert ga.tlen == 0


def test_supplementary_between_mates(tmp_path):
    a, b = make_pair("s1", 100, 400)
    supp = SamRecord(qname="s1", flag=0x1 | 0x40 | 0x800, rname="chr2",
                     pos=50, mapq=60, cigar="10M", rnext="*", pnext=0,
                     tlen=0, seq="A" * 10, qual="I" * 10)
    got = assert_matches_oracle([a, supp, b], tmp_path)
    # the primaries must have found each other across the supplementary
    ga = next(r for r in got if r.flag & 0x40 and not (r.flag & 0x800))
    gb = next(r for r in got if r.flag & 0x80)
    assert ga.pnext == 400 and gb.pnext == 100
    gs = next(r for r in got if r.flag & 0x800)
    assert gs.pnext == 0 and gs.tlen == 0   # untouched


def test_singletons_and_unpaired_flag(tmp_path):
    single = SamRecord(qname="lone", flag=0, rname="chr1", pos=10, mapq=60,
                       cigar="20M", rnext="*", pnext=0, tlen=0,
                       seq="C" * 20, qual="I" * 20)
    # same name twice but UNPAIRED flag: old + new code leave both alone
    dup1, dup2 = make_pair("d1", 100, 200, flags=(0, 0x10))
    assert_matches_oracle([single, dup1, dup2], tmp_path)


def test_mixed_stream_matches_oracle(tmp_path):
    rng = random.Random(7)
    recs = []
    for i in range(300):
        kind = rng.random()
        if kind < 0.7:
            recs += make_pair(f"q{i}", rng.randint(1, 90000),
                              rng.randint(1, 90000),
                              rname=rng.choice(["chr1", "chr2"]))
        elif kind < 0.8:
            a, b = make_pair(f"q{i}", rng.randint(1, 90000), 0)
            b.flag |= 0x4
            b.rname, b.cigar = "*", "*"
            b.pos = 0
            recs += [a, b]
        elif kind < 0.9:
            recs.append(SamRecord(
                qname=f"q{i}", flag=0, rname="chr1",
                pos=rng.randint(1, 90000), mapq=60, cigar="20M",
                rnext="*", pnext=0, tlen=0, seq="G" * 20, qual="I" * 20))
        else:
            a, b = make_pair(f"q{i}", rng.randint(1, 90000),
                             rng.randint(1, 90000))
            supp = SamRecord(
                qname=f"q{i}", flag=0x1 | 0x40 | 0x800, rname="chr2",
                pos=rng.randint(1, 90000), mapq=60, cigar="5M",
                rnext="*", pnext=0, tlen=0, seq="T" * 5, qual="I" * 5)
            recs += [a, supp, b]
    assert_matches_oracle(recs, tmp_path)


def test_streaming_never_materializes(tmp_path, monkeypatch):
    """The rework's contract: no whole-file record list.  Cap the
    allowed live record-byte objects by intercepting record_bytes calls
    between writer flushes — structurally, the implementation holds at
    most ONE pending record; this asserts the pairing still works when
    pairs straddle batch boundaries (forced tiny spans)."""
    recs = []
    for i in range(2000):
        recs += make_pair(f"m{i:05d}", 10 + i, 500 + i)
    src = str(tmp_path / "big.bam")
    dst = str(tmp_path / "big_fixed.bam")
    write_bam(src, recs)
    n = fixmate_bam(src, dst)
    assert n == 4000
    got = read_fields(dst)
    assert all(r.rnext == "=" for r in got)
    pnext_ok = sum(1 for r in got if r.pnext > 0)
    assert pnext_ok == 4000


def test_cold_query_after_fixmate(tmp_path, monkeypatch):
    """ISSUE 20 satellite pin: fixmate output routes through
    write_bam_records, so --compress-level applies, sidecars are
    co-written, and (when the name-grouped input happens to be
    coordinate-compatible, as here) the result cold-opens in
    QueryEngine with NO rescan; --no-write-index suppresses the
    sidecars."""
    import os

    import hadoop_bam_tpu.split.bai as bai_mod
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest
    from hadoop_bam_tpu.tools.cli import main

    # name-adjacent pairs laid out in ascending coordinates: fixmate's
    # name-grouped requirement and the BAI's coordinate requirement
    # hold at the same time
    recs = []
    for i in range(40):
        recs += make_pair(f"p{i:03d}", 100 * i + 1, 100 * i + 41)
    src = str(tmp_path / "in.bam")
    write_bam(src, recs)

    out = str(tmp_path / "fixed.bam")
    main(["fixmate", src, out, "--compress-level", "1"])
    assert os.path.exists(out + ".bai")        # sidecar co-written

    def no_rescan(*a, **kw):
        raise AssertionError("build_bai called — the co-written "
                             "sidecar should have served the query")
    monkeypatch.setattr(bai_mod, "build_bai", no_rescan)

    res = QueryEngine().query_records(
        [QueryRequest(out, "chr1:1-500")])
    got = [r for r in res[0].records]
    assert sorted({r.qname for r in got}) \
        == [f"p{i:03d}" for i in range(5)]
    # and the mate fields really were fixed before the write
    assert all(r.rnext == "=" and r.pnext > 0 and r.tlen != 0
               for r in got)

    out2 = str(tmp_path / "fixed_noidx.bam")
    main(["fixmate", src, out2, "--no-write-index"])
    assert not os.path.exists(out2 + ".bai")
    assert read_fields(out2) == read_fields(out)
