"""CRAM 3.1 name tokenizer (tok3, block method 8) tests.

Round-trips over adversarial name shapes, frozen golden bytes pinning the
wire layout (the reference mount is empty — SURVEY.md section 0 — so the
encoder's own output is the only available oracle and drift must at least
be loud), container-level 3.1 write/read with the RN block really using
method 8, and corrupt-stream handling.
"""
import random

import pytest

from hadoop_bam_tpu.formats.cram_name_tok3 import (
    Tok3Error, tok3_decode, tok3_encode,
)

from fixtures import make_header, make_records


@pytest.fixture(autouse=True)
def _pin_names_method(monkeypatch):
    """Ambient HBAM_CRAM31_NAMES must not flip the tok3-default tests."""
    monkeypatch.delenv("HBAM_CRAM31_NAMES", raising=False)


def _roundtrip(names, sep=b"\0"):
    payload = sep.join(names) + sep
    enc = tok3_encode(payload)
    assert tok3_decode(enc) == payload
    return enc


def test_tok3_illumina_names():
    rng = random.Random(0)
    names, x, y = [], 1000, 2000
    for i in range(3000):
        x += rng.randint(0, 30)
        y += rng.randint(0, 30)
        names.append(f"EAS139:136:FC706VJ:2:{2104 + i // 500}:{x}:{y}"
                     .encode())
    enc = _roundtrip(names)
    # the whole point: structured names compress far better than gzip
    import gzip
    payload = b"\0".join(names) + b"\0"
    assert len(enc) < len(gzip.compress(payload)) / 2


@pytest.mark.parametrize("sep", [b"\0", b"\n"])
def test_tok3_adversarial_shapes(sep):
    other = b"\n" if sep == b"\0" else b"\0"
    names = [
        b"a",                                   # single char
        b"read_0001", b"read_0002", b"read_0002",   # leading zeros + dup
        b"0",                                   # lone zero digit
        b"00",                                  # zero with leading zero
        b"99999999999999999999",                # digit run > 2^32 -> ALPHA
        b"4294967295",                          # exactly u32 max
        b"4294967296",                          # u32 max + 1 -> ALPHA
        b"x" * 300,                             # long alpha run
        b":".join(b"%d" % i for i in range(200)),   # > MAX_TOKENS tokens
        b"mixed123text456",
        b"[]{}~!@#$%^&*()",                     # punctuation alpha
    ]
    if other == b"\n":
        names.append(other + b"name")          # '\n' inside a NUL-sep name
    # duplicate whole set (exercises DUP at distance > 1)
    _roundtrip(names + names, sep)


def test_tok3_nul_inside_newline_separated_name_rejected():
    """A NUL inside a '\\n'-separated name cannot ride the NUL-terminated
    ALPHA streams; the encoder must refuse (callers fall back) rather
    than corrupt."""
    with pytest.raises(Tok3Error, match="NUL"):
        tok3_encode(b"a\0b\nnext\n")


def test_tok3_delta_paths():
    # consecutive digit fields differing by small deltas hit DDELTA;
    # zero-padded ones hit DDELTA0 (including width carries)
    names = [b"r:0001:5", b"r:0002:5", b"r:0009:260", b"r:0010:261",
             b"r:0099:300", b"r:0100:300", b"r:0999:1", b"r:1000:1"]
    _roundtrip(names)


def test_tok3_single_and_identical():
    _roundtrip([b"only"])
    _roundtrip([b"same"] * 100)


def test_tok3_rejects_unsuitable_payloads():
    for bad in (b"", b"no-separator", b"a\0b"):    # b"a\0b": trailing bytes
        with pytest.raises(Tok3Error):
            tok3_encode(bad)
    with pytest.raises(Tok3Error):
        tok3_encode(b"a\0\0")                      # empty name


def test_tok3_corrupt_streams_fail_loudly():
    names = [b"EAS1:2:3", b"EAS1:2:4", b"EAS1:2:5"] * 20
    payload = b"\0".join(names) + b"\0"
    enc = bytearray(tok3_encode(payload))
    # arithmetic-coder flag: clear unsupported error
    bad = bytearray(enc)
    bad[8] |= 0x01
    with pytest.raises(Tok3Error, match="arithmetic"):
        tok3_decode(bytes(bad))
    # duplicate-stream descriptor: loud rejection, not speculative decode
    bad = bytearray(enc)
    bad[9] |= 0x40
    with pytest.raises(Tok3Error, match="duplicate-stream"):
        tok3_decode(bytes(bad))
    # truncation at every prefix must raise, never return garbage
    from hadoop_bam_tpu.formats.cram_codecs import RansError
    for cut in range(0, len(enc), 7):
        with pytest.raises((Tok3Error, RansError)):
            tok3_decode(bytes(enc[:cut]))
    # single-byte corruptions: either a loud error or (rarely) a decode,
    # but NEVER a silent wrong-length result
    rng = random.Random(4)
    for _ in range(40):
        bad = bytearray(enc)
        i = rng.randrange(9, len(bad))
        bad[i] ^= 1 << rng.randrange(8)
        try:
            out = tok3_decode(bytes(bad))
            assert len(out) == len(payload)
        except (Tok3Error, RansError):
            pass


def test_tok3_header_size_crosscheck():
    enc = tok3_encode(b"abc\0")
    with pytest.raises(Tok3Error, match="block header"):
        tok3_decode(enc, rsize=5)
    assert tok3_decode(enc, rsize=4) == b"abc\0"


# ---------------------------------------------------------------------------
# Frozen golden bytes: pin the wire layout against drift.  If an
# intentional layout change breaks these, re-freeze AND note the break in
# PARITY.md — any 3.1 file written before the change becomes unreadable.
# ---------------------------------------------------------------------------

GOLDEN_NAMES = [b"EAS139:136:FC706VJ:2:2104:15343:197393",
                b"EAS139:136:FC706VJ:2:2104:15370:197401",
                b"EAS139:136:FC706VJ:2:2104:15370:197401",
                b"read_007", b"read_008"]
GOLDEN_SHA256 = \
    "5ec855f46facc1fedf4d28dc063d5bc0ca93ddc017fc331ceb3fe1563559661a"
# Header region frozen byte-for-byte too (ulen=0x87, nnames=5, flags=0,
# first frame = slot-0 TYPE stream): cheap to eyeball in a hexdump.
GOLDEN_PREFIX_HEX = "870000000500000000"


def test_tok3_golden_bytes():
    enc = tok3_encode(b"\0".join(GOLDEN_NAMES) + b"\0")
    import hashlib
    assert enc.hex().startswith(GOLDEN_PREFIX_HEX)
    digest = hashlib.sha256(enc).hexdigest()
    assert digest == GOLDEN_SHA256, (
        f"tok3 wire layout drifted: sha256 {digest}; if intentional, "
        f"re-freeze and document in PARITY.md")
    assert tok3_decode(enc) == b"\0".join(GOLDEN_NAMES) + b"\0"


# ---------------------------------------------------------------------------
# Container level: a 3.1 CRAM really tokenizes its RN series
# ---------------------------------------------------------------------------

def _block_methods(path):
    from hadoop_bam_tpu.formats.cram import (
        ContainerHeader, FileDefinition, parse_raw_block,
    )
    buf = open(path, "rb").read()
    pos = FileDefinition.SIZE
    methods = []
    while pos < len(buf):
        hdr, pos = ContainerHeader.from_buffer(buf, pos)
        end = pos + hdr.length
        while pos < end:
            raw, pos = parse_raw_block(buf, pos)
            methods.append(raw.method)
    return methods


def test_cram31_names_use_tok3(tmp_path):
    from hadoop_bam_tpu.formats.cram import NAME_TOK
    from hadoop_bam_tpu.formats.cramio import CramWriter, read_cram

    header = make_header()
    recs = make_records(header, 300, seed=17)
    path = str(tmp_path / "tok3.cram")
    with CramWriter(path, header, records_per_container=60,
                    version=(3, 1)) as w:
        w.write_records(recs)
    assert NAME_TOK in _block_methods(path)
    _, out = read_cram(path)
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_cram30_has_no_tok3_blocks(tmp_path):
    from hadoop_bam_tpu.formats.cram import NAME_TOK
    from hadoop_bam_tpu.formats.cramio import write_cram

    header = make_header()
    recs = make_records(header, 100, seed=18)
    path = str(tmp_path / "v30.cram")
    write_cram(path, header, recs)
    assert NAME_TOK not in _block_methods(path)


def test_cram31_names_gzip_switch(tmp_path, monkeypatch):
    """HBAM_CRAM31_NAMES=gzip keeps 3.1 read names on GZIP (the interop
    escape hatch while the tok3 frame layout is only self-validated)."""
    from hadoop_bam_tpu.formats.cram import NAME_TOK
    from hadoop_bam_tpu.formats.cramio import CramWriter, read_cram

    monkeypatch.setenv("HBAM_CRAM31_NAMES", "gzip")
    header = make_header()
    recs = make_records(header, 200, seed=19)
    path = str(tmp_path / "tok3_off.cram")
    with CramWriter(path, header, records_per_container=50,
                    version=(3, 1)) as w:
        w.write_records(recs)
    assert NAME_TOK not in _block_methods(path)
    _, out = read_cram(path)
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_cram31_names_bad_knob_fails_closed(tmp_path, monkeypatch):
    from hadoop_bam_tpu.formats.cramio import CramWriter

    monkeypatch.setenv("HBAM_CRAM31_NAMES", "gz")
    header = make_header()
    recs = make_records(header, 10, seed=20)
    with pytest.raises(ValueError, match="HBAM_CRAM31_NAMES"):
        with CramWriter(str(tmp_path / "bad.cram"), header,
                        version=(3, 1)) as w:
            w.write_records(recs)
