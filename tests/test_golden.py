"""Golden wire-format fixtures: decoders vs COMMITTED bytes.

Every other round-trip test in this suite validates decoders against the
same session's encoders — circular if both drift together (round-2
VERDICT weak #5).  These tests decode bytes frozen in tests/golden/
(generated once by tests/make_goldens.py and committed), so any
behavioral drift in a decoder — or an encoder change that silently
breaks old files — fails here first.  The sha256 pins detect accidental
regeneration of the fixtures themselves.

If an INTENTIONAL format fix changes expectations: regenerate via
make_goldens.py, update the pins, and record the compatibility break in
PARITY.md.
"""
import hashlib
import os

import pytest

GOLD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")

SHA256 = {
    "golden.bam": "936fca774ce8e33e2957fe064d7d532e73eff2a8ccb599542a74e565a522f6ac",
    "golden.bam.sbi": "cbb0e4ec6abc1da2c2e666deb1804558f0be916b74b997bcc76a4e99f6797e44",
    "golden.bam.splitting-bai": "b7e02bd086cb07a279e8321e14b6fe8ed6ac807930795b909a8e8a5d03ff3df3",
    "golden.bam.voffsets": "b4bf7fa01d7ae345a671e8507db6a4294d90de1379514acb2e2b3ca14b0bfb62",
    "golden.bcf": "b22da7e37126c0bad0186a033a31171ee660f1891e82dbab60ebee0faeb75f9b",
    "golden.sam": "80228ec8432243775dc112fea108568eba7f29b43687e5a5598bca0b2913fcfa",
    "golden.vcf": "9fcdb168859cb6809799a6bc70fcb5bdb7f2681ba74d4e2bfd5e35f835e3cf91",
    "golden.vcf.gz": "651bf53ecf9d494baa30d97b6fc94a0154daed972c5f331ca05fe94f31d8db7b",
    "golden_30.cram": "646fe7cfaefe2de6e1fc7d51faff9c7b10971ba0bc4f9ed0bde55db48725b8dc",
    "golden_31.cram": "5a7ecc85d5a9507419bf447e695a3849fe19eb4449dd0ab330117ab1c50aea5e",
}

# The fixed 28-byte BGZF EOF terminator [SPEC SAMv1 4.1.2]
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")


def _path(name):
    return os.path.join(GOLD, name)


def test_fixtures_unchanged():
    found = sorted(os.listdir(GOLD))
    assert found == sorted(SHA256), "fixture set drifted"
    for name, want in SHA256.items():
        got = hashlib.sha256(open(_path(name), "rb").read()).hexdigest()
        assert got == want, (
            f"{name} bytes changed — if intentional, re-pin via "
            f"make_goldens.py and record the break in PARITY.md")


def _want_sam_lines():
    return open(_path("golden.sam")).read().splitlines()


def test_golden_bam_decodes():
    from hadoop_bam_tpu.api.dataset import open_bam
    ds = open_bam(_path("golden.bam"))
    got = [r.to_line() for r in ds.records()]
    assert got == _want_sam_lines()


def test_golden_bam_voffsets_and_eof():
    from hadoop_bam_tpu.api.dataset import open_bam
    raw = open(_path("golden.bam"), "rb").read()
    assert raw[-28:] == BGZF_EOF
    want = [int(x) for x in
            open(_path("golden.bam.voffsets")).read().split()]
    ds = open_bam(_path("golden.bam"))
    got = []
    for batch in ds.batches():
        got.extend(int(v) for v in batch.voffsets)
    assert got == want


def test_golden_sidecar_indexes():
    from hadoop_bam_tpu.split.splitting_index import SplittingIndex
    want = [int(x) for x in
            open(_path("golden.bam.voffsets")).read().split()]
    size = os.path.getsize(_path("golden.bam"))
    for suffix in (".splitting-bai", ".sbi"):
        idx = SplittingIndex.from_bytes(
            open(_path("golden.bam" + suffix), "rb").read())
        assert idx.voffsets[:-1] == want[::8]       # granularity 8
        assert idx.voffsets[-1] == size << 16
        if suffix == ".sbi":
            assert idx.granularity == 8
            assert idx.total_records == len(want)


@pytest.mark.parametrize("name", ["golden_30.cram", "golden_31.cram"])
def test_golden_cram_decodes(name):
    from hadoop_bam_tpu.formats.cramio import read_cram
    _, recs = read_cram(_path(name))
    assert [r.to_line() for r in recs] == _want_sam_lines()


def test_golden_cram31_uses_31_methods():
    """The 3.1 fixture must really exercise the 3.1 codecs (Nx16 + tok3),
    so decoding it is evidence those decode paths read old bytes."""
    from hadoop_bam_tpu.formats.cram import (
        ContainerHeader, FileDefinition, NAME_TOK, RANSNx16,
        parse_raw_block,
    )
    buf = open(_path("golden_31.cram"), "rb").read()
    pos = FileDefinition.SIZE
    methods = set()
    while pos < len(buf):
        hdr, pos = ContainerHeader.from_buffer(buf, pos)
        end = pos + hdr.length
        while pos < end:
            raw, pos = parse_raw_block(buf, pos)
            methods.add(raw.method)
    assert NAME_TOK in methods
    assert RANSNx16 in methods


def _want_vcf_lines():
    return open(_path("golden.vcf")).read().splitlines()


@pytest.mark.parametrize("name", ["golden.vcf.gz", "golden.bcf"])
def test_golden_variants_decode(name):
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    ds = open_vcf(_path(name))
    got = [r.to_line() for r in ds.records()]
    assert got == _want_vcf_lines()


def test_golden_vcf_gz_is_bgzf_with_eof():
    raw = open(_path("golden.vcf.gz"), "rb").read()
    assert raw[:4] == b"\x1f\x8b\x08\x04"      # BGZF magic + FEXTRA
    assert raw[-28:] == BGZF_EOF
