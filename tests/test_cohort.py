"""Cohort variant plane (hadoop_bam_tpu/cohort/).

The load-bearing pins:

- **Oracle join identity**: the streaming k-way merge + harmonize +
  FeedPipeline tiling is VALUE-IDENTICAL to an independent serial
  per-site Python oracle (dict-of-sites, written from the harmonization
  spec, sharing no code with the join) across randomized
  k / missingness / multi-allelic / duplicate / swap fixtures and
  mixed containers (text VCF, BGZF VCF, BCF).
- **Harmonization edge cases**: multi-allelic split/merge, REF/ALT
  swap, allele reorder, duplicate positions within one input,
  inconsistent REF shapes -> sentinel.
- **Sentinel propagation**: rows beyond each shard's n_records carry
  -1 dosage / NaN qual through ``tensor_batches``.
- **GWAS parity**: the shard_map drivers match NumPy reference
  implementations of af / call rate / HWE chi2 / score chi2 to float32
  tolerance.
- **Per-input fault domains**: a corrupt sample under chaos
  quarantines (sentinel column + manifest entry + fed breaker) without
  failing the build; the fraction circuit and the quarantine=off path
  raise.
- **Cohort-slice serving**: warm slices are answered entirely from
  device-resident tiles (zero host decode in an isolated
  MetricsContext), wire round-trip included.
"""
import dataclasses
import json
import math
import os
import random

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.cohort import (
    CohortDataset, CohortManifest, as_manifest, cohort_gwas, load_manifest,
)
from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError

pytestmark = pytest.mark.cohort

_HDR = ("##fileformat=VCFv4.2\n"
        "##contig=<ID=chr20,length=64444167>\n"
        "##contig=<ID=chr21,length=46709983>\n"
        '##FILTER=<ID=q10,Description="low">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        '##FORMAT=<ID=DP,Number=1,Type=Integer,Description="Depth">\n')


def _write_sample(path, sample_id, lines):
    """One single-sample VCF in the container the extension names."""
    text = (_HDR + "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\t"
            f"FORMAT\t{sample_id}\n" + "".join(l + "\n" for l in lines))
    if path.endswith(".vcf"):
        with open(path, "w") as f:
            f.write(text)
        return path
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord

    header = VCFHeader.from_text(_HDR + "#CHROM\tPOS\tID\tREF\tALT\tQUAL\t"
                                 f"FILTER\tINFO\tFORMAT\t{sample_id}\n")
    with open_vcf_writer(path, header) as w:
        for l in lines:
            w.write_record(VcfRecord.from_line(l))
    return path


def _manifest(tmp_path, files, ids=None):
    man = {"samples": [
        {"id": ids[i] if ids else f"s{i}", "path": str(p)}
        for i, p in enumerate(files)]}
    mp = tmp_path / "cohort.json"
    mp.write_text(json.dumps(man))
    return str(mp)


# ---------------------------------------------------------------------------
# the independent serial per-site oracle
# ---------------------------------------------------------------------------

def _oracle_join(paths, config=DEFAULT_CONFIG):
    """Dict-of-sites reference join: read every record of every sample,
    bucket by (contig, pos), harmonize per the spec (README "Cohort
    analysis"), emit sorted columns.  Shares no code with
    cohort/join.py or cohort/harmonize.py."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf

    datasets = [open_vcf(p, config) for p in paths]
    contigs = []
    for ds in datasets:
        for c in ds.header.contigs:
            if c not in contigs:
                contigs.append(c)
    cidx = {c: i for i, c in enumerate(contigs)}
    k = len(paths)
    sites = {}                       # (ci, pos) -> {si: [rec, ...]}
    for si, ds in enumerate(datasets):
        for rec in ds.records():
            sites.setdefault((cidx[rec.chrom], rec.pos), {}) \
                .setdefault(si, []).append(rec)
    rows = []
    for key in sorted(sites):
        per = sites[key]
        chosen = {si: recs[0] for si, recs in per.items()}  # dup: first
        order = sorted(chosen)
        refs = [chosen[si].ref for si in order]
        ref = max(set(refs), key=lambda r: (refs.count(r), -refs.index(r)))
        alts = []
        for si in order:
            r = chosen[si]
            if r.ref == ref:
                for a in r.alts:
                    if a != ref and a not in alts:
                        alts.append(a)
        canon = {ref: 0, **{a: j + 1 for j, a in enumerate(alts)}}
        dosage = np.full(k, -1, np.int8)
        qual = np.full(k, np.nan, np.float32)
        for si in order:
            r = chosen[si]
            if r.qual is not None:
                qual[si] = np.float32(r.qual)
            if not r.fmt or r.fmt[0] != "GT" or not r.genotypes:
                continue
            gt = r.genotypes[0].split(":", 1)[0]
            if not gt:
                continue
            if r.ref != ref and r.ref not in canon:
                continue             # incompatible shape: sentinel
            local = (r.ref,) + tuple(r.alts)
            dose, ok = 0, True
            for a in gt.replace("|", "/").split("/"):
                if not a.isdigit() or int(a) >= len(local):
                    ok = False
                    break
                c = canon.get(local[int(a)])
                if c is None:
                    ok = False
                    break
                dose += 1 if c != 0 else 0
            if ok:
                dosage[si] = min(dose, 127)
        rows.append((key[0], key[1], 1 + len(alts), dosage, qual))
    return contigs, rows


def _collect_batches(ds, mesh=None):
    """Drain tensor_batches into trimmed host columns (the join's
    public value surface)."""
    chrom, pos, nall, dosage, qual = [], [], [], [], []
    for out in ds.tensor_batches(mesh=mesh):
        counts = np.asarray(out["n_records"])
        h = {kk: np.asarray(out[kk]) for kk in
             ("chrom", "pos", "n_allele", "dosage", "qual")}
        for dev in range(counts.shape[0]):
            c = int(counts[dev])
            if c:
                chrom.append(h["chrom"][dev, :c])
                pos.append(h["pos"][dev, :c])
                nall.append(h["n_allele"][dev, :c])
                dosage.append(h["dosage"][dev, :c])
                qual.append(h["qual"][dev, :c])
    if not chrom:
        return None
    return {
        "chrom": np.concatenate(chrom), "pos": np.concatenate(pos),
        "n_allele": np.concatenate(nall),
        "dosage": np.concatenate(dosage), "qual": np.concatenate(qual),
    }


def _assert_join_matches_oracle(paths, config=DEFAULT_CONFIG):
    contigs, rows = _oracle_join(paths, config)
    ds = CohortDataset(list(paths), config)
    assert ds.contigs == contigs
    got = _collect_batches(ds)
    k = len(paths)
    if got is None:
        assert rows == []
        return ds
    assert got["chrom"].tolist() == [r[0] for r in rows]
    assert got["pos"].tolist() == [r[1] for r in rows]
    assert got["n_allele"].tolist() == [r[2] for r in rows]
    want_d = np.stack([r[3] for r in rows])
    want_q = np.stack([r[4] for r in rows])
    np.testing.assert_array_equal(got["dosage"][:, :k], want_d)
    np.testing.assert_array_equal(np.isnan(got["qual"][:, :k]),
                                  np.isnan(want_q))
    np.testing.assert_allclose(
        np.nan_to_num(got["qual"][:, :k]), np.nan_to_num(want_q),
        rtol=1e-6)
    return ds


# ---------------------------------------------------------------------------
# randomized oracle identity
# ---------------------------------------------------------------------------

def _random_sample_lines(rng, n_sites=40):
    """One sample's sorted lines over a shared position grid with
    missingness, multi-allelic records, swaps, duplicates, polyploid
    and missing genotypes."""
    lines = []
    for chrom in ("chr20", "chr21"):
        pos = 0
        for _ in range(n_sites):
            pos += rng.randint(1, 25)
            if rng.random() < 0.35:
                continue                      # this sample skips the site
            ref = rng.choice("ACGT")
            n_alt = rng.choice([1, 1, 1, 2, 3])
            alts = rng.sample([c for c in "ACGT" if c != ref], n_alt)
            if rng.random() < 0.1:            # REF/ALT swap shape
                ref, alts[0] = alts[0], ref
            gt = rng.choice(["0/0", "0/1", "1/1", "./.", "1|0", ".",
                             "0/1/1", "2/1" if n_alt >= 2 else "0/1"])
            qual = rng.choice([".", str(rng.randint(1, 99)),
                               f"{rng.random() * 50:.2f}"])
            dp = rng.randint(1, 40)
            dup = 2 if rng.random() < 0.06 else 1
            for _d in range(dup):
                lines.append(f"{chrom}\t{pos}\t.\t{ref}\t"
                             f"{','.join(alts)}\t{qual}\tPASS\t.\t"
                             f"GT:DP\t{gt}:{dp}")
    return lines


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_join_matches_oracle_randomized(tmp_path, seed):
    rng = random.Random(seed)
    k = rng.randint(2, 6)
    exts = [".vcf", ".vcf.gz", ".bcf"]
    paths = []
    for s in range(k):
        ext = exts[s % len(exts)]
        paths.append(_write_sample(str(tmp_path / f"s{s}{ext}"), f"s{s}",
                                   _random_sample_lines(rng)))
    _assert_join_matches_oracle(paths)


def test_join_across_mixed_containers_small(tmp_path):
    """A tiny hand-checked cohort across all three containers."""
    p0 = _write_sample(str(tmp_path / "a.vcf"), "a", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1",
        "chr21\t5\t.\tC\tT\t7\tPASS\t.\tGT\t1/1",
    ])
    p1 = _write_sample(str(tmp_path / "b.vcf.gz"), "b", [
        "chr20\t100\t.\tA\tT\t11\tPASS\t.\tGT\t1/1",
    ])
    p2 = _write_sample(str(tmp_path / "c.bcf"), "c", [
        "chr20\t100\t.\tA\tG\t22\tPASS\t.\tGT\t1/1",
        "chr21\t5\t.\tC\tT\t9\tPASS\t.\tGT\t0/1",
    ])
    ds = _assert_join_matches_oracle([p0, p1, p2])
    got = _collect_batches(ds)
    # chr20:100 joins A->[G, T]: multi-allelic union in sample order
    assert got["n_allele"].tolist() == [3, 2]
    np.testing.assert_array_equal(got["dosage"][0, :3], [1, 2, 2])


# ---------------------------------------------------------------------------
# harmonization edge cases (explicit)
# ---------------------------------------------------------------------------

def _join_two(tmp_path, lines_a, lines_b, config=DEFAULT_CONFIG):
    pa = _write_sample(str(tmp_path / "ha.vcf"), "ha", lines_a)
    pb = _write_sample(str(tmp_path / "hb.vcf"), "hb", lines_b)
    ds = CohortDataset([pa, pb], config)
    return ds, _collect_batches(ds)


def test_harmonize_ref_alt_swap(tmp_path):
    """One caller normalized the other way: its hom-ref is dosage 2
    against the canonical orientation."""
    ds, got = _join_two(
        tmp_path,
        ["chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"],
        ["chr20\t100\t.\tG\tA\t30\tPASS\t.\tGT\t0/0"])
    assert got["n_allele"].tolist() == [2]
    # sample b's REF G maps to canonical ALT G: 0/0 -> two G alleles ->
    # dosage 2
    np.testing.assert_array_equal(got["dosage"][0, :2], [1, 2])


def test_harmonize_multiallelic_split_and_reorder(tmp_path):
    """Split multi-allelics merge into one allele set; ALT order
    differences map by string, not by index."""
    ds, got = _join_two(
        tmp_path,
        ["chr20\t100\t.\tA\tG,T\t30\tPASS\t.\tGT\t1/2"],
        ["chr20\t100\t.\tA\tT,G\t30\tPASS\t.\tGT\t1/1"])
    assert got["n_allele"].tolist() == [3]      # A -> [G, T]
    # b's "1" is T (its own ALT order) -> canonical non-ref: dosage 2
    np.testing.assert_array_equal(got["dosage"][0, :2], [2, 2])


def test_harmonize_duplicate_positions_first_wins(tmp_path):
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    with MetricsContext() as m:
        ds, got = _join_two(
            tmp_path,
            ["chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t1/1",
             "chr20\t100\t.\tA\tG\t99\tPASS\t.\tGT\t0/0"],  # dup: ignored
            ["chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    np.testing.assert_array_equal(got["dosage"][0, :2], [2, 1])
    assert got["qual"][0, 0] == np.float32(30)
    assert m.snapshot()["counters"].get("cohort.duplicate_sites") == 1


def test_harmonize_inconsistent_ref_goes_sentinel(tmp_path):
    """An indel REF overlapping a SNP site cannot map: that sample's
    call is missing, and no fabricated allele appears."""
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    with MetricsContext() as m:
        ds, got = _join_two(
            tmp_path,
            ["chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"],
            ["chr20\t100\t.\tAT\tA\t30\tPASS\t.\tGT\t1/1"])
    assert got["n_allele"].tolist() == [2]      # A -> [G] only
    np.testing.assert_array_equal(got["dosage"][0, :2], [1, -1])
    assert m.snapshot()["counters"].get("cohort.harmonize_dropped") == 1


def test_harmonize_missing_and_polyploid(tmp_path):
    ds, got = _join_two(
        tmp_path,
        ["chr20\t100\t.\tA\tG\t.\tPASS\t.\tGT\t./.",
         "chr20\t200\t.\tC\tT\t5\tPASS\t.\tGT\t0/1/1"],   # triploid
        ["chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t1/1"])
    np.testing.assert_array_equal(got["dosage"][0, :2], [-1, 2])
    assert np.isnan(got["qual"][0, 0])          # '.' QUAL -> NaN
    np.testing.assert_array_equal(got["dosage"][1, :2], [2, -1])


def test_abandoned_join_restarts_from_file_start(tmp_path):
    """An abandoned iteration (early tensor_batches break, a tripped
    circuit) must not make the NEXT join silently resume mid-file
    (reviewed: VcfDataset.records() only auto-resets after full
    exhaustion)."""
    rng = random.Random(31)
    paths = [_write_sample(str(tmp_path / f"r{s}.vcf"), f"r{s}",
                           _random_sample_lines(rng, n_sites=30))
             for s in range(2)]
    cfg = dataclasses.replace(DEFAULT_CONFIG, cohort_chunk_sites=4)
    ds = CohortDataset(paths, cfg)
    full = _collect_batches(CohortDataset(paths, cfg))
    # abandon a site_chunks iteration mid-stream...
    it = ds.site_chunks()
    next(it)
    it.close()
    # ...then both the host surface and the GWAS driver still cover
    # the whole cohort
    got = _collect_batches(ds)
    np.testing.assert_array_equal(got["pos"], full["pos"])
    assert ds.gwas()["n_variants"] == full["pos"].shape[0]


def test_sentinel_propagation_through_tensor_batches(tmp_path):
    """Rows past each shard's n_records carry -1 dosage / NaN qual —
    the PR-4 sentinel convention, on every shard including empty
    ones."""
    p = _write_sample(str(tmp_path / "one.vcf"), "one", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    ds = CohortDataset([p])
    outs = list(ds.tensor_batches())
    assert len(outs) == 1
    out = outs[0]
    counts = np.asarray(out["n_records"])
    dosage = np.asarray(out["dosage"])
    qual = np.asarray(out["qual"])
    assert counts.sum() == 1
    for dev in range(counts.shape[0]):
        c = int(counts[dev])
        assert (dosage[dev, c:] == -1).all()
        assert np.isnan(qual[dev, c:]).all()


# ---------------------------------------------------------------------------
# GWAS drivers vs NumPy references
# ---------------------------------------------------------------------------

def _np_gwas_reference(dosage, n_samples, pheno=None):
    """Independent float64 NumPy implementations of the driver
    formulas (cohort/gwas.py docstring)."""
    d = dosage[:, :n_samples].astype(np.int64)
    called = d >= 0
    n_called = called.sum(axis=1)
    alt = np.where(called, d, 0).sum(axis=1)
    with np.errstate(invalid="ignore", divide="ignore"):
        af = np.where(n_called > 0, alt / (2.0 * np.maximum(n_called, 1)),
                      np.nan)
        call_rate = n_called / n_samples
        n0 = ((d == 0) & called).sum(axis=1).astype(float)
        n1 = ((d == 1) & called).sum(axis=1).astype(float)
        n2 = ((d == 2) & called).sum(axis=1).astype(float)
        m = n0 + n1 + n2
        p = np.where(m > 0, (2 * n2 + n1) / (2 * np.maximum(m, 1)), 0.0)
        hwe = np.full(d.shape[0], np.nan)
        for i in range(d.shape[0]):
            if m[i] <= 0:
                continue
            chi = 0.0
            for obs, exp in (
                    (n0[i], (1 - p[i]) ** 2 * m[i]),
                    (n1[i], 2 * p[i] * (1 - p[i]) * m[i]),
                    (n2[i], p[i] ** 2 * m[i])):
                if exp > 0:
                    chi += (obs - exp) ** 2 / exp
            hwe[i] = chi
        score = np.full(d.shape[0], np.nan)
        if pheno is not None:
            y = np.asarray(pheno, float)
            for i in range(d.shape[0]):
                use = called[i] & np.isfinite(y)
                n = use.sum()
                if n <= 1:
                    continue
                yi, gi = y[use], d[i, use].astype(float)
                u = ((yi - yi.mean()) * (gi - gi.mean())).sum()
                vg = ((gi - gi.mean()) ** 2).sum()
                vy = ((yi - yi.mean()) ** 2).sum() / n
                if vy * vg > 1e-12:
                    score[i] = u * u / (vy * vg)
    return {"af": af, "call_rate": call_rate, "hwe_chi2": hwe,
            "score_chi2": score}


def test_gwas_matches_numpy_reference(tmp_path):
    rng = random.Random(11)
    k = 5
    paths = [_write_sample(str(tmp_path / f"g{s}.vcf"), f"g{s}",
                           _random_sample_lines(rng, n_sites=30))
             for s in range(k)]
    ds = CohortDataset(paths)
    pheno = np.asarray([0.2, 1.5, float("nan"), -0.7, 0.9], np.float32)
    res = ds.gwas(phenotype=pheno)
    got = _collect_batches(CohortDataset(paths))
    ref = _np_gwas_reference(got["dosage"], k, pheno)
    assert res["n_variants"] == got["dosage"].shape[0] > 0
    for col in ("af", "call_rate", "hwe_chi2", "score_chi2"):
        np.testing.assert_allclose(res[col], ref[col], rtol=2e-4,
                                   atol=2e-4, equal_nan=True,
                                   err_msg=col)


def test_gwas_without_phenotype_and_bad_phenotype(tmp_path):
    p = _write_sample(str(tmp_path / "p.vcf"), "p", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    ds = CohortDataset([p])
    res = ds.gwas()
    assert np.isnan(res["score_chi2"]).all()
    with pytest.raises(PlanError):
        ds.gwas(phenotype=np.zeros(3, np.float32))


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------

def test_manifest_forms_and_plan_errors(tmp_path):
    p = _write_sample(str(tmp_path / "m.vcf"), "m", [])
    mp = tmp_path / "man.json"
    # relative paths resolve against the manifest's directory
    mp.write_text(json.dumps({"samples": [{"id": "m", "path": "m.vcf"}]}))
    man = load_manifest(str(mp))
    assert man.samples[0].path == str(tmp_path / "m.vcf")
    assert man.sample_ids == ["m"]
    # bare path list form + default ids
    assert as_manifest([p]).sample_ids == ["m"]
    # malformed shapes are PLAN class
    with pytest.raises(PlanError):
        CohortManifest.from_doc({"nope": []})
    with pytest.raises(PlanError):
        CohortManifest.from_doc([])
    with pytest.raises(PlanError):
        CohortManifest.from_doc([{"path": p}, {"path": p}])  # dup id
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(PlanError):
        load_manifest(str(bad))
    with pytest.raises(FileNotFoundError):
        load_manifest(str(tmp_path / "absent.json"))


def test_manifest_identity_tracks_inputs(tmp_path):
    p = _write_sample(str(tmp_path / "i.vcf"), "i", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    mp = _manifest(tmp_path, [p])
    i0 = load_manifest(mp).identity()
    assert i0 == load_manifest(mp).identity()
    os.utime(p, ns=(1, 1))       # touch an input: identity changes
    assert load_manifest(mp).identity() != i0
    assert i0[0] == os.path.abspath(mp)   # anchor = manifest abspath


# ---------------------------------------------------------------------------
# per-input-file fault domains
# ---------------------------------------------------------------------------

def test_corrupt_input_under_chaos_quarantines(tmp_path):
    """A byte-flipped sample stream quarantines: sentinel column,
    manifest entry, fed fault domain — the build completes."""
    from hadoop_bam_tpu import resilience
    from hadoop_bam_tpu.utils.metrics import MetricsContext
    from hadoop_bam_tpu.utils.resilient import clear_chaos, \
        install_chaos_seeded

    good = _write_sample(str(tmp_path / "ok.vcf"), "ok", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1",
        "chr20\t200\t.\tC\tT\t30\tPASS\t.\tGT\t1/1"])
    bad = _write_sample(str(tmp_path / "bad.bcf"), "bad", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t1/1",
        "chr20\t200\t.\tC\tT\t30\tPASS\t.\tGT\t0/1"])
    ds = CohortDataset([good, bad])      # headers read CLEAN, then...
    install_chaos_seeded(bad, seed=99, bitflip_rate=1.0)
    try:
        with MetricsContext() as m:
            got = _collect_batches(ds)
    finally:
        clear_chaos(bad)
    # the good sample's column is intact; the bad one is all sentinel
    assert got["pos"].tolist() == [100, 200]
    np.testing.assert_array_equal(got["dosage"][:, 0], [1, 2])
    np.testing.assert_array_equal(got["dosage"][:, 1], [-1, -1])
    assert list(ds.manifest.quarantined) == ["bad"]
    assert m.snapshot()["counters"]["cohort.samples_quarantined"] == 1
    # the input's fault domain breaker was fed
    states = resilience.registry().states()
    assert any(k.startswith("cohort/input/") for k in states)


def test_out_of_order_input_quarantines_and_strict_raises(tmp_path):
    good = _write_sample(str(tmp_path / "g.vcf"), "g", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    unsorted = _write_sample(str(tmp_path / "u.vcf"), "u", [
        "chr20\t500\t.\tA\tG\t30\tPASS\t.\tGT\t1/1",
        "chr20\t100\t.\tC\tT\t30\tPASS\t.\tGT\t0/1"])
    ds = CohortDataset([good, unsorted])
    got = _collect_batches(ds)
    assert "u" in ds.manifest.quarantined
    # records BEFORE the fault still joined (degrade, don't discard)
    assert 500 in got["pos"].tolist()
    # quarantine off: the same data fault raises
    cfg = dataclasses.replace(DEFAULT_CONFIG,
                              cohort_quarantine_inputs=False)
    with pytest.raises(CorruptDataError):
        _collect_batches(CohortDataset([good, unsorted], cfg))


def test_quarantine_fraction_circuit(tmp_path):
    """Losing more than cohort_max_quarantine_fraction of the columns
    fails the build — mostly-sentinel output is not a result."""
    u1 = _write_sample(str(tmp_path / "u1.vcf"), "u1", [
        "chr20\t500\t.\tA\tG\t30\tPASS\t.\tGT\t1/1",
        "chr20\t100\t.\tC\tT\t30\tPASS\t.\tGT\t0/1"])
    u2 = _write_sample(str(tmp_path / "u2.vcf"), "u2", [
        "chr21\t500\t.\tA\tG\t30\tPASS\t.\tGT\t1/1",
        "chr21\t100\t.\tC\tT\t30\tPASS\t.\tGT\t0/1"])
    cfg = dataclasses.replace(DEFAULT_CONFIG,
                              cohort_max_quarantine_fraction=0.5)
    with pytest.raises(CorruptDataError, match="quarantined"):
        _collect_batches(CohortDataset([u1, u2], cfg))


def test_corrupt_header_quarantines_at_build(tmp_path):
    """Corruption that already breaks the HEADER read is still data,
    not configuration: the sample quarantines before the join starts
    and its column is all sentinel."""
    good = _write_sample(str(tmp_path / "hok.vcf"), "hok", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    broken = _write_sample(str(tmp_path / "hbad.bcf"), "hbad", [
        "chr20\t100\t.\tA\tG\t30\tPASS\t.\tGT\t1/1"])
    raw = bytearray(open(broken, "rb").read())
    raw[20:60] = os.urandom(40)              # garble the header block
    with open(broken, "wb") as f:
        f.write(raw)
    cfg = dataclasses.replace(DEFAULT_CONFIG,
                              cohort_max_quarantine_fraction=0.6)
    ds = CohortDataset([good, broken], cfg)
    assert "hbad" in ds.manifest.quarantined
    got = _collect_batches(ds)
    np.testing.assert_array_equal(got["dosage"][:, :2], [[1, -1]])
    # the default 0.5 fraction circuit counts header casualties too
    with pytest.raises(CorruptDataError):
        CohortDataset([broken], dataclasses.replace(
            DEFAULT_CONFIG, cohort_max_quarantine_fraction=0.4))
    # quarantine off: the corruption raises
    with pytest.raises(Exception):
        CohortDataset([good, broken], dataclasses.replace(
            DEFAULT_CONFIG, cohort_quarantine_inputs=False))


def test_missing_input_is_plan_never_quarantined(tmp_path):
    with pytest.raises(FileNotFoundError):
        CohortDataset([str(tmp_path / "nope.vcf")])


# ---------------------------------------------------------------------------
# cohort-slice serving
# ---------------------------------------------------------------------------

def _serve_fixture(tmp_path, k=3, n_sites=25):
    rng = random.Random(21)
    paths = []
    for s in range(k):
        lines = []
        pos = 0
        for _ in range(n_sites):
            pos += rng.randint(1, 20)
            if rng.random() < 0.2:
                continue
            lines.append(f"chr20\t{pos}\t.\tA\tG\t30\tPASS\t.\tGT\t"
                         f"{rng.choice(['0/0', '0/1', '1/1', './.'])}")
        paths.append(_write_sample(str(tmp_path / f"v{s}.vcf"), f"v{s}",
                                   lines))
    return _manifest(tmp_path, [str(p) for p in paths]), paths


def test_cohort_slice_serving_warm_bypass(tmp_path):
    """Cold builds the joined tiles; every warm slice is answered from
    the device tier — zero host decode in an isolated context — and
    counts match the host oracle."""
    from hadoop_bam_tpu.serve import ServeLoop
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    man, paths = _serve_fixture(tmp_path)
    contigs, rows = _oracle_join([str(p) for p in paths])
    lo, hi = 1, 150
    want = sum(1 for r in rows if r[0] == 0 and lo <= r[1] <= hi)
    with ServeLoop() as loop:
        cold = loop.query(man, [f"chr20:{lo}-{hi}"], cohort=True)[0]
        assert cold.count == want
        assert cold.tile_misses >= 1 and cold.tile_hits == 0
        assert cold.extra["n_samples"] == 3
        with MetricsContext() as m:
            warm = loop.query(man, [f"chr20:{lo}-{hi}"], cohort=True,
                              want_records=True)[0]
        snap = m.snapshot()
        assert warm.count == want
        assert warm.tile_hits >= 1 and warm.tile_misses == 0
        # THE bypass proof: repeat slices do no host decode / join work
        assert snap["wall_timers"].get("cohort.join_wall", 0.0) == 0.0
        assert snap["wall_timers"].get("pipeline.host_decode_wall",
                                       0.0) == 0.0
        # records mode: wire-shaped per-variant dicts, sorted, af in range
        assert len(warm.records) == want
        assert all(r["chrom"] == "chr20" and lo <= r["pos"] <= hi
                   for r in warm.records)
        assert all(r["af"] is None or 0.0 <= r["af"] <= 1.0
                   for r in warm.records)
        # a different slice over the same cohort is ALSO warm (tiles
        # hold the whole joined tensor, keyed by manifest identity)
        with MetricsContext() as m2:
            other = loop.query(man, ["chr20:151-100000"], cohort=True)[0]
        assert m2.snapshot()["wall_timers"].get("cohort.join_wall",
                                                0.0) == 0.0
        want2 = sum(1 for r in rows if r[0] == 0 and 151 <= r[1] <= 100000)
        assert other.count == want2


def test_cohort_slice_input_rewrite_invalidates(tmp_path):
    """Rewriting one sample file changes the manifest identity: the
    next slice re-joins instead of serving stale tiles."""
    from hadoop_bam_tpu.serve import ServeLoop

    man, paths = _serve_fixture(tmp_path, k=2, n_sites=8)
    with ServeLoop() as loop:
        before = loop.query(man, ["chr20"], cohort=True)[0]
        # rewrite sample 0 with an extra site at pos 1
        _write_sample(str(paths[0]), "v0", [
            "chr20\t1\t.\tA\tG\t30\tPASS\t.\tGT\t1/1"])
        after = loop.query(man, ["chr20"], cohort=True)[0]
        assert after.tile_misses >= 1        # re-built, not stale
        assert after.count != before.count or after.n_candidates \
            != before.n_candidates


def test_cohort_slice_serves_through_header_corrupt_sample(tmp_path):
    """The serve path shares the CLI/API quarantine policy: a sample
    whose HEADER bytes are corrupt quarantines inside the serve build
    instead of failing the request (reviewed: the old separate
    header-read path raised out of serve())."""
    from hadoop_bam_tpu.serve import ServeLoop

    good = _write_sample(str(tmp_path / "sg.vcf"), "sg", [
        "chr20\t10\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    broken = _write_sample(str(tmp_path / "sb.bcf"), "sb", [
        "chr20\t10\t.\tA\tG\t30\tPASS\t.\tGT\t1/1"])
    raw = bytearray(open(broken, "rb").read())
    raw[20:60] = os.urandom(40)
    with open(broken, "wb") as f:
        f.write(raw)
    man = _manifest(tmp_path, [good, broken], ids=["sg", "sb"])
    cfg = dataclasses.replace(DEFAULT_CONFIG,
                              cohort_max_quarantine_fraction=0.6)
    with ServeLoop(config=cfg) as loop:
        res = loop.query(man, ["chr20:1-100"], cohort=True)[0]
        assert res.count == 1
        assert res.extra["n_samples"] == 2
        assert res.extra["quarantined"] == ["sb"]


def test_cohort_slice_bad_contig_and_quarantine_on_wire(tmp_path):
    import io

    from hadoop_bam_tpu.serve import ServeLoop
    from hadoop_bam_tpu.serve.transport import handle_stream

    good = _write_sample(str(tmp_path / "w.vcf"), "w", [
        "chr20\t10\t.\tA\tG\t30\tPASS\t.\tGT\t0/1"])
    unsorted = _write_sample(str(tmp_path / "x.vcf"), "x", [
        "chr20\t500\t.\tA\tG\t30\tPASS\t.\tGT\t1/1",
        "chr20\t100\t.\tC\tT\t30\tPASS\t.\tGT\t0/1"])
    man = _manifest(tmp_path, [good, unsorted], ids=["w", "x"])
    with ServeLoop() as loop:
        with pytest.raises(PlanError):
            loop.query(man, ["chrBOGUS:1-2"], cohort=True)
        reqs = (json.dumps({"id": 1, "cohort": True, "path": man,
                            "regions": ["chr20:1-1000"]}) + "\n"
                + json.dumps({"id": 2, "cohort": True, "path": man,
                              "regions": ["chrBOGUS:1-2"]}) + "\n")
        out = io.StringIO()
        handle_stream(loop, io.StringIO(reqs), out)
        docs = {d["id"]: d for d in
                (json.loads(l) for l in out.getvalue().splitlines())}
        r1 = docs[1]["results"][0]
        # w's chr20:10 + x's chr20:500 (x's out-of-order 100 is where
        # its stream faulted and quarantined)
        assert r1["count"] == 2
        assert r1["n_samples"] == 2
        # the quarantined sample surfaces on the wire
        assert r1["quarantined"] == ["x"]
        assert docs[2]["kind"] == "plan"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_cohort_stats_and_tsv(tmp_path, capsys):
    from hadoop_bam_tpu.tools.cli import main

    man, _paths = _serve_fixture(tmp_path, k=2, n_sites=10)
    pheno = tmp_path / "pheno.txt"
    pheno.write_text("1.0\n0.0\n")
    tsv = tmp_path / "stats.tsv"
    assert main(["cohort", man, "--pheno", str(pheno),
                 "--tsv", str(tsv)]) == 0
    out = capsys.readouterr().out
    assert "samples\t2" in out
    assert "variants\t" in out and "mean_af\t" in out
    header = tsv.read_text().splitlines()[0].split("\t")
    assert header == ["chrom", "pos", "n_allele", "af", "call_rate",
                      "hwe_chi2", "score_chi2"]
    # --region slices the report
    assert main(["cohort", man, "--region", "chr20:1-3"]) == 0
    out2 = capsys.readouterr().out
    assert "variants\t0" in out2
