"""Fused preprocessing plane tests (hadoop_bam_tpu/prep/): mesh
duplicate marking byte-validated against the serial host oracle over a
fuzz corpus (unmapped / mate-unmapped / secondary / supplementary,
S/H-clipped 5' ends, score ties), tie-break determinism across shard
counts and round sizes, byte-flip corruption classing, SIGKILL-and-
resume at every fused-stage boundary, and the cold QueryEngine open of
the output with no rescan.

The kill tests are REAL (same protocol as test_jobs.py): a subprocess
running the real fused pipeline SIGKILLs itself after the Nth committed
journal unit of the targeted stage — mid-sort round, mid-markdup,
mid-write part — and the parent resumes from the journal and compares
bytes against the uninterrupted serial oracle.
"""
import dataclasses
import os
import random
import re
import signal
import subprocess
import sys
import tempfile
import textwrap

import pytest

from hadoop_bam_tpu.api.dataset import open_bam
from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.jobs import JobJournal, journal_path_for
from hadoop_bam_tpu.parallel.mesh import make_mesh
from hadoop_bam_tpu.prep import markdup_bam_mesh, markdup_bam_oracle
from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError
from hadoop_bam_tpu.utils.metrics import MetricsContext

pytestmark = pytest.mark.prep

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))

NOSYNC = dataclasses.replace(DEFAULT_CONFIG, journal_fsync=False)

# @RG lines: rg0/rg2 share a library, rg1 is its own — so library_from=
# "rg" groups differently from "none"; records tagged rg3 (absent from
# the header) and untagged records both take the "unknown library" slot
_HDR_TEXT = (
    "@HD\tVN:1.6\tSO:coordinate\n"
    "@SQ\tSN:chr1\tLN:1000000\n"
    "@SQ\tSN:chr2\tLN:2000000\n"
    "@RG\tID:rg0\tLB:libA\tSM:s0\n"
    "@RG\tID:rg1\tLB:libB\tSM:s0\n"
    "@RG\tID:rg2\tLB:libA\tSM:s0\n")

# leading/trailing S and H clips move the unclipped 5' end on both
# strands; D/N/I vary the reference span without changing it
_CIGARS = ["30M", "5S25M", "25M5S", "3H27M", "27M3H", "4S22M4H",
           "10M2D8M3N12M", "16M2I12M"]
# mapped fwd/rev, proper pairs both orientations, unmapped, mate-
# unmapped primaries, secondary (both strands), supplementary
_FLAGS = [0, 16, 99, 147, 83, 163, 4, 256, 272, 2048, 73, 137]


def _qlen(cigar: str) -> int:
    return sum(int(n) for n, op in re.findall(r"(\d+)([MIDNSHP=X])",
                                              cigar) if op in "MIS=X")


def fuzz_header() -> SAMHeader:
    return SAMHeader(text=_HDR_TEXT, ref_names=["chr1", "chr2"],
                     ref_lengths=[1_000_000, 2_000_000])


def make_fuzz_records(header, n, seed):
    """Duplicate-heavy fuzz corpus: positions drawn from a small grid so
    signature collisions are frequent, quals drawn from four flat levels
    so score TIES are frequent (the gidx tie-break must decide)."""
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        flag = rng.choice(_FLAGS)
        cigar = rng.choice(_CIGARS)
        rid = rng.randrange(2)
        pos = 1 + rng.randrange(30) * 53
        q = rng.choice((10, 20, 30, 40))
        tags = []
        if rng.random() < 0.8:
            tags.append(("RG", "Z", f"rg{rng.randrange(4)}"))
        if flag & 0x4:
            # half placed-unmapped (coordinate kept), half unplaced
            placed = rng.random() < 0.5
            rname = header.ref_names[rid] if placed else "*"
            p, cg, l = (pos if placed else 0), "*", 20
        else:
            rname, p, cg = header.ref_names[rid], pos, cigar
            l = _qlen(cigar)
        qual = "*" if rng.random() < 0.1 else chr(33 + q) * l
        recs.append(SamRecord(
            qname=f"q{i:05d}", flag=flag, rname=rname, pos=p,
            mapq=rng.randrange(61), cigar=cg,
            rnext=("=" if flag & 0x1 else "*"),
            pnext=(1 + rng.randrange(20) * 31 if flag & 0x1 else 0),
            tlen=0, seq="A" * l, qual=qual, tags=tags))
    rng.shuffle(recs)
    return recs


@pytest.fixture(scope="module")
def prep_fixture(tmp_path_factory):
    """The fuzz BAM plus serial-oracle outputs for all option pairs."""
    d = tmp_path_factory.mktemp("prep")
    header = fuzz_header()
    recs = make_fuzz_records(header, 400, seed=7)
    src = str(d / "in.bam")
    with BamWriter(src, header) as w:
        for r in recs:
            w.write_sam_record(r)
    oracle = {}
    for rm in (False, True):
        for lf in ("none", "rg"):
            out = str(d / f"oracle_{int(rm)}_{lf}.bam")
            n = markdup_bam_oracle(src, out, config=DEFAULT_CONFIG,
                                   remove_duplicates=rm,
                                   library_from=lf)
            oracle[(rm, lf)] = {"path": out,
                                "bytes": open(out, "rb").read(),
                                "records": n}
    return {"dir": d, "header": header, "src": src,
            "n_input": len(recs), "oracle": oracle}


def _read_flags(path):
    ds = open_bam(path)
    return [SamRecord.from_line(b.to_sam_line(i)).flag
            for b in ds.batches() for i in range(len(b))]


# ---------------------------------------------------------------------------
# oracle sanity: the corpus actually exercises the policy
# ---------------------------------------------------------------------------

def test_fuzz_corpus_marks_and_removes_duplicates(prep_fixture):
    marked = prep_fixture["oracle"][(False, "none")]
    removed = prep_fixture["oracle"][(True, "none")]
    flags = _read_flags(marked["path"])
    n_dup = sum(1 for f in flags if f & 0x400)
    assert n_dup > 0                          # collisions happened
    assert marked["records"] == prep_fixture["n_input"]
    assert removed["records"] == marked["records"] - n_dup
    # ineligible classes are never marked
    assert not any(f & 0x400 for f in flags if f & 0x904)
    # the removal arm writes no 0x400 flag at all
    assert not any(f & 0x400 for f in _read_flags(removed["path"]))
    # rg mode groups by library, so it must differ from flat mode here
    rg = prep_fixture["oracle"][(False, "rg")]
    assert rg["bytes"] != marked["bytes"]


# ---------------------------------------------------------------------------
# mesh vs oracle byte identity (the tentpole acceptance bar)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k,rm,lf,rr", [
    (2, False, "none", 64),
    (4, False, "rg", 90),
    (4, True, "none", 1000),
    (8, True, "rg", 64),
    (8, False, "none", 150),
    (2, True, "rg", 150),
])
def test_mesh_markdup_matches_oracle(tmp_path, prep_fixture,
                                     k, rm, lf, rr):
    out = str(tmp_path / "out.bam")
    n = markdup_bam_mesh(prep_fixture["src"], out, mesh=make_mesh((k,)),
                         remove_duplicates=rm, library_from=lf,
                         round_records=rr)
    want = prep_fixture["oracle"][(rm, lf)]
    assert n == want["records"]
    assert open(out, "rb").read() == want["bytes"]
    assert not os.path.isdir(out + ".mkdup-spill")


def test_tie_breaks_deterministic_across_shards_and_rounds(
        tmp_path, prep_fixture):
    """Score ties are broken by global record index, which must not
    depend on how the mesh shards or how rounds split the input: every
    (mesh size, round size) lands on the SAME oracle bytes."""
    want = prep_fixture["oracle"][(False, "none")]["bytes"]
    for k, rr in ((2, 47), (4, 128), (8, 400)):
        out = str(tmp_path / f"out_{k}_{rr}.bam")
        markdup_bam_mesh(prep_fixture["src"], out, mesh=make_mesh((k,)),
                         round_records=rr)
        assert open(out, "rb").read() == want, (k, rr)


# ---------------------------------------------------------------------------
# corruption + misconfiguration taxonomy
# ---------------------------------------------------------------------------

def test_byte_flip_same_error_class_both_paths(tmp_path, prep_fixture):
    raw = bytearray(open(prep_fixture["src"], "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    bad = str(tmp_path / "bad.bam")
    with open(bad, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(CorruptDataError):
        markdup_bam_oracle(bad, str(tmp_path / "o.bam"),
                           config=DEFAULT_CONFIG)
    with pytest.raises(CorruptDataError):
        markdup_bam_mesh(bad, str(tmp_path / "m.bam"),
                         mesh=make_mesh((2,)))


def test_misconfiguration_is_plan_error(tmp_path, prep_fixture):
    with pytest.raises(PlanError):
        markdup_bam_oracle(prep_fixture["src"],
                           str(tmp_path / "o.bam"),
                           config=DEFAULT_CONFIG, library_from="lb")
    with pytest.raises(PlanError):
        markdup_bam_mesh(prep_fixture["src"], str(tmp_path / "m.bam"),
                         mesh=make_mesh((2,)), library_from="lb")
    with pytest.raises(PlanError):
        markdup_bam_mesh(prep_fixture["src"], str(tmp_path / "m.bam"),
                         mesh=make_mesh((2,)), round_records=0)


# ---------------------------------------------------------------------------
# SIGKILL at each fused-stage boundary -> resume, byte-identical
# ---------------------------------------------------------------------------

_MKDUP_CHILD = """
    import os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import signal
    from hadoop_bam_tpu.jobs import JobJournal
    kill_kind, kill_after = sys.argv[1], int(sys.argv[2])
    src, out, jp, rr = (sys.argv[3], sys.argv[4], sys.argv[5],
                        int(sys.argv[6]))
    orig = JobJournal.unit_done
    n = [0]
    def patched(self, kind, key, **kw):
        orig(self, kind, key, **kw)
        if kind == kill_kind:
            n[0] += 1
            if n[0] >= kill_after:
                os.kill(os.getpid(), signal.SIGKILL)
    JobJournal.unit_done = patched
    import dataclasses
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.prep import markdup_bam_mesh
    cfg = dataclasses.replace(DEFAULT_CONFIG, journal_fsync=False)
    markdup_bam_mesh(src, out, round_records=rr, journal_path=jp,
                     config=cfg)
    raise SystemExit("unreachable: child must have been killed")
"""


def _child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


def _run_child(script_body, *args, timeout=240):
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(textwrap.dedent(script_body))
        script = f.name
    try:
        return subprocess.run(
            [sys.executable, script, *map(str, args)],
            env=_child_env(), timeout=timeout, capture_output=True,
            text=True)
    finally:
        os.unlink(script)


@pytest.mark.parametrize("kill_kind,kill_after", [
    ("round", 2),        # mid-sort: some rounds spilled, some not
    ("markdup", 1),      # after the duplicate bitmap, before any part
    ("shard", 3),        # mid-write: 3 of 8 parts committed
])
def test_sigkill_each_stage_resumes_byte_identical(
        tmp_path, prep_fixture, kill_kind, kill_after):
    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    # spans round up to a multiple of n_dev (8): 400 records at 15 per
    # round plans 32 spans -> 4 sort rounds, so a kill after round 2
    # leaves real work on both sides of the boundary
    rr = 15
    r = _run_child(_MKDUP_CHILD, kill_kind, kill_after,
                   prep_fixture["src"], out, jp, rr)
    assert r.returncode == -signal.SIGKILL, (r.returncode,
                                             r.stderr[-2000:])
    st = JobJournal.replay(jp)
    committed = {k: len([u for (kk, _), u in st.units.items()
                         if kk == k])
                 for k in ("round", "markdup", "shard")}
    assert committed[kill_kind] == kill_after
    assert os.path.isdir(out + ".mkdup-spill")  # survived the kill

    with MetricsContext() as m:
        n = markdup_bam_mesh(prep_fixture["src"], out,
                             round_records=rr, journal_path=jp,
                             config=NOSYNC)
    snap = m.snapshot()
    want = prep_fixture["oracle"][(False, "none")]
    assert n == want["records"]
    assert open(out, "rb").read() == want["bytes"]
    c = snap["counters"]
    # every unit the child committed is verified and skipped, never
    # re-run: the journal grains are the resume contract
    assert c.get("jobs.rounds_skipped", 0) == committed["round"]
    if committed["round"]:
        assert c.get("jobs.spans_skipped", 0) > 0
    assert c.get("jobs.markdup_skipped", 0) == committed["markdup"]
    assert c.get("jobs.shards_skipped", 0) == committed["shard"]
    ev = JobJournal.replay(jp).last_event("resume_plan")
    assert ev is not None \
        and ev["rounds_skipped"] == committed["round"]
    assert not os.path.isdir(out + ".mkdup-spill")  # clean on success


def test_completed_job_is_a_verified_noop(tmp_path, prep_fixture):
    out = str(tmp_path / "out.bam")
    jp = journal_path_for(out)
    n = markdup_bam_mesh(prep_fixture["src"], out, round_records=90,
                         journal_path=jp, config=NOSYNC)
    want = prep_fixture["oracle"][(False, "none")]
    assert n == want["records"]
    with MetricsContext() as m:
        n2 = markdup_bam_mesh(prep_fixture["src"], out,
                              round_records=90, journal_path=jp,
                              config=NOSYNC)
    assert n2 == n
    assert m.snapshot()["counters"].get("jobs.jobs_skipped") == 1
    assert open(out, "rb").read() == want["bytes"]


# ---------------------------------------------------------------------------
# cold QueryEngine open — no rescan (the fused-write acceptance bar)
# ---------------------------------------------------------------------------

def test_mkdup_output_cold_queries_without_rescan(tmp_path,
                                                  prep_fixture,
                                                  monkeypatch):
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest
    import hadoop_bam_tpu.split.bai as bai_mod

    out = str(tmp_path / "cold.bam")
    markdup_bam_mesh(prep_fixture["src"], out, mesh=make_mesh((4,)),
                     round_records=120)
    oracle_path = prep_fixture["oracle"][(False, "none")]["path"]

    def no_rescan(*a, **kw):
        raise AssertionError("build_bai called — the co-written "
                             "sidecar should have served the query")
    monkeypatch.setattr(bai_mod, "build_bai", no_rescan)

    regions = ["chr1:1-5000", "chr2:1-2000", "chr1:999000-1000000"]
    res_new = QueryEngine().query_records(
        [QueryRequest(out, r) for r in regions])
    res_old = QueryEngine().query_records(
        [QueryRequest(oracle_path, r) for r in regions])
    for a, b in zip(res_new, res_old):
        assert [r.to_line() for r in a.records] \
            == [r.to_line() for r in b.records]
    assert sum(len(r.records) for r in res_new) > 0


# ---------------------------------------------------------------------------
# CLI: hbam mkdup / hbam explain mkdup
# ---------------------------------------------------------------------------

def test_cli_mkdup_matches_oracle(tmp_path, prep_fixture, capsys):
    from hadoop_bam_tpu.tools.cli import main

    out = str(tmp_path / "cli.bam")
    main(["mkdup", prep_fixture["src"], out,
          "--library-from", "rg", "--run-records", "150"])
    got = capsys.readouterr().out
    assert got.startswith("wrote ") and "duplicates marked" in got
    assert open(out, "rb").read() \
        == prep_fixture["oracle"][(False, "rg")]["bytes"]
    assert os.path.exists(out + ".bai")       # sidecars co-written

    out2 = str(tmp_path / "cli_rm.bam")
    main(["mkdup", prep_fixture["src"], out2, "--remove-duplicates",
          "--run-records", "150"])
    assert "duplicates removed" in capsys.readouterr().out
    assert open(out2, "rb").read() \
        == prep_fixture["oracle"][(True, "none")]["bytes"]


def test_cli_explain_mkdup(prep_fixture, capsys):
    from hadoop_bam_tpu.tools.cli import main

    main(["explain", "mkdup", prep_fixture["src"]])
    got = capsys.readouterr().out
    assert "markdup" in got and "sort_exchange" in got \
        and "flag_patch_write" in got
