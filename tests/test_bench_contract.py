"""bench.py emission contract: the FINAL stdout line must stay under
FINAL_LINE_BUDGET so the driver's 2000-char tail always parses it
(VERDICT r5 next-round #1 — the r5 line grew to 2.2 KB and parsed as
null)."""
import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_under_test",
                                                  os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fill_state(bench, n_notes=6):
    rows = [
        ("bam_decode_records_per_sec_per_chip", 907987.4, "records/s", 2.87),
        ("bgzf_inflate_gbps", 0.305, "GB/s", 3.9),
        ("split_guess_p50_ms_per_boundary", 5.1, "ms", 1.6),
        ("faulted_flagstat_records_per_sec", 650123.9, "records/s", 0.93),
        ("cram_tensor_records_per_sec", 432087.1, "records/s", 6.7),
        ("vcf_variants_per_sec", 507001.2, "variants/s", 1.5),
        ("bcf_variants_per_sec", 612345.7, "variants/s", 1.21),
        ("region_query_queries_per_sec", 41.7, "queries/s", 2.4),
        ("region_serve_queries_per_sec", 200.3, "queries/s", 9.5),
        ("faulted_serve_queries_per_sec", 151.2, "queries/s", 0.81),
        ("obs_overhead_pct", 1.3, "%", None),
        ("plan_overhead_pct", 0.6, "%", None),
        ("cohort_join_variants_per_sec", 48211.5, "variants/s", None),
        ("device_inflate_records_per_sec", 93211.4, "records/s", 0.42),
        ("device_plane_families_records_per_sec", 141002.3, "records/s",
         0.48),
        ("fastq_reads_per_sec", 188001.0, "reads/s", 2.37),
        ("bam_write_records_per_sec", 301222.5, "records/s", 2.1),
        ("deflate_tokenize_gbps", 0.41, "GB/s", 0.8),
        ("coverage_records_per_sec", 375000.2, "records/s", 1.25),
        ("sort_records_per_sec_mesh", 47368.1, "records/s", 6.6),
        ("resume_overhead_pct", 1.4, "%", None),
        ("sort_write_mb_per_sec", 38.52, "MB/s", 0.97),
        ("mkdup_mb_per_sec", 31.04, "MB/s", None),
        ("seq_pallas_kernel_bases_per_sec", 1.9e9, "bases/s", 12.2),
        ("cigar_pileup_kernel_records_per_sec", 8.1e6, "records/s", None),
        ("mesh_sort_device_sort_keys_per_sec", 5.4e7, "keys/s", None),
    ]
    comps = []
    for m, v, u, vs in rows:
        row = {"metric": m, "value": v, "unit": u,
               "note": "x" * 120}          # progress lines carry detail
        if vs is not None:
            row["vs_baseline"] = vs
        if m == "vcf_variants_per_sec":
            # per-stage wall spans ride the FULL row only; the compact
            # line keeps just the numeric value
            row["vcf_stage_seconds"] = {
                "inflate_wall": 0.21, "tokenize_wall": 0.33,
                "dosage_pack_wall": 0.12, "dispatch_wall": 0.18}
        if m == "region_query_queries_per_sec":
            row.update(cold_queries_per_sec=17.1, cache_hit_rate=0.93,
                       regions=250, records_matched=2_551_000,
                       latency_p50_ms=19.2, latency_p99_ms=88.4)
        if m == "region_serve_queries_per_sec":
            # the r11 serving row: tile-cache bypass + prefetch
            # usefulness + client saturation ride the FULL row only
            row.update(cold_queries_per_sec=23.6, tile_hit_rate=1.0,
                       zipf_first_pass_hit_rate=0.9356,
                       prefetch_hit_rate=0.28, prefetch_issued=168,
                       latency_p50_ms=4.6, latency_p99_ms=9.3,
                       cold_p50_ms=44.2, warm_host_decode_share=0.0,
                       clients_qps=[[1, 196.0], [8, 188.9]],
                       regions=250, distinct_windows=51,
                       # the r19 fleet arm: 1->2 endpoint q/s, the
                       # cross-replica tile hit rate from the fleet
                       # counters, and the client-observed SIGKILL
                       # failover p99 — full row only
                       fleet_replicas=2,
                       fleet_qps=[[1, 41.2], [2, 66.9]],
                       cross_replica_tile_hit_rate=0.44,
                       fleet_kill_p99_ms=61.3,
                       fleet_failed_requests=0)
        if m == "faulted_serve_queries_per_sec":
            # the r14 degrade-and-heal row: shed accounting, degraded vs
            # clean p50, ladder heal time and the reproducibility seed —
            # full row only; the compact line keeps the number
            row.update(shed_rate=0.175, served=66, shed=14,
                       degraded_p50_ms=6.1, warm_chaos_p50_ms=5.2,
                       clean_p50_ms=4.8, ladder_heal_s=0.41,
                       chaos_seed=1234)
        if m == "sort_write_mb_per_sec":
            # the write-path row: parallel vs serial arm, deflate wall
            # share, byte identity — full row only; the contract pins
            # row SHAPE (the speedup is host-dependent on the 1-core
            # bench machine), never a ratio
            row.update(serial_mb_per_sec=39.7, write_deflate_share=0.41,
                       records=100000, output_bytes=9_100_000,
                       byte_identical_to_serial=True)
        if m == "mkdup_mb_per_sec":
            # the r22 fused preprocessing row: fused vs staged arms,
            # per-stage wall shares, oracle byte identity — full row
            # only; the compact line keeps the fused MB/s
            row.update(vs_staged=1.12, staged_mb_per_sec=27.7,
                       stage_wall_shares={"sort": 0.58, "markdup": 0.07,
                                          "write": 0.31},
                       records=100000, duplicates_marked=1834,
                       output_bytes=9_100_000,
                       byte_identical_to_oracle=True)
        if m == "obs_overhead_pct":
            row.update(instrumented_s=0.1301, null_s=0.1284)
        if m == "plan_overhead_pct":
            # the r18 plan-layer row: both arm walls + the value-identity
            # pin ride the FULL row only; the compact line keeps the
            # overhead number
            row.update(plan_s=0.1310, inline_s=0.1302,
                       identical_to_inline=True)
        if m == "resume_overhead_pct":
            # the r16 crash-safe jobs row: journal-on vs journal-off
            # walls, and the SIGKILL-resume arm's journal-verified
            # skipped-work fraction + byte identity — full row only;
            # the compact line keeps the overhead number
            row.update(journaled_wall_s=2.113, plain_wall_s=2.084,
                       round_records=3125, records=100000,
                       byte_identical_to_plain=True,
                       resume_records=100000, resume_wall_s=1.61,
                       resume_rounds_skipped=1,
                       resume_fraction_skipped=0.25,
                       resume_byte_identical=True)
        if m == "cohort_join_variants_per_sec":
            # the r15 cohort-plane row: k-way join+pack rate, per-stage
            # wall shares, warm vs cold cohort-slice serving — full row
            # only; the compact line keeps the number
            row.update(samples=64, variants=91234,
                       stage_wall_shares={"join": 0.41, "feed": 0.22,
                                          "dispatch": 0.09},
                       cold_slice_p50_ms=310.2, warm_slice_p50_ms=3.1,
                       warm_host_decode_share=0.0)
        if m == "device_plane_families_records_per_sec":
            # r21: the three new device-plane families (payload seq_stats,
            # BCF variant, cold serve tiles) — per-arm host-oracle
            # identity and the ~0 host-decode share ride the FULL row
            # only; the compact line keeps the payload-arm rate
            row.update(
                seq_stats={"device_records_per_sec": 141002.3,
                           "host_records_per_sec": 293755.1,
                           "host_decode_share": 0.021,
                           "identical_to_host": True,
                           "records": 24000, "spans": 12},
                variant={"device_variants_per_sec": 88211.0,
                         "host_variants_per_sec": 152003.4,
                         "host_decode_share": 0.0,
                         "identical_to_host": True, "variants": 50000},
                serve_cold={"device_queries_per_sec": 21.4,
                            "host_queries_per_sec": 23.8,
                            "host_decode_share": 0.0,
                            "device_tile_builds": 51,
                            "identical_counts": True, "regions": 51})
        if m == "device_inflate_records_per_sec":
            # r11: the decode-plane wall breakdown (tokenize vs on-mesh
            # resolve and their overlap) rides the FULL row only
            row.update(
                fused_records_per_sec=221931.0, records=24000, spans=12,
                decode_plane_walls={
                    "device": {"tokenize_wall_s": 0.083,
                               "device_resolve_wall_s": 0.211,
                               "overlap_s": 0.064,
                               "overlap_efficiency": 0.77,
                               "nonoverlap_inflate_share": 0.071},
                    "fused": {"fused_decode_wall_s": 0.0718,
                              "dispatch_wall_s": 0.0441,
                              "overlap_s": 0.011,
                              "overlap_efficiency": 0.25,
                              "nonoverlap_inflate_share": 0.56}})
        comps.append(row)
    comps.append({"metric": "broken_row", "error": "RuntimeError: boom"})
    comps.append({"metric": "late_row", "skipped": "deadline"})
    bench._STATE.update({
        "platform": "cpu",
        "headline": comps[0],
        "components": comps,
        "notes": [f"note {i}: " + "y" * 90 for i in range(n_notes)],
        "scaling": {
            "host_cores": 1,
            "note": "z" * 200,
            "devices": [
                {"n_devices": n, "jax_devices": n, "file_records": 100000,
                 "flagstat_records_per_sec": 862000.0 / n,
                 "flagstat_stage_seconds_per_run": {"pipeline.inflate": 0.2},
                 "flagstat_wall_seconds_per_run":
                     {"pipeline.feed_wall": 0.31,
                      "pipeline.dispatch_wall": 0.24,
                      "pipeline.host_decode_wall": 0.28},
                 "flagstat_overlap_efficiency": 0.774,
                 "flagstat_dispatch_bytes": 3301400,
                 "seq_stats_records_per_sec": 250000.0 / n,
                 "seq_stats_overlap_efficiency": 0.61,
                 "seq_stats_dispatch_bytes": 76600000,
                 "coverage_records_per_sec": 400000.0 / n}
                for n in (1, 8, 2, 4)],
        },
    })


def test_final_line_fits_budget_and_parses(bench):
    _fill_state(bench)
    line = json.dumps(bench._compact_snapshot(bench._snapshot("ok")))
    assert len(line) <= bench.FINAL_LINE_BUDGET
    out = json.loads(line)
    # driver contract keys
    assert out["metric"] == "bam_decode_records_per_sec_per_chip"
    assert out["value"] == 907987.4
    assert out["unit"] == "records/s"
    assert out["vs_baseline"] == 2.87
    # compressed matrix: name -> value, errors/skips as strings
    assert out["components"]["bcf_variants_per_sec"] == 612345.7
    assert out["components"]["sort_write_mb_per_sec"] == 38.52
    assert out["components"]["broken_row"] == "error"
    assert out["components"]["late_row"] == "skipped"
    # r9: the obs overhead row rides the compact matrix, and the warm
    # region-query [p50_ms, p99_ms] pair rides as the latency component
    assert out["components"]["obs_overhead_pct"] == 1.3
    assert out["latency"] == [19.2, 88.4]
    # scaling compressed to [n_dev, flagstat rec/s] pairs, sorted
    assert out["scaling"][0] == [1, 862000.0]
    assert [r[0] for r in out["scaling"]] == [1, 2, 4, 8]


def test_final_line_budget_survives_pathological_notes(bench):
    _fill_state(bench, n_notes=40)
    line = json.dumps(bench._compact_snapshot(bench._snapshot("timeout")))
    assert len(line) <= bench.FINAL_LINE_BUDGET
    assert json.loads(line)["status"] == "timeout"


def test_full_snapshot_keeps_detail_on_progress_lines(bench):
    _fill_state(bench)
    full = bench._snapshot("partial")
    assert any("note" in c for c in full["components"])
    assert "flagstat_stage_seconds_per_run" in \
        full["scaling"]["devices"][0]
    by_metric = {c.get("metric"): c for c in full["components"]}
    # r9: VCF per-stage walls + region-query cache detail stay on the
    # progress lines (the compact line keeps only the numeric values)
    assert set(by_metric["vcf_variants_per_sec"]["vcf_stage_seconds"]) \
        == {"inflate_wall", "tokenize_wall", "dosage_pack_wall",
            "dispatch_wall"}
    rq = by_metric["region_query_queries_per_sec"]
    assert 0.0 <= rq["cache_hit_rate"] <= 1.0
    assert rq["regions"] >= 200
    # r9: warm-pass latency percentiles from the query.latency_s
    # histogram ride the full region-query row
    assert rq["latency_p99_ms"] >= rq["latency_p50_ms"] > 0
    # r11: the serving row pins the tile-cache bypass (hit rate, ~zero
    # warm host-decode share), prefetch usefulness, and the 1->8 client
    # saturation pairs — full row only, compact line keeps the number
    rs = by_metric["region_serve_queries_per_sec"]
    assert 0.0 <= rs["tile_hit_rate"] <= 1.0
    assert 0.0 <= rs["prefetch_hit_rate"] <= 1.0
    assert rs["warm_host_decode_share"] < 0.1
    assert rs["cold_p50_ms"] > rs["latency_p50_ms"] > 0
    assert [c for c, _q in rs["clients_qps"]] == [1, 8]
    assert all(q > 0 for _c, q in rs["clients_qps"])
    # r19: the fleet arm pins the 1->2 endpoint q/s pairs, a bounded
    # cross-replica tile hit rate, the client-observed kill-failover
    # p99 and ZERO failed requests through the SIGKILL — shape only
    # (rates are host-dependent), compact line keeps the number
    assert rs["fleet_replicas"] == 2
    assert [n for n, _q in rs["fleet_qps"]] == [1, 2]
    assert all(q > 0 for _n, q in rs["fleet_qps"])
    assert 0.0 <= rs["cross_replica_tile_hit_rate"] <= 1.0
    assert rs["fleet_kill_p99_ms"] > 0
    assert rs["fleet_failed_requests"] == 0
    ov = by_metric["obs_overhead_pct"]
    assert ov["instrumented_s"] > 0 and ov["null_s"] > 0
    # r12: the device decode plane row pins the tokenize / device-resolve
    # wall breakdown and overlap accounting — full row only, the compact
    # line keeps just the rate
    # the write-path row pins the arm comparison fields and byte
    # identity — shape only, no ratio (host-dependent on 1 core)
    # r14: the degrade-and-heal serving row pins shed accounting (rate
    # consistent with the counts), the degraded-vs-clean p50 pair, the
    # ladder heal time and the chaos seed — shape only, no host ratio
    fs = by_metric["faulted_serve_queries_per_sec"]
    assert 0.0 <= fs["shed_rate"] <= 1.0
    assert fs["shed_rate"] == pytest.approx(
        fs["shed"] / (fs["served"] + fs["shed"]), abs=1e-3)
    assert fs["degraded_p50_ms"] > 0 and fs["clean_p50_ms"] > 0
    assert fs["warm_chaos_p50_ms"] > 0
    assert fs["ladder_heal_s"] > 0
    assert isinstance(fs["chaos_seed"], int)
    # r15: the cohort-plane row pins the join's per-stage wall shares,
    # the cold-vs-warm slice pair and the warm host-decode bypass —
    # shape only (the rate is host-dependent), compact line keeps the
    # number
    cj = by_metric["cohort_join_variants_per_sec"]
    assert cj["samples"] > 1 and cj["variants"] > 0
    assert set(cj["stage_wall_shares"]) == {"join", "feed", "dispatch"}
    assert all(0.0 <= v <= 1.0 for v in cj["stage_wall_shares"].values())
    assert cj["cold_slice_p50_ms"] > cj["warm_slice_p50_ms"] > 0
    assert cj["warm_host_decode_share"] < 0.1
    sw = by_metric["sort_write_mb_per_sec"]
    assert sw["serial_mb_per_sec"] > 0
    assert 0.0 <= sw["write_deflate_share"] <= 1.0
    assert sw["byte_identical_to_serial"] is True
    assert sw["records"] > 0 and sw["output_bytes"] > 0
    # r22: the fused preprocessing row pins the fused-vs-staged arm
    # pair, per-stage wall shares over the three prep spans, and byte
    # identity against the serial markdup oracle — shape only (the
    # ratio is host-dependent), compact line keeps the fused MB/s
    mk = by_metric["mkdup_mb_per_sec"]
    assert mk["staged_mb_per_sec"] > 0
    assert set(mk["stage_wall_shares"]) == {"sort", "markdup", "write"}
    assert all(0.0 <= v <= 1.0
               for v in mk["stage_wall_shares"].values())
    assert mk["byte_identical_to_oracle"] is True
    assert mk["records"] > 0 and mk["output_bytes"] > 0
    assert mk["duplicates_marked"] >= 0
    # r21: the device-plane families row pins per-arm host-oracle
    # identity and the ~0 host-decode wall share on every device arm —
    # full row only, the compact line keeps the payload-arm rate
    dp = by_metric["device_plane_families_records_per_sec"]
    for arm in ("seq_stats", "variant", "serve_cold"):
        assert 0.0 <= dp[arm]["host_decode_share"] < 0.1
    assert dp["seq_stats"]["identical_to_host"] is True
    assert dp["seq_stats"]["records"] > 0 and dp["seq_stats"]["spans"] > 0
    assert dp["variant"]["identical_to_host"] is True
    assert dp["variant"]["variants"] > 0
    assert dp["serve_cold"]["identical_counts"] is True
    assert dp["serve_cold"]["device_tile_builds"] > 0
    assert dp["serve_cold"]["regions"] > 0
    di = by_metric["device_inflate_records_per_sec"]
    planes = di["decode_plane_walls"]
    assert set(planes) == {"device", "fused"}
    dv = planes["device"]
    assert dv["tokenize_wall_s"] > 0 and dv["device_resolve_wall_s"] > 0
    assert 0.0 <= dv["overlap_efficiency"] <= 1.0
    assert 0.0 <= dv["nonoverlap_inflate_share"] <= 1.0
    assert 0.0 <= planes["fused"]["nonoverlap_inflate_share"] <= 1.0
    assert di["fused_records_per_sec"] > 0 and di["spans"] > 0
    line = json.dumps(bench._compact_snapshot(full))
    assert len(line) <= bench.FINAL_LINE_BUDGET
    out = json.loads(line)
    assert out["components"]["region_query_queries_per_sec"] == 41.7
    assert out["components"]["device_inflate_records_per_sec"] == 93211.4
    assert out["components"][
        "device_plane_families_records_per_sec"] == 141002.3


def test_latency_component_dropped_before_components(bench):
    """Budget pressure sheds notes, then latency, then scaling —
    components (the driver-parsed matrix) go last."""
    _fill_state(bench, n_notes=0)
    full = bench._snapshot("ok")
    out = bench._compact_snapshot(full)
    assert "latency" in out
    # a region-query row without the percentile fields (old artifacts,
    # error rows) must simply omit the component, not crash
    for c in full["components"]:
        c.pop("latency_p50_ms", None)
    out2 = bench._compact_snapshot(full)
    assert "latency" not in out2
    assert len(json.dumps(out2)) <= bench.FINAL_LINE_BUDGET


def test_scaling_rows_pin_feed_overlap_fields(bench):
    """The r8 feed-pipeline fields ride the full scaling rows (and the
    compact final line still fits the budget with them aboard): per
    driver, ``*_overlap_efficiency`` (device-busy wall / feed wall from
    Metrics.wall_timer spans) and ``*_dispatch_bytes``."""
    _fill_state(bench)
    full = bench._snapshot("ok")
    for row in full["scaling"]["devices"]:
        for prefix in ("flagstat", "seq_stats"):
            assert f"{prefix}_overlap_efficiency" in row
            assert 0.0 <= row[f"{prefix}_overlap_efficiency"] <= 1.0
            assert row[f"{prefix}_dispatch_bytes"] > 0
        assert "pipeline.feed_wall" in row["flagstat_wall_seconds_per_run"]
    line = json.dumps(bench._compact_snapshot(full))
    assert len(line) <= bench.FINAL_LINE_BUDGET


def test_resume_row_shape_pinned(bench):
    """The r16 crash-safe jobs row: the full row carries both arms
    (journal-on/off walls, the resume arm's fraction-of-work-skipped
    and byte identity); the compact final line keeps only the overhead
    number and still fits the budget."""
    _fill_state(bench)
    full = bench._snapshot("ok")
    row = next(c for c in full["components"]
               if c["metric"] == "resume_overhead_pct")
    assert row["unit"] == "%"
    assert row["journaled_wall_s"] > 0 and row["plain_wall_s"] > 0
    assert row["byte_identical_to_plain"] is True
    assert row["resume_byte_identical"] is True
    assert 0.0 < row["resume_fraction_skipped"] < 1.0
    assert row["resume_rounds_skipped"] >= 1
    out = bench._compact_snapshot(full)
    assert out["components"]["resume_overhead_pct"] == 1.4
    assert len(json.dumps(out)) <= bench.FINAL_LINE_BUDGET


def test_plan_overhead_row_shape_pinned(bench):
    """The r18 plan/execute-layer row: the full row carries both arm
    walls and the identity pin (flagstat via the executor must be
    value-identical to the inline mesh-feed impl); the compact final
    line keeps only the overhead number and still fits the budget."""
    _fill_state(bench)
    full = bench._snapshot("ok")
    row = next(c for c in full["components"]
               if c["metric"] == "plan_overhead_pct")
    assert row["unit"] == "%"
    assert row["plan_s"] > 0 and row["inline_s"] > 0
    assert row["identical_to_inline"] is True
    out = bench._compact_snapshot(full)
    assert out["components"]["plan_overhead_pct"] == 0.6
    assert len(json.dumps(out)) <= bench.FINAL_LINE_BUDGET


def test_stale_sidecars_healed_fresh_kept(bench, tmp_path):
    """The stale-sidecar auto-heal (the recurring 'truncated BGZF
    header' scaling failure): sidecars OLDER than their fixture are
    removed, fresh ones are kept, and the purge flavor removes
    everything."""
    bam = tmp_path / "f.bam"
    bam.write_bytes(b"x" * 10)
    stale = tmp_path / "f.bam.bai"
    stale.write_bytes(b"old")
    os.utime(stale, ns=(1, 1))                 # older than the fixture
    fresh = tmp_path / "f.bam.sbi"
    fresh.write_bytes(b"new")
    os.utime(fresh, ns=(2**62, 2**62))         # newer than the fixture
    removed = bench._heal_stale_sidecars(str(bam))
    assert removed == ["f.bam.bai"]
    assert not stale.exists() and fresh.exists()
    # idempotent + missing fixture is a no-op
    assert bench._heal_stale_sidecars(str(bam)) == []
    assert bench._heal_stale_sidecars(str(tmp_path / "absent.bam")) == []
    assert bench._purge_sidecars(str(bam)) == ["f.bam.sbi"]
    assert not fresh.exists()


def test_snapshot_mutation_not_duplicated_by_compact(bench):
    """_compact_snapshot must consume an existing snapshot dict —
    _snapshot appends a note when the headline is missing, and the old
    double-call duplicated it in the final artifact."""
    _fill_state(bench)
    bench._STATE["headline"] = None
    full = bench._snapshot("ok")
    out = bench._compact_snapshot(full)
    assert out["status"] == "partial"          # downgraded, not "ok"
    note = "headline measurement failed; see components"
    assert bench._STATE["notes"].count(note) == 1
