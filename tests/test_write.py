"""Write-path tests (``pytest -m write``): ParallelBGZFWriter byte
identity vs the serial oracle under randomized chunking and worker
counts, index-during-write sidecars, atomic publication, the sharded
writer protocol, and the write→query round trip — sorted output written
by the new path opened COLD by the query engine using only its
co-written sidecars, byte-identical to querying a serially-written
oracle file.
"""
import concurrent.futures as cf
import dataclasses
import io
import os
import random

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.write import (
    ParallelBGZFWriter, ShardedFileWriter, resolve_index_kinds,
    write_bam_records, write_bam_shards_concat, write_bcf_records,
)

from fixtures import make_header, make_records

pytestmark = pytest.mark.write


def _coord_sorted(header, recs):
    def key(r):
        rid = (header.ref_names.index(r.rname) if r.rname != "*"
               else 1 << 30)
        return (rid, r.pos)
    return sorted(recs, key=key)


def _record_chunks(header, recs, n_chunks=4):
    """(data, offsets) chunks of encoded records, file order."""
    blobs = [r.to_bam_bytes(header) for r in recs]
    per = max(1, len(blobs) // n_chunks)
    for i in range(0, len(blobs), per):
        group = blobs[i:i + per]
        lens = np.asarray([len(b) for b in group], np.int64)
        yield b"".join(group), np.cumsum(lens) - lens


# ---------------------------------------------------------------------------
# ParallelBGZFWriter ≡ BGZFWriter bytes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("workers", [0, 1, 4, 8])
def test_parallel_bgzf_byte_identity_fuzz(workers):
    """The acceptance bar: byte-identical to the serial writer across
    randomized payload splits and worker counts (0 = serial in-line)."""
    rng = random.Random(workers)
    data = (bytes(rng.randrange(256) for _ in range(200_000))
            + b"G" * 400_000
            + bytes(rng.randrange(4) for _ in range(150_000)))
    oracle = io.BytesIO()
    with bgzf.BGZFWriter(oracle, level=6) as w:
        w.write(data)
    pool = cf.ThreadPoolExecutor(max(workers, 1)) if workers else None
    try:
        sink = io.BytesIO()
        pw = ParallelBGZFWriter(sink, level=6, pool=pool,
                                max_inflight=workers)
        i = 0
        while i < len(data):
            n = rng.randrange(1, 100_000)
            pw.write(data[i:i + n])
            i += n
        pw.close()
        assert sink.getvalue() == oracle.getvalue()
    finally:
        if pool:
            pool.shutdown()


def test_parallel_bgzf_levels_and_eof():
    data = b"ACGT" * 50_000
    for level in (1, 6, 9):
        oracle = io.BytesIO()
        with bgzf.BGZFWriter(oracle, level=level) as w:
            w.write(data)
        sink = io.BytesIO()
        with ParallelBGZFWriter(sink, level=level, max_inflight=2,
                                pool=cf.ThreadPoolExecutor(2)) as pw:
            pw.write(data)
        assert sink.getvalue() == oracle.getvalue()
        assert sink.getvalue().endswith(bgzf.EOF_BLOCK)
    # no-EOF flavor concatenates like a headerless shard
    sink = io.BytesIO()
    with ParallelBGZFWriter(sink, write_eof=False, max_inflight=0) as pw:
        pw.write(data)
    assert not sink.getvalue().endswith(bgzf.EOF_BLOCK)
    assert bgzf.decompress_bytes(sink.getvalue()) == data


def test_resolved_voffsets_match_serial_tracking():
    """Payload-offset tokens resolve to exactly the voffsets the serial
    BamWriter records at write time — the property every index sidecar
    rests on."""
    header = make_header()
    recs = _coord_sorted(header, make_records(header, 800, seed=5))
    blobs = [r.to_bam_bytes(header) for r in recs]

    oracle = io.BytesIO()
    w = BamWriter(oracle, header, track_voffsets=True)
    for b in blobs:
        w.write_record_bytes(b)
    w.close()
    serial_voffs = w.record_voffsets()

    sink = io.BytesIO()
    pw = ParallelBGZFWriter(sink, max_inflight=4,
                            pool=cf.ThreadPoolExecutor(4))
    tokens = []
    pw.write(header.to_bam_bytes())
    for b in blobs:
        tokens.append(pw.tell_payload_offset())
        pw.write(b)
    pw.close()
    assert sink.getvalue() == oracle.getvalue()
    resolved = pw.resolve_voffsets(np.asarray(tokens, np.int64))
    assert [int(v) for v in resolved] == [int(v) for v in serial_voffs]


def test_resolve_before_close_is_plan_error():
    pw = ParallelBGZFWriter(io.BytesIO(), max_inflight=0)
    pw.write(b"x" * 10)
    with pytest.raises(PlanError):
        pw.resolve_voffsets(np.asarray([0]))
    pw.close()


def test_parallel_writer_sink_error_propagates_without_hang():
    class BadSink:
        def write(self, b):
            raise OSError("disk on fire")

    pw = ParallelBGZFWriter(BadSink(), max_inflight=2,
                            pool=cf.ThreadPoolExecutor(2))
    with pytest.raises(OSError, match="disk on fire"):
        # enough payload to force blocks through the committer
        for _ in range(64):
            pw.write(b"z" * bgzf.WRITE_PAYLOAD_SIZE)
        pw.close()


# ---------------------------------------------------------------------------
# write_bam_records: bytes, sidecars, atomicity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sorted_fixture():
    header = make_header(2)
    recs = _coord_sorted(header, make_records(header, 1500, seed=11))
    return header, recs


def _oracle_bam(tmp_path, header, recs, name="oracle.bam"):
    path = str(tmp_path / name)
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path


def test_write_bam_records_byte_identical_with_sidecars(tmp_path,
                                                        sorted_fixture):
    header, recs = sorted_fixture
    oracle = _oracle_bam(tmp_path, header, recs)
    out = str(tmp_path / "par.bam")
    res = write_bam_records(out, header, _record_chunks(header, recs))
    assert res.records == len(recs)
    assert open(out, "rb").read() == open(oracle, "rb").read()
    assert sorted(res.sidecars) == [".bai", ".sbi"]
    assert os.path.exists(out + ".bai") and os.path.exists(out + ".sbi")
    # no tmp litter
    assert not [f for f in os.listdir(tmp_path) if "hbam-write-tmp" in f]


def test_cowritten_bai_queries_like_posthoc_bai(tmp_path, sorted_fixture):
    """The co-written .bai answers interval queries exactly like a
    post-hoc build_bai over the same bytes."""
    from hadoop_bam_tpu.split.bai import BaiIndex, build_bai

    header, recs = sorted_fixture
    out = str(tmp_path / "q.bam")
    write_bam_records(out, header, _record_chunks(header, recs))
    cowritten = BaiIndex.from_bytes(open(out + ".bai", "rb").read())
    posthoc = build_bai(out)
    for rid in range(len(header.ref_names)):
        for beg, end in ((0, 1 << 29), (5_000, 20_000), (0, 1),
                         (100_000, 400_000)):
            assert cowritten.query(rid, beg, end) \
                == posthoc.query(rid, beg, end), (rid, beg, end)


def test_cowritten_sbi_matches_index_on_write(tmp_path, sorted_fixture):
    """The co-written .sbi equals BamWriter's index-on-write sidecar
    byte for byte (same granularity, same sampled voffsets)."""
    header, recs = sorted_fixture
    g = DEFAULT_CONFIG.splitting_index_granularity
    oracle = str(tmp_path / "o.bam")
    with BamWriter(oracle, header, index_granularity=g,
                   index_flavor="sbi") as w:
        for r in recs:
            w.write_sam_record(r)
    out = str(tmp_path / "p.bam")
    write_bam_records(out, header, _record_chunks(header, recs))
    assert open(out + ".sbi", "rb").read() \
        == open(oracle + ".sbi", "rb").read()


def test_write_index_kinds_none_and_validation(tmp_path, sorted_fixture):
    header, recs = sorted_fixture
    out = str(tmp_path / "noidx.bam")
    cfg = dataclasses.replace(DEFAULT_CONFIG, write_index_kinds="none")
    res = write_bam_records(out, header, _record_chunks(header, recs),
                            config=cfg)
    assert res.sidecars == {}
    assert not os.path.exists(out + ".bai")
    with pytest.raises(PlanError):
        resolve_index_kinds(
            dataclasses.replace(DEFAULT_CONFIG, write_index_kinds="tbi"),
            "bam")
    assert resolve_index_kinds(DEFAULT_CONFIG, "bcf") == ("tbi",)


def test_failed_write_leaves_nothing_visible(tmp_path, sorted_fixture):
    header, recs = sorted_fixture
    out = str(tmp_path / "crash.bam")

    def bad_chunks():
        yield from _record_chunks(header, recs, n_chunks=8)
        raise RuntimeError("producer died")

    with pytest.raises(RuntimeError, match="producer died"):
        write_bam_records(out, header, bad_chunks())
    assert not os.path.exists(out)
    assert not os.path.exists(out + ".bai")
    assert [f for f in os.listdir(tmp_path) if "crash" in f] == []


def test_write_compress_level_threads_through(tmp_path, sorted_fixture):
    header, recs = sorted_fixture
    cfg = dataclasses.replace(DEFAULT_CONFIG, write_compress_level=1)
    oracle = str(tmp_path / "l1.bam")
    with BamWriter(oracle, header, level=1) as w:
        for r in recs:
            w.write_sam_record(r)
    out = str(tmp_path / "l1p.bam")
    write_bam_records(out, header, _record_chunks(header, recs),
                      config=cfg)
    assert open(out, "rb").read() == open(oracle, "rb").read()


# ---------------------------------------------------------------------------
# ShardedFileWriter
# ---------------------------------------------------------------------------

def test_sharded_writer_parts_and_atomic_concat(tmp_path, sorted_fixture):
    header, recs = sorted_fixture
    final = str(tmp_path / "final.bam")
    sw = ShardedFileWriter(final, 3)
    sw.prepare()
    thirds = [recs[i::3] for i in range(3)]
    for k in range(3):
        with sw.open_shard(k) as f:
            with BamWriter(f, header, write_header=False,
                           write_eof=False) as w:
                for r in _coord_sorted(header, thirds[k]):
                    w.write_sam_record(r)
        assert os.path.exists(sw.shard_path(k))
        assert not os.path.exists(sw.shard_path(k) + ".tmp")
    assert sw.missing_parts() == []
    res = sw.concatenate(lambda parts: write_bam_shards_concat(
        parts, final, header))
    assert res.records == len(recs)
    assert not os.path.isdir(sw.shard_dir)
    from hadoop_bam_tpu.formats.bamio import read_bam
    _, batch = read_bam(final)
    assert len(batch) == len(recs)


def test_sharded_writer_missing_part_refuses(tmp_path):
    final = str(tmp_path / "f.bam")
    sw = ShardedFileWriter(final, 2)
    with sw.open_shard(0) as f:
        f.write(b"")
    # TRANSIENT class since the ET3xx scope extension: a missing part is
    # shared-filesystem lag (retryable), not data corruption
    from hadoop_bam_tpu.utils.errors import TransientIOError
    with pytest.raises(TransientIOError, match="missing"):
        sw.concatenate(lambda parts: None, what="unit")
    assert not os.path.exists(final)


def test_sharded_writer_failed_shard_leaves_no_part(tmp_path):
    sw = ShardedFileWriter(str(tmp_path / "f.bam"), 1)
    with pytest.raises(RuntimeError, match="boom"):
        with sw.open_shard(0) as f:
            f.write(b"xx")
            raise RuntimeError("boom")
    assert sw.missing_parts() == [sw.shard_path(0)]
    assert not os.path.exists(sw.shard_path(0) + ".tmp")


# ---------------------------------------------------------------------------
# write→query round trip (the acceptance bar)
# ---------------------------------------------------------------------------

def test_bam_write_query_round_trip_cold(tmp_path, sorted_fixture,
                                         monkeypatch):
    """Output written by the new path is served COLD by QueryEngine
    using only the co-written sidecars — no rescan, no build_bai — with
    results byte-identical to querying a serially-written oracle."""
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest
    import hadoop_bam_tpu.split.bai as bai_mod

    header, recs = sorted_fixture
    oracle = _oracle_bam(tmp_path, header, recs)
    bai_mod.write_bai(oracle)
    out = str(tmp_path / "cold.bam")
    write_bam_records(out, header, _record_chunks(header, recs))

    # any rescan attempt on the new file is a test failure
    def no_rescan(*a, **kw):
        raise AssertionError("build_bai called — the co-written sidecar "
                             "should have served the query")
    monkeypatch.setattr(bai_mod, "build_bai", no_rescan)

    regions = [f"{header.ref_names[0]}:1-60000",
               f"{header.ref_names[1]}:100000-900000",
               f"{header.ref_names[0]}:999999-1000000"]
    res_new = QueryEngine().query_records(
        [QueryRequest(out, r) for r in regions])
    res_old = QueryEngine().query_records(
        [QueryRequest(oracle, r) for r in regions])
    for a, b in zip(res_new, res_old):
        assert [r.to_line() for r in a.records] \
            == [r.to_line() for r in b.records]
    assert sum(len(r.records) for r in res_new) > 0


def _make_vcf_records(n, seed=3):
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    hdr_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr20,length=64444167>\n"
        "##contig=<ID=chr21,length=46709983>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="GT">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts0\n")
    header = VCFHeader.from_text(hdr_text)
    rng = random.Random(seed)
    recs = []
    for chrom in ("chr20", "chr21"):
        pos = 1
        for i in range(n // 2):
            pos += rng.randint(1, 50)
            ref = rng.choice("ACGT")
            alt = rng.choice([c for c in "ACGT" if c != ref])
            recs.append(VcfRecord.from_line(
                f"{chrom}\t{pos}\t.\t{ref}\t{alt}\t{30 + i % 40}\tPASS\t"
                f"DP={i % 90}\tGT\t{rng.choice(['0/0', '0/1', '1/1'])}"))
    return header, recs


def test_bcf_write_query_round_trip_cold(tmp_path):
    """BCF + co-written tabix: byte-identical to the serial BcfWriter,
    cold-queried identically to a serially-written + write_tabix'd
    oracle."""
    from hadoop_bam_tpu.formats.bcfio import BcfWriter
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest
    from hadoop_bam_tpu.split.tabix import write_tabix

    header, recs = _make_vcf_records(900)
    oracle = str(tmp_path / "o.bcf")
    with BcfWriter(oracle, header) as w:
        for r in recs:
            w.write_record(r)
    write_tabix(oracle)

    out = str(tmp_path / "p.bcf")
    res = write_bcf_records(out, header, iter(recs))
    assert res.records == len(recs)
    assert open(out, "rb").read() == open(oracle, "rb").read()
    assert sorted(res.sidecars) == [".tbi"]

    regions = ["chr20:1-5000", "chr21:1-100000", "chr20:999000-999999"]
    res_new = QueryEngine().query_records(
        [QueryRequest(out, r) for r in regions])
    res_old = QueryEngine().query_records(
        [QueryRequest(oracle, r) for r in regions])
    for a, b in zip(res_new, res_old):
        assert [r.to_line() for r in a.records] \
            == [r.to_line() for r in b.records]
    assert sum(len(r.records) for r in res_new) > 0


def test_mesh_sort_output_is_immediately_queryable(tmp_path):
    """sort_bam_mesh through the write path: sidecars land next to the
    output and the query engine opens it cold (the ISSUE acceptance
    composition)."""
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest

    header = make_header()
    recs = make_records(header, 700, seed=23)
    random.Random(4).shuffle(recs)
    src = str(tmp_path / "in.bam")
    with BamWriter(src, header) as w:
        for r in recs:
            w.write_sam_record(r)
    out = str(tmp_path / "sorted.bam")
    n = sort_bam_mesh(src, out)
    assert n == len(recs)
    assert os.path.exists(out + ".bai")
    assert os.path.exists(out + ".sbi")
    res = QueryEngine().query_records(
        [QueryRequest(out, f"{header.ref_names[0]}:1-400000")])
    mapped = [r for r in recs
              if r.rname == header.ref_names[0]
              and r.pos <= 400000 and r.pos + len(r.seq) - 1 >= 1]
    assert len(res[0].records) == len(mapped)


def test_mesh_sort_no_write_index_cli(tmp_path):
    from hadoop_bam_tpu.tools.cli import main

    header = make_header()
    recs = make_records(header, 200, seed=8)
    src = str(tmp_path / "in.bam")
    with BamWriter(src, header) as w:
        for r in recs:
            w.write_sam_record(r)
    out = str(tmp_path / "s.bam")
    assert main(["sort", "--mesh", "--no-write-index",
                 "--compress-level", "4", src, out]) == 0
    assert os.path.exists(out)
    assert not os.path.exists(out + ".bai")
    # level threaded: bytes match a level-4 serial sort
    from hadoop_bam_tpu.utils.sort import sort_bam
    ref = str(tmp_path / "ref.bam")
    cfg = dataclasses.replace(DEFAULT_CONFIG, write_compress_level=4)
    sort_bam(src, ref, config=cfg)
    assert open(out, "rb").read() == open(ref, "rb").read()


def test_vcf_sort_bcf_output_gets_tabix(tmp_path):
    """hbam vcf-sort to .bcf routes through write_bcf_records: sorted
    output plus a co-written .tbi."""
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.utils.sort import sort_vcf

    header, recs = _make_vcf_records(300, seed=9)
    shuffled = list(recs)
    random.Random(2).shuffle(shuffled)
    src = str(tmp_path / "in.vcf")
    with open_vcf_writer(src, header) as w:
        for r in shuffled:
            w.write_record(r)
    out = str(tmp_path / "sorted.bcf")
    n = sort_vcf(src, out)
    assert n == len(recs)
    assert os.path.exists(out + ".tbi")
    from hadoop_bam_tpu.formats.bcfio import read_bcf
    _, back = read_bcf(out)
    assert [(r.chrom, r.pos) for r in back] \
        == [(r.chrom, r.pos) for r in recs]


def test_sidecar_write_failure_leaves_final_name_unpublished(
        tmp_path, sorted_fixture):
    """A sidecar I/O failure must abort BEFORE the data rename: the old
    output and its old sidecars stay intact, nothing is half-published
    (the 'ENOSPC between data rename and sidecar write' hole)."""
    from hadoop_bam_tpu.write.api import _TMP_SUFFIX

    header, recs = sorted_fixture
    out = str(tmp_path / "v.bam")
    old_data, old_bai = b"OLD-DATA", b"OLD-BAI"
    with open(out, "wb") as f:
        f.write(old_data)
    with open(out + ".bai", "wb") as f:
        f.write(old_bai)
    # a directory squatting on the .bai temp name makes the sidecar
    # temp write fail deterministically, standing in for ENOSPC
    os.mkdir(out + ".bai" + _TMP_SUFFIX)
    with pytest.raises(OSError):
        write_bam_records(out, header, _record_chunks(header, recs))
    assert open(out, "rb").read() == old_data
    assert open(out + ".bai", "rb").read() == old_bai
    leftovers = [f for f in os.listdir(tmp_path)
                 if f.startswith("v.bam") and _TMP_SUFFIX in f
                 and not os.path.isdir(str(tmp_path / f))]
    assert leftovers == []


def test_data_rename_failure_cleans_sidecar_temps(tmp_path,
                                                  sorted_fixture):
    """If the data-file os.replace itself fails (dir squatting on the
    final name), the already-written sidecar temps must not leak."""
    from hadoop_bam_tpu.write.api import _TMP_SUFFIX

    header, recs = sorted_fixture
    out = str(tmp_path / "w.bam")
    os.mkdir(out)                       # os.replace(file -> dir) raises
    with pytest.raises(OSError):
        write_bam_records(out, header, _record_chunks(header, recs))
    assert [f for f in os.listdir(tmp_path) if _TMP_SUFFIX in f] == []


def test_bai_from_columns_matches_incremental_builder():
    """The vectorized column build is bit-identical to per-record
    BAIBuilder.add over randomized coordinate-sorted inputs: multi-ref,
    multi-window spans, same-bin runs broken by bin hops and by
    unmapped records, unmapped tail."""
    from hadoop_bam_tpu.split.bai import BAIBuilder, bai_from_columns

    for seed in range(5):
        rng = random.Random(seed)
        n_ref = rng.randint(1, 4)
        rows = []
        voff = (rng.randrange(1, 1000) << 16) | rng.randrange(100)
        for rid in range(n_ref):
            pos = 0
            for _ in range(rng.randrange(0, 300)):
                pos += rng.randrange(0, 60_000)     # bin/window hops
                span = rng.choice([1, 50, 151, 20_000, 40_000])
                rows.append((rid, pos, pos + span, voff))
                voff += rng.randrange(1, 90_000)    # crosses blocks
        for _ in range(rng.randrange(0, 4)):        # unmapped tail
            rows.append((-1, -1, 0, voff))
            voff += rng.randrange(1, 1000)
        end_v = voff + 37
        cols = np.asarray(rows, np.int64).reshape(-1, 4)
        b = BAIBuilder(n_ref)
        for rid, beg, end, v in rows:
            b.add(rid, beg, end, v)
        serial = b.finalize(end_v).to_bytes()
        vec = bai_from_columns(
            n_ref, cols[:, 0], cols[:, 1], cols[:, 2],
            cols[:, 3].astype(np.uint64), end_v).to_bytes()
        assert vec == serial, f"seed {seed}"


def test_cli_compress_level_range_validated(tmp_path):
    from hadoop_bam_tpu.tools.cli import main

    with pytest.raises(SystemExit, match="0-9"):
        main(["sort", "--compress-level", "15", "in.bam", "out.bam"])


def test_bcf_write_honors_header_and_terminator_knobs(tmp_path):
    """write_bcf_records keeps the BcfShardWriter semantics it replaced
    in sort_vcf: config.write_header / write_terminator change the
    output bytes identically on both writers."""
    from hadoop_bam_tpu.api.writers import BcfShardWriter

    header, recs = _make_vcf_records(120, seed=3)
    for knobs in ({"write_terminator": False},
                  {"write_header": False},
                  {"write_header": False, "write_terminator": False}):
        cfg = dataclasses.replace(DEFAULT_CONFIG, **knobs)
        oracle = str(tmp_path / "o.bcf")
        w = BcfShardWriter(oracle, header, cfg)
        for r in recs:
            w.write_record(r)
        w.close()
        out = str(tmp_path / "p.bcf")
        write_bcf_records(out, header, iter(recs), config=cfg,
                          index_kinds=())
        assert open(out, "rb").read() == open(oracle, "rb").read(), knobs


def test_plain_sort_cowrites_sidecars_and_honors_flags(tmp_path):
    """Non-mesh `hbam sort` routes coordinate output through the write
    path too: sidecars co-written, --no-write-index honored, -n
    (queryname) output never indexed."""
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest
    from hadoop_bam_tpu.tools.cli import main

    header = make_header()
    recs = make_records(header, 250, seed=31)
    random.Random(6).shuffle(recs)
    src = str(tmp_path / "in.bam")
    with BamWriter(src, header) as w:
        for r in recs:
            w.write_sam_record(r)

    out = str(tmp_path / "s.bam")
    assert main(["sort", src, out]) == 0
    assert os.path.exists(out + ".bai")
    assert os.path.exists(out + ".sbi")
    res = QueryEngine().query_records(
        [QueryRequest(out, f"{header.ref_names[0]}:1-500000")])
    assert len(res[0].records) > 0

    bare = str(tmp_path / "bare.bam")
    assert main(["sort", "--no-write-index", src, bare]) == 0
    assert not os.path.exists(bare + ".bai")
    assert open(bare, "rb").read() == open(out, "rb").read()

    by_name = str(tmp_path / "n.bam")
    assert main(["sort", "-n", src, by_name]) == 0
    assert not os.path.exists(by_name + ".bai")


# ---------------------------------------------------------------------------
# BAIBuilder (satellite: incremental core behind build_bai)
# ---------------------------------------------------------------------------

def test_bai_builder_incremental_matches_posthoc(tmp_path,
                                                 sorted_fixture):
    """Feeding BAIBuilder record-at-a-time from writer-tracked voffsets
    reproduces build_bai's query answers on the same file."""
    from hadoop_bam_tpu.split.bai import BAIBuilder, build_bai

    header, recs = sorted_fixture
    path = str(tmp_path / "b.bam")
    w = BamWriter(path, header, track_voffsets=True)
    spans = []
    for r in recs:
        rid = header.ref_names.index(r.rname) if r.rname != "*" else -1
        spans.append((rid, r.pos - 1, r.pos - 1 + max(len(r.seq), 1)))
        w.write_sam_record(r)
    w.close()
    builder = BAIBuilder(len(header.ref_names))
    for (rid, beg, end), v in zip(spans, w.record_voffsets()):
        builder.add(rid, beg, end, int(v))
    # normalized end-of-data: coffset of the EOF block
    incr = builder.finalize(os.path.getsize(path) << 16)
    posthoc = build_bai(path)
    for rid in range(len(header.ref_names)):
        for beg, end in ((0, 1 << 29), (2_000, 30_000), (0, 1)):
            assert incr.query(rid, beg, end) == posthoc.query(
                rid, beg, end)
