"""rANS Nx16 codec tests (CRAM 3.1 block method 5).

Covers the codec the same way the reference's CRAM tests cover htsjdk's
codecs (SURVEY.md section 4): parametrized round-trips over every flag
combination and adversarial payload shapes, container-level 3.1
write->read, a device-backend read of a 3.1 file, decode-only vectors for
the foreign-stream branches our encoder never produces, and FROZEN GOLDEN
BYTES pinning the wire layout against drift (the in-image environment has
no htslib to cross-validate against — SURVEY.md section 0 fallback, so
committed bytes are the only drift guard available).
"""
import numpy as np
import pytest

from hadoop_bam_tpu.formats.cram_codecs import RansError
from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
    NX16_CAT, NX16_NOSZ, NX16_ORDER1, NX16_PACK, NX16_RLE, NX16_STRIPE,
    NX16_X32, _encode_order0_core, _encode_order1_core,
    _read_order1_ctx_tables, _rle_encode, rans_nx16_decode, rans_nx16_encode,
    var_get_u32, var_put_u32,
)

from fixtures import make_header, make_records


# ---------------------------------------------------------------------------
# Payload shapes: each chosen to hit a distinct codec edge
# ---------------------------------------------------------------------------

def _payloads():
    rng = np.random.default_rng(42)
    qual_syms = np.frombuffer(b"!#%+5<AFI", dtype=np.uint8)  # 9 symbols
    out = {
        "empty": b"",
        "one": b"Q",
        "tiny16": b"AB" * 8,                      # < 32 -> CAT fallback
        "cat_edge31": bytes(rng.integers(0, 256, 31, dtype=np.uint8)),
        "cat_edge32": bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
        "runs": b"A" * 500 + b"B" * 300 + b"C" + b"D" * 199,
        "two_sym": bytes(rng.choice(np.frombuffer(b"XY", np.uint8),
                                    1001).tobytes()),
        "four_sym": bytes(rng.choice(np.frombuffer(b"ACGT", np.uint8),
                                     997).tobytes()),
        "qual9": bytes(rng.choice(qual_syms, 4095).tobytes()),
        "sym17": bytes(rng.integers(0, 17, 513, dtype=np.uint8)),  # PACK drops
        "dense": bytes(rng.integers(0, 256, 2048, dtype=np.uint8)),
        "stripe_tail": bytes(rng.choice(qual_syms, 1003).tobytes()),  # %4==3
        "x32_tail": bytes(rng.choice(qual_syms, 95).tobytes()),       # < 3*32
    }
    return out


FLAG_SETS = [
    0,
    NX16_ORDER1,
    NX16_PACK,
    NX16_RLE,
    NX16_PACK | NX16_RLE,
    NX16_PACK | NX16_ORDER1,
    NX16_RLE | NX16_ORDER1,
    NX16_STRIPE,
    NX16_STRIPE | NX16_ORDER1,
    NX16_STRIPE | NX16_PACK | NX16_RLE,
    NX16_X32,
    NX16_X32 | NX16_ORDER1,
    NX16_CAT,
]


@pytest.mark.parametrize("flags", FLAG_SETS)
@pytest.mark.parametrize("name", sorted(_payloads()))
def test_nx16_roundtrip(flags, name):
    data = _payloads()[name]
    enc = rans_nx16_encode(data, flags)
    assert rans_nx16_decode(enc) == data


@pytest.mark.parametrize("flags", [0, NX16_ORDER1, NX16_PACK | NX16_RLE])
def test_nx16_nosz_roundtrip(flags):
    data = _payloads()["qual9"]
    enc = rans_nx16_encode(data, flags | NX16_NOSZ)
    assert rans_nx16_decode(enc, len(data)) == data
    with pytest.raises(RansError):
        rans_nx16_decode(enc)          # NOSZ stream needs external size


@pytest.mark.parametrize("v", [0, 1, 127, 128, 16383, 16384, (1 << 28) - 1,
                               1 << 28, (1 << 32) - 1])
def test_varint_roundtrip(v):
    buf = var_put_u32(v)
    got, pos = var_get_u32(buf, 0)
    assert got == v and pos == len(buf)


def test_pack_dropped_above_16_symbols():
    data = _payloads()["sym17"]
    enc = rans_nx16_encode(data, NX16_PACK)
    assert not (enc[0] & NX16_PACK)
    assert rans_nx16_decode(enc) == data


def test_tiny_payload_falls_back_to_cat():
    enc = rans_nx16_encode(b"AB" * 8, NX16_ORDER1)
    assert enc[0] & NX16_CAT
    assert not (enc[0] & NX16_ORDER1)


def test_truncated_and_garbage_streams_raise():
    data = _payloads()["qual9"]
    enc = rans_nx16_encode(data, 0)
    with pytest.raises(RansError):
        rans_nx16_decode(b"")
    with pytest.raises(RansError):
        rans_nx16_decode(enc[: len(enc) // 2])


@pytest.mark.parametrize("flags", [0, NX16_ORDER1, NX16_X32])
def test_corrupt_nx16_stream_raises_not_garbage(flags):
    """A bit-flipped renorm byte raises RansError via the final-state
    integrity check — same contract as the 4x8 decoders."""
    data = _payloads()["qual9"]
    enc = bytearray(rans_nx16_encode(data, flags))
    assert not (enc[0] & NX16_CAT)
    enc[-30] ^= 0xFF
    with pytest.raises(RansError):
        rans_nx16_decode(bytes(enc))


def test_lying_out_size_nx16_raises():
    data = _payloads()["qual9"]
    enc = bytearray(rans_nx16_encode(data, 0))
    # out_size varint directly follows the flag byte for non-NOSZ; patch
    # a same-width varint claiming 64 extra bytes
    old = var_put_u32(len(data))
    new = var_put_u32(len(data) + 64)
    assert enc[1:1 + len(old)] == old and len(new) == len(old)
    enc[1:1 + len(old)] = new
    with pytest.raises(RansError):
        rans_nx16_decode(bytes(enc))


# ---------------------------------------------------------------------------
# Foreign-stream branches our encoder never emits (decode-only vectors)
# ---------------------------------------------------------------------------

def test_compressed_rle_meta_branch():
    """mlen bit0 CLEAR: the RLE metadata is itself order-0 compressed.

    Our encoder always stores RLE meta raw; real htscodecs streams may
    compress it, so pin the decode path with a hand-built vector."""
    data = b"A" * 400 + b"C" * 300 + bytes(range(64)) * 4 + b"G" * 200
    rled = _rle_encode(data)
    assert rled is not None
    meta, lits = rled
    assert len(lits) >= 32
    comp_meta = _encode_order0_core(meta, 4)
    stream = bytearray([NX16_RLE])
    stream += var_put_u32(len(data))
    stream += var_put_u32(len(meta) << 1)       # bit0 clear: compressed
    stream += var_put_u32(len(comp_meta))
    stream += comp_meta
    stream += var_put_u32(len(lits))
    stream += _encode_order0_core(lits, 4)
    assert rans_nx16_decode(bytes(stream)) == data


def test_compressed_order1_tables_branch():
    """order-1 lead byte bit0 SET: the context tables are themselves
    order-0 compressed.  Built by recompressing our own plain tables."""
    rng = np.random.default_rng(7)
    data = bytes(rng.choice(np.frombuffer(b"ACGT", np.uint8),
                            2000).tobytes())
    core = _encode_order1_core(data, 4)
    shift = core[0] >> 4
    assert core[0] & 1 == 0
    _, _, _, end = _read_order1_ctx_tables(core, 1, shift)
    tbl_plain, rest = core[1:end], core[end:]
    comp_tbl = _encode_order0_core(tbl_plain, 4)
    stream = bytearray([NX16_ORDER1])
    stream += var_put_u32(len(data))
    stream.append((shift << 4) | 1)             # bit0 set: compressed tables
    stream += var_put_u32(len(tbl_plain))
    stream += var_put_u32(len(comp_tbl))
    stream += comp_tbl
    stream += rest
    assert rans_nx16_decode(bytes(stream)) == data


# ---------------------------------------------------------------------------
# Frozen golden bytes: encoder output is pinned per flag combo.  If any of
# these change, the wire format drifted — bump deliberately, never silently.
# ---------------------------------------------------------------------------

GOLDEN_INPUT = (b"GATTACA-" * 6 + b"Q" * 40 + bytes(range(8)) * 4)  # 120 B, 14 syms

GOLDEN = {
    0x00: "00780001062d41434751540081088108810881088108810881088108814c8466814c814c8a5d8319da58010001670100788f7605203f0f007e35cf078cffaadfdda684666f2f5e7769584f35344f1f19c9c944bac0aa0f10a90042f1dce13012c80260f3f8e3",
    0x01: "0178c00001062d41434751540001020041475100900084008400840084000200a0000300a0000400a0000500a0000600a0000700a0000000a0004751009a56852a2d4354008a568a558a554100a0004100a000005100699f1741540090009000af5cdd123eaa18007b4f75020008200082ce6cc7",
    0x80: "80780e00010203040506072d414347515410325476899ba9ccdd0082118211821182118319831983198a588319be46bd052bafe0059f38c405dd54b6058616ac6f3d281a050e311f5141626373",
    0x40: "407807015127510001062d414347515400814a814a814a814a814a814a814a814a822f8713822f822f32845e9e9c510cc79724005a9e0000fd9ebc017769bdebe45fe7f7b04af6b9c5ef819ceeb33ba41a55fa05e4331c941ee52036",
    0xc0: "c0780e00010203040506072d41434751540701cc132910325476899ba9ccdd00830f830f830f830f845c84578457638457386a0500c55d4d24231da9235d66c720919a1fa0f9c1d3e31796",
    0x08: "0878041f1f1f1f30474147414741474147414741515151515151515151510004000400040004304143414341434143414341435151515151515151515101050105010501053054415441544154415441544151515151515151515151020602060206020630542d542d542d542d542d542d515151515151515151510307030703070307",
    0x04: "04780001062d41434751540081088108810881088108810881088108814c8466814c814c8a5d8319d2f886465a25c907ffce8c1187cf8c112886c907bee78b463887c907dee48c46d2f886465a25c907ffce8c1187cf8c112886c907bee78b463887c907dee48c4651c87a0a99567b03c82e3a05c49e3a05d6a67b0310c87b0a80b57b0356e47b0a083983037ac62a01849ec0010c9fc001acd52a01b4778303bcd62a01c6848303",
    0x05: "0578c00001062d414347515400010200052d414347515400834771718163852a816381638c3d83470200a0000300a0000400a0000500a0000600a0000700a0000000a0004700a0002d4354008a568a558a554100a0004100a00000510081179e694154009000900035d71b00723e1b00dac3080064b41200f6230900f90209003d4f120010471a0035d71b00723e1b00dac3080064b41200f6230900f90209003d4f120010471a005c6901005c6901005c6901005c6901005c6901005c6901005c6901005c6901005c6901005c6901005c6901005c6901005c69010080183901361212008eb29e33",
    0x20: "2078474154544143412d474154544143412d474154544143412d474154544143412d474154544143412d474154544143412d515151515151515151515151515151515151515151515151515151515151515151515151515151510001020304050607000102030405060700010203040506070001020304050607",
}


def _golden_cases():
    return [0, NX16_ORDER1, NX16_PACK, NX16_RLE, NX16_PACK | NX16_RLE,
            NX16_STRIPE, NX16_X32, NX16_X32 | NX16_ORDER1, NX16_CAT]


@pytest.mark.parametrize("flags", _golden_cases())
def test_nx16_golden_bytes(flags):
    enc = rans_nx16_encode(GOLDEN_INPUT, flags)
    assert enc.hex() == GOLDEN[flags], (
        f"rANS Nx16 wire format drifted for flags=0x{flags:02x}")
    assert rans_nx16_decode(bytes.fromhex(GOLDEN[flags])) == GOLDEN_INPUT


# ---------------------------------------------------------------------------
# Container-level CRAM 3.1
# ---------------------------------------------------------------------------

def _block_methods(path):
    from hadoop_bam_tpu.formats.cram import (
        ContainerHeader, FileDefinition, parse_raw_block,
    )
    buf = open(path, "rb").read()
    pos = FileDefinition.SIZE
    methods = []
    while pos < len(buf):
        hdr, pos = ContainerHeader.from_buffer(buf, pos)
        end = pos + hdr.length
        while pos < end:
            raw, pos = parse_raw_block(buf, pos)
            methods.append(raw.method)
    return methods


def test_cram31_container_roundtrip(tmp_path):
    from hadoop_bam_tpu.formats.cram import RANSNx16
    from hadoop_bam_tpu.formats.cramio import CramWriter, read_cram

    header = make_header()
    recs = make_records(header, 300, seed=13)
    path = str(tmp_path / "v31.cram")
    with CramWriter(path, header, records_per_container=50,
                    version=(3, 1)) as w:
        w.write_records(recs)
    raw = open(path, "rb").read()
    assert raw[4] == 3 and raw[5] == 1          # file definition says 3.1
    assert RANSNx16 in _block_methods(path)     # blocks really use Nx16
    _, out = read_cram(path)
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_cram31_dataset_reads_with_device_backend(tmp_path, monkeypatch):
    """A 3.1 file reads identically under HBAM_RANS_BACKEND=device (4x8
    blocks go to the device path; Nx16 blocks decode on host)."""
    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.formats.cramio import CramWriter

    header = make_header()
    recs = make_records(header, 200, seed=21)
    path = str(tmp_path / "dev31.cram")
    with CramWriter(path, header, records_per_container=40,
                    version=(3, 1)) as w:
        w.write_records(recs)
    host = [r.to_line() for r in open_cram(path).records()]
    monkeypatch.setenv("HBAM_RANS_BACKEND", "device")
    dev = [r.to_line() for r in open_cram(path).records()]
    assert host == dev == [r.to_line() for r in recs]
