"""Device DEFLATE tests: host Huffman tokenizer + device LZ77 resolution.

Parity oracle is zlib — every payload below must survive
compress -> tokenize -> device-resolve -> compare against the original
bytes, across all DEFLATE block types (stored / fixed / dynamic), deep
copy chains, and multi-block streams (SURVEY.md section 2.8 row 1: the
zlib-JNI inflate the reference leaned on, section 7 hard part #1)."""
import io
import random
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.ops.inflate import inflate_span
from hadoop_bam_tpu.ops.inflate_device import (
    inflate_span_device, resolve_tokens,
)
from hadoop_bam_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native tokenizer unavailable")


def _tokenize_one(comp: bytes, out_cap: int):
    src = np.frombuffer(comp, np.uint8)
    return native.deflate_tokenize_batch(
        src, np.array([0], np.int64), np.array([len(comp)], np.int32),
        max(16, out_cap))


def _roundtrip(data: bytes, level: int = 6, strategy: int = 0):
    co = zlib.compressobj(level, zlib.DEFLATED, -15, 9, strategy)
    comp = co.compress(data) + co.flush()
    toks, nt, ol = _tokenize_one(comp, len(data) + 1)
    assert int(ol[0]) == len(data)
    P = 256
    while P < max(256, len(data)):
        P <<= 1
    out = np.asarray(resolve_tokens(jnp.asarray(toks), jnp.asarray(nt), P))
    assert out[0, : len(data)].tobytes() == data


def _payloads():
    rng = random.Random(3)
    return {
        "empty": b"",
        "one": b"A",
        "text": b"hello deflate world " * 200,
        "random": bytes(rng.randrange(256) for _ in range(50000)),
        "dna": bytes(rng.choice(b"ACGT") for _ in range(60000)),
        "rle_deep": b"A" * 65000,             # dist-1 overlapping copies
        "alternating": b"AB" * 30000,
        "qual": bytes(rng.choice(b"FFFFFF:,#IIII") for _ in range(64000)),
    }


@pytest.mark.parametrize("level", [0, 1, 6, 9])   # 0 = stored blocks
@pytest.mark.parametrize("name", sorted(_payloads()))
def test_token_parity_vs_zlib(name, level):
    _roundtrip(_payloads()[name], level)


@pytest.mark.parametrize("name", ["dna", "rle_deep", "random"])
def test_fixed_huffman_blocks(name):
    _roundtrip(_payloads()[name], 6, zlib.Z_FIXED)


def test_multi_deflate_block_stream():
    rng = random.Random(11)
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    parts, data = [], b""
    for _ in range(5):
        d = bytes(rng.choice(b"ACGTN") for _ in range(8000))
        data += d
        parts.append(co.compress(d))
        parts.append(co.flush(zlib.Z_FULL_FLUSH))
    parts.append(co.flush())
    comp = b"".join(parts)
    toks, nt, ol = _tokenize_one(comp, len(data) + 16)
    assert int(ol[0]) == len(data)
    out = np.asarray(resolve_tokens(jnp.asarray(toks), jnp.asarray(nt),
                                    65536))
    assert out[0, : len(data)].tobytes() == data


def test_bgzf_span_device_matches_host():
    rng = random.Random(7)
    payload = bytes(rng.choice(b"ACGTN!@#qual") for _ in range(300000))
    sink = io.BytesIO()
    w = bgzf.BGZFWriter(sink)
    w.write(payload)
    w.close()
    raw = sink.getvalue()
    host_data, host_ubase = inflate_span(raw, backend="auto")
    dev_data, dev_ubase = inflate_span(raw, backend="device")
    assert np.array_equal(host_data, dev_data)
    assert np.array_equal(host_ubase, dev_ubase)
    assert dev_data.tobytes() == payload


def test_batch_tokenize_many_blocks():
    """Batch API over heterogeneous blocks, strided token rows."""
    rng = random.Random(13)
    datas = [bytes(rng.choice(b"ACGT") for _ in range(rng.randrange(1, 3000)))
             for _ in range(40)]
    comps, offs, lens = [], [], []
    pos = 0
    for d in datas:
        co = zlib.compressobj(rng.choice([1, 6, 9]), zlib.DEFLATED, -15)
        c = co.compress(d) + co.flush()
        comps.append(c)
        offs.append(pos)
        lens.append(len(c))
        pos += len(c)
    src = np.frombuffer(b"".join(comps), np.uint8)
    stride = max(len(d) for d in datas) + 1
    toks, nts, ols = native.deflate_tokenize_batch(
        src, np.array(offs, np.int64), np.array(lens, np.int32), stride)
    assert [int(o) for o in ols] == [len(d) for d in datas]
    P = 4096
    out = np.asarray(resolve_tokens(jnp.asarray(toks), jnp.asarray(nts), P))
    for i, d in enumerate(datas):
        assert out[i, : len(d)].tobytes() == d, f"block {i}"


def test_corrupt_stream_rejected():
    data = b"ACGTN" * 5000
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = bytearray(co.compress(data) + co.flush())
    comp[10] ^= 0xFF
    src = np.frombuffer(bytes(comp), np.uint8)
    with pytest.raises(ValueError):
        native.deflate_tokenize_batch(
            src, np.array([0], np.int64),
            np.array([len(comp)], np.int32), len(data) + 16)


def test_truncated_stream_rejected():
    data = b"ACGTN" * 5000
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = co.compress(data) + co.flush()
    src = np.frombuffer(comp[: len(comp) // 2], np.uint8)
    with pytest.raises(ValueError):
        native.deflate_tokenize_batch(
            src, np.array([0], np.int64),
            np.array([src.size], np.int32), len(data) + 16)
