"""Device DEFLATE tests: host Huffman tokenizer + device LZ77 resolution.

Parity oracle is zlib — every payload below must survive
compress -> tokenize -> device-resolve -> compare against the original
bytes, across all DEFLATE block types (stored / fixed / dynamic), deep
copy chains, and multi-block streams (SURVEY.md section 2.8 row 1: the
zlib-JNI inflate the reference leaned on, section 7 hard part #1).

The round-11 additions cover the production device decode plane:
byte identity vs the zlib oracle over randomized split offsets and
BCF/tabix-shaped BGZF containers, byte-flip fuzz pinning identical error
classes on both planes, the tokenize-time CRC fold, the pow2 shape
ladder's jit-cache bound, and the token-feed flagstat driver (walk +
unpack on device, host fixup for cut/over-wide spans)."""
import dataclasses
import io
import random
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.ops.inflate import inflate_span
from hadoop_bam_tpu.ops.inflate_device import (
    inflate_span_device, ladder_pow2, resolve_tokens,
    resolve_tokens_packed,
)
from hadoop_bam_tpu.utils import native

pytestmark = [
    pytest.mark.device_inflate,
    pytest.mark.skipif(not native.available(),
                       reason="native tokenizer unavailable"),
]


def _tokenize_one(comp: bytes, out_cap: int):
    src = np.frombuffer(comp, np.uint8)
    return native.deflate_tokenize_batch(
        src, np.array([0], np.int64), np.array([len(comp)], np.int32),
        max(16, out_cap))


def _roundtrip(data: bytes, level: int = 6, strategy: int = 0):
    co = zlib.compressobj(level, zlib.DEFLATED, -15, 9, strategy)
    comp = co.compress(data) + co.flush()
    toks, nt, ol = _tokenize_one(comp, len(data) + 1)
    assert int(ol[0]) == len(data)
    P = 256
    while P < max(256, len(data)):
        P <<= 1
    out = np.asarray(resolve_tokens(jnp.asarray(toks), jnp.asarray(nt), P))
    assert out[0, : len(data)].tobytes() == data


def _payloads():
    rng = random.Random(3)
    return {
        "empty": b"",
        "one": b"A",
        "text": b"hello deflate world " * 200,
        "random": bytes(rng.randrange(256) for _ in range(50000)),
        "dna": bytes(rng.choice(b"ACGT") for _ in range(60000)),
        "rle_deep": b"A" * 65000,             # dist-1 overlapping copies
        "alternating": b"AB" * 30000,
        "qual": bytes(rng.choice(b"FFFFFF:,#IIII") for _ in range(64000)),
    }


@pytest.mark.parametrize("level", [0, 1, 6, 9])   # 0 = stored blocks
@pytest.mark.parametrize("name", sorted(_payloads()))
def test_token_parity_vs_zlib(name, level):
    _roundtrip(_payloads()[name], level)


@pytest.mark.parametrize("name", ["dna", "rle_deep", "random"])
def test_fixed_huffman_blocks(name):
    _roundtrip(_payloads()[name], 6, zlib.Z_FIXED)


def test_multi_deflate_block_stream():
    rng = random.Random(11)
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    parts, data = [], b""
    for _ in range(5):
        d = bytes(rng.choice(b"ACGTN") for _ in range(8000))
        data += d
        parts.append(co.compress(d))
        parts.append(co.flush(zlib.Z_FULL_FLUSH))
    parts.append(co.flush())
    comp = b"".join(parts)
    toks, nt, ol = _tokenize_one(comp, len(data) + 16)
    assert int(ol[0]) == len(data)
    out = np.asarray(resolve_tokens(jnp.asarray(toks), jnp.asarray(nt),
                                    65536))
    assert out[0, : len(data)].tobytes() == data


def test_bgzf_span_device_matches_host():
    rng = random.Random(7)
    payload = bytes(rng.choice(b"ACGTN!@#qual") for _ in range(300000))
    sink = io.BytesIO()
    w = bgzf.BGZFWriter(sink)
    w.write(payload)
    w.close()
    raw = sink.getvalue()
    host_data, host_ubase = inflate_span(raw, backend="auto")
    dev_data, dev_ubase = inflate_span(raw, backend="device")
    assert np.array_equal(host_data, dev_data)
    assert np.array_equal(host_ubase, dev_ubase)
    assert dev_data.tobytes() == payload


def test_batch_tokenize_many_blocks():
    """Batch API over heterogeneous blocks, strided token rows."""
    rng = random.Random(13)
    datas = [bytes(rng.choice(b"ACGT") for _ in range(rng.randrange(1, 3000)))
             for _ in range(40)]
    comps, offs, lens = [], [], []
    pos = 0
    for d in datas:
        co = zlib.compressobj(rng.choice([1, 6, 9]), zlib.DEFLATED, -15)
        c = co.compress(d) + co.flush()
        comps.append(c)
        offs.append(pos)
        lens.append(len(c))
        pos += len(c)
    src = np.frombuffer(b"".join(comps), np.uint8)
    stride = max(len(d) for d in datas) + 1
    toks, nts, ols = native.deflate_tokenize_batch(
        src, np.array(offs, np.int64), np.array(lens, np.int32), stride)
    assert [int(o) for o in ols] == [len(d) for d in datas]
    P = 4096
    out = np.asarray(resolve_tokens(jnp.asarray(toks), jnp.asarray(nts), P))
    for i, d in enumerate(datas):
        assert out[i, : len(d)].tobytes() == d, f"block {i}"


def test_corrupt_stream_rejected():
    data = b"ACGTN" * 5000
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = bytearray(co.compress(data) + co.flush())
    comp[10] ^= 0xFF
    src = np.frombuffer(bytes(comp), np.uint8)
    with pytest.raises(ValueError):
        native.deflate_tokenize_batch(
            src, np.array([0], np.int64),
            np.array([len(comp)], np.int32), len(data) + 16)


def test_truncated_stream_rejected():
    data = b"ACGTN" * 5000
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = co.compress(data) + co.flush()
    src = np.frombuffer(comp[: len(comp) // 2], np.uint8)
    with pytest.raises(ValueError):
        native.deflate_tokenize_batch(
            src, np.array([0], np.int64),
            np.array([src.size], np.int32), len(data) + 16)


# ---------------------------------------------------------------------------
# round-11: the production device decode plane
# ---------------------------------------------------------------------------

def _bgzf_bytes(payload: bytes) -> bytes:
    sink = io.BytesIO()
    w = bgzf.BGZFWriter(sink)
    w.write(payload)
    w.close()
    return sink.getvalue()


def _bam_fixture(tmp_path, n=3000, seed=11, name="dev.bam"):
    from fixtures import make_header, make_records

    from hadoop_bam_tpu.formats.bamio import write_bam

    h = make_header()
    path = str(tmp_path / name)
    write_bam(path, h, make_records(h, n, seed=seed))
    return path, h


def test_span_device_randomized_split_offsets():
    """Byte identity vs the zlib oracle over BGZF streams whose block
    boundaries land at randomized offsets (mixed tiny/large blocks —
    the shapes real split plans produce)."""
    rng = random.Random(41)
    payload = bytes(rng.choice(b"ACGTNacgtn#!Fqual\t|") for _ in range(150000))
    sink = io.BytesIO()
    w = bgzf.BGZFWriter(sink)
    pos = 0
    while pos < len(payload):
        take = rng.choice([37, 511, 2048, 30000, 65000])
        w.write(payload[pos:pos + take])
        w.flush_block() if hasattr(w, "flush_block") else None
        pos += take
    w.close()
    raw = sink.getvalue()
    host_data, host_ubase = inflate_span(raw, backend="zlib")
    dev_data, dev_ubase = inflate_span_device(raw)
    assert np.array_equal(host_data, dev_data)
    assert np.array_equal(host_ubase, dev_ubase)
    assert dev_data.tobytes() == payload


def test_bcf_and_tabix_shaped_spans_device_identity(tmp_path):
    """The plane is container-agnostic: BCF bytes (binary BGZF) and a
    bgzipped VCF (the tabix container shape) inflate byte-identically
    to the zlib oracle, like the BAM fixtures."""
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord

    hdr_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr20,length=64444167>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="GT">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts0\ts1\n")
    header = VCFHeader.from_text(hdr_text)
    rng = random.Random(5)
    lines = []
    bcf = str(tmp_path / "t.bcf")
    with open_vcf_writer(bcf, header) as w:
        for i in range(500):
            rec = VcfRecord.from_line(
                f"chr20\t{1000 + 7 * i}\t.\tA\tG\t{rng.randint(1, 99)}"
                f"\tPASS\tDP={rng.randint(1, 60)}\tGT"
                f"\t{rng.choice(['0/0', '0/1', '1/1'])}"
                f"\t{rng.choice(['0/0', './.'])}")
            w.write_record(rec)
            lines.append(rec.to_line())
    bcf_raw = open(bcf, "rb").read()
    tabix_raw = _bgzf_bytes((hdr_text + "\n".join(lines) + "\n").encode())
    for raw in (bcf_raw, tabix_raw):
        host_data, host_ubase = inflate_span(raw, backend="zlib")
        dev_data, dev_ubase = inflate_span_device(raw, check_crc=True)
        assert np.array_equal(host_data, dev_data)
        assert np.array_equal(host_ubase, dev_ubase)


def test_byte_flip_fuzz_same_error_class_as_host():
    """Flipping a byte anywhere in the compressed span raises the SAME
    outcome on the device plane as on the zlib host plane: same
    success/failure, BGZFError on both, same taxonomy class."""
    from hadoop_bam_tpu.utils.errors import CORRUPT, classify_error

    rng = random.Random(9)
    payload = bytes(rng.choice(b"ACGT#F!") for _ in range(40000))
    raw = _bgzf_bytes(payload)
    positions = rng.sample(range(len(raw)), 40)
    mismatches = []
    for pos in positions:
        bad = bytearray(raw)
        bad[pos] ^= 0xFF
        bad = bytes(bad)
        outcomes = []
        for run in (lambda: inflate_span(bad, backend="zlib"),
                    lambda: inflate_span_device(bad)):
            try:
                data, _ = run()
                outcomes.append(("ok", data.tobytes()))
            except Exception as e:  # noqa: BLE001 — class comparison
                outcomes.append(("err", isinstance(e, bgzf.BGZFError),
                                 classify_error(e)))
        if outcomes[0] != outcomes[1]:
            mismatches.append((pos, outcomes))
        if outcomes[0][0] == "err":
            assert outcomes[0][2] == CORRUPT
    assert not mismatches, mismatches


def test_crc_flip_only_fails_with_check_crc():
    rng = random.Random(3)
    payload = bytes(rng.choice(b"ACGT") for _ in range(30000))
    raw = _bgzf_bytes(payload)
    from hadoop_bam_tpu.ops.inflate import block_table

    table = block_table(raw)
    # the CRC footer sits 8 bytes before each block's end
    foot = int(table["cdata_off"][0] + table["cdata_len"][0])
    bad = bytearray(raw)
    bad[foot] ^= 0xFF
    bad = bytes(bad)
    data, _ = inflate_span_device(bad)              # fold off: passes
    assert data.tobytes() == payload
    with pytest.raises(bgzf.BGZFError, match="CRC32 mismatch"):
        inflate_span_device(bad, check_crc=True)
    # host parity: the separate verify sweep raises the same class
    from hadoop_bam_tpu.ops.inflate import verify_crcs

    hdata, hubase = inflate_span(bad, backend="zlib")
    with pytest.raises(bgzf.BGZFError, match="CRC32 mismatch"):
        verify_crcs(bad, block_table(bad), hdata, hubase)


def test_native_missing_is_plan_error(monkeypatch):
    """Selecting the device plane without the native tokenizer is a
    configuration fault: PlanError (never retried, never quarantined),
    not a transient or corrupt classification."""
    from hadoop_bam_tpu.utils import errors

    raw = _bgzf_bytes(b"ACGT" * 100)
    monkeypatch.setattr(native, "available", lambda: False)
    with pytest.raises(errors.PlanError) as ei:
        inflate_span_device(raw)
    assert errors.classify_error(ei.value) == errors.PLAN


def test_jit_cache_ladder_pinned():
    """Mixed spans whose max ISIZE wanders within one ladder rung share
    ONE resolve compile; crossing a rung adds exactly one more — the
    per-chunk-pow2 churn the ladder exists to kill."""
    assert ladder_pow2(100) == 1 << 10
    assert ladder_pow2(1024) == 1 << 10
    assert ladder_pow2(1025) == 1 << 13
    assert ladder_pow2(65536) == 1 << 16
    with pytest.raises(bgzf.BGZFError):
        ladder_pow2((1 << 16) + 1)

    rng = random.Random(1)
    before = resolve_tokens_packed._cache_size()
    # three spans, max isize 200 / 600 / 1000 — same rung, same B pad
    for size in (200, 600, 1000):
        payload = bytes(rng.choice(b"ACGT") for _ in range(size))
        inflate_span_device(_bgzf_bytes(payload))
    mid = resolve_tokens_packed._cache_size()
    assert mid - before <= 1, "same-rung spans recompiled the resolve"
    # crossing to the next rung costs exactly one more entry
    payload = bytes(rng.choice(b"ACGT") for _ in range(5000))
    inflate_span_device(_bgzf_bytes(payload))
    after = resolve_tokens_packed._cache_size()
    assert after - mid <= 1


# ---------------------------------------------------------------------------
# the token-feed flagstat driver (resolve + walk + unpack on device)
# ---------------------------------------------------------------------------

def _flagstat(path, **kw):
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file

    return flagstat_file(path, **kw)


def test_flagstat_device_plane_matches_host(tmp_path):
    from hadoop_bam_tpu.config import DEFAULT_CONFIG

    path, _h = _bam_fixture(tmp_path)
    host = _flagstat(path)
    cfg = dataclasses.replace(DEFAULT_CONFIG, inflate_backend="device")
    assert _flagstat(path, config=cfg) == host


def test_flagstat_device_plane_explicit_spans_and_crc(tmp_path):
    """A pinned multi-span plan forces cut-final-record fixups (every
    span boundary cuts a record); parity must hold, with and without
    the tokenize-time CRC fold."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    path, _h = _bam_fixture(tmp_path, n=4000, seed=23)
    host = _flagstat(path)
    hdr, _ = read_bam_header(path)
    spans = plan_spans_cached(path, hdr, DEFAULT_CONFIG, num_spans=6)
    assert len(spans) > 1
    cfg = dataclasses.replace(DEFAULT_CONFIG, inflate_backend="device")
    assert _flagstat(path, config=cfg, spans=spans, header=hdr) == host
    cfg_crc = dataclasses.replace(cfg, check_crc=True)
    assert _flagstat(path, config=cfg_crc, spans=spans, header=hdr) == host


def test_flagstat_device_plane_overwide_span_remainder(tmp_path,
                                                      monkeypatch):
    """A span wider than the 64-block device ladder degrades gracefully:
    the device decodes its first 64 blocks, the host fixup decodes the
    remainder, totals stay exact."""
    monkeypatch.setattr(bgzf, "WRITE_PAYLOAD_SIZE", 2048)
    path, _h = _bam_fixture(tmp_path, n=1500, seed=7, name="tiny.bam")
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.ops.inflate import block_table

    assert block_table(open(path, "rb").read())["isize"].size > 64
    host = _flagstat(path)
    cfg = dataclasses.replace(DEFAULT_CONFIG, inflate_backend="device")
    assert _flagstat(path, config=cfg) == host


def test_flagstat_device_plane_corrupt_chain_same_class(tmp_path):
    """A corrupted record chain (absurd block_size mid-span) raises the
    CORRUPT taxonomy class on BOTH planes."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.inflate import inflate_span as _is, walk_records
    from hadoop_bam_tpu.utils.errors import CORRUPT, classify_error

    path, _h = _bam_fixture(tmp_path, n=800, seed=3, name="chain.bam")
    raw = open(path, "rb").read()
    data, _ub = _is(raw)
    _hdr, voff = read_bam_header(path)
    offs, _tail = walk_records(data, start=voff & 0xFFFF)
    victim = int(offs[len(offs) // 2])
    bad = bytearray(data.tobytes())
    bad[victim:victim + 4] = (5).to_bytes(4, "little")   # block_size 5
    sink = io.BytesIO()
    w = bgzf.BGZFWriter(sink)
    w.write(bytes(bad))
    w.close()
    corrupt_path = str(tmp_path / "corrupt.bam")
    with open(corrupt_path, "wb") as f:
        f.write(sink.getvalue())
    classes = []
    for cfg in (DEFAULT_CONFIG,
                dataclasses.replace(DEFAULT_CONFIG,
                                    inflate_backend="device")):
        with pytest.raises(Exception) as ei:
            _flagstat(corrupt_path, config=cfg)
        classes.append(classify_error(ei.value))
    assert classes == [CORRUPT, CORRUPT]


def test_flagstat_device_plane_requires_native(tmp_path, monkeypatch):
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.utils.errors import PLAN, PlanError, classify_error

    path, _h = _bam_fixture(tmp_path, n=100, seed=1, name="n.bam")
    import hadoop_bam_tpu.utils.native as native_mod

    monkeypatch.setattr(native_mod, "available", lambda: False)
    cfg = dataclasses.replace(DEFAULT_CONFIG, inflate_backend="device")
    with pytest.raises(PlanError) as ei:
        _flagstat(path, config=cfg)
    assert classify_error(ei.value) == PLAN


def test_inflate_backend_knob_and_selector():
    from hadoop_bam_tpu.config import (
        HBamConfig, resolve_inflate_backend,
    )
    from hadoop_bam_tpu.utils.errors import PlanError

    cfg = HBamConfig.from_dict({"hbam.inflate-backend": "device"})
    assert cfg.inflate_backend == "device"
    assert resolve_inflate_backend(cfg) == "device"
    assert resolve_inflate_backend(
        HBamConfig(inflate_backend="zlib")) == "zlib"
    with pytest.raises(PlanError):
        resolve_inflate_backend(HBamConfig(inflate_backend="warp"))
    # "auto" on the CPU backend resolves to the host plane without
    # paying the probe's jit compile (the device cannot beat the host
    # at being the host)
    import jax

    if jax.default_backend() == "cpu":
        assert resolve_inflate_backend(HBamConfig()) == "native"


def test_flagstat_zlib_backend_honored(tmp_path):
    """inflate_backend='zlib' rides the host path with the fused native
    plane disabled — same totals, portable plane."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG

    path, _h = _bam_fixture(tmp_path, n=500, seed=2, name="z.bam")
    host = _flagstat(path)
    cfg = dataclasses.replace(DEFAULT_CONFIG, inflate_backend="zlib")
    assert _flagstat(path, config=cfg) == host


def test_probe_device_plane_reports_measurements():
    from hadoop_bam_tpu.ops.inflate_device import probe_device_plane

    out = probe_device_plane(payload_bytes=1 << 14, force=True)
    assert set(out) >= {"device_wins", "tokenize_s", "resolve_s",
                        "inflate_s", "backend"}
    assert isinstance(out["device_wins"], bool)
    assert out["tokenize_s"] > 0 and out["resolve_s"] > 0
