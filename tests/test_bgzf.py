"""BGZF layer tests — cross-checked against Python's independent gzip module
(BGZF blocks are legal gzip members [SPEC], so gzip.decompress is an oracle
the framework's own code never touches)."""
import gzip
import io
import os
import zlib

import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf


def test_eof_block_is_valid_empty_block():
    info = bgzf.parse_block_header(bgzf.EOF_BLOCK)
    assert info.block_size == 28
    assert info.isize == 0
    assert bgzf.inflate_block(bgzf.EOF_BLOCK, info) == b""


def test_roundtrip_small():
    payload = b"hello bgzf world" * 10
    block = bgzf.deflate_block(payload)
    info = bgzf.parse_block_header(block)
    assert info.block_size == len(block)
    assert bgzf.inflate_block(block, info) == payload
    # independent oracle: gzip can decompress a BGZF member
    assert gzip.decompress(block) == payload


def test_roundtrip_large_multiblock():
    rng = np.random.default_rng(0)
    # mix of compressible and incompressible data, > several blocks
    data = (b"ACGT" * 40000) + rng.integers(0, 256, 200000, dtype=np.uint8).tobytes()
    comp = bgzf.compress_bytes(data)
    assert bgzf.decompress_bytes(comp) == data
    # gzip oracle: concatenated members decompress to the whole payload
    assert gzip.decompress(comp) == data
    blocks = bgzf.scan_blocks(comp)
    assert blocks[-1].is_eof_block
    assert all(b.block_size <= bgzf.MAX_BLOCK_SIZE for b in blocks)


def test_incompressible_payload_still_fits():
    rng = np.random.default_rng(1)
    payload = rng.integers(0, 256, bgzf.WRITE_PAYLOAD_SIZE, dtype=np.uint8).tobytes()
    block = bgzf.deflate_block(payload, level=9)
    assert len(block) <= bgzf.MAX_BLOCK_SIZE
    assert bgzf.inflate_block(block) == payload


def test_crc_validation():
    payload = b"payload under test"
    block = bytearray(bgzf.deflate_block(payload))
    block[-5] ^= 0xFF  # corrupt CRC byte
    with pytest.raises(bgzf.BGZFError):
        bgzf.inflate_block(bytes(block), check_crc=True)


def test_find_block_starts_numpy():
    data = b"x" * 100000
    comp = bgzf.compress_bytes(data)
    truth = [b.coffset for b in bgzf.scan_blocks(comp)]
    cand = bgzf.find_block_starts_numpy(np.frombuffer(comp, dtype=np.uint8))
    # every true block start must be among candidates
    assert set(truth) <= set(cand.tolist())


def test_reader_seek_and_read(tmp_path):
    data = bytes(range(256)) * 1000
    path = tmp_path / "t.bgzf"
    path.write_bytes(bgzf.compress_bytes(data))
    r = bgzf.BGZFReader(str(path), check_crc=True)
    assert r.read_all_from(0) == data
    # voffset round-trip mid-stream
    r.seek_voffset(0)
    r.read(1000)
    v = r.voffset()
    rest = r.read(len(data))
    r.seek_voffset(v)
    assert r.read(len(data)) == rest


def test_writer_voffsets_monotonic():
    sink = io.BytesIO()
    w = bgzf.BGZFWriter(sink)
    vs = []
    for i in range(5000):
        vs.append(w.tell_voffset())
        w.write(b"record%06d" % i)
    w.close()
    assert vs == sorted(vs)
    assert len(set(vs)) == len(vs)
    # each recorded voffset points at its record
    r = bgzf.BGZFReader(sink.getvalue())
    for i in [0, 1, 4999, 2500]:
        r.seek_voffset(vs[i])
        assert r.read(12) == b"record%06d" % i


def test_is_bgzf():
    assert bgzf.is_bgzf(bgzf.compress_bytes(b"abc"))
    assert not bgzf.is_bgzf(gzip.compress(b"abc"))  # plain gzip: no BC subfield
    assert not bgzf.is_bgzf(b"plain text here....")
