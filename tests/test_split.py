"""Split machinery tests — the reference's core test idea (SURVEY.md section
4): place split boundaries at adversarial offsets and assert the union of all
spans yields each record exactly once."""
import io
import random

import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam
from hadoop_bam_tpu.split.bam_guesser import BAMSplitGuesser
from hadoop_bam_tpu.split.bgzf_guesser import BGZFSplitGuesser
from hadoop_bam_tpu.split.planners import (
    plan_bam_spans, plan_text_spans, read_bam_span, read_text_span,
)
from hadoop_bam_tpu.split.spans import FileByteSpan
from hadoop_bam_tpu.split.splitting_index import (
    SplittingIndex, build_splitting_index, write_splitting_index,
)

from fixtures import make_header, make_records


@pytest.fixture(scope="module")
def bam_file(tmp_path_factory):
    """A multi-block BAM with known per-record virtual offsets."""
    path = str(tmp_path_factory.mktemp("bam") / "fixture.bam")
    header = make_header()
    records = make_records(header, 3000, seed=42)
    with BamWriter(path, header, track_voffsets=True) as w:
        for r in records:
            w.write_sam_record(r)
        voffs = list(w.record_voffsets())
    return path, header, records, voffs


def test_bgzf_guesser_every_offset(bam_file):
    path, *_ = bam_file
    data = open(path, "rb").read()
    truth = [b.coffset for b in bgzf.scan_blocks(data)]
    g = BGZFSplitGuesser(data)
    # every byte offset in the first 2 blocks + around every block boundary
    offsets = set(range(0, truth[1] if len(truth) > 1 else len(data)))
    for t in truth:
        offsets.update(range(max(0, t - 3), min(len(data), t + 4)))
    for off in sorted(offsets):
        expect = next((t for t in truth if t >= off), None)
        got = g.guess_next_block_start(off)
        assert got == expect, f"offset {off}: got {got}, want {expect}"


def test_bam_guesser_samples(bam_file):
    path, header, records, voffs = bam_file
    data = open(path, "rb").read()
    block_starts = [b.coffset for b in bgzf.scan_blocks(data)]
    g = BAMSplitGuesser(data, header)

    def expected_for(offset):
        # first record whose containing block starts at-or-after offset
        bs = next((t for t in block_starts if t >= offset), None)
        if bs is None:
            return None
        return next((v for v in voffs if (v >> 16) >= bs), None)

    offsets = set(range(0, 400))                        # dense at file head
    offsets.update(range(0, len(data), 997))            # stride sample
    for t in block_starts:                              # block boundaries
        offsets.update((max(0, t - 2), t, t + 1, t + 2))
    for off in sorted(o for o in offsets if o < len(data)):
        got = g.guess_next_record_start(off)
        assert got == expected_for(off), f"offset {off}"


def test_splitting_index_build_and_roundtrip(bam_file, tmp_path):
    path, header, records, voffs = bam_file
    gran = 100
    idx = build_splitting_index(path, granularity=gran)
    assert idx.total_records == len(records)
    assert idx.voffsets[:-1] == voffs[::gran]
    assert idx.end_voffset == len(open(path, "rb").read()) << 16

    legacy = SplittingIndex.from_bytes(idx.to_splitting_bai_bytes())
    assert legacy.voffsets == idx.voffsets
    sbi = SplittingIndex.from_bytes(idx.to_sbi_bytes(12345))
    assert sbi.voffsets == idx.voffsets
    assert sbi.granularity == gran
    assert sbi.total_records == len(records)


@pytest.mark.parametrize("num_spans", [1, 2, 7, 16, 64])
@pytest.mark.parametrize("use_index", [False, True])
def test_span_union_exactly_once(bam_file, tmp_path, num_spans, use_index):
    """THE split-robustness property: union over spans == every record once."""
    path, header, records, voffs = bam_file
    index = build_splitting_index(path, granularity=16) if use_index else None
    spans = plan_bam_spans(path, num_spans=num_spans, index=index,
                           header=header)
    got_voffs = []
    got_names = []
    for span in spans:
        batch = read_bam_span(path, span, header=header)
        got_voffs.extend(int(v) for v in batch.voffsets)
        got_names.extend(batch.read_name(i) for i in range(len(batch)))
    assert got_voffs == voffs
    assert got_names == [r.qname for r in records]


@pytest.mark.parametrize("num_spans", [2, 8, 64])
def test_plan_balanced_saturates(bam_file, num_spans):
    """Record-balanced planning cuts inside BGZF blocks so every span gets
    near-equal record counts — no idle devices on small files."""
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    path, header, records, voffs = bam_file
    spans = plan_bam_spans_balanced(path, num_spans, header=header)
    assert len(spans) == num_spans
    counts, got_voffs = [], []
    for span in spans:
        batch = read_bam_span(path, span, header=header)
        counts.append(len(batch))
        got_voffs.extend(int(v) for v in batch.voffsets)
    assert got_voffs == voffs                       # exactly-once union
    assert min(counts) > 0
    assert max(counts) - min(counts) <= len(records) // num_spans + 1


def test_plan_balanced_respects_sidecar_granularity(bam_file):
    """With a coarse index provided, boundaries land on sampled voffsets."""
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    path, header, records, voffs = bam_file
    idx = build_splitting_index(path, granularity=100)
    spans = plan_bam_spans_balanced(path, 8, header=header, index=idx)
    sampled = set(idx.voffsets)
    for s in spans:
        assert s.start_voffset in sampled
    got = []
    for span in spans:
        got.extend(int(v) for v in read_bam_span(path, span,
                                                 header=header).voffsets)
    assert got == voffs


def test_plan_respects_sidecar(bam_file, tmp_path):
    path, header, records, voffs = bam_file
    sidecar = write_splitting_index(path, granularity=50)
    loaded = SplittingIndex.load_for(path)
    assert loaded is not None
    spans = plan_bam_spans(path, num_spans=8, header=header)
    # all interior boundaries must be sampled record voffsets
    sampled = set(loaded.voffsets)
    for s in spans[1:]:
        assert s.start_voffset in sampled
    import os
    os.remove(sidecar)


def test_text_span_every_offset(tmp_path):
    lines = [f"line{i:04d}|{'x' * (i % 37)}\n".encode() for i in range(200)]
    data = b"".join(lines)
    path = tmp_path / "t.txt"
    path.write_bytes(data)
    # 2-way partition at EVERY byte offset
    for cut in range(0, len(data) + 1, 1):
        a = read_text_span(data, FileByteSpan("t", 0, cut))
        b = read_text_span(data, FileByteSpan("t", cut, len(data)))
        assert a + b == data, f"cut at {cut}"
    # random 5-way partitions
    rng = random.Random(0)
    for _ in range(50):
        cuts = sorted(rng.randrange(len(data) + 1) for _ in range(4))
        bounds = [0] + cuts + [len(data)]
        parts = [read_text_span(data, FileByteSpan("t", bounds[i], bounds[i + 1]))
                 for i in range(5)]
        assert b"".join(parts) == data


def test_index_on_write_matches_posthoc(tmp_path):
    """BamWriter(index_granularity=N) emits the same sidecar the standalone
    indexer builds after the fact (hb/SplittingBAMIndexer MR-integrated
    mode vs main())."""
    from fixtures import make_header, make_records
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.splitting_index import (
        SplittingIndex, build_splitting_index,
    )

    header = make_header()
    records = make_records(header, 1000, seed=3)
    path = str(tmp_path / "iw.bam")
    with BamWriter(path, header, index_granularity=64) as w:
        for r in records:
            w.write_sam_record(r)
    sidecar = path + ".splitting-bai"
    import os
    assert os.path.exists(sidecar)
    got = SplittingIndex.from_bytes(open(sidecar, "rb").read())
    ref = build_splitting_index(path, granularity=64)
    assert list(got.voffsets) == list(ref.voffsets)

    # sbi flavor round-trips too
    path2 = str(tmp_path / "iw2.bam")
    with BamWriter(path2, header, index_granularity=64,
                   index_flavor="sbi") as w:
        for r in records:
            w.write_sam_record(r)
    assert os.path.exists(path2 + ".sbi")


def test_plan_spans_cached_semantics(tmp_path):
    """The getSplits()-once cache: identical request -> same plan without
    re-guessing; rewriting the file invalidates; returned lists are
    copies (caller mutation cannot poison the cache)."""
    import os

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam_header
    from hadoop_bam_tpu.split.planners import (
        plan_spans_cached, plan_spans_maybe_intervals,
    )

    header = make_header()
    path = str(tmp_path / "c.bam")
    with BamWriter(path, header) as w:
        for r in make_records(header, 800, seed=4):
            w.write_sam_record(r)
    hdr, _ = read_bam_header(path)
    fresh = plan_spans_maybe_intervals(path, hdr, DEFAULT_CONFIG,
                                       num_spans=4)
    a = plan_spans_cached(path, hdr, DEFAULT_CONFIG, num_spans=4)
    assert [s.to_dict() for s in a] == [s.to_dict() for s in fresh]
    a.clear()                               # must not poison the cache
    b = plan_spans_cached(path, hdr, DEFAULT_CONFIG, num_spans=4)
    assert [s.to_dict() for s in b] == [s.to_dict() for s in fresh]
    # a different request is a different key
    c = plan_spans_cached(path, hdr, DEFAULT_CONFIG, num_spans=2)
    assert len(c) <= len(b)

    # rewrite -> invalidated (size/mtime key)
    with BamWriter(path, header) as w:
        for r in make_records(header, 100, seed=5):
            w.write_sam_record(r)
    os.utime(path)                          # ensure the mtime moves
    hdr2, _ = read_bam_header(path)
    d = plan_spans_cached(path, hdr2, DEFAULT_CONFIG, num_spans=4)
    fresh2 = plan_spans_maybe_intervals(path, hdr2, DEFAULT_CONFIG,
                                        num_spans=4)
    assert [s.to_dict() for s in d] == [s.to_dict() for s in fresh2]
