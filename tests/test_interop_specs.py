"""Externally-derived interop fixtures (VERDICT r3 item #5).

Every byte literal in this file was transcribed or hand-derived from the
PUBLIC hts-specs documents (SAMv1.pdf, VCFv4.3.pdf, CRAMv3.pdf) — NOT
produced by this repo's encoders — so these tests break the
self-referential golden loop: they pin the codecs against the published
wire formats themselves.  Each literal's derivation is spelled out next
to it so an auditor can re-check it against the spec text without
running any code.

Families covered: BGZF (the spec's published EOF literal), BAM (the
SAMv1 section 1.1 example read r001 hand-encoded via section 4.2's
layout), the binning scheme (clean-room port of the section 5.3 C
code), BCF2 typed values + a hand-built record (VCFv4.3 section 6.3),
and CRAM ITF8/LTF8 vectors (CRAMv3 section 2.3).
"""
import struct

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# BGZF: the EOF marker is published byte-for-byte in SAMv1 section 4.1.2
# ---------------------------------------------------------------------------

# [SPEC-transcribed] SAMv1 4.1.2: "The absence of a final block with
# SLEN=0 ... an end-of-file marker":
SPEC_BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")


def test_bgzf_eof_literal_matches_spec():
    from hadoop_bam_tpu.formats import bgzf

    assert bgzf.EOF_BLOCK == SPEC_BGZF_EOF
    info = bgzf.parse_block_header(SPEC_BGZF_EOF)
    assert info.block_size == len(SPEC_BGZF_EOF) == 28
    assert info.isize == 0
    assert bgzf.inflate_block(SPEC_BGZF_EOF) == b""


def test_bgzf_header_magic_fields():
    """SAMv1 4.1: ID1=31, ID2=139, CM=8, FLG=4, XLEN>=6, SI1=66, SI2=67,
    SLEN=2 — asserted on the spec's own EOF literal."""
    b = SPEC_BGZF_EOF
    assert (b[0], b[1], b[2], b[3]) == (31, 139, 8, 4)
    xlen = struct.unpack_from("<H", b, 10)[0]
    assert xlen == 6
    assert (b[12], b[13]) == (66, 67)                  # 'B', 'C'
    assert struct.unpack_from("<H", b, 14)[0] == 2     # SLEN
    assert struct.unpack_from("<H", b, 16)[0] == 27    # BSIZE-1


# ---------------------------------------------------------------------------
# BAM record wire: SAMv1 section 1.1's first example alignment, encoded by
# hand following the section 4.2 layout table.
#
#   r001  99  ref  7  30  8M2I4M1D3M  =  37  39  TTAGATAAAGGATACTG  *
#
# Field derivation (every value computed from the spec text, not code):
#   block_size  = 32 fixed + 5 name + 20 cigar + 9 seq + 17 qual = 83
#   refID       = 0,   pos = 7-1 = 6 (0-based)
#   l_read_name = len("r001")+NUL = 5,  MAPQ = 30
#   bin         = reg2bin(6, 22): CIGAR consumes 8M+4M+1D+3M = 16 ref
#                 bases, so [beg,end) = [6,22); 6>>14 == 21>>14 == 0
#                 -> 4681 + 0 = 4681 = 0x1249 (section 5.3)
#   n_cigar_op  = 5,  FLAG = 99,  l_seq = 17
#   next_refID  = 0 ('='),  next_pos = 37-1 = 36,  tlen = 39
#   CIGAR uint32s (op_len<<4|op; MIDNSHP=X -> 0..8):
#       8M=0x80  2I=0x21  4M=0x40  1D=0x12  3M=0x30
#   SEQ nibbles ('=ACMGRSVTWYHKDBN' -> 0..15): T=8 A=1 G=4 C=2, pairs
#       TT AG AT AA AG GA TA CT G. -> 88 14 18 11 14 41 81 28 40
#   QUAL '*'    = 17 bytes of 0xFF (section 4.2.3)
# ---------------------------------------------------------------------------

SPEC_BAM_R001 = (
    struct.pack("<i", 83)
    + struct.pack("<iiBBHHHiiii",
                  0, 6, 5, 30, 0x1249, 5, 99, 17, 0, 36, 39)
    + b"r001\x00"
    + bytes.fromhex("80000000" "21000000" "40000000" "12000000" "30000000")
    + bytes.fromhex("881418111441812840")
    + b"\xff" * 17
)


def _r001_header():
    from hadoop_bam_tpu.formats.bam import SAMHeader

    return SAMHeader.from_sam_text("@HD\tVN:1.6\n@SQ\tSN:ref\tLN:45\n")


def test_bam_spec_example_decodes_field_by_field():
    from hadoop_bam_tpu.formats.bam import BamBatch, walk_record_offsets

    data = np.frombuffer(SPEC_BAM_R001, dtype=np.uint8)
    offs = walk_record_offsets(data)
    assert offs.size == 1
    b = BamBatch(data, offs, header=_r001_header())
    assert b.read_name(0) == "r001"
    assert int(b.flag[0]) == 99
    assert int(b.refid[0]) == 0
    assert int(b.pos[0]) == 6
    assert int(b.mapq[0]) == 30
    assert int(b.bin[0]) == 4681
    assert b.cigar_string(0) == "8M2I4M1D3M"
    assert int(b.mate_refid[0]) == 0
    assert int(b.mate_pos[0]) == 36
    assert int(b.tlen[0]) == 39
    assert b.seq_string(0) == "TTAGATAAAGGATACTG"
    assert b.to_sam_line(0) == ("r001\t99\tref\t7\t30\t8M2I4M1D3M\t=\t37\t"
                                "39\tTTAGATAAAGGATACTG\t*")


def test_bam_spec_example_encodes_byte_identical():
    """The encoder must reproduce the hand-derived spec bytes exactly."""
    from hadoop_bam_tpu.formats.bam import encode_record

    enc = encode_record(
        name="r001", flag=99, refid=0, pos=6, mapq=30,
        cigar=[(8, "M"), (2, "I"), (4, "M"), (1, "D"), (3, "M")],
        mate_refid=0, mate_pos=36, tlen=39,
        seq="TTAGATAAAGGATACTG", qual="*")
    assert enc == SPEC_BAM_R001


def _spec_reg2bin(beg: int, end: int) -> int:
    """Clean-room transcription of SAMv1 section 5.3's C function
    reg2bin(), used as an independent oracle for ours."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def test_reg2bin_against_spec_oracle():
    from hadoop_bam_tpu.formats.bam import reg2bin

    # level anchors from the scheme: leaf bins start at 4681, 16 KiB wide
    assert reg2bin(0, 1) == 4681
    assert reg2bin(1 << 14, (1 << 14) + 1) == 4682
    assert reg2bin(0, (1 << 29)) == 0      # whole-chromosome -> root bin
    rng = np.random.default_rng(7)
    for _ in range(500):
        beg = int(rng.integers(0, 1 << 29))
        end = beg + int(rng.integers(1, 1 << 20))
        assert reg2bin(beg, end) == _spec_reg2bin(beg, end)
    # boundary sweep: intervals straddling every level's tile edges
    for shift in (14, 17, 20, 23, 26):
        edge = 1 << shift
        for beg, end in ((edge - 1, edge + 1), (edge, edge + 1),
                         (edge - 1, edge)):
            assert reg2bin(beg, end) == _spec_reg2bin(beg, end)


# ---------------------------------------------------------------------------
# BCF2 typed values: VCFv4.3 section 6.3.3.  Descriptor byte is
# (count<<4)|type with types 1/2/3=int8/16/32, 5=float, 7=char; int8
# MISSING=0x80, END_OF_VECTOR=0x81; counts >= 15 overflow into a
# following typed int.
# ---------------------------------------------------------------------------

def test_bcf_typed_atoms_match_spec_literals():
    from hadoop_bam_tpu.formats.bcf import (
        encode_typed_int_scalar, encode_typed_ints, encode_typed_string,
        read_typed,
    )

    # scalar 1 -> int8: descriptor 0x11, payload 0x01
    assert encode_typed_int_scalar(1) == b"\x11\x01"
    # 300 needs int16: descriptor 0x12, LE payload 0x2c 0x01
    assert encode_typed_int_scalar(300) == b"\x12\x2c\x01"
    # 70000 needs int32: descriptor 0x13
    assert encode_typed_int_scalar(70000) == b"\x13" + struct.pack(
        "<i", 70000)
    # "PASS" -> descriptor (4<<4)|7 = 0x47 + ASCII
    assert encode_typed_string("PASS") == b"\x47PASS"
    # [3, None] -> int8 vector with MISSING sentinel 0x80
    assert encode_typed_ints([3, None]) == b"\x21\x03\x80"
    # padding uses END_OF_VECTOR 0x81
    assert encode_typed_ints([3], pad_to=2) == b"\x21\x03\x81"
    # count 15 overflows: descriptor 0xF1 + typed count + 16 payload bytes
    enc = encode_typed_ints([1] * 16)
    assert enc[:3] == b"\xf1\x11\x10"
    # decode direction on a spec-shaped literal: 2 x int16 [256, -1]
    typ, vals, off = read_typed(b"\x22\x00\x01\xff\xff", 0)
    assert vals == [256, -1] and off == 5


def test_bcf_hand_built_record_decodes():
    """A complete BCF2 record assembled by hand from the section 6.3
    layout table (l_shared/l_indiv, CHROM/POS/rlen/QUAL, packed counts,
    typed site fields, typed genotype block), then decoded by the codec.

    Site: chr1:100 rs1 A->C qual 30, FILTER PASS, INFO DP=7,
    one sample with GT 0/1.
    String dictionary [SPEC 6.2.1]: PASS=0, then DP=1, GT=2 (order of
    appearance); contig dictionary: chr1=0.
    """
    from hadoop_bam_tpu.formats.bcf import BCFRecordCodec
    from hadoop_bam_tpu.formats.vcf import VCFHeader

    shared = (
        struct.pack("<iii", 0, 99, 1)        # CHROM=0, POS0=99, rlen=1
        + struct.pack("<f", 30.0)            # QUAL
        + struct.pack("<HH", 1, 2)           # n_info=1 | n_allele=2
        + struct.pack("<I", (1 << 24) | 1)   # n_fmt=1 | n_sample=1
        + b"\x37rs1"                         # ID: 3 chars
        + b"\x17A" + b"\x17C"                # REF, ALT alleles
        + b"\x11\x00"                        # FILTER: [PASS=0]
        + b"\x11\x01" + b"\x11\x07"          # INFO: key DP=1, value 7
    )
    indiv = (
        b"\x11\x02"                          # FORMAT key GT=2
        + b"\x21\x02\x04"                    # 2 x int8/sample: 0/1 ->
    )                                        # (0+1)<<1=2, (1+1)<<1=4
    rec_bytes = struct.pack("<II", len(shared), len(indiv)) + shared + indiv

    header = VCFHeader.from_text(
        "##fileformat=VCFv4.3\n"
        "##contig=<ID=chr1,length=1000>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\n")
    assert header.string_dictionary()[:3] == ["PASS", "DP", "GT"]

    codec = BCFRecordCodec(header)
    rec, off = codec.decode(rec_bytes)
    assert off == len(rec_bytes)
    assert rec.chrom == "chr1"
    assert rec.pos == 100                    # 1-based in VCF terms
    assert rec.id == "rs1"
    assert rec.ref == "A"
    assert rec.alts == ("C",)
    assert rec.qual == 30.0
    assert rec.filters == ("PASS",)
    assert rec.info.get("DP") in (7, "7")
    assert rec.fmt == ("GT",)
    assert rec.genotypes == ["0/1"]


# ---------------------------------------------------------------------------
# CRAM ITF8 / LTF8: CRAMv3 section 2.3.  The leading bits of the first
# byte give the byte count; the 5-byte ITF8 form keeps only the LOW 4
# bits of the final byte.  Vectors hand-derived from those rules.
# ---------------------------------------------------------------------------

ITF8_VECTORS = [
    (0, "00"),
    (1, "01"),
    (127, "7f"),                    # largest 1-byte value (7 bits)
    (128, "8080"),                  # 0x80|(v>>8), v&0xff
    (16383, "bfff"),                # largest 2-byte value (14 bits)
    (16384, "c04000"),              # 0xc0|(v>>16), ...
    (2097151, "dfffff"),            # largest 3-byte value (21 bits)
    (2097152, "e0200000"),
    (268435455, "efffffff"),        # largest 4-byte value (28 bits)
    (268435456, "f100000000"),      # 5-byte form: low nibble of last byte
    (-1, "ffffffff0f"),             # 0xffffffff via the 5-byte quirk
]

LTF8_VECTORS = [
    (0, "00"),
    (127, "7f"),
    (128, "8080"),
    (1 << 14, "c04000"),            # 16384 -> 0xc0|(v>>16), 0x40, 0x00
    ((1 << 56) - 1, "fe" + "ff" * 7),
    (-1, "ff" + "ff" * 8),          # 64-bit -1: 9 bytes, all set
]


@pytest.mark.parametrize("value,hexbytes", ITF8_VECTORS)
def test_itf8_spec_vectors(value, hexbytes):
    from hadoop_bam_tpu.formats.cram import read_itf8, write_itf8

    raw = bytes.fromhex(hexbytes)
    assert write_itf8(value) == raw
    got, pos = read_itf8(raw, 0)
    assert got == value and pos == len(raw)


@pytest.mark.parametrize("value,hexbytes", LTF8_VECTORS)
def test_ltf8_spec_vectors(value, hexbytes):
    from hadoop_bam_tpu.formats.cram import read_ltf8, write_ltf8

    raw = bytes.fromhex(hexbytes)
    assert write_ltf8(value) == raw
    got, pos = read_ltf8(raw, 0)
    assert got == value and pos == len(raw)


# ---------------------------------------------------------------------------
# CRAM 3.1 codecs (CRAMcodecs spec): what CAN be externally pinned is —
# derived by hand below, independent of this repo's encoders.  What
# CANNOT be pinned without htscodecs output is listed in
# test_cram31_divergence_notes so the gap is explicit, not implied.
# ---------------------------------------------------------------------------

def test_uint7_varint_spec_vectors():
    """[SPEC-derived] CRAMcodecs: sizes are 'uint7' varints — big-endian
    7-bit groups, high bit = continuation.  Vectors computed by hand
    from that definition alone:
      0       -> 00
      127     -> 7f
      128     -> 81 00        (0b1  0000000)
      1000    -> 87 68        (0b0000111 1101000)
      16384   -> 81 80 00     (0b1 0000000 0000000)
      2^32-1  -> 8f ff ff ff 7f
    """
    from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
        var_get_u32, var_put_u32,
    )

    vectors = [
        (0, "00"), (127, "7f"), (128, "8100"), (1000, "8768"),
        (16384, "818000"), ((1 << 32) - 1, "8fffffff7f"),
    ]
    for value, hexs in vectors:
        assert var_put_u32(value) == bytes.fromhex(hexs), value
        got, used = var_get_u32(bytes.fromhex(hexs), 0)
        assert (got, used) == (value, len(hexs) // 2)


def test_rans_nx16_constants_and_constant_stream_states():
    """[SPEC-derived] rANS Nx16 state machine: 16-bit renormalization
    with lower bound 2^15 and a 12-bit default frequency shift.  For a
    single-symbol alphabet the normalized frequency is the full 4096,
    so the encode step
        x' = ((x // f) << 12) + (x % f) + cum   (f=4096, cum=0)
    is the identity: every state stays at the 2^15 initial bound and the
    stream's state section must be exactly N little-endian u32 0x8000
    words, independent of payload length — hand-derivable with no
    encoder in the loop."""
    import struct

    from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
        RANS_LOW_16, _encode_order0_core,
    )

    assert RANS_LOW_16 == 1 << 15
    for n in (4, 100):
        stream = _encode_order0_core(b"A" * n, N=4)
        # state section = last 16 bytes (no renorm words can follow:
        # states never exceeded the bound, so none were emitted)
        states = struct.unpack("<4I", stream[-16:])
        assert states == (0x8000, 0x8000, 0x8000, 0x8000)


def _rans_nx16_reference_decode_order0(buf, out_size, N=4, shift=12):
    """Clean-room scalar transcription of the CRAMcodecs rANS Nx16
    order-0 decode loop (state machine as published: slot = x & mask;
    x = f*(x>>shift) + slot - cum; renorm one u16 LE word when
    x < 2^15), sharing ONLY the table parser with the implementation
    under test — an independent check of the entropy core."""
    import struct

    from hadoop_bam_tpu.formats.cram_codecs_nx16 import _read_freqs_nx16

    freqs, pos = _read_freqs_nx16(buf, 0, shift)
    cum = [0] * 257
    for s in range(256):
        cum[s + 1] = cum[s] + int(freqs[s])
    slot2sym = bytearray(1 << shift)
    for s in range(256):
        for k in range(cum[s], cum[s + 1]):
            slot2sym[k] = s
    states = list(struct.unpack_from(f"<{N}I", buf, pos))
    pos += 4 * N
    out = bytearray()
    mask = (1 << shift) - 1
    for i in range(out_size):
        x = states[i % N]
        slot = x & mask
        s = slot2sym[slot]
        out.append(s)
        x = int(freqs[s]) * (x >> shift) + slot - cum[s]
        if x < (1 << 15):
            x = (x << 16) | struct.unpack_from("<H", buf, pos)[0]
            pos += 2
        states[i % N] = x
    return bytes(out)


def test_rans_nx16_order0_against_independent_decoder():
    import random

    from hadoop_bam_tpu.formats.cram_codecs_nx16 import _encode_order0_core

    rng = random.Random(17)
    for n in (64, 1000, 4097):
        data = bytes(rng.choice(b"ACGTN!") for _ in range(n))
        stream = _encode_order0_core(data, N=4)
        assert _rans_nx16_reference_decode_order0(stream, n) == data


def _range_coder_reference_decode(buf, schedule):
    """Clean-room transcription of the CRAM 3.1 adaptive coders' range
    decoder (LZMA-style carry coder as published: skip the first cache
    byte, 32-bit code/range, 24-bit renormalization), driven by a FIXED
    (cum, freq, tot) schedule so no adaptive-model constants are in the
    loop — pins the coder arithmetic alone."""
    pos = 1
    code = int.from_bytes(buf[pos:pos + 4], "big")
    pos += 4
    rng = 0xFFFFFFFF
    out = []
    for cum_freq_tot in schedule:
        cum, freq, tot = cum_freq_tot
        rng //= tot
        f = code // rng
        out.append(f)
        code -= cum * rng
        rng *= freq
        while rng < (1 << 24):
            rng <<= 8
            b = buf[pos] if pos < len(buf) else 0
            code = ((code << 8) | b) & 0xFFFFFFFF
            pos += 1
    return out


def test_range_coder_against_independent_decoder():
    """The fqzcomp/arith range ENCODER's output decodes under the
    independent transcription above, for a fixed frequency table
    (A:60%, B:30%, C:10% of 1000) over a pseudo-random symbol stream."""
    import random

    from hadoop_bam_tpu.formats.cram_fqzcomp import RangeEncoder

    cumfreq = {0: (0, 600), 1: (600, 300), 2: (900, 100)}
    rng = random.Random(23)
    syms = [rng.choices([0, 1, 2], weights=[6, 3, 1])[0]
            for _ in range(2000)]
    enc = RangeEncoder()
    for s in syms:
        cum, freq = cumfreq[s]
        enc.encode(cum, freq, 1000)
    stream = enc.finish()

    schedule = [(cumfreq[s][0], cumfreq[s][1], 1000) for s in syms]
    got = _range_coder_reference_decode(stream, schedule)
    # the reference decoder returns the slot value f in [0, tot); map
    # back to symbols via the cumulative table
    decoded = []
    for f in got:
        decoded.append(0 if f < 600 else (1 if f < 900 else 2))
    assert decoded == syms


def test_cram31_divergence_notes():
    """The honest ledger (VERDICT r4 #5): constants and layouts that
    remain [SPEC-recalled] — reconstructed from knowledge of the public
    htscodecs library, validated ONLY by same-module round-trips plus
    the independent state-machine checks above, because no htscodecs
    build exists in this environment to emit reference bytes.  Each has
    a loud failure mode rather than silent corruption:

    - rANS Nx16 PACK/RLE/STRIPE *metadata* byte layouts
      (cram_codecs_nx16.py): a mismatch fails table parsing or the
      final size check, never silently.
    - tok3 frame header field order (cram_name_tok3.py): mismatch
      raises Tok3Error; 3.1 writes can pin names to GZIP via
      HBAM_CRAM31_NAMES=gzip.
    - fqzcomp adaptive-model constants MODEL_STEP=8 and rescale bound
      2^16-8 (cram_fqzcomp.py): a mismatch desyncs the range coder —
      guarded by the decode-time per-record-length tripwire
      (check_fqz_rec_lens), which raises CRAMError instead of
      returning wrong qualities.
    - arith RLE run-model arrangement (cram_arith.py): 3-deep
      256-symbol model chain with 255-extension; mismatch fails the
      output-size check.

    This test pins the *documented shape* of those fallbacks so a
    refactor cannot silently drop a guard."""
    from hadoop_bam_tpu.formats import cram_fqzcomp
    from hadoop_bam_tpu.formats.cram_arith import _RUN_CTXS
    from hadoop_bam_tpu.formats.cram_decode import check_fqz_rec_lens

    assert cram_fqzcomp.MODEL_STEP == 8
    assert cram_fqzcomp.MODEL_MAX_TOTAL == (1 << 16) - 8
    assert _RUN_CTXS == 3
    assert callable(check_fqz_rec_lens)
    # the gzip escape hatch for interop-critical 3.1 name blocks exists
    import pathlib

    import hadoop_bam_tpu.formats.cram_encode as ce
    assert "HBAM_CRAM31_NAMES" in pathlib.Path(ce.__file__).read_text()


# ---------------------------------------------------------------------------
# CRAM 3.1 WRITER frames through independent clean-room decoders
# (VERDICT r5 missing #4): bytes produced by this repo's 3.1 encoders
# (cram_encode's bulk-series codec, cram_name_tok3, cram_fqzcomp,
# cram_arith) decoded by transcriptions that share NO decode code with
# the implementation — one test per codec.  A failure here is a
# DIVERGENCE-LEDGER event: record it in test_cram31_divergence_notes
# (and fix the constant) rather than papering over it, because these
# oracles re-derive the published algorithms from the spec text alone.
# ---------------------------------------------------------------------------

def _uint7_get(buf: bytes, pos: int):
    """[SPEC-derived] uint7 varint: big-endian 7-bit groups, high bit =
    continuation (independent of cram_codecs_nx16.var_get_u32)."""
    v = 0
    while True:
        b = buf[pos]
        pos += 1
        v = (v << 7) | (b & 0x7F)
        if not b & 0x80:
            return v, pos


def _oracle_nx16_payload(payload: bytes) -> bytes:
    """Decode one FRAMED rANS Nx16 stream (flag byte + uint7 size +
    payload) via the independent order-0 state-machine decoder above.
    Only the shapes this repo's encoder emits for small/plain inputs are
    accepted: CAT (0x20) and order-0; anything else means the fixture
    drifted and the test should be rewritten, not silently skipped."""
    flags = payload[0]
    pos = 1
    assert not flags & 0x10, "NOSZ frame needs an external size"
    size, pos = _uint7_get(payload, pos)
    if flags & 0x20:                         # CAT: stored bytes
        assert len(payload) - pos == size
        return payload[pos:pos + size]
    assert flags & ~0x20 == 0, f"unexpected Nx16 flags 0x{flags:02x}"
    return _rans_nx16_reference_decode_order0(payload[pos:], size)


def test_cram31_rans_nx16_written_frames_decode_via_oracle():
    """cram_encode.py's 3.1 bulk-series codec (rans_nx16_encode, plain
    order-0 frame) must decode under the independent state-machine
    transcription — including the frame header (flag byte + uint7 size)
    parsed by spec-derived rules alone."""
    import random

    from hadoop_bam_tpu.formats.cram_codecs_nx16 import rans_nx16_encode

    rng = random.Random(41)
    # BAM-flavoured byte series: qualities, flags, small ints
    for data in (bytes(rng.choice(b"!#$%&'()*+,-.") for _ in range(4096)),
                 bytes(rng.randrange(4) for _ in range(1000)),
                 b"Q" * 500):
        payload = rans_nx16_encode(data, 0)
        assert _oracle_nx16_payload(payload) == data


class _OracleRangeDecoder:
    """Clean-room incremental transcription of the CRAM 3.1 adaptive
    coders' LZMA-style range decoder (skip the initial cache byte,
    32-bit big-endian code, 24-bit renormalization) — the stateful twin
    of _range_coder_reference_decode above, shared by the fqzcomp and
    arith oracles."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos + 1                   # skip the cache byte
        self.code = int.from_bytes(buf[self.pos:self.pos + 4], "big")
        self.pos += 4
        self.range = 0xFFFFFFFF

    def get_freq(self, tot: int) -> int:
        self.range //= tot
        return self.code // self.range

    def advance(self, cum: int, freq: int) -> None:
        self.code -= cum * self.range
        self.range *= freq
        while self.range < (1 << 24):
            self.range <<= 8
            b = self.buf[self.pos] if self.pos < len(self.buf) else 0
            self.code = ((self.code << 8) | b) & 0xFFFFFFFF
            self.pos += 1


class _OracleAdaptiveModel:
    """Clean-room transcription of the published fqzcomp adaptive
    frequency model: all symbols start at frequency 1, a used symbol
    bumps by 8, totals rescale at 2^16-8 (each freq loses its own half,
    f -= f>>1), and a used symbol swaps one slot toward the front when
    it overtakes its neighbour.  The constants are the [SPEC-recalled]
    ones the divergence ledger pins — a mismatch desyncs here loudly."""

    STEP = 8
    MAX_TOTAL = (1 << 16) - 8

    def __init__(self, nsym: int):
        self.total = nsym
        self.freqs = [1] * nsym
        self.syms = list(range(nsym))

    def decode(self, rc: _OracleRangeDecoder) -> int:
        f = rc.get_freq(self.total)
        acc = i = 0
        while acc + self.freqs[i] <= f:
            acc += self.freqs[i]
            i += 1
        rc.advance(acc, self.freqs[i])
        sym = self.syms[i]
        self.freqs[i] += self.STEP
        self.total += self.STEP
        if i > 0 and self.freqs[i] > self.freqs[i - 1]:
            fr, sy = self.freqs, self.syms
            fr[i - 1], fr[i] = fr[i], fr[i - 1]
            sy[i - 1], sy[i] = sy[i], sy[i - 1]
        if self.total > self.MAX_TOTAL:
            t = 0
            for j in range(len(self.freqs)):
                self.freqs[j] -= self.freqs[j] >> 1
                t += self.freqs[j]
            self.total = t
        return sym


def test_cram31_arith_stream_decodes_via_oracle():
    """cram_arith.py order-0 frames (flag byte + uint7 size + max_sym +
    range-coded symbols) must decode under the independent adaptive
    model + range decoder."""
    import random

    from hadoop_bam_tpu.formats.cram_arith import arith_encode

    rng = random.Random(43)
    data = bytes(rng.choice(b"ACGTN") for _ in range(3000))
    payload = arith_encode(data, 0)
    assert payload[0] == 0                   # plain order-0 frame
    size, pos = _uint7_get(payload, 1)
    assert size == len(data)
    max_sym = payload[pos]
    pos += 1
    model = _OracleAdaptiveModel(max_sym)
    rc = _OracleRangeDecoder(payload, pos)
    out = bytes(model.decode(rc) for _ in range(size))
    assert out == data


def _oracle_read_runlen_array(buf: bytes, p: int, n: int):
    """[SPEC-recalled transcription] fqzcomp table: run length per value
    0,1,2,... with 255-extension."""
    a = [0] * n
    i = v = 0
    while i < n:
        run = 0
        while True:
            b = buf[p]
            p += 1
            run += b
            if b != 255:
                break
        for _ in range(run):
            a[i] = v
            i += 1
        v += 1
    return a, p


def test_cram31_fqzcomp_stream_decodes_via_oracle():
    """cram_fqzcomp.py quality streams must decode under an independent
    transcription of the published fqzcomp decoder: parameter block,
    quantizer tables, context mixing, and the adaptive model/range
    coder above — no code shared with _fqz_decode."""
    import random
    import struct as _struct

    from hadoop_bam_tpu.formats.cram_fqzcomp import fqz_encode

    rng = random.Random(47)
    n_rec, rec_len = 40, 100
    quals = bytes(rng.choice((2, 12, 25, 37)) for _ in range(n_rec *
                                                             rec_len))
    lens = [rec_len] * n_rec
    buf = fqz_encode(quals, lens)

    # --- header + single parameter set (gflags 0: our encoder) ---
    assert buf[0] == 5 and buf[1] == 0       # vers, gflags
    p = 2
    context0 = _struct.unpack_from("<H", buf, p)[0]
    pflags, max_sym = buf[p + 2], buf[p + 3]
    qbits, qshift = buf[p + 4] >> 4, buf[p + 4] & 15
    qloc, sloc = buf[p + 5] >> 4, buf[p + 5] & 15
    ploc, dloc = buf[p + 6] >> 4, buf[p + 6] & 15
    p += 7
    HAVE_QMAP, HAVE_PTAB, HAVE_DTAB, HAVE_QTAB, DO_LEN = 16, 32, 64, 128, 4
    qmap = None
    if pflags & HAVE_QMAP:
        qmap = list(buf[p:p + max_sym])
        p += max_sym
    qtab = list(range(256))
    if pflags & HAVE_QTAB:
        qtab, p = _oracle_read_runlen_array(buf, p, 256)
    ptab = [0] * 1024
    if pflags & HAVE_PTAB:
        ptab, p = _oracle_read_runlen_array(buf, p, 1024)
    dtab = [0] * 256
    if pflags & HAVE_DTAB:
        dtab, p = _oracle_read_runlen_array(buf, p, 256)

    # --- adaptive decode loop [SPEC transcription] ---
    rc = _OracleRangeDecoder(buf, p)
    nsym = max_sym + 1
    qual_models = {}
    len_models = [_OracleAdaptiveModel(256) for _ in range(4)]
    qmask = (1 << qbits) - 1
    out = bytearray()
    last_len = 0
    while len(out) < len(quals):
        if (pflags & DO_LEN) or last_len == 0:
            b0 = len_models[0].decode(rc)
            b1 = len_models[1].decode(rc)
            b2 = len_models[2].decode(rc)
            b3 = len_models[3].decode(rc)
            last_len = b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
        qctx = 0
        pos_left = last_len
        delta = prevq = 0
        ctx = context0
        for _ in range(last_len):
            m = qual_models.get(ctx)
            if m is None:
                m = qual_models[ctx] = _OracleAdaptiveModel(nsym)
            q = m.decode(rc)
            out.append(qmap[q] if qmap is not None else q)
            qctx = ((qctx << qshift) + qtab[q]) & 0xFFFFFFFF
            nxt = context0 + ((qctx & qmask) << qloc)
            if pflags & HAVE_PTAB:
                pos_left -= 1
                nxt += ptab[min(1023, pos_left)] << ploc
            if pflags & HAVE_DTAB:
                nxt += dtab[min(255, delta)] << dloc
                delta += 1 if prevq != q else 0
                prevq = q
            ctx = nxt & 0xFFFF
    assert bytes(out) == quals


def _oracle_tokenize(name: bytes):
    """[SPEC transcription] tok3 token split: digit runs (DIGITS, or
    DIGITS0 when zero-padded; >uint32 degrades to ALPHA), single
    non-digit bytes CHAR, longer runs ALPHA; token list capped at 128
    with the tail folded into one ALPHA."""
    T_ALPHA, T_CHAR, T_DIGITS0, T_DIGITS = 1, 2, 4, 7
    toks = []
    i, n = 0, len(name)
    while i < n:
        if 0x30 <= name[i] <= 0x39:
            j = i + 1
            while j < n and 0x30 <= name[j] <= 0x39:
                j += 1
            run = name[i:j]
            if len(run) > 9 or int(run) > 0xFFFFFFFF:
                toks.append((T_ALPHA, run))
            elif run[0] == 0x30 and len(run) > 1:
                toks.append((T_DIGITS0, run))
            else:
                toks.append((T_DIGITS, run))
            i = j
        else:
            j = i + 1
            while j < n and not (0x30 <= name[j] <= 0x39):
                j += 1
            run = name[i:j]
            toks.append((T_CHAR, run) if len(run) == 1
                        else (T_ALPHA, run))
            i = j
    if len(toks) >= 128:
        head, tail = toks[:127], toks[127:]
        head.append((T_ALPHA, b"".join(t for _, t in tail)))
        toks = head
    return toks


def test_cram31_tok3_frames_decode_via_oracle():
    """cram_name_tok3.py name frames must reconstruct under an
    independent walk of the frame (descriptors + uint7 lengths + Nx16
    streams via the order-0 oracle) and the published token model
    (DUP/DIFF selectors, per-position typed token streams)."""
    import struct as _struct

    from hadoop_bam_tpu.formats.cram_name_tok3 import tok3_encode

    T_TYPE, T_ALPHA, T_CHAR, T_DZLEN, T_DIGITS0 = 0, 1, 2, 3, 4
    T_DUP, T_DIFF, T_DIGITS, T_DDELTA, T_DDELTA0 = 5, 6, 7, 11, 12
    T_MATCH, T_NOP, T_END = 13, 14, 15

    names = [b"IL3:6:1:100:0042", b"IL3:6:1:101:0043",
             b"IL3:6:1:101:0043", b"IL3:6:2:7:0999", b"read*odd",
             b"IL3:6:2:8:1000"]
    payload = b"".join(n + b"\0" for n in names)
    frame = tok3_encode(payload)

    ulen, nnames = _struct.unpack_from("<II", frame, 0)
    assert (ulen, nnames) == (len(payload), len(names))
    flags = frame[8]
    assert not flags & 0x01                  # rANS streams, not arith
    sep = b"\n" if flags & 0x02 else b"\0"

    streams = {}
    i, pos = 9, 0
    while i < len(frame):
        desc = frame[i]
        i += 1
        assert not desc & 0x40               # no duplicate-stream frames
        if desc & 0x80:
            pos += 1
        clen, i = _uint7_get(frame, i)
        streams[(pos, desc & 0x0F)] = [_oracle_nx16_payload(
            frame[i:i + clen]), 0]
        i += clen

    def take(p, t, n):
        data, cur = streams[(p, t)]
        assert cur + n <= len(data)
        streams[(p, t)][1] = cur + n
        return data[cur:cur + n]

    def take_cstr(p, t):
        data, cur = streams[(p, t)]
        end = data.index(b"\0", cur)
        streams[(p, t)][1] = end + 1
        return data[cur:end]

    got = []
    for _ in range(nnames):
        sel = take(0, T_TYPE, 1)[0]
        if sel == T_DUP:
            (dist,) = _struct.unpack("<I", take(0, T_DUP, 4))
            name = got[len(got) - dist]
        else:
            assert sel == T_DIFF
            (dist,) = _struct.unpack("<I", take(0, T_DIFF, 4))
            ref = _oracle_tokenize(got[len(got) - dist]) if dist else []
            parts = []
            p = 1
            while True:
                t = take(p, T_TYPE, 1)[0]
                if t == T_END:
                    break
                if t == T_NOP:
                    p += 1
                    continue
                rtok = ref[p - 1] if p - 1 < len(ref) else None
                if t == T_MATCH:
                    parts.append(rtok[1])
                elif t == T_ALPHA:
                    parts.append(take_cstr(p, T_ALPHA))
                elif t == T_CHAR:
                    parts.append(take(p, T_CHAR, 1))
                elif t == T_DIGITS:
                    (v,) = _struct.unpack("<I", take(p, T_DIGITS, 4))
                    parts.append(b"%d" % v)
                elif t == T_DIGITS0:
                    (v,) = _struct.unpack("<I", take(p, T_DIGITS0, 4))
                    w = take(p, T_DZLEN, 1)[0]
                    parts.append(b"%0*d" % (w, v))
                elif t == T_DDELTA:
                    d = take(p, T_DDELTA, 1)[0]
                    parts.append(b"%d" % (int(rtok[1]) + d))
                elif t == T_DDELTA0:
                    d = take(p, T_DDELTA0, 1)[0]
                    parts.append(b"%0*d" % (len(rtok[1]),
                                            int(rtok[1]) + d))
                else:
                    raise AssertionError(f"unknown token type {t}")
                p += 1
            name = b"".join(parts)
        got.append(name)
    assert b"".join(n + sep for n in got) == payload
    # every stream fully consumed: nothing the oracle failed to model
    for (p, t), (data, cur) in streams.items():
        assert cur == len(data), (p, t)
