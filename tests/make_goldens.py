"""Generate the frozen wire-format fixtures under tests/golden/.

Run from the repo root: ``python tests/make_goldens.py``.  DO NOT
regenerate casually: the whole point of the goldens (SURVEY.md section 4
round-trip philosophy; reference mount empty, so these are the only
cross-session oracle) is that decoders are asserted against bytes
written by a PAST encoder, not the same session's.  If an intentional
format fix changes bytes, regenerate, update the pinned hashes in
test_golden.py, and record the break in PARITY.md — files written
before the change may become unreadable.
"""
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fixtures import make_header, make_records  # noqa: E402

GOLD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


def main() -> None:
    os.makedirs(GOLD, exist_ok=True)
    # enough records that the 3.1 entropy codecs (Nx16, tok3) genuinely
    # beat RAW and engage — tiny payloads fall back to stored blocks
    header = make_header()
    recs = make_records(header, 96, seed=20260729)

    # --- BAM + sidecar indexes + expected SAM text + voffsets
    from hadoop_bam_tpu.formats.bamio import BamWriter
    bam = os.path.join(GOLD, "golden.bam")
    with BamWriter(bam, header, track_voffsets=True) as w:
        for r in recs:
            w.write_sam_record(r)
        voffs = list(w.record_voffsets())
    with open(os.path.join(GOLD, "golden.bam.voffsets"), "w") as f:
        f.write("\n".join(str(v) for v in voffs) + "\n")
    with open(os.path.join(GOLD, "golden.sam"), "w") as f:
        for r in recs:
            f.write(r.to_line() + "\n")
    from hadoop_bam_tpu.split.splitting_index import write_splitting_index
    write_splitting_index(bam, granularity=8, flavor="splitting-bai")
    write_splitting_index(bam, granularity=8, flavor="sbi")

    # --- CRAM 3.0 and 3.1 (same records)
    from hadoop_bam_tpu.formats.cramio import CramWriter
    # containers big enough that the 3.1 entropy codecs beat RAW and the
    # blocks genuinely carry methods 5 (Nx16) and 8 (tok3)
    for version in ((3, 0), (3, 1)):
        path = os.path.join(GOLD, f"golden_{version[0]}{version[1]}.cram")
        with CramWriter(path, header, records_per_container=48,
                        version=version) as w:
            w.write_records(recs)

    # --- VCF.gz (BGZF) + BCF + expected VCF text
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    vh = VCFHeader.from_text(
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr1,length=248956422>\n"
        "##contig=<ID=chr2,length=242193529>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##INFO=<ID=AF,Number=A,Type=Float,Description="Freq">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="GT">\n'
        '##FORMAT=<ID=GQ,Number=1,Type=Integer,Description="GQ">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts0\ts1\n")
    rng = random.Random(20260729)
    vlines = []
    pos = 0
    for i in range(20):
        pos += rng.randint(1, 500)
        ref = rng.choice("ACGT")
        alt = rng.choice([c for c in "ACGT" if c != ref])
        gts = "\t".join(
            f"{rng.choice(['0/0', '0/1', '1/1', './.'])}:{rng.randint(1, 99)}"
            for _ in range(2))
        vlines.append(f"chr{1 + i % 2}\t{pos}\t.\t{ref}\t{alt}\t"
                      f"{20 + i}\tPASS\tDP={i};AF=0.5\tGT:GQ\t{gts}")
    with open(os.path.join(GOLD, "golden.vcf"), "w") as f:
        f.write("\n".join(vlines) + "\n")
    for ext in ("vcf.gz", "bcf"):
        path = os.path.join(GOLD, f"golden.{ext}")
        with open_vcf_writer(path, vh) as w:
            for line in vlines:
                w.write_record(VcfRecord.from_line(line))

    import hashlib
    for name in sorted(os.listdir(GOLD)):
        p = os.path.join(GOLD, name)
        print(f'    "{name}": "{hashlib.sha256(open(p, "rb").read()).hexdigest()}",')


if __name__ == "__main__":
    main()
