"""Serving-fleet tests (``pytest -m serve``): rendezvous ownership
determinism + minimal disruption, heartbeat membership on an injected
clock (suspicion/eviction/rejoin, quorum), the chunk-source routing
table, the ``serve.peer`` chaos point feeding per-peer breakers, the
wire chunk codec, enqueue-anchored deadline re-budgeting across the
hop, hedged peer-fetch (first result wins), two in-process replicas
over real TCP (peer fetch vs the single-replica oracle, trace/replica
stamping, degraded partition mode), the fleet ops views (``hbam
fleet``, ``hbam top --endpoints``) — and the REAL failover test: a
replica subprocess SIGKILLed mid-load with zero client-visible
failures, eviction inside the window, and rejoin through half-open
probes.
"""
import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

import pytest

from hadoop_bam_tpu import resilience
from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.query import QueryEngine, QueryRequest
from hadoop_bam_tpu.resilience import CLOSED, OPEN
from hadoop_bam_tpu.resilience.chaos import PointFault, fault_points_on
from hadoop_bam_tpu.serve import ServeLoop, make_tcp_server
from hadoop_bam_tpu.serve.fleet import (
    Fleet, decode_chunk_doc, effective_deadline_s, encode_chunk_doc,
    parse_peers,
)
from hadoop_bam_tpu.serve.membership import (
    ALIVE, EVICTED, SUSPECT, Membership, owners, rank_members,
    rendezvous_weight,
)
from hadoop_bam_tpu.utils.errors import (
    CorruptDataError, PlanError, TransientIOError,
)

from fixtures import make_header, make_records

pytestmark = pytest.mark.serve

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


@pytest.fixture(autouse=True)
def _restore_replica_id():
    # Fleet.start() stamps the process-global replica id, and the
    # in-process replica loops bump the global METRICS counters;
    # both would otherwise leak into every later test.
    from hadoop_bam_tpu.obs import context as obs_context
    from hadoop_bam_tpu.utils.metrics import METRICS

    prev = obs_context.replica_id()
    yield
    obs_context.set_replica_id(prev)
    METRICS.reset()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _coord_sorted(header, recs):
    def key(r):
        rid = (header.ref_names.index(r.rname) if r.rname != "*"
               else 1 << 30)
        return (rid, r.pos)
    return sorted(recs, key=key)


@pytest.fixture(scope="module")
def fleet_bam(tmp_path_factory):
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    path = str(tmp_path_factory.mktemp("fleet") / "f.bam")
    header = make_header(2)
    recs = _coord_sorted(header, make_records(header, 2000, seed=11))
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    write_bai(path)
    return path


_REGIONS = ["chr1:1000-200000", "chr1:500000-650000", "chr2:1-5000",
            "chr2:100000-400000"]


def _oracle_counts(path, regions=_REGIONS):
    engine = QueryEngine()
    res = engine.query_records([QueryRequest(path, r) for r in regions])
    return [len(r.records) for r in res]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wire(port, doc, timeout=10.0):
    """One JSONL round trip to a replica's TCP transport."""
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        f = s.makefile("rw", encoding="utf-8", newline="\n")
        f.write(json.dumps(doc) + "\n")
        f.flush()
        line = f.readline()
    return json.loads(line)


# ---------------------------------------------------------------------------
# rendezvous ownership: deterministic, total, minimally disruptive
# ---------------------------------------------------------------------------

def test_rendezvous_weight_is_keyed_blake2b_not_salted_hash():
    # pinned values: the weight must be identical across processes and
    # Python runs (a salted hash() here would silently split the fleet
    # into disagreeing ownership views)
    k = ("ident", (0, 100), "iv")
    assert rendezvous_weight(k, "r1") == rendezvous_weight(k, "r1")
    w1, w2 = rendezvous_weight(k, "r1"), rendezvous_weight(k, "r2")
    assert w1 != w2
    assert 0 <= w1 < (1 << 64)
    # ranking is a permutation with total order (ties broken by id)
    ms = ["r1", "r2", "r3", "r4"]
    ranked = rank_members(k, ms)
    assert sorted(ranked) == sorted(ms)
    assert rank_members(k, list(reversed(ms))) == ranked


def test_rendezvous_same_ranking_in_subprocess(tmp_path):
    """The cross-process determinism contract, tested literally."""
    keys = [("id", (i, i + 10), "iv") for i in range(20)]
    ms = ["a", "b", "c"]
    script = textwrap.dedent("""
        import json, sys
        from hadoop_bam_tpu.serve.membership import rank_members
        keys = [tuple(k) if not isinstance(k, list) else
                (k[0], tuple(k[1]), k[2])
                for k in json.loads(sys.argv[1])]
        print(json.dumps([rank_members(k, ["a", "b", "c"])
                          for k in keys]))
    """)
    sp = str(tmp_path / "rdv.py")
    open(sp, "w").write(script)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, sp, json.dumps(keys)], env=env,
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout) == [rank_members(k, ms) for k in keys]


def test_rendezvous_removal_moves_only_the_dead_members_share():
    """Minimal disruption: dropping one member re-ranks ONLY the keys
    that member owned — every other key keeps its exact owner list."""
    ms = ["r1", "r2", "r3", "r4", "r5"]
    keys = [("f", (i * 100, i * 100 + 99), "iv") for i in range(300)]
    before = {k: owners(k, ms, 2) for k in keys}
    after = {k: owners(k, [m for m in ms if m != "r3"], 2) for k in keys}
    moved = untouched = 0
    for k in keys:
        if "r3" in before[k]:
            moved += 1
            # survivors keep their relative order; r3's slot backfills
            kept = [m for m in before[k] if m != "r3"]
            assert after[k][:len(kept)] == kept
        else:
            untouched += 1
            assert after[k] == before[k]
    assert moved > 0 and untouched > 0


# ---------------------------------------------------------------------------
# heartbeat membership on an injected clock
# ---------------------------------------------------------------------------

def test_membership_suspicion_eviction_rejoin_transitions():
    clk = FakeClock()
    m = Membership("r1", ["r2", "r3"], suspicion_s=1.0, eviction_s=3.0,
                   clock=clk)
    assert m.members() == ["r1", "r2", "r3"]
    assert m.sweep() == []                       # everyone fresh
    clk.advance(1.5)
    m.observe("r2")                              # r2 heartbeats, r3 silent
    assert dict(m.sweep())["r3"] == SUSPECT
    # SUSPECT stays ranked: a hiccup must not move tile ownership
    assert "r3" in m.members()
    clk.advance(2.0)                             # r3 now 3.5s silent
    assert dict(m.sweep())["r3"] == EVICTED
    assert m.members() == ["r1", "r2"]
    assert m.evictions_total == 1
    key = ("f", (0, 10), "iv")
    assert "r3" not in m.owners_for(key, 3)
    # an observation re-admits immediately (breakers still gate traffic)
    assert m.observe("r3") is True
    assert m.rejoins_total == 1
    assert "r3" in m.members()
    assert m.observe("r3") is False              # already alive
    assert m.observe("stranger") is False        # not in the roster


def test_membership_quorum_and_degraded_boundary():
    clk = FakeClock()
    m = Membership("r1", ["r2", "r3", "r4"], suspicion_s=0.5,
                   eviction_s=1.0, clock=clk)
    assert m.has_quorum()                        # 4/4 visible
    clk.advance(2.0)
    m.observe("r2")
    m.sweep()                                    # r3, r4 evicted
    # 2 of 4 visible: NOT a majority — degraded
    assert not m.has_quorum()
    m.observe("r3")
    assert m.has_quorum()                        # 3 of 4 again
    assert m.states()["peers"]["r4"]["state"] == EVICTED


def test_membership_empty_id_is_plan_error():
    with pytest.raises(PlanError):
        Membership("", ["r2"])


# ---------------------------------------------------------------------------
# chunk-source routing (plan/executor.select_chunk_source)
# ---------------------------------------------------------------------------

def test_select_chunk_source_routing_table():
    from hadoop_bam_tpu.plan.executor import select_chunk_source

    def pick(**kw):
        base = dict(tile_cached=False, fleet_owned=False, degraded=False,
                    want_records=False, peer_ready=True)
        base.update(kw)
        return select_chunk_source(**base)[0]

    assert pick(tile_cached=True) == "tile"          # hit beats all
    assert pick(degraded=True) == "local"            # partition mode
    assert pick(want_records=True) == "local"        # records are local
    assert pick(fleet_owned=True) == "local"         # we own it
    assert pick(peer_ready=False) == "local"         # nobody to ask
    assert pick() == "peer"                          # peer-owned: fetch
    # every row explains itself (the explain-plane discipline)
    _, why = select_chunk_source(
        tile_cached=False, fleet_owned=False, degraded=False,
        want_records=False, peer_ready=True)
    assert why


# ---------------------------------------------------------------------------
# wire plumbing: peer specs, deadline re-anchor, chunk codec
# ---------------------------------------------------------------------------

def test_parse_peers_specs_and_errors():
    assert parse_peers("a=127.0.0.1:7001, b=h2:7002") == {
        "a": ("127.0.0.1", 7001), "b": ("h2", 7002)}
    assert parse_peers("127.0.0.1:9000") == {
        "127.0.0.1:9000": ("127.0.0.1", 9000)}
    assert parse_peers("") == {}
    for bad in ("a=nohost", "a=host:", "a=:77", "x=h:7a"):
        with pytest.raises(PlanError):
            parse_peers(bad)


def test_effective_deadline_reanchors_to_originating_enqueue():
    assert effective_deadline_s(None, 1.0) is None   # unbudgeted
    assert effective_deadline_s(2.0, 0.5) == 1.5     # age already spent
    assert effective_deadline_s(2.0, None) == 2.0
    assert effective_deadline_s(1.0, 5.0) == 0.0     # exhausted, not fresh
    # hostile/corrupt ages are ignored, never trusted into a negative
    # or bonus budget
    assert effective_deadline_s(2.0, -3.0) == 2.0
    assert effective_deadline_s(2.0, 1e9) == 2.0
    assert effective_deadline_s(2.0, "junk") == 2.0


def test_chunk_doc_codec_round_trip_and_corrupt_shape():
    import numpy as np

    value = {"n": 3, "nbytes": 4096,
             "rid": np.array([0, 0, 1], np.int32),
             "pos1": np.array([10, 20, 30], np.int32),
             "end1": np.array([15, 25, 35], np.int32)}
    doc = encode_chunk_doc(value)
    back = decode_chunk_doc(doc)
    assert back["n"] == 3 and back["nbytes"] == 4096
    assert back["records"] == []                 # records never hop
    for k in ("rid", "pos1", "end1"):
        assert back[k].tolist() == value[k].tolist()
    # a short column is CORRUPT at decode time, not an index error later
    bad = dict(doc, n=5)
    with pytest.raises(CorruptDataError):
        decode_chunk_doc(bad)
    # the quarantine marker (n=0 AND nbytes=0) survives the hop
    empty = decode_chunk_doc(encode_chunk_doc(
        {"n": 0, "nbytes": 0,
         "rid": np.zeros(0, np.int32), "pos1": np.zeros(0, np.int32),
         "end1": np.zeros(0, np.int32)}))
    assert empty["n"] == 0 and empty["nbytes"] == 0


# ---------------------------------------------------------------------------
# the serve.peer chaos point + per-peer breakers
# ---------------------------------------------------------------------------

def _mini_fleet(peer_ports=None, clock=None, **cfg_kw):
    peers = ",".join(f"p{i}=127.0.0.1:{p}"
                     for i, p in enumerate(peer_ports or [1]))
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, serve_replica_id="self", serve_peers=peers,
        **cfg_kw)
    return Fleet(cfg, clock=clock or time.monotonic)


def test_chaos_point_serve_peer_is_known_and_fires_before_dial():
    from hadoop_bam_tpu.resilience.chaos import KNOWN_POINTS
    assert "serve.peer" in KNOWN_POINTS

    resilience.reset()
    fleet = _mini_fleet()
    with fault_points_on("serve.peer",
                         [PointFault("transient", count=1000)]):
        with pytest.raises(TransientIOError):
            fleet._peer_call("p0", {"op": "heartbeat"}, timeout_s=0.1)
    with fault_points_on("serve.peer",
                         [PointFault("disconnect", count=1000)]):
        with pytest.raises(ConnectionResetError):
            fleet._peer_call("p0", {"op": "heartbeat"}, timeout_s=0.1)
    resilience.reset()


def test_injected_peer_faults_feed_the_peer_breaker_and_fallback():
    """The observation contract: chaos at serve.peer exercises exactly
    the breaker + fallback stack a real peer fault would."""
    resilience.reset()
    fleet = _mini_fleet(breaker_failure_threshold=2.0)
    key = ("f", (0, 10), "iv")
    with fault_points_on("serve.peer",
                         [PointFault("transient", count=1000)]):
        with pytest.raises(TransientIOError):
            fleet.fetch_chunk("/nope.bam", key, 0, 10)
        with pytest.raises(TransientIOError):
            fleet.fetch_chunk("/nope.bam", key, 0, 10)
    states = resilience.registry().states()
    dom = states["serve/peer/p0"]
    assert dom["failures_total"] >= 2
    assert dom["state"] == OPEN
    assert fleet.peer_fetch_failed == 2
    # with the breaker OPEN the peer is not even dialed: candidates are
    # exhausted instantly and the caller falls back to local decode
    with pytest.raises(TransientIOError, match="unavailable"):
        fleet.fetch_chunk("/nope.bam", key, 0, 10)
    resilience.reset()


def test_heartbeat_breaker_opens_then_heals_through_half_open_probe():
    """The rejoin contract end to end on one process: a dead peer's
    breaker opens (heartbeats ARE the failure source), membership
    evicts it on the injected clock, and after the peer comes back the
    heartbeat doubles as the half-open probe that heals the breaker
    BEFORE query traffic flows."""
    clk = FakeClock()
    resilience.reset(clock=clk)
    port = _free_port()
    fleet = _mini_fleet(peer_ports=[port], clock=clk,
                        breaker_failure_threshold=2.0,
                        breaker_cooldown_s=5.0,
                        fleet_suspicion_s=1.0, fleet_eviction_s=3.0)
    # nobody listening: each round dials, fails, feeds the breaker
    fleet.heartbeat_round()
    clk.advance(1.5)
    fleet.heartbeat_round()
    states = resilience.registry().states()
    assert states["serve/peer/p0"]["state"] == OPEN
    assert fleet.membership.states()["peers"]["p0"]["state"] == SUSPECT
    clk.advance(2.0)
    fleet.heartbeat_round()                      # breaker OPEN: no dial
    assert fleet.membership.states()["peers"]["p0"]["state"] == EVICTED
    assert fleet.degraded()                      # 1 of 2 visible
    # the peer comes back: a fake JSONL responder on the same port
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(4)

    def responder():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return
            with c:
                f = c.makefile("rw", encoding="utf-8", newline="\n")
                if f.readline():
                    f.write(json.dumps({"ok": True}) + "\n")
                    f.flush()

    t = threading.Thread(target=responder, daemon=True)
    t.start()
    try:
        fleet.heartbeat_round()                  # still cooling down
        assert resilience.registry().states()["serve/peer/p0"]["state"] \
            == OPEN
        clk.advance(5.1)                         # cooldown elapses
        fleet.heartbeat_round()                  # half-open probe = hb
        states = resilience.registry().states()
        assert states["serve/peer/p0"]["state"] == CLOSED
        assert states["serve/peer/p0"]["healed_total"] == 1
        assert fleet.membership.states()["peers"]["p0"]["state"] == ALIVE
        assert fleet.membership.rejoins_total == 1
        assert not fleet.degraded()
    finally:
        srv.close()
        t.join(2.0)
    resilience.reset()


# ---------------------------------------------------------------------------
# hedged peer-fetch: first result wins past the decaying-p95 deadline
# ---------------------------------------------------------------------------

def test_hedge_races_next_ranked_replica_first_result_wins():
    resilience.reset()
    fleet = _mini_fleet(peer_ports=[1, 2], fleet_hedge_min_s=0.02)
    for _ in range(16):                          # warm the p95
        fleet.latency.observe(0.005)
    assert fleet.latency.soft_deadline_s() is not None

    def fake_timed(pid, doc, timeout_s):
        if pid == "p0":
            time.sleep(0.5)                      # the straggler primary
            return {"who": "p0"}
        return {"who": "p1"}

    fleet._timed_call = fake_timed
    t0 = time.perf_counter()
    resp = fleet._fetch_hedged(["p0", "p1"], {"op": "chunk"})
    took = time.perf_counter() - t0
    assert resp["who"] == "p1"                   # the hedge won
    assert fleet.hedges == 1 and fleet.hedge_wins == 1
    assert took < 0.45                           # did not wait out p0
    resilience.reset()


def test_hedge_errors_fall_through_to_next_owner():
    resilience.reset()
    fleet = _mini_fleet(peer_ports=[1, 2])

    calls = []

    def fake_timed(pid, doc, timeout_s):
        calls.append(pid)
        if pid == "p0":
            raise TransientIOError("p0 is sick")
        return {"who": pid}

    fleet._timed_call = fake_timed
    assert fleet._fetch_hedged(["p0", "p1"], {})["who"] == "p1"
    assert calls == ["p0", "p1"]
    fleet._timed_call = lambda *a: (_ for _ in ()).throw(
        TransientIOError("all sick"))
    with pytest.raises(TransientIOError, match="every owner"):
        fleet._fetch_hedged(["p0", "p1"], {})
    resilience.reset()


# ---------------------------------------------------------------------------
# two in-process replicas over real TCP
# ---------------------------------------------------------------------------

@pytest.fixture()
def two_replicas(fleet_bam):
    resilience.reset()
    p1, p2 = _free_port(), _free_port()
    peers = f"r1=127.0.0.1:{p1},r2=127.0.0.1:{p2}"
    loops, servers, threads = [], [], []
    for rid, port in (("r1", p1), ("r2", p2)):
        cfg = dataclasses.replace(
            DEFAULT_CONFIG, serve_replica_id=rid, serve_peers=peers,
            fleet_replication=1, fleet_heartbeat_s=0.1,
            serve_prefetch=False)
        loop = ServeLoop(config=cfg)
        loop.start()
        srv = make_tcp_server(loop, host="127.0.0.1", port=port)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        loops.append(loop)
        servers.append(srv)
        threads.append(t)
    try:
        yield loops, (p1, p2)
    finally:
        for srv in servers:
            srv.shutdown()
            srv.server_close()
        for loop in loops:
            loop.stop()
        for t in threads:
            t.join(5.0)
        resilience.reset()


def test_fleet_peer_fetch_matches_oracle_and_splits_ownership(
        two_replicas, fleet_bam):
    loops, _ports = two_replicas
    want = _oracle_counts(fleet_bam)
    res1 = loops[0].query(fleet_bam, _REGIONS)
    res2 = loops[1].query(fleet_bam, _REGIONS)
    assert [r.count for r in res1] == want
    assert [r.count for r in res2] == want
    # replication=1 over 2 replicas: each owns a strict subset, so BOTH
    # sides peer-fetched something and served something for the other
    f1, f2 = loops[0].fleet, loops[1].fleet
    assert f1.peer_fetch_ok + f2.peer_fetch_ok > 0
    assert f1.chunks_served + f2.chunks_served > 0
    assert f1.peer_fetch_ok == f2.chunks_served
    assert f2.peer_fetch_ok == f1.chunks_served
    assert f1.peer_fetch_failed == f2.peer_fetch_failed == 0
    # provenance rides the results
    assert all(r.extra["replica"] == "r1" for r in res1)
    assert any(r.extra.get("peer_chunks") for r in res1 + res2)
    assert not any(r.extra.get("degraded") for r in res1 + res2)


def test_fleet_records_mode_stays_local_and_byte_identical(
        two_replicas, fleet_bam):
    loops, _ports = two_replicas
    engine = QueryEngine()
    oracle = engine.query_records(
        [QueryRequest(fleet_bam, r) for r in _REGIONS[:2]])
    before = loops[0].fleet.peer_fetch_ok
    res = loops[0].query(fleet_bam, _REGIONS[:2], want_records=True)
    for out, want in zip(res, oracle):
        assert [r.to_line() for r in out.records] == \
            [r.to_line() for r in want.records]
    # records mode never peer-fetches (materialization is local)
    assert loops[0].fleet.peer_fetch_ok == before


def test_fleet_wire_ops_and_trace_replica_stamping(two_replicas,
                                                   fleet_bam):
    loops, (p1, p2) = two_replicas
    want = _oracle_counts(fleet_bam, [_REGIONS[0]])
    # a client request with a trace id: the reply echoes the SAME id
    # (the adopted hop contract) and names the answering replica
    doc = _wire(p1, {"id": 1, "path": fleet_bam, "region": _REGIONS[0],
                     "trace": "trace-abc123"})
    assert doc["trace"] == "trace-abc123"
    assert doc["replica"] == "r1"
    assert doc["results"][0]["count"] == want[0]
    assert doc["results"][0]["replica"] == "r1"
    # heartbeat op: the sender is observed, the reply names the replica
    hb = _wire(p2, {"op": "heartbeat", "from": "r1", "id": 9})
    assert hb["ok"] is True and hb["replica"] == "r2"
    # fleet op: membership + per-peer breakers + counters
    fl = _wire(p1, {"op": "fleet", "id": 10})["fleet"]
    assert fl["replica_id"] == "r1"
    assert fl["membership"]["peers"]["r2"]["state"] == ALIVE
    assert fl["peer_breakers"]["r2"]["state"] == CLOSED
    # chunk op errors are wire-taxonomy classified
    bad = _wire(p1, {"op": "chunk", "id": 11})
    assert bad["kind"] == "plan"
    # health carries the fleet view
    h = _wire(p1, {"op": "health", "id": 12})["health"]
    assert h["fleet"]["replica_id"] == "r1"


def test_wire_deadline_reanchors_not_refreshes(two_replicas, fleet_bam):
    loops, (p1, _p2) = two_replicas
    # a request whose budget the PRIOR hops already spent: deadline_s
    # minus enqueue_age_s leaves ~nothing — the replica must shed it as
    # a deadline miss (transient, retryable) instead of re-anchoring to
    # a fresh budget
    doc = _wire(p1, {"id": 1, "path": fleet_bam, "region": _REGIONS[0],
                     "deadline_s": 5.0, "enqueue_age_s": 4.9999999})
    assert doc["kind"] == "transient"
    assert "deadline" in doc["error"]
    # the same request with its age intact is answerable
    ok = _wire(p1, {"id": 2, "path": fleet_bam, "region": _REGIONS[0],
                    "deadline_s": 30.0, "enqueue_age_s": 0.5})
    assert ok["results"][0]["count"] == _oracle_counts(
        fleet_bam, [_REGIONS[0]])[0]


def test_degraded_partition_serves_with_flag_instead_of_erroring(
        fleet_bam):
    """A replica that lost quorum keeps serving what it can, marked
    ``extra.degraded`` — partition behavior, not an outage."""
    resilience.reset()
    clk = FakeClock()
    # a 3-member fleet where both peers are dead ports: no quorum once
    # they age out on the injected clock
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, serve_replica_id="solo",
        serve_peers=(f"solo=127.0.0.1:1,pa=127.0.0.1:{_free_port()},"
                     f"pb=127.0.0.1:{_free_port()}"),
        fleet_replication=1, fleet_suspicion_s=0.5, fleet_eviction_s=1.0,
        serve_prefetch=False)
    fleet = Fleet(cfg, clock=clk)
    fleet.heartbeat_round()
    clk.advance(2.0)
    fleet.heartbeat_round()
    assert fleet.degraded()
    with ServeLoop(config=cfg, fleet=fleet) as loop:
        res = loop.query(fleet_bam, _REGIONS)
        assert [r.count for r in res] == _oracle_counts(fleet_bam)
        assert all(r.extra["degraded"] is True for r in res)
        assert all(r.extra["replica"] == "solo" for r in res)
    assert fleet.degraded_serves > 0
    assert fleet.peer_fetch_ok == 0              # degraded: all local
    resilience.reset()


# ---------------------------------------------------------------------------
# fleet ops views: hbam fleet, hbam top --endpoints
# ---------------------------------------------------------------------------

def test_hbam_fleet_and_top_endpoints_render_live_fleet(
        two_replicas, fleet_bam, capsys):
    from hadoop_bam_tpu.tools import cli

    loops, (p1, p2) = two_replicas
    loops[0].query(fleet_bam, _REGIONS)          # live traffic
    rc = cli.main(["fleet", "--port", str(p1)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "replica=r1" in out and "r2" in out
    assert "breaker=closed" in out
    assert "peer_fetch_ok=" in out

    rc = cli.main(["fleet", "--port", str(p2), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["replica_id"] == "r2"

    # the fleet table: one row per replica + aggregates, DOWN rows for
    # unreachable endpoints instead of a failed frame
    dead = _free_port()
    rc = cli.main(["top", "--endpoints",
                   f"127.0.0.1:{p1},127.0.0.1:{p2},127.0.0.1:{dead}",
                   "--once", "--timeout", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "r1" in out and "r2" in out
    assert "DOWN" in out
    assert "up=2/3" in out
    assert "cross_replica_tile_rate=" in out


def test_top_requires_port_or_endpoints(capsys):
    from hadoop_bam_tpu.tools import cli

    assert cli.main(["top", "--once"]) == 2
    assert "--endpoints" in capsys.readouterr().err
    assert cli.main(["top", "--endpoints", "garbage", "--once"]) == 2


def test_serve_verb_validates_fleet_flags(capsys):
    from hadoop_bam_tpu.tools import cli

    assert cli.main(["serve", "--peers", "a=127.0.0.1:1",
                     "--port", "0"]) == 2
    assert "--replica-id" in capsys.readouterr().err
    assert cli.main(["serve", "--peers", "a=127.0.0.1:1",
                     "--replica-id", "a"]) == 2
    assert "--port" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the REAL failover test: SIGKILL a replica subprocess mid-load
# ---------------------------------------------------------------------------

_REPLICA_SCRIPT = """
    import dataclasses, sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.serve import ServeLoop, make_tcp_server

    rid, port, peers, warm = sys.argv[1], int(sys.argv[2]), \\
        sys.argv[3], sys.argv[4]
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, serve_replica_id=rid, serve_peers=peers,
        fleet_replication=1, fleet_heartbeat_s=0.15,
        fleet_suspicion_s=0.6, fleet_eviction_s=1.5,
        breaker_cooldown_s=0.5, breaker_failure_threshold=2.0,
        serve_prefetch=False)
    with ServeLoop(config=cfg) as loop:
        loop.engine._file_meta(warm)
        server = make_tcp_server(loop, host="127.0.0.1", port=port)
        print("READY", flush=True)
        server.serve_forever()
"""


def _spawn_replica(rid, port, peers, warm):
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(textwrap.dedent(_REPLICA_SCRIPT))
        script = f.name
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return script, subprocess.Popen(
        [sys.executable, script, rid, str(port), peers, warm],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)


def _await_replica(port, deadline_s=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        try:
            doc = _wire(port, {"op": "health", "id": 1}, timeout=2.0)
            if doc.get("health", {}).get("status"):
                return
        except (OSError, ValueError):
            time.sleep(0.25)
    raise AssertionError(f"replica on port {port} never became healthy")


def _query_with_retry(port, path, region, retries=3):
    """The documented client contract: one retry on transport-level
    failure is allowed; an error DOC or exhausted retries is a
    client-visible failure."""
    last = None
    for _ in range(retries):
        try:
            doc = _wire(port, {"id": 1, "path": path, "region": region},
                        timeout=30.0)
        except (OSError, ValueError) as e:
            last = str(e)
            time.sleep(0.2)
            continue
        if "error" in doc:
            return None, f"error doc: {doc}"
        return doc, None
    return None, f"transport: {last}"


def test_sigkill_failover_eviction_and_halfopen_rejoin(fleet_bam):
    """Kill one replica of a live 2-replica fleet with SIGKILL:

    - every client request against the surviving replica still answers,
      byte-identical to the single-replica oracle (zero client-visible
      failures after the allowed retry);
    - the dead replica is EVICTED within the suspicion/eviction window;
    - the restarted replica REJOINS through half-open breaker probes
      and serves again.
    """
    want = _oracle_counts(fleet_bam)
    p1, p2 = _free_port(), _free_port()
    peers = f"r1=127.0.0.1:{p1},r2=127.0.0.1:{p2}"
    s1, proc1 = _spawn_replica("r1", p1, peers, fleet_bam)
    s2, proc2 = _spawn_replica("r2", p2, peers, fleet_bam)
    procs = [proc1, proc2]
    try:
        _await_replica(p1)
        _await_replica(p2)
        failures = []

        def drive(port, tag):
            for i, region in enumerate(_REGIONS):
                doc, err = _query_with_retry(port, fleet_bam, region)
                if err is not None:
                    failures.append((tag, region, err))
                elif doc["results"][0]["count"] != want[i]:
                    failures.append((tag, region, "count mismatch",
                                     doc["results"][0]["count"]))

        drive(p1, "warm-r1")                     # both replicas warm;
        drive(p2, "warm-r2")                     # peer fetch is live
        fl = _wire(p1, {"op": "fleet", "id": 1})["fleet"]
        assert fl["peer_fetch_ok"] + fl["chunks_served"] > 0

        # ---- SIGKILL r2 mid-load -------------------------------------
        proc2.kill()                             # SIGKILL, not TERM
        proc2.wait(timeout=30)
        assert proc2.returncode == -signal.SIGKILL
        # the survivor answers every request through the kill: peer
        # fetches fail onto the local-decode fallback, never the client
        t_kill = time.monotonic()
        for _ in range(3):
            drive(p1, "during-kill")
        assert failures == [], failures

        # ---- eviction within the window ------------------------------
        evicted_at = None
        while time.monotonic() - t_kill < 20.0:
            fl = _wire(p1, {"op": "fleet", "id": 1})["fleet"]
            if fl["membership"]["peers"]["r2"]["state"] == "evicted":
                evicted_at = time.monotonic() - t_kill
                break
            time.sleep(0.2)
        assert evicted_at is not None, "r2 never evicted"
        # window: eviction_s (1.5) + heartbeat jitter + poll slack
        assert evicted_at < 15.0
        assert fl["degraded"] is True            # 1 of 2 visible
        breaker = fl["peer_breakers"]["r2"]
        assert breaker["opened_total"] >= 1      # heartbeats tripped it
        drive(p1, "post-evict")
        assert failures == [], failures

        # ---- rejoin through half-open probes -------------------------
        s2b, proc2 = _spawn_replica("r2", p2, peers, fleet_bam)
        procs[1] = proc2
        _await_replica(p2)
        rejoined = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0:
            fl = _wire(p1, {"op": "fleet", "id": 1})["fleet"]
            st = fl["membership"]["peers"]["r2"]["state"]
            brk = fl["peer_breakers"]["r2"]
            if st == "alive" and brk["state"] == "closed":
                rejoined = True
                break
            time.sleep(0.2)
        assert rejoined, f"r2 never rejoined: {fl}"
        assert fl["peer_breakers"]["r2"]["healed_total"] >= 1
        assert fl["membership"]["rejoins_total"] >= 1
        assert fl["degraded"] is False
        drive(p1, "post-rejoin")
        drive(p2, "rejoined-r2")
        assert failures == [], failures
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                pass
        for sp in (s1, s2):
            if os.path.exists(sp):
                os.unlink(sp)
