"""Deterministic synthetic fixtures.

The reference checks in tiny .sam/.bam/.vcf files under src/test/resources/
(SURVEY.md section 4).  With no reference mount and no pysam in the image, our
fixtures are *generated from the spec layer itself* and cross-checked against
independent implementations where possible (Python gzip for BGZF, hand-built
byte layouts for BAM records).
"""
from __future__ import annotations

import random
import string
from typing import List

from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.sam import SamRecord


def make_header(n_ref: int = 3) -> SAMHeader:
    names = [f"chr{i + 1}" for i in range(n_ref)]
    lengths = [1_000_000 * (i + 1) for i in range(n_ref)]
    text = "@HD\tVN:1.6\tSO:coordinate\n" + "".join(
        f"@SQ\tSN:{n}\tLN:{l}\n" for n, l in zip(names, lengths))
    return SAMHeader(text=text, ref_names=names, ref_lengths=lengths)


def make_records(header: SAMHeader, n: int, seed: int = 0,
                 with_tags: bool = True) -> List[SamRecord]:
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        l_seq = rng.randint(20, 150)
        seq = "".join(rng.choice("ACGT") for _ in range(l_seq))
        qual = "".join(chr(33 + rng.randint(0, 41)) for _ in range(l_seq))
        rid = rng.randrange(header.n_ref)
        pos = rng.randint(1, header.ref_lengths[rid] - l_seq)
        flag = rng.choice([0, 16, 99, 147, 83, 163, 4])
        tags = []
        if with_tags:
            tags = [("NM", "i", rng.randint(0, 5)),
                    ("RG", "Z", f"rg{rng.randint(0, 3)}")]
            if rng.random() < 0.3:
                tags.append(("AS", "i", rng.randint(0, 300)))
        cigar = f"{l_seq}M" if flag != 4 else "*"
        recs.append(SamRecord(
            qname=f"read{i:06d}_{''.join(rng.choice(string.ascii_lowercase) for _ in range(4))}",
            flag=flag,
            rname=header.ref_names[rid] if flag != 4 else "*",
            pos=pos if flag != 4 else 0,
            mapq=rng.randint(0, 60) if flag != 4 else 0,
            cigar=cigar,
            rnext="=" if flag & 0x1 else "*",
            pnext=pos + rng.randint(-200, 200) if flag & 0x1 else 0,
            tlen=rng.randint(-500, 500) if flag & 0x1 else 0,
            seq=seq, qual=qual, tags=tags))
    return recs
