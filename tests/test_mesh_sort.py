"""Mesh bucketed sort tests (parallel/mesh_sort.py).

The acceptance bar from the build plan: byte-identical output to the
single-process spill-merge sort on the virtual 8-device CPU mesh — the
all_to_all bucket exchange and the device multi-key sort must reproduce
a stable (key, input order) sort exactly, including pathological key
distributions (everything in one bucket, all-unmapped, ties everywhere).
"""
import os
import random

import pytest

from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
from hadoop_bam_tpu.utils.sort import sort_bam

from fixtures import make_header, make_records


def _write_shuffled(tmp_path, recs, header, seed=1):
    rng = random.Random(seed)
    recs = list(recs)
    rng.shuffle(recs)
    path = str(tmp_path / "in.bam")
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path


def _assert_identical(tmp_path, path, exchange=None):
    a = str(tmp_path / "single.bam")
    b = str(tmp_path / "mesh.bam")
    n1 = sort_bam(path, a)
    n2 = sort_bam_mesh(path, b, exchange=exchange)
    assert n1 == n2
    assert open(a, "rb").read() == open(b, "rb").read()
    return n1


def test_mesh_sort_byte_identical(tmp_path):
    header = make_header()
    recs = make_records(header, 3000, seed=42)
    path = _write_shuffled(tmp_path, recs, header)
    assert _assert_identical(tmp_path, path) == 3000


def test_mesh_sort_skewed_single_bucket(tmp_path):
    """Every record at the same (refid, pos): ties everywhere, one bucket
    receives the entire file — exercises the n_dev*records_cap receive
    capacity and the input-order tie-break."""
    from hadoop_bam_tpu.formats.sam import SamRecord
    header = make_header()
    recs = [SamRecord(qname=f"r{i}", flag=0, rname=header.ref_names[0],
                      pos=500, mapq=9, cigar="10M", rnext="*", pnext=0,
                      tlen=0, seq="ACGTACGTAC", qual="IIIIIIIIII")
            for i in range(800)]
    path = _write_shuffled(tmp_path, recs, header, seed=3)
    _assert_identical(tmp_path, path)


def test_mesh_sort_unmapped_mix(tmp_path):
    """Unmapped records (refid -1) must sort last, exactly as the
    single-process coordinate_key orders them."""
    from hadoop_bam_tpu.formats.sam import SamRecord
    header = make_header()
    rng = random.Random(5)
    recs = []
    for i in range(600):
        unmapped = rng.random() < 0.3
        recs.append(SamRecord(
            qname=f"q{i}", flag=4 if unmapped else 0,
            rname="*" if unmapped else rng.choice(header.ref_names),
            pos=0 if unmapped else rng.randint(1, 10000), mapq=0,
            cigar="*" if unmapped else "8M", rnext="*", pnext=0, tlen=0,
            seq="ACGTACGT", qual="IIIIIIII"))
    path = _write_shuffled(tmp_path, recs, header, seed=6)
    _assert_identical(tmp_path, path)


def test_mesh_sort_fewer_records_than_devices(tmp_path):
    header = make_header()
    recs = make_records(header, 3, seed=9)
    path = _write_shuffled(tmp_path, recs, header, seed=9)
    _assert_identical(tmp_path, path)


@pytest.mark.parametrize("case", ["mixed", "skewed", "tiny"])
def test_mesh_sort_bytes_exchange_identical(tmp_path, case):
    """The byte-exchange shuffle (records ride the all_to_all) must be
    byte-identical to both the index-exchange mesh sort and sort_bam."""
    from hadoop_bam_tpu.formats.sam import SamRecord
    header = make_header()
    if case == "mixed":
        recs = make_records(header, 1500, seed=21)
    elif case == "skewed":
        recs = [SamRecord(qname=f"r{i}", flag=0, rname=header.ref_names[0],
                          pos=500, mapq=9, cigar="10M", rnext="*", pnext=0,
                          tlen=0, seq="ACGTACGTAC", qual="IIIIIIIIII")
                for i in range(700)]
    else:
        recs = make_records(header, 5, seed=23)
    path = _write_shuffled(tmp_path, recs, header, seed=22)
    _assert_identical(tmp_path, path, exchange="bytes")


def test_mesh_sort_exchange_validation(tmp_path):
    header = make_header()
    recs = make_records(header, 10, seed=30)
    path = _write_shuffled(tmp_path, recs, header, seed=30)
    with pytest.raises(ValueError, match="exchange"):
        sort_bam_mesh(path, str(tmp_path / "o.bam"), exchange="nope")


_MULTIHOST_CHILD = """\
import os, sys
idx, port, src, out = int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4]
# 2 virtual CPU devices per process via XLA_FLAGS: works on every jax
# (the jax_num_cpu_devices config option only exists on newer releases)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=idx)
assert jax.process_count() == 2 and len(jax.devices()) == 4
from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
n = sort_bam_mesh(src, out)      # multi-host default: exchange="bytes"
print("SORTED", n, flush=True)
n2 = sort_bam_mesh(src, out + ".spill", round_records=150)
print("SPILLED", n2, flush=True)
"""


def test_mesh_sort_two_process_distributed(tmp_path):
    """The VERDICT r3 acceptance bar: a REAL 2-process jax.distributed
    run (gloo CPU collectives, 2 devices per process) where each process
    decodes only its spans, byte-identical to sort_bam."""
    from _multihost import run_two_process

    header = make_header()
    recs = make_records(header, 1200, seed=33)
    path = _write_shuffled(tmp_path, recs, header, seed=33)
    out = str(tmp_path / "dist.bam")
    for rc, so, se in run_two_process(tmp_path, _MULTIHOST_CHILD,
                                      [path, out]):
        assert rc == 0, f"child failed:\n{so}\n{se[-2000:]}"
        assert "SORTED 1200" in so
        assert "SPILLED 1200" in so
    ref = str(tmp_path / "ref.bam")
    sort_bam(path, ref)
    assert open(out, "rb").read() == open(ref, "rb").read()
    # the multi-round spill exchange (1200 records through 150-record
    # rounds = 2+ rounds of 4 devices) is byte-identical too
    assert open(out + ".spill", "rb").read() == open(ref, "rb").read()


def test_mesh_sort_cli(tmp_path):
    from hadoop_bam_tpu.tools.cli import main
    header = make_header()
    recs = make_records(header, 400, seed=12)
    path = _write_shuffled(tmp_path, recs, header, seed=12)
    out = str(tmp_path / "cli.bam")
    assert main(["sort", "--mesh", path, out]) == 0
    ref = str(tmp_path / "ref.bam")
    sort_bam(path, ref)
    assert open(out, "rb").read() == open(ref, "rb").read()
    # --mesh with -n is a loud error, not a silent wrong sort
    with pytest.raises(SystemExit):
        main(["sort", "--mesh", "-n", path, str(tmp_path / "x.bam")])


# ---------------------------------------------------------------------------
# multi-round spill exchange (VERDICT r4 #6)
# ---------------------------------------------------------------------------

def _assert_spill_identical(tmp_path, path, round_records):
    a = str(tmp_path / "single_sp.bam")
    b = str(tmp_path / "mesh_sp.bam")
    n1 = sort_bam(path, a)
    n2 = sort_bam_mesh(path, b, round_records=round_records)
    assert n1 == n2
    assert open(a, "rb").read() == open(b, "rb").read()
    return n1


def test_spill_sort_byte_identical_many_rounds(tmp_path):
    """round_records far below the file size forces several all_to_all
    rounds + per-bucket run merges; output must still be byte-identical
    to the single-process sort (file >> per-round capacity — the r4
    verdict's acceptance case)."""
    header = make_header()
    recs = make_records(header, 4000, seed=77)
    path = _write_shuffled(tmp_path, recs, header, seed=5)
    # ~4000 records / 200 per span -> 20 spans -> 3 rounds on 8 devices
    assert _assert_spill_identical(tmp_path, path, round_records=200) \
        == 4000


def test_spill_sort_single_round_degenerate(tmp_path):
    """round_records >= the file: one round, still identical."""
    header = make_header()
    recs = make_records(header, 600, seed=9)
    path = _write_shuffled(tmp_path, recs, header, seed=6)
    assert _assert_spill_identical(tmp_path, path, round_records=10_000) \
        == 600


def test_spill_sort_skew_and_ties(tmp_path):
    """All records on one key: every round dumps its whole tile into one
    bucket, and the cross-round merge must still reproduce input order
    (gidx ties) exactly."""
    from hadoop_bam_tpu.formats.sam import SamRecord
    header = make_header()
    recs = [SamRecord(qname=f"r{i}", flag=0, rname=header.ref_names[0],
                      pos=500, mapq=9, cigar="10M", rnext="*", pnext=0,
                      tlen=0, seq="ACGTACGTAC", qual="IIIIIIIIII")
            for i in range(900)]
    path = _write_shuffled(tmp_path, recs, header, seed=11)
    _assert_spill_identical(tmp_path, path, round_records=100)


def test_spill_sort_unmapped_mix(tmp_path):
    """Unmapped records (refid -1, in make_records' random flag mix)
    sort last across rounds too."""
    header = make_header()
    recs = make_records(header, 1200, seed=13)
    path = _write_shuffled(tmp_path, recs, header, seed=7)
    _assert_spill_identical(tmp_path, path, round_records=150)


def test_spill_requires_bytes_exchange(tmp_path):
    header = make_header()
    recs = make_records(header, 50, seed=1)
    path = _write_shuffled(tmp_path, recs, header)
    with pytest.raises(ValueError, match="bytes"):
        sort_bam_mesh(path, str(tmp_path / "o.bam"), exchange="index",
                      round_records=10)


def test_bytes_and_spill_on_single_device_mesh(tmp_path):
    """A 1-device mesh produces whole-axis shard indices (slice(None),
    start=None): the bucket extraction must map that to bucket 0 — both
    byte-exchange flavors previously crashed on single-device meshes."""
    import jax
    from jax.sharding import Mesh

    header = make_header()
    recs = make_records(header, 500, seed=44)
    path = _write_shuffled(tmp_path, recs, header, seed=44)
    import numpy as np
    mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    ref = str(tmp_path / "ref1.bam")
    sort_bam(path, ref)
    for label, kw in (("bytes", dict(exchange="bytes")),
                      ("spill", dict(round_records=100))):
        out = str(tmp_path / f"one_{label}.bam")
        n = sort_bam_mesh(path, out, mesh=mesh1, **kw)
        assert n == 500
        assert open(out, "rb").read() == open(ref, "rb").read(), label


def test_spill_dir_removed_on_success_and_failure(tmp_path, monkeypatch):
    """The .mesh-spill run directory must not survive the sort — neither
    a clean run nor one that dies mid-merge (ADVICE r5) — unless the
    debug_keep_spill knob asks for the post-mortem."""
    import dataclasses

    import hadoop_bam_tpu.parallel.mesh_sort as ms
    from hadoop_bam_tpu.config import DEFAULT_CONFIG

    header = make_header()
    recs = make_records(header, 600, seed=21)
    path = _write_shuffled(tmp_path, recs, header, seed=9)
    out = str(tmp_path / "o.bam")
    spill = out + ".mesh-spill"

    sort_bam_mesh(path, out, round_records=100)
    assert not os.path.exists(spill)

    def boom(run_paths):
        raise RuntimeError("injected merge failure")
    monkeypatch.setattr(ms, "_merge_bucket_runs", boom)
    with pytest.raises(RuntimeError, match="injected merge failure"):
        sort_bam_mesh(path, out + "2", round_records=100)
    assert not os.path.exists(out + "2.mesh-spill")

    cfg = dataclasses.replace(DEFAULT_CONFIG, debug_keep_spill=True)
    with pytest.raises(RuntimeError, match="injected merge failure"):
        sort_bam_mesh(path, out + "3", round_records=100, config=cfg)
    assert os.path.isdir(out + "3.mesh-spill")      # kept for autopsy


def test_int32_ceiling_raises_plan_error_up_front(tmp_path, monkeypatch):
    """Past 2^31 records the int32 global-index layout would silently
    wrap; the guard must be a clearly-messaged PlanError — and when a
    splitting-index sidecar records the exact total, it must fire UP
    FRONT, before any planning or decoding touches the file (VERDICT r5
    next #8)."""
    from hadoop_bam_tpu.parallel import mesh_sort as ms
    from hadoop_bam_tpu.split.splitting_index import SplittingIndex
    from hadoop_bam_tpu.utils.errors import PlanError

    with pytest.raises(PlanError, match="global-index ceiling"):
        ms.check_global_index_ceiling(2**31, "unit")
    with pytest.raises(ValueError):      # PlanError stays a ValueError
        ms.check_global_index_ceiling(2**31, "unit")
    ms.check_global_index_ceiling(ms.GLOBAL_INDEX_CEILING, "unit")  # at cap

    class _Huge:
        total_records = 2**31 + 5
        granularity = 4096
        voffsets = [0, 1 << 16]

    monkeypatch.setattr(SplittingIndex, "load_for",
                        classmethod(lambda cls, p: _Huge()))
    # a nonexistent input proves the check fires before any file I/O
    with pytest.raises(PlanError, match="spill"):
        sort_bam_mesh(str(tmp_path / "absent.bam"),
                      str(tmp_path / "out.bam"))
