"""Payload tiles + Pallas seq/qual kernels, on the virtual CPU mesh
(interpret mode; the TPU lowering is exercised by bench/CLI runs)."""
import random

import numpy as np
import pytest

from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.ops.seq_pallas import (
    seq_qual_stats, seq_qual_stats_host, unpack_bases,
)
from hadoop_bam_tpu.parallel.pipeline import (
    PayloadGeometry, decode_span_payload_host, seq_stats_file,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans

GEOM = PayloadGeometry(max_len=160, tile_records=1 << 10, block_n=256)


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    rng = random.Random(7)
    path = str(tmp_path_factory.mktemp("seqp") / "p.bam")
    header = SAMHeader.from_sam_text(
        "@HD\tVN:1.6\n@SQ\tSN:c1\tLN:1000000\n")
    recs = []
    for i in range(3000):
        n = rng.randint(30, 170)  # some exceed max_len -> truncation path
        seq = "".join(rng.choice("ACGTN") for _ in range(n))
        qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(n))
        recs.append(SamRecord(
            qname=f"q{i}", flag=99, rname="c1", pos=10 + i * 3, mapq=60,
            cigar=f"{n}M", rnext="=", pnext=500, tlen=100, seq=seq,
            qual=qual))
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path, header, recs


def test_payload_pack_native_matches_fallback(bam):
    path, header, recs = bam
    from hadoop_bam_tpu.utils import native
    if not native.available():
        pytest.skip("native library unavailable")
    spans = plan_bam_spans(path, num_spans=3, header=header)
    for s in spans:
        p1, s1, q1, _ = decode_span_payload_host(path, s, GEOM)
        orig = native.available
        native.available = lambda: False
        try:
            p2, s2, q2, _ = decode_span_payload_host(path, s, GEOM)
        finally:
            native.available = orig
        np.testing.assert_array_equal(p1, p2)
        np.testing.assert_array_equal(s1, s2)
        np.testing.assert_array_equal(q1, q2)


def test_payload_pack_content(bam):
    """Packed seq/qual decode back to the original read strings."""
    path, header, recs = bam
    spans = plan_bam_spans(path, num_spans=1, header=header)
    prefix, seq, qual, _ = decode_span_payload_host(path, spans[0], GEOM)
    assert prefix.shape[0] == len(recs)
    codes = np.asarray(unpack_bases(seq))
    code_to_base = {1: "A", 2: "C", 4: "G", 8: "T", 15: "N"}
    for i in (0, 7, len(recs) - 1):
        n = min(len(recs[i].seq), GEOM.max_len)
        got = "".join(code_to_base[int(c)] for c in codes[i, :n])
        assert got == recs[i].seq[:n]
        got_q = "".join(chr(33 + int(q)) for q in qual[i, :n])
        assert got_q == recs[i].qual[:n]


@pytest.mark.parametrize("force_pallas", [False, True])
def test_kernel_matches_host_oracle(bam, force_pallas):
    path, header, recs = bam
    spans = plan_bam_spans(path, num_spans=1, header=header)
    prefix, seq, qual, _ = decode_span_payload_host(path, spans[0], GEOM)
    n = prefix.shape[0]
    pad = (-n) % GEOM.block_n
    seq = np.concatenate([seq, np.zeros((pad, seq.shape[1]), np.uint8)])
    qual = np.concatenate([qual, np.zeros((pad, qual.shape[1]), np.uint8)])
    l_seq = prefix[:, 20:24].copy().view("<i4")[:, 0]
    lens = np.concatenate([np.minimum(l_seq, GEOM.max_len).astype(np.int32),
                           np.zeros(pad, np.int32)])
    out = seq_qual_stats(seq, qual, lens, block_n=GEOM.block_n,
                         force_pallas=force_pallas)
    ref = seq_qual_stats_host(seq, qual, lens)
    np.testing.assert_allclose(np.asarray(out["gc"]), ref["gc"], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["mean_qual"]),
                               ref["mean_qual"], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["base_hist"]),
                               ref["base_hist"])


@pytest.mark.parametrize("force_pallas", [False, True])
def test_base_hist_exact_past_2_24(force_pallas):
    """Histogram counts stay exact past 2^24 total bases — the f32
    accumulator this replaced loses integer precision there (and cannot
    represent the odd total at all)."""
    n, block_n = 2048, 256
    L = 16383
    seq = np.full((n, (L + 1) // 2), 0x11, np.uint8)   # all 'A' (code 1)
    qual = np.full((n, L), 40, np.uint8)
    lengths = np.full(n, L, np.int32)
    lengths[0] = L - 1                                  # odd total
    out = seq_qual_stats(seq, qual, lengths, block_n=block_n,
                         force_pallas=force_pallas)
    hist = np.asarray(out["base_hist"])
    assert hist.dtype.kind == "i"
    total = int(lengths.astype(np.int64).sum())
    assert total > (1 << 24) and total % 2 == 1
    assert int(hist[1]) == total
    assert int(hist.sum()) == total


def test_seq_stats_file_matches_oracle(bam):
    path, header, recs = bam
    stats = seq_stats_file(path, header=header, geometry=GEOM)
    assert stats["n_reads"] == len(recs)
    gcs, mqs, total = [], [], 0
    for r in recs:
        s, q = r.seq[:GEOM.max_len], r.qual[:GEOM.max_len]
        gcs.append(sum(1 for c in s if c in "GC") / len(s))
        mqs.append(sum(ord(c) - 33 for c in q) / len(q))
        total += len(s)
    assert abs(stats["mean_gc"] - float(np.mean(gcs))) < 1e-6
    assert abs(stats["mean_qual"] - float(np.mean(mqs))) < 1e-4
    assert abs(stats["base_hist"].sum() - total) < 1e-3


def test_fastq_stats_and_tensor_batches(tmp_path):
    """FASTQ through the same payload kernel: stats match a host oracle
    and tensor batches cover every read once."""
    rng = random.Random(11)
    path = str(tmp_path / "r.fastq")
    reads = []
    with open(path, "w") as f:
        for i in range(2500):
            n = rng.randint(40, 170)
            seq = "".join(rng.choice("ACGTN") for _ in range(n))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(n))
            reads.append((seq, qual))
            f.write(f"@read{i}\n{seq}\n+\n{qual}\n")
    from hadoop_bam_tpu.parallel.pipeline import fastq_seq_stats_file
    stats = fastq_seq_stats_file(path, geometry=GEOM)
    assert stats["n_reads"] == 2500
    gcs = [sum(1 for c in s[:160] if c in "GC") / len(s[:160])
           for s, _ in reads]
    mqs = [sum(ord(c) - 33 for c in q[:160]) / len(q[:160])
           for _, q in reads]
    assert abs(stats["mean_gc"] - float(np.mean(gcs))) < 1e-6
    assert abs(stats["mean_qual"] - float(np.mean(mqs))) < 1e-4

    from hadoop_bam_tpu.api.read_datasets import open_fastq
    ds = open_fastq(path)
    total = 0
    for batch in ds.tensor_batches(geometry=GEOM, num_spans=3):
        counts = np.asarray(batch["n_records"])
        total += int(counts.sum())
        assert batch["seq_packed"].shape[1:] == (GEOM.tile_records,
                                                 GEOM.seq_stride)
        # decode the first read of the first shard and compare
        if total == int(counts.sum()) and counts[0]:
            codes = np.asarray(unpack_bases(np.asarray(
                batch["seq_packed"])[0][:1]))
            code_to_base = {1: "A", 2: "C", 4: "G", 8: "T", 15: "N"}
            ln = int(np.asarray(batch["lengths"])[0, 0])
            got = "".join(code_to_base[int(c)] for c in codes[0, :ln])
            assert got == reads[0][0][:GEOM.max_len]
    assert total == 2500


def test_tensor_batches_api(bam):
    path, header, recs = bam
    from hadoop_bam_tpu.api import open_bam
    from hadoop_bam_tpu.ops.unpack_bam import unpack_fixed_fields_tile
    ds = open_bam(path)
    total = 0
    for batch in ds.tensor_batches(geometry=GEOM, num_spans=4):
        counts = np.asarray(batch["n_records"])
        total += int(counts.sum())
        assert batch["seq_packed"].shape[1:] == (GEOM.tile_records,
                                                 GEOM.seq_stride)
        # spot-check: first shard's first record columns decode sanely
        cols = unpack_fixed_fields_tile(np.asarray(batch["prefix"])[0])
        if counts[0]:
            assert int(np.asarray(cols["flag"])[0]) == 99
    assert total == len(recs)


def test_fasta_window_tensor_batches(tmp_path):
    """Reference windows pack into nibble tiles covering every base."""
    rng = random.Random(3)
    path = str(tmp_path / "ref.fa")
    sizes = {"ctg0": 700, "ctg1": 1500, "ctg2": 2300}
    contigs = {name: "".join(rng.choice("ACGT") for _ in range(n))
               for name, n in sizes.items()}
    with open(path, "w") as f:
        for name, seq in contigs.items():
            f.write(f">{name}\n")
            for i in range(0, len(seq), 70):
                f.write(seq[i:i + 70] + "\n")
    from hadoop_bam_tpu.api.read_datasets import open_fasta
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry
    ds = open_fasta(path)
    g = PayloadGeometry(max_len=1024, tile_records=256, block_n=256)
    windows = 0
    for batch in ds.window_tensor_batches(window=1024, geometry=g,
                                          num_spans=2):
        windows += int(np.asarray(batch["n_records"]).sum())
        lens = np.asarray(batch["lengths"])
    # 700 -> 1 short window; 1500 -> ceil((1500-1024)/1024)+... starts
    # {0, 476}; 2300 -> starts {0, 1024, 1276}
    assert windows == 1 + 2 + 3


def test_qseq_stats_driver(tmp_path):
    """QSEQ through the payload stats driver (vectorized fast path) must
    match a host oracle computed from the parsed fragments."""
    import random

    from hadoop_bam_tpu.api.writers import QseqShardWriter
    from hadoop_bam_tpu.formats.fastq import SequencedFragment
    from hadoop_bam_tpu.parallel.pipeline import fastq_seq_stats_file

    rng = random.Random(13)
    frags = []
    for i in range(800):
        n = rng.randint(30, 150)
        seq = "".join(rng.choice("ACGTN") for _ in range(n))
        qual = "".join(chr(33 + rng.randint(0, 41)) for _ in range(n))
        f = SequencedFragment.from_name(
            f"M:1:F:1:{i}:{i}:{i} 1:N:0:AAA", seq, qual)
        frags.append(f)
    path = str(tmp_path / "r.qseq")
    with QseqShardWriter(path) as w:
        for f in frags:
            w.write_record(f)
    stats = fastq_seq_stats_file(path, geometry=GEOM)
    assert stats["n_reads"] == len(frags)
    gcs = [sum(1 for c in f.sequence[:GEOM.max_len] if c in "GC")
           / len(f.sequence[:GEOM.max_len]) for f in frags]
    mqs = [sum(ord(c) - 33 for c in f.quality[:GEOM.max_len])
           / len(f.quality[:GEOM.max_len]) for f in frags]
    assert abs(stats["mean_gc"] - float(np.mean(gcs))) < 1e-6
    assert abs(stats["mean_qual"] - float(np.mean(mqs))) < 1e-4


def test_jnp_fallback_matches_pallas_interpreter_and_host():
    """The plain-XLA twin (non-TPU fast path) must agree with BOTH the
    Pallas kernel (run via the interpreter, force_pallas=True) and the
    NumPy host oracle."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    N, L = 512, 151
    seq = rng.integers(0, 256, (N, (L + 1) // 2), dtype=np.uint8)
    qual = rng.integers(0, 42, (N, L), dtype=np.uint8)
    lens = rng.integers(0, L + 1, N).astype(np.int32)
    a = seq_qual_stats(jnp.asarray(seq), jnp.asarray(qual),
                       jnp.asarray(lens), interpret=True)
    b = seq_qual_stats(jnp.asarray(seq), jnp.asarray(qual),
                       jnp.asarray(lens), interpret=True,
                       force_pallas=True)
    h = seq_qual_stats_host(seq, qual, lens)
    for got in (a, b):
        np.testing.assert_allclose(np.asarray(got["gc"]),
                                   np.asarray(h["gc"]), atol=1e-6)
        np.testing.assert_allclose(np.asarray(got["mean_qual"]),
                                   np.asarray(h["mean_qual"]), atol=1e-4)
        np.testing.assert_array_equal(np.asarray(got["base_hist"]),
                                      np.asarray(h["base_hist"]))


def test_cram_seq_stats_driver(tmp_path):
    """CRAM member of the seq-stats family: driver answers must match
    the host oracle computed from the decoded records."""
    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.cramio import write_cram
    from hadoop_bam_tpu.formats.sam import SamRecord as SR
    from hadoop_bam_tpu.parallel.pipeline import cram_seq_stats_file

    rng = random.Random(19)
    hdr = SAMHeader.from_sam_text(
        "@HD\tVN:1.6\n@SQ\tSN:c1\tLN:100000\n")
    recs = []
    pos = 1
    for i in range(700):
        l = rng.randint(20, 100)
        seq = "".join(rng.choice("ACGT") for _ in range(l))
        qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(l))
        pos += rng.randint(1, 9)
        recs.append(SR(qname=f"r{i}", flag=0, rname="c1", pos=pos,
                       mapq=60, cigar=f"{l}M", rnext="*", pnext=0,
                       tlen=0, seq=seq, qual=qual))
    path = str(tmp_path / "s.cram")
    with open(path, "wb") as f:
        write_cram(f, hdr, recs)

    stats = cram_seq_stats_file(path)
    assert stats["n_reads"] == 700
    gc_ref = np.mean([sum(c in "GC" for c in r.seq) / len(r.seq)
                      for r in recs])
    mq_ref = np.mean([np.mean([ord(c) - 33 for c in r.qual])
                      for r in recs])
    assert abs(stats["mean_gc"] - gc_ref) < 1e-3
    assert abs(stats["mean_qual"] - mq_ref) < 1e-2
    total_bases = sum(len(r.seq) for r in recs)
    assert int(np.asarray(stats["base_hist"]).sum()) == total_bases


def test_cli_seq_stats_cram(tmp_path, capsys):
    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.cramio import write_cram
    from hadoop_bam_tpu.formats.sam import SamRecord as SR
    from hadoop_bam_tpu.tools.cli import main

    hdr = SAMHeader.from_sam_text("@HD\tVN:1.6\n@SQ\tSN:c1\tLN:9999\n")
    recs = [SR(qname=f"r{i}", flag=0, rname="c1", pos=1 + i, mapq=60,
               cigar="10M", rnext="*", pnext=0, tlen=0,
               seq="ACGTACGTAC", qual="IIIIIIIIII") for i in range(200)]
    path = str(tmp_path / "cli.cram")
    with open(path, "wb") as f:
        write_cram(f, hdr, recs)
    assert main(["seq-stats", path]) == 0
    out = capsys.readouterr().out
    assert "reads\t200" in out
