"""Shared 2-process ``jax.distributed`` test harness.

Both multi-host tests (mesh sort, distributed flagstat) spawn two real
coordinated processes with gloo CPU collectives; this is the one copy
of the orchestration (child script materialization, coordinator port,
PYTHONPATH, spawn, kill-on-failure).
"""
import os
import socket
import subprocess
import sys


def run_two_process(tmp_path, child_source: str, child_args,
                    timeout: float = 360.0):
    """Run ``child_source`` in two coordinated subprocesses.

    Each child gets argv ``(index, coordinator_port, *child_args)``.
    Children that outlive a timeout or failure are killed.  Returns
    ``[(returncode, stdout, stderr), ...]`` in process order.
    """
    child = str(tmp_path / "multihost_child.py")
    with open(child, "w") as f:
        f.write(child_source)
    with socket.socket() as s:
        # bind-then-close has a TOCTOU window; acceptable on the
        # single-tenant CI host
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, child, str(i), str(port), *map(str, child_args)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo) for i in range(2)]
    try:
        outs = [p.communicate(timeout=timeout) for p in procs]
    finally:
        for p in procs:        # a hung/failed child must not outlive pytest
            if p.poll() is None:
                p.kill()
                p.communicate()
    return [(p.returncode, so, se) for p, (so, se) in zip(procs, outs)]
