"""hbam-lint suite tests: seeded-violation corpus, baseline round-trip,
and the repo-lints-clean CI gate (``pytest -m lint``).

Each analyzer gets at least one intentionally-bad snippet proving it
fires, plus a clean twin proving the approved idiom passes — the lint
suite is itself under test, so a silent analyzer regression (an analyzer
that stops finding anything) fails here, not in review.
"""
import json

import pytest

from hadoop_bam_tpu.analysis.core import (
    Baseline, Finding, Project, run_analyzers,
)

pytestmark = pytest.mark.lint


def lint_sources(sources, only=None):
    return run_analyzers(Project.from_sources(sources), only=only)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# trace safety (TS1xx)
# ---------------------------------------------------------------------------

def test_ts_seeded_violations_fire():
    findings = lint_sources({"hadoop_bam_tpu/ops/bad.py": '''
import jax
import numpy as np

@jax.jit
def f(x, n):
    if x > 0:                  # TS102
        x = x + 1
    for i in range(n):         # TS103
        x = x + i
    y = np.asarray(x)          # TS104
    return x.item()            # TS101
'''}, only=["trace_safety"])
    assert rules_of(findings) == {"TS101", "TS102", "TS103", "TS104"}
    assert all(f.path == "hadoop_bam_tpu/ops/bad.py" for f in findings)
    assert all(f.severity == "error" for f in findings)


def test_ts_reaches_through_shard_map_and_calls():
    findings = lint_sources({"hadoop_bam_tpu/parallel/bad.py": '''
from hadoop_bam_tpu.parallel.mesh import shard_map

def make_step(mesh):
    def per_device(tile, count):
        return helper(tile)
    return shard_map(per_device, mesh=mesh, in_specs=(), out_specs=())

def helper(t):
    return t.tolist()          # TS101, two hops from the shard_map root
'''}, only=["trace_safety"])
    assert rules_of(findings) == {"TS101"}
    assert "helper" in findings[0].message


def test_ts_static_argnames_and_shape_are_not_tracers():
    findings = lint_sources({"hadoop_bam_tpu/ops/good.py": '''
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def f(x, block_n, interpret):
    n = x.shape[0]
    if interpret:              # static arg: allowed
        block_n = 2 * block_n
    for i in range(n // block_n):   # shape-derived: allowed
        x = x + i
    return jnp.sum(x)
'''}, only=["trace_safety"])
    assert findings == []


def test_ts_unreached_host_helper_not_flagged():
    findings = lint_sources({"hadoop_bam_tpu/ops/oracle.py": '''
import numpy as np

def host_oracle(x):            # never traced: host NumPy is fine here
    out = np.asarray(x)
    return out.item()
'''}, only=["trace_safety"])
    assert findings == []


def test_ts_findings_pinned_across_engine_extraction():
    """TS1xx now runs on the shared interprocedural engine
    (``analysis/callgraph.py``); this pins rule, path, line, message,
    severity AND fingerprint so the extraction stays observably
    identical (fingerprints feed the baseline contract)."""
    findings = lint_sources({
        "hadoop_bam_tpu/ops/bad.py": '''
import jax
import numpy as np

@jax.jit
def f(x, n):
    if x > 0:
        x = x + 1
    for i in range(n):
        x = x + i
    y = np.asarray(x)
    return x.item()
''',
        "hadoop_bam_tpu/parallel/bad.py": '''
from hadoop_bam_tpu.parallel.mesh import shard_map

def make_step(mesh):
    def per_device(tile, count):
        return helper(tile)
    return shard_map(per_device, mesh=mesh, in_specs=(), out_specs=())

def helper(t):
    return t.tolist()
''',
    }, only=["trace_safety"])
    got = [(f.rule, f.path, f.line, f.message, f.severity, f.fingerprint)
           for f in findings]
    assert got == [
        ("TS102", "hadoop_bam_tpu/ops/bad.py", 7,
         "data-dependent Python branch on a traced value; use jnp.where "
         "/ lax.cond (in traced function 'f')", "error",
         "9b285a92eb74ecba"),
        ("TS103", "hadoop_bam_tpu/ops/bad.py", 9,
         "Python loop over a traced value; use lax control flow or "
         "vectorize (in traced function 'f')", "error",
         "c1b5129827abde42"),
        ("TS104", "hadoop_bam_tpu/ops/bad.py", 11,
         "host NumPy call 'np.asarray' on a traced value; use jnp "
         "(in traced function 'f')", "error", "3e9860b427381ca6"),
        ("TS101", "hadoop_bam_tpu/ops/bad.py", 12,
         ".item() forces a host sync on a traced value (in traced "
         "function 'f')", "error", "cc4fe5181e8ea137"),
        ("TS101", "hadoop_bam_tpu/parallel/bad.py", 10,
         ".tolist() forces a host sync on a traced value (in traced "
         "function 'helper')", "error", "045e954f117b94e5"),
    ]


# ---------------------------------------------------------------------------
# collective lockstep (CL2xx)
# ---------------------------------------------------------------------------

_CL_BAD = '''
import jax
import numpy as np
from jax.experimental import multihost_utils

def bad_rank_nested(x):
    pid = jax.process_index()
    if pid == 0:
        multihost_utils.process_allgather(x)      # CL201

def bad_divergent_order(x, flag):
    if flag:
        multihost_utils.broadcast_one_to_all(x)   # CL202: A then B
        multihost_utils.process_allgather(x)
    else:
        multihost_utils.process_allgather(x)      # CL202: B then A
        multihost_utils.broadcast_one_to_all(x)
'''

_CL_GOOD = '''
import jax
import numpy as np
from jax.experimental import multihost_utils

def good(plan, x):
    pid = jax.process_index()
    if jax.process_count() == 1:       # uniform test: fine
        return plan
    payload = plan if pid == 0 else None      # data diverges, not control
    out = multihost_utils.broadcast_one_to_all(x)   # unconditional
    if pid == 0:
        print("planner host")          # no collective under the rank test
    return out
'''


def test_cl_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/bad.py": _CL_BAD}, only=["lockstep"])
    assert rules_of(findings) == {"CL201", "CL202"}
    by_rule = {f.rule: f for f in findings}
    assert "bad_rank_nested" in by_rule["CL201"].message
    assert "bad_divergent_order" in by_rule["CL202"].message


def test_cl_uniform_and_data_conditionals_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/good.py": _CL_GOOD}, only=["lockstep"])
    assert findings == []


def test_cl_symmetric_branches_pass():
    findings = lint_sources({"hadoop_bam_tpu/parallel/sym.py": '''
from jax.experimental import multihost_utils

def symmetric(x, big):
    if big:
        y = multihost_utils.process_allgather(2 * x)
    else:
        y = multihost_utils.process_allgather(x)
    return y
'''}, only=["lockstep"])
    assert findings == []


# ---------------------------------------------------------------------------
# error taxonomy (ET3xx)
# ---------------------------------------------------------------------------

def test_et_seeded_violation_fires_only_at_boundaries():
    bad = '''
def f(n):
    if n < 0:
        raise ValueError("bad n")          # ET301 at a boundary module
'''
    findings = lint_sources(
        {"hadoop_bam_tpu/split/planners.py": bad}, only=["taxonomy"])
    assert rules_of(findings) == {"ET301"}
    # same code OUTSIDE the policy boundaries is not taxonomy-scoped
    findings = lint_sources(
        {"hadoop_bam_tpu/utils/other.py": bad}, only=["taxonomy"])
    assert findings == []


def test_et_scope_covers_write_and_serve_boundaries():
    """ISSUE 11 scope extension: bare builtins raised in the write-path
    and serve-tier boundary modules reach clients as the WRONG wire
    taxonomy (transport.error_kind) or poison the parallel writer —
    ET301 now fires there too."""
    bad = '''
def merge(parts, missing):
    if missing:
        raise RuntimeError("shards missing at merge time")
'''
    for mod in ("hadoop_bam_tpu/write/sharded.py",
                "hadoop_bam_tpu/write/parallel_bgzf.py",
                "hadoop_bam_tpu/serve/transport.py",
                "hadoop_bam_tpu/serve/loop.py"):
        findings = lint_sources({mod: bad}, only=["taxonomy"])
        assert rules_of(findings) == {"ET301"}, mod
    # non-boundary serve-adjacent code stays out of scope
    findings = lint_sources(
        {"hadoop_bam_tpu/serve/__init__.py": bad}, only=["taxonomy"])
    assert findings == []


def test_et_write_serve_clean_twin_passes():
    """The classified version of the same boundary code is clean."""
    good = '''
from hadoop_bam_tpu.utils.errors import PlanError, TransientIOError

def merge(parts, missing):
    if missing:
        raise TransientIOError("shards missing — shared-fs lag, retry")

def parse(doc):
    if not isinstance(doc, dict):
        raise PlanError("request must be a JSON object")
'''
    for mod in ("hadoop_bam_tpu/write/sharded.py",
                "hadoop_bam_tpu/serve/transport.py"):
        assert lint_sources({mod: good}, only=["taxonomy"]) == []


def test_et_scope_covers_cohort_boundaries():
    """ISSUE 12 scope extension: the cohort plane's boundary modules —
    a bare builtin there makes the per-input fault guard quarantine a
    configuration error (or fail a build on data the policy should
    have quarantined)."""
    bad = '''
def join(manifest):
    if not manifest:
        raise ValueError("empty manifest")
'''
    for mod in ("hadoop_bam_tpu/cohort/manifest.py",
                "hadoop_bam_tpu/cohort/join.py",
                "hadoop_bam_tpu/cohort/serving.py"):
        findings = lint_sources({mod: bad}, only=["taxonomy"])
        assert rules_of(findings) == {"ET301"}, mod
    # non-boundary cohort code (the pure harmonizer, the device
    # drivers) stays out of scope
    for mod in ("hadoop_bam_tpu/cohort/harmonize.py",
                "hadoop_bam_tpu/cohort/gwas.py",
                "hadoop_bam_tpu/cohort/dataset.py"):
        assert lint_sources({mod: bad}, only=["taxonomy"]) == [], mod


def test_et_cohort_clean_twin_passes():
    """The classified version of the same cohort boundary code is
    clean: PlanError for configuration, CorruptDataError for bytes."""
    good = '''
from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError

def load(doc):
    if not isinstance(doc, dict):
        raise PlanError("cohort manifest must be a JSON object")

def stream(records):
    for last, key in records:
        if key < last:
            raise CorruptDataError("records out of (contig, pos) order")
'''
    for mod in ("hadoop_bam_tpu/cohort/manifest.py",
                "hadoop_bam_tpu/cohort/join.py"):
        assert lint_sources({mod: good}, only=["taxonomy"]) == [], mod


def test_et_scope_covers_fleet_boundaries():
    """ISSUE 16 scope extension: the fleet modules are policy
    boundaries twice over — the error class decides whether a peer
    answer feeds that peer's circuit breaker (PLAN never does) AND what
    ``error_kind`` the peer sees on the wire.  A bare builtin raised
    there misroutes both."""
    bad = '''
def answer(resp):
    if "cols" not in resp:
        raise ValueError("peer answered without columns")
'''
    for mod in ("hadoop_bam_tpu/serve/fleet.py",
                "hadoop_bam_tpu/serve/membership.py"):
        findings = lint_sources({mod: bad}, only=["taxonomy"])
        assert rules_of(findings) == {"ET301"}, mod


def test_et_fleet_clean_twin_passes():
    """The classified version of the same fleet boundary code is
    clean: CorruptDataError for bad peer bytes, PlanError for a
    misconfigured roster, TransientIOError for a dead peer."""
    good = '''
from hadoop_bam_tpu.utils.errors import (
    CorruptDataError, PlanError, TransientIOError,
)

def answer(resp):
    if "cols" not in resp:
        raise CorruptDataError("peer answered without columns")

def roster(spec):
    if not spec:
        raise PlanError("a fleet needs a non-empty peer roster")

def dial(ok):
    if not ok:
        raise TransientIOError("peer closed the connection; retry")
'''
    for mod in ("hadoop_bam_tpu/serve/fleet.py",
                "hadoop_bam_tpu/serve/membership.py"):
        assert lint_sources({mod: good}, only=["taxonomy"]) == [], mod


def test_et_classified_raises_pass():
    findings = lint_sources({"hadoop_bam_tpu/formats/bgzf.py": '''
from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError

class BGZFError(CorruptDataError):
    pass

def f(buf, n):
    if n < 0:
        raise PlanError("bad span parameters")
    if not buf:
        raise BGZFError("truncated block")
    raise KeyboardInterrupt                    # re-raise style: not scoped
'''}, only=["taxonomy"])
    assert findings == []


# ---------------------------------------------------------------------------
# layout contracts (LC4xx)
# ---------------------------------------------------------------------------

def test_lc_unknown_struct_format_fires():
    findings = lint_sources({"hadoop_bam_tpu/formats/bad.py": '''
import struct

def parse(buf):
    return struct.unpack_from("<QQi", buf, 0)     # LC401: unregistered
'''}, only=["layout"])
    assert rules_of(findings) == {"LC401"}
    assert "<QQi" in findings[0].message


def test_lc_offset_contract_violations_fire():
    findings = lint_sources({"hadoop_bam_tpu/split/bam_guesser.py": '''
class BAMSplitGuesser:
    def _chain_ok(self, data, p, n):
        return data[p:p + 4]

    def _record_ok(self, data, p, n):
        ok = data[p + 13]                    # inside mapq: fine
        bad_span = data[p + 17:p + 19]       # LC403: crosses n_cigar/flag
        bad_byte = data[p + 36]              # LC403: past the prefix
        return ok
'''}, only=["layout"])
    lc403 = [f for f in findings if f.rule == "LC403"]
    assert len(lc403) == 2
    assert {f.line for f in lc403} == {8, 9}


def test_lc_exact_field_reads_pass():
    findings = lint_sources({"hadoop_bam_tpu/split/bam_guesser.py": '''
class BAMSplitGuesser:
    def _record_ok(self, data, p, n):
        bs = int.from_bytes(data[p:p + 4], "little", signed=True)
        refid = int.from_bytes(data[p + 4:p + 8], "little", signed=True)
        n_cigar = int.from_bytes(data[p + 16:p + 18], "little")
        whole = data[p:p + 36]               # full contiguous field run
        return bs, refid, n_cigar, whole

    def _chain_ok(self, data, p, n):
        return data[p:p + 4]
'''}, only=["layout"])
    assert [f for f in findings if f.severity == "error"] == []


def test_lc_runtime_mirror_drift_fires():
    findings = lint_sources({"hadoop_bam_tpu/ops/unpack_bam.py": '''
FIXED_FIELDS = {
    "block_size": (0, 4, True),
    "refid": (4, 4, True),
    "pos": (9, 4, True),
}
'''}, only=["layout"])
    assert "LC404" in rules_of(findings)
    (f,) = [f for f in findings if f.rule == "LC404"]
    assert "pos" in f.message


def test_lc_spec_table_self_check():
    from hadoop_bam_tpu.analysis.layout_specs import (
        SPECS, Field, LayoutSpec, spec_self_check,
    )
    for spec in SPECS.values():
        assert spec_self_check(spec) == (), spec.name
    broken = LayoutSpec(
        name="broken", doc="", fmt="<II",
        fields=(Field("a", 0, 4, "u32"), Field("b", 6, 2, "u16")))
    problems = spec_self_check(broken)
    assert any("gap or overlap" in p for p in problems)
    assert any("calcsize" in p for p in problems)


# ---------------------------------------------------------------------------
# feed-path allocation discipline (PF5xx)
# ---------------------------------------------------------------------------

_PF_BAD = '''
import numpy as np

def driver(stream, n_dev, cap, w):
    group = []

    def dispatch():
        out = np.zeros((n_dev, cap, w), dtype=np.uint8)    # PF501: emit fn
        return out

    def emit_group():
        return np.empty((n_dev, cap), dtype=np.int8)       # PF501: emit fn

    for tile in stream:
        pad = np.full((n_dev, cap, w), -1, np.int8)        # PF501: loop
        group.append(pad)
    return dispatch(), emit_group()
'''

_PF_CLEAN = '''
import numpy as np

def stack_span_group(source, n_dev, cap):
    # top-level body, not a loop, not an emit helper: one-shot staging
    data = np.zeros((n_dev, cap), dtype=np.uint8)
    return data

def dispatch(counts, n_dev):
    cvec = np.zeros((n_dev,), dtype=np.int32)   # 1-D count vector: noise
    return cvec

def per_tile(stream, cap, w):
    for t in stream:
        tile = np.zeros((cap, w), np.uint8)     # no device leading dim
        yield tile
'''


def test_pf_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/bad_feed.py": _PF_BAD},
        only=["feedpath"])
    assert rules_of(findings) == {"PF501"}
    assert len(findings) == 3
    assert all(f.severity == "error" for f in findings)
    assert "staging ring" in findings[0].message


def test_pf_clean_idioms_and_staging_ring_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/clean_feed.py": _PF_CLEAN},
        only=["feedpath"])
    assert findings == []
    # the staging ring module itself is the allowed owner of group
    # buffers — allocations there are exempt even inside loops
    findings = lint_sources({"hadoop_bam_tpu/parallel/staging.py": '''
import numpy as np

def ring(n_dev, cap, slots):
    out = []
    for _ in range(slots):
        out.append(np.full((n_dev, cap), 0, np.uint8))
    return out
'''}, only=["feedpath"])
    assert findings == []


def test_pf_outside_parallel_not_scoped():
    findings = lint_sources(
        {"hadoop_bam_tpu/ops/elsewhere.py": _PF_BAD}, only=["feedpath"])
    assert findings == []


# ---------------------------------------------------------------------------
# query-cache key identity (QE5xx)
# ---------------------------------------------------------------------------

_QE_BAD = '''
def lookup(cache, path, lo, hi):
    hit = cache.get((path, lo, hi))            # QE501: raw-path key
    if hit is None:
        hit = decode(path, lo, hi)
        cache.put((path, lo, hi), hit, 128)    # QE501 again
    return hit

def decode(path, lo, hi):
    return path
'''

_QE_CLEAN = '''
from hadoop_bam_tpu.query.cache import file_identity

def lookup(cache, path, lo, hi):
    ident = file_identity(path)
    hit = cache.get((ident, lo, hi))                 # identity name: ok
    if hit is None:
        hit = decode(path, lo, hi)
        cache.put((file_identity(path), lo, hi), hit, 128)  # call: ok
    stats = cache.get("toc")                         # no path at all: ok
    return hit, stats

def decode(path, lo, hi):
    return path
'''


def test_qe_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/query/bad_keys.py": _QE_BAD},
        only=["querycache"])
    assert rules_of(findings) == {"QE501"}
    assert len(findings) == 2
    assert all(f.severity == "error" for f in findings)
    assert "file_identity" in findings[0].message


def test_qe_identity_keys_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/query/good_keys.py": _QE_CLEAN},
        only=["querycache"])
    assert findings == []


def test_qe_outside_query_not_scoped():
    findings = lint_sources(
        {"hadoop_bam_tpu/split/elsewhere.py": _QE_BAD},
        only=["querycache"])
    assert findings == []


# ---------------------------------------------------------------------------
# observability discipline (OB6xx)
# ---------------------------------------------------------------------------

_OB_RAW_CLOCK = '''
import time

def decode_stage(spans):
    t0 = time.perf_counter()     # OB601: interval never reaches Metrics
    out = [s * 2 for s in spans]
    dt = time.perf_counter() - t0
    print("stage took", dt)
    return out
'''

_OB_CLOCK_FEEDS_METRICS = '''
import time
from hadoop_bam_tpu.utils.metrics import METRICS

def dispatch(arrays, do):
    t0 = time.perf_counter()
    out = do(arrays)
    METRICS.add_wall("pipeline.dispatch_wall", time.perf_counter() - t0)
    return out
'''

_OB_POOLED_TIMER = '''
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

def driver(pool, spans, work):
    def decode(span):
        with METRICS.timer("fmt.host_decode"):   # OB602: pool tasks
            return work(span)                    # overlap; thread-sum
    return list(_iter_windowed(pool, spans, decode, 8))
'''

_OB_POOLED_TIMER_WITH_WALL = '''
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

def driver(pool, spans, work):
    def decode(span):
        with METRICS.timer("fmt.host_decode"), \\
                METRICS.wall_timer("fmt.host_decode_wall"):
            return work(span)
    return list(_iter_windowed(pool, spans, decode, 8))
'''

_OB_POOLED_SPAN = '''
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.parallel.pipeline import _iter_windowed

def driver(pool, spans, work):
    def decode(span):
        with METRICS.span("fmt.host_decode_wall"):
            return work(span)
    return list(_iter_windowed(pool, spans, decode, 8))
'''


def test_ob_raw_clock_seeded_violation_fires():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/bad_clock.py": _OB_RAW_CLOCK},
        only=["obs"])
    assert rules_of(findings) == {"OB601"}
    assert len(findings) == 2        # both perf_counter calls
    assert all(f.severity == "error" for f in findings)
    assert "Metrics" in findings[0].message


def test_ob_clock_feeding_metrics_passes():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/ok_clock.py": _OB_CLOCK_FEEDS_METRICS},
        only=["obs"])
    assert findings == []


def test_ob_timer_in_pooled_decode_fires():
    findings = lint_sources(
        {"hadoop_bam_tpu/query/bad_timer.py": _OB_POOLED_TIMER},
        only=["obs"])
    assert rules_of(findings) == {"OB602"}
    assert "wall_timer" in findings[0].message


def test_ob_pooled_timer_with_wall_or_span_passes():
    for src in (_OB_POOLED_TIMER_WITH_WALL, _OB_POOLED_SPAN):
        findings = lint_sources(
            {"hadoop_bam_tpu/query/ok_timer.py": src}, only=["obs"])
        assert findings == []


def test_ob_outside_hot_paths_not_scoped():
    findings = lint_sources(
        {"hadoop_bam_tpu/formats/elsewhere.py": _OB_RAW_CLOCK},
        only=["obs"])
    assert findings == []


# ---------------------------------------------------------------------------
# OB603: entry points must mint/propagate a TraceContext
# ---------------------------------------------------------------------------

_OB_UNTRACED_ENTRY = '''
def handle_stream(loop, rfile, wfile):
    for line in rfile:                    # OB603: starts work with no
        fut = loop.submit(line)           # TraceContext minted
        fut.result()
'''

_OB_TRACED_ENTRY = '''
from hadoop_bam_tpu.obs.context import trace_context

def handle_stream(loop, rfile, wfile):
    for line in rfile:
        with trace_context(op="serve.request"):
            fut = loop.submit(line)
            fut.result()
'''

_OB_CLI_MAIN_MINTS = '''
from hadoop_bam_tpu.obs.context import trace_context

def cmd_sort(args):
    return run_sort(args.input)

def main(argv=None):
    args = parse(argv)
    with trace_context(op=f"cli.{args.verb}"):
        return args.fn(args)
'''

_OB_CLI_NO_MAIN_MINT = '''
def cmd_sort(args):
    return run_sort(args.input)

def main(argv=None):
    args = parse(argv)
    return args.fn(args)
'''


def test_ob603_untraced_entry_point_fires():
    findings = lint_sources(
        {"hadoop_bam_tpu/serve/bad_entry.py": _OB_UNTRACED_ENTRY},
        only=["obs"])
    assert rules_of(findings) == {"OB603"}
    assert "TraceContext" in findings[0].message


def test_ob603_traced_entry_point_passes():
    findings = lint_sources(
        {"hadoop_bam_tpu/serve/good_entry.py": _OB_TRACED_ENTRY},
        only=["obs"])
    assert findings == []


def test_ob603_cli_verbs_covered_by_main_mint():
    # the CLI-frontend idiom: one trace_context in main() covers every
    # cmd_* verb it dispatches to
    findings = lint_sources(
        {"hadoop_bam_tpu/tools/cli.py": _OB_CLI_MAIN_MINTS},
        only=["obs"])
    assert findings == []
    # ...but a main() that does NOT mint leaves the verbs flagged
    findings = lint_sources(
        {"hadoop_bam_tpu/tools/cli.py": _OB_CLI_NO_MAIN_MINT},
        only=["obs"])
    assert rules_of(findings) == {"OB603"}


def test_ob603_jobs_entry_and_scope():
    # run_job_level in jobs/ is an entry point...
    findings = lint_sources({"hadoop_bam_tpu/jobs/bad_runner.py": '''
def run_job_level(journal_path, kind, run):
    return run()
'''}, only=["obs"])
    assert rules_of(findings) == {"OB603"}
    # ...the same code outside the entry scope is not in scope
    findings = lint_sources({"hadoop_bam_tpu/split/elsewhere.py": '''
def run_job_level(journal_path, kind, run):
    return run()
'''}, only=["obs"])
    assert findings == []


def test_ob603_entry_point_with_no_work_passes():
    # an entry-point NAME that starts no work (pure accessor) is fine
    findings = lint_sources({"hadoop_bam_tpu/serve/idle.py": '''
def submit(self):
    return self._queue
'''}, only=["obs"])
    assert findings == []


# ---------------------------------------------------------------------------
# decode-path copy discipline (DP7xx)
# ---------------------------------------------------------------------------

_DP_BAD = '''
import numpy as np

def walk_fallback(data, start):
    buf = data.tobytes()                     # DP701: whole-span copy
    arr = np.frombuffer(buf, np.uint8).copy()  # DP702: copy of a view
    return buf, arr

class Decoder:
    def pack(self):
        return self.data.tobytes()           # DP701: attribute receiver
'''

_DP_CLEAN = '''
import numpy as np

def walk_fallback(data, start, s, e):
    head = data[s:e].tobytes()               # bounded slice: blessed
    crc_src = data[int(s):int(e)].tobytes()  # ditto
    view = np.frombuffer(head, np.uint8)     # zero-copy view: blessed
    whole = data.tobytes                     # bare reference, no call
    return head, crc_src, view, whole

FULL = None
SNAPSHOT = np.frombuffer(b"x", np.uint8)
'''


def test_dp_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/ops/inflate.py": _DP_BAD}, only=["decodepath"])
    assert rules_of(findings) == {"DP701", "DP702"}
    assert sum(f.rule == "DP701" for f in findings) == 2
    assert all(f.severity == "error" for f in findings)


def test_dp_clean_idioms_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/pipeline.py": _DP_CLEAN},
        only=["decodepath"])
    assert findings == []


def test_dp_outside_decode_path_not_scoped():
    # same bad source in a module off the inflated-span hot path: silent
    findings = lint_sources(
        {"hadoop_bam_tpu/formats/bam.py": _DP_BAD,
         "hadoop_bam_tpu/parallel/mesh_sort.py": _DP_BAD},
        only=["decodepath"])
    assert findings == []


def test_dp_module_level_code_not_scoped():
    # the rule fires only inside function bodies: module-level fixture
    # materializations (test corpora, constants) stay out of scope
    findings = lint_sources(
        {"hadoop_bam_tpu/ops/inflate.py": '''
import numpy as np
GOLDEN = np.zeros(4, np.uint8).tobytes()
'''}, only=["decodepath"])
    assert findings == []


# ---------------------------------------------------------------------------
# serving-tier cache bounds (SV8xx)
# ---------------------------------------------------------------------------

_SV_BAD = '''
from collections import OrderedDict

_STEP_CACHE = {}                     # SV801: module dict, insert only

def get_step(key, build):
    if key not in _STEP_CACHE:
        _STEP_CACHE[key] = build()
    return _STEP_CACHE[key]

class TileServer:
    def __init__(self):
        self.tile_cache = OrderedDict()   # SV801: never evicted
        self.client_log = []              # SV802: append-only registry

    def serve(self, key, tiles, who):
        self.tile_cache[key] = tiles
        self.client_log.append(who)
        return self.tile_cache[key]
'''

_SV_CLEAN = '''
import collections
from collections import OrderedDict

_STEP_CACHE = {}
_CAP = 8

def get_step(key, build):
    if key not in _STEP_CACHE:
        while len(_STEP_CACHE) >= _CAP:
            _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
        _STEP_CACHE[key] = build()
    return _STEP_CACHE[key]

class TileServer:
    def __init__(self, budget):
        self.tile_cache = OrderedDict()            # LRU: popitem below
        self.recent_clients = collections.deque(maxlen=16)  # bounded
        self._bytes, self.budget = 0, budget

    def serve(self, key, tiles, nbytes, who):
        self.tile_cache[key] = tiles
        self.recent_clients.append(who)
        self._bytes += nbytes
        while self._bytes > self.budget and len(self.tile_cache) > 1:
            _k, v = self.tile_cache.popitem(last=False)
            self._bytes -= v.nbytes
        return self.tile_cache[key]

def working_state(items):
    # locals are out of scope: they die with the call
    batch_cache = {}
    for k, v in items:
        batch_cache[k] = v
    return batch_cache
'''


def test_sv_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/serve/bad_caches.py": _SV_BAD},
        only=["servebounds"])
    assert rules_of(findings) == {"SV801", "SV802"}
    assert sum(f.rule == "SV801" for f in findings) == 2
    assert sum(f.rule == "SV802" for f in findings) == 1
    assert all(f.severity == "error" for f in findings)
    assert any("popitem" in f.message or "LRU" in f.message
               for f in findings)


def test_sv_bounded_idioms_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/query/good_caches.py": _SV_CLEAN},
        only=["servebounds"])
    assert findings == []


def test_sv_reassignment_reset_counts_as_bound():
    # draining by rebinding (self.pending = still_pending) is a bound
    findings = lint_sources({"hadoop_bam_tpu/serve/drained.py": '''
class Builder:
    def __init__(self):
        self.pending_tiles = []

    def add(self, t):
        self.pending_tiles.append(t)

    def reap(self):
        done = [t for t in self.pending_tiles if t.ready()]
        self.pending_tiles = [t for t in self.pending_tiles
                              if not t.ready()]
        return done
'''}, only=["servebounds"])
    assert findings == []


def test_sv_outside_query_and_serve_not_scoped():
    findings = lint_sources(
        {"hadoop_bam_tpu/formats/elsewhere.py": _SV_BAD,
         "hadoop_bam_tpu/parallel/elsewhere.py": _SV_BAD},
        only=["servebounds"])
    assert findings == []


def test_sv_non_cacheish_names_not_flagged():
    # plain working-state containers (no cache-ish name) stay out of
    # scope even when append-only — the rule targets lookup structures
    findings = lint_sources({"hadoop_bam_tpu/serve/state.py": '''
class Loop:
    def __init__(self):
        self.results = {}
        self.errors = []

    def run(self, k, v, e):
        self.results[k] = v
        self.errors.append(e)
'''}, only=["servebounds"])
    assert findings == []


# ---------------------------------------------------------------------------
# write-path discipline (WR10x)
# ---------------------------------------------------------------------------

_WR_BAD = '''
import os
from hadoop_bam_tpu.formats.bgzf import deflate_block

def publish(final_path, blocks):
    with open(final_path, "wb") as f:      # WR101: no temp, no replace
        for b in blocks:
            f.write(b)

def compress_all(payloads):
    out = []
    for p in payloads:
        out.append(deflate_block(p, 6))    # WR102: serial deflate loop
    return out
'''

_WR_CLEAN = '''
import os
from hadoop_bam_tpu.formats.bgzf import deflate_block

def publish(final_path, blocks):
    tmp_path = final_path + ".tmp"
    with open(tmp_path, "wb") as f:        # temp name + atomic replace
        for b in blocks:
            f.write(b)
    os.replace(tmp_path, final_path)

def _deflate_task(payload):
    return deflate_block(payload, 6)       # single block, pool-submitted

class Writer:
    def _commit_loop(self, q, sink):
        while True:
            fut = q.get()
            if fut is None:
                return
            sink.write(fut.result())
'''


def test_wr_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/write/bad_writer.py": _WR_BAD},
        only=["writepath"])
    assert rules_of(findings) == {"WR101", "WR102"}
    assert all(f.severity == "error" for f in findings)
    assert any("os.replace" in f.message for f in findings)
    assert any("ParallelBGZFWriter" in f.message for f in findings)


def test_wr_clean_idioms_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/write/good_writer.py": _WR_CLEAN},
        only=["writepath"])
    assert findings == []


def test_wr_replace_in_function_exempts_open():
    # a function that opens the final path but renames it into place is
    # the approved idiom even when the variable name is not tmp-ish
    findings = lint_sources({"hadoop_bam_tpu/write/renamer.py": '''
import os

def publish(final_path, data):
    staging = final_path + ".new"
    with open(staging, "wb") as f:
        f.write(data)
    os.replace(staging, final_path)
'''}, only=["writepath"])
    assert findings == []


def test_wr_outside_write_not_scoped():
    findings = lint_sources(
        {"hadoop_bam_tpu/utils/elsewhere.py": _WR_BAD,
         "hadoop_bam_tpu/formats/elsewhere.py": _WR_BAD},
        only=["writepath"])
    assert findings == []


def test_wr_read_mode_open_not_flagged():
    findings = lint_sources({"hadoop_bam_tpu/write/reader.py": '''
def load(final_path):
    with open(final_path, "rb") as f:
        return f.read()
'''}, only=["writepath"])
    assert findings == []


# ---------------------------------------------------------------------------
# crash-safe job discipline (JS1xx)
# ---------------------------------------------------------------------------

_JS_BAD = '''
import os
import tempfile                            # JS102: tempfile import

def publish_bucket(payload, final_path):
    staging = final_path + ".new"
    with open(staging, "wb") as f:
        f.write(payload)
    os.replace(staging, final_path)        # JS101: unjournaled rename

def spill_round(payload, out_dir):
    # JS102: pid-derived temp name — resume can never sweep/verify it
    path = os.path.join(out_dir, f"run-{os.getpid()}.tmp")
    with open(path, "wb") as f:
        f.write(payload)
    return path
'''

_JS_CLEAN = '''
import os

def _publish(tmp_path, path):
    os.replace(tmp_path, path)             # blessed publication helper

def open_shard(part, payload):
    tmp_part = part + ".tmp"               # deterministic job-scoped
    with open(tmp_part, "wb") as f:
        f.write(payload)
    os.replace(tmp_part, part)

def commit_round(journal, t, path, payload):
    with open(path + ".tmp", "wb") as f:
        f.write(payload)
    os.rename(path + ".tmp", path)         # journaled alongside:
    journal.unit_done("round", t, path=path)
'''


def test_js_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/write/bad_jobs.py": _JS_BAD},
        only=["jobsafety"])
    assert rules_of(findings) == {"JS101", "JS102"}
    assert all(f.severity == "error" for f in findings)
    assert sum(f.rule == "JS102" for f in findings) == 2  # import + pid
    assert any("journal" in f.message for f in findings)


def test_js_clean_idioms_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/write/good_jobs.py": _JS_CLEAN,
         "hadoop_bam_tpu/parallel/mesh_sort.py": _JS_CLEAN},
        only=["jobsafety"])
    assert findings == []


def test_js_scope_is_write_and_mesh_sort_only():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/mesh_sort.py": _JS_BAD,
         "hadoop_bam_tpu/parallel/pipeline.py": _JS_BAD,
         "hadoop_bam_tpu/utils/elsewhere.py": _JS_BAD,
         "hadoop_bam_tpu/query/engine.py": _JS_BAD},
        only=["jobsafety"])
    assert {f.path for f in findings} == \
        {"hadoop_bam_tpu/parallel/mesh_sort.py"}


def test_js_rename_args_checked_for_nondeterminism():
    findings = lint_sources({"hadoop_bam_tpu/write/renamer.py": '''
import os
import time

def open_shard(part, payload):
    tmp = part + "." + str(time.time_ns()) + ".tmp"   # JS102 even in a
    with open(tmp, "wb") as f:                        # blessed helper
        f.write(payload)
    os.replace(tmp, part)
'''}, only=["jobsafety"])
    assert rules_of(findings) == {"JS102"}


# ---------------------------------------------------------------------------
# baseline round-trip / suppression
# ---------------------------------------------------------------------------

_BAD_FOR_BASELINE = {"hadoop_bam_tpu/split/planners.py": '''
def f(n):
    raise ValueError("legacy")
'''}


def test_baseline_round_trip_suppresses(tmp_path):
    findings = lint_sources(_BAD_FOR_BASELINE)
    assert findings
    path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).save(path)
    loaded = Baseline.load(path)
    unsup, sup, stale = loaded.apply(findings)
    assert unsup == [] and len(sup) == len(findings) and stale == []
    # the stored entries keep human-readable context
    doc = json.loads(open(path).read())
    assert doc["findings"][0]["rule"] == "ET301"


def test_baseline_is_line_insensitive_but_not_content_insensitive():
    f1 = Finding("ET301", "error", "a/b.py", 10, "bare 'ValueError' ...")
    f2 = Finding("ET301", "error", "a/b.py", 99, "bare 'ValueError' ...")
    f3 = Finding("ET301", "error", "a/c.py", 10, "bare 'ValueError' ...")
    bl = Baseline.from_findings([f1])
    assert bl.suppresses(f2)          # same finding, shifted line
    assert not bl.suppresses(f3)      # moved to a new file: surfaces


def test_baseline_stale_entries_reported():
    findings = lint_sources(_BAD_FOR_BASELINE)
    bl = Baseline.from_findings(findings)
    unsup, sup, stale = bl.apply([])      # violation since fixed
    assert unsup == [] and sup == [] and len(stale) == len(findings)


def test_missing_baseline_file_is_empty(tmp_path):
    bl = Baseline.load(str(tmp_path / "nope.json"))
    assert len(bl) == 0


# ---------------------------------------------------------------------------
# device-plane sync discipline (DV9xx)
# ---------------------------------------------------------------------------

_DV_BAD = '''
import numpy as np
import jax

def _device_plane_drain(chunks, handles):
    out = []
    for h in handles:
        out.append(np.asarray(h))          # DV901: sync per iteration
    total = 0
    i = 0
    while i < len(handles):
        total += handles[i].item()         # DV901: sync per iteration
        i += 1
    for h in handles:
        vals = jax.device_get(h)           # DV901: sync per iteration
        total += int(vals[0])
    return out, total
'''

_DV_CLEAN = '''
import numpy as np
import jax

def _device_plane_drain(chunks, handles):
    # the approved idiom: ONE bulk fetch, loops over host data
    fetched = jax.device_get(handles)
    total = 0
    for vals in fetched:
        total += int(vals[0])
    return total

def inflate_span_device(raw, table, chunk=64):
    # host-boundary library function: its contract IS host bytes, the
    # chunk-granular sync is the API, exempt by name
    dst = []
    for lo in range(0, 8, chunk):
        dst.append(np.asarray(_resolve(raw, lo)))
    return dst

def _resolve(raw, lo):
    return raw

def _summary(handle):
    return np.asarray(handle)              # not in a loop: one sync
'''


def test_dv_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/ops/inflate_device.py": _DV_BAD},
        only=["devicesync"])
    assert rules_of(findings) == {"DV901"}
    assert len(findings) == 3
    assert all(f.severity == "error" for f in findings)
    assert all("_device_plane_drain" in f.message for f in findings)


def test_dv_clean_idioms_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/pipeline.py": _DV_CLEAN},
        only=["devicesync"])
    assert findings == []


def test_dv_for_iter_expression_is_once_not_per_iteration():
    # device_get in the for statement's ITERATOR evaluates once — the
    # exact bulk-drain idiom the rule's message recommends
    findings = lint_sources({"hadoop_bam_tpu/parallel/pipeline.py": '''
import jax

def _device_plane_totals(pairs):
    tf = 0
    for f, i in jax.device_get(pairs):
        tf += f + i
    return tf
'''}, only=["devicesync"])
    assert findings == []


def test_dv_outside_plane_not_scoped():
    # same bad source off the device decode plane: silent (serve/loop.py
    # stays unscoped — its record-filter loop reads per-chunk hit counts
    # by design; the PLANE files are tiles.py and the pipelines)
    findings = lint_sources(
        {"hadoop_bam_tpu/ops/inflate.py": _DV_BAD,
         "hadoop_bam_tpu/serve/loop.py": _DV_BAD},
        only=["devicesync"])
    assert findings == []


@pytest.mark.parametrize("path", [
    "hadoop_bam_tpu/parallel/variant_pipeline.py",
    "hadoop_bam_tpu/serve/tiles.py",
])
def test_dv_round21_families_are_scoped(path):
    # the variant and cold-serve-tile device drivers joined the plane in
    # round 21: the same seeded violations fire there...
    findings = lint_sources({path: _DV_BAD}, only=["devicesync"])
    assert rules_of(findings) == {"DV901"}
    assert len(findings) == 3
    # ...and the approved idioms stay silent
    assert lint_sources({path: _DV_CLEAN}, only=["devicesync"]) == []


def test_dv_live_plane_files_are_clean():
    # the REAL driver sources hold the discipline they are linted for —
    # baseline stays empty
    import pathlib
    root = pathlib.Path(__file__).resolve().parents[1]
    from hadoop_bam_tpu.analysis.devicesync import SCOPE
    srcs = {rel: (root / rel).read_text() for rel in SCOPE}
    assert lint_sources(srcs, only=["devicesync"]) == []


# ---------------------------------------------------------------------------
# plane-routing discipline (PL101)
# ---------------------------------------------------------------------------

_PL_BAD = '''
def gate(config, intervals):
    if config.use_fused_decode:                      # PL101: solo knob
        pass
    b = "x" if getattr(config, "inflate_backend", "auto") == "native" \
        else "y"                                     # PL101: getattr form
    return (not config.skip_bad_spans) and intervals is None \
        and config.use_fused_decode                  # PL101: combo gate
'''

_PL_GOOD = '''
from hadoop_bam_tpu.plan.executor import select_plane


def run(config, source, ops, intervals, quarantine):
    decision = select_plane(source, ops, config, intervals=intervals)
    if decision.stream_fused:          # consuming the decision: fine
        pass
    if config.skip_bad_spans:          # solo read: failure policy,
        return None                    # not plane routing
    backend = config.inflate_backend   # assignment, not a gate
    import dataclasses
    cfg = dataclasses.replace(config, use_fused_decode=False)  # kwarg
    return decision.plane, backend, cfg
'''


def test_pl_seeded_violations_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/bad.py": _PL_BAD}, only=["planroute"])
    assert rules_of(findings) == {"PL101"}
    assert all(f.severity == "error" for f in findings)
    knobs = {k for f in findings
             for k in ("use_fused_decode", "inflate_backend",
                       "skip_bad_spans") if f"'{k}'" in f.message}
    # the solo knobs fire, and skip_bad_spans fires in the combo gate
    assert knobs == {"use_fused_decode", "inflate_backend",
                     "skip_bad_spans"}


def test_pl_clean_twin_and_policy_reads_pass():
    findings = lint_sources(
        {"hadoop_bam_tpu/parallel/good.py": _PL_GOOD},
        only=["planroute"])
    assert findings == []


def test_pl_scope_excludes_plan_and_config():
    # the same gate inside plan/ (its one home) and config.py (knob
    # definitions + the auto resolver) is silent; in a driver package
    # it fires
    src = ("def f(c, intervals):\n"
           "    return c.use_fused_decode and intervals is None\n")
    assert lint_sources({"hadoop_bam_tpu/plan/executor.py": src},
                        only=["planroute"]) == []
    assert lint_sources({"hadoop_bam_tpu/config.py": src},
                        only=["planroute"]) == []
    assert rules_of(lint_sources({"hadoop_bam_tpu/query/gate.py": src},
                                 only=["planroute"])) == {"PL101"}


# ---------------------------------------------------------------------------
# thread-topology races & lock discipline (TH1xx/LK2xx)
# ---------------------------------------------------------------------------

_TH101_BAD = '''
import threading


class Fleet:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            self._count += 1           # TH101: heartbeat side, no lock

    def bump(self):
        self._count += 1               # TH101: client side, no lock
'''

_TH101_GOOD = '''
import threading


class Fleet:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            with self._lock:
                self._count += 1

    def bump(self):
        with self._lock:
            self._count += 1
'''


def test_th101_seeded_cross_thread_writes_fire():
    findings = lint_sources(
        {"hadoop_bam_tpu/serve/bad.py": _TH101_BAD},
        only=["threadsafety"])
    assert rules_of(findings) == {"TH101"}
    assert len(findings) == 2          # both unguarded write sites
    assert all("Fleet.self._count" in f.message for f in findings)
    assert all(f.severity == "error" for f in findings)


def test_th101_clean_twin_locked_writes_pass():
    assert lint_sources({"hadoop_bam_tpu/serve/good.py": _TH101_GOOD},
                        only=["threadsafety"]) == []


def test_th101_helper_called_only_under_lock_is_guarded():
    # the entry-guard fixpoint: every call site of _record holds the
    # lock, so its write is guarded even with no lexical `with` inside
    findings = lint_sources({"hadoop_bam_tpu/serve/entry.py": '''
import threading


class Meter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _record(self):
        self._n += 1

    def _loop(self):
        while True:
            with self._lock:
                self._record()

    def add(self):
        with self._lock:
            self._record()
'''}, only=["threadsafety"])
    assert findings == []


def test_th101_scope_excludes_formats():
    # the identical race outside serve/parallel/write/jobs/resilience/
    # utils/pools.py is not this analyzer's business
    assert lint_sources({"hadoop_bam_tpu/formats/bad.py": _TH101_BAD},
                        only=["threadsafety"]) == []


def test_th_no_thread_roots_means_no_findings():
    # single-threaded scope: nothing is cross-thread, whole analyzer
    # stands down (the 'client' root alone can never conflict)
    assert lint_sources({"hadoop_bam_tpu/serve/calm.py": '''
N = 0


def bump():
    global N
    N += 1


def reset():
    global N
    N = 0
'''}, only=["threadsafety"]) == []


_TH102_BAD = '''
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._seen = {}
        self._t = threading.Thread(target=self._sweep, daemon=True)

    def _sweep(self):
        with self._lock:
            self._seen.clear()

    def put(self, k, v):
        if k not in self._seen:        # TH102: the decision is unlocked
            with self._lock:
                self._seen[k] = v

    def drain(self):
        if not self._seen:             # TH102: emptiness probe, unlocked
            with self._lock:
                self._seen.update({})
'''

_TH102_GOOD = '''
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._seen = {}
        self._t = threading.Thread(target=self._sweep, daemon=True)

    def _sweep(self):
        with self._lock:
            self._seen.clear()

    def put(self, k, v):
        with self._lock:
            if k not in self._seen:
                self._seen[k] = v

    def drain(self):
        with self._lock:
            if not self._seen:
                self._seen.update({})
'''


def test_th102_check_then_act_fires():
    # note every WRITE here is lock-guarded — TH101 stays silent; the
    # defect is purely the unlocked decision (classic TOCTOU)
    findings = lint_sources({"hadoop_bam_tpu/serve/bad.py": _TH102_BAD},
                            only=["threadsafety"])
    assert rules_of(findings) == {"TH102"}
    assert len(findings) == 2
    assert all("Cache.self._seen" in f.message for f in findings)


def test_th102_clean_twin_atomic_check_passes():
    assert lint_sources({"hadoop_bam_tpu/serve/good.py": _TH102_GOOD},
                        only=["threadsafety"]) == []


_LK201_BAD = '''
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._a:
            with self._b:
                pass

    def poke(self):
        with self._b:
            with self._a:               # LK201: opposite nesting order
                pass
'''

_LK201_GOOD = '''
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._a:
            with self._b:
                pass

    def poke(self):
        with self._a:
            with self._b:               # same global order: fine
                pass
'''


def test_lk201_lock_order_cycle_fires():
    findings = lint_sources({"hadoop_bam_tpu/serve/bad.py": _LK201_BAD},
                            only=["threadsafety"])
    assert rules_of(findings) == {"LK201"}
    [f] = findings
    assert "Pair.self._a -> Pair.self._b -> Pair.self._a" in f.message


def test_lk201_clean_twin_single_order_passes():
    assert lint_sources({"hadoop_bam_tpu/serve/good.py": _LK201_GOOD},
                        only=["threadsafety"]) == []


def test_th101_parallel_bgzf_prefix_pattern_regression():
    """Both directions of the in-PR fix: the PRE-fix shape of
    write/parallel_bgzf.py (committer thread and close() racing on
    _err with no lock) must keep firing, and the shipped module (now
    serialized through _mu) must stay clean."""
    findings = lint_sources({"hadoop_bam_tpu/write/bad.py": '''
import threading


class Writer:
    def __init__(self):
        self._err = None
        self._t = threading.Thread(target=self._commit_loop, daemon=True)

    def _commit_loop(self):
        try:
            self._commit()
        except Exception as e:
            if self._err is None:
                self._err = e

    def _commit(self):
        pass

    def close(self):
        err, self._err = self._err, None
        if err is not None:
            raise err
'''}, only=["threadsafety"])
    assert rules_of(findings) == {"TH101"}
    assert len(findings) == 2
    assert all("Writer.self._err" in f.message for f in findings)

    repo = run_analyzers(Project.load(), only=["threadsafety"])
    assert repo == []


# ---------------------------------------------------------------------------
# the CI gate: the repo itself lints clean
# ---------------------------------------------------------------------------

def test_repo_lints_clean():
    """``python -m hadoop_bam_tpu lint`` exits 0: zero unsuppressed
    findings against the checked-in baseline.  New violations anywhere in
    the package fail HERE — this test is the tier-1 lint gate."""
    from hadoop_bam_tpu.analysis.core import lint_main
    assert lint_main([]) == 0


def test_lint_cli_exit_codes(tmp_path, capsys):
    """The lint frontend exits 1 on unsuppressed findings and 0 once they
    are baselined (exercises --root / --baseline / --update-baseline)."""
    from hadoop_bam_tpu.analysis.core import lint_main

    pkg = tmp_path / "hadoop_bam_tpu" / "split"
    pkg.mkdir(parents=True)
    (pkg / "planners.py").write_text(
        "def f(n):\n    raise ValueError('x')\n")
    root = str(tmp_path / "hadoop_bam_tpu")
    bl = str(tmp_path / "bl.json")
    assert lint_main(["--root", root, "--baseline", bl]) == 1
    assert lint_main(["--root", root, "--baseline", bl,
                      "--update-baseline"]) == 0
    assert lint_main(["--root", root, "--baseline", bl]) == 0
    out = capsys.readouterr().out
    assert "ET301" in out and "1 suppressed" in out


# ---------------------------------------------------------------------------
# output formats & the findings cache
# ---------------------------------------------------------------------------

def _seed_bad_tree(tmp_path):
    """One-module tree with a single ET301 finding at line 2."""
    pkg = tmp_path / "hadoop_bam_tpu" / "split"
    pkg.mkdir(parents=True)
    (pkg / "planners.py").write_text(
        "def f(n):\n    raise ValueError('x')\n")
    return str(tmp_path / "hadoop_bam_tpu"), str(tmp_path / "bl.json")


def test_lint_format_json(tmp_path, capsys):
    from hadoop_bam_tpu.analysis.core import lint_main
    root, bl = _seed_bad_tree(tmp_path)
    rc = lint_main(["--root", root, "--baseline", bl,
                    "--format", "json", "--no-cache"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["tool"] == "hbam-lint"
    [f] = doc["findings"]
    assert f["rule"] == "ET301"
    assert f["path"].endswith("planners.py")
    assert f["line"] == 2
    assert f["severity"] == "error"
    assert len(f["fingerprint"]) == 16
    assert doc["summary"]["unsuppressed"] == 1


def test_lint_format_sarif(tmp_path, capsys):
    from hadoop_bam_tpu.analysis.core import lint_main
    root, bl = _seed_bad_tree(tmp_path)
    rc = lint_main(["--root", root, "--baseline", bl,
                    "--format", "sarif", "--no-cache"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "hbam-lint"
    assert run["tool"]["driver"]["rules"] == [{"id": "ET301"}]
    [res] = run["results"]
    assert res["ruleId"] == "ET301"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("planners.py")
    assert loc["region"]["startLine"] == 2
    assert "hbamLint/v1" in res["partialFingerprints"]


def test_lint_format_json_suppressed_exit_zero(tmp_path, capsys):
    from hadoop_bam_tpu.analysis.core import lint_main
    root, bl = _seed_bad_tree(tmp_path)
    assert lint_main(["--root", root, "--baseline", bl,
                      "--update-baseline", "--no-cache"]) == 0
    capsys.readouterr()
    assert lint_main(["--root", root, "--baseline", bl,
                      "--format", "json", "--no-cache"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == []
    assert doc["summary"]["suppressed"] == 1


def test_lint_cache_replay_and_invalidation(tmp_path, capsys,
                                            monkeypatch):
    from hadoop_bam_tpu.analysis.core import lint_main
    cache = tmp_path / "cache.json"
    monkeypatch.setenv("HBAM_LINT_CACHE", str(cache))
    root, bl = _seed_bad_tree(tmp_path)

    assert lint_main(["--root", root, "--baseline", bl]) == 1
    out_cold = capsys.readouterr().out
    assert cache.exists()

    # warm replay: byte-identical report and exit code off the digest
    assert lint_main(["--root", root, "--baseline", bl]) == 1
    assert capsys.readouterr().out == out_cold

    # any tree drift invalidates: fixing the file flips the exit code
    fixed = tmp_path / "hadoop_bam_tpu" / "split" / "planners.py"
    fixed.write_text("def f(n):\n    return n\n")
    assert lint_main(["--root", root, "--baseline", bl]) == 0
    capsys.readouterr()

    # --no-cache neither reads nor writes the cache file
    stamp = cache.stat().st_mtime_ns
    assert lint_main(["--root", root, "--baseline", bl,
                      "--no-cache"]) == 0
    assert cache.stat().st_mtime_ns == stamp


# ---------------------------------------------------------------------------
# ISSUE 20: prep/ joins the ET3xx / JS1xx / TH1xx scopes
# ---------------------------------------------------------------------------

_PREP_ET_BAD = '''
def signature(rec):
    if len(rec) < 36:
        raise ValueError("record shorter than fixed header")  # ET301
'''

_PREP_ET_GOOD = '''
from hadoop_bam_tpu.utils.errors import CorruptDataError


def signature(rec):
    if len(rec) < 36:
        raise CorruptDataError("record shorter than fixed header")
'''


def test_et_scope_covers_prep_boundaries():
    """ISSUE 20 scope extension: the fused preprocessing plane's
    modules classify faults for retry/quarantine policy — a bare
    ValueError from the signature walk would retry corrupt bytes."""
    for mod in ("hadoop_bam_tpu/prep/oracle.py",
                "hadoop_bam_tpu/prep/markdup.py",
                "hadoop_bam_tpu/prep/pipeline.py"):
        findings = lint_sources({mod: _PREP_ET_BAD}, only=["taxonomy"])
        assert rules_of(findings) == {"ET301"}, mod
        assert lint_sources({mod: _PREP_ET_GOOD},
                            only=["taxonomy"]) == [], mod
    # prep's package __init__ is not a policy boundary
    assert lint_sources({"hadoop_bam_tpu/prep/__init__.py":
                         _PREP_ET_BAD}, only=["taxonomy"]) == []


_PREP_JS_BAD = '''
import os


def publish_bitmap(spill_dir, bits):
    tmp = os.path.join(spill_dir, "dupbits." + str(os.getpid()))
    with open(tmp, "wb") as f:                # JS102: pid-derived name
        f.write(bits)
    os.replace(tmp, os.path.join(spill_dir, "dupbits.u8"))  # JS101
'''

_PREP_JS_GOOD = '''
import os


def publish_bitmap(jr, spill_dir, bits, size, crc):
    tmp = os.path.join(spill_dir, "dupbits.u8.tmp")
    with open(tmp, "wb") as f:
        f.write(bits)
    final = os.path.join(spill_dir, "dupbits.u8")
    os.replace(tmp, final)
    jr.unit_done("markdup", 0, path=final, size=size, crc=crc)
'''


def test_js_scope_covers_prep_pipeline():
    """ISSUE 20: the fused pipeline publishes spill runs, column
    sidecars and the duplicate bitmap — JS1xx polices it like the
    write path (deterministic temp names, journaled publication)."""
    findings = lint_sources(
        {"hadoop_bam_tpu/prep/pipeline.py": _PREP_JS_BAD},
        only=["jobsafety"])
    assert rules_of(findings) == {"JS101", "JS102"}
    # the journaled-commit twin is the blessed shape
    assert lint_sources({"hadoop_bam_tpu/prep/pipeline.py":
                         _PREP_JS_GOOD}, only=["jobsafety"]) == []
    # the same bad code outside the crash-safe scope is not JS-scoped
    assert lint_sources({"hadoop_bam_tpu/tools/other.py": _PREP_JS_BAD},
                        only=["jobsafety"]) == []


_PREP_TH_BAD = '''
import threading


class StepCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._steps = {}
        self._t = threading.Thread(target=self._warm, daemon=True)
        self._t.start()

    def _warm(self):
        self._steps["warm"] = 1        # TH101: warmer side, no lock

    def get(self, key):
        self._steps[key] = object()    # TH101: caller side, no lock
'''

_PREP_TH_GOOD = '''
import threading


class StepCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._steps = {}
        self._t = threading.Thread(target=self._warm, daemon=True)
        self._t.start()

    def _warm(self):
        with self._lock:
            self._steps["warm"] = 1

    def get(self, key):
        with self._lock:
            self._steps[key] = object()
'''


def test_th_scope_covers_prep():
    """ISSUE 20: a warmed compile-step cache in prep/ shared with a
    background thread gets the same TH1xx policing as serve/."""
    findings = lint_sources(
        {"hadoop_bam_tpu/prep/steps.py": _PREP_TH_BAD},
        only=["threadsafety"])
    assert rules_of(findings) == {"TH101"}
    assert lint_sources({"hadoop_bam_tpu/prep/steps.py":
                         _PREP_TH_GOOD}, only=["threadsafety"]) == []


def test_prep_repo_modules_lint_clean():
    """The shipped prep/ modules themselves pass their new scopes —
    and the committed baseline stays EMPTY (no grandfathered debt)."""
    import json as _json
    import os as _os

    root = _os.path.join(_os.path.dirname(__file__), _os.pardir)
    sources = {}
    for name in ("oracle.py", "markdup.py", "pipeline.py",
                 "__init__.py"):
        rel = f"hadoop_bam_tpu/prep/{name}"
        with open(_os.path.join(root, rel)) as f:
            sources[rel] = f.read()
    findings = run_analyzers(
        Project.from_sources(sources),
        only=["taxonomy", "jobsafety", "threadsafety"])
    assert findings == []
    with open(_os.path.join(root, "hadoop_bam_tpu", "analysis",
                            "baseline.json")) as f:
        assert _json.load(f)["findings"] == []
