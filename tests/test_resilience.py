"""Degrade-and-heal resilience tests (``pytest -m resilience``):

- the closed/open/half-open ``CircuitBreaker`` state machine on an
  injected clock (decayed windows, probe budgets, retry-after hints);
- the decode-plane demotion ladder: flagstat under injected device /
  native plane faults completes byte-identical to the zlib oracle,
  demotes mid-run, and heals back through a half-open probe;
- the upgraded quarantine circuit (fast-fail gate + heal on a clean
  probe run);
- serve-tier degradation: per-tenant breakers, shed taxonomy with
  ``retry_after_s`` on the wire, transport disconnect chaos that ends
  one stream without hanging the dispatcher, the health op, and
  prefetch auto-pause under fault pressure;
- chaos fault points past byte sources (pool submission, writer deflate
  workers) and the seed-derived deterministic schedules that make chaos
  runs reproducible from one ``chaos_seed``.
"""
import dataclasses
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from hadoop_bam_tpu import resilience
from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.resilience import (
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker, DecayingWindow, chaos,
)
from hadoop_bam_tpu.resilience.chaos import PointFault, fault_points_on
from hadoop_bam_tpu.utils.errors import (
    CircuitBreakerError, CorruptDataError, PlanError, TransientIOError,
)
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.resilient import (
    FaultInjectingByteSource, FaultSpec, SeededFaultSchedule, chaos_on,
    install_chaos_seeded, clear_chaos,
)

from fixtures import make_header, make_records

pytestmark = pytest.mark.resilience


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, d):
        self.t += d


# fast-backoff config shared by the driver-level tests
def _cfg(**kw):
    base = dict(retry_backoff_base_s=0.001, retry_backoff_max_s=0.002)
    base.update(kw)
    return dataclasses.replace(DEFAULT_CONFIG, **base)


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    """Coordinate-sorted + indexed, so both the scan drivers AND the
    serve tier (region resolution needs the .bai) run against it."""
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    path = str(tmp_path_factory.mktemp("resil") / "r.bam")
    header = make_header(2)

    def key(r):
        return (header.ref_names.index(r.rname) if r.rname != "*"
                else 1 << 30, r.pos)

    records = sorted(make_records(header, 3000, seed=11), key=key)
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    write_bai(path)
    return path, header, records


def _spans(path, header, n=4):
    from hadoop_bam_tpu.split.planners import plan_bam_spans
    return plan_bam_spans(path, num_spans=n, header=header)


def _flagstat(path, header, spans, config):
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    return flagstat_file(path, header=header, spans=spans, config=config)


# ---------------------------------------------------------------------------
# breaker state machine (injected clock, no real time)
# ---------------------------------------------------------------------------

def test_breaker_full_lifecycle():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=3, window_s=10, cooldown_s=5,
                       half_open_probes=1, clock=clk, name="t")
    assert b.state == CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED        # under threshold
    b.record_failure()
    assert b.state == OPEN and not b.allow()
    assert 0 < b.retry_after_s() <= 5.0
    clk.advance(4.99)
    assert not b.allow()            # still cooling down
    clk.advance(0.02)
    assert b.state == HALF_OPEN
    assert b.allow()                # the one probe slot
    assert not b.allow()            # budget spent
    b.record_success()
    assert b.state == CLOSED and b.allow()
    assert b.opened_total == 1 and b.healed_total == 1


def test_breaker_half_open_failure_reopens():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=1, window_s=10, cooldown_s=2,
                       clock=clk)
    b.record_failure()
    assert b.state == OPEN
    clk.advance(2.1)
    assert b.allow()                # half-open probe
    b.record_failure()              # probe failed
    assert b.state == OPEN          # re-armed
    assert not b.allow()
    clk.advance(2.1)
    assert b.allow()
    b.record_success()
    assert b.state == CLOSED


def test_breaker_decayed_window_forgets_old_failures():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=3, window_s=5, cooldown_s=1,
                       clock=clk)
    b.record_failure()
    b.record_failure()
    clk.advance(60)                 # 12 windows: ~e^-12 left
    assert b.failure_rate() < 0.01
    b.record_failure()              # old burst must NOT push this over
    assert b.state == CLOSED

    w = DecayingWindow(window_s=2.0, clock=clk)
    w.add(4.0)
    clk.advance(2.0)
    assert w.value() == pytest.approx(4.0 * np.exp(-1.0), rel=1e-6)


def test_breaker_probe_budget_multiple():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=1, cooldown_s=1,
                       half_open_probes=2, clock=clk)
    b.record_failure()
    clk.advance(1.5)
    assert b.allow() and b.allow() and not b.allow()


def test_breaker_trip_writes_flight_dump(tmp_path):
    """THE flight-recorder acceptance pin: a breaker trip auto-dumps a
    redacted snapshot containing the trip transition, the triggering
    request's trace_id, and the prior span completions — and the dump
    directory honors the rotation cap."""
    from hadoop_bam_tpu.obs import flight
    from hadoop_bam_tpu.obs.context import trace_context

    fr = flight.reset()
    fr.configure(dump_dir=str(tmp_path), dump_cap=2)
    try:
        clk = FakeClock()
        br = CircuitBreaker(failure_threshold=3.0, window_s=30.0,
                            cooldown_s=5.0, clock=clk,
                            name="tenant/web")
        with trace_context(op="serve.request", tenant="web") as ctx:
            # the request does some work (span completions land in the
            # always-on ring), then its failures trip the breaker
            for i in range(4):
                with METRICS.span("bam.fetch_wall", chunk=i):
                    pass
                br.record_failure()
        files = sorted(f for f in os.listdir(tmp_path)
                       if f.endswith(".json"))
        assert len(files) == 1          # exactly one trip, one dump
        doc = json.load(open(os.path.join(str(tmp_path), files[0])))
        assert doc["reason"] == "breaker_open:tenant/web"
        # the triggering request's trace id, at dump time and on the
        # recorded transition
        assert doc["trace"] == ctx.trace_id
        trips = [t for t in doc["transitions"]
                 if t["kind"] == "breaker" and t["state"] == "open"]
        assert trips and trips[-1]["name"] == "tenant/web"
        assert trips[-1]["trace"] == ctx.trace_id
        # the prior N span completions, attributed to the same trace
        prior = [s for s in doc["spans"] if s["name"] == "bam.fetch_wall"]
        assert len(prior) >= 3
        assert all(s["trace"] == ctx.trace_id for s in prior)
        # rotation cap: five more incidents leave at most cap files
        for k in range(5):
            CircuitBreaker(failure_threshold=1.0, clock=clk,
                           name=f"tenant/t{k}").record_failure()
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == 2
        assert fr.dumps_written == 6
    finally:
        flight.reset()


# ---------------------------------------------------------------------------
# demotion ladder: flagstat demotes then heals, byte-identical throughout
# ---------------------------------------------------------------------------

def test_native_faults_demote_to_zlib_then_heal(bam):
    """THE acceptance pin: injected native-plane faults -> flagstat
    completes byte-identical to the zlib oracle, the native domain's
    breaker opens (demotion), and after the cooldown a half-open probe
    heals it — all mid-run, no failed driver calls anywhere."""
    path, header, records = bam
    spans = _spans(path, header, n=5)
    clk = FakeClock()
    resilience.reset(clock=clk)

    oracle = _flagstat(path, header, spans, _cfg(
        inflate_backend="zlib", adaptive_planes=False))
    assert oracle["total"] == len(records)

    cfg = _cfg(inflate_backend="native")
    with fault_points_on("decode.native",
                         [PointFault("corrupt", count=1000)]):
        faulted = _flagstat(path, header, spans, cfg)
    assert faulted == oracle        # byte-identical through the demotion
    key = f"decode/native/{os.path.abspath(path)}"
    states = resilience.registry().states()
    assert states[key]["state"] == OPEN          # demoted: breaker open
    assert states[key]["failures_total"] >= 3

    # while OPEN (chaos cleared, cooldown NOT elapsed): runs stay on
    # zlib — and still match
    demoted = _flagstat(path, header, spans, cfg)
    assert demoted == oracle
    assert resilience.registry().states()[key]["state"] == OPEN

    # cooldown elapses -> half-open probe on native succeeds -> healed
    clk.advance(float(cfg.breaker_cooldown_s) + 0.1)
    healed = _flagstat(path, header, spans, cfg)
    assert healed == oracle
    states = resilience.registry().states()
    assert states[key]["state"] == CLOSED
    assert states[key]["healed_total"] == 1
    assert METRICS.get("resilience.heals") >= 1


def test_pure_data_corruption_charges_no_plane(bam, tmp_path):
    """Both planes fail on genuinely corrupt bytes: the ladder must NOT
    blame the native plane (oracle confirmation) — and the error class
    is CORRUPT either way."""
    from hadoop_bam_tpu.formats import bgzf

    path, header, _ = bam
    raw = open(path, "rb").read()
    data = bytearray(raw)
    spans = _spans(path, header, n=3)
    mid = (spans[1].start[0] + spans[1].end[0]) // 2
    victim = min((b for b in bgzf.scan_blocks(raw) if b.isize),
                 key=lambda b: abs(b.coffset - mid))
    for i in range(victim.cdata_offset + 10, victim.cdata_offset + 40):
        data[i] ^= 0xFF
    bad = str(tmp_path / "bad.bam")
    open(bad, "wb").write(bytes(data))
    with pytest.raises(CorruptDataError):
        _flagstat(bad, header, _spans(bad, header, n=3),
                  _cfg(inflate_backend="native"))
    assert resilience.registry().states() == {}     # nobody charged


def test_adaptive_planes_off_keeps_static_selection(bam):
    """The kill switch: with adaptive_planes=False an injected native
    fault raises instead of demoting (the pre-ISSUE-11 behavior)."""
    path, header, _ = bam
    spans = _spans(path, header, n=2)
    cfg = _cfg(inflate_backend="native", adaptive_planes=False)
    with fault_points_on("decode.native",
                         [PointFault("corrupt", count=1000)]):
        with pytest.raises(CorruptDataError):
            _flagstat(path, header, spans, cfg)
    assert resilience.registry().states() == {}


@pytest.mark.skipif(
    not __import__("hadoop_bam_tpu.utils.native",
                   fromlist=["available"]).available(),
    reason="device plane needs the native tokenizer")
def test_device_step_faults_demote_to_host_then_heal(bam):
    """Device rung of the ladder: an injected shard_map-step fault
    unwinds the device-plane run; flagstat demotes to the host planes
    mid-call (identical result), charges the device domain only after
    the host run completes, and a half-open probe heals it."""
    path, header, records = bam
    spans = _spans(path, header, n=3)
    clk = FakeClock()
    resilience.reset(clock=clk)
    oracle = _flagstat(path, header, spans, _cfg(
        inflate_backend="zlib", adaptive_planes=False))

    cfg = _cfg(inflate_backend="device", breaker_failure_threshold=1.0)
    with fault_points_on("device.step",
                         [PointFault("transient", count=1)]):
        faulted = _flagstat(path, header, spans, cfg)
    assert faulted == oracle
    key = f"decode/device/{os.path.abspath(path)}"
    states = resilience.registry().states()
    assert states[key]["state"] == OPEN          # threshold 1: open now

    # OPEN device circuit: the run starts straight on the host planes
    demoted = _flagstat(path, header, spans, cfg)
    assert demoted == oracle
    # cooled down: half-open probe goes back through the device plane
    clk.advance(float(cfg.breaker_cooldown_s) + 0.1)
    healed = _flagstat(path, header, spans, cfg)
    assert healed == oracle
    states = resilience.registry().states()
    assert states[key]["state"] == CLOSED
    assert states[key]["healed_total"] == 1


def test_device_plan_error_never_demotes(bam, monkeypatch):
    """PLAN-class failures (native library missing under a forced
    device backend) raise through the ladder untouched — a
    misconfigured run must not silently degrade (pinned since PR 9)."""
    from hadoop_bam_tpu.utils import native as native_mod

    path, header, _ = bam
    monkeypatch.setattr(native_mod, "available", lambda: False)
    with pytest.raises(PlanError):
        _flagstat(path, header, _spans(path, header, n=2),
                  _cfg(inflate_backend="device"))
    assert resilience.registry().states() == {}


# ---------------------------------------------------------------------------
# quarantine circuit: no longer one-way
# ---------------------------------------------------------------------------

def test_quarantine_circuit_gates_then_heals(bam, tmp_path):
    path, header, _ = bam
    data = bytearray(open(path, "rb").read())
    clean_bytes = bytes(data)
    spans = _spans(path, header, n=4)
    mid = (spans[1].start[0] + spans[1].end[0]) // 2
    for i in range(mid + 12, mid + 40):
        data[i] ^= 0xFF
    bad = str(tmp_path / "q.bam")
    open(bad, "wb").write(bytes(data))
    bad_spans = _spans(bad, header, n=4)
    clk = FakeClock()
    resilience.reset(clock=clk)

    cfg = _cfg(skip_bad_spans=True, span_retries=0,
               max_bad_span_fraction=0.1)
    # run 1: trips the fraction breaker — which now also OPENS the
    # per-file quarantine circuit (retry-after hint attached)
    with pytest.raises(CircuitBreakerError,
                       match="max_bad_span_fraction") as ei:
        _flagstat(bad, header, bad_spans, cfg)
    assert ei.value.retry_after_s is not None

    # run 2: fast-fails AT THE GATE (no planning, no decode) while OPEN
    t0 = METRICS.get("pipeline.spans")
    with pytest.raises(CircuitBreakerError, match="quarantine circuit"):
        _flagstat(bad, header, bad_spans, cfg)
    assert METRICS.get("pipeline.spans") == t0    # nothing was decoded
    assert METRICS.get("resilience.quarantine_gate_shed") >= 1

    # cooldown -> half-open: the probe run is admitted; still corrupt,
    # so it trips and re-opens
    clk.advance(float(cfg.breaker_cooldown_s) + 0.1)
    with pytest.raises(CircuitBreakerError, match="max_bad_span_fraction"):
        _flagstat(bad, header, bad_spans, cfg)
    br = resilience.quarantine_breaker(bad, config=cfg)
    assert br.state == OPEN and br.opened_total == 2

    # the file is repaired in place; the next cooled-down probe run
    # finishes clean and HEALS the circuit
    open(bad, "wb").write(clean_bytes)
    clk.advance(float(cfg.breaker_cooldown_s) + 0.1)
    out = _flagstat(bad, header, bad_spans, cfg)
    assert "quarantine" not in out
    assert br.state == CLOSED and br.healed_total == 1


# ---------------------------------------------------------------------------
# serve tier: tenant breakers, shed taxonomy, retry-after, health
# ---------------------------------------------------------------------------

def test_tenant_breaker_unit_shed_and_heal():
    from hadoop_bam_tpu.serve.tenancy import TenantQuotas

    clk = FakeClock()
    q = TenantQuotas(DEFAULT_CONFIG, clock=clk)
    for _ in range(3):
        q.record_outcome("noisy", CorruptDataError("bad tile"))
    # PLAN failures never count (the client's own malformed request)
    q.record_outcome("polite", PlanError("bad region"))

    with pytest.raises(TransientIOError) as ei:
        with q.admit("noisy"):
            pass
    assert ei.value.retry_after_s is not None and ei.value.retry_after_s > 0
    assert METRICS.get("resilience.tenant_shed") >= 1
    with q.admit("polite"):          # isolation: other tenants admit
        pass

    clk.advance(float(DEFAULT_CONFIG.breaker_cooldown_s) + 0.1)
    with q.admit("noisy"):           # half-open probe admits
        pass
    q.record_outcome("noisy", None)  # probe succeeded
    assert q.breaker("noisy").state == CLOSED
    assert q.breaker_states()["noisy"]["healed_total"] == 1


def test_serve_loop_tenant_breaker_sheds_with_taxonomy(bam):
    """Repeated corrupt-serving failures for one tenant open its
    breaker; the next request sheds TRANSIENT (with retry_after) while
    another tenant keeps serving — degradation, not an outage."""
    from hadoop_bam_tpu.serve import ServeLoop

    path, header, _ = bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False)
    with ServeLoop(config=cfg) as loop:
        loop.query(path, ["chr1:1-100000"], tenant="good")  # warm meta
        real_chunk = loop.engine._chunk

        def corrupt_chunk(meta, s, e):
            raise CorruptDataError("injected corrupt tile")

        loop.engine._chunk = corrupt_chunk
        try:
            # distinct uncached windows: a warm tile hit would bypass
            # the chunk tier entirely and never see the fault
            for i in range(3):
                with pytest.raises(CorruptDataError):
                    loop.query(
                        path, [f"chr2:{1 + i * 5000}-{4000 + i * 5000}"],
                        tenant="noisy")
            # breaker open: sheds at admission, TRANSIENT taxonomy
            with pytest.raises(TransientIOError) as ei:
                loop.query(path, ["chr2:90000-95000"], tenant="noisy")
            assert ei.value.retry_after_s is not None
        finally:
            loop.engine._chunk = real_chunk
        # isolation + liveness: the other tenant still gets answers
        res = loop.query(path, ["chr1:1-100000"], tenant="good")
        assert res[0].count >= 0
        h = loop.health()
        assert h["status"] == "serving"
        assert h["tenant_breakers"]["noisy"]["state"] == OPEN


class _StubLoop:
    """Minimal ServeLoop stand-in for transport-only tests."""

    def __init__(self, exc=None):
        self.exc = exc

    def submit(self, path, regions, **kw):
        import concurrent.futures as cf
        if self.exc is not None:
            raise self.exc
        fut = cf.Future()
        fut.set_result([])
        return fut

    def health(self):
        return {"status": "serving", "domains": {}, "tenant_breakers": {}}


def test_transport_error_lines_carry_retry_after():
    from hadoop_bam_tpu.serve.transport import handle_stream

    loop = _StubLoop(exc=TransientIOError("shed", retry_after_s=0.25))
    out = io.StringIO()
    handle_stream(loop, io.StringIO(
        '{"id": 7, "path": "x.bam", "region": "chr1:1-10"}\n'), out)
    doc = json.loads(out.getvalue().strip())
    # the PR-14 request-id contract: every response line echoes the
    # request's trace id (16 hex chars)
    trace = doc.pop("trace")
    assert isinstance(trace, str) and len(trace) == 16
    assert doc == {"id": 7, "error": "shed", "kind": "transient",
                   "retry_after_s": 0.25}


def test_transport_health_op_reports_state():
    from hadoop_bam_tpu.serve.transport import handle_stream

    out = io.StringIO()
    handle_stream(_StubLoop(), io.StringIO('{"id": 1, "op": "health"}\n'),
                  out)
    doc = json.loads(out.getvalue().strip())
    assert doc["id"] == 1 and doc["health"]["status"] == "serving"


def test_transport_disconnect_chaos_no_hang_no_crash(bam):
    """An injected mid-stream disconnect ends THAT stream cleanly
    (bounded time, no exception) and the dispatcher keeps serving."""
    from hadoop_bam_tpu.serve import ServeLoop, handle_stream

    path, header, _ = bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, serve_prefetch=False)
    lines = "".join(
        json.dumps({"id": i, "path": path, "region": "chr1:1-100000"})
        + "\n" for i in range(3))
    with ServeLoop(config=cfg) as loop:
        out = io.StringIO()
        t0 = time.monotonic()
        with fault_points_on("serve.transport",
                             [PointFault("disconnect", at_call=1)]):
            n = handle_stream(loop, io.StringIO(lines), out)
        assert time.monotonic() - t0 < 30.0       # never a hang
        assert n == 1                              # stream ended at line 2
        assert METRICS.get("serve.transport_disconnects") >= 1
        # the response that made it out is a real answer
        docs = [json.loads(x) for x in out.getvalue().splitlines()]
        assert docs and "results" in docs[0]
        # dispatcher alive: a fresh stream serves normally
        out2 = io.StringIO()
        assert handle_stream(loop, io.StringIO(lines), out2) == 3
        assert all("results" in json.loads(x)
                   for x in out2.getvalue().splitlines())


def test_health_after_decode_chaos_reports_domains(bam):
    """Under decode chaos the serve path sheds/fails classified, and
    the health surface names the charged fault domains."""
    from hadoop_bam_tpu.serve import ServeLoop

    path, header, _ = bam
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, serve_prefetch=False,
        retry_backoff_base_s=0.001, retry_backoff_max_s=0.002)
    with ServeLoop(config=cfg) as loop:
        # seed a fault domain the way a degraded decode would
        resilience.registry().domain(
            "decode", "native", "somefile").record_failure()
        h = loop.health()
        assert h["fault_pressure"] > 0
        assert "decode/native/somefile" in h["domains"]


def test_prefetch_auto_pauses_under_fault_pressure(bam):
    from hadoop_bam_tpu.serve import ServeLoop

    path, header, _ = bam
    with ServeLoop() as loop:
        d = resilience.registry().domain("decode", "native", "pressure")
        for _ in range(5):
            d.record_failure()
        assert resilience.registry().fault_pressure() >= \
            DEFAULT_CONFIG.serve_prefetch_pause_pressure
        loop.query(path, ["chr1:1-50000"])
        loop.prefetcher.drain()
        st = loop.prefetcher.stats()
        assert st["issued"] == 0 and st["paused_total"] >= 1
        assert METRICS.get("serve.prefetch_paused") >= 1

        resilience.reset()           # pressure decays away -> resumes
        loop.query(path, ["chr1:50001-100000"])
        loop.prefetcher.drain()
        assert loop.prefetcher.stats()["issued"] > 0


# ---------------------------------------------------------------------------
# chaos fault points: pool submission + writer deflate workers
# ---------------------------------------------------------------------------

def test_pool_submit_chaos_observed_and_healed(bam):
    path, header, records = bam
    spans = _spans(path, header, n=4)
    clean = _flagstat(path, header, spans, _cfg())
    with fault_points_on("pool.submit",
                         [PointFault("transient", count=2)]):
        out = _flagstat(path, header, spans, _cfg())
        assert chaos.injected_counts("pool.submit") == {"transient": 2}
    assert out == clean
    assert METRICS.get("pool.submit_retries") >= 2


def test_writer_deflate_transient_faults_recover_byte_identical():
    cfg = _cfg()
    payload = np.random.default_rng(3).integers(
        0, 255, size=200_000, dtype=np.uint8).tobytes()
    from hadoop_bam_tpu.write.parallel_bgzf import ParallelBGZFWriter

    def run(faults):
        sink = io.BytesIO()
        with fault_points_on("write.deflate", list(faults)):
            with ParallelBGZFWriter(sink, level=6, max_inflight=4,
                                    config=cfg) as w:
                for lo in range(0, len(payload), 37_000):
                    w.write(payload[lo:lo + 37_000])
        return sink.getvalue()

    clean = run([])
    faulted = run([PointFault("transient", count=3)])
    assert faulted == clean          # worker faults healed in place
    assert chaos.injected_counts("write.deflate") == {}  # cleared
    assert METRICS.get("write.deflate_retries") >= 3


def test_writer_deflate_corrupt_fault_fails_fast():
    from hadoop_bam_tpu.write.parallel_bgzf import ParallelBGZFWriter

    payload = b"x" * 200_000
    sink = io.BytesIO()
    with fault_points_on("write.deflate", [PointFault("corrupt",
                                                      count=1000)]):
        with pytest.raises(CorruptDataError):
            with ParallelBGZFWriter(sink, level=6, max_inflight=2,
                                    config=_cfg()) as w:
                w.write(payload)


# ---------------------------------------------------------------------------
# chaos-registry audit: every byte path observes installed faults
# ---------------------------------------------------------------------------

def test_shard_concat_reads_observe_chaos(bam, tmp_path):
    """The write-path shard concat reads parts through the registry:
    installed transient faults are observed AND healed by its retry."""
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.write.api import write_bam_shards_concat

    path, header, records = bam
    part = str(tmp_path / "part0.bam")
    with BamWriter(part, header, write_header=False) as w:
        for r in records[:50]:
            w.write_sam_record(r)
    final = str(tmp_path / "final.bam")
    t0 = METRICS.get("chaos.injected_faults")
    with chaos_on(part, [FaultSpec("transient", at_read=0, count=1)]):
        res = write_bam_shards_concat([part], final, header, config=_cfg())
    assert res.records == 50
    assert METRICS.get("chaos.injected_faults") == t0 + 1
    assert METRICS.get("write.part_read_retries") >= 1


def test_cram_toc_walk_observes_chaos(tmp_path):
    """The query engine's CRAM container-table walk goes through
    as_byte_source: installed faults are observed (classified), not
    silently bypassed via a raw open()."""
    from hadoop_bam_tpu.api.writers import CramShardWriter
    from hadoop_bam_tpu.query.engine import QueryEngine

    header = make_header(2)
    recs = [r for r in make_records(header, 300, seed=9) if r.flag != 4]
    recs.sort(key=lambda r: (header.ref_names.index(r.rname), r.pos))
    path = str(tmp_path / "t.cram")
    with CramShardWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    engine = QueryEngine()
    meta = engine._file_meta(path)
    t0 = METRICS.get("chaos.injected_faults")
    with chaos_on(path, [FaultSpec("transient", count=1000)]):
        with pytest.raises(TransientIOError):
            engine._cram_container_table(path, ("fresh", 1))
    assert METRICS.get("chaos.injected_faults") > t0
    assert meta is not None


def test_serve_prefetch_background_reads_observe_chaos(bam):
    """Prefetch's background chunk decodes flow through the registry
    (and their faults stay out of the foreground serve path)."""
    from hadoop_bam_tpu.serve import ServeLoop

    path, header, _ = bam
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, retry_backoff_base_s=0.001,
        retry_backoff_max_s=0.002, span_retries=3)
    with ServeLoop(config=cfg) as loop:
        loop.query(path, ["chr1:1-50000"])       # warm meta cleanly
        t0 = METRICS.get("chaos.injected_faults")
        with chaos_on(path, [FaultSpec("transient", count=2)]):
            res = loop.query(path, ["chr1:50001-120000"])
            loop.prefetcher.drain()
        assert res[0].count >= 0                 # foreground unharmed
        assert METRICS.get("chaos.injected_faults") > t0


# ---------------------------------------------------------------------------
# seed-derived deterministic schedules
# ---------------------------------------------------------------------------

def test_seeded_schedule_is_deterministic_and_offset_keyed():
    data = bytes(np.random.default_rng(0).integers(
        0, 255, size=100_000, dtype=np.uint8))

    def fire_set(seed, order):
        src = FaultInjectingByteSource(
            data, schedule=SeededFaultSchedule(seed, transient_rate=0.4))
        fired = set()
        for off in order:
            try:
                src.pread(off, 512)
            except TransientIOError:
                fired.add(off)
        return fired

    offsets = list(range(0, 100_000, 1013))
    a = fire_set(123, offsets)
    b = fire_set(123, list(reversed(offsets)))   # order-independent
    assert a == b and 0 < len(a) < len(offsets)
    assert fire_set(124, offsets) != a           # seed changes timeline


def test_seeded_schedule_once_budget_heals_on_retry():
    sched = SeededFaultSchedule(7, transient_rate=1.0)
    src = FaultInjectingByteSource(b"abcdef" * 100, schedule=sched)
    with pytest.raises(TransientIOError):
        src.pread(0, 64)
    assert src.pread(0, 64) == (b"abcdef" * 100)[:64]   # healed


def test_chaos_seed_reproduces_flagstat_fault_timeline(bam):
    """One ``chaos_seed`` knob reproduces the whole chaos run: same
    injected offsets, same healed result, run after run."""
    path, header, records = bam
    spans = _spans(path, header, n=4)
    cfg = _cfg(span_retries=4)
    clean = _flagstat(path, header, spans, cfg)

    def seeded_run(seed):
        sched = install_chaos_seeded(path, seed, transient_rate=0.5)
        try:
            out = _flagstat(path, header, spans, cfg)
        finally:
            clear_chaos(path)
        return out, frozenset(sched._fired)

    out1, fired1 = seeded_run(42)
    out2, fired2 = seeded_run(42)
    assert out1 == out2 == clean
    assert fired1 == fired2 and len(fired1) > 0
    _, fired3 = seeded_run(43)
    assert fired3 != fired1


def test_seeded_point_faults_deterministic():
    a = chaos.seeded_point_faults(5, "pool.submit",
                                  ["transient", "delay"], 4, 32)
    b = chaos.seeded_point_faults(5, "pool.submit",
                                  ["transient", "delay"], 4, 32)
    assert [(f.kind, f.at_call) for f in a] == \
        [(f.kind, f.at_call) for f in b]
    c = chaos.seeded_point_faults(6, "pool.submit",
                                  ["transient", "delay"], 4, 32)
    assert [(f.kind, f.at_call) for f in c] != \
        [(f.kind, f.at_call) for f in a]


# ---------------------------------------------------------------------------
# soak: serve/write under combined chaos (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_serve_under_combined_chaos(bam):
    """Sustained multi-tenant serving under byte-source + transport +
    pool chaos with tight quotas: every failure is a classified
    taxonomy error (never a hang, never an unclassified crash), the
    loop answers health throughout, and after the chaos clears the
    answers match the clean oracle."""
    from hadoop_bam_tpu.serve import ServeLoop

    path, header, _ = bam
    regions = ["chr1:1-100000", "chr1:100001-300000", "chr1:1-50000",
               "chr2:1-80000"]
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, serve_prefetch=True, span_retries=3,
        retry_backoff_base_s=0.001, retry_backoff_max_s=0.005,
        serve_tenant_max_in_flight=2, serve_tenant_queue_depth=1,
        breaker_cooldown_s=0.2)
    with ServeLoop(config=cfg) as loop:
        oracle = [r.count for r in loop.query(path, regions)]
        sched = install_chaos_seeded(path, 1234, transient_rate=0.25,
                                     slow_rate=0.1, delay_s=0.001)
        errs = []
        done = [0]

        def client(tenant, n):
            rng = np.random.default_rng(hash(tenant) % 2**32)
            for i in range(n):
                try:
                    loop.query(path, [regions[int(rng.integers(
                        0, len(regions)))]], tenant=tenant,
                        deadline_s=20.0)
                    done[0] += 1
                except (TransientIOError, CorruptDataError,
                        CircuitBreakerError) as e:
                    errs.append(e)      # classified: acceptable shed
                except PlanError as e:  # never expected here
                    errs.append(AssertionError(e))

        try:
            with fault_points_on("pool.submit",
                                 chaos.seeded_point_faults(
                                     99, "pool.submit", ["transient"],
                                     6, 200)):
                ts = [threading.Thread(target=client,
                                       args=(f"t{k}", 15))
                      for k in range(3)]
                t0 = time.monotonic()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=240)
                assert all(not t.is_alive() for t in ts)   # no hang
                assert time.monotonic() - t0 < 240
                h = loop.health()
                assert h["status"] == "serving"
        finally:
            clear_chaos(path)
        assert not any(isinstance(e, AssertionError) for e in errs)
        assert done[0] > 0
        assert len(sched._fired) > 0
        # chaos off: the loop answers the oracle again (degrade-and-
        # heal, not degrade-and-stay-broken)
        time.sleep(0.3)              # past breaker_cooldown_s
        assert [r.count for r in loop.query(path, regions)] == oracle
