"""BAM codec tests: round-trip through our writer/reader, plus a hand-built
record byte layout as an independent spec oracle."""
import struct

import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import (
    BamBatch, SAMHeader, encode_record, parse_cigar_string, reg2bin,
    walk_record_offsets,
)
from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam, read_bam_header
from hadoop_bam_tpu.formats.sam import SamRecord

from fixtures import make_header, make_records


def hand_built_record() -> bytes:
    """Spec-literal record: read 'r1', flag 0, chr1(0):pos 100 (0-based 99),
    mapq 30, cigar 4M, seq ACGT, qual IIII (phred 40)."""
    name = b"r1\x00"
    cigar = struct.pack("<I", (4 << 4) | 0)  # 4M
    seq = bytes([(1 << 4) | 2, (4 << 4) | 8])  # A=1 C=2 G=4 T=8
    qual = bytes([40, 40, 40, 40])
    body = struct.pack("<iiBBHHHiiii",
                       0,        # refID
                       99,       # pos
                       len(name),  # l_read_name
                       30,       # mapq
                       reg2bin(99, 103),  # bin
                       1,        # n_cigar
                       0,        # flag
                       4,        # l_seq
                       -1, -1, 0)  # mate refid, mate pos, tlen
    body += name + cigar + seq + qual
    return struct.pack("<i", len(body)) + body


def test_decode_hand_built_record():
    raw = hand_built_record()
    batch = BamBatch(np.frombuffer(raw, dtype=np.uint8),
                     walk_record_offsets(raw), header=make_header())
    assert len(batch) == 1
    assert batch.read_name(0) == "r1"
    assert int(batch.pos[0]) == 99
    assert int(batch.mapq[0]) == 30
    assert batch.cigar_string(0) == "4M"
    assert batch.seq_string(0) == "ACGT"
    assert batch.qual_string(0) == "IIII"
    line = batch.to_sam_line(0)
    assert line.split("\t")[:6] == ["r1", "0", "chr1", "100", "30", "4M"]


def test_encode_matches_hand_built():
    enc = encode_record(name="r1", flag=0, refid=0, pos=99, mapq=30,
                        cigar=parse_cigar_string("4M"), seq="ACGT", qual="IIII")
    assert enc == hand_built_record()


def test_header_roundtrip():
    h = make_header(5)
    raw = h.to_bam_bytes()
    h2, after = SAMHeader.from_bam_bytes(raw)
    assert after == len(raw)
    assert h2.ref_names == h.ref_names
    assert h2.ref_lengths == h.ref_lengths
    assert h2.text == h.text


@pytest.mark.parametrize("n", [1, 100, 3000])
def test_full_file_roundtrip(tmp_path, n):
    header = make_header()
    records = make_records(header, n, seed=n)
    path = str(tmp_path / "t.bam")
    with BamWriter(path, header, track_voffsets=True) as w:
        for r in records:
            w.write_sam_record(r)
        voffs = list(w.record_voffsets())
    hdr, batch = read_bam(path)
    assert hdr.ref_names == header.ref_names
    assert len(batch) == n
    for i in [0, n // 2, n - 1]:
        expect = records[i]
        got = SamRecord.from_line(batch.to_sam_line(i))
        assert got == expect
    assert len(voffs) == n


def test_read_bam_header_voffset(tmp_path):
    header = make_header()
    records = make_records(header, 50, seed=7)
    path = str(tmp_path / "t.bam")
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    hdr, first_voffset = read_bam_header(path)
    assert hdr.ref_names == header.ref_names
    # seeking to first_voffset must land exactly on record 0
    r = bgzf.BGZFReader(path)
    r.seek_voffset(first_voffset)
    raw = r.read(1 << 20)
    batch = BamBatch(np.frombuffer(raw, dtype=np.uint8),
                     walk_record_offsets(raw), header=hdr)
    assert batch.read_name(0) == records[0].qname


def test_tag_roundtrip():
    tags = [("NM", "i", 3), ("RG", "Z", "grp1"), ("XF", "f", 1.5),
            ("XA", "A", "c"), ("XB", "B", ("S", [1, 2, 65535]))]
    enc = encode_record(name="t", flag=4, refid=-1, pos=-1, mapq=0, tags=tags)
    batch = BamBatch(np.frombuffer(enc, dtype=np.uint8),
                     walk_record_offsets(enc))
    got = batch.tags(0)
    assert [t[0] for t in got] == ["NM", "RG", "XF", "XA", "XB"]
    assert got[1][2] == "grp1"
    assert got[4][2] == ("S", [1, 2, 65535])


def test_sam_line_parse_format_roundtrip():
    header = make_header()
    for rec in make_records(header, 20, seed=3):
        assert SamRecord.from_line(rec.to_line()) == rec
