"""Round-21 device decode plane families: payload (seq_stats), variant
(BCF stats), and cold serve tiles, all on the token-feed mesh plane.

Three contracts pinned per family (ISSUE round 21 acceptance):

- **parity**: the device route produces value-identical results to the
  host oracle on clean inputs, and the SAME outcome/error class under
  byte-flip fuzz, CRC-footer flips, and truncation — never a different
  answer, never a different failure taxonomy;
- **demotion**: an injected ``device.step`` chaos fault demotes the run
  through the PR-11 ladder to a byte-identical host result and charges
  the device breaker only after the host run completes;
- **metering**: a cold serve tile built on the device plane does zero
  host record decode (``pipeline.host_decode_wall`` stays exactly 0).
"""
import dataclasses
import os
import random

import numpy as np
import pytest

from hadoop_bam_tpu import resilience
from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.resilience import OPEN
from hadoop_bam_tpu.resilience.chaos import PointFault, fault_points_on
from hadoop_bam_tpu.utils import native
from hadoop_bam_tpu.utils.errors import CORRUPT, classify_error
from hadoop_bam_tpu.utils.metrics import MetricsContext

from fixtures import make_header

pytestmark = [
    pytest.mark.device_inflate,
    pytest.mark.skipif(not native.available(),
                       reason="native tokenizer unavailable"),
]


def _dev_cfg(**kw):
    base = dict(inflate_backend="device", retry_backoff_base_s=0.001,
                retry_backoff_max_s=0.002)
    base.update(kw)
    return dataclasses.replace(DEFAULT_CONFIG, **base)


def _host_cfg(**kw):
    base = dict(retry_backoff_base_s=0.001, retry_backoff_max_s=0.002)
    base.update(kw)
    return dataclasses.replace(DEFAULT_CONFIG, **base)


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    from test_serve import _write_bam

    path = str(tmp_path_factory.mktemp("devplane") / "p.bam")
    header = make_header(2)
    _write_bam(path, header, 1200, seed=29)
    return path, header


@pytest.fixture(scope="module")
def bcf(tmp_path_factory):
    from test_bcf_columns import CROSS_LINES, _write_pair

    tmp = tmp_path_factory.mktemp("devvar")
    _vcf, bcf_path, header, _recs = _write_pair(tmp, CROSS_LINES * 8)
    return bcf_path, header


def _seq_stats(path, config=None):
    from hadoop_bam_tpu.parallel.pipeline import seq_stats_file

    kw = {"config": config} if config is not None else {}
    return seq_stats_file(path, **kw)


def _variant_stats(path, config=None):
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file

    kw = {"config": config} if config is not None else {}
    return variant_stats_file(path, **kw)


def _close(a, b):
    """Value parity between two stats dicts: counts exact, float
    reductions within reduce-order jitter (the device plane folds f32
    tile partials that the host sums in f64 — ~1e-6 relative)."""
    if set(a) != set(b):
        return False
    for k in a:
        va, vb = a[k], b[k]
        if isinstance(va, (int, np.integer)):
            if int(va) != int(vb):
                return False
        elif not np.allclose(np.asarray(va, np.float64),
                             np.asarray(vb, np.float64),
                             rtol=1e-5, atol=1e-8):
            return False
    return True


def _outcome(fn):
    try:
        return ("ok", fn())
    except Exception as e:  # noqa: BLE001 — taxonomy-class comparison
        return ("err", classify_error(e))


def _same(host, dev):
    if host[0] != dev[0]:
        return False
    return host[1] == dev[1] if host[0] == "err" else _close(host[1], dev[1])


# ---------------------------------------------------------------------------
# clean parity
# ---------------------------------------------------------------------------

def test_seq_stats_device_matches_host(bam):
    path, _h = bam
    host = _seq_stats(path)
    dev = _seq_stats(path, _dev_cfg())
    assert _close(dev, host), (dev, host)
    assert host["n_reads"] == 1200


def test_variant_stats_device_matches_host(bcf):
    path, _h = bcf
    host = _variant_stats(path)
    with MetricsContext() as m:
        dev = _variant_stats(path, _dev_cfg())
    assert _close(dev, host), (dev, host)
    snap = m.snapshot()
    # whole-span device route: zero host record decode on the clean run
    assert snap.get("wall_timers", {}).get(
        "pipeline.host_decode_wall", 0.0) == 0.0
    assert "vcf.device_resolve_wall" in snap.get("wall_timers", {})


# ---------------------------------------------------------------------------
# byte-flip / CRC-flip / truncation fuzz: same outcome class both planes
# ---------------------------------------------------------------------------

def _fuzz_family(tmp_path, raw, suffix, run, n_flips, seed):
    """Flip one byte at a time across the compressed container and run
    the host and device arms; every position must yield the SAME
    outcome — same values on success, same taxonomy class on failure.
    A final truncated arm pins the cut-stream class too."""
    rng = random.Random(seed)
    positions = rng.sample(range(len(raw)), n_flips)
    mismatches = []
    for pos in positions:
        bad = bytearray(raw)
        bad[pos] ^= 0xFF
        p = str(tmp_path / f"flip{pos}{suffix}")
        with open(p, "wb") as f:
            f.write(bytes(bad))
        host = _outcome(lambda: run(p, _host_cfg()))
        dev = _outcome(lambda: run(p, _dev_cfg()))
        if not _same(host, dev):
            mismatches.append((pos, host, dev))
    assert not mismatches, mismatches
    # truncation: cut mid-stream, both planes raise the same class
    p = str(tmp_path / f"trunc{suffix}")
    with open(p, "wb") as f:
        f.write(raw[: len(raw) * 2 // 3])
    host = _outcome(lambda: run(p, _host_cfg()))
    dev = _outcome(lambda: run(p, _dev_cfg()))
    assert _same(host, dev), (host, dev)
    assert host[0] == "err"


def test_payload_byte_flip_fuzz_same_outcome(bam, tmp_path):
    path, _h = bam
    raw = open(path, "rb").read()
    _fuzz_family(tmp_path, raw, ".bam",
                 lambda p, cfg: _seq_stats(p, cfg), n_flips=8, seed=17)


def test_variant_byte_flip_fuzz_same_outcome(bcf, tmp_path):
    path, _h = bcf
    raw = open(path, "rb").read()
    _fuzz_family(tmp_path, raw, ".bcf",
                 lambda p, cfg: _variant_stats(p, cfg), n_flips=8, seed=19)


@pytest.mark.parametrize("family", ["payload", "variant"])
def test_crc_flip_same_outcome_both_planes(family, bam, bcf, tmp_path):
    """CRC-footer damage (data bytes intact) keeps the planes in
    lockstep per family contract: the BAM payload route honors
    ``check_crc`` on both planes (invisible off, CORRUPT on); the
    variant route folds CRCs unconditionally on both planes — the host
    BGZF frame reader always verifies, so the device tokenize-time fold
    is always on there too."""
    from hadoop_bam_tpu.ops.inflate import block_table

    path, run = ((bam[0], _seq_stats) if family == "payload"
                 else (bcf[0], _variant_stats))
    raw = open(path, "rb").read()
    table = block_table(raw)
    # flip the footer of the largest DATA block — block 0 holds the
    # format header, whose reader folds CRCs unconditionally
    idx = int(np.argmax(table["cdata_len"]))
    foot = int(table["cdata_off"][idx] + table["cdata_len"][idx])
    bad = bytearray(raw)
    bad[foot] ^= 0xFF
    p = str(tmp_path / f"crc_{family}")
    with open(p, "wb") as f:
        f.write(bytes(bad))
    host = _outcome(lambda: run(p, _host_cfg()))
    dev = _outcome(lambda: run(p, _dev_cfg()))
    if family == "payload":
        clean = run(path, _host_cfg())
        assert _same(host, ("ok", clean)) and _same(dev, ("ok", clean))
    else:
        assert host == dev == ("err", CORRUPT)
    host = _outcome(lambda: run(p, _host_cfg(check_crc=True)))
    dev = _outcome(lambda: run(p, _dev_cfg(check_crc=True)))
    assert host == dev == ("err", CORRUPT)


# ---------------------------------------------------------------------------
# seeded chaos: every family demotes through the ladder to host parity
# ---------------------------------------------------------------------------

def test_payload_chaos_demotes_to_host_result(bam):
    path, _h = bam
    oracle = _seq_stats(path)
    cfg = _dev_cfg(breaker_failure_threshold=1.0)
    with fault_points_on("device.step", [PointFault("transient", count=1)]):
        faulted = _seq_stats(path, cfg)
    assert _close(faulted, oracle), (faulted, oracle)
    key = f"decode/device/{os.path.abspath(path)}"
    assert resilience.registry().states()[key]["state"] == OPEN


def test_variant_chaos_demotes_to_host_result(bcf):
    path, _h = bcf
    oracle = _variant_stats(path)
    cfg = _dev_cfg(breaker_failure_threshold=1.0)
    with fault_points_on("device.step", [PointFault("transient", count=1)]):
        faulted = _variant_stats(path, cfg)
    assert _close(faulted, oracle), (faulted, oracle)
    key = f"decode/device/{os.path.abspath(path)}"
    assert resilience.registry().states()[key]["state"] == OPEN


def test_serve_chaos_demotes_to_host_tiles(tmp_path):
    from test_serve import _REGIONS, _oracle_counts, _write_bam

    from hadoop_bam_tpu.serve import ServeLoop

    path = str(tmp_path / "c.bam")
    _write_bam(path, make_header(2), 2000, seed=31)
    want, _ = _oracle_counts(path, _REGIONS)
    cfg = _dev_cfg(serve_prefetch=False, breaker_failure_threshold=1.0)
    with ServeLoop(config=cfg) as loop:
        with fault_points_on("device.step",
                             [PointFault("transient", count=1)]):
            cold = loop.query(path, _REGIONS)
        assert [r.count for r in cold] == want
    key = f"decode/device/{os.path.abspath(path)}"
    assert resilience.registry().states()[key]["state"] == OPEN


# ---------------------------------------------------------------------------
# cold serve tiles: device-built, zero host decode, warm hits intact
# ---------------------------------------------------------------------------

def test_serve_cold_device_tiles_zero_host_decode(tmp_path):
    from test_serve import _REGIONS, _oracle_counts, _write_bam

    from hadoop_bam_tpu.serve import ServeLoop

    path = str(tmp_path / "s.bam")
    _write_bam(path, make_header(2), 2500, seed=77)
    want, oracle = _oracle_counts(path, _REGIONS)
    cfg = _dev_cfg(serve_prefetch=False)
    with ServeLoop(config=cfg) as loop:
        with MetricsContext() as m:
            cold = loop.query(path, _REGIONS)
        assert [r.count for r in cold] == want
        snap = m.snapshot()
        # the round-21 pin: a cold miss on the device tile route does
        # NO host inflate and NO host record walk at all
        assert snap.get("wall_timers", {}).get(
            "pipeline.host_decode_wall", 0.0) == 0.0
        assert snap["counters"].get("serve.device_tile_builds", 0) > 0
        assert snap["counters"].get("query.chunks_decoded", 0) == 0
        # warm pass: resident device tiles serve every region
        warm = loop.query(path, _REGIONS)
        assert [r.count for r in warm] == want
        assert all(r.tile_misses == 0 and r.tile_hits > 0 for r in warm)
        # records mode stays on the host oracle plane, byte-identical
        res = loop.query(path, _REGIONS[:2], want_records=True)
        _, oracle2 = _oracle_counts(path, _REGIONS[:2])
        for out, w in zip(res, oracle2):
            assert ([r.to_line() for r in out.records]
                    == [r.to_line() for r in w.records])
