"""Mesh pipeline tests on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.parallel.distributed import assign_spans, broadcast_plan
from hadoop_bam_tpu.parallel.mesh import make_mesh
from hadoop_bam_tpu.parallel.pipeline import (
    DecodeGeometry, decode_span_host, flagstat_file, iter_span_groups,
    make_unpack_step, stack_span_group,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans

from fixtures import make_header, make_records


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("pipe") / "p.bam")
    header = make_header()
    records = make_records(header, 5000, seed=11)
    with BamWriter(path, header, track_voffsets=True) as w:
        for r in records:
            w.write_sam_record(r)
        voffs = list(w.record_voffsets())
    return path, header, records, voffs


GEOM = DecodeGeometry(bytes_cap=1 << 21, records_cap=1 << 14)


def test_decode_span_host_union(bam):
    """Union-exactly-once for the pipeline's own span decoder."""
    path, header, records, voffs = bam
    spans = plan_bam_spans(path, num_spans=7, header=header)
    got = []
    for s in spans:
        d, o, n, v = decode_span_host(path, s, GEOM)
        got.extend(int(x) for x in v)
        assert n == v.size
    assert got == voffs


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert int(np.prod(mesh.devices.shape)) == 8


def test_flagstat_file_on_mesh(bam):
    path, header, records, voffs = bam
    mesh = make_mesh()
    stats = flagstat_file(path, mesh=mesh, geometry=GEOM, header=header)
    flags = np.asarray([r.flag for r in records])
    assert stats["total"] == len(records)
    assert stats["mapped"] == int(np.sum((flags & 0x4) == 0))
    assert stats["paired"] == int(np.sum((flags & 0x1) != 0))
    assert stats["secondary"] == int(np.sum((flags & 0x100) != 0))


def test_unpack_step_sharded(bam):
    path, header, records, voffs = bam
    mesh = make_mesh()
    spans = plan_bam_spans(path, num_spans=8, header=header)
    group = list(iter_span_groups(spans, 8))[0]
    batch = stack_span_group(path, group, 8, GEOM)
    step = make_unpack_step(mesh)
    cols = step(batch.data, batch.offsets, batch.n_records)
    assert cols["pos"].shape == (8, GEOM.records_cap)
    # device 0's first records match host decode of span 0
    d, o, n, v = decode_span_host(path, group[0], GEOM)
    from hadoop_bam_tpu.formats.bam import BamBatch
    hb = BamBatch(d, o[:n].astype(np.int64))
    np.testing.assert_array_equal(np.asarray(cols["pos"])[0, :n], hb.pos)
    valid = np.asarray(cols["valid"])
    assert valid[0, :n].all() and not valid[0, n:].any()


def test_broadcast_and_assign(bam):
    path, header, *_ = bam
    spans = plan_bam_spans(path, num_spans=6, header=header)
    assert broadcast_plan(spans) == spans
    # partition over 3 fake hosts: disjoint cover
    parts = [assign_spans(spans, index=i, count=3) for i in range(3)]
    flat = [s for p in parts for s in p]
    assert sorted(flat, key=lambda s: s.start_voffset) == spans
    assert all(len(p) >= 1 for p in parts)
