"""Mesh pipeline tests on the virtual 8-device CPU mesh."""
import os

import numpy as np
import pytest

from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.parallel.distributed import assign_spans, broadcast_plan
from hadoop_bam_tpu.parallel.mesh import make_mesh
from hadoop_bam_tpu.parallel.pipeline import (
    DecodeGeometry, decode_span_host, flagstat_file, iter_span_groups,
    make_unpack_step, stack_span_group,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans

from fixtures import make_header, make_records


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("pipe") / "p.bam")
    header = make_header()
    records = make_records(header, 5000, seed=11)
    with BamWriter(path, header, track_voffsets=True) as w:
        for r in records:
            w.write_sam_record(r)
        voffs = list(w.record_voffsets())
    return path, header, records, voffs


GEOM = DecodeGeometry(bytes_cap=1 << 21, records_cap=1 << 14)


def test_decode_span_host_union(bam):
    """Union-exactly-once for the pipeline's own span decoder."""
    path, header, records, voffs = bam
    spans = plan_bam_spans(path, num_spans=7, header=header)
    got = []
    for s in spans:
        d, o, n, v = decode_span_host(path, s, GEOM)
        got.extend(int(x) for x in v)
        assert n == v.size
    assert got == voffs


def test_record_chain_spanning_many_blocks(tmp_path):
    """A record whose bytes span >=64 BGZF blocks decodes correctly — the
    span decoder's tail-extension path (one concatenate, not a per-block
    re-copy) must fetch the whole chain."""
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.formats.bamio import read_bam
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core
    from hadoop_bam_tpu.split.spans import FileVirtualSpan

    header = make_header()
    # header-only BAM, EOF stripped, then the record re-blocked tiny
    base = str(tmp_path / "hdr.bam")
    with BamWriter(base, header) as w:
        pass
    hdr_bytes = open(base, "rb").read()[:-len(bgzf.EOF_BLOCK)]

    recs = make_records(header, 2, seed=3)
    tmp = str(tmp_path / "tmp.bam")
    with BamWriter(tmp, header) as w:
        w.write_sam_record(recs[0])
        long = recs[1]
        long.seq = "ACGT" * 30000          # 120k bases -> ~180 KB record
        long.qual = "I" * len(long.seq)
        long.cigar = f"{len(long.seq)}M"
        w.write_sam_record(long)
    _, tmp_batch = read_bam(tmp)
    wire = [tmp_batch.record_bytes(0), tmp_batch.record_bytes(1)]

    payload = b"".join(wire)
    chunk = 1024                            # ~180 blocks for the chain
    blocks = b"".join(bgzf.deflate_block(payload[i:i + chunk])
                      for i in range(0, len(payload), chunk))
    path = str(tmp_path / "chain.bam")
    with open(path, "wb") as f:
        f.write(hdr_bytes + blocks + bgzf.EOF_BLOCK)

    first_c = len(hdr_bytes)
    # span owns only the first block: both records start in it, the second
    # extends across the whole chain
    span = FileVirtualSpan(path, (first_c << 16),
                           ((first_c + bgzf.parse_block_header(
                               open(path, "rb").read()[first_c:], 0
                           ).block_size) << 16))
    data, offs, voffs, _ = _decode_span_core(path, span)
    assert offs.size == 2
    got = [bytes(data[int(offs[0]):int(offs[1])]),
           bytes(data[int(offs[1]):int(offs[1]) + len(wire[1])])]
    assert got == wire

    # and the dataset surface decodes it end to end
    ds = open_bam(path)
    batches = list(ds.batches())
    total = sum(len(b) for b in batches)
    assert total == 2
    last = batches[-1]
    assert last.read_name(len(last) - 1) == long.qname
    assert last.seq_string(len(last) - 1) == long.seq


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert int(np.prod(mesh.devices.shape)) == 8


def test_flagstat_file_on_mesh(bam):
    path, header, records, voffs = bam
    mesh = make_mesh()
    stats = flagstat_file(path, mesh=mesh, geometry=GEOM, header=header)
    flags = np.asarray([r.flag for r in records])
    assert stats["total"] == len(records)
    assert stats["mapped"] == int(np.sum((flags & 0x4) == 0))
    assert stats["paired"] == int(np.sum((flags & 0x1) != 0))
    assert stats["secondary"] == int(np.sum((flags & 0x100) != 0))


def test_unpack_step_sharded(bam):
    path, header, records, voffs = bam
    mesh = make_mesh()
    spans = plan_bam_spans(path, num_spans=8, header=header)
    group = list(iter_span_groups(spans, 8))[0]
    batch = stack_span_group(path, group, 8, GEOM)
    step = make_unpack_step(mesh)
    cols = step(batch.data, batch.offsets, batch.n_records)
    assert cols["pos"].shape == (8, GEOM.records_cap)
    # device 0's first records match host decode of span 0
    d, o, n, v = decode_span_host(path, group[0], GEOM)
    from hadoop_bam_tpu.formats.bam import BamBatch
    hb = BamBatch(d, o[:n].astype(np.int64))
    np.testing.assert_array_equal(np.asarray(cols["pos"])[0, :n], hb.pos)
    valid = np.asarray(cols["valid"])
    assert valid[0, :n].all() and not valid[0, n:].any()


def test_decode_span_prefix_host_matches_span_mode(bam):
    """Prefix-tile rows must equal the 36-byte record prefixes from the
    full-span decode, for both native and NumPy-fallback packers."""
    path, header, records, voffs = bam
    from hadoop_bam_tpu.parallel.pipeline import decode_span_prefix_host
    spans = plan_bam_spans(path, num_spans=5, header=header)
    got_voffs = []
    for s in spans:
        d, o, n, v = decode_span_host(path, s, GEOM)
        rows, pv = decode_span_prefix_host(path, s)
        assert rows.shape == (n, 36)
        got_voffs.extend(int(x) for x in pv)
        idx = o[:n].astype(np.int64)[:, None] + np.arange(36)[None, :]
        np.testing.assert_array_equal(rows, d[idx])
    assert got_voffs == voffs


def test_projection_pack_and_unpack(bam):
    """Projected rows decode to the same columns as the full-field path."""
    path, header, records, voffs = bam
    from hadoop_bam_tpu.ops.unpack_bam import (
        FLAGSTAT_PROJECTION, projection_ranges, projection_row_bytes,
        unpack_projected_tile,
    )
    from hadoop_bam_tpu.parallel.pipeline import decode_span_prefix_host
    assert projection_ranges(tuple(
        ["block_size", "refid", "pos", "l_read_name", "mapq", "bin",
         "n_cigar", "flag", "l_seq", "mate_refid", "mate_pos", "tlen"])) \
        == [(0, 36)]
    spans = plan_bam_spans(path, num_spans=3, header=header)
    rows, _ = decode_span_prefix_host(
        path, spans[0], projection=FLAGSTAT_PROJECTION, want_voffs=False)
    assert rows.shape[1] == projection_row_bytes(FLAGSTAT_PROJECTION) == 11
    cols = unpack_projected_tile(rows, FLAGSTAT_PROJECTION)
    full, _ = decode_span_prefix_host(path, spans[0])
    from hadoop_bam_tpu.ops.unpack_bam import unpack_fixed_fields_tile
    ref = unpack_fixed_fields_tile(full)
    for name in FLAGSTAT_PROJECTION:
        np.testing.assert_array_equal(np.asarray(cols[name]),
                                      np.asarray(ref[name]))


def test_native_walk_packed_matches_fallback(bam):
    path, header, records, voffs = bam
    from hadoop_bam_tpu.utils import native
    if not native.available():
        pytest.skip("native library unavailable")
    from hadoop_bam_tpu.ops import inflate as inflate_ops
    from hadoop_bam_tpu.formats.bam import SAMHeader
    raw = open(path, "rb").read()
    data, _ = inflate_ops.inflate_span(raw)
    _, after = SAMHeader.from_bam_bytes(data.tobytes())
    offs, tail = inflate_ops.walk_records(data, start=after)
    rows, offs2, tail2 = native.walk_bam_packed(
        data, after, offs.size + 16, [(18, 2), (4, 4)], 6)
    np.testing.assert_array_equal(offs, offs2)
    assert tail == tail2
    # spot-check packing: bytes 18-19 (flag) then 4-7 (refid)
    i = len(records) // 2
    rec_off = int(offs[i])
    np.testing.assert_array_equal(rows[i, :2], data[rec_off + 18:rec_off + 20])
    np.testing.assert_array_equal(rows[i, 2:6], data[rec_off + 4:rec_off + 8])


def test_broadcast_and_assign(bam):
    path, header, *_ = bam
    spans = plan_bam_spans(path, num_spans=6, header=header)
    assert broadcast_plan(spans) == spans
    # partition over 3 fake hosts: disjoint cover
    parts = [assign_spans(spans, index=i, count=3) for i in range(3)]
    flat = [s for p in parts for s in p]
    assert sorted(flat, key=lambda s: s.start_voffset) == spans
    assert all(len(p) >= 1 for p in parts)


def test_two_host_simulation(bam):
    """Simulate the multi-host protocol single-process: host 0 plans,
    every 'host' decodes only its assigned spans, and the per-host stats
    sum to the whole-file answer (psum-over-DCN equivalence)."""
    path, header, records, voffs = bam
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file

    spans = plan_bam_spans(path, num_spans=6, header=header)
    whole = flagstat_file(path, header=header, spans=spans)
    merged = {k: 0 for k in FLAGSTAT_FIELDS}
    for host in range(2):
        part = assign_spans(spans, index=host, count=2)
        assert part, "each host must get work"
        stats = flagstat_file(path, header=header, spans=part)
        for k in FLAGSTAT_FIELDS:
            merged[k] += stats[k]
    assert merged == whole
    assert whole["total"] == len(records)


_DIST_STATS_CHILD = """\
import json, os, sys
import numpy as np
idx, port, bam_src, vcf_src, fq_src = (int(sys.argv[1]), sys.argv[2],
                                       sys.argv[3], sys.argv[4],
                                       sys.argv[5])
# 2 virtual CPU devices per process via XLA_FLAGS: works on every jax
# (the jax_num_cpu_devices config option only exists on newer releases)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=idx)
from hadoop_bam_tpu.parallel.distributed import (
    distributed_coverage, distributed_fastq_seq_stats, distributed_flagstat,
    distributed_seq_stats, distributed_variant_stats,
)
print("FLAGSTAT", json.dumps(distributed_flagstat(bam_src)), flush=True)
cov = distributed_coverage(bam_src, "chr1:1-16384")
print("COV", json.dumps([int(x) for x in cov]), flush=True)
s = distributed_seq_stats(bam_src)
s["base_hist"] = [int(v) for v in s["base_hist"]]
print("SEQ", json.dumps(s), flush=True)
v = distributed_variant_stats(vcf_src)
v["sample_callrate"] = [round(float(x), 9) for x in v["sample_callrate"]]
print("VAR", json.dumps(v), flush=True)
f = distributed_fastq_seq_stats(fq_src)
f["base_hist"] = [int(x) for x in f["base_hist"]]
print("FQ", json.dumps(f), flush=True)
"""


def test_distributed_stats_two_process(bam, tmp_path):
    """REAL 2-process jax.distributed stats drivers (gloo CPU
    collectives): host 0 plans + broadcasts, each process reduces only
    its share over its local devices, one allgather combines — both
    processes must report whole-file answers matching single-process."""
    import json

    from _multihost import run_two_process
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    from hadoop_bam_tpu.parallel.pipeline import seq_stats_file
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file

    path, header, records, _ = bam
    whole = flagstat_file(path, header=header)
    whole_seq = seq_stats_file(path, header=header)

    vh = VCFHeader.from_text(
        "##fileformat=VCFv4.2\n##contig=<ID=chr1,length=248956422>\n"
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="G">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts0\n")
    vcf_path = str(tmp_path / "dist.vcf.gz")
    with open_vcf_writer(vcf_path, vh) as w:
        for i in range(500):
            w.write_record(VcfRecord.from_line(
                f"chr1\t{100 + i * 7}\t.\tA\tC\t30\tPASS\t.\tGT\t"
                f"{'0/1' if i % 3 else './.'}"))
    whole_var = variant_stats_file(vcf_path)

    import random as _random
    from hadoop_bam_tpu.parallel.pipeline import fastq_seq_stats_file
    rng = _random.Random(9)
    fq_path = str(tmp_path / "dist.fastq")
    with open(fq_path, "w") as f:
        for i in range(3000):
            seq = "".join(rng.choice("ACGT") for _ in range(100))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(100))
            f.write(f"@r{i}\n{seq}\n+\n{qual}\n")
    whole_fq = fastq_seq_stats_file(fq_path)

    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    whole_cov = [int(x) for x in coverage_file(path, "chr1:1-16384",
                                               header=header)]

    got = {"FLAGSTAT": [], "SEQ": [], "VAR": [], "FQ": [], "COV": []}
    for rc, so, se in run_two_process(tmp_path, _DIST_STATS_CHILD,
                                      [path, vcf_path, fq_path]):
        assert rc == 0, f"child failed:\n{so}\n{se[-2000:]}"
        for key in got:
            line = next(ln for ln in so.splitlines()
                        if ln.startswith(key + " "))
            got[key].append(json.loads(line[len(key) + 1:]))
    assert got["FLAGSTAT"][0] == got["FLAGSTAT"][1] == whole
    # per-base depths sum exactly across hosts: integer equality
    assert got["COV"][0] == got["COV"][1] == whole_cov
    assert sum(whole_cov) > 0
    for g in got["SEQ"]:
        assert g["n_reads"] == whole_seq["n_reads"]
        # f32 partial sums regroup across hosts: tolerance is f32-scale
        assert abs(g["mean_gc"] - whole_seq["mean_gc"]) < 1e-4
        assert abs(g["mean_qual"] - whole_seq["mean_qual"]) < 1e-4
        assert g["base_hist"] == [int(v) for v in whole_seq["base_hist"]]
    for g in got["VAR"]:
        assert g["n_variants"] == whole_var["n_variants"] == 500
        assert g["n_snp"] == whole_var["n_snp"]
        assert g["n_pass"] == whole_var["n_pass"]
        assert abs(g["mean_af"] - whole_var["mean_af"]) < 1e-4
    for g in got["FQ"]:
        assert g["n_reads"] == whole_fq["n_reads"] == 3000
        assert abs(g["mean_gc"] - whole_fq["mean_gc"]) < 1e-4
        assert abs(g["mean_qual"] - whole_fq["mean_qual"]) < 1e-4
        assert g["base_hist"] == [int(v) for v in whole_fq["base_hist"]]
    assert whole["total"] == len(records)


def test_bucketed_final_tile_matches_full_cap(tmp_path):
    """The small-input dispatch ladder (_bucket_cap): a file far smaller
    than tile_records dispatches a shrunk final tile, and every stats
    answer is identical to the full-cap geometry's."""
    import random as _random

    from hadoop_bam_tpu.parallel.pipeline import (
        PayloadGeometry, _bucket_cap, fastq_seq_stats_file,
    )

    # ladder arithmetic: block_n-aligned, monotone, capped
    assert _bucket_cap(100, 1 << 16, 256) == 4096
    assert _bucket_cap(5000, 1 << 16, 256) == 16384
    assert _bucket_cap(40000, 1 << 16, 256) == 1 << 16
    assert _bucket_cap(100, 1536, 256) == 256       # cap//16 rounded up
    assert _bucket_cap(100, 256, 256) == 256        # no smaller bucket
    for cap, bn in ((1 << 16, 256), (1536, 256), (32768, 8192)):
        for c in (1, 200, cap // 4, cap):
            b = _bucket_cap(c, cap, bn)
            assert b % bn == 0 and c <= b <= cap

    rng = _random.Random(21)
    fq = str(tmp_path / "small.fastq")
    with open(fq, "w") as f:
        for i in range(700):
            seq = "".join(rng.choice("ACGT") for _ in range(80))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(80))
            f.write(f"@r{i}\n{seq}\n+\n{qual}\n")

    big = PayloadGeometry(tile_records=4096, block_n=256)
    small = PayloadGeometry(tile_records=256, block_n=256)
    got = fastq_seq_stats_file(fq, geometry=big)        # shrink path
    want = fastq_seq_stats_file(fq, geometry=small)     # full tiles only
    assert got["n_reads"] == want["n_reads"] == 700
    assert abs(got["mean_gc"] - want["mean_gc"]) < 1e-5
    assert abs(got["mean_qual"] - want["mean_qual"]) < 1e-5
    assert [int(v) for v in got["base_hist"]] == \
        [int(v) for v in want["base_hist"]]


def test_bucketed_tensor_batches_shapes(tmp_path):
    """tensor_batches: full batches keep tile_records rows; the final
    batch may shrink to a bucket, and totals are unchanged."""
    import numpy as np

    from hadoop_bam_tpu.api.read_datasets import open_fastq
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry

    fq = str(tmp_path / "shapes.fastq")
    with open(fq, "w") as f:
        for i in range(600):
            f.write(f"@r{i}\nACGTACGTAC\n+\nIIIIIIIIII\n")
    geom = PayloadGeometry(tile_records=4096, block_n=256)
    batches = list(open_fastq(fq).tensor_batches(geometry=geom))
    total = sum(int(np.asarray(b["n_records"]).sum()) for b in batches)
    assert total == 600
    # the lone batch shrank to the smallest bucket that holds 600 rows
    assert batches[-1]["qual"].shape[1] <= 1024


def test_fixed_shape_geometry_pads_final_batch(tmp_path):
    """PayloadGeometry(fixed_shape=True): the final batch PADS to
    tile_records instead of shrinking — the opt-out for consumers that
    preallocate by tile_records.  Totals are unchanged."""
    import numpy as np

    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.api.read_datasets import open_fastq
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry

    fq = str(tmp_path / "fixed.fastq")
    with open(fq, "w") as f:
        for i in range(600):
            f.write(f"@r{i}\nACGTACGTAC\n+\nIIIIIIIIII\n")
    geom = PayloadGeometry(tile_records=4096, block_n=256,
                           fixed_shape=True)
    batches = list(open_fastq(fq).tensor_batches(geometry=geom))
    assert all(b["qual"].shape[1] == 4096 for b in batches)
    assert sum(int(np.asarray(b["n_records"]).sum())
               for b in batches) == 600

    # the BAM payload feed honors it too
    bam = str(tmp_path / "fixed.bam")
    header = make_header()
    with BamWriter(bam, header) as w:
        for r in make_records(header, 500, seed=3):
            w.write_sam_record(r)
    batches = list(open_bam(bam).tensor_batches(geometry=geom))
    assert all(b["prefix"].shape[1] == 4096 for b in batches)
    assert sum(int(np.asarray(b["n_records"]).sum())
               for b in batches) == 500


def test_assign_spans_empty_plan():
    """A .bai-pruned region with zero aligned reads yields an empty
    plan; every host must receive an empty assignment (not IndexError)
    so distributed coverage of read-free tiles returns zeros."""
    assert assign_spans([], index=0, count=2) == []
    assert assign_spans([], index=1, count=2) == []
    assert assign_spans([], index=0, count=1) == []
