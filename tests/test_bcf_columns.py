"""Columnar BCF decode parity: formats/bcf_columns.py vs the record
codec and the record-serial scanner, plus corruption fuzz (the columnar
path must raise on malformed input, never mis-decode silently).

Quick selection: ``pytest -m bcf``; the suite is part of tier-1.
"""
import random
import struct

import numpy as np
import pytest

from hadoop_bam_tpu.formats.bcf import (
    BCFError, BCFRecordCodec, scan_variant_columns,
)
from hadoop_bam_tpu.formats.bcf_columns import (
    STAT_KEYS, decode_bcf_columns, frame_record_starts, stat_columns,
)
from hadoop_bam_tpu.formats.vcf import VariantBatch, VCFHeader, VcfRecord

pytestmark = pytest.mark.bcf

N_SAMPLES = 4
HDR = (
    "##fileformat=VCFv4.2\n"
    "##contig=<ID=c1,length=1000000>\n"
    "##contig=<ID=c2,length=500000>\n"
    '##FILTER=<ID=q10,Description="x">\n'
    '##FILTER=<ID=s50,Description="x">\n'
    '##INFO=<ID=DP,Number=1,Type=Integer,Description="x">\n'
    '##INFO=<ID=AF,Number=A,Type=Float,Description="x">\n'
    '##INFO=<ID=NM,Number=1,Type=String,Description="x">\n'
    '##INFO=<ID=DB,Number=0,Type=Flag,Description="x">\n'
    '##INFO=<ID=END,Number=1,Type=Integer,Description="x">\n'
    '##FORMAT=<ID=GT,Number=1,Type=String,Description="x">\n'
    '##FORMAT=<ID=DP,Number=1,Type=Integer,Description="x">\n'
    '##FORMAT=<ID=AD,Number=R,Type=Integer,Description="x">\n'
    '##FORMAT=<ID=GL,Number=G,Type=Float,Description="x">\n'
    '##FORMAT=<ID=FT,Number=1,Type=String,Description="x">\n'
    "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
    + "\t".join(f"s{i}" for i in range(N_SAMPLES)) + "\n")

# every typed-value type (int8/int16/int32/float/char/flag), missing
# values at every position, multi-allelic, END/rlen, extended (>14)
# counts via long strings, mixed ploidy, phased-missing, wide GT, a
# record with no genotype block, and GT in a non-leading FORMAT slot
LINES = [
    # plain SNP; int8-range INFO; full genotypes
    "c1\t100\trs1\tA\tC\t30.5\tPASS\tDP=8;AF=0.25\tGT:DP\t"
    "0/1:3\t1|1:7\t0/0:0\t./.:.",
    # multi-allelic SNP, non-PASS filter, flag INFO, >14-char string
    # (extended char count), int16 INFO
    "c1\t200\t.\tA\tC,G,T\t.\tq10\tDB;NM=averylongstringvalue0123456789;"
    "DP=4000\tGT\t1/2\t0|3\t2\t.",
    # long REF (not a SNP), END-driven rlen, float FORMAT with missing,
    # int32 INFO
    "c2\t300\t.\tACGTACGTACGTACGTACGT\tA\t0\t.\tEND=500;DP=3000000\t"
    "GT:GL\t0/0:-1.5,0,-2\t0/1:.\t1/1:0,0,0\t0/0:.",
    # symbolic-ish ALT (indel), mixed ploidy, negative int16 INFO
    "c2\t400\t.\tG\tGTT\t12\tPASS\tDP=-40000\tGT\t0|0\t0/1/1\t.\t0",
    # phased-missing alleles, multi-filter (not PASS), AD vector
    "c2\t500\t.\tT\tA\t1e6\tq10;s50\tAF=0.5,0.25\tGT:AD\t"
    "0|.\t./0\t1/.\t.|1",
    # no genotype block at all
    "c1\t600\t.\tC\tG\t9\tPASS\tDP=1\t",
    # GT not in the leading FORMAT slot + char FORMAT field
    "c1\t700\t.\tG\tT\t5\tPASS\tDP=2\tDP:GT:FT\t1:0/1:ok\t"
    "2:1/1:no\t.:./.:x\t3:0|1:y",
]


def _header():
    return VCFHeader.from_text(HDR)


def _wide_lines():
    """>63 ALTs force int16 GT vectors (value (70+1)<<1 > int8 max)."""
    alts = ",".join("ACGT"[i % 4] * (i // 4 + 2) for i in range(70))
    return [
        f"c1\t100\t.\tA\t{alts}\t30\tPASS\t.\tGT\t0/70\t70/70\t0/0\t./.",
        f"c1\t200\t.\tA\t{alts}\t30\tPASS\t.\tGT\t0/.\t./0\t1/.\t0|70",
    ]


def _encode(lines, header=None):
    header = header or _header()
    codec = BCFRecordCodec(header)
    recs = [VcfRecord.from_line(ln.rstrip("\t")) for ln in lines]
    buf = b"".join(codec.encode(r) for r in recs)
    return header, codec, recs, buf


@pytest.mark.parametrize("lines", [LINES, _wide_lines(),
                                   LINES + _wide_lines()])
def test_columns_match_record_scanner(lines):
    """STAT_KEYS columns == scan_variant_columns, column for column."""
    header, _, _, buf = _encode(lines)
    cols = decode_bcf_columns(buf, header, 8)
    assert cols is not None
    scan = scan_variant_columns(buf, header, 8)
    for k in STAT_KEYS:
        np.testing.assert_array_equal(cols[k], scan[k], err_msg=k)
        assert cols[k].dtype == scan[k].dtype, k


def test_extended_columns_match_record_codec():
    """rlen/qual/n_allele/n_fmt == the VariantBatch view of the decoded
    records (incl. the INFO/END-driven rlen)."""
    header, codec, recs, buf = _encode(LINES)
    cols = decode_bcf_columns(buf, header, 8)
    decoded = []
    off = 0
    while off < len(buf):
        r, off = codec.decode(buf, off)
        decoded.append(r)
    vb = VariantBatch(decoded, header)
    np.testing.assert_array_equal(cols["chrom"], vb.chrom)
    np.testing.assert_array_equal(cols["pos"], vb.pos)
    np.testing.assert_array_equal(cols["rlen"], vb.rlen)
    np.testing.assert_array_equal(cols["n_allele"], vb.n_allele)
    np.testing.assert_array_equal(np.isnan(cols["qual"]),
                                  np.isnan(vb.qual))
    m = ~np.isnan(vb.qual)
    np.testing.assert_allclose(cols["qual"][m], vb.qual[m])
    np.testing.assert_array_equal(
        cols["n_fmt"], [len(r.fmt) for r in decoded])
    assert cols["rlen"][2] == 500 - 300 + 1            # END semantics


def test_dosage_matches_variant_batch_oracle():
    """GT-leading records: dosage == VariantBatch.dosage_matrix (the
    pre-columnar oracle), padding columns stay -1."""
    gt_first = [ln for ln in LINES if "\tGT" in ln and "DP:GT" not in ln]
    header, codec, recs, buf = _encode(gt_first)
    cols = decode_bcf_columns(buf, header, 8)
    vb = VariantBatch(recs, header)
    np.testing.assert_array_equal(cols["dosage"][:, :N_SAMPLES],
                                  vb.dosage_matrix())
    assert (cols["dosage"][:, N_SAMPLES:] == -1).all()


def test_frame_starts_and_span_reader_agree(tmp_path):
    """read_bcf_span_frames' free framing == frame_record_starts."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.split.vcf_planners import read_bcf_span_frames

    header, _, recs, buf = _encode(LINES)
    np.testing.assert_array_equal(
        frame_record_starts(buf),
        np.cumsum([0] + [len(BCFRecordCodec(header).encode(r))
                         for r in recs])[:-1])
    path = str(tmp_path / "frames.bcf")
    with open_vcf_writer(path, header) as w:
        for r in recs:
            w.write_record(r)
    ds = open_vcf(path)
    total = 0
    for span in ds.spans(2):
        raw, starts = read_bcf_span_frames(path, span, ds._is_bgzf_bcf)
        np.testing.assert_array_equal(starts, frame_record_starts(raw))
        total += starts.size
    assert total == len(recs)


def test_empty_buffer():
    cols = decode_bcf_columns(b"", _header(), 8)
    assert cols["chrom"].size == 0
    assert cols["dosage"].shape == (0, 8)


# ---------------------------------------------------------------------------
# corruption fuzz: raise, never mis-decode
# ---------------------------------------------------------------------------

def test_truncation_always_raises():
    """Every cut that is not a record boundary must raise BCFError."""
    header, _, _, buf = _encode(LINES)
    bounds = set(frame_record_starts(buf).tolist()) | {len(buf)}
    step = max(1, len(buf) // 400)      # dense but bounded fuzz
    for cut in range(1, len(buf), step):
        if cut in bounds:
            continue
        with pytest.raises(BCFError):
            decode_bcf_columns(buf[:cut], header, 8)


def test_corrupt_lengths_and_type_codes_raise():
    header, codec, recs, buf = _encode(LINES)
    starts = frame_record_starts(buf)

    # l_shared below the fixed-field floor
    bad = bytearray(buf)
    struct.pack_into("<I", bad, int(starts[1]), 10)
    with pytest.raises(BCFError):
        decode_bcf_columns(bytes(bad), header, 8,
                           starts=starts)          # framing bypassed
    # l_shared ballooned past the buffer
    bad = bytearray(buf)
    struct.pack_into("<I", bad, int(starts[1]), 1 << 30)
    with pytest.raises(BCFError):
        decode_bcf_columns(bytes(bad), header, 8, starts=starts)
    # reserved typed-value code in the ID slot (descriptor at the fixed
    # 24-byte prefix's end): type nibble 4 is undefined by the spec
    bad = bytearray(buf)
    off = int(starts[0]) + 32
    bad[off] = (bad[off] & 0xF0) | 0x04
    with pytest.raises(BCFError):
        decode_bcf_columns(bytes(bad), header, 8, starts=starts)


def test_random_byte_flips_never_decode_loosely():
    """Flipping one byte either still yields records framed exactly as
    claimed (decode succeeds or falls back) or raises BCFError — no
    crash, no out-of-range read."""
    header, _, _, buf = _encode(LINES + _wide_lines())
    rng = random.Random(11)
    for _ in range(300):
        bad = bytearray(buf)
        i = rng.randrange(len(bad))
        bad[i] ^= 1 << rng.randrange(8)
        try:
            starts = frame_record_starts(bytes(bad))
            decode_bcf_columns(bytes(bad), header, 8, starts=starts)
        except BCFError:
            pass


# ---------------------------------------------------------------------------
# pipeline integration: the stats driver takes the columnar path
# ---------------------------------------------------------------------------

# the cross-container comparisons must drop the GT-not-first record:
# the text paths (tokenizer + VariantBatch) only read a LEADING GT,
# while both binary scanners key on the GT dictionary id anywhere —
# a pre-existing, documented divergence (see PARITY.md)
CROSS_LINES = [ln for ln in LINES
               if not ln.endswith("\t") and "DP:GT" not in ln]


def _write_pair(tmp_path, lines):
    """The same records as text VCF and BGZF BCF."""
    from hadoop_bam_tpu.api.writers import open_vcf_writer

    header, _, recs, _ = _encode(lines)
    vcf = str(tmp_path / "t.vcf")
    with open(vcf, "w") as f:
        f.write(HDR)
        for r in recs:
            f.write(r.to_line() + "\n")
    bcf = str(tmp_path / "t.bcf")
    with open_vcf_writer(bcf, header) as w:
        for r in recs:
            w.write_record(r)
    return vcf, bcf, header, recs


def test_variant_stats_bcf_uses_columnar_path(tmp_path, monkeypatch):
    """variant_stats_file on BCF == on the text twin, via the columnar
    decoder (the record-serial scanner is poisoned to prove no
    fallback)."""
    from hadoop_bam_tpu import formats
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file

    vcf, bcf, header, recs = _write_pair(tmp_path, CROSS_LINES)
    expect = variant_stats_file(vcf)

    def boom(*a, **k):
        raise AssertionError("record-serial scan used on an eligible span")
    monkeypatch.setattr(formats.bcf, "scan_variant_columns", boom)
    got = variant_stats_file(bcf)
    for k in ("n_variants", "n_snp", "n_pass", "n_af"):
        assert got[k] == expect[k], k
    assert abs(got["mean_af"] - expect["mean_af"]) < 1e-6
    np.testing.assert_allclose(got["sample_callrate"],
                               expect["sample_callrate"], atol=1e-9)


def test_variant_stats_bcf_fallback_matches(tmp_path, monkeypatch):
    """With the columnar decoder declining every span, the scanner
    fallback must produce identical stats."""
    import hadoop_bam_tpu.formats.bcf_columns as bc
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file

    _, bcf, header, recs = _write_pair(tmp_path, CROSS_LINES)
    expect = variant_stats_file(bcf)
    monkeypatch.setattr(bc, "decode_bcf_columns", lambda *a, **k: None)
    got = variant_stats_file(bcf)
    assert {k: v for k, v in got.items() if k != "sample_callrate"} \
        == {k: v for k, v in expect.items() if k != "sample_callrate"}
    np.testing.assert_array_equal(got["sample_callrate"],
                                  expect["sample_callrate"])


def test_tensor_batches_bcf_matches_text(tmp_path):
    """VcfDataset.tensor_batches over BCF (columnar feed) == over the
    text twin (record feed), tile for tile."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.parallel.variant_pipeline import VariantGeometry

    vcf, bcf, header, recs = _write_pair(tmp_path, CROSS_LINES * 30)
    g = VariantGeometry(tile_records=64, n_samples=header.n_samples)

    def collect(path):
        out = []
        for batch in open_vcf(path).tensor_batches(geometry=g,
                                                   num_spans=2):
            out.append({k: np.asarray(v) for k, v in batch.items()})
        return out

    a, b = collect(bcf), collect(vcf)
    assert len(a) == len(b)
    for ta, tb in zip(a, b):
        assert set(ta) == set(tb)
        for k in ta:
            np.testing.assert_array_equal(ta[k], tb[k], err_msg=k)
