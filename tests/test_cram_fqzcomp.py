"""fqzcomp quality codec tests (formats/cram_fqzcomp.py).

Round-trips drive the decoder through the encoder's feature matrix;
hand-assembled streams (built from the module's own primitives,
mirroring the spec's stream grammar) cover the decode-only features the
default encoder never emits (multi-param + selector, dedup, reverse).
Corrupt streams must fail loudly, never return wrong bytes silently.
"""
import random
import struct

import pytest

from hadoop_bam_tpu.formats.cram_fqzcomp import (
    FQZ_VERS, GFLAG_DO_REV, GFLAG_HAVE_STAB, GFLAG_MULTI_PARAM,
    PFLAG_DO_DEDUP, PFLAG_DO_LEN, PFLAG_DO_SEL, FqzError, FqzParam,
    RangeDecoder, RangeEncoder, SimpleModel, _Models, _encode_length,
    _read_array, _store_array, _update_ctx, _write_param, fqz_decode,
    fqz_encode,
)


def _mkquals(n_recs, lens, seed=1, alphabet=(2, 11, 25, 37, 40)):
    rng = random.Random(seed)
    quals = bytearray()
    out_lens = []
    for i in range(n_recs):
        ln = lens[i % len(lens)]
        out_lens.append(ln)
        prev = rng.choice(alphabet)
        for _ in range(ln):
            # quality-like data: sticky with occasional jumps
            if rng.random() < 0.8:
                q = prev
            else:
                q = rng.choice(alphabet)
            quals.append(q)
            prev = q
    return bytes(quals), out_lens


# ---------------------------------------------------------------------------
# range coder + model primitives
# ---------------------------------------------------------------------------

def test_range_coder_roundtrip():
    rng = random.Random(3)
    syms = [rng.randrange(64) for _ in range(5000)]
    enc_model = SimpleModel(64)
    rc = RangeEncoder()
    for s in syms:
        enc_model.encode(rc, s)
    comp = rc.finish()
    dec_model = SimpleModel(64)
    rd = RangeDecoder(comp)
    assert [dec_model.decode(rd) for _ in syms] == syms


def test_model_adaptation_compresses_skew():
    """A heavily skewed stream must compress well below 1 byte/symbol —
    evidence the adaptive frequencies actually adapt."""
    syms = [0] * 9000 + [1] * 100
    random.Random(5).shuffle(syms)
    m = SimpleModel(2)
    rc = RangeEncoder()
    for s in syms:
        m.encode(rc, s)
    comp = rc.finish()
    assert len(comp) < len(syms) / 8


def test_store_read_array_roundtrip():
    cases = [
        [0] * 256,
        list(range(256)),
        [min(15, i >> 4) for i in range(256)],
        [min(15, i >> 6) for i in range(1024)],
        [0] * 300 + [5] * 724,          # value jump -> zero-length runs
    ]
    for a in cases:
        raw = _store_array(a)
        got, p = _read_array(raw, 0, len(a))
        assert got == a and p == len(raw)


# ---------------------------------------------------------------------------
# encoder-driven round-trips
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lens", [[151], [151, 151], [100, 151, 75]])
def test_fqz_roundtrip(lens):
    quals, out_lens = _mkquals(40, lens, seed=7)
    comp = fqz_encode(quals, out_lens)
    assert fqz_decode(comp, len(quals)) == quals
    # it should actually compress quality-like data
    assert len(comp) < len(quals)


def test_fqz_roundtrip_dense_alphabet():
    """>16 distinct values: no qmap, raw symbol domain."""
    quals, lens = _mkquals(30, [120], seed=9,
                           alphabet=tuple(range(2, 42)))
    comp = fqz_encode(quals, lens)
    assert fqz_decode(comp, len(quals)) == quals


def test_fqz_roundtrip_single_record():
    quals = bytes([30] * 500)
    comp = fqz_encode(quals, [500])
    assert fqz_decode(comp, 500) == quals


def test_fqz_encode_validates():
    with pytest.raises(FqzError):
        fqz_encode(b"\x01\x02", [3])
    with pytest.raises(FqzError):
        fqz_encode(b"\x01\x02", [2, 0])


# ---------------------------------------------------------------------------
# hand-assembled streams: decode-only features
# ---------------------------------------------------------------------------

def _simple_param(pflags=0, context=0, max_sym=40):
    pm = FqzParam()
    pm.pflags = pflags
    pm.context = context
    pm.max_sym = max_sym
    pm.qbits, pm.qshift, pm.qloc = 9, 3, 0
    pm.qmask = (1 << pm.qbits) - 1
    pm.qtab = [min(v, 7) for v in range(256)]
    pm.pflags |= 128                       # HAVE_QTAB
    return pm


_encode_lengths = _encode_length   # the module's real length encoder


def test_fqz_decode_multi_param_selector():
    """Two parameter sets + selector table, records alternating between
    them — the stream grammar the single-param encoder never emits."""
    recs = [bytes([10, 10, 12, 12, 10]), bytes([30, 31, 30, 31, 30]),
            bytes([10, 12, 10, 12, 10]), bytes([31, 31, 30, 30, 31])]
    pms = [_simple_param(pflags=PFLAG_DO_SEL | PFLAG_DO_LEN),
           _simple_param(pflags=PFLAG_DO_SEL | PFLAG_DO_LEN, context=1234)]
    for pm in pms:
        pm.sloc = 14
    stab = [0, 1] + [1] * 254
    head = bytearray([FQZ_VERS, GFLAG_MULTI_PARAM | GFLAG_HAVE_STAB, 2, 1])
    head += _store_array(stab)
    for pm in pms:
        head += _write_param(pm)
    models = _Models(41, 1)
    rc = RangeEncoder()
    for r, rec in enumerate(recs):
        s = r % 2
        models.sel.encode(rc, s)
        pm = pms[s]
        _encode_lengths(models, rc, len(rec))
        state = {"qctx": 0, "p": len(rec), "delta": 0, "prevq": 0, "s": s}
        ctx = (pm.context + (s << pm.sloc)) & 0xFFFF
        for v in rec:
            models.qual_model(ctx).encode(rc, v)
            ctx = _update_ctx(pm, state, v)
    comp = bytes(head) + rc.finish()
    assert fqz_decode(comp, sum(map(len, recs))) == b"".join(recs)


def test_fqz_decode_dedup():
    """PFLAG_DO_DEDUP: a dup=1 record copies the previous record."""
    rec = bytes([20, 21, 20, 22, 20, 20])
    pm = _simple_param(pflags=PFLAG_DO_DEDUP)
    head = bytearray([FQZ_VERS, 0]) + _write_param(pm)
    models = _Models(41, 0)
    rc = RangeEncoder()
    # record 1: lengths encoded once (fixed length), dup=0, then bases
    _encode_lengths(models, rc, len(rec))
    models.dup.encode(rc, 0)
    state = {"qctx": 0, "p": len(rec), "delta": 0, "prevq": 0, "s": 0}
    ctx = pm.context
    for v in rec:
        models.qual_model(ctx).encode(rc, v)
        ctx = _update_ctx(pm, state, v)
    # record 2: dup=1 -> no bases in the stream
    models.dup.encode(rc, 1)
    comp = bytes(head) + rc.finish()
    assert fqz_decode(comp, 2 * len(rec)) == rec + rec


def test_fqz_decode_reverse_flag():
    """GFLAG_DO_REV: flagged records come out reversed."""
    rec = bytes([5, 6, 7, 8, 9, 10])
    pm = _simple_param()
    head = bytearray([FQZ_VERS, GFLAG_DO_REV]) + _write_param(pm)
    models = _Models(41, 0)
    rc = RangeEncoder()
    for flag in (0, 1):
        if flag == 0:
            _encode_lengths(models, rc, len(rec))   # first record only
        models.rev.encode(rc, flag)
        state = {"qctx": 0, "p": len(rec), "delta": 0, "prevq": 0, "s": 0}
        ctx = pm.context
        for v in rec:
            models.qual_model(ctx).encode(rc, v)
            ctx = _update_ctx(pm, state, v)
    comp = bytes(head) + rc.finish()
    assert fqz_decode(comp, 2 * len(rec)) == rec + rec[::-1]


# ---------------------------------------------------------------------------
# corrupt inputs fail loudly
# ---------------------------------------------------------------------------

def test_fqz_corrupt_inputs_raise():
    quals, lens = _mkquals(10, [50], seed=11)
    comp = bytearray(fqz_encode(quals, lens))
    with pytest.raises(FqzError):
        fqz_decode(b"", 10)
    with pytest.raises(FqzError):
        fqz_decode(b"\x04\x00", 10)          # wrong version
    with pytest.raises(FqzError):
        fqz_decode(bytes(comp[:8]), len(quals))   # truncated header
    # wrong out_size: the decoder must not fabricate a record
    with pytest.raises(FqzError):
        fqz_decode(bytes(comp), len(quals) + 1)


# ---------------------------------------------------------------------------
# wired through the CRAM block layer (method 7)
# ---------------------------------------------------------------------------

def test_block_method_dispatch():
    from hadoop_bam_tpu.formats.cram import FQZCOMP, decompress_block_payload
    quals, lens = _mkquals(20, [151], seed=13)
    comp = fqz_encode(quals, lens)
    assert decompress_block_payload(FQZCOMP, comp, len(quals)) == quals


def test_fqz_byteflip_fuzz_never_escapes_fqzerror():
    """Every single-bit corruption either still yields out_size bytes
    (wrong data is fine — range coders can absorb flips) or raises
    FqzError; bare IndexError/struct.error must never escape."""
    quals, lens = _mkquals(10, [80], seed=19)
    comp = bytearray(fqz_encode(quals, lens))
    rng = random.Random(23)
    for _ in range(300):
        pos = rng.randrange(len(comp))
        bit = 1 << rng.randrange(8)
        comp[pos] ^= bit
        try:
            out = fqz_decode(bytes(comp), len(quals))
            assert len(out) == len(quals)
        except FqzError:
            pass
        comp[pos] ^= bit


def test_cram31_file_roundtrip_fqzcomp_quals(tmp_path, monkeypatch):
    """HBAM_CRAM31_QUAL=fqzcomp routes the QS series of a 3.1 file
    through method 7; the file must read back record-identical (and the
    blocks must really be fqzcomp, not a silent rans fallback)."""
    from fixtures import make_header, make_records
    from hadoop_bam_tpu.formats.cram import (
        FQZCOMP, ContainerHeader, FileDefinition, parse_raw_block,
    )
    from hadoop_bam_tpu.formats.cramio import CramWriter, read_cram

    monkeypatch.setenv("HBAM_CRAM31_QUAL", "fqzcomp")
    header = make_header()
    recs = make_records(header, 200, seed=17)
    path = str(tmp_path / "fqz31.cram")
    with CramWriter(path, header, records_per_container=50,
                    version=(3, 1)) as w:
        w.write_records(recs)

    buf = open(path, "rb").read()
    pos = FileDefinition.SIZE
    methods = set()
    while pos < len(buf):
        hdr, pos = ContainerHeader.from_buffer(buf, pos)
        end = pos + hdr.length
        while pos < end:
            raw, pos = parse_raw_block(buf, pos)
            methods.add(raw.method)
    assert FQZCOMP in methods

    _, out = read_cram(path)
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_cram31_qual_knob_validates(monkeypatch):
    from fixtures import make_header, make_records
    from hadoop_bam_tpu.formats.cramio import CramWriter

    monkeypatch.setenv("HBAM_CRAM31_QUAL", "zstd")
    header = make_header()
    with pytest.raises(ValueError, match="HBAM_CRAM31_QUAL"):
        import io
        with CramWriter(io.BytesIO(), header, version=(3, 1)) as w:
            w.write_records(make_records(header, 5, seed=1))


def test_arith_now_decodes_and_fails_loudly_on_garbage():
    """Method 6 no longer raises 'not supported': valid streams decode
    (tests/test_cram_arith.py) and garbage fails with the normalized
    codec error instead of silently wrong bytes."""
    from hadoop_bam_tpu.formats.cram import ARITH, decompress_block_payload
    from hadoop_bam_tpu.formats.cram_arith import arith_encode
    from hadoop_bam_tpu.formats.cram_codecs import RansError

    assert decompress_block_payload(ARITH, arith_encode(b"hello"), 5) \
        == b"hello"
    with pytest.raises(RansError):
        decompress_block_payload(ARITH, b"\x00\x01", 4)


def test_desync_tripwire_end_to_end(tmp_path, monkeypatch):
    """fqzcomp blocks carry the codec's own per-record lengths up to the
    slice decoder, which cross-checks them against the RL series: a
    clean file reads silently; a mismatch raises CRAMError instead of
    returning silently wrong qualities (ADVICE r4 medium)."""
    import io

    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.cram import CRAMError, read_container, FileDefinition
    from hadoop_bam_tpu.formats.cramio import (
        CramWriter, iter_container_slices, read_cram,
    )
    from hadoop_bam_tpu.formats.cram_columns import decode_slice_columns
    from hadoop_bam_tpu.formats.cram_decode import decode_slice_records
    from hadoop_bam_tpu.formats.sam import SamRecord

    monkeypatch.setenv("HBAM_CRAM31_QUAL", "fqzcomp")
    hdr = SAMHeader.from_sam_text(
        "@HD\tVN:1.6\tSO:coordinate\n@SQ\tSN:c1\tLN:100000\n")
    recs = [SamRecord(qname=f"r{i}", flag=0, rname="c1", pos=1 + 3 * i,
                      mapq=60, cigar="15M", rnext="*", pnext=0, tlen=0,
                      seq="ACGTACGTACGTACG",
                      qual="".join(chr(33 + (i + j) % 40)
                                   for j in range(15)))
            for i in range(120)]
    sink = io.BytesIO()
    with CramWriter(sink, hdr, version=(3, 1)) as w:
        w.write_records(recs)
    data = sink.getvalue()

    # clean read: tripwire stays silent
    _, got = read_cram(data)
    assert [r.qual for r in got] == [r.qual for r in recs]

    # a desynced codec (simulated: lengths disagreeing with RL) raises
    # on BOTH decode paths; the first container is the header container
    pos = FileDefinition.SIZE
    cont, pos = read_container(data, pos)
    cont, pos = read_container(data, pos)
    slices = list(iter_container_slices(cont))
    assert slices, "no data slices found"
    comp, sh, core, ext, codec_lens = slices[0]
    assert codec_lens, "fqzcomp block should carry rec lens"
    bad = {cid: [l + 1 for l in lens] for cid, lens in codec_lens.items()}
    with pytest.raises(CRAMError, match="desync"):
        decode_slice_records(comp, sh, core, dict(ext), hdr.ref_names,
                             None, codec_rec_lens=bad)
    with pytest.raises(CRAMError, match="desync"):
        decode_slice_columns(comp, sh, core, dict(ext), hdr.ref_names,
                             None, codec_rec_lens=bad)
