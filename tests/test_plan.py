"""Plan/execute layer tests (hadoop_bam_tpu/plan/).

The load-bearing pins:

- **Byte/value identity per rewired driver**: every driver that became
  a thin plan builder (flagstat, seq_stats, variant_stats, query-engine
  chunk decode, cohort tensor_batches) produces output identical to the
  pre-refactor direct path — the inline mesh-feed impls it now wraps.
- **Plane selection in ONE function**: ``select_plane`` is the single
  predicate table; the gate matrix (intervals x skip_bad_spans x
  backend x native-missing x op DAG x breaker) is pinned combination
  by combination, including the rejection reasons ``hbam explain``
  prints.
- **Digest stability**: the IR serialization is canonical — same plan,
  same digest across processes; any field change moves it; the format
  matches ``jobs.journal.plan_digest`` (24 hex chars) so the two can
  share journal headers.
"""
import dataclasses
import json
import re

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.plan import builders
from hadoop_bam_tpu.plan.executor import select_plane
from hadoop_bam_tpu.plan.ir import (
    PlanIR, SinkIR, SourceIR, SpansIR, op_node,
)
from tests.fixtures import make_header, make_records

pytestmark = pytest.mark.plan


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    d = tmp_path_factory.mktemp("plan")
    header = make_header()
    recs = make_records(header, 500, seed=11)
    path = str(d / "plan.bam")
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path, header, recs


# ---------------------------------------------------------------------------
# IR digest
# ---------------------------------------------------------------------------

def test_digest_stable_and_plan_digest_compatible(bam):
    path, _, _ = bam
    a = builders.flagstat_plan(path)
    b = builders.flagstat_plan(path)
    assert a == b
    assert a.digest() == b.digest()
    # the jobs.journal.plan_digest format: 24 lowercase hex chars
    assert re.fullmatch(r"[0-9a-f]{24}", a.digest())
    # any field change moves the digest
    other = builders.flagstat_plan(path + ".other")
    assert other.digest() != a.digest()
    cfg = dataclasses.replace(DEFAULT_CONFIG, bam_intervals="chr1")
    assert builders.flagstat_plan(path, cfg).digest() != a.digest()
    # and the doc round-trips through canonical JSON
    doc = json.loads(json.dumps(a.to_doc(), sort_keys=True))
    assert doc["source"]["fmt"] == "bam"
    assert doc["sink"]["kind"] == "flagstat"
    assert [o["op"] for o in doc["ops"]] == ["project", "flagstat_reduce"]


def test_pinned_spans_and_param_normalization():
    s = SpansIR.pin([("f.bam", 7, 99)])
    assert s.mode == "pinned" and s.pinned == (("f.bam", 7, 99),)
    assert "pinned" in s.summary()
    # list and tuple params digest identically
    assert op_node("x", cols=["a", "b"]) == op_node("x", cols=("a", "b"))
    with pytest.raises(TypeError):
        op_node("x", bad=object())
    plan = PlanIR(SourceIR("f.bam", "bam", role="chunk"), s,
                  (op_node("chunk_decode"),), SinkIR.of("chunk_columns"))
    assert plan.to_doc()["spans"]["pinned"][0][1:] == [7, 99]


# ---------------------------------------------------------------------------
# plane selection: the gate matrix
# ---------------------------------------------------------------------------

_FLAG_SRC = SourceIR("x.bam", "bam")
_FLAG_OPS = (op_node("project"), op_node("flagstat_reduce"))
_PAYLOAD_OPS = (op_node("payload_pack"), op_node("seq_stats_reduce"))


def _cfg(**kw):
    return dataclasses.replace(HBamConfig(), **kw)


def _rejected(decision):
    return dict(decision.rejected)


def test_select_native_clean_path():
    from hadoop_bam_tpu.ops.inflate import fused_available
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="native"))
    assert d.plane == "native" and d.backend == "native"
    assert d.host_backend == "native"
    assert d.use_fused == fused_available()
    assert d.stream_fused == fused_available()
    assert "device" in _rejected(d)


def test_select_zlib_pins_portable_plane():
    d = select_plane(_FLAG_SRC, _FLAG_OPS, _cfg(inflate_backend="zlib"))
    assert d.plane == "zlib"
    assert not d.use_fused and not d.stream_fused
    rej = _rejected(d)
    assert "native" in rej and "fused" in rej


def test_select_device_full_gate_pass():
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="device"))
    assert d.plane == "device"
    assert d.host_backend == "auto"      # host fallback rides auto


def test_select_device_rejected_by_intervals():
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="device"), intervals=[()])
    assert d.plane == "native"
    assert "whole-span offsets" in _rejected(d)["device"]
    # fused streaming is gated by the same condition
    assert not d.stream_fused


def test_select_device_rejected_by_skip_bad_spans():
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="device", skip_bad_spans=True))
    assert d.plane == "native"
    assert "quarantine" in _rejected(d)["device"]
    assert not d.stream_fused


def test_select_device_rejected_for_non_device_dag():
    # the query chunk-columns DAG (chunk_decode alone, host predicate
    # columns) has no device route
    d = select_plane(SourceIR("x.bam", "bam", role="chunk"),
                     (op_node("chunk_decode"),),
                     _cfg(inflate_backend="device"))
    assert d.plane == "native"
    assert "op DAG" in _rejected(d)["device"]
    # but the non-device planes keep fused streaming when eligible
    from hadoop_bam_tpu.ops.inflate import fused_available
    assert d.stream_fused == fused_available()


def test_select_device_families_round21():
    """The round-21 families pass the device gate: BAM payload, BCF
    variant, BAM serve-tile — and their near-misses reject with the
    capability reason."""
    cfg = _cfg(inflate_backend="device")
    # payload (seq_stats) on BAM
    assert select_plane(_FLAG_SRC, _PAYLOAD_OPS, cfg).plane == "device"
    # variant on BCF
    vops = (op_node("variant_pack"), op_node("variant_stats_reduce"))
    assert select_plane(SourceIR("x.bcf", "bcf"), vops,
                        cfg).plane == "device"
    # serve-tile on BAM (chunk role)
    sops = (op_node("chunk_decode"), op_node("tile_build"))
    assert select_plane(SourceIR("x.bam", "bam", role="chunk"), sops,
                        cfg).plane == "device"
    # text VCF deliberately has NO device row: the token feed needs the
    # BGZF container and the BCF binary layout
    d = select_plane(SourceIR("x.vcf", "vcf"), vops, cfg)
    assert d.plane == "native"
    assert "op DAG" in _rejected(d)["device"]
    # a CRAM source can never ride the BGZF token feed either
    d2 = select_plane(SourceIR("x.cram", "cram"), _PAYLOAD_OPS, cfg)
    assert "op DAG" in _rejected(d2)["device"]


@pytest.mark.parametrize("src,ops", [
    (SourceIR("x.bam", "bam"),
     (op_node("payload_pack"), op_node("seq_stats_reduce"))),
    (SourceIR("x.bcf", "bcf"),
     (op_node("variant_pack"), op_node("variant_stats_reduce"))),
    (SourceIR("x.bam", "bam", role="chunk"),
     (op_node("chunk_decode"), op_node("tile_build"))),
])
def test_select_round21_families_share_the_gate_matrix(src, ops):
    """Every new family rejects through the SAME gates as flagstat:
    intervals, skip_bad_spans, open breaker — reason strings included
    (the `hbam explain` surface)."""
    d = select_plane(src, ops, _cfg(inflate_backend="device"),
                     intervals=[()])
    assert d.plane != "device"
    assert "whole-span offsets" in _rejected(d)["device"]

    d = select_plane(src, ops, _cfg(inflate_backend="device",
                                    skip_bad_spans=True))
    assert d.plane != "device"
    assert "quarantine" in _rejected(d)["device"]

    class OpenLadder:
        def allow_plane(self, plane):
            return False

    d = select_plane(src, ops, _cfg(inflate_backend="device"),
                     ladder=OpenLadder())
    assert d.plane != "device"
    assert "breaker" in _rejected(d)["device"]

    d = select_plane(src, ops, _cfg(inflate_backend="native"))
    assert d.plane == "native"
    assert "inflate_backend" in _rejected(d)["device"]


def test_select_device_rejected_by_open_breaker():
    class OpenLadder:
        probes = 0

        def allow_plane(self, plane):
            self.probes += 1
            return False

    lad = OpenLadder()
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="device"), ladder=lad)
    assert d.plane == "native"
    assert "breaker" in _rejected(d)["device"]
    assert lad.probes == 1

    # the probe slot is consumed ONLY when every other gate passed
    lad2 = OpenLadder()
    select_plane(_FLAG_SRC, _FLAG_OPS,
                 _cfg(inflate_backend="device", skip_bad_spans=True),
                 ladder=lad2)
    assert lad2.probes == 0


def test_select_native_missing_disables_fused(monkeypatch):
    from hadoop_bam_tpu.ops import inflate as inflate_ops
    monkeypatch.setattr(inflate_ops, "fused_available", lambda: False)
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="native"))
    assert d.plane == "native"
    assert not d.use_fused and not d.stream_fused
    assert "unavailable" in _rejected(d)["fused"]
    # explicit device WITHOUT the native tokenizer still selects device:
    # the runner raises PlanError (configuration fault), selection must
    # not silently reroute a user's explicit plane choice
    d2 = select_plane(_FLAG_SRC, _FLAG_OPS,
                      _cfg(inflate_backend="device"))
    assert d2.plane == "device"


def test_select_fused_off_by_config():
    d = select_plane(_FLAG_SRC, _FLAG_OPS,
                     _cfg(inflate_backend="native",
                          use_fused_decode=False))
    assert not d.use_fused and not d.stream_fused
    assert "use_fused_decode" in _rejected(d)["fused"]


def test_plane_report_families():
    from hadoop_bam_tpu.plan.executor import plane_report
    rep = plane_report(_cfg(inflate_backend="native"))
    assert set(rep) == {"flagstat", "payload", "variant", "serve"}
    for fam in rep.values():
        assert fam["plane"] in ("device", "native", "zlib")
        assert isinstance(fam["rejected"], dict)
    # under the device backend every family routes device
    dev = plane_report(_cfg(inflate_backend="device"))
    assert all(f["plane"] == "device" for f in dev.values())


# ---------------------------------------------------------------------------
# byte/value identity: plan path vs the pre-refactor direct path
# ---------------------------------------------------------------------------

def test_flagstat_plan_path_identical(bam):
    from hadoop_bam_tpu.parallel.pipeline import (
        _flagstat_impl, flagstat_file,
    )
    path, header, _ = bam
    via_plan = flagstat_file(path, header=header)
    inline = _flagstat_impl(path, header=header)
    assert via_plan == inline
    assert via_plan["total"] == 500


def test_seq_stats_plan_path_identical(bam):
    from hadoop_bam_tpu.parallel.pipeline import (
        _seq_stats_impl, seq_stats_file,
    )
    path, header, _ = bam
    via_plan = seq_stats_file(path, header=header)
    inline = _seq_stats_impl(path, header=header)
    assert via_plan["n_reads"] == inline["n_reads"] > 0
    assert via_plan["mean_gc"] == inline["mean_gc"]
    assert via_plan["mean_qual"] == inline["mean_qual"]
    assert np.array_equal(via_plan["base_hist"], inline["base_hist"])


def test_variant_stats_plan_path_identical(tmp_path):
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    from hadoop_bam_tpu.parallel.variant_pipeline import (
        _variant_stats_impl, variant_stats_file,
    )
    hdr = ("##fileformat=VCFv4.2\n"
           "##contig=<ID=c1,length=100000>\n"
           '##FORMAT=<ID=GT,Number=1,Type=String,Description="G">\n'
           "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts0\n")
    path = str(tmp_path / "v.vcf")
    with open(path, "w") as f:
        f.write(hdr)
        for i in range(300):
            gt = ("0/1", "1/1", "0/0", "./.")[i % 4]
            f.write(f"c1\t{100 + i}\t.\tA\tG\t30\tPASS\t.\tGT\t{gt}\n")
    via_plan = variant_stats_file(path)
    inline = _variant_stats_impl(path)
    for k in ("n_variants", "n_snp", "n_pass", "mean_af", "n_af"):
        assert via_plan[k] == inline[k]
    assert via_plan["n_variants"] == 300
    assert np.array_equal(via_plan["sample_callrate"],
                          inline["sample_callrate"])


def test_query_chunk_plan_path_identical(bam, tmp_path):
    from hadoop_bam_tpu.parallel.pipeline import decode_with_retry
    from hadoop_bam_tpu.query.engine import QueryEngine
    from hadoop_bam_tpu.split.spans import FileVirtualSpan
    from hadoop_bam_tpu.tools.cli import main
    path, header, _ = bam
    assert main(["index", "--flavor", "bai", path]) == 0
    engine = QueryEngine(config=DEFAULT_CONFIG)
    meta = engine._file_meta(path)
    _iv, ranges = engine._resolve(meta, "chr1")
    chunks = engine._coalesce(ranges, meta.kind)
    assert chunks
    s, e = chunks[0]
    via_plan, cost = engine._compute_chunk(meta, s, e)
    direct = decode_with_retry(
        lambda sp: engine._decode_chunk(meta, sp),
        FileVirtualSpan(meta.path, s, e), engine.config)
    assert cost == int(direct["nbytes"])
    assert via_plan["n"] == direct["n"] > 0
    for k in ("rid", "pos1", "end1"):
        assert np.array_equal(via_plan[k], direct[k])
    assert np.array_equal(via_plan["batch"].data, direct["batch"].data)


def test_cohort_plan_path_identical(tmp_path):
    """tensor_batches (plan path, executor-wired feed) vs an inline
    replica of the pre-refactor wiring (variant_feed + device_put)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.cohort import CohortDataset
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_feed

    hdr = ("##fileformat=VCFv4.2\n"
           "##contig=<ID=c1,length=100000>\n"
           '##FORMAT=<ID=GT,Number=1,Type=String,Description="G">\n')

    def write_sample(name, offset):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            f.write(hdr + "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\t"
                          f"INFO\tFORMAT\t{name}\n")
            for i in range(60):
                gt = ("0/1", "1/1", "0/0")[(i + offset) % 3]
                f.write(f"c1\t{50 + 3 * i}\t.\tA\tT\t9\tPASS\t.\t"
                        f"GT\t{gt}\n")
        return p

    paths = [write_sample(f"s{i}.vcf", i) for i in range(3)]

    ds = CohortDataset(paths)
    got = list(ds.tensor_batches())

    ds2 = CohortDataset(paths)
    mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    sharding = NamedSharding(mesh, P("data"))
    keys, fp, tuples = variant_feed(ds2.site_chunks(), n_dev,
                                    ds2.geometry.tile_records,
                                    ds2.config, fixed_shape=True,
                                    fmt="cohort")

    def emit(arrays, counts):
        out = {k: jax.device_put(a, sharding)
               for k, a in zip(keys, arrays)}
        out["n_records"] = jax.device_put(counts, sharding)
        return out

    want = list(fp.stream(tuples, emit))
    assert len(got) == len(want) > 0
    for g, w in zip(got, want):
        assert set(g) == set(w)
        for k in g:
            ga, wa = np.asarray(g[k]), np.asarray(w[k])
            assert np.array_equal(ga, wa, equal_nan=(ga.dtype.kind
                                                     == "f"))


def test_cohort_tensor_batches_stays_lazy(tmp_path):
    """Building the batch iterator must start no join and open no
    journal (the executor runner is a generator)."""
    from hadoop_bam_tpu.cohort import CohortDataset
    hdr = ("##fileformat=VCFv4.2\n"
           "##contig=<ID=c1,length=1000>\n"
           '##FORMAT=<ID=GT,Number=1,Type=String,Description="G">\n')
    p = str(tmp_path / "s.vcf")
    with open(p, "w") as f:
        f.write(hdr + "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\t"
                      "FORMAT\ts\n")
        f.write("c1\t10\t.\tA\tT\t9\tPASS\t.\tGT\t0/1\n")
    jp = str(tmp_path / "j.hbam-journal")
    ds = CohortDataset([p], journal_path=jp)
    it = ds.tensor_batches()          # built, never iterated
    import os
    assert not os.path.exists(jp)
    assert not ds._journal_live
    del it
    assert len(list(ds.tensor_batches())) >= 1   # still usable after


# ---------------------------------------------------------------------------
# journal seam + executor surface
# ---------------------------------------------------------------------------

def test_plan_journal_params_carries_digest(bam):
    from hadoop_bam_tpu.jobs.runner import plan_journal_params
    path, _, _ = bam
    plan = builders.flagstat_plan(path)
    params = plan_journal_params(plan, {"input": path})
    assert params["plan_digest"] == plan.digest()
    assert params["input"] == path


def test_execute_counts_and_rejects_unknown_sink(bam):
    from hadoop_bam_tpu.plan.executor import execute
    from hadoop_bam_tpu.utils.errors import PlanError
    from hadoop_bam_tpu.utils.metrics import METRICS, MetricsContext
    path, header, _ = bam
    bad = PlanIR(SourceIR(path, "bam"), SpansIR.auto(),
                 (op_node("nope"),), SinkIR.of("nope"))
    with pytest.raises(PlanError):
        execute(bad)
    with MetricsContext():
        from hadoop_bam_tpu.parallel.pipeline import flagstat_file
        flagstat_file(path, header=header)
        snap = METRICS.snapshot()
    assert snap["counters"]["plan.executions"] == 1


def test_explain_cli_text_and_json(bam, capsys):
    from hadoop_bam_tpu.tools.cli import main
    path, _, _ = bam
    assert main(["explain", "flagstat", path]) == 0
    out = capsys.readouterr().out
    assert "plane   " in out and "sink    flagstat" in out

    assert main(["explain", "flagstat", path, "--json",
                 "--inflate-backend", "device",
                 "--skip-bad-spans"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["digest"] == builders.flagstat_plan(path).digest()
    assert doc["decision"]["plane"] == "native"
    assert "quarantine" in doc["decision"]["rejected"]["device"]


def test_explain_cli_query_pins_chunks(bam, capsys):
    from hadoop_bam_tpu.tools.cli import main
    path, _, _ = bam
    main(["index", "--flavor", "bai", path])
    capsys.readouterr()               # drain the index verb's output
    assert main(["explain", "query", path, "--region", "chr1",
                 "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["plan"]["source"]["role"] == "chunk"
    assert len(doc["plan"]["spans"]["pinned"]) >= 1
    ops = [o["op"] for o in doc["plan"]["ops"]]
    assert ops == ["chunk_decode", "overlap_filter"]


def test_explain_cli_cohort(tmp_path, capsys):
    from hadoop_bam_tpu.tools.cli import main
    hdr = ("##fileformat=VCFv4.2\n"
           "##contig=<ID=c1,length=1000>\n"
           '##FORMAT=<ID=GT,Number=1,Type=String,Description="G">\n')
    p = tmp_path / "s.vcf"
    p.write_text(hdr + "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\t"
                       "FORMAT\ts\nc1\t10\t.\tA\tT\t9\tPASS\t.\tGT\t"
                       "0/1\n")
    man = tmp_path / "cohort.json"
    man.write_text(json.dumps(
        {"samples": [{"id": "s", "path": str(p)}]}))
    assert main(["explain", "cohort", str(man), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["plan"]["source"]["role"] == "join"
    assert doc["plan"]["sink"]["kind"] == "tensor_batches"
    assert doc["plan"]["ops"][0]["op"] == "kway_join"
    assert doc["plan"]["ops"][0]["params"]["samples"] == 1
