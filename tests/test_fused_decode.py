"""Fused single-pass span decode tests (``pytest -m fused``).

The fused native path (``ops/inflate.py FusedSpanDecode`` over
``hbam_fused_*`` in native/hbam_native.cpp) collapses the two-pass hot
path's inflate -> walk -> CRC sweeps into one streamed pass.  The
two-pass path stays in-tree as the byte-identity ORACLE — every test
here pins the fused outputs (and the fused failure modes) to it:

- randomized byte-identity across split offsets, all three pack modes;
- truncation / byte-flip / CRC-mismatch fuzz raising the same error
  classes on both paths;
- chaos injection through the PR-1 ``FaultInjectingByteSource`` (the
  fetch stays inside the retry boundary even when chunks stream);
- chunk-streaming order and early-cancellation (native workers must
  join, never outlive the span's buffers).
"""
import dataclasses
import random

import numpy as np
import pytest

from hadoop_bam_tpu.config import HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.ops import inflate as inflate_ops
from hadoop_bam_tpu.ops.unpack_bam import (
    FLAGSTAT_PROJECTION, projection_ranges, projection_row_bytes,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans
from hadoop_bam_tpu.split.spans import FileVirtualSpan
from hadoop_bam_tpu.utils import native
from hadoop_bam_tpu.utils.errors import CORRUPT, classify_error

from fixtures import make_header, make_records

pytestmark = [
    pytest.mark.fused,
    pytest.mark.skipif(not inflate_ops.fused_available(),
                       reason="native fused decode unavailable"),
]

SEL = projection_ranges(FLAGSTAT_PROJECTION)
ROW_W = projection_row_bytes(FLAGSTAT_PROJECTION)
CFG_ON = HBamConfig(backend="cpu")
CFG_OFF = dataclasses.replace(CFG_ON, use_fused_decode=False)


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fused") / "f.bam")
    header = make_header()
    records = make_records(header, 4000, seed=21)
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    return path, header, records


def _span_setup(path):
    raw = open(path, "rb").read()
    table = inflate_ops.block_table(raw)
    data, ubase = inflate_ops.inflate_span(raw, table)
    _, after = SAMHeader.from_bam_bytes(data.tobytes())
    return raw, table, data, after


# ---------------------------------------------------------------------------
# byte-identity vs the two-pass oracle
# ---------------------------------------------------------------------------

def test_offsets_mode_matches_two_pass(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    offs, tail = inflate_ops.walk_records(data, start=after)
    dec = inflate_ops.FusedSpanDecode(raw, table, start=after,
                                      chunk_blocks=2)
    n, ftail = dec.run()
    assert np.array_equal(dec.data, data)
    assert np.array_equal(dec.offsets[:n], offs)
    assert ftail == tail


def test_rows_and_payload_modes_match_native_walkers(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    cap = max(16, (data.size - after) // 36 + 1)
    rows, offs, _ = native.walk_bam_packed(data, after, cap, SEL, ROW_W)
    dec = inflate_ops.FusedSpanDecode(raw, table, start=after, mode="rows",
                                      sel=SEL, row_stride=ROW_W,
                                      chunk_blocks=3)
    n, _ = dec.run()
    assert n == rows.shape[0]
    assert np.array_equal(dec.rows[:n], rows)
    assert np.array_equal(dec.offsets[:n], offs)

    pf, sq, ql, _, _ = native.walk_bam_payload(data, after, cap, 160, 96,
                                               160)
    dec2 = inflate_ops.FusedSpanDecode(raw, table, start=after,
                                       mode="payload", max_len=160,
                                       seq_stride=96, qual_stride=160,
                                       chunk_blocks=3)
    n2, _ = dec2.run()
    assert np.array_equal(dec2.prefix[:n2], pf)
    assert np.array_equal(dec2.seq[:n2], sq)
    assert np.array_equal(dec2.qual[:n2], ql)


def test_randomized_split_offsets_byte_identity(bam):
    """Fused vs two-pass across randomized span plans — the full driver
    entry points, both pack modes, voffsets included."""
    from hadoop_bam_tpu.parallel.pipeline import (
        PayloadGeometry, decode_span_payload_host, decode_span_prefix_host,
    )

    path, header, _ = bam
    rng = random.Random(7)
    geom = PayloadGeometry(max_len=120)
    for num_spans in (rng.randint(2, 9), rng.randint(10, 25),
                      rng.randint(26, 60)):
        spans = plan_bam_spans(path, num_spans=num_spans, header=header)
        for s in spans:
            r1, v1 = decode_span_prefix_host(
                path, s, projection=FLAGSTAT_PROJECTION, config=CFG_ON)
            r2, v2 = decode_span_prefix_host(
                path, s, projection=FLAGSTAT_PROJECTION, config=CFG_OFF)
            assert np.array_equal(r1, r2) and np.array_equal(v1, v2)
            p1 = decode_span_payload_host(path, s, geom, want_voffs=True,
                                          config=CFG_ON)
            p2 = decode_span_payload_host(path, s, geom, want_voffs=True,
                                          config=CFG_OFF)
            for a, b in zip(p1, p2):
                assert np.array_equal(a, b)


def test_cut_final_record_falls_back_to_oracle(tmp_path):
    """A span whose last owned record extends past its final inflated
    block (the tail-extension case) must produce oracle-identical rows —
    the fused path detects the cut and reroutes that span."""
    from hadoop_bam_tpu.parallel.pipeline import decode_span_prefix_host

    header = make_header()
    base = str(tmp_path / "hdr.bam")
    with BamWriter(base, header) as w:
        pass
    hdr_bytes = open(base, "rb").read()[:-len(bgzf.EOF_BLOCK)]

    recs = make_records(header, 40, seed=9)
    tmp = str(tmp_path / "tmp.bam")
    with BamWriter(tmp, header) as w:
        for r in recs:
            w.write_sam_record(r)
    from hadoop_bam_tpu.formats.bamio import read_bam
    _, batch = read_bam(tmp)
    payload = b"".join(batch.record_bytes(i) for i in range(40))
    rec_offs = np.cumsum([0] + [len(batch.record_bytes(i))
                                for i in range(40)])[:-1]

    chunk = 100                       # every ~130 B record crosses blocks
    blocks = b"".join(bgzf.deflate_block(payload[i:i + chunk])
                      for i in range(0, len(payload), chunk))
    path = str(tmp_path / "tiny.bam")
    with open(path, "wb") as f:
        f.write(hdr_bytes + blocks + bgzf.EOF_BLOCK)

    raw = open(path, "rb").read()
    coffs = [b.coffset for b in bgzf.scan_blocks(raw)
             if b.coffset >= len(hdr_bytes)]
    # span ends one byte past record 20's start: record 20 is OWNED and
    # extends past the end block's boundary -> fused tail < end_inflated
    u = int(rec_offs[20])
    end_block = coffs[u // chunk]
    span = FileVirtualSpan(path, (len(hdr_bytes) << 16),
                           (end_block << 16) | (u % chunk + 1))
    r1, v1 = decode_span_prefix_host(path, span, config=CFG_ON)
    r2, v2 = decode_span_prefix_host(path, span, config=CFG_OFF)
    assert r1.shape[0] == 21          # records 0..20 owned
    assert np.array_equal(r1, r2) and np.array_equal(v1, v2)

    # whole-file plans over the tiny-block layout stay identical too
    header2 = SAMHeader.from_bam_bytes(
        inflate_ops.inflate_span(raw)[0].tobytes())[0]
    for s in plan_bam_spans(path, num_spans=11, header=header2):
        a, _ = decode_span_prefix_host(path, s, config=CFG_ON)
        b, _ = decode_span_prefix_host(path, s, config=CFG_OFF)
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# corruption fuzz: same error classes on both paths
# ---------------------------------------------------------------------------

def _two_pass_decode(raw, after, check_crc=False):
    table = inflate_ops.block_table(raw)
    data, ubase = inflate_ops.inflate_span(raw, table)
    if check_crc:
        inflate_ops.verify_crcs(raw, table, data, ubase)
    return inflate_ops.walk_records(data, start=after)


def _fused_decode(raw, after, check_crc=False):
    dec = inflate_ops.FusedSpanDecode(raw, start=after,
                                      check_crc=check_crc, chunk_blocks=2)
    n, tail = dec.run()
    return dec.offsets[:n], tail


def test_byte_flip_fuzz_same_errors(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    rng = random.Random(31)
    mismatches = []
    n_corrupt = 0
    for trial in range(25):
        bad = bytearray(raw)
        pos = rng.randrange(len(raw) - len(bgzf.EOF_BLOCK))
        bad[pos] ^= (1 << rng.randrange(8))
        bad = bytes(bad)
        outcomes = []
        for fn in (_two_pass_decode, _fused_decode):
            try:
                offs, tail = fn(bad, after, check_crc=True)
                outcomes.append(("ok", offs.size, tail))
            except Exception as e:  # noqa: BLE001 — the class IS the test
                outcomes.append(("err", isinstance(e, bgzf.BGZFError),
                                 classify_error(e)))
        if outcomes[0] != outcomes[1]:
            mismatches.append((pos, outcomes))
        if outcomes[0][0] == "err":
            n_corrupt += 1
            assert outcomes[0][2] == CORRUPT
    assert not mismatches, mismatches
    assert n_corrupt >= 5    # the fuzz actually hit payloads, not just air


def test_crc_mismatch_only_with_check_crc(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    # flip a footer CRC byte (not the payload): only check_crc sees it
    foot = int(table["cdata_off"][3] + table["cdata_len"][3])
    bad = bytearray(raw)
    bad[foot] ^= 0xFF
    bad = bytes(bad)
    o1, t1 = _two_pass_decode(bad, after, check_crc=False)
    o2, t2 = _fused_decode(bad, after, check_crc=False)
    assert np.array_equal(o1, o2) and t1 == t2
    with pytest.raises(bgzf.BGZFError, match="CRC32 mismatch"):
        _two_pass_decode(bad, after, check_crc=True)
    with pytest.raises(bgzf.BGZFError, match="CRC32 mismatch"):
        _fused_decode(bad, after, check_crc=True)


def test_truncated_tail_matches(bam):
    """Truncation that cuts the final block's payload: both paths raise
    the same BGZF corruption; truncation at a block boundary walks the
    same (shorter) record set."""
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    cut_block = int(table["coffset"][5])
    clean_cut = raw[:cut_block]
    o1, t1 = _two_pass_decode(clean_cut, after)
    o2, t2 = _fused_decode(clean_cut, after)
    assert np.array_equal(o1, o2) and t1 == t2

    ragged = raw[:cut_block + 40]      # mid-header truncation
    for fn in (_two_pass_decode, _fused_decode):
        with pytest.raises(bgzf.BGZFError):
            fn(ragged, after)


def test_malformed_record_chain_same_class(bam):
    """A corrupted block_size field (valid DEFLATE, bad BAM) raises the
    CORRUPT class on both paths."""
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    # re-deflate block containing `after` with a poisoned block_size
    bad_data = bytearray(data.tobytes())
    bad_data[after:after + 4] = (5).to_bytes(4, "little")  # bs < 32
    blk = int(np.searchsorted(
        np.cumsum(table["isize"]), after, side="right"))
    lo = int(np.cumsum(table["isize"])[blk - 1]) if blk else 0
    hi = lo + int(table["isize"][blk])
    reblocked = bgzf.deflate_block(bytes(bad_data[lo:hi]))
    bad_raw = (raw[:int(table["coffset"][blk])] + reblocked
               + raw[int(table["coffset"][blk])
                     + int(bgzf.parse_block_header(
                         raw, int(table["coffset"][blk])).block_size):])
    errs = []
    for fn in (_two_pass_decode, _fused_decode):
        with pytest.raises(ValueError) as ei:
            fn(bad_raw, after)
        errs.append(ei.value)
    assert all(classify_error(e) == CORRUPT for e in errs)


# ---------------------------------------------------------------------------
# chaos injection (PR-1 FaultInjectingByteSource)
# ---------------------------------------------------------------------------

def test_transient_chaos_heals_inside_retry_boundary(bam):
    """Injected transient preads fail the FETCH, which the fused path
    runs eagerly inside decode_with_retry — the streamed chunks never
    see the fault and the result is byte-identical to a clean run."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.utils.metrics import METRICS
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on

    path, header, records = bam
    cfg = dataclasses.replace(CFG_ON, span_retries=3,
                              retry_backoff_base_s=0.0,
                              retry_backoff_max_s=0.0)
    clean = flagstat_file(path, header=header, config=cfg)
    METRICS.reset()
    with chaos_on(path, [FaultSpec(kind="transient", at_read=0, count=2)]):
        chaotic = flagstat_file(path, header=header, config=cfg)
    assert chaotic == clean
    assert clean["total"] == len(records)
    assert METRICS.counters["chaos.injected_faults"] >= 2


def test_bitflip_chaos_quarantines_span(bam):
    """Corrupting chaos + skip_bad_spans: the fused path drops back to
    buffered per-span decode so quarantine stays span-granular."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on

    path, header, records = bam
    size = len(open(path, "rb").read())
    # check_crc: a flipped payload bit may still inflate to "valid" bytes
    # — the folded CRC check makes detection deterministic
    cfg = dataclasses.replace(CFG_ON, skip_bad_spans=True, span_retries=0,
                              check_crc=True)
    # PERSISTENT corruption (budget outlives the demotion ladder's zlib
    # oracle re-read — a small budget heals on the re-read instead,
    # which is the ladder working, not this test's subject)
    with chaos_on(path, [FaultSpec(kind="bitflip",
                                   offset_range=(size // 2, size // 2 + 4),
                                   count=10_000)]):
        out = flagstat_file(path, header=header, config=cfg)
    assert "quarantine" in out
    assert 0 < out["total"] < len(records)


# ---------------------------------------------------------------------------
# chunk streaming: order, knobs, cancellation
# ---------------------------------------------------------------------------

def test_chunk_stream_order_and_coverage(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    dec = inflate_ops.FusedSpanDecode(raw, table, start=after, mode="rows",
                                      sel=SEL, row_stride=ROW_W,
                                      chunk_blocks=1)
    ranges = list(dec.chunks())
    n, _ = dec.finish()
    assert len(ranges) >= 2           # chunk_blocks=1 must actually stream
    prev = 0
    for lo, hi in ranges:             # contiguous, ascending, gap-free
        assert lo == prev and hi > lo
        prev = hi
    assert prev == n
    cap = max(16, (data.size - after) // 36 + 1)
    rows, _, _ = native.walk_bam_packed(data, after, cap, SEL, ROW_W)
    assert np.array_equal(dec.rows[:n], rows)


def test_multithreaded_workers_race_free(bam):
    """Forced 4-worker jobs over 1-block chunks: inflate workers race the
    walk frontier constantly (this host's auto thread count is 1, so the
    contention paths only run when forced).  Results must stay
    deterministic and oracle-identical.  TSan covers the same shape in
    test_native_sanitize.py."""
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    offs, tail = inflate_ops.walk_records(data, start=after)
    for _ in range(6):
        dec = inflate_ops.FusedSpanDecode(raw, table, start=after,
                                          mode="rows", sel=SEL,
                                          row_stride=ROW_W, check_crc=True,
                                          chunk_blocks=1, n_threads=4)
        n, t = dec.run()
        assert n == offs.size and t == tail
        assert np.array_equal(dec.offsets[:n], offs)


def test_chunk_blocks_knob_changes_granularity(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    n_blocks = int(table["isize"].size)
    fine = len(list(inflate_ops.FusedSpanDecode(
        raw, table, start=after, chunk_blocks=1).chunks()))
    coarse = len(list(inflate_ops.FusedSpanDecode(
        raw, table, start=after, chunk_blocks=n_blocks).chunks()))
    assert coarse == 1 and fine > coarse


def test_early_close_joins_native_workers(bam):
    path, _, _ = bam
    raw, table, data, after = _span_setup(path)
    for _ in range(4):                # repeated cancel must never wedge
        dec = inflate_ops.FusedSpanDecode(raw, table, start=after,
                                          chunk_blocks=1)
        g = dec.chunks()
        next(g)
        g.close()                     # abandon mid-stream
        assert dec.n_rows is not None   # joined: counts are final
    # the library stays fully usable after cancels
    o1, t1 = _fused_decode(raw, after)
    o2, t2 = _two_pass_decode(raw, after)
    assert np.array_equal(o1, o2) and t1 == t2


def test_driver_stream_abandoned_midway(bam):
    """A consumer abandoning tensor batches mid-stream (the query/LIMIT
    shape) unwinds the windowed fused decodes without hanging."""
    from hadoop_bam_tpu.api.dataset import open_bam

    path, header, records = bam
    ds = open_bam(path, config=CFG_ON)
    it = ds.tensor_batches()
    first = next(it)
    it.close()
    assert int(np.asarray(first["n_records"]).sum()) > 0


def test_config_knob_plumbing():
    cfg = HBamConfig.from_dict({"hbam.use-fused-decode": "false",
                                "hbam.decode-chunk-blocks": "7"})
    assert cfg.use_fused_decode is False and cfg.decode_chunk_blocks == 7
    from hadoop_bam_tpu.parallel.pipeline import _use_fused
    assert not _use_fused(cfg)
    assert _use_fused(None) == inflate_ops.fused_available()
    assert not _use_fused(HBamConfig(), inflate_backend="zlib")


def test_streamed_corruption_ticks_corrupt_spans(bam, tmp_path):
    """Corruption surfacing from the streamed consumer side must keep
    the pipeline.corrupt_spans counter in step with the buffered and
    two-pass paths (it raises outside decode_with_retry)."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.utils.metrics import METRICS

    path, header, _ = bam
    raw = bytearray(open(path, "rb").read())
    table = inflate_ops.block_table(bytes(raw))
    raw[int(table["cdata_off"][4]) + 9] ^= 0xFF
    bad = str(tmp_path / "bad.bam")
    open(bad, "wb").write(bytes(raw))
    METRICS.reset()
    with pytest.raises(bgzf.BGZFError):
        flagstat_file(bad, header=header, config=CFG_ON)
    assert METRICS.counters["pipeline.corrupt_spans"] >= 1


def test_fused_metrics_taxonomy(bam):
    """The fused sweep reports pipeline.fused_decode (+ the chunk
    histogram and the bam.fused_decode_wall span) instead of the
    two-pass inflate/walk stage pair."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.utils.metrics import METRICS

    path, header, _ = bam
    METRICS.reset()
    flagstat_file(path, header=header, config=CFG_ON)
    snap = METRICS.snapshot()
    assert "pipeline.fused_decode" in snap["timers"]
    assert "pipeline.inflate" not in snap["timers"]
    assert "bam.fused_decode_wall" in snap["wall_timers"]
    assert snap["histograms"]["pipeline.decode_chunk_s"]["count"] > 0
