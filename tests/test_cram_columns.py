"""Parity: the vectorized columnar CRAM slice decoder vs the record path.

The columnar decoder (formats/cram_columns.py) must be byte-identical to
assembling the same columns from decode_slice_records — over encoder-
produced files AND over hand-built slices that exercise the feature codes
our encoder never emits (X substitutions, B/i single bases, q/Q qual
overlays, D/N/P/H with reference fill).

Reference scope: htsjdk CRAM slice decode via hb/CRAMInputFormat.java
(SURVEY.md section 2.3).
"""
import numpy as np
import pytest

from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.cram import write_itf8
from hadoop_bam_tpu.formats.cram_columns import (
    decode_slice_columns, records_to_columns,
)
from hadoop_bam_tpu.formats.cram_decode import (
    ByteArrayLenEncoding, ByteArrayStopEncoding, CF_QUAL_STORED,
    CF_UNKNOWN_BASES, CompressionHeader, CRAMError, ExternalEncoding,
    FastaReferenceSource, HuffmanEncoding, SliceHeader,
    decode_slice_records,
)

HDR = SAMHeader.from_sam_text(
    "@HD\tVN:1.6\tSO:coordinate\n@SQ\tSN:c1\tLN:100000\n"
    "@SQ\tSN:c2\tLN:100000\n")


def _assert_columns_match(cols, recs):
    ref = records_to_columns(recs, want_names=True)
    assert cols is not None
    assert cols["n"] == ref["n"]
    for k in ("bf", "cf", "ref_id", "rl", "pos", "mapq", "read_group",
              "seq_lens", "qual_lens", "name_lens"):
        np.testing.assert_array_equal(cols[k], ref[k], err_msg=k)
    for k in ("seq_cat", "qual_cat", "name_cat"):
        assert cols[k] == ref[k], k


# ---------------------------------------------------------------------------
# hand-built slices: full control over features and layout
# ---------------------------------------------------------------------------

class _SliceBuilder:
    """Serialize records into the encoder-default external layout
    (everything external, arrays ByteArrayLen, names ByteArrayStop) in
    exact record-serial stream order — the order both decoders must
    agree on."""

    INT_SERIES = ("BF", "CF", "RL", "AP", "RG", "TL", "MF", "NS", "NP",
                  "TS", "NF", "MQ", "FN", "FP", "DL", "RS", "PD", "HC")
    BYTE_SERIES = ("FC", "QS", "BA", "BS")
    ARRAY_SERIES = ("BB", "QQ", "IN", "SC")

    def __init__(self, ref_seq_id=0):
        self.ints = {k: bytearray() for k in self.INT_SERIES}
        self.bytes_ = {k: bytearray() for k in self.BYTE_SERIES}
        self.arr_len = {k: bytearray() for k in self.ARRAY_SERIES}
        self.arr_val = {k: bytearray() for k in self.ARRAY_SERIES}
        self.names = bytearray()
        self.n = 0
        self.ref_seq_id = ref_seq_id

    def put_int(self, k, v):
        self.ints[k] += write_itf8(v)

    def put_byte(self, k, v):
        self.bytes_[k].append(v & 0xFF)

    def put_arr(self, k, data: bytes):
        self.arr_len[k] += write_itf8(len(data))
        self.arr_val[k] += data

    def add(self, *, bf=0, cf=CF_QUAL_STORED, rl=10, ap=100, rg=-1,
            name=b"r", features=(), mq=60, qual=None, ba=None):
        """features: (fpos, code, payload) with absolute 1-based fpos;
        payload is bytes for b/q/I/S, int for D/N/P/H/X, (base, qual)
        for B, base int for i, qual int for Q."""
        self.n += 1
        self.put_int("BF", bf)
        self.put_int("CF", cf)
        self.put_int("RL", rl)
        self.put_int("AP", ap)
        self.put_int("RG", rg)
        self.names += bytes(name) + b"\x00"
        self.put_int("TL", 0)
        if not bf & 0x4:
            self.put_int("FN", len(features))
            prev = 0
            for fpos, code, payload in features:
                self.put_byte("FC", ord(code))
                self.put_int("FP", fpos - prev)
                prev = fpos
                if code in ("b", "q", "I", "S"):
                    self.put_arr({"b": "BB", "q": "QQ", "I": "IN",
                                  "S": "SC"}[code], payload)
                elif code in ("D", "N", "P", "H"):
                    self.put_int({"D": "DL", "N": "RS", "P": "PD",
                                  "H": "HC"}[code], payload)
                elif code == "X":
                    self.put_byte("BS", payload)
                elif code == "B":
                    self.put_byte("BA", payload[0])
                    self.put_byte("QS", payload[1])
                elif code == "i":
                    self.put_byte("BA", payload)
                elif code == "Q":
                    self.put_byte("QS", payload)
                else:
                    raise AssertionError(code)
            self.put_int("MQ", mq)
            if cf & CF_QUAL_STORED:
                q = qual if qual is not None else bytes(range(rl))
                assert len(q) == rl
                self.bytes_["QS"] += q
        else:
            b = ba if ba is not None else b"N" * rl
            assert len(b) == rl
            self.bytes_["BA"] += b
            if cf & CF_QUAL_STORED:
                q = qual if qual is not None else bytes(range(rl))
                assert len(q) == rl
                self.bytes_["QS"] += q

    def build(self):
        comp = CompressionHeader(read_names_included=True, ap_delta=False)
        external = {}
        cid = 1
        for k in self.INT_SERIES:
            comp.data_series[k] = ExternalEncoding(cid)
            external[cid] = bytes(self.ints[k])
            cid += 1
        for k in self.BYTE_SERIES:
            comp.data_series[k] = ExternalEncoding(cid)
            external[cid] = bytes(self.bytes_[k])
            cid += 1
        for k in self.ARRAY_SERIES:
            comp.data_series[k] = ByteArrayLenEncoding(
                ExternalEncoding(cid), ExternalEncoding(cid + 1))
            external[cid] = bytes(self.arr_len[k])
            external[cid + 1] = bytes(self.arr_val[k])
            cid += 2
        comp.data_series["RN"] = ByteArrayStopEncoding(0, cid)
        external[cid] = bytes(self.names)
        hdr = SliceHeader(ref_seq_id=self.ref_seq_id, start=1, span=0,
                          n_records=self.n)
        return comp, hdr, b"", external

    def decode_both(self, ref_source=None, ref_names=("c1", "c2")):
        comp, hdr, core, external = self.build()
        recs = decode_slice_records(comp, hdr, core, dict(external),
                                    list(ref_names), ref_source)
        cols = decode_slice_columns(comp, hdr, core, dict(external),
                                    list(ref_names), ref_source,
                                    want_names=True)
        return cols, recs


REF = FastaReferenceSource(b">c1\n" + b"ACGTACGTGG" * 10000
                           + b"\n>c2\n" + b"TTGGCCAATT" * 10000 + b"\n")


def test_verbatim_bases_no_reference():
    b = _SliceBuilder()
    b.add(rl=8, ap=10, features=[(1, "b", b"ACGTACGT")])
    b.add(rl=6, ap=20, features=[(1, "b", b"GGGTTT")], name=b"second")
    cols, recs = b.decode_both()
    _assert_columns_match(cols, recs)


def test_unmapped_and_unknown_bases():
    b = _SliceBuilder(ref_seq_id=-1)
    b.add(bf=0x4, rl=7, ap=0, ba=b"ACGTNNN")
    b.add(bf=0x4, cf=0, rl=5, ap=0, ba=b"AAAAA")           # no quals
    b.add(bf=0x4, cf=CF_UNKNOWN_BASES | CF_QUAL_STORED, rl=4, ap=0,
          ba=b"NNNN")
    cols, recs = b.decode_both()
    _assert_columns_match(cols, recs)
    # unmapped records keep their BA bases even under CF_UNKNOWN_BASES
    # (the record path's '*' rewrite is mapped-only)
    assert cols["seq_lens"][2] == 4
    assert cols["qual_lens"][1] == 0       # no CF_QUAL_STORED, no qual


def test_mapped_unknown_bases_drop_seq():
    b = _SliceBuilder()
    b.add(rl=6, ap=5, cf=CF_UNKNOWN_BASES | CF_QUAL_STORED,
          features=[(1, "b", b"ACGTAC")])
    cols, recs = b.decode_both()
    _assert_columns_match(cols, recs)
    assert recs[0].seq == "*"
    assert cols["seq_lens"][0] == 0


def test_reference_fill_and_substitution():
    b = _SliceBuilder()
    # pure match: whole read from the reference
    b.add(rl=10, ap=5, features=[])
    # X substitution mid-read (code 0-3 against the default matrix)
    b.add(rl=10, ap=17, features=[(4, "X", 2)])
    # deletion + insertion + soft clip with ref fill around them
    b.add(rl=12, ap=31, features=[(3, "D", 4), (5, "I", b"TT"),
                                  (11, "S", b"GG")])
    # refskip + pad + hardclip consume no read bases
    b.add(rl=9, ap=55, features=[(4, "N", 6), (6, "P", 2), (6, "H", 3)])
    cols, recs = b.decode_both(ref_source=REF)
    _assert_columns_match(cols, recs)


def test_single_base_features_and_qual_overlays():
    b = _SliceBuilder()
    # B: base+qual pair; i: inserted base; Q/q: qual-only overlays
    b.add(rl=10, ap=5, features=[(2, "B", (ord("T"), 7)), (5, "i", ord("C")),
                                 (8, "Q", 9)])
    b.add(rl=10, ap=30, features=[(3, "q", bytes([1, 2, 3]))])
    # overlays on a record WITHOUT stored quals only touch the filler
    b.add(rl=6, ap=60, cf=0, features=[(2, "Q", 11)])
    cols, recs = b.decode_both(ref_source=REF)
    _assert_columns_match(cols, recs)


def test_colliding_qual_overlays_apply_in_feature_order():
    b = _SliceBuilder()
    # 'Q' writes qual pos 3, then a zero-advance 'q' overlapping pos 3:
    # the record path applies features in order, so the 'q' value wins
    b.add(rl=8, ap=5, features=[(3, "Q", 41), (3, "q", bytes([7, 8, 9]))])
    # and the reverse: 'q' first, overlapping 'Q' second -> 'Q' wins
    b.add(rl=8, ap=40, features=[(2, "q", bytes([5, 6, 7])), (3, "Q", 42)])
    cols, recs = b.decode_both(ref_source=REF)
    _assert_columns_match(cols, recs)
    assert cols["qual_cat"][2] == 7        # rec 0, pos 3: 'q' won
    assert cols["qual_cat"][8 + 2] == 42   # rec 1, pos 3: 'Q' won


def test_multiref_slice_and_second_contig():
    b = _SliceBuilder(ref_seq_id=-2)
    b.ints["RI"] = bytearray()
    b.INT_SERIES = b.INT_SERIES + ("RI",)
    b.ints.setdefault("RI", bytearray())
    # rebuild with RI values: interleave manually
    b2 = _SliceBuilder(ref_seq_id=-2)
    b2.ints["RI"] = bytearray()
    orig_add = b2.add

    def add_with_ri(ri, **kw):
        b2.ints["RI"] += write_itf8(ri)
        orig_add(**kw)
    b2.add_with_ri = add_with_ri
    b2.add_with_ri(0, rl=8, ap=11, features=[])
    b2.add_with_ri(1, rl=8, ap=21, features=[(3, "X", 1)])
    comp, hdr, core, external = b2.build()
    comp.data_series["RI"] = ExternalEncoding(99)
    external[99] = bytes(b2.ints["RI"])
    recs = decode_slice_records(comp, hdr, core, dict(external),
                                HDR.ref_names, REF)
    cols = decode_slice_columns(comp, hdr, core, dict(external),
                                HDR.ref_names, REF, want_names=True)
    _assert_columns_match(cols, recs)
    assert recs[1].seq[2] != "G"           # substitution applied vs c2


def test_missing_reference_falls_back_to_record_error():
    b = _SliceBuilder()
    b.add(rl=10, ap=5, features=[])        # needs ref fill
    comp, hdr, core, external = b.build()
    assert decode_slice_columns(comp, hdr, core, dict(external),
                                HDR.ref_names, None) is None
    with pytest.raises(CRAMError):
        decode_slice_records(comp, hdr, core, dict(external),
                             HDR.ref_names, None)


def test_core_bit_codec_declines():
    b = _SliceBuilder()
    b.add(rl=4, ap=5, features=[(1, "b", b"ACGT")])
    comp, hdr, core, external = b.build()
    # a non-constant Huffman (core bits) on a skipped series disables
    # the columnar path
    comp.tag_encodings[0x414143] = HuffmanEncoding([1, 2], [1, 1])
    assert decode_slice_columns(comp, hdr, core, dict(external),
                                HDR.ref_names, None) is None


def test_unknown_feature_code_raises_like_record_path():
    b = _SliceBuilder()
    b.add(rl=4, ap=5, features=[(1, "b", b"ACGT")])
    comp, hdr, core, external = b.build()
    # corrupt FC to an unknown code on both paths
    fc_cid = comp.data_series["FC"].content_id
    external[fc_cid] = b"z"
    with pytest.raises(CRAMError):
        decode_slice_records(comp, hdr, core, dict(external),
                             HDR.ref_names, REF)
    with pytest.raises(CRAMError):
        decode_slice_columns(comp, hdr, core, dict(external),
                             HDR.ref_names, REF)


# ---------------------------------------------------------------------------
# encoder-produced files: whole-file parity through the span reader
# ---------------------------------------------------------------------------

def _roundtrip_columns(records, header=HDR):
    import io

    from hadoop_bam_tpu.formats.cramio import CramWriter
    from hadoop_bam_tpu.split.cram_planner import (
        plan_cram_spans, read_cram_span_columns, read_cram_span_raw,
    )
    sink = io.BytesIO()
    with CramWriter(sink, header) as w:
        w.write_records(records)
    data = sink.getvalue()
    import os
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".cram", delete=False) as f:
        f.write(data)
        path = f.name
    try:
        spans = plan_cram_spans(path)
        all_cols = []
        all_recs = []
        for s in spans:
            all_cols.append(read_cram_span_columns(
                path, s, header=header, want_names=True))
            all_recs.extend(read_cram_span_raw(path, s, header=header))
        from hadoop_bam_tpu.formats.cram_columns import concat_columns
        return concat_columns(all_cols), all_recs
    finally:
        os.unlink(path)


def test_file_parity_mixed_cigars():
    from hadoop_bam_tpu.formats.sam import SamRecord
    recs = []
    pos = 1
    for i in range(300):
        kind = i % 4
        if kind == 0:
            cig, seq = "20M", "ACGTACGTACGTACGTACGT"
        elif kind == 1:
            cig, seq = "8M4I8M", "ACGTACGTTTTTACGTACGT"
        elif kind == 2:
            cig, seq = "5S10M5S", "GGGGGACGTACGTACGGGGG"
        else:
            cig, seq = "10M6D10M", "ACGTACGTACACGTACGTAC"
        pos += 7
        recs.append(SamRecord(
            qname=f"q{i}", flag=0, rname="c1", pos=pos, mapq=50 + i % 10,
            cigar=cig, rnext="*", pnext=0, tlen=0, seq=seq,
            qual="".join(chr(33 + (i + j) % 40) for j in range(len(seq)))))
    # a few unmapped and qual-less records in the same container
    recs.append(SamRecord(qname="u1", flag=4, rname="*", pos=0, mapq=0,
                          cigar="*", rnext="*", pnext=0, tlen=0,
                          seq="ACGTN", qual="IIIII"))
    recs.append(SamRecord(qname="u2", flag=4, rname="*", pos=0, mapq=0,
                          cigar="*", rnext="*", pnext=0, tlen=0,
                          seq="TTTT", qual="*"))
    cols, raw = _roundtrip_columns(recs)
    _assert_columns_match(cols, raw)
    assert cols["n"] == len(recs)


def test_file_parity_bench_fixture_layout():
    """Paired-flag records like the bench fixture writes (detached mates
    exercise the MF/NS/NP/TS interleave on the skipped-names path)."""
    from hadoop_bam_tpu.formats.sam import SamRecord
    recs = []
    pos = 1
    for i in range(200):
        pos += 11
        recs.append(SamRecord(
            qname=f"p{i // 2}", flag=99 if i % 2 == 0 else 147,
            rname="c1", pos=pos, mapq=60, cigar="12M", rnext="=",
            pnext=pos + 50, tlen=62, seq="ACGTACGTACGT",
            qual="JJJJJJJJJJJJ"))
    cols, raw = _roundtrip_columns(recs)
    _assert_columns_match(cols, raw)


def test_randomized_slice_parity_fuzz():
    """Property fuzz: random slices mixing every feature code, mapped and
    unmapped records, stored/missing quals, with a reference — the
    columnar decoder must match the record decoder on all of them."""
    import random

    rng = random.Random(2025)
    for trial in range(25):
        b = _SliceBuilder()
        ap = 5
        for _ in range(rng.randint(1, 40)):
            if rng.random() < 0.2:
                rl = rng.randint(1, 30)
                cf = CF_QUAL_STORED if rng.random() < 0.7 else 0
                b.add(bf=0x4, cf=cf, rl=rl, ap=0,
                      ba=bytes(rng.choice(b"ACGTN") for _ in range(rl)),
                      qual=bytes(rng.randrange(40) for _ in range(rl))
                      if cf else None)
                continue
            rl = rng.randint(8, 40)
            feats = []
            rp = 1
            while rp <= rl and rng.random() < 0.6:
                fpos = rng.randint(rp, rl)
                room = rl - fpos + 1
                code = rng.choice("bXBIiSqQDNPH")
                if code in "bIS":
                    ln = rng.randint(1, room)
                    feats.append((fpos, code, bytes(
                        rng.choice(b"ACGT") for _ in range(ln))))
                    rp = fpos + ln
                elif code == "q":
                    ln = rng.randint(1, room)
                    feats.append((fpos, code, bytes(
                        rng.randrange(40) for _ in range(ln))))
                    rp = fpos
                elif code in "DN":
                    feats.append((fpos, code, rng.randint(1, 9)))
                    rp = fpos
                elif code in "PH":
                    feats.append((fpos, code, rng.randint(1, 5)))
                    rp = fpos
                elif code == "X":
                    feats.append((fpos, code, rng.randrange(4)))
                    rp = fpos + 1
                elif code == "B":
                    feats.append((fpos, code,
                                  (rng.choice(b"ACGT"), rng.randrange(40))))
                    rp = fpos + 1
                elif code == "i":
                    feats.append((fpos, code, rng.choice(b"ACGT")))
                    rp = fpos + 1
                elif code == "Q":
                    feats.append((fpos, code, rng.randrange(40)))
                    rp = fpos
            cf = CF_QUAL_STORED if rng.random() < 0.8 else 0
            b.add(rl=rl, ap=ap, cf=cf, features=feats,
                  mq=rng.randrange(60),
                  qual=bytes(rng.randrange(40) for _ in range(rl))
                  if cf else None,
                  name=f"t{trial}".encode())
            ap += rng.randint(1, 20)
        cols, recs = b.decode_both(ref_source=REF)
        _assert_columns_match(cols, recs)


def test_unknown_bases_bs_codes_validated_on_both_paths():
    """A malformed BS code on a CF_UNKNOWN_BASES-skipped record raises
    CRAMError identically on the record and columnar decode paths (the
    record path substitutes against the 'N' placeholder row; the columnar
    path must not let the code vanish with the dropped seq)."""
    from hadoop_bam_tpu.formats.cram_decode import decode_slice_records

    def build(code):
        b = _SliceBuilder()
        b.add(rl=6, ap=5, cf=CF_UNKNOWN_BASES | CF_QUAL_STORED,
              features=[(3, "X", code)])
        b.add(rl=4, ap=20, features=[(1, "b", b"ACGT")], name=b"ok")
        return b

    # no reference: record path raises via substitute_base('N', code)
    for ref in (None, REF):
        b = build(0xFF)
        comp, hdr, core, external = b.build()
        with pytest.raises(CRAMError):
            decode_slice_records(comp, hdr, core, dict(external),
                                 ["c1", "c2"], ref)
        comp, hdr, core, external = b.build()
        with pytest.raises(CRAMError):
            decode_slice_columns(comp, hdr, core, dict(external),
                                 ["c1", "c2"], ref, want_names=True)

    # a VALID code on an unknown-bases record stays decodable and the
    # two paths still agree
    b = build(2)
    cols, recs = b.decode_both()
    _assert_columns_match(cols, recs)
    b = build(2)
    cols, recs = b.decode_both(ref_source=REF)
    _assert_columns_match(cols, recs)
