"""Causal tracing & live ops plane tests (``pytest -m obs``): the
end-to-end TraceContext contract (one serve request = ONE Chrome-trace
tree under a single trace_id, across the transport, dispatcher, decode
pool and tile-build threads), the span-attrs size guard, the flight
recorder's ring/redaction/rotation, SLO multi-window burn accounting
(a synthetic latency regression flips the fast window before the slow
one), merge_metrics classification over the post-PR-6 counter
families, ``hbam jobs --json``, and the ``hbam top`` CLI e2e against a
live TCP serve process.
"""
import dataclasses
import io
import json
import os
import threading
import time

import pytest

from hadoop_bam_tpu.obs import (
    disable_tracing, enable_tracing, flight,
)
from hadoop_bam_tpu.obs.context import (
    current_trace, current_trace_id, ensure_trace, trace_context,
)
from hadoop_bam_tpu.obs.slo import BurnWindow, SloEngine, SloObjective
from hadoop_bam_tpu.utils.metrics import (
    METRICS, Metrics, MetricsContext, trim_span_args,
)

from fixtures import make_header, make_records

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Tracing off and a pristine (memory-only) flight recorder around
    every test — the recorder is process-global."""
    disable_tracing()
    flight.reset()
    yield
    disable_tracing()
    flight.reset()


# ---------------------------------------------------------------------------
# TraceContext basics
# ---------------------------------------------------------------------------

def test_trace_context_mints_and_restores():
    assert current_trace() is None
    with trace_context(op="cli.test", tenant="t") as ctx:
        assert current_trace() is ctx
        assert len(ctx.trace_id) == 16 and ctx.span_id == 0
        assert ctx.op == "cli.test" and ctx.tenant == "t"
        with trace_context(op="inner") as inner:
            assert inner.trace_id != ctx.trace_id
        assert current_trace() is ctx
    assert current_trace() is None


def test_ensure_trace_joins_active_and_mints_when_absent():
    with ensure_trace(op="lib.call") as minted:
        assert current_trace_id() == minted.trace_id
        with ensure_trace(op="nested") as joined:
            assert joined is minted        # joined, not re-minted
    assert current_trace() is None


def test_trace_rides_the_decode_pool():
    import concurrent.futures as cf

    from hadoop_bam_tpu.utils import pools

    pool = cf.ThreadPoolExecutor(max_workers=2)
    try:
        with trace_context(op="t") as ctx:
            fut = pools.submit(pool, current_trace_id)
            assert fut.result() == ctx.trace_id
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# span attrs size guard (satellite)
# ---------------------------------------------------------------------------

def test_trim_span_args_truncates_and_caps():
    big = "x" * 10_000
    out = trim_span_args({"path": big, "n": 7, "f": 1.5, "flag": True})
    assert len(out["path"]) < 200 and out["path"].endswith("(+9880)")
    assert out["n"] == 7 and out["f"] == 1.5 and out["flag"] is True
    # non-string values stringify + truncate
    out = trim_span_args({"region": list(range(5000))})
    assert isinstance(out["region"], str) and len(out["region"]) < 200
    # key cap: first 8 kept, the cut is marked
    many = {f"k{i:02d}": i for i in range(12)}
    out = trim_span_args(many)
    assert len(out) == 9 and out["dropped_args"] == 4
    assert "k00" in out and "k11" not in out


def test_span_with_pathological_args_stays_bounded_in_ring():
    rec = enable_tracing(256)
    m = Metrics()
    with m.span("x.guard_wall", path="p" * 50_000, region="r" * 9000):
        pass
    ev = [e for e in rec.events() if e[0] == "x.guard_wall"][-1]
    args = ev[5]
    assert len(args["path"]) < 200 and len(args["region"]) < 200
    # and the flight ring got the same bounded payload
    fe = [e for e in flight.recorder()._spans
          if e[1] == "x.guard_wall"][-1]
    assert len(fe[5]["path"]) < 200


# ---------------------------------------------------------------------------
# the acceptance pin: one serve request = ONE trace tree
# ---------------------------------------------------------------------------

def _write_indexed_bam(path, n=2000, seed=7):
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    header = make_header(2)

    def key(r):
        rid = (header.ref_names.index(r.rname) if r.rname != "*"
               else 1 << 30)
        return (rid, r.pos)

    recs = sorted(make_records(header, n, seed=seed), key=key)
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    write_bai(path)
    return header


@pytest.fixture(scope="module")
def traced_bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("traceops") / "t.bam")
    _write_indexed_bam(path)
    return path


def test_serve_request_exports_one_trace_tree(traced_bam):
    from hadoop_bam_tpu.serve import ServeLoop, handle_stream

    rec = enable_tracing(1 << 15)
    out = io.StringIO()
    req = {"id": 1, "path": traced_bam,
           "regions": ["chr1:1000-200000", "chr2:1-5000"],
           "tenant": "web"}
    with ServeLoop() as loop:
        handle_stream(loop, io.StringIO(json.dumps(req) + "\n"), out)
    resp = json.loads(out.getvalue().strip())
    assert "results" in resp, resp
    trace_id = resp["trace"]
    assert isinstance(trace_id, str) and len(trace_id) == 16

    evs = [e for e in rec.events()
           if e[5] and e[5].get("trace") == trace_id]
    names = {e[0] for e in evs}
    # the causal chain: dispatcher request span, pool-side chunk
    # decode, staging-ring tile build (the device dispatch), the mesh
    # filter, and the response write — all under ONE trace id
    assert {"serve.request_wall", "query.decode_wall",
            "serve.tile_build_wall", "serve.filter_wall",
            "serve.response_wall"} <= names
    # across more than one thread (dispatcher + decode pool)
    assert len({e[4] for e in evs}) >= 2
    # well-formed tree: every parent id is the trace root (0) or
    # another event of the SAME trace
    sids = {e[5]["sid"] for e in evs}
    assert all(e[5]["psid"] == 0 or e[5]["psid"] in sids for e in evs)
    # nothing else in the ring claims this trace id, and the serve
    # request produced no orphan spans under other trace ids from
    # this request's threads
    assert len(evs) >= 5

    # the Chrome export carries the same causal args verbatim
    doc = rec.chrome_trace()
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"
          and e.get("args", {}).get("trace") == trace_id]
    assert {e["name"] for e in xs} == names
    json.dumps(doc)


def test_two_requests_get_two_disjoint_traces(traced_bam):
    from hadoop_bam_tpu.serve import ServeLoop, handle_stream

    rec = enable_tracing(1 << 15)
    out = io.StringIO()
    lines = "".join(json.dumps(
        {"id": i, "path": traced_bam, "region": "chr1:1000-100000"})
        + "\n" for i in (1, 2))
    with ServeLoop() as loop:
        handle_stream(loop, io.StringIO(lines), out)
    docs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    traces = {d["trace"] for d in docs}
    assert len(traces) == 2
    by_trace = {t: {e[0] for e in rec.events()
                    if e[5] and e[5].get("trace") == t}
                for t in traces}
    for t in traces:
        assert "serve.request_wall" in by_trace[t]


def test_client_supplied_trace_id_is_adopted(traced_bam):
    from hadoop_bam_tpu.serve import ServeLoop, handle_stream

    out = io.StringIO()
    req = {"id": 9, "path": traced_bam, "region": "chr2:1-5000",
           "trace": "feedc0dedeadbeef"}
    with ServeLoop() as loop:
        handle_stream(loop, io.StringIO(json.dumps(req) + "\n"), out)
    resp = json.loads(out.getvalue().strip())
    assert resp["trace"] == "feedc0dedeadbeef"


def test_hostile_client_trace_id_is_replaced(traced_bam):
    # an oversized / non-token "trace" must NOT ride into the rings and
    # dumps: the server mints a fresh id instead
    from hadoop_bam_tpu.serve import ServeLoop, handle_stream

    for bad in ("x" * 100_000, "has spaces\n", 7, ""):
        out = io.StringIO()
        req = {"id": 1, "path": traced_bam, "region": "chr2:1-5000",
               "trace": bad}
        with ServeLoop() as loop:
            handle_stream(loop, io.StringIO(json.dumps(req) + "\n"),
                          out)
        resp = json.loads(out.getvalue().strip())
        assert resp["trace"] != bad and len(resp["trace"]) == 16


def test_per_tenant_series_are_lru_bounded(traced_bam):
    from hadoop_bam_tpu.serve import ServeLoop

    cfg = dataclasses.replace(
        __import__("hadoop_bam_tpu.config",
                   fromlist=["DEFAULT_CONFIG"]).DEFAULT_CONFIG,
        serve_max_tenants=3)
    with ServeLoop(config=cfg) as loop:
        for i in range(6):
            loop.query(traced_bam, ["chr2:1-5000"], tenant=f"lru-{i}")
        m = loop.slo_metrics
        live = [t for t in (f"lru-{i}" for i in range(6))
                if m.get(f"serve.requests.{t}")]
        # only the newest serve_max_tenants tenants keep series; the
        # evicted ones' keys were discarded from the process-global
        # metrics (arbitrary tenant strings cannot grow it forever)
        assert live == ["lru-3", "lru-4", "lru-5"]
        assert m.hist_summary("serve.latency_s.lru-0") == {}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded_and_always_on():
    fr = flight.reset(capacity=32)
    m = Metrics()
    for i in range(100):
        with m.span(f"f.s{i}_wall"):
            pass
    assert len(fr._spans) == 32          # bounded, tracing DISABLED
    snap = fr.snapshot(reason="test")
    assert len(snap["spans"]) == 32
    assert snap["spans"][-1]["name"] == "f.s99_wall"


def test_flight_snapshot_redacts_and_carries_trace():
    fr = flight.recorder()
    with trace_context(op="t") as ctx:
        METRICS.add_wall("f.redact_wall", 0.001, t0=time.perf_counter(),
                         args={"auth_token": "hunter2", "path": "ok"})
        snap = fr.snapshot(reason="r")
        assert snap["trace"] == ctx.trace_id
    ev = [s for s in snap["spans"] if s["name"] == "f.redact_wall"][-1]
    assert ev["args"]["auth_token"] == "[redacted]"
    assert ev["args"]["path"] == "ok"
    assert ev["trace"] == ctx.trace_id


def test_flight_dump_rotation_cap(tmp_path):
    fr = flight.recorder()
    fr.configure(dump_dir=str(tmp_path), dump_cap=3)
    for i in range(7):
        assert fr.dump(f"reason_{i}") is not None
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 3
    # newest survive (sortable timestamped names)
    assert all(f.startswith("flight-") and f.endswith(".json")
               for f in files)
    doc = json.load(open(tmp_path / files[-1]))
    assert doc["reason"] == "reason_6"


def test_flight_dump_disabled_without_dir():
    fr = flight.recorder()
    assert fr.dump_dir is None
    assert fr.dump("no_dir") is None
    assert fr.dumps_written == 0


def test_deadline_miss_records_flight_transition():
    from hadoop_bam_tpu.query.scheduler import Deadline

    fr = flight.recorder()
    fake = [0.0]
    d = Deadline(0.01, clock=lambda: fake[0])
    fake[0] = 1.0
    assert d.expired
    d.book_miss()
    kinds = [(t[1], t[3]) for t in fr._transitions]
    assert ("deadline", "missed") in kinds


# ---------------------------------------------------------------------------
# SLO burn accounting
# ---------------------------------------------------------------------------

def _slo_engine(clock):
    eng = SloEngine(windows=(BurnWindow("fast", 300.0, 14.4),
                             BurnWindow("slow", 3600.0, 3.0)),
                    clock=clock, tick_s=0.0, min_events=32)
    eng.add(SloObjective(name="latency/web", source="svc.latency_s",
                         threshold_s=0.05, target=0.99))
    return eng


def test_slo_regression_flips_fast_window_before_slow():
    now = [0.0]
    m = Metrics()
    eng = _slo_engine(lambda: now[0])
    # an hour of healthy traffic: 100 good requests per minute
    for t in range(0, 3601, 60):
        now[0] = float(t)
        m.observe("svc.latency_s", 0.01, n=100)
        eng.tick(m, force=True)
    healthy = eng.burn_rates(m)["latency/web"]
    assert healthy["fast"] == 0.0 and healthy["slow"] == 0.0
    assert eng.burning("latency/web", m) is None
    # synthetic latency regression: 150 slow requests right now
    m.observe("svc.latency_s", 1.0, n=150)
    rates = eng.burn_rates(m)["latency/web"]
    # the fast window is dominated by the regression...
    assert rates["fast"] >= 14.4
    # ...while the slow window still amortizes it over the healthy hour
    assert rates["slow"] < 3.0
    assert eng.burning("latency/web", m) == "fast"
    # sustained regression eventually flips the slow window too —
    # fast-before-slow is an ORDER, not an exemption
    for t in range(3660, 7261, 60):
        now[0] = float(t)
        m.observe("svc.latency_s", 1.0, n=100)
        eng.tick(m, force=True)
    rates = eng.burn_rates(m)["latency/web"]
    assert rates["slow"] >= 3.0


def test_slo_min_events_suppresses_cold_tenants():
    now = [0.0]
    eng = _slo_engine(lambda: now[0])
    m = Metrics()
    m.observe("svc.latency_s", 9.0, n=5)     # 5 terrible requests
    eng.tick(m, force=True)
    now[0] = 60.0
    # below min_events: burn reads 0, nothing pages
    assert eng.burn_rates(m)["latency/web"]["fast"] == 0.0


def test_slo_prometheus_series_shape():
    now = [0.0]
    eng = _slo_engine(lambda: now[0])
    m = Metrics()
    m.observe("svc.latency_s", 1.0, n=100)
    eng.tick(m, force=True)
    now[0] = 10.0
    lines = eng.prometheus_lines(m)
    assert lines[0] == "# TYPE hbam_slo_burn_rate gauge"
    assert any(ln.startswith(
        'hbam_slo_burn_rate{slo="latency/web",window="fast"} ')
        for ln in lines)
    assert any('window="slow"' in ln for ln in lines)


def test_slo_error_rate_objective_reads_counters():
    now = [0.0]
    eng = SloEngine(windows=(BurnWindow("fast", 300.0, 10.0),),
                    clock=lambda: now[0], tick_s=0.0, min_events=10)
    eng.add(SloObjective(name="errors/api", source="api.requests",
                         bad_source="api.errors", kind="errors",
                         target=0.999))
    m = Metrics()
    m.count("api.requests", 1000)
    eng.tick(m, force=True)
    now[0] = 100.0
    m.count("api.requests", 100)
    m.count("api.errors", 10)
    rates = eng.burn_rates(m)["errors/api"]
    assert rates["fast"] == pytest.approx((10 / 100) / 0.001, rel=0.01)


def test_slo_batch_shed_pressure_feeds_tenancy():
    from hadoop_bam_tpu.serve.tenancy import TenantQuotas
    from hadoop_bam_tpu.utils.errors import TransientIOError

    quotas = TenantQuotas()

    class Burning:
        def burning(self, name, *a, **k):
            return "fast" if name == "latency/bulk" else None

    quotas.slo_engine = Burning()
    # burning tenant: batch sheds with a classified, hinted error...
    with pytest.raises(TransientIOError) as ei:
        with quotas.admit("bulk", priority="batch"):
            pass
    assert ei.value.retry_after_s is not None
    assert METRICS.get("slo.batch_shed") >= 1
    # ...interactive for the same tenant still admits
    with quotas.admit("bulk", priority="interactive") as d:
        assert d is not None
    # ...and a healthy tenant's batch admits
    with quotas.admit("calm", priority="batch") as d:
        assert d is not None


def test_serve_loop_installs_per_tenant_objectives(traced_bam):
    from hadoop_bam_tpu.serve import ServeLoop

    with ServeLoop() as loop:
        loop.query(traced_bam, ["chr2:1-5000"], tenant="acct-7")
        names = {o.name for o in loop.slo.objectives()}
        assert {"latency/_all", "latency/acct-7"} <= names
        # the mirrored per-tenant series exist in the server's
        # process-global metrics (what `hbam top` polls)
        assert loop.slo_metrics.get("serve.requests.acct-7") == 1
        assert loop.slo_metrics.hist_summary(
            "serve.latency_s.acct-7")["count"] == 1


def test_transport_metrics_op_json_and_prometheus(traced_bam):
    from hadoop_bam_tpu.serve import ServeLoop, handle_stream

    out = io.StringIO()
    with ServeLoop() as loop:
        # serve a request to completion first (handle_stream waits for
        # every response), THEN poll the metrics ops on a second stream
        # — the ops are answered inline on the reader thread
        handle_stream(loop, io.StringIO(json.dumps(
            {"id": 1, "path": traced_bam, "region": "chr2:1-5000",
             "tenant": "mop"}) + "\n"), out)
        handle_stream(loop, io.StringIO(
            json.dumps({"id": 2, "op": "metrics"}) + "\n"
            + json.dumps({"id": 3, "op": "metrics",
                          "format": "prometheus"}) + "\n"), out)
    docs = {d["id"]: d for d in
            (json.loads(ln) for ln in out.getvalue().splitlines())}
    snap = docs[2]["metrics"]
    assert snap["counters"].get("serve.requests.mop") == 1
    assert "slo" in docs[2] and "latency/_all" in docs[2]["slo"]
    # SLO burn-rate series in the Prometheus exposition (acceptance)
    text = docs[3]["prometheus"]
    assert "# TYPE hbam_slo_burn_rate gauge" in text
    assert 'hbam_slo_burn_rate{slo="latency/_all",window="fast"}' in text
    assert 'hbam_slo_burn_rate{slo="latency/mop",window="slow"}' in text
    assert "hbam_serve_requests_mop_total" in text


# ---------------------------------------------------------------------------
# merge_metrics over the post-PR-6 counter families (satellite)
# ---------------------------------------------------------------------------

_FAMILY_COUNTERS = (
    "serve.requests", "serve.tile_hits", "serve.prefetch_issued",
    "cohort.samples_quarantined", "cohort.duplicate_sites",
    "jobs.rounds_skipped", "jobs.journal_records",
    "write.bytes_out", "write.records", "obs.flight_dumps",
)
_FAMILY_WALLS = (
    "serve.request_wall", "serve.tile_build_wall", "cohort.join_wall",
    "write.deflate_wall", "write.commit_wall", "bam.fused_decode_wall",
)


def _family_host(seed):
    m = Metrics()
    for i, k in enumerate(_FAMILY_COUNTERS):
        m.count(k, (seed + 1) * (i + 1))
    for i, k in enumerate(_FAMILY_WALLS):
        m.add_wall(k, 0.5 * (seed + 1) + 0.1 * i)
    m.observe("serve.latency_s", 0.01 * (seed + 1), n=50)
    m.observe("pool.task_run_s", 0.001 * (seed + 1), n=20)
    return m


def test_merge_metrics_families_sum_counters_max_walls():
    hosts = [_family_host(s) for s in range(3)]
    merged = Metrics()
    for h in hosts:
        merged.merge_dict(h.to_dict())
    for i, k in enumerate(_FAMILY_COUNTERS):
        # counters SUM across hosts (work adds) — pinned per family
        assert merged.get(k) == (1 + 2 + 3) * (i + 1), k
    for i, k in enumerate(_FAMILY_WALLS):
        # wall spans take the MAX (hosts run concurrently; the mesh
        # wall is the slowest host's union, never the sum)
        assert merged.wall_timers[k] == pytest.approx(
            0.5 * 3 + 0.1 * i), k
    assert merged.hist_summary("serve.latency_s")["count"] == 150


def test_merge_metrics_families_fold_order_invariant():
    hosts = [_family_host(s) for s in range(4)]
    ab = Metrics()
    for h in hosts:
        ab.merge_dict(h.to_dict())
    ba = Metrics()
    for h in reversed(hosts):
        ba.merge_dict(h.to_dict())
    a, b = ab.to_dict(), ba.to_dict()
    assert a["counters"] == b["counters"]
    assert a["wall_timers"] == b["wall_timers"]
    assert a["histograms"]["serve.latency_s"]["buckets"] == \
        b["histograms"]["serve.latency_s"]["buckets"]


# ---------------------------------------------------------------------------
# journal trace stamping + hbam jobs --json (satellite)
# ---------------------------------------------------------------------------

def _make_journal(tmp_path, resumed=True):
    from hadoop_bam_tpu.jobs import JobJournal

    jp = str(tmp_path / "job.hbam-journal")
    with trace_context(op="job.test") as ctx:
        first_trace = ctx.trace_id
        jr, st = JobJournal.resume(jp, kind="mesh_sort_spill",
                                   inputs=[], output=None,
                                   fingerprint="fp",
                                   params={"round_records": 10})
        assert st is None
        jr.unit_done("round", 0, run="r0.bin", size=1, crc="ab")
        jr.close()
    if resumed:
        with trace_context(op="job.resume"):
            jr2, st2 = JobJournal.resume(jp, kind="mesh_sort_spill",
                                         inputs=[], output=None,
                                         fingerprint="fp",
                                         params={"round_records": 10})
            assert st2 is not None and len(st2.units) == 1
            jr2.unit_done("round", 1, run="r1.bin", size=1, crc="cd")
            jr2.close()
    return jp, first_trace


def test_journal_lines_carry_trace_id(tmp_path):
    jp, first_trace = _make_journal(tmp_path, resumed=False)
    lines = [json.loads(ln) for ln in
             open(jp, "rb").read().decode().splitlines()]
    assert all(ln.get("trace") == first_trace for ln in lines)


def test_jobs_json_shares_one_parser(tmp_path, capsys):
    from hadoop_bam_tpu.jobs import job_info_doc, job_status
    from hadoop_bam_tpu.tools import cli

    jp, first_trace = _make_journal(tmp_path)
    rc = cli.main(["jobs", str(tmp_path), "--json"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    (doc,) = [json.loads(ln) for ln in out]
    # the CLI emits exactly job_info_doc's contract
    assert doc == job_info_doc(job_status(jp))
    assert doc["kind"] == "mesh_sort_spill"
    assert doc["resume_grain"] == "round"
    assert doc["status"] == "resumable"
    assert doc["units_total"] == 2          # rounds 0 + 1 committed
    assert doc["units_skipped"] == 1        # the resume skipped round 0
    assert doc["resumes"] == 1
    assert doc["trace_id"] == first_trace   # the MINTING invocation


# ---------------------------------------------------------------------------
# hbam top against a live serve process (acceptance e2e)
# ---------------------------------------------------------------------------

def test_hbam_top_renders_live_serve(traced_bam, tmp_path, capsys):
    from hadoop_bam_tpu.serve import ServeLoop, make_tcp_server
    from hadoop_bam_tpu.tools import cli

    _make_journal(tmp_path)
    with ServeLoop() as loop:
        # live traffic so the per-tenant series exist
        loop.query(traced_bam, ["chr1:1000-200000"], tenant="webtop")
        loop.query(traced_bam, ["chr2:1-5000"], tenant="webtop")
        server = make_tcp_server(loop, port=0)
        _host, port = server.server_address[:2]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            rc = cli.main(["top", "--port", str(port), "--once",
                           "--jobs-dir", str(tmp_path)])
        finally:
            server.shutdown()
            server.server_close()
    assert rc == 0
    out = capsys.readouterr().out
    assert "hbam top" in out and "status=serving" in out
    assert "pool: workers=" in out
    assert "slo latency/_all:" in out
    # the per-tenant table row with its request count and breaker state
    assert "webtop" in out
    line = next(ln for ln in out.splitlines() if ln.startswith("webtop"))
    assert "closed" in line
    # p50/p99 render as numbers for a tenant with traffic
    assert line.split()[2] != "-" and line.split()[3] != "-"
    # job resume progress from the shared `hbam jobs --json` document
    assert "grain=round" in out and "units=1/2" in out


def test_hbam_top_unreachable_port_errors_cleanly(capsys):
    from hadoop_bam_tpu.tools import cli

    rc = cli.main(["top", "--port", "1", "--once", "--timeout", "0.5"])
    assert rc == 1
    assert "cannot poll" in capsys.readouterr().err
