"""Staging-ring / FeedPipeline suite (parallel/staging.py, r8 tentpole).

Three contracts are pinned here:

1. BYTE IDENTITY — random span-size streams through ``FeedPipeline``
   produce exactly the batches of the old serial emit path (the
   ``_iter_tile_tuples`` + fresh-group-tile loop every driver used to
   hand-roll), so the ring rebuild cannot change a single device byte.
2. NO ALIASING — a leased ring slot is never mutated while its dispatch
   is still in flight (a fake device_put snapshots the buffers, dawdles,
   and re-checks), which is the whole safety argument for reusing
   buffers under a double-buffered packer thread.
3. SHARED POOL / KNOBS — ``utils/pools.py`` hands every driver the same
   executor, honors ``decode_pool_workers`` at creation, and the
   ``set_decode_pool`` injection hook reaches real drivers.

Quick run: ``pytest -m staging``; still part of the tier-1 run.
"""
import concurrent.futures as cf
import threading
import time

import numpy as np
import pytest

from hadoop_bam_tpu.config import HBamConfig
from hadoop_bam_tpu.parallel.staging import (
    FeedPipeline, StagingRing, TileSpec, bucket_cap,
)

pytestmark = pytest.mark.staging


# ---------------------------------------------------------------------------
# the serial reference: the old per-driver emit loop, verbatim semantics
# ---------------------------------------------------------------------------

def serial_reference_groups(span_tuples, n_dev, cap, specs, block_n=16,
                            fixed_shape=False):
    """What every driver's hand-rolled loop used to produce: serial
    cross-span tiling (_iter_tile_tuples) + a fresh padded group tile
    per emit.  The FeedPipeline must match this byte for byte."""
    from hadoop_bam_tpu.parallel.pipeline import _iter_tile_tuples

    specs = [TileSpec.normalize(s) for s in specs]
    legacy = [(s.shape[0] if s.shape else None, s.dtype) for s in specs]
    group, counts, out = [], [], []

    def emit():
        b = cap if fixed_shape else \
            max(bucket_cap(c, cap, block_n) for c in counts)
        cvec = np.zeros((n_dev,), np.int32)
        cvec[:len(counts)] = counts
        stacked = []
        for j, sp in enumerate(specs):
            tile = np.full((n_dev, b) + sp.shape, sp.pad, specs[j].dtype)
            for i, g in enumerate(group):
                tile[i, :counts[i]] = g[j][:counts[i]]
            stacked.append(tile)
        out.append((stacked, cvec))
        group.clear()
        counts.clear()

    for tiles, count in _iter_tile_tuples(span_tuples, cap, legacy):
        group.append(tiles)
        counts.append(count)
        if len(group) == n_dev:
            emit()
    if group:
        emit()
    return out


def random_span_stream(rng, specs, n_spans, max_rows=57):
    """Random per-span row-array tuples (lockstep lengths, incl. empty
    spans) with distinguishable content."""
    specs = [TileSpec.normalize(s) for s in specs]
    seq = 0
    out = []
    for _ in range(n_spans):
        n = int(rng.integers(0, max_rows + 1))
        arrays = []
        for sp in specs:
            shape = (n,) + sp.shape
            if np.issubdtype(np.dtype(sp.dtype), np.floating):
                a = rng.normal(size=shape).astype(sp.dtype)
            else:
                info = np.iinfo(np.dtype(sp.dtype))
                a = (seq + np.arange(np.prod(shape, dtype=np.int64))
                     ).reshape(shape) % int(info.max) + 1
                a = a.astype(sp.dtype)
            arrays.append(a)
        seq += n
        out.append(tuple(arrays))
    return out


SPECS = (TileSpec((7,), np.uint8, 0),       # payload-ish 2-D tile
         TileSpec((3,), np.int8, -1),       # dosage-ish, pad -1
         TileSpec((), np.int32, 0))         # lengths-ish 1-D series


@pytest.mark.parametrize("n_dev,cap,fixed", [(1, 32, False), (3, 32, False),
                                             (8, 64, True), (4, 16, False)])
def test_feed_pipeline_byte_identical_to_serial_emit(n_dev, cap, fixed):
    rng = np.random.default_rng(1234 + n_dev + cap)
    for trial in range(4):
        spans = random_span_stream(rng, SPECS, n_spans=int(
            rng.integers(0, 24)))
        want = serial_reference_groups(iter(spans), n_dev, cap, SPECS,
                                       block_n=8, fixed_shape=fixed)
        fp = FeedPipeline(n_dev, cap, SPECS, block_n=8, fixed_shape=fixed,
                          ring_slots=2, dispatch_depth=2)
        got = []
        fp.feed(iter(spans), lambda arrays, counts: got.append(
            ([a.copy() for a in arrays], counts.copy())))
        assert len(got) == len(want)
        for (ga, gc), (wa, wc) in zip(got, want):
            np.testing.assert_array_equal(gc, wc)
            assert len(ga) == len(wa)
            for g, w in zip(ga, wa):
                assert g.dtype == w.dtype and g.shape == w.shape
                np.testing.assert_array_equal(g, w)


def test_feed_pipeline_property_many_seeds():
    """Wider randomized sweep at one geometry — the property-test body
    of the r8 acceptance: stream -> ring == stream -> serial, always."""
    for seed in range(12):
        rng = np.random.default_rng(seed)
        spans = random_span_stream(rng, SPECS, n_spans=int(
            rng.integers(1, 40)), max_rows=33)
        want = serial_reference_groups(iter(spans), 3, 24, SPECS, block_n=4)
        fp = FeedPipeline(3, 24, SPECS, block_n=4, ring_slots=3,
                          dispatch_depth=2)
        got = []
        fp.feed(iter(spans), lambda a, c: got.append(
            ([x.copy() for x in a], c.copy())))
        assert len(got) == len(want)
        for (ga, gc), (wa, wc) in zip(got, want):
            np.testing.assert_array_equal(gc, wc)
            for g, w in zip(ga, wa):
                np.testing.assert_array_equal(g, w)


def test_leased_slot_never_mutated_during_dispatch():
    """The aliasing contract: while a fake device_put dawdles inside
    dispatch, the packer thread must NOT touch the dispatched buffers —
    entry and exit snapshots are identical, and every snapshot equals
    the serial reference batch."""
    rng = np.random.default_rng(7)
    spans = random_span_stream(rng, SPECS, n_spans=30, max_rows=40)
    n_dev, cap = 2, 16
    want = serial_reference_groups(iter(spans), n_dev, cap, SPECS,
                                   block_n=4)
    # 2 slots + a fast packer: if leasing were broken the packer would
    # overwrite the in-flight slot during the sleep below
    fp = FeedPipeline(n_dev, cap, SPECS, block_n=4, ring_slots=2,
                      dispatch_depth=2)
    snapshots = []

    def fake_device_put_dispatch(arrays, counts):
        entry = [a.copy() for a in arrays] + [counts.copy()]
        time.sleep(0.02)          # the device_put "in flight" window
        for before, now in zip(entry, list(arrays) + [counts]):
            np.testing.assert_array_equal(before, now)
        snapshots.append(entry)

    fp.feed(iter(spans), fake_device_put_dispatch)
    assert len(snapshots) == len(want)
    for snap, (wa, wc) in zip(snapshots, want):
        for g, w in zip(snap[:-1], wa):
            np.testing.assert_array_equal(g, w)
        np.testing.assert_array_equal(snap[-1], wc)


def test_stream_mode_releases_slot_only_after_advance():
    """stream(): the yielded batch's buffers stay valid until the
    consumer asks for the next one (the borrow contract tensor_batches
    relies on)."""
    spans = [(np.full((10, 4), i + 1, np.uint8),) for i in range(12)]
    fp = FeedPipeline(2, 8, (TileSpec((4,), np.uint8),), block_n=4,
                      ring_slots=2)
    it = fp.stream(iter(spans), lambda a, c: (a[0], c))
    tile, counts = next(it)
    first = tile.copy()
    time.sleep(0.05)              # packer has every chance to misbehave
    np.testing.assert_array_equal(tile, first)
    rest = list(it)
    assert rest                   # the stream kept flowing afterwards


def test_in_flight_handles_block_before_slot_reuse():
    """The async-transfer contract: whatever a dispatch returns rides
    the slot as its in-flight handle, and the packer must wait on it
    before overwriting that slot's buffers.  Each fake handle only
    'completes' when the NEXT group is dispatched — so the feed can
    finish at all only if the packer genuinely waited in order."""
    class Handle:
        def __init__(self, i):
            self.i = i
            self.released = threading.Event()

        def block_until_ready(self):
            if not self.released.wait(timeout=10):
                raise RuntimeError(f"handle {self.i} never released")
            waited.append(self.i)

    spans = [(np.full((8, 2), i + 1, np.uint8),) for i in range(6)]
    fp = FeedPipeline(1, 8, (TileSpec((2,), np.uint8),), ring_slots=2,
                      dispatch_depth=2)
    handles, waited = [], []

    def dispatch(arrays, counts):
        h = Handle(len(handles))
        handles.append(h)
        if h.i >= 1:
            handles[h.i - 1].released.set()   # transfer i-1 'completes'
        return h

    assert fp.feed(iter(spans), dispatch) == 6
    # 2-slot ring over 6 groups: slots reused 4 times, each wait honored
    assert waited == [0, 1, 2, 3]
    for h in handles:
        h.released.set()


def test_decode_error_propagates_and_unwinds():
    """An exception in the span stream (the packer thread) re-raises at
    the caller and leaves no stuck threads behind."""
    def bad_stream():
        yield (np.zeros((5, 4), np.uint8),)
        raise RuntimeError("span decode exploded")

    fp = FeedPipeline(2, 8, (TileSpec((4,), np.uint8),), ring_slots=2)
    before = threading.active_count()
    with pytest.raises(RuntimeError, match="exploded"):
        fp.feed(bad_stream(), lambda a, c: None)
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_dispatch_error_cancels_packer():
    """The inverse: the consumer's dispatch raising must cancel the
    packer (which may be blocked on a full queue) instead of hanging."""
    spans = [(np.zeros((8, 4), np.uint8),) for _ in range(64)]
    fp = FeedPipeline(1, 8, (TileSpec((4,), np.uint8),), ring_slots=2,
                      dispatch_depth=2)

    def dispatch(arrays, counts):
        raise ValueError("device fell over")

    with pytest.raises(ValueError, match="fell over"):
        fp.feed(iter(spans), dispatch)


def test_empty_and_all_empty_streams_dispatch_nothing():
    fp = FeedPipeline(2, 8, (TileSpec((4,), np.uint8),))
    calls = []
    fp.feed(iter(()), lambda a, c: calls.append(1))
    fp.feed(iter([(np.zeros((0, 4), np.uint8),)] * 3),
            lambda a, c: calls.append(1))
    assert calls == []
    assert fp.dispatches == 0


def test_partial_tail_zeroing_uses_spec_pad():
    """Reused slots must not leak a previous group's rows: with a
    2-slot ring, the third group reuses the first group's slot, and its
    partial tail must carry the SPEC pad (0 / -1), not group 1's 9s."""
    fp = FeedPipeline(1, 8, (TileSpec((2,), np.uint8, 0),
                             TileSpec((2,), np.int8, -1)),
                      block_n=4, ring_slots=2, dispatch_depth=2)
    spans = [
        (np.full((8, 2), 9, np.uint8), np.full((8, 2), 5, np.int8)),
        (np.full((8, 2), 8, np.uint8), np.full((8, 2), 4, np.int8)),
        (np.full((3, 2), 7, np.uint8), np.full((3, 2), 2, np.int8)),
    ]
    batches = []
    fp.feed(iter(spans),
            lambda a, c: batches.append(([x.copy() for x in a], c.copy())))
    assert len(batches) == 3
    (u8, i8), c = batches[-1]
    assert int(c[0]) == 3
    assert u8.shape == (1, 4, 2)      # shrunk to the block_n bucket
    assert (u8[0, :3] == 7).all() and (u8[0, 3:] == 0).all()
    assert (i8[0, :3] == 2).all() and (i8[0, 3:] == -1).all()


def test_config_knobs_reach_the_pipeline():
    cfg = HBamConfig(feed_ring_slots=5, feed_dispatch_depth=3)
    fp = FeedPipeline(2, 8, (TileSpec((4,), np.uint8),), config=cfg)
    assert fp.ring_slots == 5 and fp.dispatch_depth == 3
    # explicit args beat the config
    fp = FeedPipeline(2, 8, (TileSpec((4,), np.uint8),), config=cfg,
                      ring_slots=2, dispatch_depth=2)
    assert fp.ring_slots == 2 and fp.dispatch_depth == 2
    ring = StagingRing(2, 8, (TileSpec((4,), np.uint8),), slots=4)
    assert ring.n_slots == 4 and len(ring.slots) == 4


def test_overlap_accounting_and_dispatch_bytes():
    from hadoop_bam_tpu.utils.metrics import Metrics

    spans = [(np.zeros((16, 4), np.uint8),) for _ in range(8)]
    fp = FeedPipeline(2, 16, (TileSpec((4,), np.uint8),), block_n=4)
    fp.feed(iter(spans), lambda a, c: time.sleep(0.005))
    assert fp.dispatches == 4
    # [2, 16, 4] u8 + [2] i32 per group
    assert fp.dispatch_bytes == 4 * (2 * 16 * 4 + 8)
    assert 0.0 < fp.overlap_efficiency <= 1.0

    # wall_timer union semantics: overlapping same-name spans count once
    m = Metrics()
    with m.wall_timer("x"):
        with m.wall_timer("x"):
            time.sleep(0.02)
    assert m.wall_calls["x"] == 1
    assert 0.015 <= m.wall_timers["x"] < 1.0


# ---------------------------------------------------------------------------
# the shared decode pool
# ---------------------------------------------------------------------------

def test_decode_pool_is_shared_and_sized_by_config():
    from hadoop_bam_tpu.utils import pools

    prev = pools.set_decode_pool(None)
    try:
        cfg = HBamConfig(decode_pool_workers=3)
        p1 = pools.decode_pool(cfg)
        assert pools.decode_pool_size() == 3
        # one process, one pool: later (different) configs get the same
        p2 = pools.decode_pool(HBamConfig(decode_pool_workers=11))
        assert p2 is p1 and pools.decode_pool_size() == 3
        p1.shutdown(wait=True)
    finally:
        pools.set_decode_pool(*prev)


def test_set_decode_pool_injection_reaches_drivers(tmp_path):
    """A driver run decodes through the injected pool — the test hook
    the r8 issue asks for."""
    from hadoop_bam_tpu.parallel.pipeline import fastq_seq_stats_file
    from hadoop_bam_tpu.utils import pools

    class RecordingPool(cf.ThreadPoolExecutor):
        def __init__(self):
            super().__init__(max_workers=2)
            self.submits = 0

        def submit(self, fn, *a, **kw):
            self.submits += 1
            return super().submit(fn, *a, **kw)

    fq = str(tmp_path / "tiny.fastq")
    with open(fq, "w") as f:
        for i in range(50):
            f.write(f"@r{i}\nACGTACGTAC\n+\nIIIIIIIIII\n")
    rec = RecordingPool()
    prev = pools.set_decode_pool(rec, size=2)
    try:
        stats = fastq_seq_stats_file(fq)
        assert stats["n_reads"] == 50
        assert rec.submits > 0
    finally:
        pools.set_decode_pool(*prev)
        rec.shutdown(wait=True)
