"""Device-op tests (run on CPU JAX per conftest): the jnp/Pallas unpack paths
must agree bit-for-bit with the host NumPy reference (formats/bam.BamBatch)."""
import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import BamBatch, walk_record_offsets
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.ops import inflate as inflate_ops
from hadoop_bam_tpu.ops.flagstat import flagstat_from_columns, format_flagstat
from hadoop_bam_tpu.ops.seq_decode import decode_qual, decode_seq
from hadoop_bam_tpu.ops.unpack_bam import (
    FIXED_FIELDS, pad_data, pad_offsets, unpack_fixed_fields,
    unpack_fixed_fields_pallas,
)
from hadoop_bam_tpu.utils import native

from fixtures import make_header, make_records


@pytest.fixture(scope="module")
def decoded_span(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("ops") / "t.bam")
    header = make_header()
    records = make_records(header, 500, seed=9)
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    raw = open(path, "rb").read()
    data, ubase = inflate_ops.inflate_span(raw)
    from hadoop_bam_tpu.formats.bam import SAMHeader
    _, after = SAMHeader.from_bam_bytes(data.tobytes())
    offs = walk_record_offsets(data.tobytes(), start=after)
    batch = BamBatch(data, offs, header=header)
    return header, records, data, offs, batch


def test_unpack_fixed_fields_matches_host(decoded_span):
    header, records, data, offs, batch = decoded_span
    cap_d = 1 << 20
    cap_n = 1024
    dev_data = pad_data(data, cap_d)
    dev_offs, n = pad_offsets(offs.astype(np.int32), cap_n)
    cols = unpack_fixed_fields(dev_data, dev_offs)
    for name in FIXED_FIELDS:
        host = getattr(batch, name)
        got = np.asarray(cols[name])[:n]
        np.testing.assert_array_equal(got.astype(np.int64), host,
                                      err_msg=f"column {name}")


def test_unpack_pallas_matches_jnp(decoded_span):
    header, records, data, offs, batch = decoded_span
    dev_data = pad_data(data, 1 << 20)
    dev_offs, n = pad_offsets(offs.astype(np.int32), 1024)
    a = unpack_fixed_fields(dev_data, dev_offs)
    b = unpack_fixed_fields_pallas(dev_data, dev_offs, block_n=256)
    for name in FIXED_FIELDS:
        np.testing.assert_array_equal(np.asarray(a[name]), np.asarray(b[name]),
                                      err_msg=f"column {name}")


def test_flagstat_matches_host(decoded_span):
    header, records, data, offs, batch = decoded_span
    dev_data = pad_data(data, 1 << 20)
    dev_offs, n = pad_offsets(offs.astype(np.int32), 1024)
    cols = unpack_fixed_fields(dev_data, dev_offs)
    valid = np.arange(1024) < n
    stats = {k: int(v) for k, v in
             flagstat_from_columns(cols, valid).items()}
    flags = np.asarray([r.flag for r in records])
    assert stats["total"] == len(records)
    assert stats["mapped"] == int(np.sum((flags & 0x4) == 0))
    assert stats["paired"] == int(np.sum((flags & 0x1) != 0))
    assert stats["properly_paired"] == int(
        np.sum(((flags & 0x2) != 0) & ((flags & 0x1) != 0) & ((flags & 0x4) == 0)))
    text = format_flagstat(stats)
    assert text.splitlines()[0].startswith(f"{len(records)} + 0 in total")


def test_seq_qual_decode_matches_host(decoded_span):
    header, records, data, offs, batch = decoded_span
    n = len(batch)
    max_len = int(batch.l_seq.max())
    dev_data = pad_data(data, 1 << 20)
    seq = np.asarray(decode_seq(dev_data, batch.seq_offset.astype(np.int32),
                                batch.l_seq.astype(np.int32), max_len))
    qual = np.asarray(decode_qual(dev_data, batch.qual_offset.astype(np.int32),
                                  batch.l_seq.astype(np.int32), max_len))
    for i in [0, 5, n - 1]:
        l = int(batch.l_seq[i])
        assert seq[i, :l].tobytes().decode() == batch.seq_string(i)
        assert qual[i, :l].tobytes().decode() == batch.qual_string(i)
        assert not seq[i, l:].any()


@pytest.mark.skipif(not native.available(), reason="native lib not built")
def test_native_inflate_matches_zlib(decoded_span, tmp_path):
    header, records, *_ = decoded_span
    path = str(tmp_path / "t2.bam")
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    raw = open(path, "rb").read()
    d1, u1 = inflate_ops.inflate_span(raw, backend="native")
    d2, u2 = inflate_ops.inflate_span(raw, backend="zlib")
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(u1, u2)
    table = inflate_ops.block_table(raw)
    inflate_ops.verify_crcs(raw, table, d1, u1)
    # corrupt one compressed byte -> native inflate or CRC must fail
    bad = bytearray(raw)
    bad[int(table["cdata_off"][0]) + 5] ^= 0xFF
    with pytest.raises(Exception):
        d3, u3 = inflate_ops.inflate_span(bytes(bad), backend="native")
        inflate_ops.verify_crcs(bytes(bad), inflate_ops.block_table(bytes(bad)),
                                d3, u3)


@pytest.mark.skipif(not native.available(), reason="native lib not built")
def test_native_walk_matches_python(decoded_span):
    header, records, data, offs, batch = decoded_span
    from hadoop_bam_tpu.formats.bam import SAMHeader
    _, after = SAMHeader.from_bam_bytes(data.tobytes())
    n_offs, tail = native.walk_bam_records(data, after, cap=10000)
    np.testing.assert_array_equal(n_offs, offs)
    assert tail == data.size
