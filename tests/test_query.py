"""Query subsystem tests (``pytest -m query``): byte-identity of region
queries against the full-scan + host-filter oracle for every container,
chunk coalescing/caching behavior, file-identity invalidation, and the
admission/deadline/fault policies riding the PR-1 taxonomy.
"""
import dataclasses
import os

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.query import (
    ChunkCache, QueryEngine, QueryRequest, QueryScheduler, file_identity,
)
from hadoop_bam_tpu.utils.errors import (
    CorruptDataError, PlanError, TransientIOError,
)
from hadoop_bam_tpu.utils.metrics import METRICS

from fixtures import make_header, make_records

pytestmark = pytest.mark.query


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _coord_sorted(header, recs):
    def key(r):
        rid = (header.ref_names.index(r.rname) if r.rname != "*"
               else 1 << 30)
        return (rid, r.pos)
    return sorted(recs, key=key)


@pytest.fixture(scope="module")
def indexed_bam(tmp_path_factory):
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    path = str(tmp_path_factory.mktemp("query") / "q.bam")
    header = make_header(2)
    recs = _coord_sorted(header, make_records(header, 2500, seed=11))
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    write_bai(path)
    return path, header


def _write_vcf_records(path, n, seed):
    import random

    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord

    hdr_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr20,length=64444167>\n"
        "##contig=<ID=chr21,length=46709983>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="GT">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts0\ts1\n")
    header = VCFHeader.from_text(hdr_text)
    rng = random.Random(seed)
    gts = ["0/0", "0/1", "1/1", "./."]
    with open_vcf_writer(path, header) as w:
        for chrom in ("chr20", "chr21"):
            pos = 1
            for i in range(n // 2):
                pos += rng.randint(1, 60)
                ref = rng.choice("ACGT")
                alt = rng.choice([c for c in "ACGT" if c != ref])
                g = "\t".join(rng.choice(gts) for _ in range(2))
                w.write_record(VcfRecord.from_line(
                    f"{chrom}\t{pos}\t.\t{ref}\t{alt}\t{30 + i % 40}\t"
                    f"PASS\tDP={i % 90}\tGT\t{g}"))
    return header


@pytest.fixture(scope="module")
def indexed_vcf(tmp_path_factory):
    from hadoop_bam_tpu.split.tabix import write_tabix

    path = str(tmp_path_factory.mktemp("query") / "q.vcf.gz")
    _write_vcf_records(path, 3000, seed=21)
    write_tabix(path)
    return path


@pytest.fixture(scope="module")
def indexed_bcf(tmp_path_factory):
    from hadoop_bam_tpu.split.tabix import write_tabix

    path = str(tmp_path_factory.mktemp("query") / "q.bcf")
    _write_vcf_records(path, 3000, seed=22)
    write_tabix(path)
    return path


@pytest.fixture(scope="module")
def cram_path(tmp_path_factory):
    from hadoop_bam_tpu.api.writers import CramShardWriter

    path = str(tmp_path_factory.mktemp("query") / "q.cram")
    header = make_header(2)
    recs = _coord_sorted(
        header, [r for r in make_records(header, 1200, seed=31)
                 if r.flag != 4])
    with CramShardWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path, header


# ---------------------------------------------------------------------------
# byte-identity vs the full-scan + host-filter oracle
# ---------------------------------------------------------------------------

_BAM_REGIONS = ["chr1:1000-200000", "chr1:500,000-650,000", "chr2",
                "chr2:1-5000", "chr1:999999-1000000"]


def _bam_oracle(path, header, region):
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.split.intervals import (
        batch_overlap_mask, resolve_interval,
    )
    iv = resolve_interval(region, header.ref_names)
    want = []
    for batch in open_bam(path).batches():
        m = batch_overlap_mask(batch, [iv], header)
        for i in np.nonzero(m)[0]:
            want.append(batch.to_sam_line(int(i)))
    return want


def test_bam_query_matches_full_scan_oracle(indexed_bam):
    path, header = indexed_bam
    engine = QueryEngine()
    res = engine.query_records(
        [QueryRequest(path, r) for r in _BAM_REGIONS])
    for region, out in zip(_BAM_REGIONS, res):
        got = [r.to_line() for r in out.records]
        assert got == _bam_oracle(path, header, region), region
    # at least one region matched something or the test is vacuous
    assert sum(len(r.records) for r in res) > 0


def _variant_oracle(path, region):
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.split.intervals import resolve_interval
    ds = open_vcf(path)
    iv = resolve_interval(region, ds.header.contigs)
    want = []
    for rec in ds.records():
        if rec.chrom != iv.rname:
            continue
        if rec.pos <= iv.end and rec.pos + max(rec.rlen, 1) - 1 >= iv.start:
            want.append(rec.to_line())
    return want


@pytest.mark.parametrize("fixture", ["indexed_vcf", "indexed_bcf"])
def test_variant_query_matches_full_scan_oracle(fixture, request):
    path = request.getfixturevalue(fixture)
    engine = QueryEngine()
    regions = ["chr20:1-30000", "chr20:40,000-60,000", "chr21",
               "chr21:1-10"]
    res = engine.query_records([QueryRequest(path, r) for r in regions])
    for region, out in zip(regions, res):
        got = [r.to_line() for r in out.records]
        assert got == _variant_oracle(path, region), region
    assert sum(len(r.records) for r in res) > 0


def test_cram_query_matches_full_scan_oracle(cram_path):
    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.query.engine import _ref_span_of_cigar
    from hadoop_bam_tpu.split.intervals import resolve_interval

    path, header = cram_path
    engine = QueryEngine()
    regions = ["chr1:1-400000", "chr2:100,000-1,500,000"]
    res = engine.query_records([QueryRequest(path, r) for r in regions])
    for region, out in zip(regions, res):
        iv = resolve_interval(region, header.ref_names)
        want = []
        for rec in open_cram(path).records():
            if rec.rname != iv.rname:
                continue
            end1 = rec.pos + max(_ref_span_of_cigar(rec.cigar, rec.seq),
                                 1) - 1
            if rec.pos <= iv.end and end1 >= iv.start:
                want.append(rec.to_line())
        assert [r.to_line() for r in out.records] == want, region
    assert sum(len(r.records) for r in res) > 0


def test_tensor_batches_mask_agrees_with_records(indexed_bam):
    import jax

    from hadoop_bam_tpu.api import query_regions

    path, _header = indexed_bam
    engine = QueryEngine()
    res = engine.query_records(
        [QueryRequest(path, r) for r in _BAM_REGIONS])
    total = 0
    for out in query_regions(path, _BAM_REGIONS, engine=engine):
        assert isinstance(out["keep"], jax.Array)   # mesh-computed mask
        total += int(np.asarray(out["keep"]).sum())
    assert total == sum(len(r.records) for r in res)


# ---------------------------------------------------------------------------
# coalescing + cache behavior
# ---------------------------------------------------------------------------

def test_overlapping_requests_share_chunk_decodes(indexed_bam):
    path, _header = indexed_bam
    engine = QueryEngine()
    batch = [
        QueryRequest(path, "chr1:10000-60000"),
        QueryRequest(path, "chr1:30000-90000"),
        QueryRequest(path, "chr1:10000-60000"),   # exact duplicate
    ]
    before = METRICS.get("query.chunks_decoded")
    engine.query_records(batch)
    first = METRICS.get("query.chunks_decoded") - before
    # three overlapping requests coalesce into ONE decoded chunk set —
    # never one decode per request
    assert 1 <= first < len(batch)
    # the identical batch again: fully warm, zero fresh decodes (chunk
    # identity = the batch's coalesced ranges + file identity, so
    # repeated queries — the zipf-hot serving shape — always hit)
    before = METRICS.get("query.chunks_decoded")
    engine.query_records(batch)
    assert METRICS.get("query.chunks_decoded") == before
    # a single hot region repeated as its own batch also self-hits
    solo = [QueryRequest(path, "chr1:10000-60000")]
    engine.query_records(solo)
    before = METRICS.get("query.chunks_decoded")
    engine.query_records(solo)
    assert METRICS.get("query.chunks_decoded") == before
    assert engine.stats()["hits"] > 0


def test_same_file_through_two_path_spellings(indexed_bam):
    """Two spellings of one file (absolute vs relative) share one file
    identity — ranges must ACCUMULATE per identity, not overwrite per
    path string (review finding: the second spelling's chunk set used to
    replace the first's, silently emptying its results)."""
    path, header = indexed_bam
    rel = os.path.relpath(path)
    assert rel != path and os.path.abspath(rel) == path
    res = QueryEngine().query_records([
        QueryRequest(path, "chr1:1000-200000"),
        QueryRequest(rel, "chr2:1-300000"),
    ])
    assert [r.to_line() for r in res[0].records] == \
        _bam_oracle(path, header, "chr1:1000-200000")
    assert [r.to_line() for r in res[1].records] == \
        _bam_oracle(path, header, "chr2:1-300000")
    assert res[0].records and res[1].records


def test_coalesce_gap_arithmetic_per_kind(indexed_bam):
    path, _header = indexed_bam
    engine = QueryEngine()
    v = lambda c, u=0: (c << 16) | u
    # voffset ranges 8 KiB apart compressed: coalesce into one chunk
    merged = engine._coalesce([(v(0), v(4096)), (v(12288), v(16384))],
                              "bam")
    assert merged == [(v(0), v(16384))]
    # raw CRAM byte ranges 1 MiB apart must NOT merge (>>16 on raw bytes
    # used to shrink the gap 65536x and coalesce across whole files)
    apart = [(0, 4096), (1 << 20, (1 << 20) + 4096)]
    assert engine._coalesce(apart, "cram") == apart
    # ...but 8 KiB apart in raw bytes still merges
    near = [(0, 4096), (12288, 16384)]
    assert engine._coalesce(near, "cram") == [(0, 16384)]


def test_skip_bad_spans_serves_quarantined_chunk_as_empty(indexed_bam):
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on

    path, header = indexed_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=0)
    engine = QueryEngine(config=cfg)
    engine.query_records([QueryRequest(path, "chr1:1-2000")])  # meta warm
    region = "chr2:500000-700000"
    before = METRICS.get("query.chunks_skipped")
    with chaos_on(path, [FaultSpec("bitflip", at_read=0, count=64,
                                   xor_mask=0xFF)]):
        res = engine.query_records([QueryRequest(path, region)])
    assert res[0].records == []                # skipped, not crashed
    assert METRICS.get("query.chunks_skipped") > before
    # nothing poisonous cached: the same region heals once chaos is off
    res = engine.query_records([QueryRequest(path, region)])
    assert [r.to_line() for r in res[0].records] == \
        _bam_oracle(path, header, region)


def test_cache_stats_are_per_instance():
    a, b = ChunkCache(1 << 20), ChunkCache(1 << 20)
    a.put(("k",), "v", 10)
    a.get(("k",))
    b.get(("absent",))
    assert a.stats()["hits"] == 1 and a.stats()["misses"] == 0
    assert b.stats()["hits"] == 0 and b.stats()["misses"] == 1


def test_cache_invalidation_on_mtime_change(tmp_path):
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    path = str(tmp_path / "inval.bam")
    header = make_header(1)

    def build(seed, n):
        recs = _coord_sorted(header, make_records(header, n, seed=seed))
        with BamWriter(path, header) as w:
            for r in recs:
                w.write_sam_record(r)
        write_bai(path)

    build(1, 400)
    engine = QueryEngine()
    region = "chr1:1-1000000"
    first = engine.query_records([QueryRequest(path, region)])[0]
    assert first.records

    build(2, 150)    # replace the file in place
    # force a visible mtime bump even on coarse-grained filesystems
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    second = engine.query_records([QueryRequest(path, region)])[0]
    assert [r.to_line() for r in second.records] == \
        _bam_oracle(path, header, region)
    assert [r.to_line() for r in second.records] != \
        [r.to_line() for r in first.records]


def test_chunk_cache_budget_evicts_lru():
    cache = ChunkCache(byte_budget=100)
    cache.put(("a",), "A", 60)
    cache.put(("b",), "B", 30)
    assert cache.get(("a",)) == "A"          # refresh a: b becomes LRU
    cache.put(("c",), "C", 40)               # evicts b (then maybe a)
    assert cache.get(("b",)) is None
    assert cache.bytes_used <= 100
    # an entry larger than the whole budget is never admitted
    cache.put(("huge",), "X", 1000)
    assert cache.get(("huge",)) is None


def test_chunk_cache_rejects_bad_budget():
    with pytest.raises(PlanError):
        ChunkCache(byte_budget=0)


def test_file_identity_changes_with_content(tmp_path):
    p = tmp_path / "f.bin"
    p.write_bytes(b"one")
    a = file_identity(p)
    p.write_bytes(b"three!")
    st = os.stat(p)
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    b = file_identity(p)
    assert a != b
    with pytest.raises(FileNotFoundError):   # PLAN class downstream
        file_identity(tmp_path / "missing.bin")


# ---------------------------------------------------------------------------
# admission control + deadlines (PR-1 taxonomy)
# ---------------------------------------------------------------------------

def test_admission_rejects_when_saturated():
    sched = QueryScheduler(max_in_flight=1, queue_depth=0)
    before = METRICS.get("query.rejected")
    with sched.admit():
        assert sched.in_flight == 1
        with pytest.raises(TransientIOError):
            with sched.admit():
                pass
    assert METRICS.get("query.rejected") == before + 1
    # slot freed: admission works again
    with sched.admit():
        pass


def test_admission_wait_deadline_expires_with_injected_clock():
    t = [0.0]

    def clock():
        t[0] += 0.5              # every look at the clock advances it
        return t[0]

    sched = QueryScheduler(max_in_flight=1, queue_depth=4,
                           default_deadline_s=1.0, clock=clock)
    with sched.admit():
        with pytest.raises(TransientIOError):
            with sched.admit():          # waits, then blows the deadline
                pass


def test_query_deadline_raises_transient(indexed_bam):
    path, _header = indexed_bam
    engine = QueryEngine(scheduler=QueryScheduler(default_deadline_s=0.0))
    before = METRICS.get("query.deadline_exceeded")
    with pytest.raises(TransientIOError):
        engine.query_records([QueryRequest(path, "chr1:1-100")])
    assert METRICS.get("query.deadline_exceeded") == before + 1


def test_per_request_deadline_override(indexed_bam):
    path, _header = indexed_bam
    engine = QueryEngine()          # no batch deadline at all
    with pytest.raises(TransientIOError):
        engine.query_records(
            [QueryRequest(path, "chr1:1-100", deadline_s=0.0)])


def test_scheduler_bad_parameters_are_plan_errors():
    with pytest.raises(PlanError):
        QueryScheduler(max_in_flight=0)
    with pytest.raises(PlanError):
        QueryScheduler(queue_depth=-1)
    with pytest.raises(PlanError):
        QueryScheduler(default_deadline_s=-1.0)


def test_missing_index_is_plan_error(tmp_path):
    from hadoop_bam_tpu.formats.bamio import BamWriter

    path = str(tmp_path / "noindex.bam")
    header = make_header(1)
    with BamWriter(path, header) as w:
        for r in _coord_sorted(header, make_records(header, 20, seed=5)):
            w.write_sam_record(r)
    with pytest.raises(PlanError, match="bai"):
        QueryEngine().query_records([QueryRequest(path, "chr1:1-100")])


def test_unknown_contig_and_container_are_plan_errors(indexed_bam,
                                                      tmp_path):
    path, _header = indexed_bam
    with pytest.raises(PlanError, match="reference dictionary"):
        QueryEngine().query_records([QueryRequest(path, "chrZ:1-100")])
    other = tmp_path / "x.fastq"
    other.write_text("@r\nACGT\n+\n!!!!\n")
    with pytest.raises(PlanError, match="region-query"):
        QueryEngine().query_records(
            [QueryRequest(str(other), "chr1:1-100")])


# ---------------------------------------------------------------------------
# fault injection through the classified retry policy
# ---------------------------------------------------------------------------

def test_transient_chunk_faults_heal_under_retry(indexed_bam):
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on

    path, header = indexed_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=3,
                              retry_backoff_base_s=0.001,
                              retry_backoff_max_s=0.002)
    engine = QueryEngine(config=cfg)
    # resolve metadata cleanly first (header/index reads are not under
    # the span-retry policy; only chunk decodes are)
    engine.query_records([QueryRequest(path, "chr1:1-2000")])
    region = "chr2:1-120000"       # cold chunk for the faulted pass
    with chaos_on(path, [FaultSpec("transient", at_read=0, count=2)]):
        res = engine.query_records([QueryRequest(path, region)])
    assert [r.to_line() for r in res[0].records] == \
        _bam_oracle(path, header, region)


def test_corrupt_chunk_fails_fast(indexed_bam):
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on

    path, _header = indexed_bam
    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=3,
                              retry_backoff_base_s=0.001,
                              retry_backoff_max_s=0.002)
    engine = QueryEngine(config=cfg)
    engine.query_records([QueryRequest(path, "chr1:1-2000")])
    before = METRICS.get("pipeline.transient_retries")
    with chaos_on(path, [FaultSpec("bitflip", at_read=0, count=64,
                                   xor_mask=0xFF)]):
        with pytest.raises((CorruptDataError, ValueError)):
            engine.query_records(
                [QueryRequest(path, "chr2:200000-400000")])
    # corruption is never retried: zero transient re-attempts burned
    assert METRICS.get("pipeline.transient_retries") == before
