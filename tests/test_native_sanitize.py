"""Sanitizer pass over the native C++ helper (SURVEY.md section 5, race
detection/sanitizers row).

The reference's Java got memory safety from the JVM; our native library
(native/hbam_native.cpp) has threads and raw offset arithmetic, so every
exported entry point is exercised here under AddressSanitizer AND
ThreadSanitizer: the library is rebuilt with -fsanitize=<mode> and
driven from a subprocess that preloads the matching runtime (a
non-instrumented python can only host an instrumented .so via
LD_PRELOAD).  The driver uses explicit n_threads=4 calls so both
sanitizers see the pthread batch loops.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The subprocess body: build fixtures in memory and push them through every
# native entry point (inflate, CRC, record walks, packed/payload walks,
# deflate, rANS 4x8 + Nx16, DEFLATE tokenize).  Multi-threaded calls are
# explicit so ASan sees the pthread paths.  It then drives the two
# Python-threaded planes TSan should watch end to end: the staging
# packer (FeedPipeline's pack thread racing the dispatch consumer over
# reused ring slots) and a two-replica serving fleet over real TCP
# (handler threads + heartbeat + decode pool + peer fetch).
DRIVER = r"""
import io, random, sys
import numpy as np
from hadoop_bam_tpu.utils import native
assert native.available(), "sanitized native build failed to load"

from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.sam import SamRecord

header = SAMHeader.from_sam_text("@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:1000000\n")
rng = random.Random(7)
sink = io.BytesIO()
with BamWriter(sink, header) as w:
    for i in range(400):
        l = rng.randint(30, 150)
        w.write_sam_record(SamRecord(
            qname=f"r{i}", flag=rng.choice([0, 16, 99]), rname="chr1",
            pos=1 + i * 10, mapq=60, cigar=f"{l}M", rnext="=",
            pnext=1 + i, tlen=200,
            seq="".join(rng.choice("ACGT") for _ in range(l)),
            qual="".join(chr(33 + rng.randint(2, 40)) for _ in range(l))))
raw = sink.getvalue()

from hadoop_bam_tpu.ops import inflate as inflate_ops
table = inflate_ops.block_table(raw)
data, ubase = inflate_ops.inflate_span(raw, table, backend="native",
                                       n_threads=4)
inflate_ops.verify_crcs(raw, table, data, ubase, n_threads=4)

hdr, after = SAMHeader.from_bam_bytes(data.tobytes())
offs, tail = native.walk_bam_records(data, after, 1024)
assert offs.size == 400, offs.size

rows, offs2, _ = native.walk_bam_packed(
    data, after, 1024, [(0, 4), (4, 4), (12, 2)], 10)
assert (offs2 == offs).all()
prefix, seq, qual, offs3, _ = native.walk_bam_payload(
    data, after, 1024, 160, 80, 160)
assert (offs3 == offs).all()

comp = native.deflate_raw(data.tobytes()[:4096], level=6)
assert comp is not None

# rANS 4x8 both orders (decode dispatches to the native loop when loaded)
from hadoop_bam_tpu.formats import cram_codecs
payload = bytes(rng.choice(b"ACGT!#") for _ in range(5000))
for order in (0, 1):
    enc = cram_codecs.rans4x8_encode(payload, order=order)
    got = cram_codecs.rans4x8_decode(enc)
    assert got == payload, order

# fused single-pass decode: 4 workers over 1-block chunks maximizes
# frontier/drain contention (inflate workers racing the walk), streamed
# consumption, the CRC fold, and the early-cancel join path
for mode, kw in (("offsets", {}),
                 ("rows", dict(sel=[(0, 4), (4, 4), (12, 2)],
                               row_stride=10)),
                 ("payload", dict(max_len=160, seq_stride=80,
                                  qual_stride=160))):
    dec = inflate_ops.FusedSpanDecode(raw, table, start=after, mode=mode,
                                      check_crc=True, chunk_blocks=1,
                                      n_threads=4, **kw)
    for _lo, _hi in dec.chunks():
        pass
    n, tail = dec.finish()
    assert n == 400 and (dec.offsets[:n] == offs).all(), (mode, n)
assert (dec.prefix[:n] == prefix).all()
assert (dec.seq[:n] == seq).all() and (dec.qual[:n] == qual).all()
cancelled = inflate_ops.FusedSpanDecode(raw, table, start=after,
                                        chunk_blocks=1, n_threads=4)
g = cancelled.chunks()
next(g)
g.close()          # join while workers may still be inflating
assert cancelled.n_rows is not None

# DEFLATE tokenize (host half of the device inflate), threaded
src = np.frombuffer(raw, dtype=np.uint8)
tokens, n_tokens, out_lens = native.deflate_tokenize_batch(
    src, table["cdata_off"], table["cdata_len"],
    int(table["isize"].max()) + 16, n_threads=4)
assert (out_lens == table["isize"]).all()

# tokenize with the CRC fold (thread-local resolve scratch under ASan/
# TSan: each worker resolves its blocks into its own growable buffer)
toks_c, nt_c, ol_c, crcs = native.deflate_tokenize_batch(
    src, table["cdata_off"], table["cdata_len"],
    int(table["isize"].max()) + 16, n_threads=4, with_crc=True)
assert (ol_c == table["isize"]).all()
assert (crcs == inflate_ops.footer_crcs(src, table)).all()

# batch ITF8 (CRAM fixed-series predecode), incl. the truncation path
from hadoop_bam_tpu.formats.cram import write_itf8
vals = [0, 1, 127, 128, 16383, 2**28, -1] * 50
itf = np.frombuffer(b"".join(write_itf8(v) for v in vals), np.uint8)
got, used = native.itf8_decode_batch(itf, len(vals))
assert [int(v) for v in got] == vals and used == itf.size
try:
    native.itf8_decode_batch(itf[:3], 7)
    raise AssertionError("truncated ITF8 did not raise")
except ValueError:
    pass

# staging packer: the FeedPipeline's background pack thread races the
# dispatching consumer over reused ring slots — drive it with a host
# dispatch so the sanitizer watches the lease/release handoff itself
from hadoop_bam_tpu.parallel.staging import FeedPipeline, TileSpec
specs = (TileSpec((4,), np.uint8, 0), TileSpec((), np.int32, 0))
spans = []
total_rows = 0
for i in range(40):
    n = rng.randint(1, 30)
    total_rows += n
    spans.append((np.full((n, 4), i % 251, np.uint8),
                  np.arange(n, dtype=np.int32)))
fp = FeedPipeline(3, 16, specs, block_n=4, ring_slots=2,
                  dispatch_depth=2)
seen = []
fp.feed(iter(spans), lambda arrays, counts: seen.append(int(counts.sum())))
assert sum(seen) == total_rows, (sum(seen), total_rows)

# serve/fleet peer fetch: two in-process replicas over real TCP.  Each
# side runs TCP handler threads, the heartbeat loop and the shared
# decode pool, and replication=1 over two replicas forces peer fetches
# — the whole fleet thread topology drives the native decode at once.
import dataclasses, os, socket, tempfile, threading
from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.query import QueryEngine, QueryRequest
from hadoop_bam_tpu.serve import ServeLoop, make_tcp_server
from hadoop_bam_tpu.split.bai import write_bai

tmpdir = tempfile.mkdtemp()
bam_path = os.path.join(tmpdir, "f.bam")
with open(bam_path, "wb") as fh:
    fh.write(raw)
write_bai(bam_path)
regions = ["chr1:1-2000", "chr1:2001-4100"]
oracle = [len(r.records) for r in QueryEngine().query_records(
    [QueryRequest(bam_path, rg) for rg in regions])]

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

p1, p2 = _free_port(), _free_port()
peer_spec = f"r1=127.0.0.1:{p1},r2=127.0.0.1:{p2}"
loops, servers, sthreads = [], [], []
for rid, port in (("r1", p1), ("r2", p2)):
    cfg = dataclasses.replace(
        DEFAULT_CONFIG, serve_replica_id=rid, serve_peers=peer_spec,
        fleet_replication=1, fleet_heartbeat_s=0.1,
        serve_prefetch=False)
    loop = ServeLoop(config=cfg)
    loop.start()
    srv = make_tcp_server(loop, host="127.0.0.1", port=port)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    loops.append(loop)
    servers.append(srv)
    sthreads.append(t)
try:
    counts1 = [r.count for r in loops[0].query(bam_path, regions)]
    counts2 = [r.count for r in loops[1].query(bam_path, regions)]
    assert counts1 == counts2 == oracle, (counts1, counts2, oracle)
    fl1, fl2 = loops[0].fleet, loops[1].fleet
    assert fl1.peer_fetch_ok + fl2.peer_fetch_ok > 0
    assert fl1.peer_fetch_failed == fl2.peer_fetch_failed == 0
finally:
    for srv in servers:
        srv.shutdown()
        srv.server_close()
    for loop in loops:
        loop.stop()
    for t in sthreads:
        t.join(5.0)
print("SANITIZED-OK")
"""


def _san_runtime(lib):
    try:
        out = subprocess.run(["g++", f"-print-file-name={lib}"],
                             capture_output=True, text=True, timeout=30)
    except Exception:
        return None
    path = out.stdout.strip()
    return path if path and os.path.sep in path and os.path.exists(path) \
        else None


# Races/interceptor noise inside the uninstrumented jax/numpy runtime
# libraries (XLA's Eigen thread pool handing buffers to numpy memcpy,
# MLIR thread-local cache teardown) are theirs, not ours: suppress by
# module so findings in native/hbam_native.cpp still fail the test.
_TSAN_SUPPRESSIONS = """\
race:xla_extension.so
race:libjaxlib_mlir_capi.so
race:_mlir.so
race:_multiarray_umath
called_from_lib:xla_extension.so
called_from_lib:libjaxlib_mlir_capi.so
"""


@pytest.mark.parametrize("mode,lib,marker", [
    ("address", "libasan.so", "AddressSanitizer"),
    ("thread", "libtsan.so", "ThreadSanitizer"),
])
def test_native_sanitized_clean(mode, lib, marker, tmp_path):
    runtime = _san_runtime(lib)
    if runtime is None:
        pytest.skip(f"g++/{lib} not available")
    # preload libstdc++ WITH the sanitizer runtime: the interceptors
    # resolve __cxa_throw at startup, before jaxlib's pybind modules
    # (which throw C++ exceptions) are dlopened — without it ASan
    # aborts on "real___cxa_throw != 0" the first time jax raises
    stdcxx = _san_runtime("libstdc++.so.6")
    preload = f"{runtime} {stdcxx}" if stdcxx else runtime
    supp = tmp_path / "tsan.supp"
    supp.write_text(_TSAN_SUPPRESSIONS)
    env = dict(os.environ)
    env.update({
        "HBAM_NATIVE_SANITIZE": mode,
        "LD_PRELOAD": preload,
        # CPython itself "leaks" interned objects; only instrument our .so's
        # heap errors, overflows, and races with the preloaded runtime.
        "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
        # CPython's own lock usage is not what we're testing — disable the
        # deadlock detector and mutex-misuse reports (the libgcc unwinder
        # and XLA's pool trip bogus ones from uninstrumented code); data
        # races in the .so's threaded batch loops still abort via
        # halt_on_error
        "TSAN_OPTIONS": "detect_deadlocks=0:report_signal_unsafe=0:"
                        "report_mutex_bugs=0:halt_on_error=1:"
                        f"suppressions={supp}",
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.run([sys.executable, "-c", DRIVER], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != 0 and "SANITIZED-OK" not in proc.stdout \
            and "unexpected memory mapping" in proc.stderr:
        # TSan refusing to initialize under LD_PRELOAD into an
        # uninstrumented interpreter (ASLR layout) is a host problem,
        # not a sanitizer finding
        pytest.skip(f"{lib} failed to initialize on this host")
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "SANITIZED-OK" in proc.stdout
    assert marker not in proc.stderr, proc.stderr[-4000:]
