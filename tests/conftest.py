"""Test configuration: force an 8-device virtual CPU platform for mesh tests.

The reference's test strategy (SURVEY.md section 4) never spins up a cluster: it
exercises the InputFormat/RecordReader *interfaces* in-process.  We adopt the
same philosophy — all distributed logic is tested on a virtual 8-device CPU
mesh, and correctness of split planning is tested with every-byte-offset
property tests.
"""
import os

# Must be set before jax initializes its backends.  The environment may pin
# JAX_PLATFORMS to a TPU plugin (and the plugin ignores the env override), so
# force the platform through jax.config instead.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.pop("JAX_PLATFORMS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    return jax.devices("cpu")


@pytest.fixture(autouse=True)
def _pristine_resilience():
    """Every test starts with closed breakers, empty fault domains and
    no armed chaos points — adaptive state (an OPEN native-plane breaker
    from a corruption test, say) must never leak into the next test's
    plane selection."""
    from hadoop_bam_tpu import resilience

    resilience.reset()
    resilience.chaos.clear_fault_points()
    yield
    resilience.reset()
    resilience.chaos.clear_fault_points()
