"""Device CIGAR geometry tests: tile unpack, reference spans (parity with
the host BamBatch), and window coverage vs a pure-Python pileup oracle.
"""
import random

import numpy as np
import pytest

from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.sam import SamRecord

from fixtures import make_header

_OPS = "MIDNSHP=X"


def _random_cigar(rng, read_len):
    """A messy but legal cigar consuming exactly read_len query bases."""
    parts = []
    q = 0
    if rng.random() < 0.3:
        c = rng.randint(1, 5)
        parts.append((c, "S"))
        q += c
    while q < read_len:
        op = rng.choice("MMMM=XIDN")
        ln = min(rng.randint(1, 40), read_len - q) \
            if op in "MI=XS" else rng.randint(1, 30)
        if ln == 0:
            continue
        parts.append((ln, op))
        if op in "MI=XS":
            q += ln
    if rng.random() < 0.2 and q < read_len + 1:
        pass
    return "".join(f"{l}{o}" for l, o in parts), q


def _make_bam(tmp_path, n=400, seed=0):
    header = make_header()
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        read_len = rng.randint(20, 80)
        unmapped = rng.random() < 0.15
        other_ref = rng.random() < 0.2
        cigar, qlen = _random_cigar(rng, read_len)
        seq = "".join(rng.choice("ACGT") for _ in range(qlen))
        qual = "I" * qlen
        recs.append(SamRecord(
            qname=f"r{i}", flag=4 if unmapped else 0,
            rname="*" if unmapped else
            (header.ref_names[1] if other_ref else header.ref_names[0]),
            pos=0 if unmapped else rng.randint(1, 5000), mapq=30,
            cigar="*" if unmapped else cigar, rnext="*", pnext=0, tlen=0,
            seq=seq, qual=qual))
    path = str(tmp_path / "c.bam")
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path, header, recs


def _batch_of(path, header):
    from hadoop_bam_tpu.api.dataset import open_bam
    ds = open_bam(path)
    batches = list(ds.batches())
    assert len(batches) == 1
    return batches[0]


def test_reference_span_parity(tmp_path):
    import jax.numpy as jnp

    from hadoop_bam_tpu.ops.cigar import (
        reference_span_from_tiles, unpack_cigar_tiles,
    )
    path, header, recs = _make_bam(tmp_path, seed=1)
    b = _batch_of(path, header)
    host = b.reference_span()
    tiles = unpack_cigar_tiles(
        jnp.asarray(b.data), jnp.asarray(b.offsets.astype(np.int32)),
        jnp.asarray(b.l_read_name.astype(np.int32)),
        jnp.asarray(b.n_cigar.astype(np.int32)), max_cigar=64)
    dev = reference_span_from_tiles(
        tiles, jnp.asarray(b.n_cigar.astype(np.int32)),
        jnp.asarray(b.l_seq.astype(np.int32)))
    assert np.asarray(dev).tolist() == host.tolist()


def _oracle_depth(recs, header, rname, win_start0, window):
    depth = np.zeros(window, dtype=np.int64)
    for r in recs:
        if r.flag & 4 or r.rname != rname or r.cigar == "*":
            continue
        ref = r.pos - 1            # 0-based cursor
        i = 0
        num = ""
        for ch in r.cigar:
            if ch.isdigit():
                num += ch
                continue
            ln = int(num)
            num = ""
            if ch in "M=X":
                s = max(ref - win_start0, 0)
                e = min(ref + ln - win_start0, window)
                if e > s:
                    depth[s:e] += 1
                ref += ln
            elif ch in "DN":
                ref += ln
        assert num == ""
    return depth


@pytest.mark.parametrize("region", ["1-6000", "901-1400", "4900-8000"])
def test_window_coverage_matches_oracle(tmp_path, region):
    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    path, header, recs = _make_bam(tmp_path, n=500, seed=2)
    rname = header.ref_names[0]
    depth = coverage_file(path, f"{rname}:{region}")
    lo, hi = (int(x) for x in region.split("-"))
    want = _oracle_depth(recs, header, rname, lo - 1, hi - lo + 1)
    assert depth.tolist() == want.tolist()
    assert want.sum() > 0       # the fixture really covers the window
    # and past-the-alignments tail really is zero (window clamp is exact)
    assert coverage_file(path, f"{rname}:6000-6200").sum() == 0


def test_coverage_interval_object_and_errors(tmp_path):
    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    from hadoop_bam_tpu.split.intervals import Interval
    path, header, recs = _make_bam(tmp_path, n=100, seed=3)
    rname = header.ref_names[0]
    d = coverage_file(path, Interval(rname, 1, 1000))
    assert d.shape == (1000,)
    with pytest.raises(ValueError, match="not in header"):
        coverage_file(path, "nope:1-100")


def test_coverage_max_cigar_guard(tmp_path):
    """A record with more ops than the tile width must raise, not silently
    under-count."""
    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    header = make_header()
    cigar = "1M1I" * 40 + "1M"          # 81 ops
    seq = "A" * 41 + "C" * 40
    path = str(tmp_path / "wide.bam")
    with BamWriter(path, header) as w:
        w.write_sam_record(SamRecord(
            qname="w", flag=0, rname=header.ref_names[0], pos=100,
            mapq=30, cigar=cigar, rnext="*", pnext=0, tlen=0,
            seq=seq, qual="I" * len(seq)))
    with pytest.raises(ValueError, match="max_cigar"):
        coverage_file(path, f"{header.ref_names[0]}:1-500", max_cigar=64)
    d = coverage_file(path, f"{header.ref_names[0]}:1-500", max_cigar=96)
    assert int(d.sum()) == 41           # only the M bases add depth


def test_coverage_high_positions(tmp_path):
    """Regression: the packed row layout once shipped the BAM 'bin' field
    (bytes 14:16) where the kernel expected FLAG (bytes 18:20); for
    positions >= 49152 reg2bin sets bit 2, so mapped reads masked as
    unmapped and depth silently dropped to zero.  Pin coverage at high
    coordinates against the oracle."""
    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    header = make_header()
    rng = random.Random(8)
    recs = []
    for i in range(300):
        l = rng.randint(30, 80)
        recs.append(SamRecord(
            qname=f"h{i}", flag=0, rname=header.ref_names[0],
            pos=rng.randint(50_000, 80_000), mapq=30, cigar=f"{l}M",
            rnext="*", pnext=0, tlen=0, seq="A" * l, qual="I" * l))
    path = str(tmp_path / "high.bam")
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    depth = coverage_file(path, f"{header.ref_names[0]}:50,000-81,000")
    want = _oracle_depth(recs, header, header.ref_names[0], 49_999, 31_001)
    assert depth.tolist() == want.tolist()
    assert want.sum() > 0


def test_unpack_cigar_tiles_tiny_buffer():
    """A data buffer shorter than one cigar word must not produce
    out-of-range gathers (clip upper bound used to go negative)."""
    import jax.numpy as jnp

    from hadoop_bam_tpu.ops.cigar import unpack_cigar_tiles

    for n_bytes in (0, 1, 3):
        data = jnp.zeros((n_bytes,), jnp.uint8)
        tiles = unpack_cigar_tiles(
            data, jnp.zeros((2,), jnp.int32), jnp.full((2,), 5, jnp.int32),
            jnp.zeros((2,), jnp.int32), max_cigar=4)
        assert tiles.shape == (2, 4)
        assert int(np.asarray(tiles).sum()) == 0
