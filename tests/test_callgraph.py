"""Unit tests for the shared interprocedural engine
(``analysis/callgraph.py``): thread-root discovery, call resolution,
guard inference (lexical + entry-guard fixpoint), closure-escape
reasoning and lock-order cycle detection.

The engine underlies both the TS1xx taint rules and the TH1xx/LK2xx
thread-safety rules, so its behavior is pinned here independently of
any one analyzer (the analyzer-level corpus lives in test_lint.py).
"""
import pytest

from hadoop_bam_tpu.analysis.callgraph import (
    CallGraphEngine, find_lock_cycles, format_access_id,
)
from hadoop_bam_tpu.analysis.core import Project

pytestmark = pytest.mark.lint

SCOPE = ("hadoop_bam_tpu/serve",)


def engine(sources, scope=SCOPE):
    return CallGraphEngine(Project.from_sources(sources), scope)


# ---------------------------------------------------------------------------
# thread-root discovery
# ---------------------------------------------------------------------------

_SPAWNS = '''
import contextvars
import threading


def tick():
    pass


def pump():
    pass


def fire():
    pass


def work(x):
    pass


def mapper(x):
    pass


def done(fut):
    pass


def handle_stream(conn):
    pass


def spawn(pool, executor, fut):
    ctx = contextvars.copy_context()
    threading.Thread(target=ctx.run, args=(tick,), daemon=True).start()
    threading.Thread(target=lambda: ctx.run(pump), daemon=True).start()
    threading.Timer(5.0, fire).start()
    pool.submit(work, 1)
    executor.map(mapper, [1])
    fut.add_done_callback(done)


class Loop:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
'''


def test_thread_root_discovery_all_spawn_forms():
    eng = engine({"hadoop_bam_tpu/serve/mod.py": _SPAWNS})
    got = {(r.key[1], r.kind) for r in eng.thread_roots()}
    assert got == {
        ("tick", "thread"),            # Thread(target=ctx.run, args=(f,))
        ("pump", "thread"),            # Thread(target=lambda: ctx.run(f))
        ("fire", "thread"),            # Timer(interval, f)
        ("work", "pool"),              # pool.submit(f, ...)
        ("mapper", "pool"),            # executor.map(f, items)
        ("done", "callback"),          # fut.add_done_callback(f)
        ("handle_stream", "handler"),  # named TCP handler root
        ("Loop._run", "thread"),       # Thread(target=self._method)
    }
    assert all(r.name == f"serve/mod.py:{r.key[1]}"
               for r in eng.thread_roots())


def test_client_entries_exclude_roots_and_private_helpers():
    eng = engine({"hadoop_bam_tpu/serve/mod.py": _SPAWNS})
    got = {k[1] for k in eng.client_entries()}
    # public surface only: root targets, _helpers and nested functions
    # are all excluded from the synthetic 'client' root
    assert got == {"spawn", "Loop.start"}


def test_scope_selects_modules():
    eng = engine({
        "hadoop_bam_tpu/serve/a.py": "def f():\n    pass\n",
        "hadoop_bam_tpu/formats/b.py": "def g():\n    pass\n",
    })
    assert set(eng.indices) == {"hadoop_bam_tpu/serve/a.py"}


def test_reachable_follows_calls_across_modules():
    eng = engine({
        "hadoop_bam_tpu/serve/a.py": '''
from hadoop_bam_tpu.serve.b import helper


def entry():
    helper()
''',
        "hadoop_bam_tpu/serve/b.py": '''
def helper():
    _deep()


def _deep():
    pass
''',
    })
    got = eng.reachable([("hadoop_bam_tpu/serve/a.py", "entry")])
    assert ("hadoop_bam_tpu/serve/b.py", "helper") in got
    assert ("hadoop_bam_tpu/serve/b.py", "_deep") in got


# ---------------------------------------------------------------------------
# guard inference
# ---------------------------------------------------------------------------

_GUARDS = '''
import threading

_LOCK = threading.Lock()
_N = 0


def _bump():
    global _N
    _N += 1


def add():
    with _LOCK:
        _bump()


def sub():
    with _LOCK:
        _bump()


def _spawn():
    threading.Thread(target=_loop).start()


def _loop():
    while True:
        add()
'''


def test_entry_guard_intersection_over_call_sites():
    path = "hadoop_bam_tpu/serve/g.py"
    eng = engine({path: _GUARDS})
    lock = ("global", path, "_LOCK")
    # every resolvable call site of _bump (add, sub — lexically; _loop
    # -> add — via the fixpoint) holds _LOCK, so _bump's write to _N is
    # guarded at entry with no `with` of its own
    assert eng.entry_guards()[(path, "_bump")] == frozenset({lock})


def test_entry_guard_dropped_by_one_unguarded_call_site():
    path = "hadoop_bam_tpu/serve/g.py"
    src = _GUARDS + '''

def reset():
    _bump()
'''
    eng = engine({path: src})
    assert eng.entry_guards()[(path, "_bump")] == frozenset()


def test_effective_guards_on_write_accesses():
    path = "hadoop_bam_tpu/serve/g.py"
    eng = engine({path: _GUARDS})
    lock = ("global", path, "_LOCK")
    writes = [a for a in eng.accesses_of((path, "_bump"))
              if a.kind == "write"
              and a.target == ("global", path, "_N")]
    assert writes, "the global AugAssign under `global` must register"
    assert all(eng.effective_guards(a) == frozenset({lock})
               for a in writes)


# ---------------------------------------------------------------------------
# closure-escape reasoning
# ---------------------------------------------------------------------------

_CLOSURE = '''
import threading


def owner():
    buf = []

    def _worker():
        buf.append(1)

    threading.Thread(target=_worker).start()
    return buf


def other():
    buf = []
    buf.append(2)
    return buf
'''


def test_closure_escape_requires_nested_spawn():
    path = "hadoop_bam_tpu/serve/c.py"
    eng = engine({path: _CLOSURE})
    # owner hands its cell to a thread spawned INSIDE itself: shared
    assert eng.closure_escapes_to_thread(("closure", path, "owner",
                                          "buf"))
    # other's cell is per-invocation; no nested spawn, never shared
    assert not eng.closure_escapes_to_thread(("closure", path, "other",
                                              "buf"))
    # non-closure identities are always shareable
    assert eng.closure_escapes_to_thread(("attr", "Fleet", "_n"))
    assert eng.closure_escapes_to_thread(("global", path, "_N"))


# ---------------------------------------------------------------------------
# lock-order cycles
# ---------------------------------------------------------------------------

def test_find_lock_cycles_unit():
    a = ("attr", "C", "_a")
    b = ("attr", "C", "_b")
    c = ("attr", "C", "_c")
    assert find_lock_cycles({}) == []
    assert find_lock_cycles({(a, b): ("p", 1), (b, c): ("p", 2)}) == []
    assert find_lock_cycles({(a, b): ("p", 1),
                             (b, a): ("p", 2)}) == [[a, b]]
    # 3-cycle reported once, rotated to start at its smallest lock
    assert find_lock_cycles({(b, c): ("p", 1), (c, a): ("p", 2),
                             (a, b): ("p", 3)}) == [[a, b, c]]


_LK_INTER = '''
import threading


class P:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._a:
            self._inner()

    def _inner(self):
        with self._b:
            pass

    def poke(self):
        with self._b:
            with self._a:
                pass
'''


def test_lock_order_edges_cross_function():
    path = "hadoop_bam_tpu/serve/lk.py"
    eng = engine({path: _LK_INTER})
    a = ("attr", "P", "_a")
    b = ("attr", "P", "_b")
    edges = eng.lock_order_edges()
    # a->b comes only from the INTERPROCEDURAL hold: _inner acquires _b
    # while _a is held at its sole call site; b->a is lexical in poke
    assert (a, b) in edges and (b, a) in edges
    assert find_lock_cycles(edges) == [[a, b]]


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def test_format_access_id():
    assert format_access_id(("attr", "Fleet", "_mu")) == "Fleet.self._mu"
    assert format_access_id(
        ("global", "hadoop_bam_tpu/utils/pools.py", "_BG_QUEUE")
    ) == "hadoop_bam_tpu/utils/pools.py::_BG_QUEUE"
    assert format_access_id(
        ("closure", "hadoop_bam_tpu/serve/c.py", "owner", "buf")
    ) == "hadoop_bam_tpu/serve/c.py::owner.buf"
