"""Parity tests: batched device rANS decode vs the host codec oracle.

Every stream is produced by formats/cram_codecs.rans4x8_encode and must
decode identically through (a) the host decoder (NumPy or native C++) and
(b) the batched device decoder in ops/rans.py — both against the original
bytes."""
import random

import numpy as np
import pytest

from hadoop_bam_tpu.formats.cram_codecs import (
    rans4x8_decode, rans4x8_encode,
)
from hadoop_bam_tpu.ops.rans import (
    rans_decode_batch, rans_decode_batch_device,
)


def _corpus():
    rng = random.Random(42)
    out = []
    # uniform bytes
    out.append(bytes(rng.randrange(256) for _ in range(5000)))
    # skewed (quality-score-like): few symbols dominate
    out.append(bytes(rng.choice(b"FFFFFFF:,#") for _ in range(8000)))
    # runs
    out.append(b"".join(bytes([rng.randrange(4)]) * rng.randrange(1, 50)
                        for _ in range(300)))
    # tiny + tail sizes
    for n in (1, 2, 3, 4, 5, 7, 127):
        out.append(bytes(rng.randrange(256) for _ in range(n)))
    # single symbol
    out.append(b"A" * 4096)
    return out


@pytest.mark.parametrize("order", [0, 1])
def test_device_decode_matches_host(order):
    data = _corpus()
    payloads = [rans4x8_encode(d, order=order) for d in data]
    host = [rans4x8_decode(p) for p in payloads]
    dev = rans_decode_batch_device(payloads)
    for i, d in enumerate(data):
        assert host[i] == d, f"host decode broken at {i}"
        assert dev[i] == d, (
            f"device decode mismatch at stream {i} "
            f"(order {order}, len {len(d)})")


def test_mixed_order_batch():
    rng = random.Random(7)
    data, payloads = [], []
    for i in range(40):
        d = bytes(rng.choice(b"ACGTN") for _ in range(rng.randrange(0, 600)))
        data.append(d)
        payloads.append(rans4x8_encode(d, order=i % 2))
    dev = rans_decode_batch_device(payloads)
    assert dev == data
    # the dispatching wrapper agrees on both backends
    assert rans_decode_batch(payloads, backend="host") == data
    assert rans_decode_batch(payloads, backend="device") == data


def test_large_batch_chunking():
    """More streams than one device chunk (order-0 chunks at 256)."""
    rng = random.Random(3)
    data = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
            for _ in range(300)]
    payloads = [rans4x8_encode(d, order=0) for d in data]
    assert rans_decode_batch_device(payloads) == data


def test_empty_stream():
    p = rans4x8_encode(b"", order=0)
    assert rans_decode_batch_device([p]) == [b""]


def test_cram_read_through_device_backend(tmp_path, monkeypatch):
    """A CRAM written with rANS blocks reads back identically whether the
    container decodes its blocks on host or through the batched device
    path (HBAM_RANS_BACKEND=device)."""
    import random as _random

    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.api.writers import CramShardWriter
    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.sam import SamRecord

    rng = _random.Random(5)
    header = SAMHeader.from_sam_text("@HD\tVN:1.6\n@SQ\tSN:c1\tLN:100000\n")
    path = str(tmp_path / "x.cram")
    with CramShardWriter(path, header) as w:
        for i in range(500):
            n = rng.randint(40, 120)
            seq = "".join(rng.choice("ACGT") for _ in range(n))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(n))
            w.write_sam_record(SamRecord(
                qname=f"q{i}", flag=0, rname="c1", pos=10 + i * 5, mapq=60,
                cigar=f"{n}M", rnext="*", pnext=0, tlen=0, seq=seq,
                qual=qual))

    host = [(r.qname, r.pos, r.seq, r.qual)
            for r in open_cram(path).records()]
    monkeypatch.setenv("HBAM_RANS_BACKEND", "device")
    dev = [(r.qname, r.pos, r.seq, r.qual)
           for r in open_cram(path).records()]
    assert host == dev
    assert len(host) == 500


@pytest.mark.parametrize("order", [0, 1])
def test_corrupt_stream_raises_not_garbage(order):
    """A corrupt payload must raise RansError from the device path, not
    silently return junk (out-of-range gathers clamp under JAX)."""
    from hadoop_bam_tpu.formats.cram_codecs import RansError

    rng = random.Random(9)
    data = bytes(rng.choice(b"ACGTN") for _ in range(2000))
    p = bytearray(rans4x8_encode(data, order=order))
    p[-40] ^= 0xFF          # flip a renorm byte deep in the body
    with pytest.raises(RansError):
        rans_decode_batch_device([bytes(p)])


def test_truncated_out_size_raises():
    """An inflated out_size (stream claims more symbols than encoded)
    must be detected by the final-state/pointer integrity check."""
    from hadoop_bam_tpu.formats.cram_codecs import RansError

    data = b"ACGT" * 500
    p = bytearray(rans4x8_encode(data, order=0))
    p[5:9] = (len(data) + 64).to_bytes(4, "little")   # lie about out_size
    with pytest.raises(RansError):
        rans_decode_batch_device([bytes(p)])
