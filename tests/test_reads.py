"""FASTQ/QSEQ/FASTA family tests.

Mirrors test/TestFastqInputFormat.java, test/TestQseqInputFormat.java,
test/TestFastaInputFormat.java (SURVEY.md section 4): codec round-trips,
metadata parsing, and the critical every-boundary split-robustness property —
including '@' appearing as the first character of quality strings, the case
the FASTQ record heuristic exists for.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

from hadoop_bam_tpu.config import BaseQualityEncoding, HBamConfig
from hadoop_bam_tpu.api.read_datasets import (
    fragments_to_arrays, open_fasta, open_fastq, open_qseq,
)
from hadoop_bam_tpu.api.writers import FastqShardWriter, QseqShardWriter
from hadoop_bam_tpu.formats.fasta import parse_fasta
from hadoop_bam_tpu.formats.fastq import (
    SequencedFragment, convert_quality, find_fastq_record_start, parse_fastq,
)
from hadoop_bam_tpu.formats.qseq import (
    format_qseq_line, parse_qseq, parse_qseq_line,
)
from hadoop_bam_tpu.split.read_planners import read_fastq_span
from hadoop_bam_tpu.split.spans import FileByteSpan


def make_fragments(n: int, seed: int = 0):
    rng = random.Random(seed)
    frags = []
    for i in range(n):
        l = rng.randint(30, 120)
        seq = "".join(rng.choice("ACGTN") for _ in range(l))
        # qualities deliberately include '@' (64) and '+' (43) as first chars
        qual = "".join(chr(rng.choice([33 + rng.randint(0, 60), 64, 43]))
                       for _ in range(l))
        name = (f"M0:{i % 4}:FC1:1:{1000 + i}:{rng.randint(0, 9999)}:"
                f"{rng.randint(0, 9999)}")
        f = SequencedFragment.from_name(name, seq, qual)
        frags.append(f)
    return frags


@pytest.fixture(scope="module")
def fastq_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("reads")
    frags = make_fragments(300, seed=11)
    path = str(d / "r.fastq")
    with FastqShardWriter(path) as w:
        for f in frags:
            w.write_record(f)
    return path, frags


def test_name_metadata_casava18():
    f = SequencedFragment.from_name(
        "EAS139:136:FC706VJ:2:2104:15343:197393 1:Y:18:ATCACG")
    assert f.instrument == "EAS139" and f.run_number == 136
    assert f.flowcell_id == "FC706VJ" and f.lane == 2 and f.tile == 2104
    assert f.xpos == 15343 and f.ypos == 197393
    assert f.read == 1 and f.filter_passed is False
    assert f.control_number == 18 and f.index_sequence == "ATCACG"


def test_name_metadata_pre18():
    f = SequencedFragment.from_name("HWUSI-EAS100R:6:73:941:1973#ATCG/1")
    assert f.instrument == "HWUSI-EAS100R" and f.lane == 6 and f.tile == 73
    assert f.xpos == 941 and f.ypos == 1973
    assert f.index_sequence == "ATCG" and f.read == 1


def test_quality_conversion():
    sanger = "II?5+#"
    illumina = convert_quality(sanger, BaseQualityEncoding.SANGER,
                               BaseQualityEncoding.ILLUMINA)
    assert convert_quality(illumina, BaseQualityEncoding.ILLUMINA) == sanger
    assert ord(illumina[0]) - ord(sanger[0]) == 31


def test_fastq_roundtrip(fastq_file):
    path, frags = fastq_file
    text = open(path, "rb").read()
    parsed = parse_fastq(text)
    assert len(parsed) == len(frags)
    for a, b in zip(parsed, frags):
        assert a.name == b.name
        assert a.sequence == b.sequence
        assert a.quality == b.quality


def test_record_start_heuristic_vs_quality_at():
    # quality line starting with '@' must not be mistaken for a record start
    text = (b"@r1\nACGT\n+\n@@@@\n"
            b"@r2\nTTTT\n+\nIIII\n")
    # from inside the first quality line, the next record is r2 at offset 16
    start = find_fastq_record_start(text, 9)
    assert text[start:start + 3] == b"@r2"
    assert find_fastq_record_start(text, 0) == 0


@pytest.mark.parametrize("num_spans", [1, 2, 5, 9])
def test_fastq_span_union(fastq_file, num_spans):
    path, frags = fastq_file
    ds = open_fastq(path)
    got = [f.name for f in ds.records(num_spans=num_spans)]
    assert got == [f.name for f in frags]


def test_fastq_every_boundary(fastq_file):
    """Two-span split at many byte offsets: union must be exact."""
    path, frags = fastq_file
    size = len(open(path, "rb").read())
    want = [f.name for f in frags]
    rng = random.Random(5)
    cuts = sorted({1, 7, size // 2, size - 3} |
                  {rng.randrange(1, size) for _ in range(60)})
    for cut in cuts:
        a = parse_fastq(read_fastq_span(path, FileByteSpan(path, 0, cut)))
        b = parse_fastq(read_fastq_span(path, FileByteSpan(path, cut, size)))
        got = [f.name for f in a] + [f.name for f in b]
        assert got == want, f"cut={cut}"


def test_fastq_filter_failed_qc(tmp_path):
    frags = []
    for i, filt in enumerate("YNYN"):
        f = SequencedFragment.from_name(
            f"M:1:F:1:1:{i}:{i} 1:{filt}:0:AAA", "ACGT", "IIII")
        frags.append(f)
    p = str(tmp_path / "f.fastq")
    with FastqShardWriter(p) as w:
        for f in frags:
            w.write_record(f)
    ds = open_fastq(p, HBamConfig(fastq_filter_failed_qc=True))
    got = list(ds.records(num_spans=1))
    assert len(got) == 2
    assert all(f.filter_passed for f in got)


# ---------------------------------------------------------------------------
# QSEQ
# ---------------------------------------------------------------------------

def test_qseq_line_roundtrip():
    line = ("M001\t5\t1\t1101\t100\t200\tACGTAC\t1\t"
            "ACGTN.AC\tabcdefgh\t1")
    f = parse_qseq_line(line)
    assert f.sequence == "ACGTNNAC"  # '.' -> 'N'
    assert f.filter_passed is True
    assert f.read == 1 and f.lane == 1 and f.tile == 1101
    # qualities arrived Illumina(+64); canonical form is Sanger(+33)
    assert ord(f.quality[0]) == ord("a") - 31
    back = format_qseq_line(f)
    assert back.split("\t")[9] == "abcdefgh"
    assert back.split("\t")[8] == "ACGT..AC"  # N -> '.' on emit


def test_qseq_span_union(tmp_path):
    rng = random.Random(3)
    frags = make_fragments(120, seed=4)
    p = str(tmp_path / "r.qseq")
    with QseqShardWriter(p) as w:
        for f in frags:
            w.write_record(f)
    ds = open_qseq(p)
    for num_spans in (1, 3, 7):
        ds2 = open_qseq(p)
        got = [f.sequence for f in ds2.records(num_spans=num_spans)]
        assert got == [f.sequence for f in frags]


# ---------------------------------------------------------------------------
# FASTA
# ---------------------------------------------------------------------------

FASTA_TEXT = b""">chr1 test contig
ACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGT
TTTTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTTTTT
ACGT
>chr2
GGGGACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTACGTCCCC
AAAA
>chr3
CCCC
"""


def test_fasta_parse_positions():
    frags = parse_fasta(FASTA_TEXT)
    assert [f.contig for f in frags] == ["chr1"] * 3 + ["chr2"] * 2 + ["chr3"]
    assert [f.position for f in frags] == [1, 61, 121, 1, 61, 1]
    merged = parse_fasta(FASTA_TEXT, line_fragments=False)
    assert len(merged) == 3
    assert merged[0].sequence.startswith("ACGT") and len(merged[0]) == 124


def test_fasta_span_union(tmp_path):
    p = str(tmp_path / "r.fa")
    open(p, "wb").write(FASTA_TEXT)
    want = [(f.contig, f.position, f.sequence)
            for f in parse_fasta(FASTA_TEXT)]
    for num_spans in (1, 2, 3, 5):
        ds = open_fasta(p)
        got = [(f.contig, f.position, f.sequence)
               for f in ds.fragments(num_spans=num_spans)]
        assert got == want, f"num_spans={num_spans}"


# ---------------------------------------------------------------------------
# device bridge
# ---------------------------------------------------------------------------

def test_fragments_to_arrays():
    frags = make_fragments(10, seed=9)
    bases, quals, lengths = fragments_to_arrays(frags, max_len=64)
    assert bases.shape == (10, 64) and quals.shape == (10, 64)
    for i, f in enumerate(frags):
        l = min(len(f.sequence), 64)
        assert lengths[i] == l
        assert (bases[i, l:] == 5).all()
        code = {"A": 0, "C": 1, "G": 2, "T": 3, "N": 4}
        assert [code[c] for c in f.sequence[:l]] == list(bases[i, :l])


# ---------------------------------------------------------------------------
# review-regression cases
# ---------------------------------------------------------------------------

def test_crlf_fastq(tmp_path):
    frags = make_fragments(5, seed=1)
    text = "".join(f.to_fastq() for f in frags).replace("\n", "\r\n")
    p = str(tmp_path / "crlf.fastq")
    open(p, "wb").write(text.encode())
    got = list(open_fastq(p).records(num_spans=2))
    assert [g.name for g in got] == [f.name for f in frags]
    assert got[0].sequence == frags[0].sequence


def test_compressed_fastq_single_span(tmp_path):
    import gzip
    frags = make_fragments(20, seed=2)
    p = str(tmp_path / "c.fastq.gz")
    open(p, "wb").write(gzip.compress(
        "".join(f.to_fastq() for f in frags).encode()))
    ds = open_fastq(p)
    assert len(ds.spans()) == 1  # non-splittable, like Hadoop gzip codecs
    got = list(ds.records())
    assert [g.name for g in got] == [f.name for f in frags]


def test_dataset_reiteration_and_plan_conflict(fastq_file):
    path, frags = fastq_file
    ds = open_fastq(path)
    a = list(ds.records(num_spans=3))
    b = list(ds.records())  # fresh iteration after exhaustion
    assert len(a) == len(b) == len(frags)
    import pytest as _pytest
    with _pytest.raises(ValueError):
        ds.spans(num_spans=8)  # conflicting re-plan must be loud


def test_bare_fasta_header_raises():
    from hadoop_bam_tpu.formats.fasta import FastaError
    import pytest as _pytest
    with _pytest.raises(FastaError):
        parse_fasta(b">\nACGT\n")


# ---------------------------------------------------------------------------
# Vectorized FASTQ tokenize (the stats drivers' fast path)
# ---------------------------------------------------------------------------

def _tiles_via_objects(text, seq_stride, qual_stride, max_len, enc):
    from hadoop_bam_tpu.api.read_datasets import fragments_to_payload_tiles
    frags = parse_fastq(text, encoding=enc)
    return fragments_to_payload_tiles(frags, seq_stride, qual_stride,
                                      max_len)


@pytest.mark.parametrize("crlf", [False, True])
@pytest.mark.parametrize("trailing_newline", [False, True])
def test_fastq_vectorized_tiles_parity(crlf, trailing_newline):
    """fastq_text_to_payload_tiles must match the per-object path exactly:
    mixed lengths, lowercase, N/ambiguity codes, reads longer than max_len."""
    from hadoop_bam_tpu.api.read_datasets import fastq_text_to_payload_tiles
    rng = random.Random(3)
    reads = []
    for i in range(137):
        n = rng.choice([1, 2, 37, 40, 160, 161, 300])
        seq = "".join(rng.choice("ACGTNacgtnRYKM") for _ in range(n))
        qual = "".join(chr(33 + rng.randint(0, 41)) for _ in range(n))
        reads.append(f"@r{i} extra meta\n{seq}\n+\n{qual}")
    sep = "\r\n" if crlf else "\n"
    text = sep.join(r.replace("\n", sep) for r in reads)
    if trailing_newline:
        text += sep
    text = text.encode()
    enc = BaseQualityEncoding.SANGER
    for seq_stride, qual_stride, max_len in ((80, 160, 160), (16, 32, 32)):
        want = _tiles_via_objects(text, seq_stride, qual_stride, max_len,
                                  enc)
        got = fastq_text_to_payload_tiles(text, seq_stride, qual_stride,
                                          max_len)
        for w, g in zip(want, got):
            assert w.dtype == g.dtype and w.shape == g.shape
            assert (w == g).all()


def test_fastq_vectorized_tiles_illumina_offset():
    from hadoop_bam_tpu.api.read_datasets import fastq_text_to_payload_tiles
    text = b"@a\nACGT\n+\nhhhi\n"   # 'h' = Phred 40 at +64
    _, qual, lens = fastq_text_to_payload_tiles(text, 8, 8, 8,
                                                qual_offset=64)
    assert lens.tolist() == [4]
    assert qual[0, :4].tolist() == [40, 40, 40, 41]


def test_fastq_vectorized_tiles_malformed():
    from hadoop_bam_tpu.api.read_datasets import fastq_text_to_payload_tiles
    from hadoop_bam_tpu.formats.fastq import FastqError
    with pytest.raises(FastqError):
        fastq_text_to_payload_tiles(b"@a\nACGT\n+\n", 8, 8, 8)  # 3 lines
    with pytest.raises(FastqError):
        fastq_text_to_payload_tiles(b"@a\nACGT\n+\nII\n", 8, 8, 8)  # len
    with pytest.raises(FastqError):
        fastq_text_to_payload_tiles(b"a\nACGT\n+\nIIII\n", 8, 8, 8)  # no @
    empty = fastq_text_to_payload_tiles(b"", 8, 8, 8)
    assert all(a.size == 0 for a in empty)


def test_fastq_vectorized_tiles_zero_length_read():
    """A legal zero-length final read must parse in both paths; a stray
    trailing blank line must raise in both paths."""
    from hadoop_bam_tpu.api.read_datasets import fastq_text_to_payload_tiles
    from hadoop_bam_tpu.formats.fastq import FastqError
    ok = b"@r0\nACGT\n+\nIIII\n@r1\n\n+\n\n"
    assert len(parse_fastq(ok)) == 2
    _, _, lens = fastq_text_to_payload_tiles(ok, 8, 8, 8)
    assert lens.tolist() == [4, 0]
    bad = b"@r0\nACGT\n+\nIIII\n\n"
    with pytest.raises(FastqError):
        parse_fastq(bad)
    with pytest.raises(FastqError):
        fastq_text_to_payload_tiles(bad, 8, 8, 8)


def test_fastq_vectorized_tiles_wrong_encoding_guard():
    """Sanger-encoded qualities under an Illumina-64 config must raise, as
    convert_quality does on the object path."""
    from hadoop_bam_tpu.api.read_datasets import fastq_text_to_payload_tiles
    from hadoop_bam_tpu.formats.fastq import FastqError
    text = b"@a\nACGT\n+\n!!!!\n"   # '!' = 33, below the +64 offset
    with pytest.raises(FastqError):
        fastq_text_to_payload_tiles(text, 8, 8, 8, qual_offset=64)


@pytest.mark.parametrize("crlf", [False, True])
def test_qseq_vectorized_tiles_parity(crlf):
    """qseq_text_to_payload_tiles must match the object path exactly,
    including '.'-as-N and the Illumina +64 re-base."""
    from hadoop_bam_tpu.api.read_datasets import (
        fragments_to_payload_tiles, qseq_text_to_payload_tiles,
    )
    from hadoop_bam_tpu.formats.qseq import format_qseq_line
    frags = make_fragments(120, seed=8)
    lines = [format_qseq_line(f) for f in frags]
    sep = "\r\n" if crlf else "\n"
    text = (sep.join(lines) + sep).encode()
    want = fragments_to_payload_tiles(
        parse_qseq(text), 80, 160, 160)
    got = qseq_text_to_payload_tiles(text, 80, 160, 160)
    for w, g in zip(want, got):
        assert w.shape == g.shape and (w == g).all()


def test_qseq_vectorized_tiles_malformed():
    from hadoop_bam_tpu.api.read_datasets import qseq_text_to_payload_tiles
    from hadoop_bam_tpu.formats.fastq import FastqError
    with pytest.raises(FastqError, match="fields"):
        qseq_text_to_payload_tiles(b"a\tb\tc\n", 8, 8, 8)
    with pytest.raises(FastqError, match="mismatch"):
        qseq_text_to_payload_tiles(
            b"M\t1\t1\t1\t1\t1\t0\t1\tACGT\tab\t1\n", 8, 8, 8)
    with pytest.raises(FastqError, match="re-encoding"):
        # Sanger-range qualities under the +64 default
        qseq_text_to_payload_tiles(
            b"M\t1\t1\t1\t1\t1\t0\t1\tACGT\t!!!!\t1\n", 8, 8, 8)
    assert all(a.size == 0 for a in
               qseq_text_to_payload_tiles(b"", 8, 8, 8))


def test_qseq_gz_single_span_and_stats(tmp_path):
    """Compressed qseq input must read as ONE span over the inflated
    stream (splitting a gzip byte stream yields garbage) — both the
    record iterator and the vectorized stats driver."""
    import gzip

    frags = make_fragments(150, seed=14)
    plain = str(tmp_path / "r.qseq")
    with QseqShardWriter(plain) as w:
        for f in frags:
            w.write_record(f)
    gz = str(tmp_path / "r.qseq.gz")
    with open(plain, "rb") as fi, gzip.open(gz, "wb") as fo:
        fo.write(fi.read())
    ds = open_qseq(gz)
    assert len(ds.spans()) == 1
    got = [f.sequence for f in ds.records()]
    assert got == [f.sequence for f in frags]

    from hadoop_bam_tpu.parallel.pipeline import fastq_seq_stats_file
    stats = fastq_seq_stats_file(gz)
    assert stats["n_reads"] == len(frags)


def test_qseq_vectorized_guard_covers_full_field():
    """The wrong-encoding guard must inspect the WHOLE quality field, not
    just the max_len prefix — parity with parse_qseq/convert_quality."""
    from hadoop_bam_tpu.api.read_datasets import qseq_text_to_payload_tiles
    from hadoop_bam_tpu.formats.fastq import FastqError
    line = b"M\t1\t1\t1\t1\t1\t0\t1\tACGTAC\tabcd!!\t1\n"
    with pytest.raises(FastqError, match="re-encoding"):
        qseq_text_to_payload_tiles(line, 8, 8, 4)   # bad bytes past max_len
    with pytest.raises(FastqError):
        parse_qseq(line)                            # object path agrees


def test_ragged_to_payload_tiles_edges():
    """Direct unit tests for the shared ragged packer: empty input,
    missing qualities, truncation, and parity with the fragment path."""
    from hadoop_bam_tpu.api.read_datasets import (
        fragments_to_payload_tiles, ragged_to_payload_tiles,
    )
    s, q, l = ragged_to_payload_tiles(b"", np.zeros(0, np.int64), b"",
                                      np.zeros(0, np.int64), 8, 8, 8)
    assert s.shape == (0, 8) and q.shape == (0, 8) and l.size == 0

    seqs = ["ACGT", "", "GGNNTT", "A" * 50]
    quals = [bytes([30, 31, 32, 33]), b"", b"", bytes(range(50))]
    seq_cat = "".join(seqs).encode()
    got = ragged_to_payload_tiles(
        seq_cat, np.asarray([len(x) for x in seqs], np.int64),
        b"".join(quals), np.asarray([len(x) for x in quals], np.int64),
        16, 32, 32, qual_offset=0)
    frags = [SequencedFragment(
        sequence=s_, quality="".join(chr(33 + b) for b in q_))
        for s_, q_ in zip(seqs, quals)]
    want = fragments_to_payload_tiles(frags, 16, 32, 32)
    for w, g in zip(want, got):
        assert w.shape == g.shape and (w == g).all()
    assert got[2].tolist() == [4, 0, 6, 32]   # truncation at max_len
