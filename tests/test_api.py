"""API layer tests: dispatch sniffing, datasets, writers, mergers."""
import io
import os

import numpy as np
import pytest

from hadoop_bam_tpu.api.dataset import open_any_sam, open_bam, open_sam
from hadoop_bam_tpu.api.dispatch import (
    SAMContainer, VCFContainer, clear_sniff_caches, sniff_sam_container,
    sniff_vcf_container,
)
from hadoop_bam_tpu.api.writers import (
    BamShardWriter, SamShardWriter, write_records,
)
from hadoop_bam_tpu.config import HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bamio import read_bam
from hadoop_bam_tpu.formats.sam import write_sam_text
from hadoop_bam_tpu.utils.mergers import merge_bam_shards, merge_sam_shards

from fixtures import make_header, make_records


@pytest.fixture
def files(tmp_path):
    header = make_header()
    records = make_records(header, 3000, seed=5)
    bam = str(tmp_path / "a.bam")
    sam = str(tmp_path / "a.sam")
    write_records(bam, header, records)
    with open(sam, "w") as f:
        f.write(write_sam_text(header, records))
    return header, records, bam, sam, tmp_path


def test_sniff_sam(files):
    header, records, bam, sam, tmp = files
    clear_sniff_caches()
    # extension-free copies force magic sniffing
    bam2, sam2 = str(tmp / "noext_b"), str(tmp / "noext_s")
    os.link(bam, bam2)
    os.link(sam, sam2)
    assert sniff_sam_container(bam) is SAMContainer.BAM
    assert sniff_sam_container(sam) is SAMContainer.SAM
    assert sniff_sam_container(bam2) is SAMContainer.BAM
    assert sniff_sam_container(sam2) is SAMContainer.SAM
    # trust_exts=False must sniff content even with extensions
    cfg = HBamConfig(trust_exts=False)
    clear_sniff_caches()
    assert sniff_sam_container(bam, cfg) is SAMContainer.BAM
    cram = str(tmp / "c.cram")
    open(cram, "wb").write(b"CRAM\x03\x00" + b"\x00" * 30)
    assert sniff_sam_container(cram) is SAMContainer.CRAM


def test_sniff_vcf(tmp_path):
    clear_sniff_caches()
    vcf = str(tmp_path / "x.vcf")
    open(vcf, "w").write("##fileformat=VCFv4.2\n#CHROM\tPOS\n")
    vcfgz = str(tmp_path / "x.vcf.gz")
    open(vcfgz, "wb").write(bgzf.compress_bytes(b"##fileformat=VCFv4.2\n"))
    bcf = str(tmp_path / "x.bcf")
    open(bcf, "wb").write(bgzf.compress_bytes(b"BCF\x02\x02" + b"\x00" * 10))
    assert sniff_vcf_container(vcf) is VCFContainer.VCF
    assert sniff_vcf_container(vcfgz) is VCFContainer.VCF_BGZF
    assert sniff_vcf_container(bcf) is VCFContainer.BCF
    # content sniffing without trusted extensions
    cfg = HBamConfig(vcf_trust_exts=False)
    clear_sniff_caches()
    assert sniff_vcf_container(bcf, cfg) is VCFContainer.BCF
    assert sniff_vcf_container(vcfgz, cfg) is VCFContainer.VCF_BGZF


def test_bam_dataset_roundtrip(files):
    header, records, bam, sam, tmp = files
    ds = open_bam(bam)
    assert ds.header.ref_names == header.ref_names
    got = list(ds.records(num_spans=4))
    assert got == records


def test_dataset_checkpoint_resume(files):
    header, records, bam, sam, tmp = files
    ds = open_bam(bam)
    it = ds.batches(num_spans=5)
    consumed = [next(it), next(it)]
    state = ds.state_dict()
    assert state["next_span"] == 2
    # resume into a fresh dataset: remaining batches continue exactly
    ds2 = open_bam(bam)
    ds2.load_state_dict(state)
    names = []
    for b in consumed + list(ds2.batches()):
        names += [b.read_name(i) for i in range(len(b))]
    assert names == [r.qname for r in records]


def test_sam_dataset(files):
    header, records, bam, sam, tmp = files
    ds = open_sam(sam)
    assert ds.header.ref_names == header.ref_names
    got = list(ds.records(num_spans=3))
    assert got == records
    assert open_any_sam(sam).__class__.__name__ == "SamDataset"
    assert open_any_sam(bam).__class__.__name__ == "BamDataset"


def test_shard_merge_bam(files, tmp_path):
    header, records, bam, sam, tmp = files
    cfg = HBamConfig(write_header=False, write_terminator=False)
    shards = []
    k = 3
    per = len(records) // k
    for i in range(k):
        p = str(tmp_path / f"part-{i:05d}")
        with BamShardWriter(p, header, cfg) as w:
            for r in records[i * per:(i + 1) * per if i < k - 1 else None]:
                w.write_sam_record(r)
        shards.append(p)
    out = str(tmp_path / "merged.bam")
    merge_bam_shards(shards, out, header)
    hdr, batch = read_bam(out)
    assert len(batch) == len(records)
    assert [batch.read_name(i) for i in range(len(batch))] == \
        [r.qname for r in records]
    # merged file ends with the EOF terminator [SPEC]
    assert open(out, "rb").read().endswith(bgzf.EOF_BLOCK)


def test_shard_merge_sam(files, tmp_path):
    header, records, bam, sam, tmp = files
    shards = []
    for i in range(2):
        p = str(tmp_path / f"s-part-{i:05d}")
        with SamShardWriter(p, header, write_header=False) as w:
            for r in records[i * 1500:(i + 1) * 1500]:
                w.write_sam_record(r)
        shards.append(p)
    out = str(tmp_path / "merged.sam")
    merge_sam_shards(shards, out, header)
    from hadoop_bam_tpu.formats.sam import read_sam_text
    hdr, got = read_sam_text(open(out).read())
    assert got == records
    assert hdr.ref_names == header.ref_names


def test_flagstat_uniform_across_containers(tmp_path):
    """open_any_sam(...).flagstat() works for BAM, SAM, and CRAM and
    agrees across containers for the same records."""
    import sys
    sys.path.insert(0, "tests")
    from fixtures import make_header, make_records
    from hadoop_bam_tpu.api import open_any_sam
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.formats.cramio import write_cram

    header = make_header()
    recs = make_records(header, 800, seed=77)
    bam = str(tmp_path / "u.bam")
    with BamWriter(bam, header) as w:
        for r in recs:
            w.write_sam_record(r)
    sam = str(tmp_path / "u.sam")
    with open(sam, "w") as f:
        f.write(header.text)
        for r in recs:
            f.write(r.to_line() + "\n")
    cram = str(tmp_path / "u.cram")
    write_cram(cram, header, recs)

    stats = {p: open_any_sam(p).flagstat() for p in (bam, sam, cram)}
    assert stats[bam]["total"] == len(recs)
    assert stats[bam] == stats[sam] == stats[cram]
