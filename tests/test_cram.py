"""CRAM 3.0 family tests: varints, rANS, round-trips, splits, mergers.

Mirrors the reference's test strategy for CRAM (SURVEY.md section 4,
test/TestCRAMInputFormat.java): round-trip through our writer/reader, split
spans over container boundaries yielding every record exactly once."""
import io
import random

import pytest

from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.cram import (
    EOF_CONTAINER, FileDefinition, read_container, read_itf8, read_ltf8,
    scan_container_offsets, write_itf8, write_ltf8,
)
from hadoop_bam_tpu.formats.cram_codecs import rans4x8_decode, rans4x8_encode
from hadoop_bam_tpu.formats.cram_decode import (
    substitute_base, substitution_code,
)
from hadoop_bam_tpu.formats.cramio import (
    CramWriter, iter_cram_records, read_cram, read_cram_header, write_cram,
)
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.split.cram_planner import (
    plan_cram_spans, read_cram_span, scan_cram_containers,
)

from fixtures import make_header, make_records


# the canonical CRAM 3.0 EOF marker, fixed by the spec [SPEC section 9]
CANONICAL_EOF = bytes.fromhex(
    "0f000000ffffffff0fe0454f4600000000010005bdd94f000100060601000100"
    "0100ee63014b")


def test_eof_container_is_canonical():
    assert EOF_CONTAINER == CANONICAL_EOF


@pytest.mark.parametrize("v", [0, 1, 127, 128, 0x3FFF, 0x4000, 0x1FFFFF,
                               0x200000, 0xFFFFFFF, 0x10000000, 0x7FFFFFFF,
                               -1, -2, -100])
def test_itf8_roundtrip(v):
    enc = write_itf8(v)
    got, pos = read_itf8(enc, 0)
    assert got == v and pos == len(enc)


@pytest.mark.parametrize("v", [0, 127, 128, 1 << 14, 1 << 21, 1 << 28,
                               1 << 35, 1 << 42, 1 << 49, 1 << 56,
                               (1 << 62) - 3, -1, -5])
def test_ltf8_roundtrip(v):
    enc = write_ltf8(v)
    got, pos = read_ltf8(enc, 0)
    assert got == v and pos == len(enc)


@pytest.mark.parametrize("order", [0, 1])
def test_rans_roundtrip(order):
    rng = random.Random(7)
    cases = [b"", b"x", b"AAAAAAA", bytes(range(256)) * 3,
             bytes(rng.choice(b"ACGTN") for _ in range(4097)),
             bytes(rng.randrange(256) for _ in range(1001))]
    for data in cases:
        assert rans4x8_decode(rans4x8_encode(data, order=order)) == data


def test_rans_compresses_skewed_data():
    data = bytes(random.Random(3).choice(b"!!!!!####&&+5") for _ in range(8192))
    assert len(rans4x8_encode(data, order=0)) < len(data) // 2


def test_substitution_matrix_inverse():
    from hadoop_bam_tpu.formats.cram_decode import DEFAULT_SUBS_MATRIX
    for ref in "ACGTN":
        for read in "ACGTN":
            if ref == read:
                continue
            code = substitution_code(DEFAULT_SUBS_MATRIX, ref, read)
            assert substitute_base(DEFAULT_SUBS_MATRIX, ref, code) == read


def _tricky_records():
    return [
        SamRecord("p1", 99, "chr1", 100, 60, "5M2I3M1D5S", "=", 300, 250,
                  "ACGTACGTACGTACG", "IIIIIIIIIIIIIII",
                  [("NM", "i", 2), ("MD", "Z", "8^T0")]),
        SamRecord("p1", 147, "chr1", 300, 60, "10M5H", "=", 100, -250,
                  "ACGTACGTAC", "JJJJJJJJJJ", [("NM", "i", 0)]),
        SamRecord("u1", 4, "*", 0, 0, "*", "*", 0, 0, "ACGTN", "IIIII"),
        SamRecord("noq", 16, "chr2", 42, 30, "10M", "*", 0, 0,
                  "ACGTACGTAC", "*", [("XX", "Z", "hello"),
                                      ("XF", "f", 1.5),
                                      ("XB", "B", ("i", [1, -2, 300]))]),
        SamRecord("noseq", 0, "chr2", 50, 20, "*", "*", 0, 0, "*", "*"),
        SamRecord("skip", 0, "chr3", 10, 55, "4M100N4M2P4M", "*", 0, 0,
                  "ACGTACGTACGT", "KKKKKKKKKKKK"),
    ]


def test_cram_roundtrip_tricky_records():
    header = make_header()
    recs = _tricky_records()
    sink = io.BytesIO()
    write_cram(sink, header, recs)
    h2, out = read_cram(sink.getvalue())
    assert h2.ref_names == header.ref_names
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_cram_roundtrip_bulk(tmp_path):
    header = make_header()
    recs = make_records(header, 500, seed=11)
    path = str(tmp_path / "bulk.cram")
    write_cram(path, header, recs)
    _, out = read_cram(path)
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_cram_multi_container_and_scan(tmp_path):
    header = make_header()
    recs = make_records(header, 250, seed=5)
    path = str(tmp_path / "multi.cram")
    with CramWriter(path, header, records_per_container=40) as w:
        w.write_records(recs)
    containers = scan_cram_containers(path)
    # 1 header container + ceil(250/40) data containers
    assert len(containers) == 1 + 7
    assert sum(n for _, _, n in containers) == 250
    out = list(iter_cram_records(path))
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


@pytest.mark.parametrize("num_spans", [1, 2, 3, 5, 100])
def test_cram_spans_cover_exactly_once(tmp_path, num_spans):
    header = make_header()
    recs = make_records(header, 300, seed=6)
    path = str(tmp_path / "spans.cram")
    with CramWriter(path, header, records_per_container=37) as w:
        w.write_records(recs)
    spans = plan_cram_spans(path, num_spans=num_spans)
    assert len(spans) <= num_spans
    # spans are disjoint, ordered, container-aligned
    offsets = {off for off, _, _ in scan_cram_containers(path)}
    got = []
    for s in spans:
        assert s.start in offsets
        got.extend(read_cram_span(path, s, header=header))
    assert [r.to_line() for r in got] == [r.to_line() for r in recs]


def test_cram_dataset_and_dispatch(tmp_path):
    import hadoop_bam_tpu as hb
    header = make_header()
    recs = make_records(header, 120, seed=9)
    path = str(tmp_path / "ds.cram")
    with CramWriter(path, header, records_per_container=30) as w:
        w.write_records(recs)
    ds = hb.open_any_sam(path)
    from hadoop_bam_tpu.api.cram_dataset import CramDataset
    assert isinstance(ds, CramDataset)
    out = list(ds.records(num_spans=4))
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]
    # checkpoint/resume at span granularity: drain span 0, snapshot, resume
    ds2 = hb.open_cram(path)
    spans = ds2.spans(num_spans=4)
    assert 2 <= len(spans) <= 4
    n0 = len(ds2.read_span(spans[0]))
    it = ds2.records(num_spans=4)
    first = [next(it) for _ in range(n0)]
    state = ds2.state_dict()
    ds3 = hb.open_cram(path)
    ds3.load_state_dict(state)
    rest = list(ds3.records())
    assert len(first) + len(rest) == len(recs)
    assert [r.to_line() for r in rest] == \
        [r.to_line() for r in recs][n0:]


def test_cram_shard_writer_and_merger(tmp_path):
    from hadoop_bam_tpu.api.writers import CramShardWriter
    from hadoop_bam_tpu.config import HBamConfig
    from hadoop_bam_tpu.utils.mergers import merge_cram_shards
    header = make_header()
    recs = make_records(header, 90, seed=13)
    shard_cfg = HBamConfig(write_header=False, write_terminator=False)
    paths = []
    for i in range(3):
        p = str(tmp_path / f"part-{i:05d}")
        with CramShardWriter(p, header, shard_cfg) as w:
            for r in recs[i * 30:(i + 1) * 30]:
                w.write_sam_record(r)
        paths.append(p)
    out_path = str(tmp_path / "merged.cram")
    merge_cram_shards(paths, out_path, header)
    _, out = read_cram(out_path)
    assert [r.to_line() for r in out] == [r.to_line() for r in recs]


def test_reference_based_decode_with_substitutions(tmp_path):
    """Hand-build a slice that uses reference-filled matches + an X
    substitution + a deletion, and decode it against a FASTA source —
    the htslib-style CRAM our reader must also understand."""
    from hadoop_bam_tpu.formats.cram_decode import (
        CompressionHeader, DEFAULT_SUBS_MATRIX, ExternalEncoding,
        FastaReferenceSource, SliceHeader, decode_slice_records, tag_key,
    )
    from hadoop_bam_tpu.formats.cram import write_itf8

    ref_seq = "ACGTACGTACGTACGTACGT"
    fasta = f">chr1\n{ref_seq}\n".encode()
    ref_source = FastaReferenceSource(fasta)

    comp = CompressionHeader(read_names_included=True, ap_delta=True,
                             reference_required=True,
                             substitution_matrix=DEFAULT_SUBS_MATRIX)
    series = ["BF", "CF", "RL", "AP", "RG", "MF", "NS", "NP", "TS", "TL",
              "FN", "FP", "MQ", "DL", "BS", "FC"]
    streams = {k: bytearray() for k in series}
    streams["RN"] = bytearray()
    for i, k in enumerate(series):
        comp.data_series[k] = ExternalEncoding(i)
    from hadoop_bam_tpu.formats.cram_decode import ByteArrayStopEncoding
    comp.data_series["RN"] = ByteArrayStopEncoding(0, 100)

    def put(k, v):
        streams[k] += write_itf8(v)

    # one record: 4M from ref, X substitution at 5 (ref A -> read C),
    # 2D deletion, 5M from ref; read length 10
    put("BF", 0)
    put("CF", 2)          # detached, no stored quals
    put("RL", 10)
    put("AP", 3)          # delta vs slice start 2 -> pos 5
    put("RG", -1)
    streams["RN"] += b"href\x00"
    put("MF", 0)
    put("NS", -1)
    put("NP", 0)
    put("TS", 0)
    put("TL", 0)
    put("FN", 2)
    streams["FC"].append(ord("X"))
    put("FP", 5)
    code = substitution_code(DEFAULT_SUBS_MATRIX, ref_seq[4 + 4], "C")
    streams["BS"] = bytearray([code])
    comp.data_series["BS"] = ExternalEncoding(series.index("BS"))
    streams["FC"].append(ord("D"))
    put("FP", 1)          # delta: feature pos 6
    put("DL", 2)
    put("MQ", 37)

    slice_hdr = SliceHeader(ref_seq_id=0, start=2, span=15, n_records=1,
                            record_counter=0, n_blocks=0)
    external = {i: bytes(streams[k]) for i, k in enumerate(series)}
    external[100] = bytes(streams["RN"])
    recs = decode_slice_records(comp, slice_hdr, b"", external,
                                ["chr1"], ref_source)
    assert len(recs) == 1
    r = recs[0]
    assert r.pos == 5
    assert r.cigar == "5M2D5M"
    # 4M from ref 5..8, sub C at ref 9, 2D skips ref 10..11, 5M from 12..16
    expect = ref_seq[4:8] + "C" + ref_seq[11:16]
    assert r.seq == expect
    assert r.mapq == 37


def test_cram_header_roundtrip(tmp_path):
    header = make_header()
    path = str(tmp_path / "h.cram")
    write_cram(path, header, [])
    h2, first = read_cram_header(path)
    assert h2.text == header.text
    assert h2.ref_names == header.ref_names
    data = open(path, "rb").read()
    assert data[:4] == b"CRAM"
    assert data.endswith(CANONICAL_EOF)


def test_cram_tensor_batches(tmp_path):
    """CRAM reads flow through the shared payload tensor feed."""
    import numpy as np

    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry

    header = make_header()
    recs = make_records(header, 600, seed=9)
    path = str(tmp_path / "t.cram")
    write_cram(path, header, recs)
    ds = open_cram(path)
    g = PayloadGeometry(max_len=160, tile_records=256, block_n=256)
    total = 0
    first_seq = None
    for batch in ds.tensor_batches(geometry=g, num_spans=2):
        counts = np.asarray(batch["n_records"])
        if first_seq is None and counts[0]:
            from hadoop_bam_tpu.ops.seq_pallas import unpack_bases
            codes = np.asarray(unpack_bases(
                np.asarray(batch["seq_packed"])[0][:1]))
            ln = int(np.asarray(batch["lengths"])[0, 0])
            code_to_base = {0: "=", 1: "A", 2: "C", 4: "G", 8: "T", 15: "N"}
            first_seq = "".join(code_to_base[int(c)] for c in codes[0, :ln])
        total += int(counts.sum())
    assert total == len(recs)
    assert first_seq == recs[0].seq[:160]


@pytest.mark.parametrize("order", [0, 1])
@pytest.mark.parametrize("force_numpy", [False, True])
def test_host_decode_rejects_corrupt_stream(order, force_numpy, monkeypatch):
    """Both host decoders (native C++ and the NumPy fallback) raise on a
    bit-flipped stream instead of returning garbage — same contract as
    the device decoder (ops/rans.py _check_final)."""
    from hadoop_bam_tpu.formats.cram_codecs import RansError
    from hadoop_bam_tpu.utils import native

    if force_numpy:
        monkeypatch.setattr(native, "available", lambda: False)
    rng = random.Random(9)
    data = bytes(rng.choice(b"ACGTN") for _ in range(2000))
    p = bytearray(rans4x8_encode(data, order=order))
    p[-40] ^= 0xFF
    with pytest.raises(RansError):
        rans4x8_decode(bytes(p))


def test_host_decode_rejects_lying_out_size():
    from hadoop_bam_tpu.formats.cram_codecs import RansError

    data = b"ACGT" * 500
    p = bytearray(rans4x8_encode(data, order=0))
    p[5:9] = (len(data) + 64).to_bytes(4, "little")
    with pytest.raises(RansError):
        rans4x8_decode(bytes(p))


def test_cram_tensor_tiles_match_record_iterator(tmp_path):
    """The columnar fast path (pre-SAM CramRecords -> ragged pack) must
    produce exactly the tiles the object path produced: same 4-bit base
    codes, same Phred values, same lengths, same record order."""
    import numpy as np

    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.api.read_datasets import (
        fragments_to_payload_tiles,
    )
    from hadoop_bam_tpu.formats.fastq import SequencedFragment
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry

    header = make_header()
    recs = make_records(header, 300, seed=23)
    path = str(tmp_path / "p.cram")
    write_cram(path, header, recs)
    g = PayloadGeometry(max_len=160, tile_records=128, block_n=128)
    ds = open_cram(path)
    got_seq, got_qual, got_len = [], [], []
    for batch in ds.tensor_batches(geometry=g):
        counts = np.asarray(batch["n_records"])
        for d in range(counts.size):
            c = int(counts[d])
            got_seq.append(np.asarray(batch["seq_packed"])[d, :c])
            got_qual.append(np.asarray(batch["qual"])[d, :c])
            got_len.append(np.asarray(batch["lengths"])[d, :c])
    got_seq = np.concatenate(got_seq)
    got_qual = np.concatenate(got_qual)
    got_len = np.concatenate(got_len)

    frags = [SequencedFragment(sequence="" if r.seq == "*" else r.seq,
                               quality="" if r.qual == "*" else r.qual)
             for r in open_cram(path).records()]
    want_seq, want_qual, want_len = fragments_to_payload_tiles(
        frags, g.seq_stride, g.qual_stride, g.max_len)
    assert (got_len == want_len).all()
    assert (got_seq == want_seq).all()
    assert (got_qual == want_qual).all()


def test_cram_tensor_tiles_quality_less_reads(tmp_path):
    """Regression: reads stored without quality (CF_QUAL_STORED clear)
    carry the decoder's 0xff filler in CramRecord.qual; the columnar
    tiles path must emit zero quality rows like the object path, not
    Phred-255 garbage."""
    import numpy as np

    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.formats.sam import SamRecord
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry

    header = make_header()
    recs = [SamRecord(qname=f"q{i}", flag=0, rname=header.ref_names[0],
                      pos=100 + i, mapq=30, cigar="8M", rnext="*",
                      pnext=0, tlen=0, seq="ACGTACGT",
                      qual="*" if i % 2 == 0 else "IIIIIIII")
            for i in range(20)]
    path = str(tmp_path / "noq.cram")
    write_cram(path, header, recs)
    g = PayloadGeometry(max_len=32, tile_records=32, block_n=32)
    ds = open_cram(path)
    for batch in ds.tensor_batches(geometry=g):
        counts = np.asarray(batch["n_records"])
        qual = np.asarray(batch["qual"])
        lens = np.asarray(batch["lengths"])
        for d in range(counts.size):
            for r in range(int(counts[d])):
                row = qual[d, r, :int(lens[d, r])]
                assert row.max(initial=0) <= 41, row  # never 0xff filler


def test_predecode_fast_path_parity(tmp_path, monkeypatch):
    """decode_slice_records must be record-identical with the vectorized
    fixed-series predecode ON (native batch ITF8) and OFF (per-record
    fallback) — including mates, tags, unmapped records, and multiref."""
    from fixtures import make_header, make_records
    from hadoop_bam_tpu.formats import cram_decode
    from hadoop_bam_tpu.formats.cramio import CramWriter, read_cram
    from hadoop_bam_tpu.utils import native

    if not native.available():
        pytest.skip("native library unavailable; no fast path to compare")

    header = make_header()
    recs = make_records(header, 300, seed=41)
    path = str(tmp_path / "p.cram")
    with CramWriter(path, header, records_per_container=64) as w:
        w.write_records(recs)

    calls = {"fast": 0}
    real_fast = cram_decode._decode_slice_records_fast

    def counting_fast(*a, **k):
        calls["fast"] += 1
        return real_fast(*a, **k)

    monkeypatch.setattr(cram_decode, "_decode_slice_records_fast",
                        counting_fast)
    _, fast = read_cram(path)
    assert calls["fast"] > 0, "predecode eligibility regressed: the " \
                              "vectorized path never ran"
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)   # force fallback path
    _, slow = read_cram(path)
    assert [r.to_line() for r in fast] == [r.to_line() for r in slow]
