"""Fault injection on the pipeline decode paths (SURVEY.md section 5:
exceed the reference's corruption coverage — corrupt BGZF blocks mid-file,
flipped CRCs, truncated streams) plus record serde round-trips."""
import numpy as np
import pytest

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.parallel.pipeline import (
    PayloadGeometry, decode_span_payload_host, decode_span_prefix_host,
    DecodeGeometry, decode_span_host,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans

from fixtures import make_header, make_records


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("faults") / "f.bam")
    header = make_header()
    records = make_records(header, 4000, seed=23)
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    return path, header, records


def _spans(path, header, n=3):
    return plan_bam_spans(path, num_spans=n, header=header)


def _corrupt_copy(path, tmp_path, mutate):
    data = bytearray(open(path, "rb").read())
    mutate(data)
    out = str(tmp_path / "corrupt.bam")
    open(out, "wb").write(bytes(data))
    return out


def test_corrupt_cdata_midfile_raises(bam, tmp_path):
    """Garbage inside a mid-file block's DEFLATE payload must raise, not
    produce silent garbage records."""
    path, header, records = bam
    blocks = list(bgzf.scan_blocks(open(path, "rb").read()))
    victim = blocks[len(blocks) // 2]

    def mutate(data):
        start = victim.cdata_offset
        for i in range(start + 10, start + 40):
            data[i] ^= 0xFF

    bad = _corrupt_copy(path, tmp_path, mutate)
    spans = _spans(path, header)  # plan from the intact twin
    with pytest.raises(Exception):
        for s in spans:
            decode_span_prefix_host(bad, s)


def test_crc_flip_detected_with_check_crc(bam, tmp_path):
    """A bit flip that still inflates cleanly is caught by the CRC check."""
    path, header, records = bam
    raw = open(path, "rb").read()
    blocks = list(bgzf.scan_blocks(raw))
    victim = blocks[len(blocks) // 2]

    def mutate(data):
        # flip the stored CRC itself: inflate succeeds, CRC mismatches
        crc_off = victim.cdata_offset + victim.cdata_size
        data[crc_off] ^= 0xFF

    bad = _corrupt_copy(path, tmp_path, mutate)
    spans = _spans(bad, header)
    with pytest.raises(bgzf.BGZFError, match="CRC"):
        for s in spans:
            decode_span_prefix_host(bad, s, True)


def test_truncated_file_raises(bam, tmp_path):
    path, header, records = bam
    raw = open(path, "rb").read()
    out = str(tmp_path / "trunc.bam")
    open(out, "wb").write(raw[:len(raw) // 2 + 37])  # mid-block cut
    spans = _spans(path, header)  # plan from the intact file
    with pytest.raises(Exception):
        for s in spans:
            decode_span_prefix_host(out, s)


def test_bad_block_size_chain_raises(bam, tmp_path):
    """Corrupting a record's block_size field breaks the walk chain."""
    path, header, records = bam
    g = DecodeGeometry(bytes_cap=1 << 24, records_cap=1 << 16)
    spans = _spans(path, header, n=1)
    data, offs, n, _ = decode_span_host(path, spans[0], g)
    # rebuild a BGZF file whose inflated payload is a record chain (no BAM
    # header) with one corrupted block_size mid-chain
    base = int(offs[0])
    payload = bytearray(data[base:int(offs[n - 1])].tobytes())
    victim = int(offs[n // 2]) - base
    payload[victim:victim + 4] = (5).to_bytes(4, "little")  # bs < 32
    out = str(tmp_path / "badchain.bam")
    open(out, "wb").write(bgzf.compress_bytes(bytes(payload)))
    from hadoop_bam_tpu.split.spans import FileVirtualSpan
    from hadoop_bam_tpu.formats.virtual_offset import make_voffset
    import os
    whole = FileVirtualSpan(out, make_voffset(0, 0),
                            make_voffset(os.path.getsize(out), 0))
    with pytest.raises(ValueError):
        decode_span_prefix_host(out, whole)
    with pytest.raises(ValueError):
        decode_span_payload_host(out, whole, PayloadGeometry())


def test_skip_bad_spans_policy(bam, tmp_path):
    """With skip_bad_spans=True, a corrupt span is retried, warned about,
    and excluded — the rest of the file still counts (the MapReduce
    task-retry analog)."""
    import dataclasses

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.utils.metrics import METRICS

    path, header, records = bam
    raw = open(path, "rb").read()
    blocks = list(bgzf.scan_blocks(raw))
    victim = blocks[len(blocks) // 2]

    def mutate(data):
        start = victim.cdata_offset
        for i in range(start + 10, start + 40):
            data[i] ^= 0xFF

    bad = _corrupt_copy(path, tmp_path, mutate)
    spans = _spans(path, header, n=4)  # plan on the intact twin

    # default policy: raise
    with pytest.raises(Exception):
        flagstat_file(bad, header=header, spans=spans)

    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=1)
    METRICS.reset()
    stats = flagstat_file(bad, header=header, spans=spans, config=cfg)
    assert 0 < stats["total"] < len(records)
    assert METRICS.counters["pipeline.bad_spans"] >= 1
    assert METRICS.counters["pipeline.span_retries"] >= 1


def test_serde_sam_round_trip(bam):
    path, header, records = bam
    from hadoop_bam_tpu.utils.serde import (
        decode_sam_records, encode_sam_records,
    )
    wire = encode_sam_records(records[:100], header)
    back = decode_sam_records(wire, header)
    assert len(back) == 100
    for a, b in zip(records[:100], back):
        assert a.to_line() == b.to_line()
    # corrupt wire fails loudly
    with pytest.raises(ValueError):
        decode_sam_records(wire[:len(wire) - 3], header)


def test_serde_variant_round_trip():
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    from hadoop_bam_tpu.utils.serde import decode_variants, encode_variants
    header_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=c1,length=1000>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts1\n")
    header = VCFHeader.from_text(header_text)
    recs = [VcfRecord.from_line(f"c1\t{10 + i}\t.\tA\tG\t50\tPASS\tDP={i}"
                                f"\tGT\t0/1") for i in range(20)]
    wire = encode_variants(recs, header)
    back = decode_variants(wire, header)
    assert len(back) == 20
    assert back[3].pos == 13 and back[3].alts == recs[3].alts


def test_metrics_counters_tick(bam):
    path, header, records = bam
    from hadoop_bam_tpu.utils.metrics import METRICS
    METRICS.reset()
    for s in _spans(path, header):
        decode_span_prefix_host(path, s)
    assert METRICS.counters["pipeline.records"] == len(records)
    assert METRICS.counters["pipeline.spans"] >= 3
    assert METRICS.counters["pipeline.blocks"] > 0
    assert "pipeline.inflate" in METRICS.timers
