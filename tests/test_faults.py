"""Fault injection on the pipeline decode paths (SURVEY.md section 5:
exceed the reference's corruption coverage — corrupt BGZF blocks mid-file,
flipped CRCs, truncated streams) plus the fault-classified resilience
layer: transient retry with injected-clock backoff, corruption fail-fast,
quarantine manifest, circuit breaker, chaos injection — and record serde
round-trips."""
import dataclasses

import numpy as np
import pytest

from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.parallel.pipeline import (
    PayloadGeometry, decode_span_payload_host, decode_span_prefix_host,
    DecodeGeometry, decode_span_host, decode_with_retry,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans
from hadoop_bam_tpu.utils.errors import (
    CircuitBreakerError, CorruptDataError, PlanError, TransientIOError,
    classify_error,
)
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.resilient import (
    FaultInjectingByteSource, FaultSpec, QuarantineManifest, RetryPolicy,
    RetryingByteSource, chaos_on,
)

from fixtures import make_header, make_records

pytestmark = pytest.mark.faults


class FakeClock:
    """Injectable clock+sleep pair: sleeping advances virtual time only,
    so backoff schedules are asserted exactly and no test ever waits."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, d):
        self.sleeps.append(d)
        self.t += d


@pytest.fixture(scope="module")
def bam(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("faults") / "f.bam")
    header = make_header()
    records = make_records(header, 4000, seed=23)
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    return path, header, records


def _spans(path, header, n=3):
    return plan_bam_spans(path, num_spans=n, header=header)


def _corrupt_copy(path, tmp_path, mutate):
    data = bytearray(open(path, "rb").read())
    mutate(data)
    out = str(tmp_path / "corrupt.bam")
    open(out, "wb").write(bytes(data))
    return out


def test_corrupt_cdata_midfile_raises(bam, tmp_path):
    """Garbage inside a mid-file block's DEFLATE payload must raise, not
    produce silent garbage records."""
    path, header, records = bam
    blocks = list(bgzf.scan_blocks(open(path, "rb").read()))
    victim = blocks[len(blocks) // 2]

    def mutate(data):
        start = victim.cdata_offset
        for i in range(start + 10, start + 40):
            data[i] ^= 0xFF

    bad = _corrupt_copy(path, tmp_path, mutate)
    spans = _spans(path, header)  # plan from the intact twin
    with pytest.raises(Exception):
        for s in spans:
            decode_span_prefix_host(bad, s)


def test_crc_flip_detected_with_check_crc(bam, tmp_path):
    """A bit flip that still inflates cleanly is caught by the CRC check."""
    path, header, records = bam
    raw = open(path, "rb").read()
    blocks = list(bgzf.scan_blocks(raw))
    victim = blocks[len(blocks) // 2]

    def mutate(data):
        # flip the stored CRC itself: inflate succeeds, CRC mismatches
        crc_off = victim.cdata_offset + victim.cdata_size
        data[crc_off] ^= 0xFF

    bad = _corrupt_copy(path, tmp_path, mutate)
    spans = _spans(bad, header)
    with pytest.raises(bgzf.BGZFError, match="CRC"):
        for s in spans:
            decode_span_prefix_host(bad, s, True)


def test_truncated_file_raises(bam, tmp_path):
    path, header, records = bam
    raw = open(path, "rb").read()
    out = str(tmp_path / "trunc.bam")
    open(out, "wb").write(raw[:len(raw) // 2 + 37])  # mid-block cut
    spans = _spans(path, header)  # plan from the intact file
    with pytest.raises(Exception):
        for s in spans:
            decode_span_prefix_host(out, s)


def test_bad_block_size_chain_raises(bam, tmp_path):
    """Corrupting a record's block_size field breaks the walk chain."""
    path, header, records = bam
    g = DecodeGeometry(bytes_cap=1 << 24, records_cap=1 << 16)
    spans = _spans(path, header, n=1)
    data, offs, n, _ = decode_span_host(path, spans[0], g)
    # rebuild a BGZF file whose inflated payload is a record chain (no BAM
    # header) with one corrupted block_size mid-chain
    base = int(offs[0])
    payload = bytearray(data[base:int(offs[n - 1])].tobytes())
    victim = int(offs[n // 2]) - base
    payload[victim:victim + 4] = (5).to_bytes(4, "little")  # bs < 32
    out = str(tmp_path / "badchain.bam")
    open(out, "wb").write(bgzf.compress_bytes(bytes(payload)))
    from hadoop_bam_tpu.split.spans import FileVirtualSpan
    from hadoop_bam_tpu.formats.virtual_offset import make_voffset
    import os
    whole = FileVirtualSpan(out, make_voffset(0, 0),
                            make_voffset(os.path.getsize(out), 0))
    with pytest.raises(ValueError):
        decode_span_prefix_host(out, whole)
    with pytest.raises(ValueError):
        decode_span_payload_host(out, whole, PayloadGeometry())


def _corrupt_midfile(bam, tmp_path):
    """Corrupt the DEFLATE payload of a mid-file block; returns the bad
    twin's path and the victim block (located on the intact file)."""
    path, header, records = bam
    raw = open(path, "rb").read()
    blocks = list(bgzf.scan_blocks(raw))
    victim = blocks[len(blocks) // 2]

    def mutate(data):
        start = victim.cdata_offset
        for i in range(start + 10, start + 40):
            data[i] ^= 0xFF

    return _corrupt_copy(path, tmp_path, mutate), victim


def test_skip_bad_spans_policy(bam, tmp_path):
    """With skip_bad_spans=True, a corrupt span is quarantined WITHOUT
    retries (corruption never heals) and excluded — the rest of the file
    still counts.  pipeline.bad_spans ticks only on the actual skip."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file

    path, header, records = bam
    bad, _victim = _corrupt_midfile(bam, tmp_path)
    spans = _spans(path, header, n=4)  # plan on the intact twin

    # default policy: raise — and bad_spans must NOT tick on a raise
    METRICS.reset()
    with pytest.raises(Exception):
        flagstat_file(bad, header=header, spans=spans)
    assert METRICS.get("pipeline.bad_spans") == 0

    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=1)
    METRICS.reset()
    stats = flagstat_file(bad, header=header, spans=spans, config=cfg)
    assert 0 < stats["total"] < len(records)
    assert METRICS.get("pipeline.bad_spans") >= 1
    assert METRICS.get("pipeline.corrupt_spans") >= 1
    # corruption is classified: the old blanket re-decode is gone
    assert METRICS.get("pipeline.transient_retries") == 0


def test_quarantine_manifest_names_bad_span(bam, tmp_path):
    """Acceptance: one corrupted mid-file block + skip_bad_spans=True
    completes with a manifest naming exactly the bad span's virtual-offset
    range, with zero retry attempts spent on it."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file

    path, header, records = bam
    spans = _spans(path, header, n=4)
    # victim: the block nearest the MIDDLE of span[1]'s compressed range —
    # strictly interior, so exactly one span reads the corrupt bytes (a
    # boundary-straddling victim would legitimately fail two spans)
    raw = open(path, "rb").read()
    blocks = list(bgzf.scan_blocks(raw))
    mid = (spans[1].start[0] + spans[1].end[0]) // 2
    victim = min((b for b in blocks if b.isize),
                 key=lambda b: abs(b.coffset - mid))

    def mutate(data):
        start = victim.cdata_offset
        for i in range(start + 10, start + 40):
            data[i] ^= 0xFF

    bad = _corrupt_copy(path, tmp_path, mutate)
    bad_spans = [spans[1]]

    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=3)
    q = QuarantineManifest()
    METRICS.reset()
    stats = flagstat_file(bad, header=header, spans=spans, config=cfg,
                          quarantine=q)
    assert len(q) == 1
    entry = q.to_dicts()[0]
    assert entry["span_start"] == bad_spans[0].start_voffset
    assert entry["span_end"] == bad_spans[0].end_voffset
    assert entry["path"] == bad_spans[0].path  # the span is self-describing
    assert entry["error_class"] == "corrupt"
    # ONE oracle re-decode, zero retry-policy re-decodes: corruption is
    # never retried on the same plane, but since ISSUE 11 the demotion
    # ladder confirms the failure on the zlib oracle before quarantining
    # (the data — not the native plane — is what gets blamed here), so
    # attempts counts the native try plus the zlib confirmation
    assert entry["attempts"] == 2
    assert METRICS.get("pipeline.transient_retries") == 0
    # no fault domain was charged: BOTH planes failed, so the ladder
    # correctly classified this as data corruption, not a plane fault
    from hadoop_bam_tpu import resilience
    assert resilience.registry().states() == {}
    # the manifest also rides the result dict (non-empty runs only)
    assert stats["quarantine"] == q.to_dicts()
    assert q.total_spans == len(spans)
    # clean runs keep the exact historical result shape
    clean = flagstat_file(path, header=header, spans=spans, config=cfg)
    assert "quarantine" not in clean


def test_circuit_breaker_aborts_run(bam, tmp_path):
    """With max_bad_span_fraction exceeded the run raises instead of
    silently degrading into a mostly-skipped answer."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file

    path, header, records = bam
    bad, _victim = _corrupt_midfile(bam, tmp_path)
    spans = _spans(path, header, n=4)
    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=0, max_bad_span_fraction=0.1)
    with pytest.raises(CircuitBreakerError, match="max_bad_span_fraction"):
        flagstat_file(bad, header=header, spans=spans, config=cfg)


def test_transient_retry_uses_injected_clock(bam):
    """A transient fault heals on retry: backoff runs on the injected
    policy (exact schedule asserted, virtual time only) and the span is
    NOT quarantined."""
    path, header, records = bam
    spans = _spans(path, header, n=1)
    clock = FakeClock()
    policy = RetryPolicy(retries=3, backoff_base_s=0.25, backoff_max_s=8.0,
                         jitter=0.0, sleep=clock.sleep, clock=clock)
    # first two preads fail transiently; every retry re-opens the decode
    src = FaultInjectingByteSource(
        path, [FaultSpec("transient", at_read=0, count=2)])

    def inner(s):
        return decode_span_prefix_host(src, s)

    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=3)
    q = QuarantineManifest(total_spans=1)
    METRICS.reset()
    rows, _ = decode_with_retry(inner, spans[0], cfg, quarantine=q,
                                policy=policy)
    assert rows.shape[0] == len(records)
    assert clock.sleeps == [0.25, 0.5]     # exponential, no real sleeps
    assert dict(src.injected) == {"transient": 2}
    assert len(q) == 0
    assert METRICS.get("pipeline.transient_retries") == 2
    assert METRICS.get("pipeline.bad_spans") == 0


def test_corrupt_fails_fast_without_retries():
    """Corruption burns zero retries even with a generous budget."""
    attempts = []

    def fn(_span):
        attempts.append(1)
        raise CorruptDataError("synthetic corruption")

    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=5)
    with pytest.raises(CorruptDataError):
        decode_with_retry(fn, _dummy_span(), cfg)
    assert len(attempts) == 1


def test_plan_error_never_retried_or_skipped():
    """PLAN-class errors raise through even under skip_bad_spans."""
    attempts = []

    def fn(_span):
        attempts.append(1)
        raise PlanError("span exceeds geometry")

    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=5,
                              skip_bad_spans=True)
    q = QuarantineManifest(total_spans=1)
    with pytest.raises(PlanError):
        decode_with_retry(fn, _dummy_span(), cfg, quarantine=q)
    assert len(attempts) == 1 and len(q) == 0


def _dummy_span():
    from hadoop_bam_tpu.split.spans import FileVirtualSpan
    return FileVirtualSpan("/nonexistent.bam", 0, 1 << 16)


def test_transient_exhaustion_quarantines_as_transient():
    """A fault that never heals is quarantined under its own class."""
    def fn(_span):
        raise TransientIOError("network is down")

    clock = FakeClock()
    policy = RetryPolicy(retries=2, backoff_base_s=0.1, jitter=0.0,
                         sleep=clock.sleep, clock=clock)
    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True)
    q = QuarantineManifest(total_spans=8)
    out = decode_with_retry(fn, _dummy_span(), cfg, quarantine=q,
                            policy=policy)
    assert out is None
    entry = q.to_dicts()[0]
    assert entry["error_class"] == "transient" and entry["attempts"] == 3
    assert clock.sleeps == [0.1, 0.2]


def test_retrying_byte_source_deadline():
    """The per-read deadline bounds backoff: when the next delay would
    overrun it, RetryingByteSource stops and raises TransientIOError."""
    from hadoop_bam_tpu.utils.seekable import BytesByteSource

    clock = FakeClock()
    always_bad = FaultInjectingByteSource(
        BytesByteSource(b"x" * 64),
        [FaultSpec("transient", count=10 ** 6)])
    src = RetryingByteSource(always_bad, RetryPolicy(
        retries=50, backoff_base_s=2.0, backoff_max_s=2.0, jitter=0.0,
        deadline_s=5.0, sleep=clock.sleep, clock=clock))
    with pytest.raises(TransientIOError):
        src.pread(0, 16)
    # 2s + 4s would pass 5s — exactly two sleeps fit under the deadline
    assert clock.sleeps == [2.0, 2.0]

    # and with a healthy budget the wrapped read heals
    clock2 = FakeClock()
    heals = FaultInjectingByteSource(
        BytesByteSource(bytes(range(64))),
        [FaultSpec("transient", at_read=0, count=2)])
    src2 = RetryingByteSource(heals, RetryPolicy(
        retries=4, backoff_base_s=0.5, jitter=0.0, sleep=clock2.sleep,
        clock=clock2))
    assert src2.pread(0, 4) == bytes(range(4))
    assert clock2.sleeps == [0.5, 1.0]


def test_chaos_registry_wraps_path_sources(bam):
    """install_chaos makes every path-opened source chaotic with zero
    driver plumbing: transient faults surface through the whole pipeline
    and heal under the span retry policy."""
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file

    path, header, records = bam
    spans = _spans(path, header, n=3)
    clean = flagstat_file(path, header=header, spans=spans)
    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=3,
                              retry_backoff_base_s=0.001,
                              retry_backoff_max_s=0.002)
    faults = [FaultSpec("transient", at_read=0, count=2)]
    METRICS.reset()
    with chaos_on(path, faults):
        stats = flagstat_file(path, header=header, spans=spans, config=cfg)
    assert {k: stats[k] for k in clean} == clean
    assert "quarantine" not in stats
    assert METRICS.get("chaos.injected_faults") >= 1
    # registry fully uninstalls: later reads are clean again
    assert flagstat_file(path, header=header, spans=spans) == clean


def test_chaos_bitflip_is_corrupt_class(bam, tmp_path):
    """A chaos bit flip inside a block body classifies as corruption:
    zero retries, quarantined when skipping is on."""
    path, header, records = bam
    raw = open(path, "rb").read()
    blocks = list(bgzf.scan_blocks(raw))
    victim = blocks[len(blocks) // 2]
    spans = _spans(path, header, n=4)
    cfg = dataclasses.replace(DEFAULT_CONFIG, skip_bad_spans=True,
                              span_retries=2, check_crc=True)
    faults = [FaultSpec("bitflip",
                        offset_range=(victim.cdata_offset,
                                      victim.cdata_offset + 16),
                        count=10 ** 6, xor_mask=0xFF)]
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    q = QuarantineManifest()
    METRICS.reset()
    with chaos_on(path, faults):
        stats = flagstat_file(path, header=header, spans=spans, config=cfg,
                              quarantine=q)
    assert 0 < stats["total"] < len(records)
    assert len(q) >= 1
    assert all(e["error_class"] == "corrupt" for e in q.to_dicts())
    assert METRICS.get("pipeline.transient_retries") == 0


def test_classify_error_taxonomy():
    assert classify_error(TransientIOError("x")) == "transient"
    assert classify_error(TimeoutError()) == "transient"
    assert classify_error(ConnectionResetError()) == "transient"
    assert classify_error(OSError(5, "EIO")) == "transient"
    assert classify_error(CorruptDataError("x")) == "corrupt"
    assert classify_error(bgzf.BGZFError("bad magic")) == "corrupt"
    assert classify_error(ValueError("malformed")) == "corrupt"
    import zlib
    assert classify_error(zlib.error("bad code")) == "corrupt"
    assert classify_error(PlanError("bad num_spans")) == "plan"
    # deterministic OSErrors are PLAN: a path typo must raise loudly, not
    # burn retries or quarantine into an empty result
    assert classify_error(FileNotFoundError("gone.bam")) == "plan"
    assert classify_error(PermissionError("denied")) == "plan"
    assert classify_error(RuntimeError("???")) == "corrupt"  # fail-fast
    # taxonomy keeps builtin compatibility
    assert isinstance(TransientIOError("x"), OSError)
    assert isinstance(CorruptDataError("x"), ValueError)
    assert isinstance(PlanError("x"), ValueError)
    assert isinstance(bgzf.BGZFError("x"), CorruptDataError)


def test_quarantine_manifest_merge_and_serde():
    """JSON round-trip plus the multi-host union: dedup by span range,
    canonical order, identical on every host."""
    s1 = _dummy_span()
    from hadoop_bam_tpu.split.spans import FileVirtualSpan
    s2 = FileVirtualSpan("/nonexistent.bam", 1 << 20, 2 << 20)
    a = QuarantineManifest(total_spans=8)
    a.add(s2, CorruptDataError("crc"), "corrupt", 1, host=0)
    b = QuarantineManifest(total_spans=8)
    b.add(s1, TransientIOError("io"), "transient", 3, host=1)
    b.add(s2, CorruptDataError("crc"), "corrupt", 1, host=1)  # dup range
    merged = a.merged_with([b])
    assert len(merged) == 2
    starts = [e["span_start"] for e in merged.to_dicts()]
    assert starts == sorted(starts)
    back = QuarantineManifest.from_json(merged.to_json())
    assert back.to_dicts() == merged.to_dicts()
    # totals SUM across hosts (disjoint plan slices): 2 bad of 16 planned
    assert merged.total_spans == 16 and back.total_spans == 16
    assert merged.bad_fraction() == 0.125

    # single-process distributed merge is the identity
    from hadoop_bam_tpu.parallel.distributed import (
        merge_quarantine_manifests,
    )
    assert merge_quarantine_manifests(a) is a


def test_plan_errors_from_planners(bam):
    path, header, records = bam
    from hadoop_bam_tpu.parallel.distributed import serialize_plan
    with pytest.raises(PlanError):
        plan_bam_spans(path, num_spans=0, header=header)
    spans = _spans(path, header, n=3)
    with pytest.raises(PlanError, match="broadcast buffer"):
        serialize_plan(spans, max_bytes=16)


def test_serde_sam_round_trip(bam):
    path, header, records = bam
    from hadoop_bam_tpu.utils.serde import (
        decode_sam_records, encode_sam_records,
    )
    wire = encode_sam_records(records[:100], header)
    back = decode_sam_records(wire, header)
    assert len(back) == 100
    for a, b in zip(records[:100], back):
        assert a.to_line() == b.to_line()
    # corrupt wire fails loudly
    with pytest.raises(ValueError):
        decode_sam_records(wire[:len(wire) - 3], header)


def test_serde_variant_round_trip():
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
    from hadoop_bam_tpu.utils.serde import decode_variants, encode_variants
    header_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=c1,length=1000>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts1\n")
    header = VCFHeader.from_text(header_text)
    recs = [VcfRecord.from_line(f"c1\t{10 + i}\t.\tA\tG\t50\tPASS\tDP={i}"
                                f"\tGT\t0/1") for i in range(20)]
    wire = encode_variants(recs, header)
    back = decode_variants(wire, header)
    assert len(back) == 20
    assert back[3].pos == 13 and back[3].alts == recs[3].alts


def test_metrics_counters_tick(bam):
    path, header, records = bam
    from hadoop_bam_tpu.utils.metrics import METRICS
    METRICS.reset()
    for s in _spans(path, header):
        decode_span_prefix_host(path, s)
    assert METRICS.counters["pipeline.records"] == len(records)
    assert METRICS.counters["pipeline.spans"] >= 3
    assert METRICS.counters["pipeline.blocks"] > 0
    # fused single-pass decode reports its one sweep as
    # pipeline.fused_decode; the two-pass fallback keeps pipeline.inflate
    assert ("pipeline.fused_decode" in METRICS.timers
            or "pipeline.inflate" in METRICS.timers)
