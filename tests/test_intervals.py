"""Interval filtering + keep-paired-reads-together split option.

Reference parity: hadoopbam.bam.intervals (hb/BAMInputFormat.java 7.7+) and
hadoopbam.bam.keep-paired-reads-together (7.9+)."""
import numpy as np
import pytest

from hadoop_bam_tpu.config import HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.split.intervals import (
    Interval, IntervalError, parse_interval, parse_intervals,
)

from fixtures import make_header, make_records


@pytest.mark.parametrize("text,expect", [
    ("chr1", Interval("chr1", 1, (1 << 31) - 1)),
    ("chr1:500", Interval("chr1", 500, 500)),
    ("chr1:500-", Interval("chr1", 500, (1 << 31) - 1)),
    ("chr1:500-900", Interval("chr1", 500, 900)),
    ("chr1:1,000-2,000", Interval("chr1", 1000, 2000)),
])
def test_parse_interval(text, expect):
    assert parse_interval(text) == expect


def test_parse_interval_errors():
    with pytest.raises(IntervalError):
        parse_interval("chr1:9-3")
    assert len(parse_intervals("chr1:1-10, chr2 ,chr3:5")) == 3


def test_parse_intervals_resolves_colon_contigs():
    # GRCh38-style contig containing ':' resolves verbatim when known
    ivs = parse_intervals("HLA-A*01:01", ref_names=["chr1", "HLA-A*01:01"])
    assert ivs == [Interval("HLA-A*01:01")]


def test_unknown_contig_raises(tmp_path):
    import hadoop_bam_tpu as hb
    header = make_header()
    path = _write(tmp_path, header, make_records(header, 5, seed=1),
                  "unk.bam")
    ds = hb.open_bam(path, HBamConfig(bam_intervals="chrX:1-100"))
    with pytest.raises(IntervalError):
        list(ds.batches())


def test_flagstat_respects_intervals(tmp_path):
    import hadoop_bam_tpu as hb
    header = make_header()
    recs = make_records(header, 200, seed=33)
    path = _write(tmp_path, header, recs, "fs.bam")
    ds = hb.open_bam(path, HBamConfig(bam_intervals="chr2"))
    stats = ds.flagstat()
    expect = sum(1 for r in recs if r.rname == "chr2")
    assert stats["total"] == expect


def _write(tmp_path, header, recs, name="t.bam"):
    path = str(tmp_path / name)
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    return path


def test_interval_filtering_exact_overlap(tmp_path):
    import hadoop_bam_tpu as hb
    header = make_header()
    # reads with known spans: pos 100 len 50 (ends 149), a deletion-extended
    # one, a soft-clipped one whose span is shorter than its seq
    recs = [
        SamRecord("a", 0, "chr1", 100, 60, "50M", "*", 0, 0, "A" * 50, "I" * 50),
        SamRecord("b", 0, "chr1", 200, 60, "10M30D10M", "*", 0, 0,
                  "A" * 20, "I" * 20),                     # span 200..249
        SamRecord("c", 0, "chr1", 300, 60, "40S10M", "*", 0, 0,
                  "A" * 50, "I" * 50),                     # span 300..309
        SamRecord("d", 0, "chr2", 100, 60, "50M", "*", 0, 0, "A" * 50, "I" * 50),
    ]
    path = _write(tmp_path, header, recs)

    def names(intervals):
        cfg = HBamConfig(bam_intervals=intervals)
        ds = hb.open_bam(path, cfg)
        return [b.read_name(i) for b in ds.batches() for i in range(len(b))]

    assert names("chr1:140-199") == ["a"]          # overlaps a's tail only
    assert names("chr1:150-199") == []             # gap between a and b
    assert names("chr1:249-249") == ["b"]          # deletion extends b's span
    assert names("chr1:310-400") == []             # soft clip does not
    assert names("chr1:309-400") == ["c"]
    assert names("chr2") == ["d"]
    assert sorted(names("chr1:100-300,chr2")) == ["a", "b", "c", "d"]


def test_interval_filtering_bulk_matches_bruteforce(tmp_path):
    import hadoop_bam_tpu as hb
    from hadoop_bam_tpu.tools.cli import _alen
    header = make_header()
    recs = make_records(header, 400, seed=21)
    path = _write(tmp_path, header, recs)
    iv = "chr1:200000-600000,chr3:1-50000"
    cfg = HBamConfig(bam_intervals=iv)
    got = {b.read_name(i) for b in hb.open_bam(path, cfg).batches()
           for i in range(len(b))}
    expect = set()
    for r in recs:
        end = r.pos + max(1, _alen(r)) - 1
        if r.rname == "chr1" and r.pos <= 600000 and end >= 200000:
            expect.add(r.qname)
        if r.rname == "chr3" and r.pos <= 50000:
            expect.add(r.qname)
    assert got == expect


def test_keep_paired_reads_together(tmp_path):
    import hadoop_bam_tpu as hb
    header = make_header()
    # queryname-grouped BAM: every name appears exactly twice, adjacent
    recs = []
    for i in range(600):
        for j, flag in enumerate((99, 147)):
            l = 100
            recs.append(SamRecord(
                f"pair{i:05d}", flag, "chr1", 1000 + i, 60, f"{l}M",
                "=", 1000 + i, l, "A" * l, "I" * l))
    path = _write(tmp_path, header, recs)
    cfg = HBamConfig(keep_paired_reads_together=True, split_size=1 << 16)
    ds = hb.open_bam(path, cfg)
    spans = ds.spans(num_spans=7)
    assert len(spans) >= 2
    all_names = []
    for span in spans:
        b = ds.read_span(span)
        names = [b.read_name(i) for i in range(len(b))]
        all_names.extend(names)
        # no span starts in the middle of a name group
        counts = {}
        for n in names:
            counts[n] = counts.get(n, 0) + 1
        # every name in this span appears exactly twice (whole pairs only)
        assert all(c == 2 for c in counts.values()), (span, counts)
    assert all_names == [r.qname for r in recs]


def test_reference_span_column(tmp_path):
    header = make_header()
    recs = [
        SamRecord("a", 0, "chr1", 10, 60, "10M5I10M", "*", 0, 0,
                  "A" * 25, "I" * 25),
        SamRecord("b", 0, "chr1", 10, 60, "5S10M100N10M", "*", 0, 0,
                  "A" * 25, "I" * 25),
        SamRecord("c", 4, "*", 0, 0, "*", "*", 0, 0, "A" * 30, "I" * 30),
    ]
    import hadoop_bam_tpu as hb
    path = _write(tmp_path, header, recs)
    ds = hb.open_bam(path)
    b = next(iter(ds.batches()))
    assert list(b.reference_span()) == [20, 120, 30]
    sub = b.select(np.array([2, 0]))
    assert [sub.read_name(i) for i in range(len(sub))] == ["c", "a"]


def test_mesh_flagstat_honors_intervals(tmp_path):
    """flagstat/seq_stats through the mesh path count only interval-
    overlapping records, matching the host-filtered oracle."""
    import dataclasses

    from fixtures import make_header, make_records
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.ops.flagstat import flagstat_from_batch

    header = make_header()
    records = make_records(header, 3000, seed=31)
    path = str(tmp_path / "iv.bam")
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    iv = f"{header.ref_names[0]}:1000-40000"
    cfg = dataclasses.replace(DEFAULT_CONFIG, bam_intervals=iv)
    ds = open_bam(path, cfg)
    stats = ds.flagstat()

    # oracle: host batch filter over the same spans
    plain = open_bam(path)
    expect = {}
    for span in plain.spans():
        batch = ds.read_span(span)  # read_span applies the interval filter
        flagstat_from_batch(batch, expect)
    assert 0 < stats["total"] < len(records)
    assert stats["total"] == expect["total"]
    assert stats["mapped"] == expect["mapped"]

    sstats = ds.seq_stats()
    assert sstats["n_reads"] == stats["total"]


def _sorted_bam(tmp_path, n=4000, seed=17):
    from fixtures import make_header, make_records
    from hadoop_bam_tpu.formats.bamio import BamWriter

    header = make_header()
    records = make_records(header, n, seed=seed)
    rid = {name: i for i, name in enumerate(header.ref_names)}
    records.sort(key=lambda r: (rid.get(r.rname, 1 << 30), r.pos))
    path = str(tmp_path / "sorted.bam")
    with BamWriter(path, header) as w:
        for r in records:
            w.write_sam_record(r)
    return path, header, records


def test_bai_round_trip_and_query(tmp_path):
    from hadoop_bam_tpu.split.bai import (
        BaiIndex, build_bai, reg2bin, reg2bins,
    )

    # spec arithmetic sanity
    assert reg2bin(0, 1) == 4681
    assert reg2bin(0, 1 << 29) == 0
    assert 4681 in reg2bins(0, 100)
    assert 0 in reg2bins(0, 100)

    path, header, records = _sorted_bam(tmp_path)
    idx = build_bai(path)
    back = BaiIndex.from_bytes(idx.to_bytes())
    assert len(back.refs) == len(header.ref_names)
    ranges = back.query(0, 0, 1 << 29)
    assert ranges and ranges[0][0] < ranges[-1][1]
    # a region beyond all data yields nothing
    assert back.query(0, (1 << 28), (1 << 28) + 100) == []


def test_bai_chunk_ends_are_block_aligned(tmp_path):
    """Chunk END voffsets must carry real block-boundary coffsets: the
    old final-record fallback packed (coffset+1, 0) — one BYTE past the
    block start — which BGZFReader-based chunk reads tolerated by
    accident but block-table consumers (plan_interval_spans ->
    coverage's raw span fetch) died on mid-block with 'truncated BGZF
    header'."""
    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.split.bai import build_bai, plan_interval_spans
    from hadoop_bam_tpu.split.intervals import resolve_interval
    from hadoop_bam_tpu.utils.seekable import as_byte_source

    path, header, _records = _sorted_bam(tmp_path)
    idx = build_bai(path)
    src = as_byte_source(path)

    def at_block_boundary(coffset):
        if coffset >= src.size:
            return True
        bgzf.parse_block_header(src.pread(coffset, 1 << 16), 0)
        return True

    n_chunks = 0
    for ref in idx.refs:
        for chunks in ref.bins.values():
            for beg, end in chunks:
                n_chunks += 1
                assert at_block_boundary(beg >> 16)
                assert at_block_boundary(end >> 16)
    assert n_chunks > 0

    # the exact failing composition: interval spans from the BAI feed
    # the raw-fetch + block-table path (what coverage_file does)
    from hadoop_bam_tpu.ops import inflate as inflate_ops
    from hadoop_bam_tpu.parallel.pipeline import _fetch_span_raw

    iv = resolve_interval(f"{header.ref_names[0]}:1-100000000",
                          header.ref_names)
    spans = plan_interval_spans(path, [iv], header, bai=idx)
    assert spans
    for span in spans:
        raw, _end_block, _next_c = _fetch_span_raw(src, span)
        table = inflate_ops.block_table(raw)   # raises on mid-block ends
        assert int(table["isize"].sum()) > 0
    src.close()


def test_bai_split_trimming_matches_full_scan(tmp_path):
    import dataclasses

    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.split.bai import write_bai

    path, header, records = _sorted_bam(tmp_path)
    iv = f"{header.ref_names[0]}:5000-20000"
    cfg = dataclasses.replace(DEFAULT_CONFIG, bam_intervals=iv)

    # full-scan (no .bai yet): plans over the whole file + row filter
    full = open_bam(path, cfg).flagstat()

    write_bai(path)
    ds = open_bam(path, cfg)
    spans = ds.spans()
    import os
    assert sum(s.compressed_size for s in spans) < os.path.getsize(path), \
        "BAI trimming should read less than the whole file"
    trimmed = ds.flagstat()
    assert trimmed == full
    assert 0 < trimmed["total"] < len(records)

    # seq stats agree too
    assert ds.seq_stats()["n_reads"] == trimmed["total"]


def test_csi_round_trip_and_query_matches_bai(tmp_path):
    """CSI round-trips and answers interval queries like the BAI it was
    derived from; split trimming works through a .csi sidecar alone."""
    import dataclasses
    import os

    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.split.bai import (
        CsiIndex, build_bai, csi_reg2bins, reg2bins,
    )

    # at 14/5 geometry CSI bins == BAI bins
    assert csi_reg2bins(5000, 20000, 14, 5) == sorted(reg2bins(5000, 20000))

    path, header, records = _sorted_bam(tmp_path, n=3000, seed=19)
    bai = build_bai(path)
    csi = CsiIndex.from_bai(bai)
    back = CsiIndex.from_bytes(csi.to_bytes())
    assert back.min_shift == 14 and back.depth == 5
    for beg, end in ((0, 30000), (5000, 20000), (100000, 200000)):
        assert back.query(0, beg, end) == bai.query(0, beg, end) or \
            back.query(0, beg, end)  # CSI lacks the linear-index clip, so
        # its ranges may start earlier; they must still COVER the BAI's
        b_r, c_r = bai.query(0, beg, end), back.query(0, beg, end)
        if b_r:
            assert c_r and c_r[0][0] <= b_r[0][0] and \
                c_r[-1][1] >= b_r[-1][1]

    # adversarial loffset case: a long record in an ancestor bin overlaps
    # a leaf bin whose own chunks start later; with an unset linear window
    # the leaf's loffset must NOT prune the ancestor's chunk
    from hadoop_bam_tpu.split.bai import BaiIndex, RefIndex
    adv = BaiIndex(refs=[RefIndex(
        bins={73: [(100, 200)],          # record A: pos 20000-140000
              585: [(200, 300)]},        # record B: pos 35000-50000
        # linear windows 0..4 unset (no record STARTS there after A),
        # window 1 holds A's start
        linear=[0, 100, 100, 100, 100, 100])])
    adv_csi = CsiIndex.from_bai(adv)
    got = adv_csi.query(0, 81920, 81921)     # window 5, only A overlaps
    assert got and got[0][0] <= 100, got     # A's chunk must survive

    # full-scan oracle BEFORE any sidecar exists
    iv = f"{header.ref_names[0]}:5000-20000"
    cfg = dataclasses.replace(DEFAULT_CONFIG, bam_intervals=iv)
    full = open_bam(path, cfg).flagstat()
    assert 0 < full["total"] < len(records)

    # interval trimming via .csi only (no .bai written)
    open(path + ".csi", "wb").write(csi.to_bytes())
    ds = open_bam(path, cfg)
    spans = ds.spans()
    assert sum(s.compressed_size for s in spans) < os.path.getsize(path)
    assert ds.flagstat() == full


def test_resolve_interval_colon_contigs():
    """samtools-style resolution: verbatim contig wins; else longest
    known contig prefix + range; else plain grammar."""
    from hadoop_bam_tpu.split.intervals import Interval, resolve_interval
    refs = ["chr1", "HLA-A*01:01", "HLA-A*01:01:02"]
    assert resolve_interval("HLA-A*01:01", refs) == Interval("HLA-A*01:01")
    got = resolve_interval("HLA-A*01:01:5-10", refs)
    assert got == Interval("HLA-A*01:01", 5, 10)
    # longest known prefix wins over a shorter one
    got = resolve_interval("HLA-A*01:01:02:7", refs)
    assert got.rname == "HLA-A*01:01:02" and got.start == got.end == 7
    assert resolve_interval("chr1:1,000-2,000", refs) == \
        Interval("chr1", 1000, 2000)
    # unknown names fall back to the plain grammar
    assert resolve_interval("chr9:5-6", refs) == Interval("chr9", 5, 6)


def test_resolve_interval_error_names_user_region():
    from hadoop_bam_tpu.split.intervals import IntervalError, resolve_interval

    with pytest.raises(IntervalError) as ei:
        resolve_interval("chr1:bogus-range", ref_names=["chr1"])
    msg = str(ei.value)
    assert "chr1:bogus-range" in msg and "'x:" not in msg
