"""Benchmark: the BASELINE.md measurement matrix, cumulative JSON lines.

Prints a cumulative JSON line after every component; the LAST stdout
line is the authoritative result (the driver parses the last line, so
an external kill at any moment costs at most the in-flight row).
The top-level keys keep the driver contract
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
for the headline metric (BAM decode records/sec/chip).  Progress lines
carry the FULL matrix
    "components": [ {metric, value, unit[, vs_baseline]}, ... ]
(BASELINE.md rows: BGZF inflate GB/s, CRAM records/s, VCF and BCF
variants/s, FASTQ reads/s, split-guess p50 latency) so per-component
regressions are visible in BENCH_r*.json; every full line is followed
by a compact twin — ``components: {metric: value}`` + ``scaling:
[[n_dev, rec_s]]``, under FINAL_LINE_BUDGET (~1.5 KB) — so the LAST
stdout line parses inside the driver's 2000-char tail no matter when
an external kill lands.

- Baselines, where present, are measured in-process on this host:
  single-thread zlib + NumPy decode (the htsjdk-single-thread analog;
  pysam/htsjdk are not in the image).
- Measured paths run on the default JAX device (the real TPU chip when
  present) through the same drivers the library exposes.

Fixture sizes scale with env vars (BENCH_RECORDS etc.) so a quick smoke
run is cheap; fixtures cache under bench_data/.
"""
from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np

BENCH_RECORDS = int(os.environ.get("BENCH_RECORDS", "300000"))
CRAM_RECORDS = int(os.environ.get("BENCH_CRAM_RECORDS", "20000"))
VCF_RECORDS = int(os.environ.get("BENCH_VCF_RECORDS", "100000"))
# same default count as the VCF fixture ON PURPOSE: the acceptance bar
# compares bcf_variants_per_sec against vcf_variants_per_sec directly
BCF_RECORDS = int(os.environ.get("BENCH_BCF_RECORDS", str(VCF_RECORDS)))
FASTQ_RECORDS = int(os.environ.get("BENCH_FASTQ_RECORDS", "200000"))
BENCH_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_data")
BENCH_BAM = os.path.join(BENCH_DIR, f"bench_{BENCH_RECORDS}.bam")

_HDR_TEXT = ("@HD\tVN:1.6\tSO:coordinate\n"
             "@SQ\tSN:chr20\tLN:64444167\n@SQ\tSN:chr21\tLN:46709983\n")

# ---------------------------------------------------------------------------
# resilience: the driver contract is JSON on stdout (last line wins), rc=0 —
# always.  The TPU backend behind the tunnel can fail to init or hang outright
# (BENCH_r03 was lost to exactly that), so:
#   * the backend is probed in a SUBPROCESS with a timeout and retries;
#     on terminal failure the run falls back to CPU and records it;
#   * every component is error-isolated (a broken row becomes an
#     {"error": ...} entry, never a crash);
#   * a watchdog thread emits whatever has been measured so far and
#     exits 0 if the whole run would blow its deadline.
# ---------------------------------------------------------------------------

PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "45"))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "2"))
# r3 and r4 were both lost to the driver's *external* timeout (rc=124)
# killing a run whose single JSON line only appeared at the very end.
# Two defenses now:  the internal deadline defaults well under any
# plausible external budget, and the cumulative JSON line is re-printed
# after EVERY component (the driver parses the last line, so a kill at
# any moment costs at most the in-flight row, never the round).
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "420"))
SCALING_DEVICES = (1, 8, 2, 4)   # endpoints first: a truncated curve
                                 # still brackets the scaling range

_T0 = time.monotonic()
_EMITTED = threading.Event()
_EMIT_LOCK = threading.Lock()
_STATE = {"platform": None, "notes": [], "components": [],
          "headline": None, "scaling": None}
# --trace FILE: record stage spans for the whole run and write a
# Chrome-trace JSON at the final emit (watchdog paths included)
_TRACE = {"path": None}


def _remaining() -> float:
    return DEADLINE_S - (time.monotonic() - _T0)


def _snapshot(status: str) -> dict:
    head = _STATE["headline"]
    if status == "ok" and head is None:
        # never report a failed headline as a measured 0.0-ok
        status = "partial"
        _STATE["notes"].append("headline measurement failed; see components")
    out = {
        "metric": "bam_decode_records_per_sec_per_chip",
        "value": head["value"] if head else 0.0,
        "unit": "records/s",
        "platform": _STATE["platform"] or "unknown",
        "status": status,
        "components": _STATE["components"],
    }
    if head and "vs_baseline" in head:
        out["vs_baseline"] = head["vs_baseline"]
    if _STATE["scaling"] is not None:
        out["scaling"] = _STATE["scaling"]
    if _STATE["notes"]:
        out["notes"] = _STATE["notes"]
    return out


# the driver tails ~2000 chars of stdout and parses the LAST line; the
# final line therefore MUST stay under this budget (BASELINE.md r5: the
# full snapshot grew past it and the round parsed as null)
FINAL_LINE_BUDGET = 1500


def _compact_snapshot(full: dict) -> dict:
    """The compact line derived from one already-built ``_snapshot``
    dict (never re-snapshots: ``_snapshot`` mutates notes on a missing
    headline): headline contract keys plus a compressed matrix —
    ``components`` as {metric: value} (errors/skips become the strings
    "error"/"skipped") and ``scaling`` as [[n_dev, flagstat rec/s],
    ...].  Full per-stage dicts stay on the paired full lines; this
    line exists to be parseable in a 2000-char stdout tail, and is
    hard-capped at FINAL_LINE_BUDGET bytes."""
    comp = {}
    for c in full["components"]:
        name = c.get("metric", "?")
        if isinstance(c.get("value"), (int, float)):
            comp[name] = c["value"]
        elif "error" in c:
            comp[name] = "error"
        else:
            comp[name] = "skipped"
    out = {
        "metric": full["metric"], "value": full["value"],
        "unit": full["unit"], "platform": full["platform"],
        "status": full["status"], "components": comp,
    }
    if "vs_baseline" in full:
        out["vs_baseline"] = full["vs_baseline"]
    # compact latency component (r9): warm region-query p50/p99 ms from
    # the query.latency_s histogram — the serving numbers a deadline
    # contract is written against, small enough to ride the final line
    rq = next((c for c in full["components"]
               if c.get("metric") == "region_query_queries_per_sec"
               and isinstance(c.get("latency_p50_ms"), (int, float))),
              None)
    if rq is not None:
        out["latency"] = [rq["latency_p50_ms"], rq["latency_p99_ms"]]
    scaling = full.get("scaling")
    if isinstance(scaling, dict):
        rows = [[r["n_devices"], r["flagstat_records_per_sec"]]
                for r in scaling.get("devices", [])
                if isinstance(r.get("flagstat_records_per_sec"),
                              (int, float))]
        if rows:
            out["scaling"] = sorted(rows)
    if full.get("notes"):
        out["notes"] = "; ".join(full["notes"])[:160]
    while len(json.dumps(out)) > FINAL_LINE_BUDGET:
        for k in ("notes", "latency", "scaling", "components"):
            if k in out:
                del out[k]
                break
        else:
            break
    return out


def _emit_pair(status: str) -> None:
    """One cumulative FULL line (the per-stage detail) followed by its
    compact twin — so the LAST stdout line is parseable within the
    driver's tail no matter when an external kill lands, even between
    components (the r3/r4/r5 loss modes, all three)."""
    full = _snapshot(status)
    print(json.dumps(full), flush=True)
    print(json.dumps(_compact_snapshot(full)), flush=True)


def _save_trace() -> None:
    """Flush the --trace span ring to its Chrome-trace file (called on
    every final-emit path so the watchdog's timeout exit keeps whatever
    was recorded)."""
    if not _TRACE["path"]:
        return
    try:
        from hadoop_bam_tpu.obs import active_recorder
        rec = active_recorder()
        if rec is not None:
            rec.save(_TRACE["path"])
    except Exception:  # noqa: BLE001 — tracing must never cost the run
        pass


def _emit_progress() -> None:
    with _EMIT_LOCK:
        if _EMITTED.is_set():
            return
        _emit_pair("partial")


def _emit(status: str) -> None:
    # watchdog + main thread can race here; exactly one may print the
    # final pair (progress lines before it are superseded, by contract)
    with _EMIT_LOCK:
        if _EMITTED.is_set():
            return
        _EMITTED.set()
        _emit_pair(status)
        _save_trace()


_CHILD = {"proc": None}   # in-flight scaling subprocess, for watchdog kill


def _watchdog() -> None:
    while not _EMITTED.is_set():
        if _remaining() <= 0:
            _STATE["notes"].append(
                f"deadline {DEADLINE_S:.0f}s reached; partial results")
            _emit("timeout")
            proc = _CHILD["proc"]
            if proc is not None:   # don't orphan a running scaling child
                try:
                    proc.kill()
                except OSError:
                    pass
            os._exit(0)
        time.sleep(min(5.0, max(0.5, _remaining())))


def _enable_compile_cache(role: str = "main") -> None:
    """Persistent XLA compile cache under bench_data/: rounds after the
    first hit the cache instead of re-paying every jit/scan compile
    (tens of seconds each on the tunneled chip) inside the budget.

    Separate cache dirs per process ROLE: the axon-plugin main process
    and the pure-CPU scaling children compile CPU executables with
    different target-feature sets, and loading the other role's AOT
    entries makes XLA warn about possible SIGILL (observed on the r5
    full-size run) — each role only ever reads entries it wrote."""
    import jax

    try:
        cache_dir = os.path.join(BENCH_DIR, "jax_cache", role)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass   # cache is an optimization, never a requirement


_PROBE_SRC = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "x = float(jnp.ones((256, 256)).sum())\n"
    "assert x == 65536.0, x\n"
    "print('HBAM_PROBE_OK', d[0].platform, len(d))\n"
)


def acquire_platform() -> str:
    """Pick the JAX platform for this run, never raising.

    The default backend (the tunneled TPU, when present) is exercised in a
    throwaway subprocess first: a hung or UNAVAILABLE plugin then costs a
    bounded timeout instead of the whole benchmark.  ``BENCH_PLATFORM=cpu``
    forces the fallback (note: the JAX_PLATFORMS env var is overridden by
    the axon plugin, so the forcing is done via jax.config in-process).
    """
    import jax

    _enable_compile_cache()
    forced = os.environ.get("BENCH_PLATFORM", "").strip().lower()
    if forced and forced != "cpu":
        _STATE["notes"].append(
            f"BENCH_PLATFORM={forced!r} not supported (only 'cpu' forces "
            "a backend); probing the default backend instead")
        forced = ""
    if forced == "cpu":
        jax.config.update("jax_platforms", "cpu")
        _STATE["notes"].append("platform forced to cpu via BENCH_PLATFORM")
    elif not forced:
        ok = False
        for attempt in range(PROBE_ATTEMPTS):
            budget = min(PROBE_TIMEOUT_S, max(30.0, _remaining() - 120))
            try:
                r = subprocess.run(
                    [sys.executable, "-c", _PROBE_SRC],
                    capture_output=True, text=True, timeout=budget)
                if r.returncode == 0 and "HBAM_PROBE_OK" in r.stdout:
                    ok = True
                    break
                err = r.stderr.strip().splitlines()
                _STATE["notes"].append(
                    f"backend probe {attempt + 1}/{PROBE_ATTEMPTS} failed "
                    f"rc={r.returncode}: {err[-1][:200] if err else ''}")
            except subprocess.TimeoutExpired:
                _STATE["notes"].append(
                    f"backend probe {attempt + 1}/{PROBE_ATTEMPTS} timed "
                    f"out after {budget:.0f}s")
            time.sleep(2.0)
        if not ok:
            jax.config.update("jax_platforms", "cpu")
            _STATE["notes"].append(
                "default backend unusable after probes; cpu fallback")
    try:
        devs = jax.devices()
    except Exception as e:  # probe passed but in-process init still died
        jax.config.update("jax_platforms", "cpu")
        _STATE["notes"].append(
            f"in-process backend init failed ({type(e).__name__}); "
            "cpu fallback")
        devs = jax.devices()
    return devs[0].platform


def _run_component(fn, label: str, est_s: float = 30.0) -> None:
    """Append fn()'s component dict; convert failures into error rows.

    ``est_s`` is the component's expected cost: it is skipped (with a
    row saying so) rather than started when the remaining budget could
    not absorb it — a skipped row is recoverable next round, a run
    that straddles the external kill loses the in-flight row."""
    if _remaining() < est_s + 20:
        _STATE["components"].append({"metric": label, "skipped": "deadline"})
        _emit_progress()
        return
    try:
        _STATE["components"].append(fn())
    except Exception as e:
        _STATE["components"].append(
            {"metric": label, "error": f"{type(e).__name__}: {e}"})
    _emit_progress()


_MEDIAN_REPS = 2   # timed reps per row; every call runs 1 warmup more


def _median_time(fn, reps: int = _MEDIAN_REPS):
    """Lower-median wall time of fn() over reps runs (first result
    returned): best-of for reps=2, true median for odd reps — never the
    max, so one GC/IO hiccup can't define a row.  reps default dropped
    3 -> 2 to fit the full matrix plus scaling inside the 420s budget
    (the r5 full-size run skipped scaling + kernels at reps=3)."""
    out = fn()  # warmup (jit compile, file cache)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        times.append(time.perf_counter() - t0)
    return out, sorted(times)[(len(times) - 1) // 2]


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

_SIDECAR_EXTS = (".bai", ".tbi", ".sbi", ".splitting-bai", ".csi")


def _heal_stale_sidecars(data_path: str) -> list:
    """Remove gitignored index sidecars OLDER than their fixture.

    bench_data/ persists across rounds while the code does not: a
    ``.bai`` written by an older build (the PR-8 chunk-end bug era)
    next to a newer fixture silently poisons every consumer that trusts
    the sidecar — the recurring "truncated BGZF header" scaling-child
    failure recorded in ROADMAP/CHANGES, which previously needed a
    manual ``rm``.  Deleting the stale sidecar is enough: every
    consumer path regenerates missing sidecars on demand."""
    removed = []
    try:
        data_mtime = os.path.getmtime(data_path)
    except OSError:
        return removed
    for ext in _SIDECAR_EXTS:
        sc = data_path + ext
        try:
            if os.path.exists(sc) and os.path.getmtime(sc) < data_mtime:
                os.remove(sc)
                removed.append(os.path.basename(sc))
        except OSError:
            continue                  # healing is best-effort
    if removed:
        _STATE["notes"].append(
            f"regenerated stale sidecar(s) {removed} for "
            f"{os.path.basename(data_path)}")
    return removed


def _purge_sidecars(data_path: str) -> list:
    """Remove EVERY sidecar of a fixture regardless of mtime — the
    recovery path when a scaling child dies with 'truncated BGZF
    header' (a sidecar can be newer than its fixture yet written by
    broken code; the error names the poison, so believe it)."""
    removed = []
    for ext in _SIDECAR_EXTS:
        sc = data_path + ext
        try:
            if os.path.exists(sc):
                os.remove(sc)
                removed.append(os.path.basename(sc))
        except OSError:
            continue
    return removed


def build_fixture() -> str:
    if os.path.exists(BENCH_BAM):
        return BENCH_BAM
    os.makedirs(BENCH_DIR, exist_ok=True)
    from hadoop_bam_tpu.formats.bam import SAMHeader, encode_record
    from hadoop_bam_tpu.formats.bamio import BamWriter

    from hadoop_bam_tpu.config import DEFAULT_CONFIG

    header = SAMHeader.from_sam_text(_HDR_TEXT)
    rng = random.Random(1234)
    bases = "ACGT"
    # fixture BGZF level rides the same config knob as every producing
    # path (hbam.write-compress-level), so fixture bytes and write-path
    # output stay comparable
    with BamWriter(BENCH_BAM + ".tmp", header,
                   level=DEFAULT_CONFIG.write_compress_level) as w:
        pos = 1
        for i in range(BENCH_RECORDS):
            l = 151
            seq = "".join(rng.choice(bases) for _ in range(l))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(l))
            pos += rng.randint(0, 40)
            flag = 99 if i % 2 == 0 else 147
            rec = encode_record(
                name=f"read{i:09d}", flag=flag, refid=0, pos=pos, mapq=60,
                cigar=[(l, "M")], mate_refid=0, mate_pos=pos + 200, tlen=351,
                seq=seq, qual=qual,
                tags=[("NM", "i", rng.randint(0, 4)), ("RG", "Z", "rg0")])
            w.write_record_bytes(rec)
    os.replace(BENCH_BAM + ".tmp", BENCH_BAM)
    return BENCH_BAM


def build_cram_fixture() -> str:
    path = os.path.join(BENCH_DIR, f"bench_{CRAM_RECORDS}.cram")
    if os.path.exists(path):
        return path
    from hadoop_bam_tpu.api.writers import CramShardWriter
    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.sam import SamRecord

    header = SAMHeader.from_sam_text(_HDR_TEXT)
    rng = random.Random(99)
    pos = 1
    with CramShardWriter(path + ".tmp", header) as w:
        for i in range(CRAM_RECORDS):
            l = 151
            seq = "".join(rng.choice("ACGT") for _ in range(l))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(l))
            pos += rng.randint(0, 40)
            w.write_sam_record(SamRecord(
                qname=f"read{i:09d}", flag=99 if i % 2 == 0 else 147,
                rname="chr20", pos=pos, mapq=60, cigar=f"{l}M",
                rnext="=", pnext=pos + 200, tlen=351, seq=seq, qual=qual))
    os.replace(path + ".tmp", path)
    return path


def build_vcf_fixture() -> str:
    path = os.path.join(BENCH_DIR, f"bench_{VCF_RECORDS}.vcf.gz")
    if os.path.exists(path):
        return path
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord

    hdr_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr20,length=64444167>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
        "s0\ts1\ts2\n")
    header = VCFHeader.from_text(hdr_text)
    rng = random.Random(77)
    gts = ["0/0", "0/1", "1/1", "./."]
    with open_vcf_writer(path + ".tmp.vcf.gz", header) as w:
        pos = 1
        for i in range(VCF_RECORDS):
            pos += rng.randint(1, 50)
            ref = rng.choice("ACGT")
            alt = rng.choice([c for c in "ACGT" if c != ref])
            g = "\t".join(rng.choice(gts) for _ in range(3))
            w.write_record(VcfRecord.from_line(
                f"chr20\t{pos}\t.\t{ref}\t{alt}\t{30 + i % 40}\tPASS\t"
                f"DP={i % 100}\tGT\t{g}"))
    os.replace(path + ".tmp.vcf.gz", path)
    return path


def build_bcf_fixture() -> str:
    """BGZF BCF twin of the VCF fixture: same schema, same record shape,
    so the two variant-stats rows are directly comparable."""
    path = os.path.join(BENCH_DIR, f"bench_{BCF_RECORDS}.bcf")
    if os.path.exists(path):
        return path
    os.makedirs(BENCH_DIR, exist_ok=True)
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord

    hdr_text = (
        "##fileformat=VCFv4.2\n"
        "##contig=<ID=chr20,length=64444167>\n"
        '##INFO=<ID=DP,Number=1,Type=Integer,Description="Depth">\n'
        '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
        "s0\ts1\ts2\n")
    header = VCFHeader.from_text(hdr_text)
    rng = random.Random(77)
    gts = ["0/0", "0/1", "1/1", "./."]
    tmp = path + ".tmp.bcf"
    with open_vcf_writer(tmp, header) as w:
        pos = 1
        for i in range(BCF_RECORDS):
            pos += rng.randint(1, 50)
            ref = rng.choice("ACGT")
            alt = rng.choice([c for c in "ACGT" if c != ref])
            g = "\t".join(rng.choice(gts) for _ in range(3))
            w.write_record(VcfRecord.from_line(
                f"chr20\t{pos}\t.\t{ref}\t{alt}\t{30 + i % 40}\tPASS\t"
                f"DP={i % 100}\tGT\t{g}"))
    os.replace(tmp, path)
    return path


def build_fastq_fixture() -> str:
    path = os.path.join(BENCH_DIR, f"bench_{FASTQ_RECORDS}.fastq")
    if os.path.exists(path):
        return path
    rng = random.Random(55)
    with open(path + ".tmp", "w") as f:
        for i in range(FASTQ_RECORDS):
            seq = "".join(rng.choice("ACGT") for _ in range(151))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(151))
            f.write(f"@read{i:09d}\n{seq}\n+\n{qual}\n")
    os.replace(path + ".tmp", path)
    return path


# ---------------------------------------------------------------------------
# 1. BAM decode (headline)
# ---------------------------------------------------------------------------

def baseline_single_thread(path: str) -> float:
    """records/sec: single-thread zlib + NumPy full fixed-field decode."""
    import zlib

    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.formats.bam import (
        BamBatch, SAMHeader, walk_record_offsets,
    )

    raw = open(path, "rb").read()
    t0 = time.perf_counter()
    chunks = []
    for info in bgzf.scan_blocks(raw):
        if info.isize:
            chunks.append(zlib.decompress(
                raw[info.cdata_offset:info.cdata_offset + info.cdata_size],
                wbits=-15))
    data = b"".join(chunks)
    _, after = SAMHeader.from_bam_bytes(data)
    offs = walk_record_offsets(data, start=after)
    batch = BamBatch(np.frombuffer(data, dtype=np.uint8), offs)
    # force full fixed-field decode (the htsjdk-decode-equivalent work)
    for name in ("refid", "pos", "flag", "mapq", "l_seq", "mate_refid",
                 "mate_pos", "tlen", "bin", "n_cigar", "l_read_name"):
        getattr(batch, name)
    n = len(batch)
    dt = time.perf_counter() - t0
    return n / dt


def measured_pipeline(path: str) -> float:
    """records/sec/chip: threaded native inflate + device unpack/flagstat."""
    import jax

    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.parallel.pipeline import (
        DecodeGeometry, flagstat_file,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh()
    geometry = DecodeGeometry()
    header, _ = read_bam_header(path)

    def run():
        return flagstat_file(path, mesh=mesh, geometry=geometry,
                             header=header)

    # lower-median-of-3 for the HEADLINE (one extra rep vs the matrix
    # default: the tunneled link is jittery and this is the row the
    # round is judged on)
    stats, dt = _median_time(run, reps=3)
    return stats["total"] / dt / n_dev


# ---------------------------------------------------------------------------
# 2. BGZF inflate GB/s
# ---------------------------------------------------------------------------

def bench_bgzf_inflate(path: str):
    import zlib

    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.ops import inflate as inflate_ops

    raw_b = open(path, "rb").read()

    def native_run():
        table = inflate_ops.block_table(raw_b)
        data, _ = inflate_ops.inflate_span(raw_b, table)
        return data.size

    isize, dt = _median_time(native_run)

    # single-thread zlib baseline, one timed pass
    t0 = time.perf_counter()
    total = 0
    for info in bgzf.scan_blocks(raw_b):
        if info.isize:
            total += len(zlib.decompress(
                raw_b[info.cdata_offset:info.cdata_offset + info.cdata_size],
                wbits=-15))
    base_dt = time.perf_counter() - t0
    gbps = isize / dt / 1e9
    base_gbps = total / base_dt / 1e9
    return {"metric": "bgzf_inflate_gbps", "value": round(gbps, 3),
            "unit": "GB/s", "vs_baseline": round(gbps / base_gbps, 3)}


def bench_fault_resilience(path: str):
    """Throughput under injected transient faults (the resilience-layer
    chaos hook): flagstat with a handful of injected transient read
    failures healing under the classified span-retry policy, reported as
    the slowdown vs the clean pipeline.  Correctness is asserted (the
    faulted run must produce the clean answer with nothing quarantined),
    so this row doubles as an end-to-end resilience check."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.utils.resilient import FaultSpec, chaos_on
    import dataclasses

    header, _ = read_bam_header(path)
    # plan once OUTSIDE the chaos window: planning probes are not under
    # the span retry policy (a fault there is a planner bug, not the
    # resilience path this row measures)
    from hadoop_bam_tpu.parallel.pipeline import pipeline_span_count
    from hadoop_bam_tpu.split.planners import plan_spans_cached
    import jax
    spans = plan_spans_cached(
        path, header, DEFAULT_CONFIG,
        num_spans=pipeline_span_count(path, len(jax.devices()),
                                      DEFAULT_CONFIG))
    clean, clean_dt = _median_time(
        lambda: flagstat_file(path, header=header, spans=spans))
    cfg = dataclasses.replace(DEFAULT_CONFIG, span_retries=3,
                              retry_backoff_base_s=0.001,
                              retry_backoff_max_s=0.01)

    def chaotic():
        # budget of 2 faults vs span_retries=3: even if one span's retry
        # chain eats BOTH faults (possible — the shared budget drains by
        # read order, and a 1-span plan is legal), it still heals
        faults = [FaultSpec("transient", at_read=0, count=2)]
        with chaos_on(path, faults):
            return flagstat_file(path, header=header, spans=spans,
                                 config=cfg)

    stats, dt = _median_time(chaotic)
    if {k: stats[k] for k in clean} != clean:
        raise AssertionError("faulted flagstat diverged from clean run")
    rate = stats["total"] / dt
    return {"metric": "faulted_flagstat_records_per_sec",
            "value": round(rate, 1), "unit": "records/s",
            "vs_baseline": round(clean_dt / dt, 3)}


# ---------------------------------------------------------------------------
# 3. CRAM decode records/s
# ---------------------------------------------------------------------------

def bench_cram(path: str):
    """CRAM through the tensor path (device-resident payload batches), with
    the pure-Python record iterator as the in-process baseline."""
    from hadoop_bam_tpu.api.cram_dataset import open_cram

    def run():
        ds = open_cram(path)
        total = 0
        for batch in ds.tensor_batches():
            total += int(np.asarray(batch["n_records"]).sum())
        return total

    n, dt = _median_time(run)

    def base_run():
        ds = open_cram(path)
        return sum(1 for _ in ds.records())

    bn, bdt = _median_time(base_run)
    meas, base = n / dt, bn / bdt
    return {"metric": "cram_tensor_records_per_sec",
            "value": round(meas, 1), "unit": "records/s",
            "vs_baseline": round(meas / base, 3),
            # both paths share the per-record entropy decode; the tensor
            # path skips SamRecord/mate materialization but adds tile
            # packing + device transfer, so the ratio tracks that trade
            "note": "columnar tile path vs SamRecord iterator"}


# ---------------------------------------------------------------------------
# 4. VCF variants/s (device stats driver over BGZF VCF)
# ---------------------------------------------------------------------------

def bench_vcf(path: str):
    """Device variant-stats driver vs a single-thread pure-Python parse of
    the same file (the htsjdk-VCFCodec-analog baseline)."""
    import gzip

    from hadoop_bam_tpu.formats.vcf import VcfRecord
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file
    from hadoop_bam_tpu.utils.metrics import METRICS

    def run():
        return variant_stats_file(path)

    stats, dt = _median_time(run)

    # per-stage wall spans (satellite of the r9 query round): one extra
    # isolated run so the stage union-walls aren't summed over the
    # median reps.  Progress-line detail only — the compact final line
    # keeps just the numeric value.
    METRICS.reset()
    run()
    snap = METRICS.snapshot()
    vcf_stages = {k.split(".", 1)[1]: round(v, 4)
                  for k, v in snap["wall_timers"].items()
                  if k.startswith("vcf.")}
    METRICS.reset()

    def base_run():
        n = 0
        with gzip.open(path, "rt") as f:
            for line in f:
                if not line.startswith("#"):
                    VcfRecord.from_line(line.rstrip("\n"))
                    n += 1
        return n

    bn, bdt = _median_time(base_run)
    meas, base = stats["n_variants"] / dt, bn / bdt
    return {"metric": "vcf_variants_per_sec",
            "value": round(meas, 1), "unit": "variants/s",
            "vs_baseline": round(meas / base, 3),
            # wall-clock union spans per stage (Metrics.wall_timer):
            # inflate = BGZF span read, tokenize = grid tokenizer,
            # dosage_pack = GT columns, dispatch = device_put + step
            "vcf_stage_seconds": vcf_stages}


def bench_bcf(path: str):
    """Columnar BCF decode (formats/bcf_columns.py) through the same
    variant-stats driver.  vs_baseline compares against the text-VCF
    tokenizer row measured just before on the same variant count — the
    acceptance bar is binary >= text."""
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file

    stats, dt = _median_time(lambda: variant_stats_file(path))
    meas = stats["n_variants"] / dt
    out = {"metric": "bcf_variants_per_sec",
           "value": round(meas, 1), "unit": "variants/s"}
    vcf_row = next((c for c in _STATE["components"]
                    if c.get("metric") == "vcf_variants_per_sec"
                    and isinstance(c.get("value"), (int, float))
                    and c["value"] > 0), None)
    if vcf_row is not None and VCF_RECORDS == BCF_RECORDS:
        out["vs_baseline"] = round(meas / vcf_row["value"], 3)
        out["note"] = ("baseline = the text-VCF tokenizer driver row on "
                       "the same variant count")
    else:
        out["note"] = ("no vs_baseline: vcf_variants_per_sec row missing "
                       "or fixture sizes differ")
    return out


def _region_query_fixture(path: str):
    """(bam_path, regions): the 100k scaling BAM with a .bai sidecar and
    a zipf-skewed batch of >= 200 regions over it — hot windows repeat,
    so the warm pass exercises chunk-cache reuse the way a serving
    workload would."""
    bam = _scaling_fixture(path)
    _heal_stale_sidecars(bam)         # a stale .bai regenerates below
    if not os.path.exists(bam + ".bai"):
        from hadoop_bam_tpu.split.bai import write_bai
        write_bai(bam)
    rng = random.Random(4242)
    n_windows, width = 64, 200_000
    # fixture positions advance ~20/record from 1: ~100k records span
    # ~2 Mbp of chr20; windows tile that head
    starts = [1 + i * 30_000 for i in range(n_windows)]
    weights = [1.0 / (i + 1) for i in range(n_windows)]  # zipf s=1
    regions = []
    for _ in range(250):
        w = rng.choices(range(n_windows), weights=weights)[0]
        lo = starts[w]
        regions.append(f"chr20:{lo}-{lo + width - 1}")
    return bam, regions


def bench_region_query(path: str):
    """The query subsystem's serving row: zipf-skewed region queries via
    QueryEngine (BAI chunk resolution -> cached chunk decode -> device
    interval predicate).  Cold = fresh engine/cache; warm = same engine
    again; vs_baseline = warm/cold speedup (the cache's whole point)."""
    import numpy as np

    from hadoop_bam_tpu.query import QueryEngine, QueryRequest
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    bam, regions = _region_query_fixture(path)

    def run_pass(engine):
        matched = 0
        for region in regions:
            for out in engine.tensor_batches(
                    [QueryRequest(bam, region)]):
                matched += int(np.asarray(out["keep"]).sum())
        return matched

    engine = QueryEngine()
    run_pass(engine)              # warmup: jit compile only (fresh
    #                               engines below re-measure cold decode)
    cold_engine = QueryEngine()
    t0 = time.perf_counter()
    n_matched = run_pass(cold_engine)
    cold_dt = time.perf_counter() - t0

    s0 = cold_engine.stats()      # instance counters: warm-pass delta
    t0 = time.perf_counter()
    # run-scoped metrics: each region is a single-request batch, so the
    # warm pass's query.latency_s histogram IS the per-query latency
    # distribution — the p50/p99 a serving deadline is written against
    with MetricsContext() as warm_metrics:
        warm_matched = run_pass(cold_engine)   # same engine: warm cache
    warm_dt = time.perf_counter() - t0
    lat = warm_metrics.hist_summary("query.latency_s")
    s1 = cold_engine.stats()
    d_hits = s1["hits"] - s0["hits"]
    d_total = d_hits + s1["misses"] - s0["misses"]
    stats = {"hit_rate": d_hits / d_total if d_total else 0.0}

    if warm_matched != n_matched:
        raise AssertionError(
            f"warm pass matched {warm_matched} records vs cold "
            f"{n_matched} — cache served stale chunks")
    cold_qps = len(regions) / cold_dt
    warm_qps = len(regions) / warm_dt
    return {"metric": "region_query_queries_per_sec",
            "value": round(warm_qps, 1), "unit": "queries/s",
            # baseline = the cold pass: > 1 means cache reuse is real;
            # acceptance bar is >= 2x
            "vs_baseline": round(warm_qps / cold_qps, 3),
            "cold_queries_per_sec": round(cold_qps, 1),
            "cache_hit_rate": round(stats["hit_rate"], 4),
            "regions": len(regions),
            "records_matched": int(n_matched),
            # warm-pass per-query latency from the query.latency_s
            # histogram (run-scoped MetricsContext, so concurrent rows
            # cannot smear into it); also rides the compact FINAL line
            # as the "latency" component
            "latency_p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
            "latency_p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
            "note": "zipf-skewed 250-region batch over the 100k BAM; "
                    "warm pass re-serves decoded chunks from the LRU"}


def bench_region_serve(path: str):
    """The serving-tier saturation row, four arms on the zipf fixture:

    1. COLD: fresh ServeLoop (prefetch off), each DISTINCT window once
       — true first-touch latency (the zipf set repeats windows, so a
       naive cold pass self-warms and understates the decode cost).
    2. WARM: the full 250-query zipf set against the now-resident tiles
       — every query is a tile hit; p50/p99 + sustained q/s + the
       host-decode wall share (the bypass proof: ~0).
    3. CLIENTS: the warm set driven by 1 then 8 concurrent client
       threads against the one dispatcher — sustained q/s must not
       regress as clients scale.
    4. PREFETCH: a fresh loop with prefetch ON serving the zipf order —
       prefetch usefulness (useful/issued) and realistic first-pass
       tile hit rate.
    5. FLEET: two REAL replica subprocesses (rendezvous ownership,
       replication 1, hedged peer-fetch over TCP): wire q/s against 1
       then both endpoints, the cross-replica tile hit rate from the
       fleet counters (peer-fetched / decoded-anywhere), and the
       kill-one-replica arm — SIGKILL one replica and measure the
       surviving replica's client-observed p99 through the failover
       (every request must still answer; peer faults fall back to
       local decode, never to the client).

    Acceptance bars: warm tile-hit p50 >= 5x better than cold p50 (vs
    the 3.1-3.7x byte-LRU-only warm speedup of PR 5), warm host_decode
    share ~0, q/s(8 clients) >= q/s(1 client), zero failed fleet
    requests through the kill."""
    import dataclasses as _dc
    import threading as _th

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.serve import ServeLoop
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    bam, regions = _region_query_fixture(path)
    unique = list(dict.fromkeys(regions))
    quiet = _dc.replace(DEFAULT_CONFIG, serve_prefetch=False)

    with ServeLoop(config=quiet) as warmup:
        warmup.query(bam, [regions[0]])      # jit/mesh warmup only

    with ServeLoop(config=quiet) as loop:
        # -- arm 1: true cold (first touch, no prefetch, no repeats) --
        with MetricsContext() as cold_m:
            t0 = time.perf_counter()
            for region in unique:
                loop.query(bam, [region])
            cold_dt = time.perf_counter() - t0
        cold_lat = cold_m.hist_summary("serve.latency_s")

        # -- arm 2: warm zipf set, all tile hits ----------------------
        s0 = loop.tiles.stats()
        with MetricsContext() as warm_m:
            t0 = time.perf_counter()
            for region in regions:
                loop.query(bam, [region])
            warm_dt = time.perf_counter() - t0
        warm_lat = warm_m.hist_summary("serve.latency_s")
        s1 = loop.tiles.stats()
        d_hits = s1["hits"] - s0["hits"]
        d_total = d_hits + s1["misses"] - s0["misses"]
        tile_hit_rate = d_hits / d_total if d_total else 0.0
        warm_walls = warm_m.snapshot()["wall_timers"]
        warm_decode_share = (
            warm_walls.get("pipeline.host_decode_wall", 0.0)
            + warm_walls.get("query.decode_wall", 0.0)) / max(
            warm_dt, 1e-9)

        # -- arm 3: client scaling on the warm loop -------------------
        def qps_with_clients(c: int) -> float:
            slices = [regions[i::c] for i in range(c)]
            errs = []

            def client(idx, rs):
                try:
                    for region in rs:
                        loop.query(bam, [region], tenant=f"client{idx}")
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            t0 = time.perf_counter()
            ts = [_th.Thread(target=client, args=(i, rs))
                  for i, rs in enumerate(slices) if rs]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            return len(regions) / dt

        clients_qps = [[c, round(qps_with_clients(c), 1)]
                       for c in (1, 8)]

    # -- arm 4: prefetch usefulness on a fresh loop, zipf order -------
    with ServeLoop(config=DEFAULT_CONFIG) as pf_loop:
        p0 = pf_loop.tiles.stats()
        for region in regions:
            pf_loop.query(bam, [region])
        pf_loop.prefetcher.drain()
        prefetch = pf_loop.prefetcher.stats()
        p1 = pf_loop.tiles.stats()
        zipf_hits = p1["hits"] - p0["hits"]
        zipf_total = zipf_hits + p1["misses"] - p0["misses"]

    # -- arm 5: the replica fleet (2 subprocesses, SIGKILL failover) --
    fleet = _fleet_serve_arm(bam, regions)

    cold_qps = len(unique) / cold_dt
    warm_qps = len(regions) / warm_dt
    cold_p50 = cold_lat.get("p50", 0.0)
    warm_p50 = max(warm_lat.get("p50", 0.0), 1e-9)
    return {"metric": "region_serve_queries_per_sec",
            "value": round(warm_qps, 1), "unit": "queries/s",
            # baseline = first-touch cold p50; the bar is >= 5x
            "vs_baseline": round(cold_p50 / warm_p50, 3),
            "cold_queries_per_sec": round(cold_qps, 1),
            "tile_hit_rate": round(tile_hit_rate, 4),
            "zipf_first_pass_hit_rate": round(
                zipf_hits / zipf_total if zipf_total else 0.0, 4),
            "prefetch_hit_rate": round(prefetch["hit_rate"], 4),
            "prefetch_issued": int(prefetch["issued"]),
            "latency_p50_ms": round(warm_p50 * 1e3, 3),
            "latency_p99_ms": round(warm_lat.get("p99", 0.0) * 1e3, 3),
            "cold_p50_ms": round(cold_p50 * 1e3, 3),
            "warm_host_decode_share": round(warm_decode_share, 4),
            "clients_qps": clients_qps,
            "regions": len(regions),
            "distinct_windows": len(unique),
            **fleet,
            "note": ("zipf 250-region set via ServeLoop; cold = each "
                     "distinct window first-touch (prefetch off); warm "
                     "= all-tile-hit zipf set (no decode at all); "
                     "vs_baseline = cold_p50/warm_p50, bar >= 5x; "
                     "clients_qps pins 1->8 client saturation; "
                     "fleet_qps pins 1->2 replica endpoints, "
                     "fleet_kill_p99_ms the client-observed failover")}


_FLEET_REPLICA_SRC = """
import dataclasses, sys
import jax
jax.config.update("jax_platforms", "cpu")
from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.serve import ServeLoop, make_tcp_server
rid, port, peers, warm = sys.argv[1], int(sys.argv[2]), sys.argv[3], \\
    sys.argv[4]
cfg = dataclasses.replace(
    DEFAULT_CONFIG, serve_replica_id=rid, serve_peers=peers,
    fleet_replication=1, fleet_heartbeat_s=0.15, fleet_suspicion_s=0.6,
    fleet_eviction_s=1.5, breaker_cooldown_s=0.5,
    breaker_failure_threshold=2.0, serve_prefetch=False)
with ServeLoop(config=cfg) as loop:
    loop.engine._file_meta(warm)
    server = make_tcp_server(loop, host="127.0.0.1", port=port)
    print("READY", flush=True)
    server.serve_forever()
"""


def _fleet_serve_arm(bam: str, regions):
    """Arm 5 of ``bench_region_serve``: a real 2-replica fleet.  Every
    request is a wire round trip (socket JSONL), so the numbers are
    endpoint-observed, failover included."""
    import json as _json
    import socket as _socket
    import tempfile as _tf
    import threading as _th

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    def wire(port, doc, timeout=30.0):
        with _socket.create_connection(("127.0.0.1", port),
                                       timeout=timeout) as s:
            s.settimeout(timeout)
            f = s.makefile("rw", encoding="utf-8", newline="\n")
            f.write(_json.dumps(doc) + "\n")
            f.flush()
            return _json.loads(f.readline())

    p1, p2 = free_port(), free_port()
    peers = f"r1=127.0.0.1:{p1},r2=127.0.0.1:{p2}"
    with _tf.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_FLEET_REPLICA_SRC)
        script = f.name
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))

    def spawn(rid, port):
        return subprocess.Popen(
            [sys.executable, script, rid, str(port), peers, bam],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)

    def await_healthy(port, deadline_s=180.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            try:
                if wire(port, {"op": "health", "id": 1},
                        timeout=2.0).get("health"):
                    return
            except (OSError, ValueError):
                time.sleep(0.25)
        raise TimeoutError(f"fleet replica on {port} never healthy")

    subset = regions[:60]
    failed = [0]

    def drive(ports, rs, threads=4):
        slices = [rs[i::threads] for i in range(threads)]

        def client(i, chunk):
            for j, region in enumerate(chunk):
                port = ports[(i + j) % len(ports)]
                try:
                    doc = wire(port, {"id": 1, "path": bam,
                                      "region": region})
                    if "error" in doc:
                        failed[0] += 1
                except (OSError, ValueError):
                    failed[0] += 1

        t0 = time.perf_counter()
        ts = [_th.Thread(target=client, args=(i, c))
              for i, c in enumerate(slices) if c]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return len(rs) / (time.perf_counter() - t0)

    procs = [spawn("r1", p1), spawn("r2", p2)]
    try:
        await_healthy(p1)
        await_healthy(p2)
        drive([p1, p2], subset)                      # warm both tiles
        qps_one = drive([p1], subset)                # 1 endpoint
        qps_two = drive([p1, p2], subset)            # both endpoints
        fl1 = wire(p1, {"op": "fleet", "id": 1})["fleet"]
        fl2 = wire(p2, {"op": "fleet", "id": 1})["fleet"]
        fetched = fl1["peer_fetch_ok"] + fl2["peer_fetch_ok"]
        decoded = fl1["local_decodes"] + fl2["local_decodes"]
        cross_rate = fetched / max(1, fetched + decoded)
        # the kill arm: SIGKILL r2, then the surviving endpoint's
        # client-observed latency through eviction + re-ranking
        procs[1].kill()
        procs[1].wait(timeout=30)
        lats = []
        for region in subset[:40]:
            t0 = time.perf_counter()
            doc = wire(p1, {"id": 1, "path": bam, "region": region})
            lats.append(time.perf_counter() - t0)
            if "error" in doc:
                failed[0] += 1
        lats.sort()
        kill_p99 = lats[int(0.99 * (len(lats) - 1))]
        return {"fleet_replicas": 2,
                "fleet_qps": [[1, round(qps_one, 1)],
                              [2, round(qps_two, 1)]],
                "cross_replica_tile_hit_rate": round(cross_rate, 4),
                "fleet_kill_p99_ms": round(kill_p99 * 1e3, 3),
                "fleet_failed_requests": failed[0]}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                pass
        os.unlink(script)


def bench_faulted_serve(path: str):
    """The degrade-and-heal serving row (ISSUE 11), three arms:

    1. CLEAN: warm ServeLoop p50 over a zipf region subset — the
       healthy-path reference.
    2. CHAOS: a fresh loop under a seed-derived byte-source fault
       schedule (transient + slow reads, reproducible from chaos_seed)
       with a tight tenant quota driven by 4 concurrent clients — the
       shed rate (every shed must be TRANSIENT taxonomy, never a hang),
       the degraded warm p50, and its ratio to the clean p50.
    3. HEAL: the decode-plane demotion ladder's recovery time — native
       faults demote flagstat to zlib (breaker opens), then measure the
       wall time until a half-open probe heals the plane after the
       cooldown (byte-identity vs the clean answer asserted on every
       run, faulted or not).
    """
    import dataclasses as _dc
    import threading as _th

    from hadoop_bam_tpu import resilience
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.resilience.chaos import (
        PointFault, fault_points_on,
    )
    from hadoop_bam_tpu.serve import ServeLoop
    from hadoop_bam_tpu.utils.errors import (
        CorruptDataError, TransientIOError,
    )
    from hadoop_bam_tpu.utils.metrics import MetricsContext
    from hadoop_bam_tpu.utils.resilient import (
        clear_chaos, install_chaos_seeded,
    )

    bam, regions = _region_query_fixture(path)
    regions = regions[:80]
    chaos_seed = int(getattr(DEFAULT_CONFIG, "chaos_seed", None) or 1234)
    quiet = _dc.replace(DEFAULT_CONFIG, serve_prefetch=False)
    resilience.reset()

    # -- arm 1: clean warm p50 -------------------------------------------
    with ServeLoop(config=quiet) as loop:
        for region in dict.fromkeys(regions):
            loop.query(bam, [region])            # warm tiles
        with MetricsContext() as clean_m:
            for region in regions:
                loop.query(bam, [region])
        clean_lat = clean_m.hist_summary("serve.latency_s")

    # -- arm 2: seeded chaos + tight quota, 4 clients --------------------
    # transient_rate is per-READ-OFFSET and chunk decodes touch many
    # block offsets, so the effective per-chunk fault count is ~rate *
    # reads — each retry heals one offset and may trip the next; the
    # retry budget must cover the expected fault count per chunk
    chaos_cfg = _dc.replace(
        DEFAULT_CONFIG, serve_prefetch=False, span_retries=8,
        retry_backoff_base_s=0.001, retry_backoff_max_s=0.01,
        serve_tenant_max_in_flight=2, serve_tenant_queue_depth=1,
        breaker_cooldown_s=0.2)
    # per-thread counters, summed after join — list[0] += 1 from 4
    # threads is a non-atomic read/add/store and loses increments
    served_k = [0, 0, 0, 0]
    shed_k = [0, 0, 0, 0]
    unclassified = []
    with ServeLoop(config=chaos_cfg) as loop:
        loop.query(bam, [regions[0]])            # jit/meta warmup
        install_chaos_seeded(bam, chaos_seed, transient_rate=0.08,
                             slow_rate=0.05, delay_s=0.001)
        try:
            with MetricsContext() as chaos_m:
                def client(k):
                    for region in regions[k::4]:
                        try:
                            loop.query(bam, [region], tenant="web",
                                       deadline_s=30.0)
                            served_k[k] += 1
                        except (TransientIOError, CorruptDataError):
                            shed_k[k] += 1       # classified: the contract
                        except Exception as e:  # noqa: BLE001
                            unclassified.append(e)

                # threads do NOT inherit contextvars: give each client a
                # copy of the MetricsContext-carrying context, or the
                # degraded latency histogram lands in the process global
                import contextvars as _cv
                ctxs = [_cv.copy_context() for _ in range(4)]
                ts = [_th.Thread(target=ctxs[k].run, args=(client, k))
                      for k in range(4)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                chaos_dt = time.perf_counter() - t0
                served = [sum(served_k)]
                shed = [sum(shed_k)]
            # the acceptance bar's number: warm (tile-resident) p50 with
            # chaos STILL INSTALLED, single client — what a well-behaved
            # client sees from a degraded-but-serving loop; must regress
            # < 2x vs the clean arm (tile hits never touch the faulting
            # byte source, so the chaos tax here is dispatcher overhead)
            with MetricsContext() as warm_chaos_m:
                for region in regions:
                    try:
                        loop.query(bam, [region], tenant="warm",
                                   deadline_s=30.0)
                    except (TransientIOError, CorruptDataError):
                        pass             # tolerated; not part of p50
            warm_chaos_lat = warm_chaos_m.hist_summary("serve.latency_s")
        finally:
            clear_chaos(bam)
    if unclassified:
        raise AssertionError(
            f"unclassified failure under chaos: {unclassified[0]!r}")
    chaos_lat = chaos_m.hist_summary("serve.latency_s")
    total = served[0] + shed[0]
    shed_rate = shed[0] / total if total else 0.0
    degraded_qps = served[0] / chaos_dt if chaos_dt else 0.0

    # -- arm 3: ladder heal time -----------------------------------------
    resilience.reset()
    header, _ = read_bam_header(bam)
    # threshold 1: a small fixture may plan a single span — one
    # oracle-confirmed demotion must open the breaker so the heal
    # measurement starts (the heal time, not the threshold, is the row)
    heal_cfg = _dc.replace(
        DEFAULT_CONFIG, inflate_backend="native",
        retry_backoff_base_s=0.001, retry_backoff_max_s=0.01,
        breaker_cooldown_s=0.2, breaker_failure_threshold=1.0)
    clean_stats = flagstat_file(bam, header=header, config=heal_cfg)
    key = f"decode/native/{os.path.abspath(bam)}"
    with fault_points_on("decode.native",
                         [PointFault("corrupt", count=10_000)]):
        faulted_stats = flagstat_file(bam, header=header, config=heal_cfg)
    if faulted_stats != clean_stats:
        raise AssertionError("demoted flagstat diverged from clean run")
    if resilience.registry().states().get(key, {}).get("state") != "open":
        raise AssertionError("native-plane breaker did not open")
    t0 = time.perf_counter()
    heal_s = None
    while time.perf_counter() - t0 < 30.0:
        out = flagstat_file(bam, header=header, config=heal_cfg)
        if out != clean_stats:
            raise AssertionError("healing flagstat diverged")
        st = resilience.registry().states().get(key, {})
        if st.get("state") == "closed":
            heal_s = time.perf_counter() - t0
            break
        time.sleep(0.05)
    if heal_s is None:
        raise AssertionError("native plane never healed")
    resilience.reset()

    clean_p50 = max(clean_lat.get("p50", 0.0), 1e-9)
    degraded_p50 = max(chaos_lat.get("p50", 0.0), 1e-9)
    warm_chaos_p50 = max(warm_chaos_lat.get("p50", 0.0), 1e-9)
    return {"metric": "faulted_serve_queries_per_sec",
            "value": round(degraded_qps, 1), "unit": "queries/s",
            # baseline = clean warm p50 / warm-under-chaos p50; the
            # acceptance bar is < 2x regression, i.e. vs_baseline > 0.5
            # (tiles absorb the byte-source chaos; sheds are counted,
            # never hung)
            "vs_baseline": round(clean_p50 / warm_chaos_p50, 3),
            "shed_rate": round(shed_rate, 4),
            "served": served[0], "shed": shed[0],
            "degraded_p50_ms": round(degraded_p50 * 1e3, 3),
            "warm_chaos_p50_ms": round(warm_chaos_p50 * 1e3, 3),
            "clean_p50_ms": round(clean_p50 * 1e3, 3),
            "ladder_heal_s": round(heal_s, 3),
            "chaos_seed": chaos_seed,
            "note": ("seeded byte-source chaos (transient 0.08 / slow "
                     "0.05 per offset) + 4 clients on a 2-deep tenant "
                     "quota; every failure classified TRANSIENT/CORRUPT "
                     "— no hangs; heal = demote-to-zlib then half-open "
                     "re-probe wall time at 0.2s cooldown")}


COHORT_SAMPLES = int(os.environ.get("BENCH_COHORT_SAMPLES", "64"))
COHORT_GRID_SITES = int(os.environ.get("BENCH_COHORT_GRID_SITES", "1500"))


def build_cohort_fixture():
    """k single-sample BCFs over a shared chr20 position grid (~80%
    presence each) + the manifest joining them — cached under
    bench_data/cohort_{k}/."""
    cdir = os.path.join(BENCH_DIR, f"cohort_{COHORT_SAMPLES}")
    man = os.path.join(cdir, "cohort.json")
    if os.path.exists(man):
        return man
    os.makedirs(cdir, exist_ok=True)
    from hadoop_bam_tpu.api.writers import open_vcf_writer
    from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord

    rng = random.Random(4321)
    grid = []
    pos = 0
    for _ in range(COHORT_GRID_SITES):
        pos += rng.randint(1, 40)
        grid.append((pos, rng.choice("ACGT")))
    gts = ["0/0", "0/1", "1/1", "./."]
    samples = []
    for s in range(COHORT_SAMPLES):
        sid = f"s{s:03d}"
        spath = os.path.join(cdir, f"{sid}.bcf")
        samples.append({"id": sid, "path": spath})
        if os.path.exists(spath):
            continue
        hdr_text = (
            "##fileformat=VCFv4.2\n"
            "##contig=<ID=chr20,length=64444167>\n"
            '##FORMAT=<ID=GT,Number=1,Type=String,Description="GT">\n'
            f"#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
            f"{sid}\n")
        header = VCFHeader.from_text(hdr_text)
        srng = random.Random(1000 + s)
        with open_vcf_writer(spath + ".tmp.bcf", header) as w:
            for p, ref in grid:
                if srng.random() < 0.2:
                    continue                 # per-sample missingness
                alt = srng.choice([c for c in "ACGT" if c != ref])
                w.write_record(VcfRecord.from_line(
                    f"chr20\t{p}\t.\t{ref}\t{alt}\t{30 + p % 40}\tPASS"
                    f"\t.\tGT\t{srng.choice(gts)}"))
        os.replace(spath + ".tmp.bcf", spath)
    tmp = man + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"samples": samples}, f)
    os.replace(tmp, man)
    return man


def bench_cohort_join(path: str):
    """The cohort variant plane row: k single-sample BCFs joined on
    position into the [variants, samples] mesh tensor.

    - join+pack rate (variants/s through tensor_batches, the full
      merge -> harmonize -> FeedPipeline -> device path) with per-stage
      wall SHARES (join / feed / dispatch over the run wall);
    - cohort-slice serving: cold first-slice latency (the join runs
      and tiles park on device) vs warm p50 over repeated slices, plus
      the warm host-decode share (~0 is the bypass proof).
    """
    from hadoop_bam_tpu.cohort import CohortDataset
    from hadoop_bam_tpu.serve import ServeLoop
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    man = build_cohort_fixture()

    CohortDataset(man)                # header-read warmup (page cache)
    with MetricsContext() as m:
        t0 = time.perf_counter()
        ds = CohortDataset(man)
        n_joined = 0
        for out in ds.tensor_batches():
            n_joined += int(np.asarray(out["n_records"]).sum())
        dt = time.perf_counter() - t0
    snap = m.snapshot()
    walls = snap["wall_timers"]
    shares = {
        "join": round(walls.get("cohort.join_wall", 0.0) / dt, 4),
        "feed": round(walls.get("cohort.feed_wall", 0.0) / dt, 4),
        "dispatch": round(walls.get("cohort.dispatch_wall", 0.0) / dt, 4),
    }

    # serving arm: cold slice (join + tile build) vs warm repeats
    regions = ["chr20:1-20000", "chr20:20001-40000", "chr20:1-60000"]
    with ServeLoop() as loop:
        t0 = time.perf_counter()
        cold = loop.query(man, [regions[0]], cohort=True)[0]
        cold_ms = (time.perf_counter() - t0) * 1e3
        warm_times = []
        with MetricsContext() as wm:
            for i in range(24):
                t0 = time.perf_counter()
                loop.query(man, [regions[i % len(regions)]], cohort=True)
                warm_times.append(time.perf_counter() - t0)
        wsnap = wm.snapshot()
        warm_host = wsnap["wall_timers"].get("pipeline.host_decode_wall",
                                             0.0) \
            + wsnap["wall_timers"].get("cohort.join_wall", 0.0)
        warm_p50_ms = sorted(warm_times)[len(warm_times) // 2] * 1e3
        assert cold.tile_misses >= 1

    return {
        "metric": "cohort_join_variants_per_sec",
        "value": round(n_joined / dt, 1), "unit": "variants/s",
        "samples": COHORT_SAMPLES, "variants": int(n_joined),
        "stage_wall_shares": shares,
        "cold_slice_p50_ms": round(cold_ms, 3),
        "warm_slice_p50_ms": round(warm_p50_ms, 3),
        "warm_host_decode_share": round(
            warm_host / max(sum(warm_times), 1e-9), 4),
        "note": f"k={COHORT_SAMPLES} single-sample BCFs joined on "
                f"position (kmerge + harmonize + FeedPipeline); serve "
                f"arm slices the resident cohort tiles",
    }


def bench_obs_overhead(path: str):
    """What the always-on instrumentation itself costs (tracing
    DISABLED, the default state): flagstat through an isolated normal
    MetricsContext vs the same run through NullMetrics (every span/
    counter/histogram a no-op).  The acceptance bar for the obs layer
    is < 2% — pinned here so span creep shows up as a bench regression,
    not a slow mystery."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import (
        flagstat_file, pipeline_span_count,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached
    from hadoop_bam_tpu.utils.metrics import MetricsContext, NullMetrics
    import jax

    bam = _scaling_fixture(path)
    header, _ = read_bam_header(bam)
    spans = plan_spans_cached(
        bam, header, DEFAULT_CONFIG,
        num_spans=pipeline_span_count(bam, len(jax.devices()),
                                      DEFAULT_CONFIG))

    from hadoop_bam_tpu.obs import install_recorder
    from hadoop_bam_tpu.utils.metrics import Metrics

    def run(metrics_cls):
        with MetricsContext(metrics_cls()):
            return flagstat_file(bam, header=header, spans=spans)

    # interleaved best-of-N: on this 1-core host the run-to-run jitter
    # (GC, page cache, the shared decode pool warming) is larger than
    # the overhead being measured, so alternate the two variants and
    # compare their MINIMA — drift hits both arms equally.  The trace
    # recorder is SUSPENDED for the row: under `bench.py --trace` a
    # live ring would make the instrumented arm pay tracing-enabled
    # costs (the row's bar is the tracing-DISABLED state) and flood
    # the trace file with this row's 12 flagstat runs.
    prev_recorder = install_recorder(None)
    try:
        run(Metrics)
        run(NullMetrics)          # warmup both arms (jit, pool, cache)
        dt_on, dt_off = [], []
        for _ in range(5):
            t0 = time.perf_counter()
            run(Metrics)
            dt_on.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            run(NullMetrics)
            dt_off.append(time.perf_counter() - t0)
    finally:
        install_recorder(prev_recorder)
    on, off = min(dt_on), min(dt_off)
    overhead = (on - off) / off * 100.0
    return {"metric": "obs_overhead_pct",
            "value": round(overhead, 2), "unit": "%",
            "note": ("flagstat with live spans/counters/histograms "
                     "(tracing disabled) vs NullMetrics, interleaved "
                     "best-of-5; bar is < 2%"),
            "instrumented_s": round(on, 4),
            "null_s": round(off, 4)}


def bench_plan_overhead(path: str):
    """What the plan/execute layer costs per driver call: flagstat
    through the plan path (flagstat_file -> builders.flagstat_plan ->
    executor.execute -> _flagstat_impl) vs the legacy inline path
    (_flagstat_impl called directly), same pinned spans + header,
    ORDER-ALTERNATED interleaved best-of-8 minima: the 1-core host's
    jitter exceeds the delta, and whichever arm runs first in a round
    systematically pays the previous round's teardown (ring buffers
    freeing under it), so a fixed order reads pure noise as overhead
    (measured: fixed order ~6%, alternated ~1%, true wrapper cost is
    microseconds by profile).  The bar is < 2% — the IR compile,
    digesting, and dispatch must stay invisible next to the decode
    itself."""
    import jax

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import (
        _flagstat_impl, flagstat_file, pipeline_span_count,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    bam = _scaling_fixture(path)
    header, _ = read_bam_header(bam)
    spans = plan_spans_cached(
        bam, header, DEFAULT_CONFIG,
        num_spans=pipeline_span_count(bam, len(jax.devices()),
                                      DEFAULT_CONFIG))

    def via_plan():
        return flagstat_file(bam, header=header, spans=spans)

    def inline():
        return _flagstat_impl(bam, header=header, spans=spans)

    # warmup both arms (jit, pool, page cache) AND pin identity: the
    # plan path must be value-identical to the inline path it wraps
    identical = via_plan() == inline()
    dt = {"plan": [], "inline": []}
    for i in range(8):
        arms = [("plan", via_plan), ("inline", inline)]
        if i % 2:
            arms.reverse()            # order-alternated (docstring)
        for name, fn in arms:
            t0 = time.perf_counter()
            fn()
            dt[name].append(time.perf_counter() - t0)
    on, off = min(dt["plan"]), min(dt["inline"])
    overhead = (on - off) / off * 100.0
    return {"metric": "plan_overhead_pct",
            "value": round(overhead, 2), "unit": "%",
            "plan_s": round(on, 4), "inline_s": round(off, 4),
            "identical_to_inline": bool(identical),
            "note": ("flagstat via plan builders + the one executor vs "
                     "the inline mesh-feed impl, order-alternated "
                     "interleaved best-of-8; bar is < 2%")}


def bench_fused_decode(path: str):
    """The round-10 contract row: fused single-pass span decode
    (inflate + walk + pack + CRC fold in one cache-resident native
    sweep, chunk-streamed into the staging ring) vs the two-pass oracle
    path on the 100k scaling fixture — same host, interleaved
    best-of-N, flagstat records/sec.  Also measures what check_crc adds
    ON the fused path (the fold makes it nearly free; bar < 10%) and
    reports the stage wall-share shift: the combined inflate+walk share
    of host-decode work vs the fused sweep's single share."""
    import dataclasses as _dc

    import jax

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.inflate import fused_available
    from hadoop_bam_tpu.parallel.pipeline import flagstat_file
    from hadoop_bam_tpu.split.planners import plan_spans_cached
    from hadoop_bam_tpu.utils.metrics import METRICS

    if not fused_available():
        return {"metric": "fused_decode_records_per_sec",
                "error": "native fused decode unavailable"}
    bam = _scaling_fixture(path)
    header, _ = read_bam_header(bam)
    src_size = os.path.getsize(bam)
    spans = plan_spans_cached(
        bam, header, DEFAULT_CONFIG,
        num_spans=max(len(jax.devices()),
                      int(np.ceil(src_size / (4 << 20)))))
    cfg_fused = _dc.replace(DEFAULT_CONFIG, use_fused_decode=True)
    cfg_two = _dc.replace(DEFAULT_CONFIG, use_fused_decode=False)

    def run(cfg):
        return flagstat_file(bam, header=header, spans=spans, config=cfg)

    n_records = run(cfg_fused)["total"]     # warmup: jit + page cache
    run(cfg_two)
    arms = {"fused": cfg_fused, "two_pass": cfg_two,
            "fused_crc": _dc.replace(cfg_fused, check_crc=True),
            "two_pass_crc": _dc.replace(cfg_two, check_crc=True)}
    best = {k: float("inf") for k in arms}
    # interleaved best-of-4: run-to-run jitter on this host exceeds the
    # deltas being measured, so the arms alternate and compare minima
    for _ in range(4):
        for k, cfg in arms.items():
            t0 = time.perf_counter()
            run(cfg)
            dt = time.perf_counter() - t0
            best[k] = min(best[k], dt)
    fused_rate = n_records / best["fused"]
    two_rate = n_records / best["two_pass"]

    def decode_share(cfg):
        """Host-decode stage breakdown (stage seconds per host-decode
        second, check_crc=True): two-pass splits into its three sweeps
        (inflate / walk / crc), fused reports its one.  The fused arm
        runs BUFFERED (skip_bad_spans gates chunk streaming off) so its
        sweep timer nests inside pipeline.host_decode exactly like the
        two-pass stage timers — same denominator, comparable shares."""
        METRICS.reset()
        run(_dc.replace(cfg, check_crc=True, skip_bad_spans=True))
        t = dict(METRICS.snapshot()["timers"])
        denom = max(t.get("pipeline.host_decode", 0.0), 1e-9)
        return {k.split(".", 1)[1]: round(t[k] / denom, 3)
                for k in ("pipeline.inflate", "pipeline.walk",
                          "pipeline.crc", "pipeline.fused_decode")
                if k in t}

    return {"metric": "fused_decode_records_per_sec",
            "value": round(fused_rate, 1), "unit": "records/s",
            "vs_baseline": round(fused_rate / two_rate, 3),
            "two_pass_records_per_sec": round(two_rate, 1),
            "check_crc_overhead_pct": round(
                (best["fused_crc"] - best["fused"]) / best["fused"]
                * 100.0, 2),
            "two_pass_crc_overhead_pct": round(
                (best["two_pass_crc"] - best["two_pass"])
                / best["two_pass"] * 100.0, 2),
            "decode_share_fused": decode_share(cfg_fused),
            "decode_share_two_pass": decode_share(cfg_two),
            "note": ("flagstat on the 100k fixture, interleaved "
                     "best-of-4; vs_baseline = fused/two-pass; bars: "
                     ">= 1.2x and fused CRC overhead < 10%; "
                     "decode_share arms run check_crc=True")}


def bench_device_inflate(path: str):
    """The round-11 contract row: the token-feed device decode plane
    (host Huffman tokenize overlapped with on-mesh LZ77 resolve + record
    walk + fixed-field unpack; ops/inflate_device.py) vs the fused-native
    host plane, flagstat over the same pinned span subset of the scaling
    fixture.  Reports the tokenize / device-resolve wall-share breakdown
    and the overlap between them — the structural claim this row pins is
    that the host half of inflate (Huffman tokenize, ~1/3 of inflate
    cost) OVERLAPS the device half, so the non-overlapped inflate share
    of flagstat wall drops vs the fused-native arm where the whole
    inflate is host wall.  CAVEAT (recorded in the note): this 1-core
    host runs the "device" stage on XLA:CPU, so the row measures overlap
    STRUCTURE and plane correctness, not TPU speedup — tokenize and
    resolve time-slice one core here, and resolve is far slower than
    native inflate."""
    import dataclasses as _dc

    import jax

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import (
        DEVICE_PLANE_SPAN_BYTES, flagstat_file,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached
    from hadoop_bam_tpu.utils import native as nat
    from hadoop_bam_tpu.utils.metrics import METRICS

    if not nat.available():
        return {"metric": "device_inflate_records_per_sec",
                "error": "native tokenizer unavailable"}
    bam = _scaling_fixture(path)
    header, _ = read_bam_header(bam)
    src_size = os.path.getsize(bam)
    n_spans = max(len(jax.devices()),
                  int(np.ceil(src_size / DEVICE_PLANE_SPAN_BYTES)))
    spans = list(plan_spans_cached(bam, header, DEFAULT_CONFIG,
                                   num_spans=n_spans))
    # a ~6 MiB compressed prefix bounds the XLA:CPU walk cost per run on
    # this host; both arms run the SAME pinned subset so rates compare
    budget = 6 << 20
    take, acc = [], 0
    for s in spans:
        take.append(s)
        acc += s.compressed_size
        if acc >= budget:
            break
    cfg_dev = _dc.replace(DEFAULT_CONFIG, inflate_backend="device")
    cfg_fused = _dc.replace(DEFAULT_CONFIG, inflate_backend="native")

    def run(cfg):
        return flagstat_file(bam, header=header, spans=take, config=cfg)

    n_records = run(cfg_dev)["total"]    # warmup: resolve/walk jit
    fused_total = run(cfg_fused)["total"]
    if fused_total != n_records:
        # a silent device-walk counting bug must fail the row, not
        # produce plausible rates from the wrong denominator
        return {"metric": "device_inflate_records_per_sec",
                "error": f"plane parity break: device total {n_records} "
                         f"!= fused total {fused_total}"}
    best = {"device": float("inf"), "fused": float("inf")}
    walls = {}
    for _ in range(2):                   # interleaved best-of-2
        for arm, cfg in (("device", cfg_dev), ("fused", cfg_fused)):
            METRICS.reset()
            t0 = time.perf_counter()
            run(cfg)
            dt = time.perf_counter() - t0
            if dt < best[arm]:
                best[arm] = dt
                w = dict(METRICS.snapshot()["wall_timers"])
                w["_total"] = dt
                walls[arm] = w

    def share(arm, host_key, dev_key):
        w = walls[arm]
        total = max(w["_total"], 1e-9)
        host = float(w.get(host_key, 0.0))
        devw = float(w.get(dev_key, 0.0))
        overlap = max(0.0, host + devw - total)
        return {
            f"{host_key.split('.')[1]}_s": round(host, 4),
            f"{dev_key.split('.')[1]}_s": round(devw, 4),
            "overlap_s": round(overlap, 4),
            "overlap_efficiency": round(
                overlap / max(min(host, devw), 1e-9), 3),
            # the host inflate work NOT hidden behind the other stage,
            # as a share of the arm's flagstat wall
            "nonoverlap_inflate_share": round(
                max(0.0, host - overlap) / total, 3),
        }

    breakdown = {
        "device": share("device", "bam.tokenize_wall",
                        "bam.device_resolve_wall"),
        "fused": share("fused", "bam.fused_decode_wall",
                       "bam.dispatch_wall"),
    }
    dev_rate = n_records / best["device"]
    fused_rate = n_records / best["fused"]
    return {"metric": "device_inflate_records_per_sec",
            "value": round(dev_rate, 1), "unit": "records/s",
            "vs_baseline": round(dev_rate / fused_rate, 3),
            "fused_records_per_sec": round(fused_rate, 1),
            "records": int(n_records),
            "spans": len(take),
            "decode_plane_walls": breakdown,
            "note": ("flagstat on a pinned ~6 MiB span subset of the "
                     "scaling fixture, interleaved best-of-2; "
                     "vs_baseline = device-plane/fused-native rate; "
                     "device arm = host tokenize overlapped with "
                     "on-mesh resolve+walk+unpack.  1-core XLA:CPU "
                     "caveat: measures overlap structure, not TPU "
                     "speedup — the 'device' here IS the host CPU")}


# ---------------------------------------------------------------------------
# 4b. device decode plane families (round 21): payload / variant / cold serve
# ---------------------------------------------------------------------------

def bench_device_planes(path: str):
    """The round-21 contract row: the token-feed device plane extended
    past flagstat to three more families, one arm each —

    - ``seq_stats``: segmented seq/qual payload projections unpacked
      on-mesh vs the host driver, same pinned span subset both arms;
    - ``variant``: BCF variant stats (device fixed-prefix unpack +
      grouped GT dosage gathers over the resolved mesh buffer) vs the
      host columnar decoder;
    - ``serve_cold``: a cold region_serve pass whose tiles are built
      entirely on-device (serve/tiles.device_build_chunk) vs a cold
      host-built pass over the same distinct windows.

    Every arm asserts value identity against its host oracle IN-RUN (a
    parity break fails the row instead of reporting plausible rates)
    and reports the device arm's pipeline.host_decode_wall share — the
    structural claim is that the new routes keep host record decode off
    the critical path (~0; payload span fixups may contribute epsilon).
    Same 1-core XLA:CPU caveat as the device_inflate row: this pins
    overlap structure and plane correctness, not TPU speedup."""
    import dataclasses as _dc

    import jax

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import (
        DEVICE_PLANE_SPAN_BYTES, seq_stats_file,
    )
    from hadoop_bam_tpu.parallel.variant_pipeline import variant_stats_file
    from hadoop_bam_tpu.split.planners import plan_spans_cached
    from hadoop_bam_tpu.utils import native as nat
    from hadoop_bam_tpu.utils.metrics import METRICS

    metric = "device_plane_families_records_per_sec"
    if not nat.available():
        return {"metric": metric, "error": "native tokenizer unavailable"}
    cfg_dev = _dc.replace(DEFAULT_CONFIG, inflate_backend="device")

    def match(a, b):
        """Counts exact, float reductions within device/host
        reduce-order jitter (f32 tile partials vs f64 host sums)."""
        if set(a) != set(b):
            return False
        for k in a:
            va, vb = a[k], b[k]
            if isinstance(va, (int, np.integer)):
                if int(va) != int(vb):
                    return False
            elif not np.allclose(np.asarray(va, np.float64),
                                 np.asarray(vb, np.float64),
                                 rtol=1e-5, atol=1e-8):
                return False
        return True

    def race(run_dev, run_host):
        """Interleaved best-of-2 of both arms; returns (best walls,
        device-arm host_decode_wall share at its best run)."""
        best = {"device": float("inf"), "host": float("inf")}
        share = {}
        for _ in range(2):
            for arm, run in (("device", run_dev), ("host", run_host)):
                METRICS.reset()
                t0 = time.perf_counter()
                run()
                dt = time.perf_counter() - t0
                if dt < best[arm]:
                    best[arm] = dt
                    w = METRICS.snapshot()["wall_timers"]
                    share[arm] = (float(w.get("pipeline.host_decode_wall",
                                              0.0)) / max(dt, 1e-9))
        return best, share

    # --- payload arm: seq_stats over the pinned ~6 MiB span subset ---
    bam = _scaling_fixture(path)
    header, _ = read_bam_header(bam)
    n_spans = max(len(jax.devices()),
                  int(np.ceil(os.path.getsize(bam)
                              / DEVICE_PLANE_SPAN_BYTES)))
    spans = list(plan_spans_cached(bam, header, DEFAULT_CONFIG,
                                   num_spans=n_spans))
    budget = 6 << 20
    take, acc = [], 0
    for s in spans:
        take.append(s)
        acc += s.compressed_size
        if acc >= budget:
            break

    def seq_dev():
        return seq_stats_file(bam, header=header, spans=take,
                              config=cfg_dev)

    def seq_host():
        return seq_stats_file(bam, header=header, spans=take)

    dev_stats = seq_dev()                    # warmup: resolve/unpack jit
    host_stats = seq_host()
    if not match(dev_stats, host_stats):
        return {"metric": metric,
                "error": "seq_stats device plane parity break vs host"}
    n_records = int(host_stats["n_reads"])
    sbest, sshare = race(seq_dev, seq_host)
    seq_arm = {
        "device_records_per_sec": round(n_records / sbest["device"], 1),
        "host_records_per_sec": round(n_records / sbest["host"], 1),
        "host_decode_share": round(sshare["device"], 4),
        "identical_to_host": True,
        "records": n_records, "spans": len(take)}

    # --- variant arm: BCF stats, whole-file both planes ---
    bcfp = build_bcf_fixture()

    def var_dev():
        return variant_stats_file(bcfp, config=cfg_dev)

    def var_host():
        return variant_stats_file(bcfp)

    vd, vh = var_dev(), var_host()           # warmup + parity
    if not match(vd, vh):
        return {"metric": metric,
                "error": "variant device plane parity break vs host"}
    n_variants = int(vh["n_variants"])
    vbest, vshare = race(var_dev, var_host)
    var_arm = {
        "device_variants_per_sec": round(n_variants / vbest["device"], 1),
        "host_variants_per_sec": round(n_variants / vbest["host"], 1),
        "host_decode_share": round(vshare["device"], 4),
        "identical_to_host": True, "variants": n_variants}

    # --- serve arm: one cold pass per plane over the distinct windows ---
    from hadoop_bam_tpu.serve import ServeLoop

    bam_q, regions = _region_query_fixture(path)
    # 16 distinct windows bound the XLA:CPU device-walk cost of the cold
    # pass on this 1-core host; identity and metering pin the same way
    windows = sorted(set(regions))[:16]
    counts, serve_arm = {}, {}
    for arm, cfg in (("device", _dc.replace(cfg_dev,
                                            serve_prefetch=False)),
                     ("host", _dc.replace(DEFAULT_CONFIG,
                                          serve_prefetch=False))):
        with ServeLoop(config=cfg) as loop:
            METRICS.reset()
            t0 = time.perf_counter()
            res = loop.query(bam_q, windows)
            dt = time.perf_counter() - t0
            snap = METRICS.snapshot()
        counts[arm] = [r.count for r in res]
        serve_arm[f"{arm}_queries_per_sec"] = round(len(windows) / dt, 1)
        if arm == "device":
            serve_arm["host_decode_share"] = round(
                float(snap["wall_timers"].get(
                    "pipeline.host_decode_wall", 0.0)) / max(dt, 1e-9), 4)
            serve_arm["device_tile_builds"] = int(
                snap["counters"].get("serve.device_tile_builds", 0))
    if counts["device"] != counts["host"]:
        return {"metric": metric,
                "error": "cold serve device tiles parity break vs host"}
    serve_arm["identical_counts"] = True
    serve_arm["regions"] = len(windows)

    rate = seq_arm["device_records_per_sec"]
    return {"metric": metric, "value": rate, "unit": "records/s",
            "vs_baseline": round(
                rate / max(seq_arm["host_records_per_sec"], 1e-9), 3),
            "seq_stats": seq_arm, "variant": var_arm,
            "serve_cold": serve_arm,
            "note": ("round-21 device plane families: per-arm host-oracle "
                     "identity asserted in-run; host_decode_share is the "
                     "device arm's pipeline.host_decode_wall / wall.  "
                     "1-core XLA:CPU caveat: overlap structure, not TPU "
                     "speedup — the 'device' here IS the host CPU")}


# ---------------------------------------------------------------------------
# 5. FASTQ reads/s (device payload stats driver)
# ---------------------------------------------------------------------------

def bench_fastq(path: str):
    """Device payload-stats driver (vectorized span tokenize) vs the
    single-thread per-object parse path as baseline."""
    from hadoop_bam_tpu.api.read_datasets import (
        fragments_to_payload_tiles, open_fastq,
    )
    from hadoop_bam_tpu.parallel.pipeline import fastq_seq_stats_file

    def run():
        return fastq_seq_stats_file(path)

    stats, dt = _median_time(run)

    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry
    geom = PayloadGeometry()

    def base_run():
        ds = open_fastq(path)
        n = 0
        for span in ds.spans():
            tiles = fragments_to_payload_tiles(
                ds.read_span(span), geom.seq_stride, geom.qual_stride,
                geom.max_len)
            n += tiles[2].size
        return n

    bn, bdt = _median_time(base_run)
    meas, base = stats["n_reads"] / dt, bn / bdt
    return {"metric": "fastq_reads_per_sec",
            "value": round(meas, 1), "unit": "reads/s",
            "vs_baseline": round(meas / base, 3)}


# ---------------------------------------------------------------------------
# 6. split-guess p50 latency (index-less BAM split planning)
# ---------------------------------------------------------------------------

def bench_split_guess(path: str):
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.split.planners import plan_bam_spans

    header, _ = read_bam_header(path)
    # PINNED config: 16 requested spans on the standard 300k-record fixture.
    # Do not change either without re-pinning SPLIT_GUESS_BASELINE_MS below,
    # or the cross-round series breaks (VERDICT r2 weak #6).
    n_spans = 16
    SPLIT_GUESS_BASELINE_MS = 8.2   # r2 driver-captured, same config

    def run():
        return plan_bam_spans(path, num_spans=n_spans, header=header)

    spans, dt = _median_time(run)
    boundaries = max(len(spans) - 1, 1)  # first boundary is free (header)
    ms = dt / boundaries * 1e3
    out = {"metric": "split_guess_p50_ms_per_boundary",
           "value": round(ms, 3), "unit": "ms"}
    if BENCH_RECORDS == 300000:
        # latency metric: >1 means faster than the pinned r2 baseline
        out["vs_baseline"] = round(SPLIT_GUESS_BASELINE_MS / ms, 3)
    else:
        # a smoke-size fixture makes the pinned baseline meaningless
        out["note"] = (f"no vs_baseline: fixture is {BENCH_RECORDS} "
                       f"records, baseline pinned at 300000")
    return out


def _collect_record_bytes(path: str, n: int):
    """First n raw record byte strings from a BAM (shared by the sort and
    write benches)."""
    from hadoop_bam_tpu.api.dataset import open_bam

    ds = open_bam(path)
    recs = []
    for batch in ds.batches():
        for i in range(len(batch)):
            recs.append(batch.record_bytes(i))
            if len(recs) >= n:
                return ds, recs
    return ds, recs


def bench_sort(path: str):
    """Mesh bucketed sort (device keys + all_to_all) vs the single-process
    spill-merge sort on a shuffled slice of the main fixture."""
    import tempfile

    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
    from hadoop_bam_tpu.utils.sort import sort_bam

    import shutil

    n_slice = min(BENCH_RECORDS, int(os.environ.get("BENCH_SORT_RECORDS",
                                                    "100000")))
    src = os.path.join(BENCH_DIR, f"bench_sort_{n_slice}.bam")
    if not os.path.exists(src):
        import random as _random

        from hadoop_bam_tpu.config import DEFAULT_CONFIG
        from hadoop_bam_tpu.formats.bamio import BamWriter
        ds, recs = _collect_record_bytes(path, n_slice)
        _random.Random(9).shuffle(recs)
        with BamWriter(src + ".tmp", ds.header,
                       level=DEFAULT_CONFIG.write_compress_level) as w:
            for r in recs:
                w.write_record_bytes(r)
        os.replace(src + ".tmp", src)

    tmp = tempfile.mkdtemp(prefix="hbam_bench_sort_")
    try:
        def run():
            return sort_bam_mesh(src, os.path.join(tmp, "mesh.bam"))

        n, dt = _median_time(run)

        def base_run():
            return sort_bam(src, os.path.join(tmp, "single.bam"))

        bn, bdt = _median_time(base_run)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    meas, base = n / dt, bn / bdt
    return {"metric": "sort_records_per_sec_mesh",
            "value": round(meas, 1), "unit": "records/s",
            "vs_baseline": round(meas / base, 3),
            # On the tunneled single chip this ratio is dominated by
            # shipping whole inflated spans H2D (~40-175 MB/s link) and
            # ~100 ms dispatch latency, not by the exchange/sort; on the
            # 8-device CPU mesh the same code is byte-identical to and
            # competitive with the single-process sort (test_mesh_sort).
            "note": "end-to-end incl. tunneled H2D of span bytes"}


def bench_sort_write(path: str):
    """Mesh-sort + parallel write throughput (write/ subsystem): the
    sort's output stage through ParallelBGZFWriter + index-during-write
    vs the same sort forced onto the serial in-line writer
    (write_parallel_workers=0).  Value is output MB/s of the parallel
    arm; ``write_deflate_share`` is the deflate stage's union-wall share
    of the parallel arm's end-to-end wall.  The parallel-vs-serial ratio
    is HOST-DEPENDENT: on this 1-core bench machine pool deflate cannot
    beat in-line deflate (no spare cores), so the contract pins the row
    shape and byte-identity, never a ratio."""
    import dataclasses
    import shutil
    import tempfile

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    n_slice = min(BENCH_RECORDS, int(os.environ.get("BENCH_SORT_RECORDS",
                                                    "100000")))
    src = os.path.join(BENCH_DIR, f"bench_sort_{n_slice}.bam")
    if not os.path.exists(src):
        bench_sort(path)                 # builds the shuffled fixture
    tmp = tempfile.mkdtemp(prefix="hbam_bench_sortwrite_")
    try:
        par_out = os.path.join(tmp, "par.bam")
        ser_out = os.path.join(tmp, "ser.bam")

        with MetricsContext() as m:
            def par_run():
                return sort_bam_mesh(src, par_out, config=DEFAULT_CONFIG)
            n, dt = _median_time(par_run)
        snap = m.snapshot()
        deflate_wall = float(snap["wall_timers"].get(
            "write.deflate_wall", 0.0))
        ser_cfg = dataclasses.replace(DEFAULT_CONFIG,
                                      write_parallel_workers=0)

        def ser_run():
            return sort_bam_mesh(src, ser_out, config=ser_cfg)
        bn, bdt = _median_time(ser_run)
        assert n == bn
        identical = open(par_out, "rb").read() == open(ser_out,
                                                       "rb").read()
        out_bytes = os.path.getsize(par_out)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    meas = out_bytes / dt / 1e6
    base = out_bytes / bdt / 1e6
    # MetricsContext accumulated deflate wall over warmup + reps runs;
    # normalize to a per-run share of the measured wall
    runs = _MEDIAN_REPS + 1
    share = min(1.0, deflate_wall / runs / max(dt, 1e-9))
    return {"metric": "sort_write_mb_per_sec",
            "value": round(meas, 2), "unit": "MB/s",
            "vs_baseline": round(meas / base, 3),
            "serial_mb_per_sec": round(base, 2),
            "write_deflate_share": round(share, 4),
            "records": int(n), "output_bytes": int(out_bytes),
            "byte_identical_to_serial": bool(identical),
            "note": ("parallel-deflate vs serial-writer arm; ratio is "
                     "host-dependent (1-core bench host has no spare "
                     "cores for the pool) — contract pins row shape + "
                     "byte identity, not a ratio")}


def bench_mkdup(path: str):
    """Fused preprocessing row (prep/): read -> mesh sort exchange ->
    markdup -> indexed write as ONE pass (`hbam mkdup`) vs the staged
    equivalent (mesh sort to disk, then the serial markdup oracle
    re-reading it).  Value is output MB/s of the fused arm;
    ``stage_wall_shares`` splits its wall across the three stage spans;
    the identity flag byte-compares the fused output against the serial
    oracle run on the SAME input (the prep/ validation contract —
    staged-arm bytes can differ on score ties, its input order is
    already sorted)."""
    import shutil
    import tempfile

    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
    from hadoop_bam_tpu.prep import markdup_bam_mesh, markdup_bam_oracle
    from hadoop_bam_tpu.utils.metrics import MetricsContext

    n_slice = min(BENCH_RECORDS, int(os.environ.get("BENCH_SORT_RECORDS",
                                                    "100000")))
    src = os.path.join(BENCH_DIR, f"bench_sort_{n_slice}.bam")
    if not os.path.exists(src):
        bench_sort(path)                 # builds the shuffled fixture
    tmp = tempfile.mkdtemp(prefix="hbam_bench_mkdup_")
    try:
        fused_out = os.path.join(tmp, "fused.bam")
        with MetricsContext() as m:
            def fused_run():
                return markdup_bam_mesh(src, fused_out)
            n, dt = _median_time(fused_run)
        snap = m.snapshot()
        dups = int(snap["counters"].get("prep.duplicates_marked", 0))
        runs = _MEDIAN_REPS + 1
        shares = {
            stage: round(min(1.0, float(
                snap["wall_timers"].get(f"prep.{stage}_wall", 0.0))
                / runs / max(dt, 1e-9)), 4)
            for stage in ("sort", "markdup", "write")}

        sorted_out = os.path.join(tmp, "sorted.bam")
        staged_out = os.path.join(tmp, "staged.bam")

        def staged_run():
            sort_bam_mesh(src, sorted_out)
            return markdup_bam_oracle(sorted_out, staged_out)
        bn, bdt = _median_time(staged_run)
        assert n == bn

        oracle_out = os.path.join(tmp, "oracle.bam")
        markdup_bam_oracle(src, oracle_out)
        identical = open(fused_out, "rb").read() == open(
            oracle_out, "rb").read()
        out_bytes = os.path.getsize(fused_out)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    meas = out_bytes / dt / 1e6
    base = out_bytes / bdt / 1e6
    return {"metric": "mkdup_mb_per_sec",
            "value": round(meas, 2), "unit": "MB/s",
            "vs_staged": round(meas / base, 3),
            "staged_mb_per_sec": round(base, 2),
            "stage_wall_shares": shares,
            "records": int(n), "duplicates_marked": dups // runs,
            "output_bytes": int(out_bytes),
            "byte_identical_to_oracle": bool(identical),
            "note": ("fused read->sort->markdup->write vs staged "
                     "sort-to-disk + serial oracle; identity pinned "
                     "vs the oracle on the same input")}


_RESUME_KILL_CHILD = """
import os, signal, sys
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
from hadoop_bam_tpu.jobs import JobJournal
src, out, jp, rr = sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4])
orig = JobJournal.unit_done
n = [0]
def patched(self, kind, key, **kw):
    orig(self, kind, key, **kw)
    if kind == "round":
        n[0] += 1
        if n[0] >= 1:
            os.kill(os.getpid(), signal.SIGKILL)
JobJournal.unit_done = patched
from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
sort_bam_mesh(src, out, round_records=rr, journal_path=jp)
"""

_RESUME_RESUME_CHILD = """
import json, os, sys, time
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
from hadoop_bam_tpu.utils.metrics import MetricsContext
src, out, jp, rr = sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4])
t0 = time.perf_counter()
with MetricsContext() as m:
    n = sort_bam_mesh(src, out, round_records=rr, journal_path=jp)
snap = m.snapshot()
print(json.dumps({
    "records": n, "wall_s": time.perf_counter() - t0,
    "spans_skipped": snap["counters"].get("jobs.spans_skipped", 0),
    "rounds_skipped": snap["counters"].get("jobs.rounds_skipped", 0)}))
"""


def bench_resume(path: str):
    """Crash-safe jobs row (jobs/): (1) journaling overhead — spill-mode
    mesh sort with and without a journal, interleaved best-of, bar <3%
    (the journal writes one fsync'd record per ROUND, not per record);
    (2) a resume arm — a subprocess running the same journaled sort
    SIGKILLs itself after its first committed round, a second process
    resumes from the journal, and the row reports the fraction of span
    decodes the journal let it skip plus byte identity vs the
    journal-off output.  The kill/resume pair runs on the forced-CPU
    8-device mesh in subprocesses so the round partitioning is
    identical between the killed and resuming runs regardless of the
    bench platform."""
    import shutil
    import tempfile

    from hadoop_bam_tpu.jobs import JobJournal, journal_path_for
    from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

    n_slice = min(BENCH_RECORDS, int(os.environ.get("BENCH_SORT_RECORDS",
                                                    "100000")))
    src = os.path.join(BENCH_DIR, f"bench_sort_{n_slice}.bam")
    if not os.path.exists(src):
        bench_sort(path)                 # builds the shuffled fixture
    import jax
    rr = max(500, n_slice // max(1, 4 * jax.device_count()))
    tmp = tempfile.mkdtemp(prefix="hbam_bench_resume_")
    try:
        plain_out = os.path.join(tmp, "plain.bam")
        jr_out = os.path.join(tmp, "journaled.bam")
        jr_jp = journal_path_for(jr_out)

        def plain_run():
            return sort_bam_mesh(src, plain_out, round_records=rr)

        def journaled_run():
            # fresh journal per rep: a done-job journal would turn the
            # rep into a verified no-op and measure nothing
            if os.path.exists(jr_jp):
                os.unlink(jr_jp)
            return sort_bam_mesh(src, jr_out, round_records=rr,
                                 journal_path=jr_jp)

        n, pdt = _median_time(plain_run)
        jn, jdt = _median_time(journaled_run)
        assert n == jn
        identical = open(plain_out, "rb").read() == open(jr_out,
                                                         "rb").read()
        overhead_pct = (jdt - pdt) / max(pdt, 1e-9) * 100.0

        # --- resume arm (subprocess kill + subprocess resume) ---
        kill_out = os.path.join(tmp, "killed.bam")
        kill_jp = journal_path_for(kill_out)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count="
                            + str(jax.device_count())).strip()
        budget = min(150.0, max(30.0, _remaining() - 30))
        r1 = subprocess.run(
            [sys.executable, "-c", _RESUME_KILL_CHILD, src, kill_out,
             kill_jp, str(rr)], env=env, capture_output=True, text=True,
            timeout=budget)
        resume = {}
        if r1.returncode >= 0:
            resume = {"error": f"kill child exited rc={r1.returncode} "
                               f"instead of dying: "
                               f"{(r1.stderr or '')[-200:]}"}
        else:
            r2 = subprocess.run(
                [sys.executable, "-c", _RESUME_RESUME_CHILD, src,
                 kill_out, kill_jp, str(rr)], env=env,
                capture_output=True, text=True, timeout=budget)
            try:
                out = json.loads(r2.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                out = {"error": f"resume child rc={r2.returncode}: "
                                f"{(r2.stderr or '')[-200:]}"}
            if "error" not in out:
                st = JobJournal.replay(kill_jp)
                n_spans = int((st.last_event("plan") or {}).get(
                    "n_spans", 0))
                resume = {
                    "resume_records": out["records"],
                    "resume_wall_s": round(out["wall_s"], 3),
                    "resume_rounds_skipped": out["rounds_skipped"],
                    "resume_fraction_skipped": round(
                        out["spans_skipped"] / max(1, n_spans), 4),
                    "resume_byte_identical": bool(
                        open(kill_out, "rb").read()
                        == open(plain_out, "rb").read()),
                }
            else:
                resume = out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {"metric": "resume_overhead_pct",
            "value": round(overhead_pct, 2), "unit": "%",
            "journaled_wall_s": round(jdt, 3),
            "plain_wall_s": round(pdt, 3),
            "round_records": rr, "records": int(n),
            "byte_identical_to_plain": bool(identical),
            **resume,
            "note": ("journal-on vs journal-off spill mesh sort "
                     "(bar <3%); resume arm SIGKILLs a child after "
                     "round 1 and reports journal-verified skipped "
                     "span fraction")}


def bench_bam_write(path: str):
    """Write path: re-encode a decoded slice through BamWriter (native
    libdeflate BGZF) vs the same pipeline forced onto Python zlib —
    the reference's BlockCompressedOutputStream analog."""
    import io

    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.utils import native as nat

    if not nat.available():
        return {"metric": "bam_write_records_per_sec", "value": 0.0,
                "unit": "records/s",
                "note": "native deflate unavailable; zlib-vs-zlib would "
                        "be a vacuous baseline"}
    n_slice = min(BENCH_RECORDS, 100_000)
    ds, recs = _collect_record_bytes(path, n_slice)

    def write_with(use_native: bool):
        saved = nat._lib, nat._tried
        if not use_native:
            nat._lib, nat._tried = None, True    # force zlib fallback
        try:
            sink = io.BytesIO()
            with BamWriter(sink, ds.header) as w:
                for r in recs:
                    w.write_record_bytes(r)
            return sink.tell()
        finally:
            nat._lib, nat._tried = saved

    _, dt = _median_time(lambda: write_with(True))
    _, bdt = _median_time(lambda: write_with(False))
    meas = len(recs) / dt
    base = len(recs) / bdt
    return {"metric": "bam_write_records_per_sec",
            "value": round(meas, 1), "unit": "records/s",
            "vs_baseline": round(meas / base, 3)}


def bench_coverage(path: str):
    """Device cigar pileup (coverage_file) vs a single-thread NumPy host
    pileup over the same window — records/s through the coverage driver."""
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.parallel.pipeline import coverage_file

    # fixture positions advance ~20/record from 1; 2^22 covers the head
    window = 1 << 22
    region = f"chr20:1-{window}"

    def run():
        return coverage_file(path, region)

    depth, dt = _median_time(run)

    def base_run():
        # host oracle: same diff-scatter pileup, NumPy single-thread
        total = 0
        diff = np.zeros(window + 1, np.int64)
        for batch in open_bam(path).batches():
            total += len(batch)
            n_c = batch.n_cigar.astype(np.int64)
            m = (n_c > 0) & ((batch.flag & 4) == 0) & (batch.refid == 0)
            idx = np.flatnonzero(m)
            counts = n_c[idx]
            if not counts.size:
                continue
            firsts = np.cumsum(counts) - counts
            flat = (np.arange(int(counts.sum()), dtype=np.int64)
                    - np.repeat(firsts, counts))
            offs = np.repeat(batch.cigar_offset[idx], counts) + 4 * flat
            vals = (batch.data[offs[:, None] + np.arange(4)]
                    .astype(np.uint32))
            vals = (vals[:, 0] | (vals[:, 1] << 8) | (vals[:, 2] << 16)
                    | (vals[:, 3] << 24))
            op = (vals & 0xF).astype(np.int64)
            ln = (vals >> 4).astype(np.int64)
            consumes = np.isin(op, (0, 2, 3, 7, 8))
            adv = ln * consumes
            excl = np.cumsum(adv) - adv          # global exclusive cumsum
            rec0 = np.repeat(excl[firsts], counts)
            seg_start = np.repeat(batch.pos[idx], counts) + (excl - rec0)
            aligned = np.isin(op, (0, 7, 8))
            s = np.clip(seg_start[aligned], 0, window)
            e = np.clip(seg_start[aligned] + ln[aligned], 0, window)
            np.add.at(diff, s, 1)
            np.add.at(diff, e, -1)
        np.cumsum(diff[:window])
        return total

    n_records, bdt = _median_time(base_run)
    meas = n_records / dt
    base = n_records / bdt
    return {"metric": "coverage_records_per_sec",
            "value": round(meas, 1), "unit": "records/s",
            "vs_baseline": round(meas / base, 3),
            # per-device cost is O(window) (diff cumsum) + O(records):
            # at this fixture's ~1.4x depth the window term dominates and
            # a single-thread host pass wins; the device path amortizes
            # at WGS-scale depth where records >> window
            "note": "device pileup vs single-thread NumPy pileup"}


def bench_deflate_tokenize(path: str):
    """Host half of the device-DEFLATE experiment (BASELINE.md r3 "Device
    DEFLATE"): Huffman tokenize GB/s, with vs_baseline = tokenize/full-
    native-inflate speed ratio.  vs_baseline < 1 records that the
    two-stage device split cannot beat host inflate even granting a free
    device stage — the measured negative result."""
    import numpy as np

    from hadoop_bam_tpu.ops import inflate as inflate_ops
    from hadoop_bam_tpu.utils import native as nat

    if not nat.available():
        return {"metric": "deflate_tokenize_gbps", "value": 0.0,
                "unit": "GB/s", "note": "native tokenizer unavailable"}
    raw_b = open(path, "rb").read()
    table = inflate_ops.block_table(raw_b)
    src = np.frombuffer(raw_b, np.uint8)
    total = int(table["isize"].sum())
    stride = max(16, int(table["isize"].max()))

    def run():
        return nat.deflate_tokenize_batch(
            src, table["cdata_off"], table["cdata_len"], stride, 1)

    _, dt = _median_time(run)

    def base_run():
        return inflate_ops.inflate_span(raw_b, table, backend="native",
                                        n_threads=1)

    _, bdt = _median_time(base_run)
    return {"metric": "deflate_tokenize_gbps",
            "value": round(total / dt / 1e9, 3), "unit": "GB/s",
            "vs_baseline": round(bdt / dt, 3)}


# ---------------------------------------------------------------------------
# on-chip kernel rows (VERDICT r3 #7): what the TPU itself contributes per
# stage, timed with the readback-grounded method from the r3 DEFLATE
# experiment (BASELINE.md): block_until_ready can return before execution
# completes on the tunneled chip, so each measurement is serialized chained
# execution with a SCALAR readback per step, minus the measured
# dispatch+readback floor.
# ---------------------------------------------------------------------------

_FLOOR_CACHE = {"v": None}


def _readback_floor(reps: int = 10) -> float:
    """Per-call dispatch + scalar-readback cost of a trivial jitted op.
    Measured once and cached so all kernel rows share one floor."""
    if _FLOOR_CACHE["v"] is not None:
        return _FLOOR_CACHE["v"]
    import jax
    import jax.numpy as jnp

    x = jnp.ones((8, 128), jnp.float32)
    f = jax.jit(lambda a: (a * 2.0).sum())
    float(f(x))
    t0 = time.perf_counter()
    for _ in range(reps):
        float(f(x))
    _FLOOR_CACHE["v"] = (time.perf_counter() - t0) / reps
    return _FLOOR_CACHE["v"]


def _chained_time(fn, reps: int = 5) -> float:
    """Mean wall seconds per fn() call, where fn returns a device scalar
    whose float() forces completion through the tunnel."""
    float(fn())                       # warmup: compile + caches
    t0 = time.perf_counter()
    for _ in range(reps):
        float(fn())
    return (time.perf_counter() - t0) / reps


def _scan_chain(step, length: int):
    """Wrap a carry -> scalar kernel step in a length-iteration lax.scan
    so one dispatch amortizes the ~70 ms floor over that many
    data-dependent kernel executions (the carry feeds each step's
    inputs, so XLA cannot hoist or elide the repeats).  Returns a
    jitted fn(*args) -> scalar."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(*args):
        def body(c, _):
            return step(c, *args), ()
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=length)
        return c
    return run


def _kernel_rate(step, args, work_per_iter: float):
    """(work-units/s, extras) for one kernel iteration, floor-corrected.

    The chain length adapts: it grows until the whole chain's wall time
    dominates the dispatch floor (the tunneled floor is jittery, so a
    fixed length can land inside its noise and make the subtraction
    meaningless).  If even the longest chain stays within noise, the
    row is flagged unreliable instead of reporting an absurd rate."""
    floor = _readback_floor()
    # start long and cap low: every retry is a fresh lax.scan compile
    # (~tens of seconds on the tunneled chip), and the r3/r4 runs spent
    # more budget compiling chain lengths than measuring them
    k = 64
    while True:
        run = _scan_chain(step, k)
        raw = _chained_time(lambda: run(*args), reps=3)
        if raw >= 4 * floor or k >= 1024:
            break
        k = min(k * 4, 1024)
    dt = max(raw - floor, 1e-9)
    extras = {"chain_len": k}
    if raw < 1.5 * floor:
        extras["unreliable"] = (
            f"chain wall {raw * 1e3:.1f} ms is within noise of the "
            f"{floor * 1e3:.1f} ms dispatch floor even at {k} steps")
    return work_per_iter * k / dt, extras


def bench_seq_pallas_kernel():
    """Fused seq/qual Pallas kernel, bases/s on the device itself, vs the
    single-thread NumPy host analog of the same stats."""
    import jax.numpy as jnp

    from hadoop_bam_tpu.ops.seq_pallas import (
        seq_qual_stats, seq_qual_stats_host,
    )

    N, L = 8192, 151
    rng = np.random.default_rng(3)
    seq_np = rng.integers(0, 256, (N, (L + 1) // 2), dtype=np.uint8)
    qual_np = rng.integers(0, 42, (N, L), dtype=np.uint8)
    lens_np = np.full(N, L, np.int32)
    seq, qual, lens = map(jnp.asarray, (seq_np, qual_np, lens_np))

    def step(c, s, q, l):
        # carry perturbs the qual tile: data dependence between steps
        st = seq_qual_stats(s, (q + c.astype(jnp.uint8)) & 0x3F, l)
        total = (st["gc"].sum() + st["mean_qual"].sum()
                 + st["base_hist"].sum().astype(jnp.float32))
        return c + 1.0 + total * jnp.float32(1e-20)   # keep st live

    bases = N * L
    rate, extras = _kernel_rate(step, (seq, qual, lens), bases)

    _, bdt = _median_time(
        lambda: seq_qual_stats_host(seq_np, qual_np, lens_np), reps=3)
    return {"metric": "seq_pallas_kernel_bases_per_sec",
            "value": round(rate, 1), "unit": "bases/s",
            "vs_baseline": round(rate / (bases / bdt), 3),
            "note": (f"on-chip only, adaptive scan chain, "
                     "floor-corrected; baseline = single-thread NumPy "
                     "host analog"), **extras}


def bench_cigar_pileup_kernel(path: str):
    """Device cigar-unpack + window-coverage kernels alone (no file IO,
    no H2D in the timed region): records/s through the pileup math."""
    import jax.numpy as jnp

    from hadoop_bam_tpu.formats.bam import BamBatch, walk_record_offsets
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.cigar import (
        unpack_cigar_tiles, window_coverage_from_tiles,
    )
    from hadoop_bam_tpu.split.planners import plan_bam_spans
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core

    header, _ = read_bam_header(path)
    span = plan_bam_spans(path, num_spans=4, header=header)[0]
    data, offs, _v, _ = _decode_span_core(path, span, False, "auto",
                                          want_voffs=False)
    batch = BamBatch(data, offs)
    n = len(batch)
    max_cigar = max(int(batch.n_cigar.max()), 4)
    window = 1 << 22

    dev = {
        "data": jnp.asarray(data),
        "offsets": jnp.asarray(offs.astype(np.int32)),
        "lrn": jnp.asarray(batch.l_read_name.astype(np.int32)),
        "ncig": jnp.asarray(batch.n_cigar.astype(np.int32)),
        "pos": jnp.asarray(batch.pos.astype(np.int32)),
        "refid": jnp.asarray(batch.refid.astype(np.int32)),
        "flag": jnp.asarray(batch.flag.astype(np.int32)),
    }
    valid = jnp.ones(n, bool)

    def step(c, d):
        # carry shifts the window start: dependent, never hoistable
        tiles = unpack_cigar_tiles(d["data"], d["offsets"], d["lrn"],
                                   d["ncig"], max_cigar)
        depth = window_coverage_from_tiles(
            tiles, d["pos"], d["refid"], d["flag"], valid,
            jnp.int32(0), c.astype(jnp.int32) % 64, window)
        return c + 1.0 + depth.sum().astype(jnp.float32) * jnp.float32(
            1e-20)

    rate, extras = _kernel_rate(step, (dev,), n)
    return {"metric": "cigar_pileup_kernel_records_per_sec",
            "value": round(rate, 1), "unit": "records/s",
            "note": (f"on-chip unpack+pileup only ({n} records, "
                     f"max_cigar={max_cigar}, 4 MiB window), "
                     f"adaptive scan chain, floor-corrected"),
            **extras}


def bench_mesh_sort_kernel():
    """The mesh sort's device stage alone: three-key lexicographic
    lax.sort ((hi, lo, tie-break index), the bucket-local sort) —
    keys/s on the chip."""
    import jax
    import jax.numpy as jnp

    R = 1 << 18
    rng = np.random.default_rng(11)
    hi = jnp.asarray(rng.integers(0, 64, R, dtype=np.uint32))
    lo = jnp.asarray(rng.integers(0, 1 << 28, R, dtype=np.uint32))
    ix = jnp.arange(R, dtype=jnp.int32)

    def step(c, a, b, t):
        # carry xors the low key: each step sorts different data
        a2 = a ^ c.astype(jnp.uint32)
        _, _, six = jax.lax.sort((a2, b, t), num_keys=3)
        return c + 1.0 + six.sum().astype(jnp.float32) * jnp.float32(
            1e-20)

    rate, extras = _kernel_rate(step, (hi, lo, ix), R)
    return {"metric": "mesh_sort_device_sort_keys_per_sec",
            "value": round(rate, 1), "unit": "keys/s",
            "note": ("on-chip 3-key lax.sort of the bucket-local stage "
                     f"({R} keys), adaptive scan chain, "
                     "floor-corrected"), **extras}


# ---------------------------------------------------------------------------
# device-scaling curve (VERDICT r3 #2): flagstat/seq-stats/coverage at
# 1/2/4/8 virtual CPU devices, each measured in a subprocess so the forced
# device count can't leak into (or hang) the main run.  On this 1-core host
# the virtual devices share one core, so the curve measures how the WORK
# partitions (per-stage timers: host inflate/walk vs sharded device step),
# not wall-clock speedup — that caveat is recorded in the JSON itself.
# ---------------------------------------------------------------------------

def _scaling_child(n_dev: int) -> None:
    """Runs in a subprocess with xla_force_host_platform_device_count set."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the children re-trace the same programs
    # every round — cached, a child's cost is runs, not compiles
    _enable_compile_cache("child")

    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.parallel.pipeline import (
        coverage_file, flagstat_file, seq_stats_file,
    )
    from hadoop_bam_tpu.utils.metrics import METRICS

    path = os.environ.get("BENCH_SCALING_BAM", BENCH_BAM)
    header, _ = read_bam_header(path)
    mesh = make_mesh()
    out = {"n_devices": n_dev, "jax_devices": len(jax.devices())}
    # cumulative emission, same contract as the parent: the parent reads
    # the LAST '{' line, so a child killed mid-pipeline still delivers
    # every pipeline it finished (the r3/r4 loss mode, fixed one level
    # down too)
    print(json.dumps(out), flush=True)

    def timed(fn, reps=2):
        fn()                       # warmup: jit compile + page cache
        METRICS.reset()
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            res = fn()
            times.append(time.perf_counter() - t0)
        snap = METRICS.snapshot()
        timers = {k: round(v / reps, 4) for k, v in snap["timers"].items()}
        walls = {k: round(v / reps, 4)
                 for k, v in snap["wall_timers"].items()}
        counters = {k: v // reps for k, v in snap["counters"].items()}
        # lower median: best-of for reps=2, true median for odd reps —
        # never the max (a GC hiccup must not define the curve)
        return (res, sorted(times)[(len(times) - 1) // 2], timers, walls,
                counters)

    def feed_overlap(walls, counters, prefix):
        """overlap_efficiency (device-busy wall / total feed wall) +
        dispatch_bytes per driver row — the wall-clock spans the
        FeedPipeline records; the thread-summed stage timers cannot
        show overlap, these can."""
        row = {}
        fw = walls.get("pipeline.feed_wall")
        if fw:
            dw = walls.get("pipeline.dispatch_wall", 0.0)
            row[f"{prefix}_overlap_efficiency"] = round(dw / fw, 4)
        db = counters.get("pipeline.dispatch_bytes")
        if db:
            row[f"{prefix}_dispatch_bytes"] = int(db)
        return row

    stats, dt, timers, walls, counters = timed(
        lambda: flagstat_file(path, mesh=mesh, header=header))
    n_file_records = stats["total"]
    out["file_records"] = n_file_records
    out["flagstat_records_per_sec"] = round(n_file_records / dt, 1)
    # host_decode/inflate/walk run in a thread pool: their values are
    # WORK seconds summed across threads (can exceed wall time); the
    # single-threaded device_put/device_drain values are wall seconds;
    # the *_wall rows (flagstat_wall_seconds_per_run) are wall-clock
    # UNION spans from Metrics.wall_timer — the overlap-visible ones.
    out["flagstat_stage_seconds_per_run"] = timers
    out["flagstat_wall_seconds_per_run"] = walls
    out.update(feed_overlap(walls, counters, "flagstat"))
    out["stage_timer_note"] = ("host_decode/inflate/walk are thread-summed "
                               "work seconds; device_* are wall seconds; "
                               "*_wall entries and overlap_efficiency are "
                               "wall-clock union spans")
    print(json.dumps(out), flush=True)

    sstats, dt, _, walls, counters = timed(
        lambda: seq_stats_file(path, mesh=mesh))
    out["seq_stats_records_per_sec"] = round(
        int(sstats.get("n_reads", n_file_records)) / dt, 1)
    out.update(feed_overlap(walls, counters, "seq_stats"))
    print(json.dumps(out), flush=True)

    # no .bai sidecar on the bench fixture: coverage streams every record
    _, dt, _, walls, counters = timed(
        lambda: coverage_file(path, "chr20:1-4194304", mesh=mesh))
    out["coverage_records_per_sec"] = round(n_file_records / dt, 1)
    out.update(feed_overlap(walls, counters, "coverage"))

    print(json.dumps(out), flush=True)


def _scaling_fixture(path: str) -> str:
    """A smaller sorted BAM for the scaling children: the curve measures
    work partitioning, which a 100k slice shows as well as the full
    fixture at a third of the per-child cost on this 1-core host."""
    n = min(BENCH_RECORDS, int(os.environ.get("BENCH_SCALING_RECORDS",
                                              "100000")))
    if n >= BENCH_RECORDS:
        return path
    dst = os.path.join(BENCH_DIR, f"bench_scaling_{n}.bam")
    if not os.path.exists(dst):
        from hadoop_bam_tpu.config import DEFAULT_CONFIG
        from hadoop_bam_tpu.formats.bamio import BamWriter

        ds, recs = _collect_record_bytes(path, n)
        with BamWriter(dst + ".tmp", ds.header,
                       level=DEFAULT_CONFIG.write_compress_level) as w:
            for r in recs:
                w.write_record_bytes(r)
        os.replace(dst + ".tmp", dst)
    _heal_stale_sidecars(dst)
    return dst


def bench_scaling(path: str) -> dict:
    rows = []
    try:
        scaling_bam = _scaling_fixture(path)
    except Exception as e:
        return {"error": f"scaling fixture: {type(e).__name__}: {e}"}
    def run_child(n):
        """One scaling-child run: (row entry, raw stderr text)."""
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + f" --xla_force_host_platform_device_count={n}"
                            ).strip()
        env["BENCH_SCALING_BAM"] = scaling_bam
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--scaling-child", str(n)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)
        _CHILD["proc"] = proc
        timed_out = False
        try:
            stdout, stderr = proc.communicate(
                timeout=min(180.0, max(45.0, _remaining() - 30)))
        except subprocess.TimeoutExpired:
            proc.kill()
            stdout, stderr = proc.communicate()
            timed_out = True
        finally:
            _CHILD["proc"] = None
        row = None
        for ln in reversed((stdout or "").splitlines()):
            # a kill can truncate the final line mid-write: take the
            # newest line that actually parses
            if ln.startswith("{"):
                try:
                    row = json.loads(ln)
                    break
                except ValueError:
                    continue
        if row is not None and (timed_out or proc.returncode == 0):
            if timed_out:
                # the child emits cumulatively too: keep whatever
                # pipelines it finished before the kill
                row["partial"] = "timeout"
            return row, stderr or ""
        if timed_out:
            return {"n_devices": n, "error": "timeout"}, stderr or ""
        err = (stderr or "").strip().splitlines()
        return ({"n_devices": n, "error":
                 f"rc={proc.returncode}: "
                 f"{err[-1][:200] if err else 'no output'}"},
                stderr or "")

    for n in SCALING_DEVICES:
        if _remaining() < 70:
            rows.append({"n_devices": n, "skipped": "deadline"})
            continue
        try:
            row, stderr = run_child(n)
            if "truncated BGZF header" in stderr + json.dumps(row):
                # the recurring stale-sidecar failure (ROADMAP note): a
                # bench_data sidecar from an older code state poisons
                # the child's index-trusting path.  Purge the scaling
                # fixture's sidecars and retry ONCE — consumers
                # regenerate what they need.
                purged = _purge_sidecars(scaling_bam)
                _STATE["notes"].append(
                    f"scaling child n={n} hit 'truncated BGZF header'; "
                    f"purged sidecars {purged or 'none'} and retried")
                if _remaining() > 70:
                    row, _stderr = run_child(n)
            rows.append(row)
        except Exception as e:
            rows.append({"n_devices": n,
                         "error": f"{type(e).__name__}: {e}"})
    return {
        "host_cores": os.cpu_count(),
        "note": ("virtual CPU devices share this host's "
                 f"{os.cpu_count()} core(s): the curve shows work "
                 "partitioning and per-stage cost, not wall speedup; "
                 "stage timers separate host decode from the sharded "
                 "device step"),
        "devices": rows,
    }


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()
    try:
        _STATE["platform"] = acquire_platform()
    except Exception as e:   # acquire_platform shouldn't raise; belt+braces
        _STATE["platform"] = "unknown"
        _STATE["notes"].append(
            f"platform acquisition failed: {type(e).__name__}: {e}")

    try:
        path = build_fixture()
    except Exception as e:
        _STATE["notes"].append(
            f"fixture build failed: {type(e).__name__}: {e}")
        _emit("error")
        return

    # headline: measured pipeline vs single-thread host decode
    base = None
    try:
        base = baseline_single_thread(path)
    except Exception as e:
        _STATE["notes"].append(
            f"baseline measurement failed: {type(e).__name__}: {e}")
    try:
        meas = measured_pipeline(path)
        head = {"metric": "bam_decode_records_per_sec_per_chip",
                "value": round(meas, 1), "unit": "records/s"}
        if base:
            head["vs_baseline"] = round(meas / base, 3)
        _STATE["headline"] = head
        _STATE["components"].append(head)
    except Exception as e:
        _STATE["components"].append(
            {"metric": "bam_decode_records_per_sec_per_chip",
             "error": f"{type(e).__name__}: {e}"})
    _emit_progress()

    # ordered cheapest/highest-value first: an external kill costs the
    # tail, so the tail is the rows a verdict can best live without
    _run_component(lambda: bench_bgzf_inflate(path), "bgzf_inflate_gbps",
                   est_s=15)
    _run_component(lambda: bench_split_guess(path),
                   "split_guess_p50_ms_per_boundary", est_s=10)
    _run_component(lambda: bench_device_inflate(path),
                   "device_inflate_records_per_sec", est_s=150.0)
    _run_component(lambda: bench_device_planes(path),
                   "device_plane_families_records_per_sec", est_s=150.0)
    _run_component(lambda: bench_fused_decode(path),
                   "fused_decode_records_per_sec", est_s=30)
    _run_component(lambda: bench_fault_resilience(path),
                   "faulted_flagstat_records_per_sec", est_s=20)
    _run_component(lambda: bench_cram(build_cram_fixture()),
                   "cram_tensor_records_per_sec", est_s=25)
    _run_component(lambda: bench_vcf(build_vcf_fixture()),
                   "vcf_variants_per_sec", est_s=25)
    _run_component(lambda: bench_bcf(build_bcf_fixture()),
                   "bcf_variants_per_sec", est_s=25)
    _run_component(lambda: bench_region_query(path),
                   "region_query_queries_per_sec", est_s=45)
    _run_component(lambda: bench_region_serve(path),
                   "region_serve_queries_per_sec", est_s=110)
    _run_component(lambda: bench_faulted_serve(path),
                   "faulted_serve_queries_per_sec", est_s=50)
    _run_component(lambda: bench_obs_overhead(path),
                   "obs_overhead_pct", est_s=25)
    _run_component(lambda: bench_plan_overhead(path),
                   "plan_overhead_pct", est_s=25)
    _run_component(lambda: bench_cohort_join(path),
                   "cohort_join_variants_per_sec", est_s=45)
    _run_component(lambda: bench_fastq(build_fastq_fixture()),
                   "fastq_reads_per_sec", est_s=25)
    _run_component(lambda: bench_bam_write(path),
                   "bam_write_records_per_sec", est_s=25)
    _run_component(lambda: bench_deflate_tokenize(path),
                   "deflate_tokenize_gbps", est_s=15)
    _run_component(lambda: bench_coverage(path),
                   "coverage_records_per_sec", est_s=35)
    _run_component(lambda: bench_sort(path), "sort_records_per_sec_mesh",
                   est_s=45)
    _run_component(lambda: bench_resume(path), "resume_overhead_pct",
                   est_s=75)
    _run_component(lambda: bench_sort_write(path), "sort_write_mb_per_sec",
                   est_s=40)
    _run_component(lambda: bench_mkdup(path), "mkdup_mb_per_sec",
                   est_s=55)

    # the scaling curve outranks the single-kernel rows (VERDICT r4 #3)
    if _remaining() > 70:
        try:
            _STATE["scaling"] = bench_scaling(path)
        except Exception as e:
            _STATE["scaling"] = {"error": f"{type(e).__name__}: {e}"}
    else:
        _STATE["scaling"] = {"skipped": "deadline"}
    _emit_progress()

    _run_component(bench_seq_pallas_kernel,
                   "seq_pallas_kernel_bases_per_sec", est_s=40)
    _run_component(lambda: bench_cigar_pileup_kernel(path),
                   "cigar_pileup_kernel_records_per_sec", est_s=40)
    _run_component(bench_mesh_sort_kernel,
                   "mesh_sort_device_sort_keys_per_sec", est_s=40)

    _emit("ok")


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        _scaling_child(int(sys.argv[sys.argv.index("--scaling-child") + 1]))
        sys.exit(0)
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace") + 1
        if i < len(sys.argv):
            _TRACE["path"] = sys.argv[i]
            from hadoop_bam_tpu.obs import enable_tracing
            enable_tracing(1 << 18)
        else:
            # the rc-0/JSON-out contract covers bad invocations too:
            # record the problem as a note instead of tracebacking
            _STATE["notes"].append("--trace given without a file path; "
                                   "tracing disabled for this run")
    try:
        main()
    except BaseException as e:   # the contract: JSON out, rc 0, always
        if not isinstance(e, (KeyboardInterrupt, SystemExit)):
            _STATE["notes"].append(
                f"unhandled: {type(e).__name__}: {e}")
            _emit("error")
        else:
            raise
