"""Benchmark: BAM decode records/sec/chip vs single-thread CPU baseline.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

- Baseline: single-thread host decode — per-block zlib inflate + full
  fixed-field decode in NumPy (the htsjdk-single-thread-equivalent of
  BASELINE.md config #1; real htsjdk/pysam are not in this image).
- Measured: the framework pipeline on the default JAX device — threaded
  native C++ inflate + record walk feeding the jitted device unpack+flagstat
  step (the reference hot loop of SURVEY.md section 3.2 rebuilt).
"""
from __future__ import annotations

import io
import json
import os
import random
import sys
import time

import numpy as np

BENCH_RECORDS = int(os.environ.get("BENCH_RECORDS", "300000"))
BENCH_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_data")
BENCH_BAM = os.path.join(BENCH_DIR, f"bench_{BENCH_RECORDS}.bam")


def build_fixture() -> str:
    if os.path.exists(BENCH_BAM):
        return BENCH_BAM
    os.makedirs(BENCH_DIR, exist_ok=True)
    from hadoop_bam_tpu.formats.bam import SAMHeader, encode_record
    from hadoop_bam_tpu.formats.bamio import BamWriter

    header = SAMHeader.from_sam_text(
        "@HD\tVN:1.6\tSO:coordinate\n"
        "@SQ\tSN:chr20\tLN:64444167\n@SQ\tSN:chr21\tLN:46709983\n")
    rng = random.Random(1234)
    bases = "ACGT"
    with BamWriter(BENCH_BAM + ".tmp", header) as w:
        pos = 1
        for i in range(BENCH_RECORDS):
            l = 151
            seq = "".join(rng.choice(bases) for _ in range(l))
            qual = "".join(chr(33 + rng.randint(2, 40)) for _ in range(l))
            pos += rng.randint(0, 40)
            flag = 99 if i % 2 == 0 else 147
            rec = encode_record(
                name=f"read{i:09d}", flag=flag, refid=0, pos=pos, mapq=60,
                cigar=[(l, "M")], mate_refid=0, mate_pos=pos + 200, tlen=351,
                seq=seq, qual=qual,
                tags=[("NM", "i", rng.randint(0, 4)), ("RG", "Z", "rg0")])
            w.write_record_bytes(rec)
    os.replace(BENCH_BAM + ".tmp", BENCH_BAM)
    return BENCH_BAM


def baseline_single_thread(path: str) -> float:
    """records/sec: single-thread zlib + NumPy full fixed-field decode."""
    import zlib

    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.formats.bam import BamBatch, SAMHeader, walk_record_offsets

    raw = open(path, "rb").read()
    t0 = time.perf_counter()
    chunks = []
    for info in bgzf.scan_blocks(raw):
        if info.isize:
            chunks.append(zlib.decompress(
                raw[info.cdata_offset:info.cdata_offset + info.cdata_size],
                wbits=-15))
    data = b"".join(chunks)
    _, after = SAMHeader.from_bam_bytes(data)
    offs = walk_record_offsets(data, start=after)
    batch = BamBatch(np.frombuffer(data, dtype=np.uint8), offs)
    # force full fixed-field decode (the htsjdk-decode-equivalent work)
    for name in ("refid", "pos", "flag", "mapq", "l_seq", "mate_refid",
                 "mate_pos", "tlen", "bin", "n_cigar", "l_read_name"):
        getattr(batch, name)
    n = len(batch)
    dt = time.perf_counter() - t0
    return n / dt


def measured_pipeline(path: str) -> float:
    """records/sec/chip: threaded native inflate + device unpack/flagstat."""
    import jax

    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.parallel.pipeline import (
        DecodeGeometry, flagstat_file,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh()
    geometry = DecodeGeometry()
    header, _ = read_bam_header(path)

    # warmup (compile)
    stats = flagstat_file(path, mesh=mesh, geometry=geometry, header=header)
    n_records = stats["total"]
    # timed runs: median-of-5 (tunneled TPU links are jittery)
    reps = 5
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        stats = flagstat_file(path, mesh=mesh, geometry=geometry,
                              header=header)
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[reps // 2]
    return stats["total"] / dt / n_dev


def main() -> None:
    path = build_fixture()
    base = baseline_single_thread(path)
    meas = measured_pipeline(path)
    print(json.dumps({
        "metric": "bam_decode_records_per_sec_per_chip",
        "value": round(meas, 1),
        "unit": "records/s",
        "vs_baseline": round(meas / base, 3),
    }))


if __name__ == "__main__":
    main()
